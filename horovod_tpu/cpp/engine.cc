#include "engine.h"

#include <fcntl.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cassert>
#include <cctype>
#include <cerrno>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <map>
#include <sstream>

#if defined(__x86_64__)
#include <cpuid.h>
#include <immintrin.h>
#endif

namespace hvd {

// ---------------------------------------------------------------------------
// Reduction kernels
// ---------------------------------------------------------------------------

// IEEE half <-> float, scalar bit twiddling (no F16C dependency; the
// compiler auto-vectorizes the loops below well enough for a host-side
// control-plane data path).
static inline float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t man = h & 0x3ffu;
  uint32_t f;
  if (exp == 0) {
    if (man == 0) {
      f = sign;
    } else {
      exp = 127 - 15 + 1;
      while ((man & 0x400u) == 0) {
        man <<= 1;
        exp--;
      }
      man &= 0x3ffu;
      f = sign | (exp << 23) | (man << 13);
    }
  } else if (exp == 0x1f) {
    f = sign | 0x7f800000u | (man << 13);
  } else {
    f = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float out;
  memcpy(&out, &f, 4);
  return out;
}

// Round-to-nearest-EVEN, exactly like the F16C hardware converter
// (_MM_FROUND_TO_NEAREST_INT): the SIMD kernel below handles 8-lane
// groups and this scalar handles the tails, so any rounding divergence
// would make results depend on where chunk/shard edges land — the
// multi-channel bit-exactness guarantee forbids that.
static inline uint16_t FloatToHalf(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  uint32_t sign = (f >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((f >> 23) & 0xff) - 127 + 15;
  uint32_t man = f & 0x7fffffu;
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    man |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half_man = man >> shift;
    uint32_t halfbit = 1u << (shift - 1);
    uint32_t rem = man & ((1u << shift) - 1u);
    if (rem > halfbit || (rem == halfbit && (half_man & 1u))) half_man += 1;
    return static_cast<uint16_t>(sign | half_man);
  }
  if (exp >= 0x1f) {
    // Source NaN (exponent field 0xff, mantissa nonzero) must become a
    // QUIET half NaN with the truncated payload — exactly what the F16C
    // converter emits — not infinity: the SIMD/scalar split falls on
    // chunk and shard edges, and any divergence would break the
    // channel-count bit-exactness guarantee.  Finite overflow (source
    // exponent < 0xff) still rounds to infinity.
    if (exp == 0xff - 127 + 15 && man != 0) {
      return static_cast<uint16_t>(sign | 0x7e00u | (man >> 13));
    }
    return static_cast<uint16_t>(sign | 0x7c00u);
  }
  uint32_t half = sign | (static_cast<uint32_t>(exp) << 10) | (man >> 13);
  uint32_t rem = man & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1u))) half += 1;
  return static_cast<uint16_t>(half);
}

// bfloat16 is float32's top 16 bits — the TPU-native conversion is two
// shifts (with round-to-nearest-even on the way down).
static inline float BF16ToFloat(uint16_t h) {
  uint32_t f = static_cast<uint32_t>(h) << 16;
  float out;
  memcpy(&out, &f, 4);
  return out;
}

static inline uint16_t FloatToBF16(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  uint32_t rounding = 0x7fffu + ((f >> 16) & 1u);
  return static_cast<uint16_t>((f + rounding) >> 16);
}

// __restrict: dst and src never alias (dst is the accumulating local
// buffer, src a received scratch chunk), and telling GCC 10 so is what
// lets it vectorize the combine without runtime overlap checks.  The
// 4-way unrolled body keeps the vectorizer on the wide path even when a
// chunk tail disables peeling.
template <typename T, typename F>
static void CombineLoop(void* dst, const void* src, int64_t n, F f) {
  T* __restrict d = static_cast<T*>(dst);
  const T* __restrict s = static_cast<const T*>(src);
  int64_t i = 0;
  for (; i + 4 <= n; i += 4) {
    d[i] = f(d[i], s[i]);
    d[i + 1] = f(d[i + 1], s[i + 1]);
    d[i + 2] = f(d[i + 2], s[i + 2]);
    d[i + 3] = f(d[i + 3], s[i + 3]);
  }
  for (; i < n; ++i) d[i] = f(d[i], s[i]);
}

template <typename T>
static void TypedReduce(void* dst, const void* src, int64_t n, ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM:
      CombineLoop<T>(dst, src, n, [](T a, T b) { return static_cast<T>(a + b); });
      return;
    case ReduceOp::MIN:
      CombineLoop<T>(dst, src, n, [](T a, T b) { return b < a ? b : a; });
      return;
    case ReduceOp::MAX:
      CombineLoop<T>(dst, src, n, [](T a, T b) { return a < b ? b : a; });
      return;
    case ReduceOp::PROD:
      CombineLoop<T>(dst, src, n, [](T a, T b) { return static_cast<T>(a * b); });
      return;
  }
}

// 16-bit floats combine through fp32, staged in blocks: convert a block
// of each side to fp32, combine, convert back — four SIMPLE loops GCC 10
// autovectorizes independently (the bf16 conversions are branch-free
// shifts), where the fused per-element convert-combine-convert body
// defeated its cost model.  fp16's subnormal-handling conversions stay
// scalar either way — its SUM hot path goes through the F16C kernel
// below.  This is the eager/DCN hot loop for fused 64 MB gradient
// buffers (the TPU jit path never touches it).
template <float (*ToF)(uint16_t), uint16_t (*FromF)(float), typename F>
static void HalfCombineLoop(uint16_t* __restrict d,
                            const uint16_t* __restrict s, int64_t n, F f) {
  constexpr int64_t kBlock = 256;
  float a[kBlock], b[kBlock];
  int64_t i = 0;
  for (; i + kBlock <= n; i += kBlock) {
    for (int64_t j = 0; j < kBlock; ++j) a[j] = ToF(d[i + j]);
    for (int64_t j = 0; j < kBlock; ++j) b[j] = ToF(s[i + j]);
    for (int64_t j = 0; j < kBlock; ++j) a[j] = f(a[j], b[j]);
    for (int64_t j = 0; j < kBlock; ++j) d[i + j] = FromF(a[j]);
  }
  for (; i < n; ++i) d[i] = FromF(f(ToF(d[i]), ToF(s[i])));
}

#if defined(__x86_64__)
// IEEE-half summation via the F16C hardware converters, 8 lanes at a time
// (the scalar HalfToFloat/FloatToHalf branch on subnormals and cannot
// vectorize).  Role parity with the reference's AVX fp16 MPI op
// (common/half.cc:26-65); selected once per call via CPUID, never inside
// the loop.
__attribute__((target("f16c,avx")))
static void HalfSumF16C(uint16_t* d, const uint16_t* s, int64_t n) {
  int64_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256 a = _mm256_cvtph_ps(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(d + i)));
    __m256 b = _mm256_cvtph_ps(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i)));
    _mm_storeu_si128(
        reinterpret_cast<__m128i*>(d + i),
        _mm256_cvtps_ph(_mm256_add_ps(a, b), _MM_FROUND_TO_NEAREST_INT));
  }
  for (; i < n; ++i) d[i] = FloatToHalf(HalfToFloat(d[i]) + HalfToFloat(s[i]));
}

static bool HasF16C() {
  // Raw CPUID instead of __builtin_cpu_supports("f16c"): GCC only learned
  // the "f16c" feature name in GCC 11, and the builtin is a compile ERROR
  // (not a false) on older compilers — which silently broke the whole
  // native-engine build on GCC 10 images.
  static const bool has = [] {
    unsigned int eax = 0, ebx = 0, ecx = 0, edx = 0;
    if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx)) return false;
    if ((ecx & bit_F16C) == 0 || (ecx & bit_AVX) == 0) return false;
    // CPUID only reports CPU capability; the OS must also have enabled
    // XSAVE and YMM state (what __builtin_cpu_supports checked for us),
    // or the first VEX instruction SIGILLs.
    if ((ecx & bit_OSXSAVE) == 0) return false;
    uint32_t xlo, xhi;
    __asm__ volatile("xgetbv" : "=a"(xlo), "=d"(xhi) : "c"(0));
    return (xlo & 0x6) == 0x6;  // XMM and YMM state enabled
  }();
  return has;
}
#endif

template <float (*ToF)(uint16_t), uint16_t (*FromF)(float)>
static void HalfReduce(void* dst, const void* src, int64_t n, ReduceOp op) {
  uint16_t* d = static_cast<uint16_t*>(dst);
  const uint16_t* s = static_cast<const uint16_t*>(src);
#if defined(__x86_64__)
  if (op == ReduceOp::SUM && ToF == static_cast<float (*)(uint16_t)>(
                                 HalfToFloat) && HasF16C()) {
    HalfSumF16C(d, s, n);
    return;
  }
#endif
  switch (op) {
    case ReduceOp::SUM:
      HalfCombineLoop<ToF, FromF>(d, s, n,
                                  [](float a, float b) { return a + b; });
      return;
    case ReduceOp::MIN:
      HalfCombineLoop<ToF, FromF>(
          d, s, n, [](float a, float b) { return b < a ? b : a; });
      return;
    case ReduceOp::MAX:
      HalfCombineLoop<ToF, FromF>(
          d, s, n, [](float a, float b) { return a < b ? b : a; });
      return;
    case ReduceOp::PROD:
      HalfCombineLoop<ToF, FromF>(d, s, n,
                                  [](float a, float b) { return a * b; });
      return;
  }
}

void ReduceInto(void* dst, const void* src, int64_t count, DataType dtype,
                ReduceOp op) {
  switch (dtype) {
    case DataType::FLOAT32: TypedReduce<float>(dst, src, count, op); return;
    case DataType::FLOAT64: TypedReduce<double>(dst, src, count, op); return;
    case DataType::INT32: TypedReduce<int32_t>(dst, src, count, op); return;
    case DataType::INT64: TypedReduce<int64_t>(dst, src, count, op); return;
    case DataType::UINT8: TypedReduce<uint8_t>(dst, src, count, op); return;
    case DataType::INT8: TypedReduce<int8_t>(dst, src, count, op); return;
    case DataType::UINT16: TypedReduce<uint16_t>(dst, src, count, op); return;
    case DataType::INT16: TypedReduce<int16_t>(dst, src, count, op); return;
    case DataType::FLOAT16:
      HalfReduce<HalfToFloat, FloatToHalf>(dst, src, count, op);
      return;
    case DataType::BFLOAT16:
      HalfReduce<BF16ToFloat, FloatToBF16>(dst, src, count, op);
      return;
    case DataType::BOOL: {
      uint8_t* d = static_cast<uint8_t*>(dst);
      const uint8_t* s = static_cast<const uint8_t*>(src);
      // sum/max = logical or; min/prod = logical and.
      bool lor = op == ReduceOp::SUM || op == ReduceOp::MAX;
      for (int64_t i = 0; i < count; ++i) {
        d[i] = lor ? (d[i] || s[i]) : (d[i] && s[i]);
      }
      return;
    }
  }
}

// ---------------------------------------------------------------------------
// Wire-compression kernels (quantize / dequantize / block reduce)
// ---------------------------------------------------------------------------

// fp8 e4m3 (1/4/3, bias 7, saturating "fn" variant: no infinity, 0x7f =
// NaN, max finite 448).  Encode is RNE like every other wire conversion;
// decode goes through a 256-entry table built once (the dequant hot loop
// is a single gather).
static inline uint8_t FloatToFp8E4M3(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  uint32_t sign = (f >> 24) & 0x80u;
  uint32_t absf = f & 0x7fffffffu;
  if (absf >= 0x7f800000u) return static_cast<uint8_t>(sign | 0x7fu);  // NaN/inf
  // Saturate finite overflow to the max finite (448), e4m3fn-style.
  // 0x43e00000 = 448.0f; values that ROUND past 448 saturate too — the
  // RNE step below cannot exceed 0x7e after this clamp.
  float av;
  memcpy(&av, &absf, 4);
  if (av > 448.0f) return static_cast<uint8_t>(sign | 0x7eu);
  int32_t exp = static_cast<int32_t>(absf >> 23) - 127 + 7;
  uint32_t man = absf & 0x7fffffu;
  if (exp <= 0) {
    // Subnormal target: smallest normal is 2^-6, subnormal lsb 2^-9.
    if (exp < -3) return static_cast<uint8_t>(sign);  // underflows to 0
    man |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(21 - exp);  // man>>shift -> 3 bits
    uint32_t q = man >> shift;
    uint32_t halfbit = 1u << (shift - 1);
    uint32_t rem = man & ((1u << shift) - 1u);
    if (rem > halfbit || (rem == halfbit && (q & 1u))) q += 1;
    return static_cast<uint8_t>(sign | q);
  }
  uint32_t q = (static_cast<uint32_t>(exp) << 3) | (man >> 20);
  uint32_t rem = man & 0xfffffu;
  if (rem > 0x80000u || (rem == 0x80000u && (q & 1u))) q += 1;
  if (q >= 0x7fu) q = 0x7eu;  // rounded past the top: saturate, not NaN
  return static_cast<uint8_t>(sign | q);
}

static inline float Fp8E4M3ToFloatScalar(uint8_t b) {
  uint32_t sign = (b & 0x80u) ? 0x80000000u : 0;
  uint32_t exp = (b >> 3) & 0xfu;
  uint32_t man = b & 0x7u;
  uint32_t f;
  if (exp == 0) {
    if (man == 0) {
      f = sign;
    } else {
      int e = 127 - 7 + 1;
      while ((man & 0x8u) == 0) {
        man <<= 1;
        e--;
      }
      f = sign | (static_cast<uint32_t>(e) << 23) | ((man & 0x7u) << 20);
    }
  } else if (exp == 0xfu && man == 0x7u) {
    f = sign | 0x7fc00000u;  // NaN
  } else {
    f = sign | ((exp - 7 + 127) << 23) | (man << 20);
  }
  float out;
  memcpy(&out, &f, 4);
  return out;
}

static const float* Fp8DecodeTable() {
  static const float* table = [] {
    float* t = new float[256];
    for (int i = 0; i < 256; ++i) {
      t[i] = Fp8E4M3ToFloatScalar(static_cast<uint8_t>(i));
    }
    return t;
  }();
  return table;
}

// Round-to-nearest-even float -> int8 in [-127, 127] (the symmetric
// range; -128 unused so negation is exact).  rintf honors the current FP
// rounding mode — FE_TONEAREST (RNE) per C default, matching every other
// wire conversion in this file.  Saturating comparisons first, NaN
// check last: casting a NaN or out-of-range float to int8 is UB, and a
// non-finite block already routed through the NaN-scale path below.
static inline int8_t QuantizeI8(float x) {
  float r = rintf(x);
  if (r >= 127.f) return 127;
  if (r <= -127.f) return -127;
  if (!(r == r)) return 0;  // NaN element: the block scale carries it
  return static_cast<int8_t>(r);
}

// One quantized block: [fp32 scale][block_elems codes], scale chosen so
// the block's max |value| maps to the top code (127 / 448).  An all-zero
// block carries scale 0 and zero codes.  A block containing ANY
// non-finite element (a mixed-precision overflow step) carries a NaN
// scale and zero codes: dequantization turns the whole block into NaNs,
// so the overflow PROPAGATES to every rank — block-granular, like fp16
// overflow — instead of silently zeroing the gradient out from under a
// GradScaler-style detector (and instead of the UB a NaN→int8 cast
// would be).
static void QuantizeBlock(const float* src, int64_t n, hvd::WireDtype wire,
                          uint8_t* dst, int64_t block_elems) {
  float maxabs = 0.f;
  bool finite = true;
  for (int64_t i = 0; i < n; ++i) {
    float a = fabsf(src[i]);
    finite = finite && std::isfinite(a);
    if (a > maxabs) maxabs = a;  // NaN compares false: `finite` covers it
  }
  const float top = wire == hvd::WireDtype::FP8 ? 448.f : 127.f;
  float scale = maxabs > 0.f ? maxabs / top : 0.f;
  if (!finite) scale = std::numeric_limits<float>::quiet_NaN();
  float inv = scale > 0.f ? 1.f / scale : 0.f;
  if (!std::isfinite(inv)) {
    // A subnormal-magnitude block (max|value| ~< 1e-36): 1/scale
    // overflows to inf, which would NaN-poison finite input through
    // 0*inf.  Values this small are below every wire format's
    // resolution anyway — flush the block to exact zero (scale 0).
    scale = 0.f;
    inv = 0.f;
  }
  memcpy(dst, &scale, 4);
  uint8_t* q = dst + 4;
  if (!finite || inv == 0.f) {
    for (int64_t i = 0; i < block_elems; ++i) q[i] = 0;
    return;
  }
  if (wire == hvd::WireDtype::FP8) {
    for (int64_t i = 0; i < n; ++i) q[i] = FloatToFp8E4M3(src[i] * inv);
  } else {
    for (int64_t i = 0; i < n; ++i) {
      q[i] = static_cast<uint8_t>(QuantizeI8(src[i] * inv));
    }
  }
  // Zero-pad the tail of a partial last block: padding dequantizes to
  // exactly 0 and can never move the block scale of any peer.
  for (int64_t i = n; i < block_elems; ++i) q[i] = 0;
}

static void DequantizeBlock(const uint8_t* src, int64_t n,
                            hvd::WireDtype wire, float* dst) {
  float scale;
  memcpy(&scale, src, 4);
  const uint8_t* q = src + 4;
  if (wire == hvd::WireDtype::FP8) {
    const float* table = Fp8DecodeTable();
    for (int64_t i = 0; i < n; ++i) dst[i] = table[q[i]] * scale;
  } else {
    const int8_t* s = reinterpret_cast<const int8_t*>(q);
    for (int64_t i = 0; i < n; ++i) dst[i] = static_cast<float>(s[i]) * scale;
  }
}

// ---------------------------------------------------------------------------
// Data-plane thread pool
// ---------------------------------------------------------------------------

void DataPool::Start(int nthreads) {
  Stop();
  {
    std::lock_guard<std::mutex> lk(mu_);
    stop_ = false;
  }
  for (int i = 0; i < nthreads; ++i) {
    threads_.emplace_back(&DataPool::Loop, this);
  }
}

void DataPool::Stop() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    stop_ = true;
  }
  cv_.notify_all();
  for (auto& t : threads_) t.join();
  threads_.clear();
  std::lock_guard<std::mutex> lk(mu_);
  q_.clear();
  idle_ = 0;
}

void DataPool::Submit(std::function<void()> fn) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    q_.push_back(std::move(fn));
  }
  cv_.notify_one();
}

bool DataPool::TrySubmitIfIdle(std::function<void()> fn) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (idle_ - static_cast<int>(q_.size()) <= 0) return false;
    q_.push_back(std::move(fn));
  }
  cv_.notify_one();
  return true;
}

void DataPool::Loop() {
  std::unique_lock<std::mutex> lk(mu_);
  while (true) {
    ++idle_;
    cv_.wait(lk, [&] { return stop_ || !q_.empty(); });
    --idle_;
    if (q_.empty()) {
      if (stop_) return;
      continue;
    }
    auto fn = std::move(q_.front());
    q_.pop_front();
    lk.unlock();
    fn();
    lk.lock();
  }
}

// ---------------------------------------------------------------------------
// Engine lifecycle
// ---------------------------------------------------------------------------

Engine& Engine::Get() {
  static Engine* engine = new Engine();
  return *engine;
}

static int64_t EnvInt64(const char* name, int64_t dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr || v[0] == '\0') return dflt;
  return std::strtoll(v, nullptr, 10);
}

// Magic status prefix the Python layer maps to its StepSkipped
// exception (like __sparse_retry__): a clean per-step outcome, not an
// engine abort — the world stays healthy and the next enqueue works.
static const char kSkippedStepError[] =
    "__skipped_step__: a backup-worker partial commit "
    "(HOROVOD_BACKUP_WORKERS) left this rank out of this step's "
    "reduction — skip the local update or re-sync, then continue";

// Identity used for co-location grouping at rendezvous.  HOROVOD_HOST_KEY
// overrides (tests fake multi-host topologies on one box with it);
// otherwise hostname#boot-id — the boot id disambiguates containers that
// share a hostname but not a kernel (where shm would silently not be
// shared).
static std::string HostKey() {
  const char* k = std::getenv("HOROVOD_HOST_KEY");
  if (k != nullptr && k[0] != '\0') return k;
  char host[256] = {0};
  ::gethostname(host, sizeof(host) - 1);
  std::string key(host);
  if (FILE* f = std::fopen("/proc/sys/kernel/random/boot_id", "r")) {
    char b[64] = {0};
    if (std::fgets(b, sizeof(b), f) != nullptr) {
      for (char* p = b; *p; ++p) {
        if (*p == '\n' || *p == '\r') *p = '\0';
      }
      key += "#";
      key += b;
    }
    std::fclose(f);
  }
  return key;
}

// Derive this rank's group view (node id, members, leaders) from the
// committed rank_host_ table — identical on every rank, so the shm edge
// names and the two-level message pattern agree across the world.
void Engine::AdoptTopology() {
  const int n = size_;
  if (static_cast<int>(rank_host_.size()) != n) rank_host_.assign(n, 0);
  nnodes_ = 1;
  for (auto g : rank_host_) nnodes_ = std::max(nnodes_, g + 1);
  node_id_ = rank_host_[rank_];
  group_members_.clear();
  group_leaders_.assign(nnodes_, -1);
  for (int r = 0; r < n; ++r) {
    if (group_leaders_[rank_host_[r]] < 0) group_leaders_[rank_host_[r]] = r;
    if (rank_host_[r] == node_id_) group_members_.push_back(r);
  }
  group_size_ = static_cast<int>(group_members_.size());
  local_index_ = 0;
  for (int i = 0; i < group_size_; ++i) {
    if (group_members_[i] == rank_) local_index_ = i;
  }
}

int Engine::Init(int rank, int size, int local_rank, int local_size,
                 const std::string& coordinator_addr) {
  if (initialized_.load()) return 0;
  rank_ = rank;
  size_ = size;
  local_rank_ = local_rank;
  local_size_ = local_size;
  // The launch identity is the persistent worker id and the job's full
  // world size; an elastic rendezvous commit may assign a different
  // (contiguous) rank_ and a smaller/restored size_ below.
  worker_id_ = rank;
  world_size_ = size;
  shut_down_.store(false);
  shutdown_requested_.store(false);

  // Knobs (reference operations.cc:1556-1618).
  cycle_time_ms_.store(
      std::max(1, static_cast<int>(EnvInt64("HOROVOD_CYCLE_TIME", 5))));
  cache_capacity_ = EnvInt64("HOROVOD_CACHE_CAPACITY", 1024);
  if (cache_capacity_ < 0) cache_capacity_ = 0;
  // Slot ids must stay under the wire format's bitvector bound
  // (ParseSlotBitvector rejects nbits > 1<<20 as a corrupt frame).
  if (cache_capacity_ > (1 << 20)) cache_capacity_ = 1 << 20;
  cache_enabled_ = cache_capacity_ > 0 && size_ > 1;
  // An elastic re-Init (shutdown + init in the same process) must start
  // with an empty cache on every rank: the new world's coordinator
  // assigns slots from scratch, and a replayed stale slot id would
  // execute the wrong response.  Teardown also clears (belt + braces).
  ClearCacheState();
  fusion_threshold_.store(
      EnvInt64("HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024));
  // Data-plane fan-out: HOROVOD_NUM_CHANNELS independent socket pairs per
  // ring edge (1 restores the single-socket path; default auto from the
  // core count — parallel channels need cores to drive them, and past ~4
  // the per-message overhead outweighs the loopback/NIC parallelism).
  // The value used is the COORDINATOR's, committed at rendezvous, so a
  // heterogeneous env cannot wire mismatched fan-outs.
  num_channels_ = static_cast<int>(EnvInt64("HOROVOD_NUM_CHANNELS", 0));
  if (num_channels_ <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    num_channels_ = std::min(4, std::max(1, static_cast<int>(hc)));
  }
  if (num_channels_ > 16) num_channels_ = 16;
  // Concurrent-response wave width: default = the channel fan-out
  // (exactly the pre-autotune behavior); the coordinator's resolved
  // value is committed at rendezvous next to the channel count so wave
  // grouping agrees across ranks, and TUNE frames may retune it live.
  {
    int wave = static_cast<int>(EnvInt64("HOROVOD_WAVE_WIDTH", 0));
    if (wave <= 0) wave = num_channels_;
    wave_width_.store(std::min(16, std::max(1, wave)));
  }
  socket_buf_bytes_ =
      static_cast<int>(EnvInt64("HOROVOD_SOCKET_BUF_BYTES", 0));
  {
    int64_t chunk = EnvInt64("HOROVOD_CHUNK_BYTES", 1 << 20);
    if (chunk < 4096) chunk = 4096;
    chunk_bytes_.store(chunk & ~int64_t{7});  // 8-aligned for every dtype
  }
  // Size-based algorithm selection: payloads at or under the threshold
  // take the latency star path when shm star edges exist (0 disables; the
  // coordinator's committed value is broadcast at rendezvous so every
  // rank picks the same wire pattern, and TUNE frames retune it live).
  {
    int64_t at = EnvInt64("HOROVOD_ALGO_THRESHOLD", 32 << 10);
    algo_threshold_.store(at < 0 ? 0 : at);
  }
  // Default wire format for fp32 allreduce payloads
  // (HOROVOD_WIRE_DTYPE=fp32|fp16|bf16|int8|fp8; fp32 is byte-identical
  // to the pre-compression engine and stays the default contract).
  {
    const char* w = std::getenv("HOROVOD_WIRE_DTYPE");
    int wv = 0;
    if (w != nullptr && w[0] != '\0') {
      if (std::strcmp(w, "fp32") == 0 || std::strcmp(w, "float32") == 0) {
        wv = 0;
      } else if (std::strcmp(w, "fp16") == 0 ||
                 std::strcmp(w, "float16") == 0) {
        wv = 1;
      } else if (std::strcmp(w, "bf16") == 0 ||
                 std::strcmp(w, "bfloat16") == 0) {
        wv = 2;
      } else if (std::strcmp(w, "int8") == 0) {
        wv = 3;
      } else if (std::strcmp(w, "fp8") == 0 ||
                 std::strcmp(w, "fp8e4m3") == 0) {
        wv = 4;
      } else {
        std::fprintf(stderr,
                     "horovod_tpu: unknown HOROVOD_WIRE_DTYPE '%s' (want "
                     "fp32|fp16|bf16|int8|fp8); using fp32\n", w);
      }
    }
    wire_dtype_.store(wv);
  }
  // Priority scheduling: HOROVOD_PRIORITY_BANDS is the band WIDTH
  // (band = priority / width; 0 = off — bit-identical legacy arrival
  // ordering).  The coordinator's resolution is committed at rendezvous
  // like the channel count: response ORDER is part of the wire pattern
  // (waves pair responses with channels by list index), so every rank
  // must band identically.  Live-tunable thereafter (knob #7).
  {
    int64_t pb = EnvInt64("HOROVOD_PRIORITY_BANDS", 0);
    if (pb < 0) pb = 0;
    if (pb > (1 << 20)) pb = 1 << 20;
    priority_bands_.store(pb);
  }
  // Per-band fusion-threshold ladder (autotuner-learned bucket sizes):
  // HOROVOD_FUSION_LADDER="t0,t1,..." — band b fuses up to ladder[b]
  // bytes (missing/zero entries fall back to HOROVOD_FUSION_THRESHOLD;
  // bands past the last slot share it).
  for (int b = 0; b < kFusionLadderMax; ++b) fusion_ladder_[b].store(0);
  if (const char* lad = std::getenv("HOROVOD_FUSION_LADDER");
      lad != nullptr && lad[0] != '\0') {
    std::string all(lad);
    int b = 0;
    for (size_t start = 0; start < all.size() && b < kFusionLadderMax;
         ++b) {
      size_t end = all.find(',', start);
      if (end == std::string::npos) end = all.size();
      char* endp = nullptr;
      long long v = std::strtoll(all.c_str() + start, &endp, 10);
      if (endp != nullptr && v > 0) fusion_ladder_[b].store(v);
      start = end + 1;
    }
  }
  shm_ring_bytes_ = EnvInt64("HOROVOD_SHM_RING_BYTES", 2 << 20);
  if (shm_ring_bytes_ < (1 << 16)) shm_ring_bytes_ = 1 << 16;
  // Straggler tolerance: over-provision k backup workers — the
  // coordinator commits a SUM allreduce once nvoters-k voters are ready
  // (after the grace window) instead of waiting for the whole world.
  // The coordinator's resolution is committed at rendezvous (workers
  // adopt it below, like the channel count); 0 = fully synchronous.
  {
    // HOROVOD_BACKUP_WORKERS=auto: start fully synchronous (k=0) and
    // let the coordinator arm k=1 only while its step-time window ratio
    // p99/p50 exceeds HOROVOD_BACKUP_AUTO_RATIO (default 3.0) — the
    // same percentile instrument the straggler gate judges with.
    const char* braw = std::getenv("HOROVOD_BACKUP_WORKERS");
    backup_auto_ = braw != nullptr && std::string(braw) == "auto";
    backup_armed_.store(false);
    backup_workers_ = backup_auto_
        ? 0
        : static_cast<int>(EnvInt64("HOROVOD_BACKUP_WORKERS", 0));
    if (backup_workers_ < 0) backup_workers_ = 0;
    backup_auto_ratio_ = 3.0;
    const char* rraw = std::getenv("HOROVOD_BACKUP_AUTO_RATIO");
    if (rraw != nullptr && *rraw != '\0') {
      char* end = nullptr;
      double v = std::strtod(rraw, &end);
      if (end != rraw && v > 1.0) backup_auto_ratio_ = v;
    }
  }
  backup_grace_ms_ =
      static_cast<int>(EnvInt64("HOROVOD_BACKUP_GRACE_MS", 50));
  if (backup_grace_ms_ < 0) backup_grace_ms_ = 0;
  // HOROVOD_BACKUP_AUTO_RULE: which instrument arms backup=auto —
  // "quorum" (default: per-entry quorum-lag percentiles, sees every
  // rank including a straggling coordinator) or "steptime" (the PR 12
  // rule on rank 0's own completion-latency window, kept for
  // comparability).
  backup_auto_rule_ = 0;
  if (const char* rule = std::getenv("HOROVOD_BACKUP_AUTO_RULE");
      rule != nullptr && std::strcmp(rule, "steptime") == 0) {
    backup_auto_rule_ = 1;
  }
  // Fleet telemetry cadence: every N negotiation cycles each rank
  // piggybacks counter deltas on its control frame (0 disables —
  // provably zero wire bytes: the TELEM section is simply absent).
  telemetry_cycles_ = EnvInt64("HOROVOD_TELEMETRY_CYCLES", 50);
  if (telemetry_cycles_ < 0) telemetry_cycles_ = 0;
  // A new incarnation starts a fresh fleet table (re-ranked rows from a
  // dead world would mix identities); telem_last_ deliberately SURVIVES
  // so counter deltas stay exact across the re-init.
  {
    std::lock_guard<std::mutex> lk(fleet_mu_);
    fleet_rows_.clear();
    quorum_attr_.clear();
  }
  {
    std::lock_guard<std::mutex> lk(quorum_mu_);
    quorum_lag_samples_.clear();
    quorum_lag_next_ = 0;
  }
  stall_last_warned_.clear();
  // A dead incarnation's banked skip tokens are meaningless in the new
  // world (fresh epoch, fresh commits).
  skip_tokens_.clear();
  // HOROVOD_SHM_DISABLE=1: escape hatch back to the pure-TCP data plane
  // (bit-identical — transport never changes values).  The coordinator's
  // resolution (env AND a runtime /dev/shm probe) is committed at
  // rendezvous; this env read only seeds the single-rank/world-of-one
  // value.
  shm_enabled_ = EnvInt64("HOROVOD_SHM_DISABLE", 0) == 0;
  two_level_ = false;
  shm_ring_active_ = false;
  rank_host_.clear();
  // Hierarchical coordination: the coordinator's env resolution is
  // committed in the ASSIGN frame (rendezvous sets this); refined after
  // AdoptTopology — it only activates on a >1-group topology.
  hier_coord_ = false;
  // A previous incarnation's unshipped TUNE proposal must not leak into
  // the new world (tune_trials_ stays process-cumulative like every
  // other counter).
  {
    std::lock_guard<std::mutex> lk(tune_mu_);
    tune_pending_.store(false);
  }
  channel_drivers_ =
      static_cast<int>(EnvInt64("HOROVOD_CHANNEL_DRIVERS", 0));
  if (channel_drivers_ <= 0) {
    // One driver per core: drivers mostly block in poll, so matching the
    // core count keeps every core fed without the thrash of a
    // thread-per-channel (measured on the 2-core CI box: 4 channels on
    // 2 drivers beat both 1 driver and 4).
    unsigned hc = std::thread::hardware_concurrency();
    channel_drivers_ = std::max(1, static_cast<int>(hc));
  }
  if (channel_drivers_ > 16) channel_drivers_ = 16;
  stall_check_disabled_ = EnvInt64("HOROVOD_STALL_CHECK_DISABLE", 0) != 0;
  stall_warning_sec_ =
      static_cast<int>(EnvInt64("HOROVOD_STALL_WARNING_SEC", 60));
  socket_timeout_sec_ =
      static_cast<int>(EnvInt64("HOROVOD_SOCKET_TIMEOUT_SEC", 120));
  // Link self-healing: bounded in-place reconnect of a failed data-channel
  // socket before the expensive abort/elastic machinery fires.  0 retries
  // = off (bit-for-bit the pre-heal engine).  The coordinator's resolution
  // is committed at rendezvous (workers adopt it below, like the channel
  // count).
  link_retries_ = static_cast<int>(EnvInt64("HOROVOD_LINK_RETRIES", 3));
  if (link_retries_ < 0) link_retries_ = 0;
  if (link_retries_ > 1000) link_retries_ = 1000;
  link_heal_timeout_ms_ = EnvInt64("HOROVOD_LINK_HEAL_TIMEOUT_MS", 10000);
  if (link_heal_timeout_ms_ < 1) link_heal_timeout_ms_ = 1;
  // Bound on control-plane patience for a live-but-wedged peer.  The old
  // allowance scaled as (size+4) x socket timeout (~2.3 h at 64 ranks x
  // 120 s before the descriptive abort); HOROVOD_CONTROL_PATIENCE_SEC
  // caps it.  The default keeps a mild size-aware floor because a cycle's
  // collective execution time genuinely grows with world size (a 64 MB
  // ring is size-1 hops) — 30 s/rank ~= 32 min at 64 ranks, vs hours
  // before.  Dead peers still fail fast via EOF/keepalive.
  int control_patience_sec = static_cast<int>(EnvInt64(
      "HOROVOD_CONTROL_PATIENCE_SEC",
      std::max<int64_t>(600, static_cast<int64_t>(size_) * 30)));
  // HOROVOD_FAULT_TIMEOUT_SEC: a hard failure-detection bound.  A hung
  // (not just dead) peer is only detectable by the absence of progress, so
  // cap BOTH progress bounds — the per-transfer socket timeout and the
  // control-plane patience — at a THIRD of the fault timeout: the
  // coordinator burns its patience detecting the culprit (1 round =
  // fault/3), and a worker's longer wait (2x+1 = 3 rounds, see
  // worker_patience_rounds_) still totals <= the fault timeout even in
  // the worst case where the COORDINATOR is the hung rank and no abort
  // broadcast is coming.
  // Elastic in-place membership: HOROVOD_ELASTIC=1 lets a re-init after
  // an abort commit a new world around the survivors (plus any candidates
  // that show up within the grow window) instead of requiring every
  // original rank back.
  elastic_enabled_ = EnvInt64("HOROVOD_ELASTIC", 0) != 0;
  min_size_ = static_cast<int>(EnvInt64("HOROVOD_ELASTIC_MIN_SIZE", 1));
  if (min_size_ < 1) min_size_ = 1;
  grow_timeout_sec_ =
      static_cast<int>(EnvInt64("HOROVOD_ELASTIC_GROW_TIMEOUT_SEC", 30));
  if (grow_timeout_sec_ < 1) grow_timeout_sec_ = 1;
  rendezvous_timeout_sec_ =
      static_cast<int>(EnvInt64("HOROVOD_RENDEZVOUS_TIMEOUT_SEC", 120));
  if (rendezvous_timeout_sec_ < 5) rendezvous_timeout_sec_ = 5;
  fault_timeout_sec_ =
      static_cast<int>(EnvInt64("HOROVOD_FAULT_TIMEOUT_SEC", 0));
  if (fault_timeout_sec_ > 0) {
    int third = std::max(1, fault_timeout_sec_ / 3);
    if (socket_timeout_sec_ <= 0 || socket_timeout_sec_ > third) {
      socket_timeout_sec_ = third;
    }
    control_patience_sec = std::min(control_patience_sec, third);
  }
  // Healing must finish strictly inside every OTHER rank's no-progress
  // patience: healthy ranks downstream of a healing edge stall on their
  // own cascade steps, and a heal budget past their socket timeout would
  // convert a healable blip into their "link: no progress" abort.  The
  // fault bound (when set) already capped socket_timeout_sec_ above, so
  // this single cap also keeps heal-then-escalate inside the coordinator's
  // fault-timeout verdict window.
  if (socket_timeout_sec_ > 0) {
    link_heal_timeout_ms_ = std::min<int64_t>(
        link_heal_timeout_ms_,
        static_cast<int64_t>(socket_timeout_sec_) * 1000 * 3 / 4);
    if (link_heal_timeout_ms_ < 1) link_heal_timeout_ms_ = 1;
  }
  control_patience_rounds_ =
      socket_timeout_sec_ > 0
          ? std::max(1, control_patience_sec / socket_timeout_sec_)
          : 0;  // timeout disabled: blocking reads, rounds never consulted
  // Workers out-wait the coordinator (see engine.h) so the abort verdict
  // naming the culprit wins the race against their own generic timeout.
  worker_patience_rounds_ =
      control_patience_rounds_ > 0 ? control_patience_rounds_ * 2 + 1 : 0;
  abort_reason_.clear();

  // Deterministic fault injection for the multiproc fault tests:
  // HOROVOD_FAULT_INJECT=rank:step:kind (kinds exit|hang|drop-conn).
  // One-shot per PROCESS (fault_fired_ survives re-Init): an elastic
  // recovery re-initializes the engine in the same process with the env
  // var still set, and must not re-fire the fault on every incarnation.
  fault_kind_ = FaultKind::NONE;
  fault_step_ = -1;
  enqueue_count_.store(0);
  fault_hang_.store(false);
  fault_drop_.store(false);
  fault_stale_epoch_.store(false);
  fault_conn_reset_.store(false);
  fault_stall_ms_.store(0);
  fault_reset_period_ = 1;
  fault_reset_prev_ = false;
  fault_stall_len_ms_ = 200;
  if (const char* spec = std::getenv("HOROVOD_FAULT_INJECT");
      !fault_fired_ && spec != nullptr && spec[0] != '\0') {
    // Comma-separated schedule (chaos tests inject on several ranks in
    // one job): each process arms the first entry matching its PERSISTENT
    // worker id — stable across elastic re-ranking, identical to rank in
    // a fixed world.
    std::string all(spec);
    for (size_t start = 0; start < all.size();) {
      size_t end = all.find(',', start);
      if (end == std::string::npos) end = all.size();
      std::string tok = all.substr(start, end - start);
      start = end + 1;
      // rank:step:kind[:arg] — split on ':' by hand: step may be '*'
      // (every enqueue; meaningful for `slow`) and `slow` carries a
      // 4th field (the delay in ms), neither of which sscanf's
      // %d:%lld:%s handles.
      std::vector<std::string> fields;
      for (size_t p0 = 0; p0 <= tok.size();) {
        size_t c = tok.find(':', p0);
        if (c == std::string::npos) {
          fields.push_back(tok.substr(p0));
          break;
        }
        fields.push_back(tok.substr(p0, c - p0));
        p0 = c + 1;
      }
      if (fields.size() < 3 || fields[0].empty() || fields[1].empty()) {
        continue;
      }
      // Strictly numeric rank/step fields (end-pointer checked): a
      // typo'd token must be IGNORED, not atoi'd to 0 — which would arm
      // the fault on rank 0 and kill the coordinator.
      char* endp = nullptr;
      long frank = std::strtol(fields[0].c_str(), &endp, 10);
      if (endp == nullptr || *endp != '\0') continue;
      if (frank != worker_id_) continue;
      long long fstep = -2;
      if (fields[1] != "*") {
        fstep = std::strtoll(fields[1].c_str(), &endp, 10);
        if (endp == nullptr || *endp != '\0' || fstep < 0) continue;
      }
      const std::string& fkind = fields[2];
      fault_step_ = fstep;
      if (fkind == "exit") {
        fault_kind_ = FaultKind::EXIT;
      } else if (fkind == "hang") {
        fault_kind_ = FaultKind::HANG;
      } else if (fkind == "drop-conn") {
        fault_kind_ = FaultKind::DROP_CONN;
      } else if (fkind == "stale-epoch") {
        fault_kind_ = FaultKind::STALE_EPOCH;
      } else if (fkind == "slow") {
        // rank:step:slow:ms — a deterministic per-step enqueue delay:
        // the API thread sleeps before the enqueue while the background
        // loop keeps heartbeating, i.e. a straggler, not a wedge.
        fault_kind_ = FaultKind::SLOW;
        fault_slow_ms_ = fields.size() > 3
            ? std::strtoll(fields[3].c_str(), nullptr, 10) : 100;
        if (fault_slow_ms_ < 0) fault_slow_ms_ = 0;
      } else if (fkind == "conn-reset") {
        // rank:step:conn-reset[:K][:prev] — this rank shutdown(2)s one of
        // its OWN data-channel sockets mid-cascade (the link-heal driver
        // fault).  Optional numeric field = re-arm period for step '*'
        // (a flap schedule); optional 'prev' shoots the recv-side socket,
        // which discards buffered inbound bytes — the lost-data case.
        fault_kind_ = FaultKind::CONN_RESET;
        for (size_t fi = 3; fi < fields.size(); ++fi) {
          if (fields[fi] == "prev") {
            fault_reset_prev_ = true;
          } else if (!fields[fi].empty()) {
            long long period =
                std::strtoll(fields[fi].c_str(), &endp, 10);
            if (endp != nullptr && *endp == '\0' && period > 0) {
              fault_reset_period_ = period;
            }
          }
        }
      } else if (fkind == "ckpt-kill") {
        // rank:step:ckpt-kill — Python-owned: the checkpoint writer
        // parses the shared schedule itself and SIGKILLs mid-shard-write
        // (the kill must land between the tmp file's two half-writes,
        // which only the writer can time).  Accept the kind silently so
        // the shared parser does not warn, and keep scanning for an
        // engine-side kind on this rank.
        fault_step_ = -1;
        fault_kind_ = FaultKind::NONE;
        continue;
      } else if (fkind == "recv-stall") {
        // rank:step:recv-stall:ms — the next cascade on this rank stops
        // draining one channel for ms (a transient stall, not a dead
        // link): the collective must complete with zero aborts AND zero
        // reconnects — healing classifies, waits, and stands down.
        fault_kind_ = FaultKind::RECV_STALL;
        fault_stall_len_ms_ = fields.size() > 3
            ? std::strtoll(fields[3].c_str(), nullptr, 10) : 200;
        if (fault_stall_len_ms_ < 1) fault_stall_len_ms_ = 1;
      } else {
        std::fprintf(stderr,
                     "horovod_tpu: unknown HOROVOD_FAULT_INJECT kind '%s' "
                     "(want exit|hang|drop-conn|stale-epoch|slow|"
                     "conn-reset|recv-stall|ckpt-kill); ignored\n",
                     fkind.c_str());
        fault_step_ = -1;
        fault_kind_ = FaultKind::NONE;
        continue;
      }
      break;
    }
  }
  if (size_ > 1) {
    std::string host = "127.0.0.1";
    int port = 0;
    auto colon = coordinator_addr.rfind(':');
    if (colon != std::string::npos) {
      host = coordinator_addr.substr(0, colon);
      port = std::atoi(coordinator_addr.c_str() + colon + 1);
    }
    if (port == 0) {
      last_error_ = "coordinator address host:port required for size > 1";
      return 1;
    }
    // Job tag for shm segment names: the coordinator port is unique per
    // live job on a host, and every name is additionally epoch-stamped.
    shm_prefix_ = "hvd" + std::to_string(port) + "_";
    std::string err;
    const char* my_host_env = std::getenv("HOROVOD_HOST");
    std::string my_host = my_host_env ? my_host_env : "127.0.0.1";

    // Every rank opens an ephemeral data listener for ring neighbors.
    // Backlog covers the MAXIMUM channel fan-out (16) arriving at once
    // during wiring — the committed count is only known after
    // rendezvous, and the coordinator's may exceed this rank's env
    // value (overflowed connects retry, but the backlog avoids the
    // retry latency on the common path).
    int data_port = 0;
    data_listener_ = Listen("0.0.0.0", 0, 16 + 8, &data_port, &err);
    if (!data_listener_.valid()) {
      last_error_ = "data listener: " + err;
      return 1;
    }

    // Rendezvous: workers report (worker id, host, data_port) to the
    // coordinator, which commits a membership epoch and broadcasts
    // (epoch, assigned rank, size, peer table) — the moral equivalent of
    // MPI_Init's wire-up or NCCL's ncclUniqueId broadcast (reference
    // operations.cc:894-931), extended with elastic re-formation around
    // survivors (HOROVOD_ELASTIC=1).
    std::vector<std::string> peer_hosts;
    std::vector<int> peer_ports;
    int rdv = rank_ == 0
        ? CoordinatorRendezvous(host, port, my_host, data_port,
                                &peer_hosts, &peer_ports)
        : WorkerRendezvous(host, port, my_host, data_port,
                           &peer_hosts, &peer_ports);
    if (rdv != 0) return rdv;
    // rank_/size_/epoch_ now reflect the COMMITTED world, which on an
    // elastic re-init may be smaller than the env identity.  A world
    // shrunk to one keeps its control listener open (a later candidate
    // triggers a grow re-rendezvous) but wires no rings.
    // Derive the topology view from the committed grouping: identical on
    // every rank (the table was broadcast), so leader tables, shm edge
    // names and the two-level message pattern agree across the world.
    AdoptTopology();
    // Two-level collectives need BOTH a multi-group world and at least
    // one group worth decomposing; shm must be committed because the
    // intra-group phases run over shm edges.  Everything else (single
    // host, one-rank-per-host, shm off) is a flat ring — over shm when
    // the whole world is one group and shm is on, over TCP otherwise.
    two_level_ = shm_enabled_ && nnodes_ > 1 && size_ > nnodes_;
    // Control-plane hierarchy activates on any committed >1-group
    // topology with at least one multi-member group — independent of
    // shm: the member ↔ leader control conns are plain TCP, so a
    // synthetic host grouping (HOROVOD_HOST_KEY) scales the control
    // plane even where the data plane fell back to the flat ring.
    hier_coord_ = hier_coord_ && nnodes_ > 1 && size_ > nnodes_;
    if (!shm_enabled_ && nnodes_ > 1 && size_ > nnodes_ && rank_ == 0) {
      // A hierarchical topology exists but the intra-group phases cannot
      // run (shm off or unavailable on some host), so every rank joins
      // the flat cross-network ring.  Loud, because the bandwidth cost
      // is size_/nnodes_ extra ring participants per real link.
      std::fprintf(stderr,
                   "horovod_tpu: %d hosts x %d ranks committed but shared "
                   "memory is %s — collectives fall back to the flat "
                   "world-wide TCP ring (no per-host leaders).\n",
                   nnodes_, size_ / nnodes_,
                   EnvInt64("HOROVOD_SHM_DISABLE", 0) != 0
                       ? "disabled (HOROVOD_SHM_DISABLE=1)"
                       : "unavailable on at least one host");
    }
    if (size_ > 1) {
    // Ring wiring.  Each directed ring edge is its own TCP connection —
    // the GLOBAL ring opens num_channels_ independent connections per
    // edge (the data-plane fan-out; each channel later carries its own
    // shard of a collective) — opened by the edge's source, identified
    // by an (origin rank, ring id, channel, epoch) handshake.  The epoch
    // stamp makes elastic re-rendezvous airtight per channel: a stale
    // connect from a dead incarnation is dropped instead of stealing a
    // channel slot in the new world's wiring.  Connect cannot deadlock:
    // every listener already exists, so connects complete from the
    // backlog even before the peer accepts.
    struct Edge {
      int peer;
      int32_t ring;
      int32_t channel;
      Socket* slot;
    };
    ring_next_.clear();
    ring_prev_.clear();
    ring_next_.resize(num_channels_);
    ring_prev_.resize(num_channels_);
    cross_next_.clear();
    cross_prev_.clear();
    // Link self-healing plumbing: the committed peer table outlives
    // wiring (mid-run reconnect targets), the cascade stream sequences
    // restart per incarnation (a RESUME carries the epoch, so stale
    // sequences can't collide), and a dead incarnation's parked resumes
    // are dropped.
    peer_hosts_ = peer_hosts;
    peer_ports_ = peer_ports;
    link_seq_global_.assign(num_channels_, 0);
    link_seq_cross_.assign(num_channels_, 0);
    HealInboxClear();
    std::vector<Edge> outgoing, incoming;
    for (int32_t c = 0; c < num_channels_; ++c) {
      outgoing.push_back(
          {(rank_ + 1) % size_, RING_GLOBAL, c, &ring_next_[c]});
      incoming.push_back(
          {(rank_ - 1 + size_) % size_, RING_GLOBAL, c, &ring_prev_[c]});
    }
    // Hierarchical-coordination control edges: every non-leader member
    // wires ONE control connection to its group leader (the leader's
    // per-cycle aggregation fan-in), reusing the epoch-stamped data-ring
    // handshake so a dead incarnation's connect can never steal a slot.
    leader_conn_.Close();
    member_conns_.clear();
    if (hier_coord_ && group_size_ > 1) {
      if (local_index_ == 0) {
        member_conns_.resize(group_size_);
        for (int m = 1; m < group_size_; ++m) {
          incoming.push_back({group_members_[m], RING_CTRL, 0,
                              &member_conns_[m]});
        }
      } else {
        outgoing.push_back({group_members_[0], RING_CTRL, 0, &leader_conn_});
      }
    }
    if (two_level_ && local_index_ == 0 && nnodes_ > 1) {
      // One leader per host participates in the inter-host ring, with the
      // full channel fan-out (this is the hop that crosses a real
      // network, so it gets the same sharded streaming cascade as the
      // flat ring).
      cross_next_.resize(num_channels_);
      cross_prev_.resize(num_channels_);
      for (int32_t c = 0; c < num_channels_; ++c) {
        outgoing.push_back({group_leaders_[(node_id_ + 1) % nnodes_], RING_CROSS,
                            c, &cross_next_[c]});
        incoming.push_back({group_leaders_[(node_id_ - 1 + nnodes_) %
                                           nnodes_],
                            RING_CROSS, c, &cross_prev_[c]});
      }
    }
    for (auto& edge : outgoing) {
      *edge.slot = ConnectRetry(peer_hosts[edge.peer], peer_ports[edge.peer],
                                60000, &err);
      if (!edge.slot->valid()) {
        last_error_ = "ring connect to rank " + std::to_string(edge.peer) +
                      ": " + err;
        return 1;
      }
      int32_t hello[4] = {rank_, edge.ring, edge.channel,
                          static_cast<int32_t>(epoch_.load())};
      if (!edge.slot->SendAll(hello, sizeof(hello))) {
        last_error_ = "ring handshake send failed";
        return 1;
      }
    }
    // Bounded ring accepts: a neighbor that died between rendezvous and
    // wiring must surface as a clean init error, not park the accept
    // forever (Accept honors the listener timeout; see socket.cc).
    data_listener_.SetTimeouts(5);
    auto ring_deadline = std::chrono::steady_clock::now() +
                         std::chrono::seconds(rendezvous_timeout_sec_);
    for (size_t matched_edges = 0; matched_edges < incoming.size();) {
      Socket conn;
      while (!conn.valid()) {
        if (std::chrono::steady_clock::now() > ring_deadline) {
          last_error_ = "ring accept: timed out waiting for neighbor "
                        "connections — a peer likely died during wiring";
          return 1;
        }
        conn = Accept(data_listener_, &err);
        if (!conn.valid() && err != kAcceptTimedOut) {
          last_error_ = "ring accept: " + err;
          return 1;
        }
      }
      conn.SetTimeouts(10);
      int32_t hello[4] = {-1, -1, -1, -1};
      if (!conn.RecvAll(hello, sizeof(hello))) {
        last_error_ = "ring handshake recv failed";
        return 1;
      }
      if (hello[3] != static_cast<int32_t>(epoch_.load())) {
        // A dead incarnation's delayed wiring connect (elastic
        // re-rendezvous raced the old world's teardown): drop it and
        // keep accepting this epoch's channels.
        continue;
      }
      bool matched = false;
      for (auto& edge : incoming) {
        if (edge.peer == hello[0] && edge.ring == hello[1] &&
            edge.channel == hello[2] && !edge.slot->valid()) {
          *edge.slot = std::move(conn);
          matched = true;
          ++matched_edges;
          break;
        }
      }
      if (!matched) {
        last_error_ = "unexpected ring handshake from rank " +
                      std::to_string(hello[0]) + " ring " +
                      std::to_string(hello[1]) + " channel " +
                      std::to_string(hello[2]);
        return 1;
      }
    }

    // Robustness: bound every blocking transport op and probe idle peers
    // so a dead/hung process surfaces as a clean error, not a hang.  Ring
    // data sockets additionally get HOROVOD_SOCKET_BUF_BYTES so the
    // kernel can stream ahead while userland reduces.
    std::vector<Socket*> data_socks;
    for (auto& s : ring_next_) data_socks.push_back(&s);
    for (auto& s : ring_prev_) data_socks.push_back(&s);
    for (auto& s : cross_next_) data_socks.push_back(&s);
    for (auto& s : cross_prev_) data_socks.push_back(&s);
    // ArmSocketDeadlines = keepalive probing PLUS TCP_USER_TIMEOUT bound
    // to the (fault-capped) socket timeout: a silently-dead peer errors
    // the socket inside the fault bound — data channels get a
    // classifiable error the link-heal layer can act on, and control
    // conns (rendezvous/CTRL) stop depending solely on the coordinator's
    // patience for dead-peer detection.
    std::vector<Socket*> socks = data_socks;
    socks.push_back(&coordinator_conn_);
    for (Socket* s : socks) {
      if (s->valid()) {
        s->SetTimeouts(socket_timeout_sec_);
        ArmSocketDeadlines(*s, socket_timeout_sec_);
      }
    }
    for (Socket* s : data_socks) {
      if (s->valid()) s->SetBufSizes(socket_buf_bytes_);
    }
    for (auto& c : worker_conns_) {
      if (c.valid()) {
        c.SetTimeouts(socket_timeout_sec_);
        ArmSocketDeadlines(c, socket_timeout_sec_);
      }
    }
    // Hierarchical control edges get the control-plane transport bounds
    // (not the data-socket buffer sizing): a dead member/leader must
    // surface within the same patience budget as any control peer.
    if (leader_conn_.valid()) {
      leader_conn_.SetTimeouts(socket_timeout_sec_);
      ArmSocketDeadlines(leader_conn_, socket_timeout_sec_);
    }
    for (auto& c : member_conns_) {
      if (c.valid()) {
        c.SetTimeouts(socket_timeout_sec_);
        ArmSocketDeadlines(c, socket_timeout_sec_);
      }
    }
    // Shared-memory intra-host edges: the second channel kind.  Wired
    // AFTER the TCP rings so a failure here can still use BroadcastAbort-
    // free cleanup (init error on every rank via its own wiring timeout).
    if (shm_enabled_ && group_size_ > 1) {
      std::string shm_err;
      if (!WireShmEdges(&shm_err)) {
        last_error_ = "shm wiring: " + shm_err;
        CloseShmEdges();
        return 1;
      }
      shm_ring_active_ = true;
    }
    // Data-plane pool: one worker per channel drives channel shards,
    // concurrent responses, and large parallel reductions.
    pool_.Start(num_channels_);
    }  // committed size_ > 1: ring wiring + transport bounds
  } else {
    // Env-identity world of one (no rendezvous ran): commit a local epoch
    // so restarts still advance it and stats stay meaningful.
    epoch_.fetch_add(1);
  }

  // Timeline: initialized AFTER rendezvous so the file name reflects the
  // COMMITTED rank (an elastic re-rank would otherwise mislabel tracks)
  // and the header can carry the rendezvous-estimated clock offset.
  // Rank 0 keeps the exact HOROVOD_TIMELINE path (back-compat);
  // HOROVOD_TIMELINE_ALL_RANKS=1 adds "<path>.rank<r>" per worker so
  // `python -m horovod_tpu.timeline merge` can build the fleet view.
  if (const char* tl = std::getenv("HOROVOD_TIMELINE");
      tl != nullptr && tl[0] != '\0') {
    timeline_.SetMaxBytes(EnvInt64("HOROVOD_TIMELINE_MAX_MB", 0) << 20);
    if (rank_ == 0) {
      timeline_.Initialize(tl);
    } else if (EnvInt64("HOROVOD_TIMELINE_ALL_RANKS", 0) != 0) {
      timeline_.Initialize(std::string(tl) + ".rank" +
                           std::to_string(rank_));
    }
    timeline_.SetMeta(rank_, epoch_.load(), clock_offset_ns_);
  }
  // Flight recorder: ring is in-memory always (capacity knob); dumps
  // need a sink dir.  The fatal-signal handlers are installed only when
  // a sink exists — without one a dump is a no-op anyway, and default
  // signal dispositions stay untouched.
  {
    int cap =
        static_cast<int>(EnvInt64("HOROVOD_FLIGHT_RECORDER_EVENTS", 256));
    const char* dir = std::getenv("HOROVOD_FLIGHT_RECORDER_DIR");
    GlobalFlightRecorder().Configure(cap, dir ? dir : "", rank_,
                                     epoch_.load(), clock_offset_ns_);
    if (dir != nullptr && dir[0] != '\0') InstallFlightSignalHandlers();
    GlobalFlightRecorder().Record(
        "epoch", control_cycle_seq_,
        "committed epoch=%lld rank=%d size=%d hosts=%d",
        static_cast<long long>(epoch_.load()), rank_, size_, nnodes_);
  }
  last_stall_check_ = std::chrono::steady_clock::now();
  last_sub_stall_check_ = last_stall_check_;
  last_exec_time_ = std::chrono::steady_clock::now();
  fusion_buffers_.assign(std::max(1, num_channels_),
                         std::vector<uint8_t>());
  initialized_.store(true);
  background_ = std::thread(&Engine::BackgroundLoop, this);
  return 0;
}

// Tag on every JOIN frame ("HVJN"): the coordinator's listener is a
// well-known port, and an untagged stray connection (health probe, port
// scanner) must never be mistaken for a membership candidate — in the
// mid-run path that mistake would abort the whole world.
static constexpr uint32_t kJoinMagic = 0x4e4a5648u;

// Clock-sync ping ("HVPG"), folded into the JOIN/ASSIGN handshake: right
// after adopting its ASSIGN each worker runs kClockPings request/reply
// rounds against the coordinator's rendezvous conn and keeps the min-RTT
// midpoint estimate of rank 0's monotonic clock vs its own — the offset
// the merged timeline and the flight-recorder post-mortem align tracks
// with.  Serial per-worker service is fine: only a worker's FIRST round
// can queue behind another worker's service, and min-RTT discards it.
static constexpr uint32_t kPingMagic = 0x47505648u;
static constexpr int kClockPings = 5;

static int64_t MonoNowNs() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Coordinator-led membership rendezvous (see engine.h).  The first init
// (and every non-elastic re-init) requires the full env world within
// HOROVOD_RENDEZVOUS_TIMEOUT_SEC; an elastic re-init instead waits a
// bounded HOROVOD_ELASTIC_GROW_TIMEOUT_SEC grace window for relaunched or
// new candidates and then commits the survivors — contiguous ranks sorted
// by persistent worker id, epoch + 1 — or rejects everyone with a clean
// terminal error below HOROVOD_ELASTIC_MIN_SIZE.
int Engine::CoordinatorRendezvous(const std::string& host, int port,
                                  const std::string& my_host, int data_port,
                                  std::vector<std::string>* peer_hosts,
                                  std::vector<int>* peer_ports) {
  std::string err;
  const bool regrow = elastic_enabled_ && epoch_.load() > 0;
  control_listener_ = Listen(host, port, world_size_ + 8, nullptr, &err);
  if (!control_listener_.valid()) {
    last_error_ = "coordinator listen on " + host + ":" +
                  std::to_string(port) + ": " + err;
    return 1;
  }
  // Tolerant accept loop: a restart can race a dying previous engine's
  // listener — workers whose connect landed there retry against this one,
  // so dead/garbled/duplicate connections are dropped (latest join per
  // worker id wins — safe because a worker id's old-world and new-world
  // incarnations act sequentially) rather than failing the init.  Accept
  // and each frame read are bounded so a silent remnant cannot park the
  // loop, and the whole wait has a deadline.
  control_listener_.SetTimeouts(2);  // Accept honors SO_RCVTIMEO
  struct JoinInfo {
    std::string host;
    std::string host_key;
    int data_port = 0;
    int32_t lr = 0, ls = 1;
    uint8_t shm_ok = 0;
    Socket conn;
  };
  std::map<int, JoinInfo> joined;  // worker id → latest join (sorted)
  const int64_t window_ms =
      (regrow ? grow_timeout_sec_ : rendezvous_timeout_sec_) * 1000ll;
  auto rdv_deadline = std::chrono::steady_clock::now() +
                      std::chrono::milliseconds(window_ms);
  while (static_cast<int>(joined.size()) < world_size_ - 1) {
    if (std::chrono::steady_clock::now() > rdv_deadline) {
      if (regrow) break;  // grace window over: commit whoever showed up
      last_error_ = "rendezvous timed out: heard from " +
                    std::to_string(joined.size()) + " of " +
                    std::to_string(world_size_ - 1) +
                    " workers — check the other ranks' logs";
      return 1;
    }
    Socket conn = Accept(control_listener_, &err);
    if (!conn.valid()) {
      continue;  // accept timeout tick; re-check the deadline
    }
    conn.SetTimeouts(10);
    std::vector<uint8_t> frame;
    if (!conn.RecvFrame(&frame)) {
      continue;  // peer gave up (retrying) or stale/silent remnant
    }
    Reader r(frame.data(), frame.size());
    uint32_t magic = r.u32();
    int32_t id = r.i32();
    std::string peer_host = r.str();
    int32_t peer_port = r.i32();
    int32_t lr = r.i32(), ls = r.i32();
    // Co-location fields (hostname#boot-id + a local /dev/shm probe
    // verdict): the coordinator groups ranks by host key and commits the
    // world-wide shm decision from the AND of every member's probe.
    std::string peer_key = r.str();
    uint8_t peer_shm = r.u8();
    if (!r.ok() || magic != kJoinMagic || id < 1 || id >= world_size_) {
      continue;  // not a join frame from this job
    }
    JoinInfo info;
    info.host = std::move(peer_host);
    info.host_key = std::move(peer_key);
    info.data_port = peer_port;
    info.lr = lr;
    info.ls = ls;
    info.shm_ok = peer_shm;
    info.conn = std::move(conn);
    joined[id] = std::move(info);
  }

  // Membership commit: contiguous ranks over {coordinator} ∪ survivors,
  // sorted by worker id (std::map iteration order).
  const int new_size = static_cast<int>(joined.size()) + 1;
  const int64_t new_epoch = epoch_.load() + 1;
  if (regrow && new_size < min_size_) {
    std::string msg =
        "elastic membership: the world shrank to " + std::to_string(new_size) +
        " worker(s), below HOROVOD_ELASTIC_MIN_SIZE=" +
        std::to_string(min_size_) + " (no replacement joined within the " +
        std::to_string(grow_timeout_sec_) +
        "s HOROVOD_ELASTIC_GROW_TIMEOUT_SEC window); terminating cleanly";
    Writer w;
    w.u8(1);  // reject
    w.str(msg);
    for (auto& kv : joined) kv.second.conn.SendFrame(w.bytes());
    last_error_ = msg;
    std::fprintf(stderr, "horovod_tpu coordinator: %s\n", msg.c_str());
    return 1;
  }
  peer_hosts->assign(new_size, "");
  peer_ports->assign(new_size, 0);
  std::vector<int32_t> peer_lr(new_size, 0), peer_ls(new_size, 1);
  std::vector<std::string> peer_keys(new_size);
  std::vector<int> member_ids(new_size, 0);
  std::vector<Socket> conns(new_size);
  bool shm_commit = shm_enabled_ && ShmAvailable();
  (*peer_hosts)[0] = my_host;
  (*peer_ports)[0] = data_port;
  peer_lr[0] = local_rank_;
  peer_ls[0] = local_size_;
  peer_keys[0] = HostKey();
  int next_rank = 1;
  for (auto& kv : joined) {
    (*peer_hosts)[next_rank] = kv.second.host;
    (*peer_ports)[next_rank] = kv.second.data_port;
    peer_lr[next_rank] = kv.second.lr;
    peer_ls[next_rank] = kv.second.ls;
    peer_keys[next_rank] = kv.second.host_key;
    shm_commit = shm_commit && kv.second.shm_ok != 0;
    member_ids[next_rank] = kv.first;
    conns[next_rank] = std::move(kv.second.conn);
    ++next_rank;
  }
  // Coordinator commits the host grouping GLOBALLY.  Default: group by
  // the JOIN frames' host keys (hostname#boot-id — genuinely co-located
  // ranks share one), ids assigned by first appearance in committed rank
  // order so every rank derives identical leader tables.
  // HOROVOD_HIERARCHICAL_ALLREDUCE=1 instead synthesizes a block grouping
  // rank/local_size (the reference's is_homogeneous layout,
  // operations.cc:1511-1525) — the way tests and single-host benches
  // force a multi-group topology — provided every member reports the
  // same local_size and block placement under the NEW ranks; a shrunken
  // world that broke the layout falls back to host keys automatically.
  std::vector<int32_t> groups(new_size, 0);
  bool want_hier = EnvInt64("HOROVOD_HIERARCHICAL_ALLREDUCE", 0) != 0;
  bool hier_ok = want_hier && local_size_ > 1 &&
                 new_size % local_size_ == 0 && new_size > local_size_;
  for (int i = 0; hier_ok && i < new_size; ++i) {
    hier_ok = peer_ls[i] == local_size_ && peer_lr[i] == i % local_size_;
  }
  if (want_hier && !hier_ok) {
    std::fprintf(stderr,
                 "horovod_tpu: HOROVOD_HIERARCHICAL_ALLREDUCE ignored — "
                 "needs a homogeneous block layout (equal local_size > 1 "
                 "dividing size, local_rank == rank %% local_size on "
                 "every rank); grouping by host key instead.\n");
  }
  if (hier_ok) {
    for (int i = 0; i < new_size; ++i) groups[i] = i / local_size_;
  } else {
    std::unordered_map<std::string, int32_t> key_ids;
    for (int i = 0; i < new_size; ++i) {
      auto it = key_ids.find(peer_keys[i]);
      if (it == key_ids.end()) {
        it = key_ids.emplace(peer_keys[i],
                             static_cast<int32_t>(key_ids.size())).first;
      }
      groups[i] = it->second;
    }
  }
  rank_host_ = groups;
  shm_enabled_ = shm_commit;
  if (backup_workers_ >= new_size) backup_workers_ = new_size - 1;
  // Control-plane hierarchy: the coordinator's env resolution is THE
  // resolution (default on; =0 restores the flat rank-0 star bit-for-
  // bit) — a per-rank split would leave leaders aggregating members
  // that still talk straight to rank 0.
  hier_coord_ = EnvInt64("HOROVOD_HIERARCHICAL_COORDINATOR", 1) != 0;
  // Crash-mid-wiring leftovers from dead incarnations: no current-epoch
  // segment exists yet (members create edges only after ASSIGN), so
  // everything under this job's prefix is stale.
  if (shm_enabled_) ShmSweepStale(shm_prefix_);
  // Peer-table compaction: the host strings are near-always a handful of
  // distinct values repeated across ranks — dictionary-encode them once
  // and reference by varint index, with ports/group ids as varints too,
  // so ASSIGN bytes grow with hosts + ranks·few-bytes instead of
  // ranks·(host string + 8).  assign_bytes_tx counts what actually went
  // out, per member, re-rendezvous included.
  std::vector<std::string> uniq_hosts;
  {
    std::unordered_map<std::string, uint32_t> seen_hosts;
    for (int i = 0; i < new_size; ++i) {
      if (seen_hosts.emplace((*peer_hosts)[i],
                             static_cast<uint32_t>(uniq_hosts.size()))
              .second) {
        uniq_hosts.push_back((*peer_hosts)[i]);
      }
    }
  }
  std::unordered_map<std::string, uint32_t> host_ids;
  for (uint32_t i = 0; i < uniq_hosts.size(); ++i) {
    host_ids[uniq_hosts[i]] = i;
  }
  for (int r = 1; r < new_size; ++r) {
    Writer w;
    w.u8(0);  // ok
    w.i64(new_epoch);
    w.i32(r);  // assigned rank
    w.i32(new_size);
    // Committed shm verdict (env escape hatch AND every member's runtime
    // probe): per-rank fallback would desync the wire pattern, so the
    // whole world runs shm or none of it does.
    w.u8(shm_enabled_ ? 1 : 0);
    // Committed control-plane hierarchy flag (see hier_coord_ above).
    w.u8(hier_coord_ ? 1 : 0);
    // The coordinator's data-plane fan-out is THE fan-out: every member
    // wires exactly this many channels per ring edge, so a rank whose
    // env disagrees cannot deadlock the channel accepts.  The wave width
    // rides along for the same reason: concurrent responses pick
    // channels by list index, so mismatched wave grouping would pair
    // different responses on one socket.  The algorithm-selection
    // crossover is committed here too — a size-based path split is a
    // different wire pattern, so every rank must agree on the threshold.
    w.i32(num_channels_);
    w.i32(wave_width_.load());
    w.i64(algo_threshold_.load());
    // Committed backup-worker over-provisioning (clamped to the
    // committed world): behavior is driven by the per-cycle participant
    // bitmaps, but stats()["config"] must agree on every rank.
    w.i32(backup_workers_);
    // Committed link-heal knobs: healing is a two-sided protocol (the
    // sender re-dials, the receiver accepts+ACKs), so one endpoint
    // healing an edge the other's env already abandoned must be
    // impossible by construction.
    w.i32(link_retries_);
    w.i64(link_heal_timeout_ms_);
    // Committed priority band width: response ORDER is wire pattern
    // (waves pick channels by list index), so the whole world bands
    // identically or not at all.
    w.i64(priority_bands_.load());
    w.vu(uniq_hosts.size());
    for (const auto& h : uniq_hosts) w.str(h);
    for (int i = 0; i < new_size; ++i) {
      w.vu(host_ids[(*peer_hosts)[i]]);
      w.vu(static_cast<uint64_t>((*peer_ports)[i]));
      w.vu(static_cast<uint64_t>(groups[i]));
    }
    if (!conns[r].SendFrame(w.bytes())) {
      last_error_ = "rendezvous assign to worker id " +
                    std::to_string(member_ids[r]) + " failed";
      return 1;
    }
    assign_bytes_tx_.fetch_add(static_cast<int64_t>(w.bytes().size()) + 8);
  }
  // Clock-sync service (see kPingMagic): each worker pings right after
  // parsing its ASSIGN; serve every member's rounds before the cycle
  // loop takes over the conns.
  for (int r = 1; r < new_size; ++r) {
    for (int k = 0; k < kClockPings; ++k) {
      std::vector<uint8_t> pf;
      if (!conns[r].RecvFrame(&pf)) {
        last_error_ = "clock-sync ping from worker id " +
                      std::to_string(member_ids[r]) + " failed";
        return 1;
      }
      Reader pr(pf.data(), pf.size());
      uint32_t magic = pr.u32();
      (void)pr.i64();  // worker's t0 (only the worker needs it)
      if (!pr.ok() || magic != kPingMagic) {
        last_error_ = "bad clock-sync ping frame";
        return 1;
      }
      Writer pw;
      pw.i64(MonoNowNs());
      if (!conns[r].SendFrame(pw.bytes())) {
        last_error_ = "clock-sync reply to worker id " +
                      std::to_string(member_ids[r]) + " failed";
        return 1;
      }
    }
  }
  clock_offset_ns_ = 0;  // rank 0 IS the reference clock
  worker_conns_.clear();
  worker_conns_.resize(new_size);
  for (int r = 1; r < new_size; ++r) worker_conns_[r] = std::move(conns[r]);
  if (regrow || new_size != world_size_) {
    std::string members;
    for (int i = 0; i < new_size; ++i) {
      if (!members.empty()) members += ",";
      members += std::to_string(member_ids[i]);
    }
    std::fprintf(stderr,
                 "horovod_tpu coordinator: committed membership epoch %lld: "
                 "size %d (worker ids %s)\n",
                 static_cast<long long>(new_epoch), new_size,
                 members.c_str());
  }
  rank_ = 0;
  size_ = new_size;
  epoch_.store(new_epoch);
  return 0;
}

int Engine::WorkerRendezvous(const std::string& host, int port,
                             const std::string& my_host, int data_port,
                             std::vector<std::string>* peer_hosts,
                             std::vector<int>* peer_ports) {
  std::string err;
  // Retry the whole connect+exchange: after a restart, the first connect
  // can land on the PREVIOUS engine's closing listener and die with EOF
  // before the assignment arrives — the new listener is up moments later.
  // A mid-run join candidate's first exchange dies the same way when the
  // coordinator tears the running world down to admit it.
  int64_t join_ms = static_cast<int64_t>(rendezvous_timeout_sec_) * 1000;
  if (elastic_enabled_) {
    join_ms += static_cast<int64_t>(grow_timeout_sec_) * 2000 + 30000;
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(join_ms);
  std::string lasterr = "rendezvous timed out";
  while (std::chrono::steady_clock::now() < deadline) {
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    deadline - std::chrono::steady_clock::now())
                    .count();
    coordinator_conn_ = ConnectRetry(host, port, static_cast<int>(left),
                                     &err);
    if (!coordinator_conn_.valid()) {
      lasterr = err;
      break;
    }
    // Bound the exchange: a connect that landed on a wedged previous
    // listener must time out and retry, not block forever.
    coordinator_conn_.SetTimeouts(10);
    Writer w;
    w.u32(kJoinMagic);
    w.i32(worker_id_);
    w.str(my_host);
    w.i32(data_port);
    w.i32(local_rank_);
    w.i32(local_size_);
    // Co-location identity + this host's shm capability: the coordinator
    // groups by the key and ANDs the probes into the committed verdict.
    w.str(HostKey());
    w.u8(shm_enabled_ && ShmAvailable() ? 1 : 0);
    std::vector<uint8_t> frame;
    // The assignment legitimately takes as long as the slowest member's
    // arrival plus — elastic — the entire grow grace window the
    // coordinator holds open for further candidates.
    int idle_rounds = 11 + (elastic_enabled_ ? grow_timeout_sec_ / 10 + 2
                                             : 0);
    if (!coordinator_conn_.SendFrame(w.bytes()) ||
        !coordinator_conn_.RecvFrame(&frame, idle_rounds)) {
      lasterr = "rendezvous exchange failed";
      coordinator_conn_.Close();
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
      continue;
    }
    Reader r(frame.data(), frame.size());
    uint8_t status = r.u8();
    if (status != 0) {
      // Terminal membership rejection (e.g. the surviving world is below
      // HOROVOD_ELASTIC_MIN_SIZE): no retry will change the verdict.
      std::string msg = r.str();
      last_error_ = (r.ok() && !msg.empty())
                        ? msg
                        : "membership rejected by the coordinator";
      std::fprintf(stderr, "horovod_tpu worker id %d: %s\n", worker_id_,
                   last_error_.c_str());
      return 1;
    }
    int64_t new_epoch = r.i64();
    int32_t new_rank = r.i32();
    int32_t new_size = r.i32();
    uint8_t shm_on = r.u8();
    uint8_t hier_on = r.u8();
    int32_t committed_channels = r.i32();
    int32_t committed_wave = r.i32();
    int64_t committed_algo = r.i64();
    int32_t committed_backup = r.i32();
    int32_t committed_link_retries = r.i32();
    int64_t committed_heal_ms = r.i64();
    int64_t committed_bands = r.i64();
    if (!r.ok() || new_size < 1 || new_rank < 0 || new_rank >= new_size ||
        committed_channels < 1 || committed_channels > 16 ||
        committed_wave < 1 || committed_wave > 16 || committed_algo < 0 ||
        committed_backup < 0 || committed_backup >= new_size ||
        committed_link_retries < 0 || committed_link_retries > 1000 ||
        committed_heal_ms < 1 || committed_bands < 0 ||
        committed_bands > (1 << 20)) {
      lasterr = "bad membership assignment frame";
      break;
    }
    // Dictionary-coded peer table (see CoordinatorRendezvous): unique
    // host strings once, then per-rank (host index, port, group id)
    // varint triples.
    uint64_t nhosts = r.vu();
    if (!r.ok() || nhosts < 1 ||
        nhosts > static_cast<uint64_t>(new_size)) {
      lasterr = "bad membership assignment frame";
      break;
    }
    std::vector<std::string> uniq_hosts(nhosts);
    for (uint64_t i = 0; i < nhosts; ++i) uniq_hosts[i] = r.str();
    peer_hosts->assign(new_size, "");
    peer_ports->assign(new_size, 0);
    rank_host_.assign(new_size, 0);
    bool groups_ok = true;
    for (int i = 0; i < new_size; ++i) {
      uint64_t hidx = r.vu();
      if (hidx >= nhosts) {
        groups_ok = false;
        break;
      }
      (*peer_hosts)[i] = uniq_hosts[hidx];
      (*peer_ports)[i] = static_cast<int>(r.vu());
      rank_host_[i] = static_cast<int32_t>(r.vu());
      // Group ids index leader tables (AdoptTopology) — an out-of-range
      // id from a garbled frame must fail here like the fields above,
      // not as an OOB write or a multi-GB nnodes_ allocation there.
      groups_ok = groups_ok && rank_host_[i] >= 0 && rank_host_[i] < new_size;
    }
    if (!r.ok() || !groups_ok) {
      lasterr = "bad rendezvous table";
      break;
    }
    shm_enabled_ = shm_on != 0;
    hier_coord_ = hier_on != 0;
    num_channels_ = committed_channels;
    wave_width_.store(committed_wave);
    algo_threshold_.store(committed_algo);
    backup_workers_ = committed_backup;
    link_retries_ = committed_link_retries;
    priority_bands_.store(committed_bands);
    // The committed deadline re-clamps against THIS rank's socket
    // timeout: the coordinator clamped against its own, but "healing
    // must finish strictly inside every other rank's no-progress
    // patience" is a per-rank property — under heterogeneous
    // HOROVOD_SOCKET_TIMEOUT_SEC, a worker with tighter patience would
    // otherwise abort 'link: no progress' mid-way through a peer's
    // committed-length heal.
    link_heal_timeout_ms_ = committed_heal_ms;
    if (socket_timeout_sec_ > 0) {
      link_heal_timeout_ms_ = std::min<int64_t>(
          link_heal_timeout_ms_,
          static_cast<int64_t>(socket_timeout_sec_) * 1000 * 3 / 4);
      if (link_heal_timeout_ms_ < 1) link_heal_timeout_ms_ = 1;
    }
    if (new_rank != worker_id_ || new_size != world_size_) {
      std::fprintf(stderr,
                   "horovod_tpu worker id %d: joined membership epoch %lld "
                   "as rank %d of %d\n",
                   worker_id_, static_cast<long long>(new_epoch), new_rank,
                   new_size);
    }
    // Clock-offset estimation against the coordinator (see kPingMagic):
    // min-RTT midpoint over kClockPings rounds on the still-open
    // rendezvous conn.  rank0_mono ≈ my_mono + clock_offset_ns_.
    {
      int64_t best_rtt = std::numeric_limits<int64_t>::max();
      int64_t best_off = 0;
      for (int k = 0; k < kClockPings; ++k) {
        Writer pw;
        pw.u32(kPingMagic);
        pw.i64(MonoNowNs());
        const int64_t t0 = MonoNowNs();
        std::vector<uint8_t> pf;
        if (!coordinator_conn_.SendFrame(pw.bytes()) ||
            !coordinator_conn_.RecvFrame(&pf)) {
          last_error_ = "clock-sync exchange with the coordinator failed";
          return 1;
        }
        const int64_t t1 = MonoNowNs();
        Reader pr(pf.data(), pf.size());
        const int64_t tc = pr.i64();
        if (!pr.ok()) {
          last_error_ = "bad clock-sync reply frame";
          return 1;
        }
        const int64_t rtt = t1 - t0;
        if (rtt < best_rtt) {
          best_rtt = rtt;
          best_off = tc - (t0 + rtt / 2);
        }
      }
      clock_offset_ns_ = best_off;
    }
    rank_ = new_rank;
    size_ = new_size;
    epoch_.store(new_epoch);
    return 0;
  }
  last_error_ = lasterr;
  return 1;
}

// Coordinator, elastic mode, once per cycle: a relaunched/new worker
// connecting to the control listener mid-run is a join candidate.  Its
// join triggers a collective abort so every member falls back into
// run_elastic's recovery loop and the next rendezvous admits the
// candidate under epoch+1 — the "rejoin without whole-job restart" half
// of in-place elastic membership.
bool Engine::PollJoinCandidate() {
  if (!elastic_enabled_ || worker_id_ != 0 || !control_listener_.valid()) {
    return false;
  }
  if (!HasPendingConnection(control_listener_)) return false;
  std::string err;
  Socket conn = Accept(control_listener_, &err);
  if (!conn.valid()) return false;
  // A genuine candidate sends its JOIN immediately after connecting; a
  // silent stray (health probe, scanner) must not park the negotiation
  // loop — bound the speculative read to a fraction of a cycle's budget
  // and require the join magic before this connection may abort a
  // running world.
  if (!WaitReadable(conn, 250)) return false;
  conn.SetTimeouts(1);
  std::vector<uint8_t> frame;
  if (!conn.RecvFrame(&frame)) return false;  // stray/garbled: drop it
  Reader r(frame.data(), frame.size());
  uint32_t magic = r.u32();
  int32_t id = r.i32();
  if (!r.ok() || magic != kJoinMagic || id < 1 || id >= world_size_) {
    return false;
  }
  // The candidate's connection is dropped here; it retries its join and
  // lands on the re-formed world's listener.
  BroadcastAbort(
      -1, "elastic re-rendezvous: worker id " + std::to_string(id) +
              " is waiting to join (epoch " +
              std::to_string(epoch_.load()) + ", size " +
              std::to_string(size_) +
              "); aborting in-flight collectives to re-form the world");
  return true;
}

void Engine::Shutdown() {
  if (!initialized_.load()) return;
  // The background loop may have ALREADY exited (a peer's shutdown
  // broadcast, or a transport abort) with shut_down_ set while
  // initialized_ is still true — join and clear state regardless, or a
  // subsequent Init() would see initialized_ and no-op on a dead engine.
  shutdown_requested_.store(true);
  cycle_cv_.notify_all();  // wake the event-driven cycle wait immediately
  if (background_.joinable()) background_.join();
  // The background loop waits out its in-flight waves before exiting, so
  // the pool is quiescent here; stop it so a re-Init starts fresh.
  pool_.Stop();
  initialized_.store(false);
}

void Engine::ClearCacheState() {
  cache_by_name_.clear();
  cache_entries_.clear();
  pending_cache_hits_.clear();
  cache_resubmits_.clear();
  coord_slot_bits_.clear();
  coord_slot_names_.clear();
  coord_slot_by_name_.clear();
  free_slots_.clear();
  next_slot_ = 0;
  sub_slot_bits_.clear();
  // Backup-worker skip tokens ride along: they reference the dead (or
  // about-to-be-recommitted) world's partial commits.
  skip_tokens_.clear();
}

// ---------------------------------------------------------------------------
// Background negotiation loop
// ---------------------------------------------------------------------------

// message_table_ is background-thread-only by design (no mu_); this makes
// the invariant self-checking at every access site instead of
// comment-enforced.  Deliberately NOT assert(): downstream builds override
// CXXFLAGS (?=) with -DNDEBUG and would silently compile the check out.
void Engine::AssertBackgroundThread() const {
  if (std::this_thread::get_id() != bg_thread_id_.load()) {
    std::fprintf(stderr,
                 "horovod_tpu: FATAL: message_table_ accessed off the "
                 "background thread\n");
    std::abort();
  }
}

void Engine::BackgroundLoop() {
  bg_thread_id_.store(std::this_thread::get_id());
  while (RunLoopOnce()) {
  }
  // Fail anything still in flight (reference SHUT_DOWN_ERROR,
  // operations.cc:1647-1662).  A transport abort carries the specific
  // reason (which peer died, during what) to every waiter.
  std::string reason = abort_reason_.empty()
      ? "Horovod has been shut down. This was caused by an exception on one "
        "of the ranks or an attempt to enqueue after shutdown."
      : abort_reason_;
  if (!abort_reason_.empty()) {
    // The world is dying abnormally: flush the last timeline events (the
    // cycle before a crash must never be lost to stdio buffering) and
    // dump the flight recorder for the post-mortem CLI.  A clean
    // shutdown dumps nothing — the recorder is a crash artifact.
    GlobalFlightRecorder().Record("abort", control_cycle_seq_, "%s",
                                  abort_reason_.c_str());
    GlobalFlightRecorder().Dump(abort_reason_.c_str());
    timeline_.Flush();
  }
  std::vector<TensorTableEntry> leftovers;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : tensor_table_) leftovers.push_back(std::move(kv.second));
    tensor_table_.clear();
    message_queue_.clear();
  }
  for (auto& e : leftovers) {
    FinishEntry(e, Status::Aborted(reason));
  }
  // Drop half-negotiated state so a re-Init after an abort (the elastic
  // recovery path) starts from an empty table instead of poisoning the new
  // world's readiness counts with the dead world's pending entries.
  // Thread-correct: this is still the background thread.
  message_table_.clear();
  // Same for the response cache: a recovered world must never replay the
  // dead world's slot ids (the new coordinator numbers slots from zero).
  ClearCacheState();
  // Drop the fusion-scratch high-water allocations: a dead/stopped engine
  // must not pin up to threshold-sized buffers per channel slot.
  ReleaseScratch();
  // Close every connection so peers blocked in recv see EOF immediately and
  // the failure propagates around the ring instead of stranding them until
  // their own timeout.
  CloseSockets();
  shut_down_.store(true);
  // Second drain, after the store: an Enqueue racing the first drain can
  // have inserted between it and the store (its pre-insert liveness check
  // passed).  Enqueue checks shut_down_ under mu_, so any insert not
  // caught here observed the store and was rejected — no waiter can be
  // stranded on a never-finished entry.
  std::vector<TensorTableEntry> stragglers;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : tensor_table_) stragglers.push_back(std::move(kv.second));
    tensor_table_.clear();
    message_queue_.clear();
  }
  for (auto& e : stragglers) {
    FinishEntry(e, Status::Aborted(reason));
  }
}

std::string Engine::AbortReason() const {
  // Publication order: BackgroundLoop writes abort_reason_, then
  // release-stores shut_down_; acquiring shut_down_ here makes the string
  // read race-free from API threads.
  if (!shut_down_.load()) return std::string();
  return abort_reason_;
}

void Engine::CloseSockets() {
  for (auto& s : ring_next_) s.Close();
  for (auto& s : ring_prev_) s.Close();
  for (auto& s : cross_next_) s.Close();
  for (auto& s : cross_prev_) s.Close();
  // shm edges ride along: Close() flips the shared `closed` word, so a
  // peer blocked in a ring wait fails fast — the shm analogue of the EOF
  // these socket closes propagate.
  CloseShmEdges();
  coordinator_conn_.Close();
  for (auto& c : worker_conns_) c.Close();
  leader_conn_.Close();
  for (auto& c : member_conns_) c.Close();
  control_listener_.Close();
  data_listener_.Close();
  // Parked RESUME connections belong to the incarnation being torn down.
  HealInboxClear();
}

// -- link self-healing bookkeeping --

void Engine::RecordLinkHealNs(int64_t ns) {
  std::lock_guard<std::mutex> lk(heal_ns_mu_);
  constexpr size_t kCap = 1024;
  if (heal_ns_samples_.size() < kCap) {
    heal_ns_samples_.push_back(ns);
  } else {
    heal_ns_samples_[heal_ns_next_ % kCap] = ns;
  }
  ++heal_ns_next_;
}

int64_t Engine::LinkHealNsPercentile(double p) const {
  std::vector<int64_t> snap;
  {
    std::lock_guard<std::mutex> lk(heal_ns_mu_);
    snap = heal_ns_samples_;
  }
  if (snap.empty()) return 0;
  size_t idx = static_cast<size_t>(p * (snap.size() - 1) + 0.5);
  if (idx >= snap.size()) idx = snap.size() - 1;
  std::nth_element(snap.begin(), snap.begin() + idx, snap.end());
  return snap[idx];
}

void Engine::HealInboxPut(int32_t ring, int32_t channel,
                          const LinkResume& lr, Socket conn) {
  std::lock_guard<std::mutex> lk(heal_mu_);
  auto key = std::make_pair(ring, channel);
  auto it = heal_inbox_.find(key);
  if (it != heal_inbox_.end()) {
    // Newest wins: the sender retries with fresh connects and abandons
    // old ones, so a parked older conn is at best dead weight.
    it->second = std::make_pair(lr, std::move(conn));
    return;
  }
  heal_inbox_.emplace(key, std::make_pair(lr, std::move(conn)));
  heal_inbox_size_.fetch_add(1);
}

bool Engine::HealInboxTake(int32_t ring, int32_t channel, LinkResume* lr,
                           Socket* conn) {
  if (heal_inbox_size_.load() == 0) return false;
  std::lock_guard<std::mutex> lk(heal_mu_);
  auto it = heal_inbox_.find(std::make_pair(ring, channel));
  if (it == heal_inbox_.end()) return false;
  *lr = it->second.first;
  *conn = std::move(it->second.second);
  heal_inbox_.erase(it);
  heal_inbox_size_.fetch_sub(1);
  return true;
}

void Engine::HealInboxClear() {
  std::lock_guard<std::mutex> lk(heal_mu_);
  heal_inbox_.clear();
  heal_inbox_size_.store(0);
}

// ---------------------------------------------------------------------------
// Shared-memory edges (intra-host transport + hierarchy)
// ---------------------------------------------------------------------------

void Engine::CloseShmEdges() {
  for (auto& r : shm_ring_tx_) r.Close();
  for (auto& r : shm_ring_rx_) r.Close();
  for (auto& e : shm_star_) {
    e.tx.Close();
    e.rx.Close();
  }
  shm_ring_tx_.clear();
  shm_ring_rx_.clear();
  shm_star_.clear();
  shm_ring_active_ = false;
}

void Engine::CountShmBytes(int64_t tx, int64_t rx) {
  if (tx > 0) {
    shm_bytes_tx_.fetch_add(tx);
    data_bytes_tx_.fetch_add(tx);
  }
  if (rx > 0) {
    shm_bytes_rx_.fetch_add(rx);
    data_bytes_rx_.fetch_add(rx);
  }
  if (tx + rx > 0) intra_host_bytes_.fetch_add(tx + rx);
}

void Engine::CountPortBytes(const RingPort& port, int64_t tx, int64_t rx,
                            bool compressed) {
  if (compressed && tx > 0) compressed_bytes_tx_.fetch_add(tx);
  if (port.is_shm()) {
    CountShmBytes(tx, rx);
    return;
  }
  if (tx > 0) data_bytes_tx_.fetch_add(tx);
  if (rx > 0) data_bytes_rx_.fetch_add(rx);
}

// Wire the group's shm edges for the committed epoch.  Name scheme (all
// under the job prefix, all epoch-stamped so a dead incarnation can never
// collide):  ring edge from group position i toward (i+1)%L on channel c:
//   /<prefix>e<epoch>_g<gid>_r<i>_c<c>
// star edge member i <-> leader, one ring per direction:
//   /<prefix>e<epoch>_g<gid>_u<i>   (member produces, leader consumes)
//   /<prefix>e<epoch>_g<gid>_d<i>   (leader produces, member consumes)
// Creation order is deadlock-free: every process creates ALL its segments
// first, then attaches (Attach retries until the creator's segment
// appears), then waits for its own segments' attach confirmations and
// unlinks the names — after wiring, /dev/shm holds nothing for this
// group, so a SIGKILL cannot leak entries for wired edges.
bool Engine::WireShmEdges(std::string* err) {
  const int L = group_size_;
  const int i = local_index_;
  char tag[96];
  std::snprintf(tag, sizeof(tag), "/%se%lld_g%d_", shm_prefix_.c_str(),
                static_cast<long long>(epoch_.load()), node_id_);
  // A crash DURING a previous wiring attempt on THIS host leaves named
  // segments behind; the group leader sweeps everything under the job
  // prefix that is not stamped with the current epoch (current-epoch
  // names are live peers mid-wiring and must survive the sweep).
  char keep[32];
  std::snprintf(keep, sizeof(keep), "e%lld_",
                static_cast<long long>(epoch_.load()));
  if (i == 0) ShmSweepStale(shm_prefix_, keep);
  const int64_t epoch = epoch_.load();
  const uint64_t cap = static_cast<uint64_t>(shm_ring_bytes_);
  auto name = [&](const char* kind, int idx, int ch) {
    char buf[160];
    if (ch >= 0) {
      std::snprintf(buf, sizeof(buf), "%s%s%d_c%d", tag, kind, idx, ch);
    } else {
      std::snprintf(buf, sizeof(buf), "%s%s%d", tag, kind, idx);
    }
    return std::string(buf);
  };
  shm_ring_tx_.clear();
  shm_ring_rx_.clear();
  shm_star_.clear();
  shm_ring_tx_.resize(num_channels_);
  shm_ring_rx_.resize(num_channels_);
  shm_star_.resize(i == 0 ? L : 1);
  // 1. Create everything this rank produces.
  for (int c = 0; c < num_channels_; ++c) {
    if (!shm_ring_tx_[c].Create(name("r", i, c), cap, epoch, err)) {
      return false;
    }
  }
  if (i == 0) {
    for (int m = 1; m < L; ++m) {
      if (!shm_star_[m].tx.Create(name("d", m, -1), cap, epoch, err)) {
        return false;
      }
    }
  } else {
    if (!shm_star_[0].tx.Create(name("u", i, -1), cap, epoch, err)) {
      return false;
    }
  }
  // 2. Attach everything this rank consumes (bounded by the rendezvous
  // timeout: a peer death mid-wiring surfaces as a clean init error).
  const int timeout_ms = rendezvous_timeout_sec_ * 1000;
  const int prev = (i - 1 + L) % L;
  for (int c = 0; c < num_channels_; ++c) {
    if (!shm_ring_rx_[c].Attach(name("r", prev, c), epoch, timeout_ms,
                                err)) {
      return false;
    }
  }
  if (i == 0) {
    for (int m = 1; m < L; ++m) {
      if (!shm_star_[m].rx.Attach(name("u", m, -1), epoch, timeout_ms,
                                  err)) {
        return false;
      }
    }
  } else {
    if (!shm_star_[0].rx.Attach(name("d", i, -1), epoch, timeout_ms, err)) {
      return false;
    }
  }
  // 3. Unlink-after-map: once the consumer confirmed its mapping the
  // filesystem name — the only thing a kill could leak — goes away.
  for (int c = 0; c < num_channels_; ++c) {
    if (!shm_ring_tx_[c].UnlinkAfterAttach(timeout_ms)) {
      *err = "peer never attached ring segment (died during wiring?)";
      return false;
    }
  }
  for (auto& e : shm_star_) {
    if (e.tx.valid() && !e.tx.UnlinkAfterAttach(timeout_ms)) {
      *err = "peer never attached star segment (died during wiring?)";
      return false;
    }
  }
  return true;
}

// Ring bookkeeping convention (vrank = position - 1): after a ring's
// reduce-scatter phase, (physical) position s owns fully-reduced
// segment s — so the RS half IS a first-class reducescatter (rank r
// keeps exactly its committed shard r), and segment s accumulates in
// ring order s+1, s+2, ..., s+N (mod N; outermost operand = position
// s's raw data).  Any CONSISTENT vrank assignment yields a correct
// allreduce — the choice only fixes the fold order — so the allgather
// phase and every parity anchor (transport, channels, star fold,
// two-level) follow this one convention.
Engine::RingSpec Engine::TcpRingSpec() {
  RingSpec spec;
  spec.vrank = (rank_ - 1 + size_) % size_;
  spec.rsize = size_;
  spec.span = "RING_CH";
  spec.ports.resize(num_channels_);
  for (int c = 0; c < num_channels_; ++c) {
    spec.ports[c].next = &ring_next_[c];
    spec.ports[c].prev = &ring_prev_[c];
  }
  spec.ring_id = RING_GLOBAL;
  spec.next_peer = (rank_ + 1) % size_;
  spec.prev_peer = (rank_ - 1 + size_) % size_;
  spec.seq = &link_seq_global_;
  return spec;
}

Engine::RingSpec Engine::ShmRingSpec() {
  RingSpec spec;
  spec.vrank = (local_index_ - 1 + group_size_) % group_size_;
  spec.rsize = group_size_;
  spec.span = "SHM_CH";
  spec.ports.resize(num_channels_);
  for (int c = 0; c < num_channels_; ++c) {
    spec.ports[c].shm_tx = &shm_ring_tx_[c];
    spec.ports[c].shm_rx = &shm_ring_rx_[c];
  }
  return spec;
}

Engine::RingSpec Engine::CrossRingSpec() {
  RingSpec spec;
  spec.vrank = (node_id_ - 1 + nnodes_) % nnodes_;
  spec.rsize = nnodes_;
  spec.span = "RING_CH";
  spec.ports.resize(num_channels_);
  for (int c = 0; c < num_channels_; ++c) {
    spec.ports[c].next = &cross_next_[c];
    spec.ports[c].prev = &cross_prev_[c];
  }
  spec.ring_id = RING_CROSS;
  spec.next_peer = group_leaders_[(node_id_ + 1) % nnodes_];
  spec.prev_peer = group_leaders_[(node_id_ - 1 + nnodes_) % nnodes_];
  spec.seq = &link_seq_cross_;
  return spec;
}

Engine::RingSpec Engine::FlatRingSpec() {
  // One host group spanning the whole committed world: every flat ring
  // edge is intra-host, so the shm rings carry it (group positions equal
  // committed ranks, so vrank/rsize — and therefore the segment fold
  // order — are IDENTICAL to the TCP spec's; transport never changes
  // bits).  Anything else flat runs over TCP.
  if (shm_ring_active_ && !two_level_ && group_size_ == size_) {
    return ShmRingSpec();
  }
  return TcpRingSpec();
}

std::string Engine::TransportError(const std::string& op,
                                   const std::string& name,
                                   const std::string& detail, int next_rank,
                                   int prev_rank) const {
  // SendRecvAll prefixes every peer-attributable error with the direction
  // that failed ("send"/"recv"); "link" means both directions stalled
  // (either neighbor could be the culprit).  Anything else (poll, local
  // resource errors) is a local failure — blaming a neighbor would send
  // the operator to the wrong machine's logs.
  if (detail.rfind("recv", 0) == 0) {
    return "rank " + std::to_string(prev_rank) + " disconnected during " +
           op + " of '" + name + "': " + detail;
  }
  if (detail.rfind("send", 0) == 0) {
    return "rank " + std::to_string(next_rank) + " disconnected during " +
           op + " of '" + name + "': " + detail;
  }
  if (detail.rfind("link", 0) == 0) {
    return "ring neighbor rank " + std::to_string(next_rank) + " or rank " +
           std::to_string(prev_rank) + " stalled during " + op + " of '" +
           name + "': " + detail;
  }
  return "local transport failure during " + op + " of '" + name +
         "': " + detail;
}

void Engine::BroadcastAbort(int culprit, const std::string& message) {
  abort_reason_ = message;
  std::fprintf(stderr, "horovod_tpu coordinator: %s\n", message.c_str());
  GlobalFlightRecorder().Record("abort", control_cycle_seq_,
                                "culprit=%d %s", culprit, message.c_str());
  ResponseList abort_list;
  abort_list.epoch = epoch_.load();
  abort_list.abort = true;
  abort_list.abort_rank = culprit;
  abort_list.abort_message = message;
  Writer w;
  SerializeResponseList(abort_list, &w);
  for (int r = 1; r < size_; ++r) {
    if (r == culprit || !worker_conns_[r].valid()) continue;
    // Best effort: a worker that died alongside the culprit just fails the
    // send; everyone reachable learns the culprit in one frame instead of
    // discovering the death via their own transport timeouts.
    worker_conns_[r].SendFrame(w.bytes());
  }
  // Hierarchical mode: rank 0's own group members read leader_conn_ (the
  // member_conns_ pair), not the direct worker conn the loop above wrote
  // — relay the verdict there too.  Other groups' members get it from
  // their leader, which receives this frame as its response.
  RelayToMembers(w.bytes());
}

// Epoch-gated control-frame read shared by every control gather point:
// rank 0 ← leaders (or ← workers on the flat path), leaders ← members.
bool Engine::RecvRequestListGated(Socket& conn, int patience,
                                  const char* who, RequestList* out,
                                  std::string* what) {
  for (int stale = 0;; ++stale) {
    std::vector<uint8_t> frame;
    if (!conn.RecvFrame(&frame, patience, who)) {
      *what = "lost";
      return false;
    }
    negotiation_bytes_rx_.fetch_add(static_cast<int64_t>(frame.size()) + 8);
    Reader reader(frame.data(), frame.size());
    if (!ParseRequestList(&reader, out)) {
      *what = "corrupt";
      return false;
    }
    if (out->epoch == epoch_.load()) return true;
    stale_epoch_msgs_.fetch_add(1);
    std::fprintf(stderr,
                 "horovod_tpu rank %d: dropped a stale %s (epoch %lld, "
                 "current epoch %lld)\n",
                 rank_, who, static_cast<long long>(out->epoch),
                 static_cast<long long>(epoch_.load()));
    *out = RequestList();
    if (stale >= 15) {
      *what = "stale-flood";
      return false;
    }
  }
}

void Engine::AggregateGroup(RequestList* agg) {
  AssertBackgroundThread();
  if (group_size_ <= 1) return;
  // Fold the leader's OWN hit bits through the same sub table as its
  // members' — a slot's bit goes up only when the whole group is ready,
  // the leader included (rank 0 counts GROUP grants, not rank grants).
  std::vector<uint32_t> own_hits;
  own_hits.swap(agg->cache_hits);
  auto note_hits = [&](const std::vector<uint32_t>& hits, int pos) {
    for (uint32_t slot : hits) {
      auto& sp = sub_slot_bits_[slot];
      if (sp.seen.empty()) {
        sp.seen.assign(group_size_, false);
        sp.first_seen = std::chrono::steady_clock::now();
      }
      if (!sp.seen[pos]) {
        sp.seen[pos] = true;
        sp.count++;
      }
    }
  };
  note_hits(own_hits, 0);
  std::set<uint32_t> evicts(agg->cache_evicts.begin(),
                            agg->cache_evicts.end());
  for (int m = 1; m < group_size_; ++m) {
    RequestList ml;
    std::string what;
    std::string who =
        "control frame from rank " + std::to_string(group_members_[m]);
    if (!member_conns_[m].valid() ||
        !RecvRequestListGated(member_conns_[m], control_patience_rounds_,
                              who.c_str(), &ml, &what)) {
      // Report the first dead member upward instead of failing the
      // cycle here: rank 0 broadcasts the abort naming the member, so
      // every rank — other groups included — gets the true culprit.
      if (agg->fail_rank < 0) {
        agg->fail_rank = group_members_[m];
        agg->fail_message =
            "sub-coordinator rank " + std::to_string(rank_) +
            " lost its group member rank " +
            std::to_string(group_members_[m]) +
            " — that process crashed, hung, or dropped its connection; "
            "check its logs. Aborting all ranks.";
      }
      continue;
    }
    if (ml.shutdown) agg->shutdown = true;
    if (ml.fail_rank >= 0 && agg->fail_rank < 0) {
      agg->fail_rank = ml.fail_rank;
      agg->fail_message = std::move(ml.fail_message);
    }
    for (auto& q : ml.requests) agg->requests.push_back(std::move(q));
    for (auto& te : ml.telem) agg->telem.push_back(std::move(te));
    for (uint32_t s : ml.cache_evicts) evicts.insert(s);
    note_hits(ml.cache_hits, m);
  }
  // Telemetry aggregation: SUM the group's TELEM deltas into ONE
  // per-host entry (deltas make this exact — each member's delta is
  // absorbed exactly once whether it traveled merged or alone), keep
  // the worst step-time gauge and its owning rank as the host's
  // slowest-member attribution.  Rank 0 thereby receives O(hosts)
  // telemetry bytes per telemetry cycle, same shape as the readiness
  // aggregation above.
  if (!agg->telem.empty()) {
    TelemEntry host;
    host.rank = rank_;
    host.host = node_id_;
    host.nranks = 0;
    host.deltas.assign(TC_COUNT, 0);
    for (const auto& te : agg->telem) {
      host.nranks += te.nranks;
      const size_t n = std::min<size_t>(te.deltas.size(), TC_COUNT);
      for (size_t i = 0; i < n; ++i) host.deltas[i] += te.deltas[i];
      if (te.step_p50 > host.step_p50) host.step_p50 = te.step_p50;
      if (te.step_p99 > host.step_p99) host.step_p99 = te.step_p99;
      if (te.slow_p99 >= host.slow_p99) {
        host.slow_p99 = te.slow_p99;
        host.slow_rank = te.slow_rank;
      }
    }
    agg->telem.assign(1, std::move(host));
  }
  agg->cache_evicts.assign(evicts.begin(), evicts.end());
  // A slot evicted this very cycle can never fire: drop its held bits
  // (the evict broadcast makes pending-hit members resubmit in full, so
  // nothing strands — and a freed id reassigned to a NEW tensor must not
  // inherit a stale group grant).
  for (uint32_t s : agg->cache_evicts) sub_slot_bits_.erase(s);
  for (auto it = sub_slot_bits_.begin(); it != sub_slot_bits_.end();) {
    if (it->second.count == group_size_) {
      agg->cache_hits.push_back(it->first);
      it = sub_slot_bits_.erase(it);
    } else {
      ++it;
    }
  }
}

bool Engine::RelayToMembers(const std::vector<uint8_t>& frame) {
  bool ok = true;
  for (int m = 1; m < static_cast<int>(member_conns_.size()); ++m) {
    if (!member_conns_[m].valid() || !member_conns_[m].SendFrame(frame)) {
      // Non-fatal: a member that died after reporting is detected by
      // the next cycle's gather (or by the collective's own transport
      // error) — the rest of the group still gets the frame.
      ok = false;
      continue;
    }
    negotiation_bytes_tx_.fetch_add(static_cast<int64_t>(frame.size()) + 8);
  }
  return ok;
}

void Engine::RelayAbortToMembers(const std::string& message) {
  if (member_conns_.empty()) return;
  ResponseList rl;
  rl.epoch = epoch_.load();
  rl.abort = true;
  rl.abort_rank = -1;
  rl.abort_message = message;
  Writer w;
  SerializeResponseList(rl, &w);
  RelayToMembers(w.bytes());
}

// Leader-side stall detection over held partial readiness bits (see
// engine.h): without it, a slot whose group never completes stalls
// SILENTLY under hierarchical coordination — the leader forwards
// nothing, so rank 0's detector sees count == 0 for it and skips.
void Engine::CheckForStalledSubBits() {
  if (stall_check_disabled_ || sub_slot_bits_.empty()) return;
  auto now = std::chrono::steady_clock::now();
  if (now - last_sub_stall_check_ <
      std::chrono::seconds(stall_warning_sec_)) {
    return;
  }
  last_sub_stall_check_ = now;
  AssertBackgroundThread();
  for (auto& kv : sub_slot_bits_) {
    if (kv.second.count == 0) continue;
    auto age = std::chrono::duration_cast<std::chrono::seconds>(
                   now - kv.second.first_seen)
                   .count();
    if (age < stall_warning_sec_) continue;
    std::string missing;
    for (int m = 0; m < group_size_ &&
                    m < static_cast<int>(kv.second.seen.size()); ++m) {
      if (!kv.second.seen[m]) {
        if (!missing.empty()) missing += ", ";
        missing += std::to_string(group_members_[m]);
      }
    }
    std::fprintf(stderr,
                 "horovod_tpu sub-coordinator rank %d (host %d): cached "
                 "slot %u has waited %llds for local ranks %s to "
                 "re-enqueue — a subset of this host's ranks is "
                 "submitting the tensor, which will cause deadlock.\n",
                 rank_, node_id_, kv.first, static_cast<long long>(age),
                 missing.c_str());
    stall_warnings_.fetch_add(1);
    GlobalFlightRecorder().Record(
        "stall", control_cycle_seq_, "sub slot=%u age=%llds missing=%s",
        kv.first, static_cast<long long>(age), missing.c_str());
  }
}

// ---------------------------------------------------------------------------
// Fleet telemetry (HOROVOD_TELEMETRY_CYCLES)
// ---------------------------------------------------------------------------

const char* const kTelemCounterNames[TC_COUNT] = {
    "data_bytes_tx",        "data_bytes_rx",
    "allreduce_bytes",      "reducescatter_bytes",
    "negotiation_bytes_tx", "negotiation_bytes_rx",
    "control_round_trips",  "cache_hits",
    "cache_misses",         "tensors",
    "responses",            "cycles",
    "shm_bytes_tx",         "compressed_bytes_tx",
    "wire_bytes_saved",     "backup_skips",
    "stale_epoch_msgs",     "stall_warnings",
    "priority_inversions",  "alltoall_bytes",
    "moe_tokens_dropped",
};

TelemEntry Engine::BuildTelemEntry() {
  AssertBackgroundThread();
  TelemEntry t;
  t.rank = rank_;
  t.host = node_id_;
  t.nranks = 1;
  t.step_p50 = step_time_ns_p50();
  t.step_p99 = step_time_ns_p99();
  t.slow_rank = rank_;
  t.slow_p99 = t.step_p99;
  const int64_t cur[TC_COUNT] = {
      data_bytes_tx_.load(),        data_bytes_rx_.load(),
      allreduce_bytes_.load(),      reducescatter_bytes_.load(),
      negotiation_bytes_tx_.load(), negotiation_bytes_rx_.load(),
      control_round_trips_.load(),  cache_hits_.load(),
      cache_misses_.load(),         tensors_executed_.load(),
      responses_executed_.load(),   exec_cycles_.load(),
      shm_bytes_tx_.load(),         compressed_bytes_tx_.load(),
      wire_bytes_saved_.load(),     backup_skips_.load(),
      stale_epoch_msgs_.load(),     stall_warnings_.load(),
      priority_inversions_.load(),  alltoall_bytes_.load(),
      moe_tokens_dropped_.load(),
  };
  t.deltas.resize(TC_COUNT);
  for (int i = 0; i < TC_COUNT; ++i) {
    t.deltas[i] = cur[i] - telem_last_[i];
    telem_last_[i] = cur[i];
  }
  return t;
}

void Engine::MaybeAttachTelem(RequestList* list, bool force) {
  if (telemetry_cycles_ <= 0) return;
  ++telem_cycle_count_;
  if (!force && telem_cycle_count_ % telemetry_cycles_ != 0) return;
  list->telem.push_back(BuildTelemEntry());
}

void Engine::FleetAbsorb(const TelemEntry& t) {
  std::lock_guard<std::mutex> lk(fleet_mu_);
  FleetRow& row = fleet_rows_[t.rank];
  row.nranks = t.nranks;
  row.host = t.host;
  const size_t n = std::min<size_t>(t.deltas.size(), TC_COUNT);
  for (size_t i = 0; i < n; ++i) row.counters[i] += t.deltas[i];
  row.step_p50 = t.step_p50;
  row.step_p99 = t.step_p99;
  row.slow_rank = t.slow_rank;
  row.slow_p99 = t.slow_p99;
  row.updates++;
  row.last_update_mono_ns =
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count();
}

std::string Engine::FleetJson() const {
  std::lock_guard<std::mutex> lk(fleet_mu_);
  std::string out;
  out.reserve(1024 + fleet_rows_.size() * 640);
  char buf[256];
  auto num = [&](const char* key, long long v, bool comma = true) {
    std::snprintf(buf, sizeof(buf), "\"%s\": %lld%s", key, v,
                  comma ? ", " : "");
    out += buf;
  };
  out += "{";
  num("ranks_reporting", static_cast<long long>(fleet_rows_.size()));
  num("world_size", size_);
  num("hosts", nnodes_);
  num("epoch", static_cast<long long>(epoch_.load()));
  num("telemetry_cycles", static_cast<long long>(telemetry_cycles_));
  num("quorum_lag_ns_p50",
      static_cast<long long>(QuorumLagNsPercentile(0.50)));
  num("quorum_lag_ns_p99",
      static_cast<long long>(QuorumLagNsPercentile(0.99)));
  // Slowest-rank attribution across every row's gauge.
  int32_t slow_rank = -1;
  int64_t slow_p99 = 0;
  int64_t totals[TC_COUNT] = {0};
  for (const auto& kv : fleet_rows_) {
    for (int i = 0; i < TC_COUNT; ++i) totals[i] += kv.second.counters[i];
    if (kv.second.slow_p99 >= slow_p99) {
      slow_p99 = kv.second.slow_p99;
      slow_rank = kv.second.slow_rank;
    }
  }
  out += "\"slowest\": {";
  num("rank", slow_rank);
  num("step_time_ns_p99", static_cast<long long>(slow_p99), false);
  out += "}, \"totals\": {";
  for (int i = 0; i < TC_COUNT; ++i) {
    num(kTelemCounterNames[i], static_cast<long long>(totals[i]),
        i + 1 < TC_COUNT);
  }
  out += "}, \"quorum_lag_by_rank\": {";
  {
    bool first = true;
    for (const auto& kv : quorum_attr_) {
      if (!first) out += ", ";
      first = false;
      std::snprintf(buf, sizeof(buf),
                    "\"%d\": {\"attributions\": %lld, \"max_ns\": %lld}",
                    kv.first, static_cast<long long>(kv.second.count),
                    static_cast<long long>(kv.second.max_ns));
      out += buf;
    }
  }
  out += "}, \"rows\": [";
  bool first = true;
  for (const auto& kv : fleet_rows_) {
    if (!first) out += ", ";
    first = false;
    out += "{";
    num("rank", kv.first);
    num("nranks", kv.second.nranks);
    num("host", kv.second.host);
    num("updates", static_cast<long long>(kv.second.updates));
    num("step_time_ns_p50", static_cast<long long>(kv.second.step_p50));
    num("step_time_ns_p99", static_cast<long long>(kv.second.step_p99));
    num("slow_rank", kv.second.slow_rank);
    num("slow_step_ns_p99", static_cast<long long>(kv.second.slow_p99));
    num("last_update_mono_ns",
        static_cast<long long>(kv.second.last_update_mono_ns));
    out += "\"counters\": {";
    for (int i = 0; i < TC_COUNT; ++i) {
      num(kTelemCounterNames[i],
          static_cast<long long>(kv.second.counters[i]), i + 1 < TC_COUNT);
    }
    out += "}}";
  }
  out += "]}";
  return out;
}

int64_t Engine::fleet_rows() const {
  std::lock_guard<std::mutex> lk(fleet_mu_);
  return static_cast<int64_t>(fleet_rows_.size());
}

void Engine::NoteQuorumLag(
    const std::vector<std::chrono::steady_clock::time_point>& times,
    const std::vector<int>& voter_ranks) {
  if (times.size() < 2 || times.size() != voter_ranks.size()) return;
  // Last voter and second-to-last: one pass, no sort.
  size_t last = 0;
  for (size_t i = 1; i < times.size(); ++i) {
    if (times[i] > times[last]) last = i;
  }
  auto second = std::chrono::steady_clock::time_point::min();
  for (size_t i = 0; i < times.size(); ++i) {
    if (i != last && times[i] > second) second = times[i];
  }
  const int64_t lag =
      std::chrono::duration_cast<std::chrono::nanoseconds>(times[last] -
                                                           second)
          .count();
  {
    std::lock_guard<std::mutex> lk(quorum_mu_);
    constexpr size_t kCap = 4096;
    if (quorum_lag_samples_.size() < kCap) {
      quorum_lag_samples_.push_back(lag);
    } else {
      quorum_lag_samples_[quorum_lag_next_ % kCap] = lag;
    }
    ++quorum_lag_next_;
  }
  std::lock_guard<std::mutex> lk(fleet_mu_);
  QuorumAttr& attr = quorum_attr_[voter_ranks[last]];
  attr.count++;
  if (lag > attr.max_ns) attr.max_ns = lag;
}

void Engine::NoteSkippedQuorumLag(int64_t lag_ns) {
  std::lock_guard<std::mutex> lk(quorum_mu_);
  constexpr size_t kCap = 4096;
  if (quorum_lag_samples_.size() < kCap) {
    quorum_lag_samples_.push_back(lag_ns);
  } else {
    quorum_lag_samples_[quorum_lag_next_ % kCap] = lag_ns;
  }
  ++quorum_lag_next_;
}

int64_t Engine::QuorumLagNsPercentile(double p) const {
  std::vector<int64_t> snap;
  {
    std::lock_guard<std::mutex> lk(quorum_mu_);
    snap = quorum_lag_samples_;
  }
  if (snap.empty()) return 0;
  size_t idx = static_cast<size_t>(p * (snap.size() - 1) + 0.5);
  if (idx >= snap.size()) idx = snap.size() - 1;
  std::nth_element(snap.begin(), snap.begin() + idx, snap.end());
  return snap[idx];
}

void Engine::RecordCoordCycleNs(int64_t ns) {
  std::lock_guard<std::mutex> lk(cycle_ns_mu_);
  constexpr size_t kCap = 4096;
  if (cycle_ns_samples_.size() < kCap) {
    cycle_ns_samples_.push_back(ns);
  } else {
    cycle_ns_samples_[cycle_ns_next_ % kCap] = ns;
  }
  ++cycle_ns_next_;
}

int64_t Engine::CoordCycleNsPercentile(double p) const {
  std::vector<int64_t> snap;
  {
    std::lock_guard<std::mutex> lk(cycle_ns_mu_);
    snap = cycle_ns_samples_;
  }
  if (snap.empty()) return 0;
  size_t idx = static_cast<size_t>(p * (snap.size() - 1) + 0.5);
  if (idx >= snap.size()) idx = snap.size() - 1;
  std::nth_element(snap.begin(), snap.begin() + idx, snap.end());
  return snap[idx];
}

// "Did this control frame carry negotiation payload?" — the shared rule
// behind control_round_trips_ on coordinator and workers (idle heartbeat
// exchanges don't count; see engine.h).  Any new wire field that carries
// work belongs here, or the stat skews between rank 0 and workers.
static bool HasPayload(const RequestList& l) {
  return !l.requests.empty() || !l.cache_hits.empty() ||
         !l.cache_evicts.empty() || l.shutdown || l.fail_rank >= 0;
}

static bool HasPayload(const ResponseList& l) {
  return !l.responses.empty() || !l.cached_slots.empty() ||
         !l.evict_slots.empty() || l.shutdown || l.abort || l.tune;
}

bool Engine::RunLoopOnce() {
  if (fault_hang_.load()) {
    // Injected wedge: stay alive but stop cycling.  Control frames cease;
    // peers must detect the hang via HOROVOD_FAULT_TIMEOUT_SEC /
    // HOROVOD_CONTROL_PATIENCE_SEC, exactly like a real stuck process.
    // Same event-driven primitive as the cycle gate below (no fixed
    // sleep anywhere in the loop), with no predicate: a wedge ignores
    // enqueues and shutdown by design, it only stops burning a fixed
    // 100 ms floor per poll when something else wakes the cv.
    std::unique_lock<std::mutex> lk(mu_);
    cycle_cv_.wait_for(lk, std::chrono::milliseconds(100));
    return true;
  }
  if (fault_drop_.load()) {
    abort_reason_ =
        "fault injection: dropped all connections (HOROVOD_FAULT_INJECT)";
    CloseSockets();  // abrupt: no shutdown handshake, peers see raw EOF
    return false;
  }
  // Event-driven cycle gate (replaces the unconditional
  // sleep_for(cycle_time_ms_)): wake the instant work is enqueued, or
  // after cycle_time_ms_ as an idle heartbeat so peers' control frames
  // keep flowing.  HOROVOD_CYCLE_TIME is thereby an UPPER bound on
  // negotiation latency instead of a floor under it — a single eager
  // allreduce negotiates in one control round trip, not in >= 5 ms.
  {
    std::unique_lock<std::mutex> lk(mu_);
    cycle_cv_.wait_for(lk, std::chrono::milliseconds(cycle_time_ms_.load()),
                       [&] {
      return !message_queue_.empty() || shutdown_requested_.load() ||
             tune_pending_.load() ||  // idle world ships TUNE promptly
             fault_hang_.load() || fault_drop_.load();
    });
  }
  if (fault_hang_.load() || fault_drop_.load()) return true;  // next pass

  // Idle high-water release: no collective for a while ⇒ hand the fusion
  // scratch back to the allocator (steady-state training re-executes
  // every few ms and never hits this).
  MaybeReleaseScratch();

  // Elastic rejoin: a candidate knocking on the control listener aborts
  // this world so the next rendezvous can admit it (checked before the
  // size-1 fast path — a world shrunk to one must still grow back).
  if (PollJoinCandidate()) return false;

  RequestList my_list;
  DrainMessageQueue(&my_list);
  my_list.epoch = epoch_.load();
  my_list.shutdown = shutdown_requested_.load();
  // Fleet telemetry rides the regular control frame (idle heartbeats
  // included, so a quiesced fleet's counters still converge); the
  // shutdown frame force-flushes the final deltas.
  MaybeAttachTelem(&my_list, my_list.shutdown);

  if (size_ == 1) {
    for (const auto& te : my_list.telem) FleetAbsorb(te);
    my_list.telem.clear();
    // Single process: every tensor is instantly "globally ready".
    AssertBackgroundThread();
    for (auto& q : my_list.requests) {
      timeline_.NegotiateStart(q.tensor_name);
      timeline_.NegotiateRankReady(q.tensor_name, 0);
      auto& info = message_table_[q.tensor_name];
      info.requests.assign(1, q);
      info.seen.assign(1, true);
      info.count = 1;
    }
    std::vector<Response> responses;
    for (auto& q : my_list.requests) {
      timeline_.NegotiateEnd(q.tensor_name);
      responses.push_back(BuildResponse(q.tensor_name));
      if (responses.back().type != ResponseType::ERROR) {
        timeline_.FlowSend(q.tensor_name, epoch_.load());
      }
    }
    if (priority_bands_.load() > 0) OrderResponsesByPriority(responses);
    FuseResponses(responses);
    CountPriorityInversions(responses, {});
    if (!responses.empty()) exec_cycles_.fetch_add(1);
    ExecuteResponses(responses);
    // World of one: no frame flows, so drain + apply the pending TUNE
    // locally at the same between-cycles point the wire path uses.
    ResponseList local_tune;
    if (DrainPendingTune(&local_tune)) ApplyTune(local_tune);
    return !my_list.shutdown;
  }

  if (rank_ == 0) {
    const auto cyc0 = std::chrono::steady_clock::now();
    const bool hier = HierActive();
    // A peer's next frame only arrives after it finished executing the
    // previous cycle's collectives, which can legitimately span several
    // socket-timeout rounds on slow links — hence the idle allowance,
    // bounded by HOROVOD_CONTROL_PATIENCE_SEC rather than scaling with
    // world size (a crashed peer still fails immediately via
    // EOF/keepalive).
    //
    // Hierarchical coordination: rank 0 gathers ONE aggregated frame per
    // host group (its own group's members folded in via AggregateGroup)
    // instead of one per rank — the control plane's per-cycle work and
    // bytes scale with hosts, not ranks.  The epoch gate is inside
    // RecvRequestListGated either way.
    std::vector<RequestList> lists(hier ? nnodes_ : size_);
    lists[0] = std::move(my_list);
    if (hier) AggregateGroup(&lists[0]);
    for (int v = 1; v < static_cast<int>(lists.size()); ++v) {
      const int peer = hier ? group_leaders_[v] : v;
      std::string what;
      std::string who = "control frame from rank " + std::to_string(peer);
      if (!RecvRequestListGated(worker_conns_[peer],
                                control_patience_rounds_, who.c_str(),
                                &lists[v], &what)) {
        BroadcastAbort(
            peer,
            what == "corrupt"
                ? ("coordinator received a corrupt control frame from "
                   "rank " + std::to_string(peer) + ". Aborting all ranks.")
            : what == "stale-flood"
                ? ("rank " + std::to_string(peer) +
                   " keeps sending control frames from a stale membership "
                   "epoch. Aborting all ranks.")
                : ("coordinator lost connection to rank " +
                   std::to_string(peer) +
                   " — that process crashed, hung, or dropped its "
                   "connection; check its logs. Aborting all ranks."));
        return false;
      }
    }
    // A sub-coordinator that lost one of its members reports the culprit
    // in its aggregate; the abort broadcast names the member, not the
    // leader that noticed.
    for (auto& l : lists) {
      if (l.fail_rank >= 0) {
        BroadcastAbort(l.fail_rank,
                       l.fail_message.empty()
                           ? ("rank " + std::to_string(l.fail_rank) +
                              " failed. Aborting all ranks.")
                           : l.fail_message);
        return false;
      }
    }
    // Fold every gathered TELEM entry (rank 0's own included — its
    // frame never hits the wire but carries the entry all the same)
    // into the fleet table.
    for (auto& l : lists) {
      for (const auto& te : l.telem) FleetAbsorb(te);
    }
    ResponseList response_list = CoordinatorStep(lists);
    // Piggyback a queued autotune proposal on this cycle's broadcast;
    // every rank (the coordinator included) applies it after executing
    // the cycle's responses, so the knobs flip atomically between
    // cycles on the whole world.
    DrainPendingTune(&response_list);
    // Slots the coordinator evicted beyond the gathered evict lists
    // (full-request-implies-evict): drop any readiness bits this
    // sub-coordinator still holds for them — a freed id reassigned to a
    // new tensor must not inherit a stale group grant.
    if (hier) {
      for (uint32_t s : response_list.evict_slots) sub_slot_bits_.erase(s);
      // A partially committed slot's held bits are stale: the skipped
      // group's ready members just had their entries finished "skipped"
      // and will re-report fresh hit bits for their NEXT step.
      for (const auto& ps : response_list.partial_slots) {
        sub_slot_bits_.erase(ps.slot);
      }
    }
    Writer w;
    SerializeResponseList(response_list, &w);
    const int nsends = hier ? nnodes_ : size_;
    for (int v = 1; v < nsends; ++v) {
      const int peer = hier ? group_leaders_[v] : v;
      if (!worker_conns_[peer].SendFrame(w.bytes())) {
        BroadcastAbort(
            peer, "coordinator could not reach rank " +
                      std::to_string(peer) +
                      " — that process likely crashed; check its logs. "
                      "Aborting all ranks.");
        return false;
      }
      negotiation_bytes_tx_.fetch_add(
          static_cast<int64_t>(w.bytes().size()) + 8);
    }
    // Hier: rank 0 is its own group's sub-coordinator — relay the frame
    // down to its local members exactly like every other leader.
    if (hier) RelayToMembers(w.bytes());
    // Count NEGOTIATION round trips only — cycles where some rank shipped
    // requests/hit-bits/evicts or the frame carried work back.  Idle
    // heartbeats (empty frames while every rank computes) would otherwise
    // drown the per-step signal bench and CI gate on.
    bool carried_payload = HasPayload(response_list);
    for (size_t v = 0; v < lists.size() && !carried_payload; ++v) {
      carried_payload = HasPayload(lists[v]);
    }
    if (carried_payload) {
      control_round_trips_.fetch_add(1);
      // Control-plane cycle time: gather + negotiate + distribute, the
      // quantity the big-world scale harness tracks against world size
      // (execution below is data-plane time, excluded on purpose).
      RecordCoordCycleNs(
          std::chrono::duration_cast<std::chrono::nanoseconds>(
              std::chrono::steady_clock::now() - cyc0)
              .count());
      ++control_cycle_seq_;
      size_t nreq = 0;
      for (const auto& l : lists) nreq += l.requests.size();
      GlobalFlightRecorder().Record(
          "cycle", control_cycle_seq_,
          "reqs=%zu resp=%zu cached=%zu evict=%zu partial=%zu", nreq,
          response_list.responses.size(),
          response_list.cached_slots.size(),
          response_list.evict_slots.size(),
          response_list.partial_slots.size());
    }
    // The coordinator is a cache participant like any worker: update the
    // local replica from the list it just broadcast, execute the fully
    // negotiated responses, then the agreed cached slots.
    ApplyCacheUpdates(response_list);
    // Apply a TUNE BEFORE executing this cycle's responses, not after:
    // execution wakes API threads the moment a tensor finishes
    // (FinishEntry), and an enqueue racing a post-execution apply could
    // resolve its wire dtype from the not-yet-flipped knob on one rank
    // and the flipped one on another — a clean negotiated mismatch, but
    // a failed step (a rare-but-real flake of the live wire sweep).
    // This point is equally atomic: every rank applies the same frame
    // at the same cycle boundary with no response in flight, and this
    // cycle's responses execute under the NEW knobs on every rank alike
    // (their wire formats were committed per response at negotiation;
    // chunk/wave/algo knobs flip identically everywhere).
    if (response_list.tune) ApplyTune(response_list);
    bool executed_any = false;
    if (!DispatchCycleResponses(response_list, &executed_any)) return false;
    if (executed_any) exec_cycles_.fetch_add(1);
    if (!stall_check_disabled_) CheckForStalledTensors();
    if (hier) CheckForStalledSubBits();  // rank 0 leads group 0 too
    return !response_list.shutdown;
  }

  // Non-coordinator ranks.  Three roles:
  //   * flat worker       — ship requests to rank 0, execute its response
  //   * hier group leader — aggregate the group's frames, ship ONE frame
  //     to rank 0, relay the response down verbatim, then execute
  //   * hier member       — ship requests to the group leader, execute
  //     the relayed response
  // The leader aggregates BEFORE sending (one frame carries the whole
  // group), and relays BEFORE executing (members start their data-plane
  // work in the same wave as the leader).
  const bool leader = HierActive() && IsGroupLeader();
  const bool member = HierActive() && !IsGroupLeader();
  if (leader) AggregateGroup(&my_list);
  Socket& up = member ? leader_conn_ : coordinator_conn_;
  const std::string lost_upstream =
      member ? ("lost connection to the sub-coordinator (rank " +
                std::to_string(group_members_[0]) +
                ") — it crashed, or the world is aborting; check rank " +
                std::to_string(group_members_[0]) + "'s and rank 0's logs.")
             : "lost connection to the coordinator (rank 0) — it likely "
               "crashed or another rank failed; check rank 0's logs.";
  // A member that lost its leader may still salvage the REAL verdict:
  // rank 0 broadcasts aborts DIRECTLY to every rank's rendezvous conn
  // (BroadcastAbort), so the culprit-naming frame is (or shortly will
  // be) in coordinator_conn_'s buffer even though the relay path died.
  auto salvage_abort = [&](bool wait_direct) {
    std::vector<uint8_t> frame;
    ResponseList rl;
    if (up.valid() && up.RecvFrame(&frame)) {
      Reader r(frame.data(), frame.size());
      if (ParseResponseList(&r, &rl) && rl.abort) {
        abort_reason_ = rl.abort_message;
        return;
      }
    }
    if (member && coordinator_conn_.valid() &&
        (!wait_direct || WaitReadable(coordinator_conn_, 3000))) {
      frame.clear();
      if (coordinator_conn_.RecvFrame(&frame)) {
        Reader r(frame.data(), frame.size());
        rl = ResponseList();
        if (ParseResponseList(&r, &rl) && rl.abort) {
          abort_reason_ = rl.abort_message;
        }
      }
    }
  };
  // Telemetry wire accounting: what the TELEM piggyback itself costs on
  // this rank's upstream frame (leaders count their merged entry once).
  if (!my_list.telem.empty()) {
    Writer tw;
    for (const auto& te : my_list.telem) SerializeTelemEntry(te, &tw);
    telem_bytes_tx_.fetch_add(static_cast<int64_t>(tw.bytes().size()) + 2);
  }
  Writer w;
  SerializeRequestList(my_list, &w);
  if (fault_stale_epoch_.exchange(false)) {
    // Injected dead-incarnation replay (HOROVOD_FAULT_INJECT
    // kind=stale-epoch): the same payload stamped with the PREVIOUS epoch
    // precedes the real frame; the receiver must drop and count it
    // (stale_epoch_msgs) and negotiate from the genuine frame only.
    RequestList ghost = my_list;
    ghost.epoch = my_list.epoch - 1;
    Writer gw;
    SerializeRequestList(ghost, &gw);
    up.SendFrame(gw.bytes());
  }
  negotiation_bytes_tx_.fetch_add(static_cast<int64_t>(w.bytes().size()) + 8);
  if (!up.SendFrame(w.bytes())) {
    salvage_abort(/*wait_direct=*/false);
    if (abort_reason_.empty()) abort_reason_ = lost_upstream;
    if (leader) RelayAbortToMembers(abort_reason_);
    std::fprintf(stderr, "horovod_tpu rank %d: %s\n", rank_,
                 abort_reason_.c_str());
    return false;
  }
  ResponseList response_list;
  std::vector<uint8_t> accepted_frame;
  // Epoch gate, downstream side: a response frame — including an abort
  // verdict — stamped with a different membership epoch is a dead
  // incarnation's delayed message; drop, count, read the next frame.
  // The member's allowance exceeds the leader's (which exceeds the
  // coordinator's): each relay hop must out-wait the one above it so the
  // most-informative verdict wins the race.
  const int up_patience =
      member ? worker_patience_rounds_ + control_patience_rounds_
             : worker_patience_rounds_;
  const char* up_label = member
      ? "response frame from the sub-coordinator"
      : "response frame from the coordinator (rank 0)";
  for (int stale = 0;; ++stale) {
    std::vector<uint8_t> frame;
    if (!up.RecvFrame(&frame, up_patience, up_label)) {
      salvage_abort(/*wait_direct=*/true);
      if (abort_reason_.empty()) abort_reason_ = lost_upstream;
      if (leader) RelayAbortToMembers(abort_reason_);
      std::fprintf(stderr, "horovod_tpu rank %d: %s\n", rank_,
                   abort_reason_.c_str());
      return false;
    }
    negotiation_bytes_rx_.fetch_add(static_cast<int64_t>(frame.size()) + 8);
    Reader reader(frame.data(), frame.size());
    if (!ParseResponseList(&reader, &response_list)) {
      abort_reason_ = "corrupt control frame from upstream.";
      if (leader) RelayAbortToMembers(abort_reason_);
      std::fprintf(stderr, "horovod_tpu rank %d: bad response frame\n",
                   rank_);
      return false;
    }
    if (response_list.epoch == epoch_.load()) {
      accepted_frame = std::move(frame);
      break;
    }
    stale_epoch_msgs_.fetch_add(1);
    std::fprintf(stderr,
                 "horovod_tpu rank %d: dropped a stale response frame "
                 "(epoch %lld, current epoch %lld)\n",
                 rank_, static_cast<long long>(response_list.epoch),
                 static_cast<long long>(epoch_.load()));
    response_list = ResponseList();
    if (stale >= 15) {
      abort_reason_ = "upstream keeps sending control frames from a "
                      "stale membership epoch.";
      if (leader) RelayAbortToMembers(abort_reason_);
      std::fprintf(stderr, "horovod_tpu rank %d: %s\n", rank_,
                   abort_reason_.c_str());
      return false;
    }
  }
  // Leader: relay the accepted frame verbatim — identical bytes, so
  // members parse exactly what rank 0 serialized (aborts, TUNE payloads
  // and shutdown flags included) — BEFORE processing it locally.
  if (leader) {
    RelayToMembers(accepted_frame);
    // Evicted slots drop any readiness bits still held in the sub table
    // (see AggregateGroup): pending-hit members resubmit on this very
    // frame, so nothing strands and no stale grant survives.
    for (uint32_t s : response_list.evict_slots) sub_slot_bits_.erase(s);
    // Same for partially committed slots: held bits from the skipped
    // step must not count toward the next step's group grant.
    for (const auto& ps : response_list.partial_slots) {
      sub_slot_bits_.erase(ps.slot);
    }
  }
  if (response_list.abort) {
    // Coordinator-initiated collective abort: another rank failed.
    abort_reason_ = response_list.abort_message.empty()
        ? ("coordinator aborted the job: rank " +
           std::to_string(response_list.abort_rank) + " failed")
        : response_list.abort_message;
    std::fprintf(stderr, "horovod_tpu rank %d: %s\n", rank_,
                 abort_reason_.c_str());
    return false;
  }
  // Negotiation round trips only (same HasPayload rule as the
  // coordinator): idle heartbeat exchanges are not counted.
  if (HasPayload(my_list) || HasPayload(response_list)) {
    control_round_trips_.fetch_add(1);
    ++control_cycle_seq_;
    GlobalFlightRecorder().Record(
        "cycle", control_cycle_seq_,
        "reqs=%zu hits=%zu resp=%zu cached=%zu evict=%zu",
        my_list.requests.size(), my_list.cache_hits.size(),
        response_list.responses.size(), response_list.cached_slots.size(),
        response_list.evict_slots.size());
  }
  ApplyCacheUpdates(response_list);
  // TUNE before execution — same reasoning (and the same ordering) as
  // the coordinator path above: a completion-woken enqueue must never
  // read a pre-TUNE knob after a peer already applied it.
  if (response_list.tune) ApplyTune(response_list);
  bool executed_any = false;
  if (!DispatchCycleResponses(response_list, &executed_any)) return false;
  if (executed_any) exec_cycles_.fetch_add(1);
  if (leader) CheckForStalledSubBits();
  return !response_list.shutdown;
}

// ---------------------------------------------------------------------------
// Online autotune (TUNE broadcast)
// ---------------------------------------------------------------------------

int Engine::QueueTune(int64_t chunk_bytes, int64_t fusion_threshold,
                      int64_t cycle_time_ms, int64_t wave_width,
                      int64_t algo_threshold, int64_t wire_dtype,
                      int64_t priority_bands,
                      const std::vector<int64_t>& fusion_ladder,
                      bool commit) {
  if (!initialized_.load() || shut_down_.load()) return -1;
  // Only the coordinator may propose: TUNE rides its response broadcast.
  if (size_ > 1 && rank_ != 0) return -1;
  std::lock_guard<std::mutex> lk(tune_mu_);
  pending_tune_.trial_id = tune_trial_seq_.fetch_add(1) + 1;
  pending_tune_.chunk_bytes = chunk_bytes;
  pending_tune_.fusion_threshold = fusion_threshold;
  pending_tune_.cycle_time_ms = static_cast<int32_t>(cycle_time_ms);
  pending_tune_.wave_width = static_cast<int32_t>(wave_width);
  pending_tune_.algo_threshold = algo_threshold;
  pending_tune_.wire_dtype = static_cast<int32_t>(wire_dtype);
  pending_tune_.priority_bands = priority_bands;
  // Clamp to the engine's ladder capacity BEFORE the wire: the frame
  // parser rejects oversized ladders as corrupt (a whole-world abort),
  // and entries past kFusionLadderMax could never apply anyway.
  pending_tune_.fusion_ladder = fusion_ladder;
  if (pending_tune_.fusion_ladder.size() >
      static_cast<size_t>(kFusionLadderMax)) {
    pending_tune_.fusion_ladder.resize(kFusionLadderMax);
  }
  pending_tune_.commit = commit;
  tune_pending_.store(true);
  cycle_cv_.notify_one();  // an idle world still ships the frame promptly
  return 0;
}

bool Engine::DrainPendingTune(ResponseList* out) {
  std::lock_guard<std::mutex> lk(tune_mu_);
  if (!tune_pending_.load()) return false;
  out->tune = true;
  out->tune_commit = pending_tune_.commit;
  out->tune_trial_id = pending_tune_.trial_id;
  out->tune_chunk_bytes = pending_tune_.chunk_bytes;
  out->tune_fusion_threshold = pending_tune_.fusion_threshold;
  out->tune_cycle_time_ms = pending_tune_.cycle_time_ms;
  out->tune_wave_width = pending_tune_.wave_width;
  out->tune_algo_threshold = pending_tune_.algo_threshold;
  out->tune_wire_dtype = pending_tune_.wire_dtype;
  out->tune_priority_bands = pending_tune_.priority_bands;
  out->tune_fusion_ladder = pending_tune_.fusion_ladder;
  tune_pending_.store(false);
  return true;
}

void Engine::ApplyTune(const ResponseList& list) {
  // Runs between cycles on the background thread of every rank, BEFORE
  // the carrying cycle's responses execute — no collective is in
  // flight, so the knob flip can never split one op across configs,
  // and a completion-woken enqueue can never read a pre-TUNE knob a
  // peer already flipped (the wire-dtype race the live sweep test
  // caught).  Clamps mirror Init exactly: every rank computes identical
  // effective values from the identical broadcast.
  if (list.tune_chunk_bytes > 0) {
    int64_t chunk = std::max<int64_t>(4096, list.tune_chunk_bytes);
    chunk_bytes_.store(chunk & ~int64_t{7});
  }
  if (list.tune_fusion_threshold > 0) {
    fusion_threshold_.store(list.tune_fusion_threshold);
  }
  if (list.tune_cycle_time_ms > 0) {
    cycle_time_ms_.store(std::max(1, static_cast<int>(
        list.tune_cycle_time_ms)));
  }
  if (list.tune_wave_width > 0) {
    wave_width_.store(std::min(16, std::max(1, static_cast<int>(
        list.tune_wave_width))));
  }
  // 0 is a REAL value for the algorithm crossover (small path off), so
  // "leave unchanged" is < 0 — matching the Init clamp (negatives → 0).
  if (list.tune_algo_threshold >= 0) {
    algo_threshold_.store(list.tune_algo_threshold);
  }
  // Same convention for the wire knob: 0 (fp32) is real, < 0 unchanged.
  // The new default governs enqueues AFTER this boundary; anything
  // already negotiated keeps its committed wire format, and the
  // signature change evicts the affected cache slots on first re-use.
  if (list.tune_wire_dtype >= 0 && list.tune_wire_dtype <= 4) {
    wire_dtype_.store(static_cast<int>(list.tune_wire_dtype));
  }
  // Priority band width (0 real = bands off, < 0 unchanged) — applied
  // at the same between-cycles boundary as every other knob, so the
  // whole world flips its response ordering atomically.  NOTE: the
  // Python side gates priority STAMPING on bands>0, so a live flip can
  // race one step's enqueue-time sampling across ranks (one rank stamps
  // before applying, a peer after) — that surfaces as the clean
  // "Mismatched priorities" error, never a garbled dispatch, and the
  // autotuner never sweeps this knob (only the per-band ladder, which
  // cannot change stamping).
  if (list.tune_priority_bands >= 0) {
    priority_bands_.store(
        std::min<int64_t>(1 << 20, list.tune_priority_bands));
  }
  // Per-band fusion-threshold ladder: positive entries overwrite their
  // band's threshold; <= 0 leaves the band unchanged.
  for (size_t b = 0;
       b < list.tune_fusion_ladder.size() &&
       b < static_cast<size_t>(kFusionLadderMax);
       ++b) {
    if (list.tune_fusion_ladder[b] > 0) {
      fusion_ladder_[b].store(list.tune_fusion_ladder[b]);
    }
  }
  tune_trials_.fetch_add(1);
  char desc[256];
  std::snprintf(desc, sizeof(desc),
                "chunk=%lld,fusion=%lld,cycle=%d,wave=%d,algo=%lld,wire=%s,"
                "bands=%lld",
                static_cast<long long>(chunk_bytes_.load()),
                static_cast<long long>(fusion_threshold_.load()),
                cycle_time_ms_.load(), wave_width_.load(),
                static_cast<long long>(algo_threshold_.load()),
                WireDtypeName(static_cast<WireDtype>(wire_dtype_.load())),
                static_cast<long long>(priority_bands_.load()));
  timeline_.TuneTrial(desc, list.tune_commit);
  GlobalFlightRecorder().Record("tune", control_cycle_seq_, "%s%s", desc,
                                list.tune_commit ? " (commit)" : "");
}

// Request types whose responses are pure functions of the validated
// cross-rank signature — safe to replay from the cache.  ALLGATHER is
// excluded: its response embeds every rank's RUNTIME dim-0, renegotiated
// each step.
static bool IsCacheableType(RequestType t) {
  return t == RequestType::ALLREDUCE || t == RequestType::BROADCAST ||
         t == RequestType::REDUCESCATTER || t == RequestType::ALLTOALL;
}

static bool IsCacheableResponse(ResponseType t) {
  return t == ResponseType::ALLREDUCE || t == ResponseType::BROADCAST ||
         t == ResponseType::REDUCESCATTER || t == ResponseType::ALLTOALL;
}

// Queue drain + cache classification (every rank, coordinator included).
// A request whose name maps to a live slot with a matching signature
// collapses to one hit bit; a signature CHANGE evicts the slot locally
// and travels as evict + full replacement Request in the same frame;
// everything else is a full request.
void Engine::DrainMessageQueue(RequestList* my_list) {
  AssertBackgroundThread();
  // Requests bounced back to full negotiation by a remote evict go first
  // (they have already been waiting a cycle).
  for (auto& q : cache_resubmits_) {
    cache_misses_.fetch_add(1);
    my_list->requests.push_back(std::move(q));
  }
  cache_resubmits_.clear();
  std::deque<Request> pending;
  {
    std::lock_guard<std::mutex> lk(mu_);
    pending.swap(message_queue_);
  }
  for (auto& q : pending) {
    // Backup-worker skip token: this tensor was partially committed
    // WITHOUT us before we enqueued it — consume the token and finish
    // the entry with the clean skipped status; nothing goes on the wire
    // (the coordinator already forgot the tensor).
    if (!skip_tokens_.empty()) {
      auto st = skip_tokens_.find(q.tensor_name);
      if (st != skip_tokens_.end()) {
        if (--st->second <= 0) skip_tokens_.erase(st);
        TensorTableEntry e;
        bool have = false;
        {
          std::lock_guard<std::mutex> lk(mu_);
          auto tit = tensor_table_.find(q.tensor_name);
          if (tit != tensor_table_.end()) {
            e = std::move(tit->second);
            tensor_table_.erase(tit);
            have = true;
          }
        }
        if (have) {
          FinishEntry(e, Status::PreconditionError(kSkippedStepError), 0);
        }
        continue;
      }
    }
    if (cache_enabled_ && !q.probe) {
      auto it = cache_by_name_.find(q.tensor_name);
      if (it != cache_by_name_.end()) {
        uint32_t slot = it->second;
        if (cache_entries_[slot].sig.Matches(q)) {
          cache_hits_.fetch_add(1);
          my_list->cache_hits.push_back(slot);
          pending_cache_hits_[slot] = q.tensor_name;
          continue;
        }
        // Same name, new shape/dtype/op/root: drop the slot everywhere
        // and renegotiate from scratch (airtight invalidation — the
        // fusion buffer must never see the old layout again).
        my_list->cache_evicts.push_back(slot);
        cache_entries_.erase(slot);
        cache_by_name_.erase(it);
        cache_evictions_.fetch_add(1);
      }
      if (IsCacheableType(q.type)) cache_misses_.fetch_add(1);
    }
    my_list->requests.push_back(std::move(q));
  }
}

static Request RequestFromEntry(const TensorTableEntry& e, int rank) {
  Request q;
  q.request_rank = rank;
  q.type = e.type;
  q.dtype = e.dtype;
  q.tensor_name = e.name;
  q.root_rank = e.root_rank;
  q.red_op = e.red_op;
  q.wire_dtype = e.wire_dtype;
  q.wire_default = e.wire_default;
  q.priority = e.priority;
  for (int d = 0; d < e.shape.ndim(); ++d) q.shape.push_back(e.shape.dim(d));
  q.splits = e.splits;
  return q;
}

void Engine::ApplyCacheUpdates(const ResponseList& list) {
  if (list.evict_slots.empty() && list.responses.empty()) return;
  AssertBackgroundThread();
  // Evictions FIRST: a freed slot id may be reassigned by a response in
  // this very frame.
  for (uint32_t slot : list.evict_slots) {
    auto it = cache_entries_.find(slot);
    if (it != cache_entries_.end()) {
      cache_by_name_.erase(it->second.response.tensor_names[0]);
      cache_entries_.erase(it);
      cache_evictions_.fetch_add(1);
    }
    auto pit = pending_cache_hits_.find(slot);
    if (pit != pending_cache_hits_.end()) {
      // Our hit bit rode a slot that just died; renegotiate the tensor
      // fully next cycle so it cannot strand (if the signatures really
      // diverged across ranks, full validation reports the mismatch).
      std::lock_guard<std::mutex> lk(mu_);
      auto tit = tensor_table_.find(pit->second);
      if (tit != tensor_table_.end()) {
        cache_resubmits_.push_back(RequestFromEntry(tit->second, rank_));
      }
      pending_cache_hits_.erase(pit);
    }
  }
  if (!cache_enabled_) return;
  // New slot assignments: store this rank's own signature plus the
  // single-tensor response to replay on future hits.
  for (const auto& resp : list.responses) {
    for (size_t i = 0; i < resp.tensor_names.size(); ++i) {
      if (i >= resp.cache_slots.size() || resp.cache_slots[i] < 0) continue;
      uint32_t slot = static_cast<uint32_t>(resp.cache_slots[i]);
      const std::string& name = resp.tensor_names[i];
      CacheEntry entry;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto tit = tensor_table_.find(name);
        if (tit == tensor_table_.end()) continue;  // defensive
        const TensorTableEntry& e = tit->second;
        entry.sig.type = e.type;
        entry.sig.dtype = e.dtype;
        entry.sig.root_rank = e.root_rank;
        entry.sig.red_op = e.red_op;
        entry.sig.wire_dtype = e.wire_dtype;
        entry.sig.priority = e.priority;
        for (int d = 0; d < e.shape.ndim(); ++d) {
          entry.sig.shape.push_back(e.shape.dim(d));
        }
        entry.sig.splits = e.splits;
      }
      Response single;
      single.type = resp.type;
      single.tensor_names.push_back(name);
      single.tensor_sizes = resp.tensor_sizes;
      single.root_rank = resp.root_rank;
      single.red_op = resp.red_op;
      single.wire_dtype = resp.wire_dtype;
      single.priority = entry.sig.priority;
      single.cache_slots.assign(1, -1);
      entry.response = std::move(single);
      cache_by_name_[name] = slot;
      cache_entries_[slot] = std::move(entry);
    }
  }
}

// Resolve a response's scheduling priority on THIS rank: the
// coordinator stamped it at build time, cached replays copy it from
// the replica signature, and worker-side fresh responses received the
// committed NONZERO values in the frame's trailing priority section —
// absence means the committed priority was 0.  Never read the local
// tensor-table entry: a rank that joined a negotiation via a layout
// PROBE stamped 0 locally while its peers stamped the committed value,
// and a locally-resolved order would desync the wave/channel pairing
// across ranks.  Errors and sparse retries stay unknown (-1): they
// dispatch by response content, outside the priority order.
int Engine::ResolveResponsePriority(Response& resp) {
  if (resp.priority >= 0) return resp.priority;
  if (resp.tensor_names.empty() || resp.type == ResponseType::ERROR ||
      resp.type == ResponseType::SPARSE_RETRY) {
    return -1;
  }
  if (!resp.participants.empty() &&
      !RankInParticipants(resp.participants)) {
    return -1;  // ghost ride: dispatch placement ignores priority anyway
  }
  resp.priority = 0;  // committed zero (nonzero would be in the frame)
  return resp.priority;
}

// (priority, name) dispatch order for one cycle.  Three classes, each
// placeable from CROSS-RANK-IDENTICAL information only (the lists must
// sort identically on every rank or wave/channel pairing desyncs):
// errors + sparse retries first (local finishes, no wire — they cannot
// block anything), full-commit responses sorted by (priority, first
// name) — priorities validated equal everywhere — and backup-worker
// partial commits last in arrival order (a ghost rank cannot know their
// priority, so the rule must not depend on it).
void Engine::OrderResponsesByPriority(std::vector<Response>& responses) {
  std::vector<Response> front, mid, back;
  for (auto& r : responses) {
    if (r.type == ResponseType::ERROR ||
        r.type == ResponseType::SPARSE_RETRY) {
      front.push_back(std::move(r));
    } else if (!r.participants.empty()) {
      back.push_back(std::move(r));
    } else {
      mid.push_back(std::move(r));
    }
  }
  std::stable_sort(
      mid.begin(), mid.end(), [](const Response& x, const Response& y) {
        const int px = x.priority < 0 ? 0 : x.priority;
        const int py = y.priority < 0 ? 0 : y.priority;
        if (px != py) return px < py;
        const std::string& nx =
            x.tensor_names.empty() ? std::string() : x.tensor_names[0];
        const std::string& ny =
            y.tensor_names.empty() ? std::string() : y.tensor_names[0];
        return nx < ny;
      });
  responses.clear();
  for (auto& r : front) responses.push_back(std::move(r));
  for (auto& r : mid) responses.push_back(std::move(r));
  for (auto& r : back) responses.push_back(std::move(r));
}

// Dispatch-order priority inversions for one cycle (`first` dispatches
// before `second`): a committed response whose priority is strictly
// more urgent (smaller) than one already dispatched counts once.
// Deterministic — dispatch-LIST order, not wall clock — so reruns of
// the same world read the same value; 0 by construction once the
// banded ordering is on.
void Engine::CountPriorityInversions(const std::vector<Response>& first,
                                     const std::vector<Response>& second) {
  int max_seen = -1;
  int64_t inversions = 0;
  auto scan = [&](const std::vector<Response>& rs) {
    for (const auto& r : rs) {
      if (r.type == ResponseType::ERROR ||
          r.type == ResponseType::SPARSE_RETRY ||
          !r.participants.empty() || r.priority < 0) {
        continue;
      }
      if (max_seen >= 0 && r.priority < max_seen) ++inversions;
      if (r.priority > max_seen) max_seen = r.priority;
    }
  };
  scan(first);
  scan(second);
  if (inversions > 0) priority_inversions_.fetch_add(inversions);
}

bool Engine::BuildCachedResponses(const ResponseList& list,
                                  std::vector<Response>* out) {
  out->clear();
  if (list.cached_slots.empty()) return true;
  AssertBackgroundThread();
  std::vector<Response>& cached = *out;
  cached.reserve(list.cached_slots.size());
  for (uint32_t slot : list.cached_slots) {
    auto it = cache_entries_.find(slot);
    if (it == cache_entries_.end()) {
      // Replica divergence: executing anything further would desync the
      // ring ordering across ranks — abort loudly instead of stranding
      // tensors or corrupting buffers.
      abort_reason_ = "negotiation cache protocol error: coordinator "
                      "agreed on cache slot " + std::to_string(slot) +
                      " which this rank does not hold";
      std::fprintf(stderr, "horovod_tpu rank %d: %s\n", rank_,
                   abort_reason_.c_str());
      return false;
    }
    pending_cache_hits_.erase(slot);
    timeline_.NegotiateCached(it->second.response.tensor_names[0]);
    Response resp = it->second.response;
    resp.priority = it->second.sig.priority;
    // Backup-worker partial commit on the cached path: graft the
    // cycle's committed participant set onto the replayed response, and
    // the payload geometry from the replica signature (a skipped rank
    // holds the replica even when it holds no tensor entry).
    for (const auto& ps : list.partial_slots) {
      if (ps.slot != slot) continue;
      resp.participants = ps.participants;
      int64_t elems = 1;
      for (auto d : it->second.sig.shape) elems *= d;
      resp.partial_elems = elems;
      resp.partial_dtype = static_cast<uint8_t>(it->second.sig.dtype);
      break;
    }
    cached.push_back(std::move(resp));
  }
  // Deterministic across ranks: identical slot order (from the frame) and
  // identical per-tensor dtypes/sizes/priorities (signature-agreed) ⇒
  // identical ordering ⇒ identical fusion ⇒ identical ring execution
  // order (and identical wave/channel assignment in ExecuteResponses).
  // With bands on, both ends re-order the replays by (priority, name)
  // from their replica signatures before fusing.
  if (priority_bands_.load() > 0) OrderResponsesByPriority(cached);
  FuseResponses(cached);
  return true;
}

// One cycle's full dispatch: fresh responses + cached replays.  Bands
// off: the legacy order exactly (fresh in frame order, then cached in
// ascending-slot order) — bit-identical to the pre-priority engine,
// with the inversions counter still observing what banded ordering
// WOULD have fixed.  Bands on: one merged (priority, name)-ordered
// dispatch, so a cached slot can neither head-of-line-block nor be
// blocked by an urgent fresh response.
bool Engine::DispatchCycleResponses(ResponseList& list,
                                    bool* executed_any) {
  std::vector<Response> cached;
  if (!BuildCachedResponses(list, &cached)) return false;
  for (auto& resp : list.responses) ResolveResponsePriority(resp);
  *executed_any = !list.responses.empty() || !cached.empty();
  if (priority_bands_.load() > 0) {
    std::vector<Response> all;
    all.reserve(list.responses.size() + cached.size());
    for (auto& r : list.responses) all.push_back(std::move(r));
    for (auto& r : cached) all.push_back(std::move(r));
    list.responses.clear();
    OrderResponsesByPriority(all);
    CountPriorityInversions(all, {});
    ExecuteResponses(all);
  } else {
    CountPriorityInversions(list.responses, cached);
    ExecuteResponses(list.responses);
    ExecuteResponses(cached);
  }
  return true;
}

void Engine::CoordinatorEvictSlot(uint32_t slot, ResponseList* out) {
  AssertBackgroundThread();
  auto it = coord_slot_names_.find(slot);
  if (it == coord_slot_names_.end()) return;  // duplicate evict this cycle
  GlobalFlightRecorder().Record("evict", control_cycle_seq_, "slot=%u %s",
                                slot, it->second.c_str());
  coord_slot_by_name_.erase(it->second);
  coord_slot_names_.erase(it);
  coord_slot_bits_.erase(slot);
  free_slots_.insert(slot);
  out->evict_slots.push_back(slot);
}

// Readiness counting + response construction + fusion, on the coordinator.
// Reference: IncrementTensorCount (operations.cc:282-307) +
// ConstructMPIResponse (315-517) + fusion (1815-1842); the cache-slot
// readiness bits are the reference 0.21 response-cache bitvector idea
// mapped onto this coordinator.
ResponseList Engine::CoordinatorStep(std::vector<RequestList>& lists) {
  AssertBackgroundThread();
  // One entry per VOTER: ranks on the flat path, host groups under
  // hierarchical coordination (each group's leader aggregated its
  // members, so a voter's hit bit means "my whole group is ready").
  // Full Requests carry their true request_rank either way, so
  // validation and per-rank readiness stay rank-granular.
  const int nvoters = static_cast<int>(lists.size());
  ResponseList out;
  out.epoch = epoch_.load();
  // Cache evictions first — readiness bits and slot reassignments below
  // must see the slot freed, and bits arriving for a slot evicted in the
  // same cycle are dropped (their senders renegotiate on receipt of the
  // evict broadcast).
  for (int v = 0; v < nvoters; ++v) {
    for (uint32_t slot : lists[v].cache_evicts) {
      CoordinatorEvictSlot(slot, &out);
    }
  }
  std::vector<std::string> became_ready;
  for (int v = 0; v < nvoters; ++v) {
    if (lists[v].shutdown) out.shutdown = true;
    for (auto& q : lists[v].requests) {
      const int r = q.request_rank;
      if (r < 0 || r >= size_) continue;  // garbled frame: ignore
      // A full request for a name that still holds a slot means some rank
      // invalidated it (or a replica missed the assignment): drop the
      // slot globally and fall through to full renegotiation.
      auto cs = coord_slot_by_name_.find(q.tensor_name);
      if (cs != coord_slot_by_name_.end()) {
        CoordinatorEvictSlot(cs->second, &out);
      }
      auto it = message_table_.find(q.tensor_name);
      if (it == message_table_.end()) {
        timeline_.NegotiateStart(q.tensor_name);
        PendingInfo info;
        info.requests.resize(size_);
        info.seen.assign(size_, false);
        info.seen_time.resize(size_);
        info.first_seen = std::chrono::steady_clock::now();
        it = message_table_.emplace(q.tensor_name, std::move(info)).first;
      }
      PendingInfo& info = it->second;
      if (!info.seen[r]) {
        info.seen[r] = true;
        info.seen_time[r] = std::chrono::steady_clock::now();
        info.requests[r] = q;
        info.count++;
        timeline_.NegotiateRankReady(q.tensor_name, r);
      }
      if (info.count == size_) {
        became_ready.push_back(q.tensor_name);
      }
    }
  }
  // Readiness bits against live slots; when every voter's bit is in, the
  // slot fires this cycle as a slot id — ConstructResponse is skipped
  // entirely (the validated response is replayed from each replica).
  std::vector<uint32_t> agreed;
  for (int v = 0; v < nvoters; ++v) {
    for (uint32_t slot : lists[v].cache_hits) {
      if (coord_slot_names_.find(slot) == coord_slot_names_.end()) continue;
      SlotPending& sp = coord_slot_bits_[slot];
      if (sp.seen.empty()) {
        sp.seen.assign(nvoters, false);
        sp.seen_time.resize(nvoters);
        sp.first_seen = std::chrono::steady_clock::now();
      }
      if (!sp.seen[v]) {
        sp.seen[v] = true;
        sp.seen_time[v] = std::chrono::steady_clock::now();
        sp.count++;
      }
      if (sp.count == nvoters) agreed.push_back(slot);
    }
  }
  std::sort(agreed.begin(), agreed.end());
  for (uint32_t slot : agreed) {
    // Quorum-lag sample (how far the last voter trailed the rest) before
    // the readiness bits are dropped; under hierarchical coordination a
    // voter is a host group, attributed to its leader rank.
    auto bit = coord_slot_bits_.find(slot);
    if (bit != coord_slot_bits_.end()) {
      std::vector<std::chrono::steady_clock::time_point> vt;
      std::vector<int> vr;
      for (size_t v = 0; v < bit->second.seen.size(); ++v) {
        if (bit->second.seen[v]) {
          vt.push_back(bit->second.seen_time[v]);
          vr.push_back(HierActive() ? group_leaders_[v]
                                    : static_cast<int>(v));
        }
      }
      NoteQuorumLag(vt, vr);
    }
    coord_slot_bits_.erase(slot);
    out.cached_slots.push_back(slot);
    auto nit = coord_slot_names_.find(slot);
    if (nit != coord_slot_names_.end()) {
      timeline_.FlowSend(nit->second, epoch_.load());
    }
  }
  for (auto& name : became_ready) {
    timeline_.NegotiateEnd(name);
    bool any_probe = false;
    {
      auto it = message_table_.find(name);
      for (int r = 0; it != message_table_.end() && r < size_; ++r) {
        if (it->second.requests[r].probe) any_probe = true;
      }
      // Quorum-lag sample at rank granularity (full requests carry
      // per-rank arrival times even under hierarchical coordination).
      if (it != message_table_.end() && size_ > 1) {
        std::vector<std::chrono::steady_clock::time_point> vt;
        std::vector<int> vr;
        for (int r = 0; r < size_; ++r) {
          if (it->second.seen[r]) {
            vt.push_back(it->second.seen_time[r]);
            vr.push_back(r);
          }
        }
        NoteQuorumLag(vt, vr);
      }
    }
    Response resp = BuildResponse(name);
    resp.cache_slots.assign(resp.tensor_names.size(), -1);
    // Cross-rank flow trace: the negotiation's commit is the flow SOURCE
    // ("s"); every rank's execution span carries the matching sink ("f")
    // — see Timeline::FlowSend/FlowRecv.  Errors never execute, so they
    // never open a flow.
    if (resp.type != ResponseType::ERROR) {
      timeline_.FlowSend(name, epoch_.load());
    }
    if (cache_enabled_ && !any_probe && resp.type != ResponseType::ERROR &&
        IsCacheableResponse(resp.type) &&
        static_cast<int64_t>(coord_slot_names_.size()) < cache_capacity_) {
      uint32_t slot;
      if (!free_slots_.empty()) {
        slot = *free_slots_.begin();
        free_slots_.erase(free_slots_.begin());
      } else {
        slot = next_slot_++;
      }
      coord_slot_names_[slot] = name;
      coord_slot_by_name_[name] = slot;
      resp.cache_slots[0] = static_cast<int32_t>(slot);
    }
    out.responses.push_back(std::move(resp));
  }

  // Backup-worker straggler tolerance: commit SUM allreduces that are
  // still short of full readiness but past the nvoters-k threshold and
  // the grace window (full commits above always win the race — a tensor
  // every rank reported this cycle never reaches this scan).
  if (backup_workers_ > 0 || backup_auto_) MaybePartialCommits(&out);

  // Sparse-layout rendezvous: a pending entry whose received requests are
  // ALL layout probes (ranks with no local gradient), coexisting with a
  // pending sparse gather of the same tensor ("<name>.idx"), would
  // deadlock — the probing ranks wait for peers to join the dense
  // allreduce while the peers wait for them to join the allgathers.
  // Resolve it by telling the probing ranks to retry sparsely; their
  // re-enqueued zero-entry '<name>.idx'/'.vals' complete the gathers.
  // (A NON-probe dense request conflicting with a sparse gather is a real
  // layout inconsistency across ranks and is left to the stall warning.)
  std::vector<std::pair<std::string, int64_t>> sparse_retries;
  for (auto& kv : message_table_) {
    const PendingInfo& info = kv.second;
    bool all_probe = info.count > 0;
    for (int r = 0; r < size_ && all_probe; ++r) {
      if (info.seen[r] && !info.requests[r].probe) all_probe = false;
    }
    if (!all_probe) continue;
    auto sp = message_table_.find(kv.first + ".idx");
    if (sp == message_table_.end() || sp->second.count == 0) continue;
    for (int r = 0; r < size_; ++r) {
      if (sp->second.seen[r]) {
        const auto& shape = sp->second.requests[r].shape;
        sparse_retries.emplace_back(kv.first,
                                    shape.size() > 1 ? shape[1] : 1);
        break;
      }
    }
  }
  for (auto& [name, sparse_dim] : sparse_retries) {
    timeline_.NegotiateEnd(name);
    message_table_.erase(name);
    Response resp;
    resp.type = ResponseType::SPARSE_RETRY;
    resp.tensor_names.push_back(name);
    resp.tensor_sizes.push_back(sparse_dim);
    out.responses.push_back(std::move(resp));
  }

  // Priority scheduling (HOROVOD_PRIORITY_BANDS > 0): commit the
  // cycle's responses in (priority, name) order instead of arrival
  // order, so a front-layer gradient that negotiated late in the cycle
  // still dispatches ahead of the tail — the ByteScheduler insight at
  // the coordinator's seam.  Bands off: arrival order, bit-identical to
  // the pre-priority engine.
  if (priority_bands_.load() > 0) OrderResponsesByPriority(out.responses);
  FuseResponses(out.responses);
  return out;
}

// Cross-rank validation: dtype / op / shape / root consistency.  Mismatch
// yields an ERROR response delivered to every rank instead of undefined
// collective behavior — the reference's most important failure-containment
// feature (operations.cc:315-517).
Response Engine::BuildResponse(const std::string& name) {
  // message_table_ is background-thread-only (see engine.h); no lock.
  AssertBackgroundThread();
  PendingInfo info;
  {
    auto it = message_table_.find(name);
    info = std::move(it->second);
    message_table_.erase(it);
  }
  const Request& first = info.requests[0];
  Response resp;
  resp.tensor_names.push_back(name);
  std::ostringstream err;
  // Wire-dtype reference for validation/commit: the first NON-probe
  // request with an EXPLICIT per-tensor override, else the first
  // non-probe request's knob-derived value.  A layout probe (no local
  // gradient) resolves its wire from the global knob, not the
  // per-tensor override its peers may be using — holding it to the
  // peers' format would fail the very step the probe machinery exists
  // to survive.  Knob-derived requests are advisory the same way
  // (Request::wire_default): enqueue-time knob sampling races TUNE
  // application across ranks, so the coordinator COMMITS one value
  // instead of erroring.  Execution is safe in every case: every rank
  // executes the RESPONSE's committed wire, never its own request's.
  const Request* wire_ref = nullptr;
  const Request* knob_ref = nullptr;
  for (int r = 0; r < size_; ++r) {
    const Request& q = info.requests[r];
    if (q.probe) continue;
    if (knob_ref == nullptr) knob_ref = &q;
    if (!q.wire_default) {
      wire_ref = &q;
      break;
    }
  }
  if (wire_ref == nullptr) {
    wire_ref = knob_ref != nullptr ? knob_ref : &first;
  }
  // Committed scheduling priority: the first non-probe request's value
  // (frontends stamp identically from registration order; probes adopt
  // the committed one like they adopt the wire).  Validated cross-rank
  // below, like dtype/wire.
  const Request* prio_ref = knob_ref != nullptr ? knob_ref : &first;
  resp.priority = prio_ref->priority;

  for (int r = 1; r < size_; ++r) {
    const Request& q = info.requests[r];
    if (q.type != first.type) {
      err << "Mismatched collective operations: rank 0 requested "
          << RequestTypeName(first.type) << " but rank " << r << " requested "
          << RequestTypeName(q.type) << " for tensor " << name << ".";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
    if ((first.type == RequestType::ALLREDUCE ||
         first.type == RequestType::REDUCESCATTER) &&
        q.red_op != first.red_op) {
      err << "Mismatched reduction operators: rank 0 requested "
          << ReduceOpName(first.red_op) << " but rank " << r
          << " requested " << ReduceOpName(q.red_op) << " for tensor "
          << name << ".";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
    if (q.dtype != first.dtype) {
      err << "Mismatched data types: rank 0 has " << DataTypeName(first.dtype)
          << " but rank " << r << " has " << DataTypeName(q.dtype)
          << " for tensor " << name << ".";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
    // The L1 dtype validation extended to the WIRE format: the data
    // plane quantizes on one committed format per response, so EXPLICIT
    // overrides disagreeing must fail cleanly here — never garble bytes
    // on the ring.  Probes and knob-derived (wire_default) requests are
    // exempt — they adopt the committed wire (see wire_ref above).
    if ((first.type == RequestType::ALLREDUCE ||
         first.type == RequestType::REDUCESCATTER ||
         first.type == RequestType::ALLTOALL) &&
        !q.probe && !q.wire_default && !wire_ref->wire_default &&
        q.wire_dtype != wire_ref->wire_dtype) {
      err << "Mismatched wire dtypes: rank " << wire_ref->request_rank
          << " requested " << WireDtypeName(wire_ref->wire_dtype)
          << " but rank " << r << " requested "
          << WireDtypeName(q.wire_dtype) << " for tensor " << name
          << " (set HOROVOD_WIRE_DTYPE identically on every rank, or use "
             "the same per-tensor override).";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
    // Scheduling priority is cross-rank metadata like the dtype: the
    // committed response order derives from it, so disagreeing stamps
    // must fail cleanly here — never split the dispatch order.  Probes
    // adopt the committed value (they never stamped one meaningfully).
    if (!q.probe && q.priority != prio_ref->priority) {
      err << "Mismatched priorities: rank " << prio_ref->request_rank
          << " stamped priority " << prio_ref->priority << " but rank "
          << r << " stamped " << q.priority << " for tensor " << name
          << " (pass the same priority= on every rank — frontends "
             "stamping from registration order do this automatically).";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
  }

  if (first.type == RequestType::ALLTOALL) {
    // Split geometry negotiated like the dim-0 allgather's: dims 1+ must
    // match on every rank, dim 0 may differ (each rank routes its own
    // rows).  Per-rank `splits` — when present — must be size_
    // non-negative entries summing to that rank's dim 0; an EMPTY splits
    // vector is the legacy equal-split contract (dim 0 divisible by the
    // world size).  The committed size×size split matrix rides
    // tensor_sizes row-major: row r = rank r's send splits, so rank j's
    // recv geometry is column j.
    if (first.shape.empty()) {
      err << "alltoall requires a tensor with at least one dimension for "
             "tensor " << name << ".";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
    for (int r = 1; r < size_; ++r) {
      const auto& s = info.requests[r].shape;
      bool ok = s.size() == first.shape.size() && !s.empty();
      for (size_t d = 1; ok && d < s.size(); ++d) ok = s[d] == first.shape[d];
      if (!ok) {
        err << "Mismatched alltoall tensor shapes: all dimensions except "
               "the first must match across ranks for tensor "
            << name << ".";
        resp.type = ResponseType::ERROR;
        resp.error_message = err.str();
        return resp;
      }
    }
    for (int r = 0; r < size_; ++r) {
      const Request& q = info.requests[r];
      const int64_t rows = q.shape[0];
      if (q.splits.empty()) {
        if (rows % size_ != 0) {
          err << "alltoall requires dimension 0 (" << rows
              << ") to be divisible by the number of ranks (" << size_
              << ") for tensor " << name
              << " when no explicit splits are passed.";
          resp.type = ResponseType::ERROR;
          resp.error_message = err.str();
          return resp;
        }
        for (int d = 0; d < size_; ++d) {
          resp.tensor_sizes.push_back(rows / size_);
        }
        continue;
      }
      if (static_cast<int>(q.splits.size()) != size_) {
        err << "alltoall splits for tensor " << name << " on rank " << r
            << " has " << q.splits.size() << " entries; expected one per "
            << "rank (" << size_ << ").";
        resp.type = ResponseType::ERROR;
        resp.error_message = err.str();
        return resp;
      }
      int64_t sum = 0;
      for (int64_t s : q.splits) {
        if (s < 0) {
          err << "alltoall splits for tensor " << name << " on rank " << r
              << " contain a negative entry (" << s << ").";
          resp.type = ResponseType::ERROR;
          resp.error_message = err.str();
          return resp;
        }
        sum += s;
      }
      if (sum != rows) {
        err << "alltoall splits for tensor " << name << " on rank " << r
            << " sum to " << sum << " but dimension 0 is " << rows << ".";
        resp.type = ResponseType::ERROR;
        resp.error_message = err.str();
        return resp;
      }
      for (int64_t s : q.splits) resp.tensor_sizes.push_back(s);
    }
    resp.type = ResponseType::ALLTOALL;
    // Committed wire format: alltoall rides the same codec seam as the
    // reductions (fp16/bf16 half staging, int8/fp8 block quantization of
    // the routed activations).
    resp.wire_dtype = wire_ref->wire_dtype;
    return resp;
  }
  if (first.type == RequestType::REDUCESCATTER) {
    // Needs identical shapes on every rank (the output partitioning is
    // computed from the common shape).
    for (int r = 1; r < size_; ++r) {
      if (info.requests[r].shape != first.shape) {
        err << "Mismatched " << RequestTypeName(first.type)
            << " tensor shapes: all ranks must pass identical shapes for "
               "tensor " << name << ".";
        resp.type = ResponseType::ERROR;
        resp.error_message = err.str();
        return resp;
      }
    }
    if (first.shape.empty()) {
      err << RequestTypeName(first.type) << " requires a tensor with at "
          << "least one dimension for tensor " << name << ".";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
    // Reducescatter: rows split as evenly as possible, earlier ranks get
    // the remainder (largest-first — the same convention as the ring
    // segments, which is exactly what makes the 1-D shard geometry
    // coincide with the allreduce's EvenSegments and the RS half
    // bit-parity hold by construction).
    resp.type = ResponseType::REDUCESCATTER;
    resp.red_op = first.red_op;
    // Committed wire format, negotiated + validated like the allreduce's
    // (the RS data plane shares the codec seam).
    resp.wire_dtype = wire_ref->wire_dtype;
    int64_t rows = first.shape[0];
    for (int r = 0; r < size_; ++r) {
      resp.tensor_sizes.push_back(rows / size_ +
                                  (r < rows % size_ ? 1 : 0));
    }
    return resp;
  }
  if (first.type == RequestType::ALLREDUCE ||
      first.type == RequestType::BROADCAST) {
    for (int r = 1; r < size_; ++r) {
      if (info.requests[r].shape != first.shape) {
        TensorShape s0, sr;
        for (auto d : first.shape) s0.AddDim(d);
        for (auto d : info.requests[r].shape) sr.AddDim(d);
        err << "Mismatched " << RequestTypeName(first.type)
            << " tensor shapes: rank 0 has shape " << s0.DebugString()
            << " but rank " << r << " has shape " << sr.DebugString()
            << " for tensor " << name << ".";
        resp.type = ResponseType::ERROR;
        resp.error_message = err.str();
        return resp;
      }
    }
  }
  if (first.type == RequestType::BROADCAST) {
    for (int r = 1; r < size_; ++r) {
      if (info.requests[r].root_rank != first.root_rank) {
        err << "Mismatched broadcast root ranks: rank 0 has root "
            << first.root_rank << " but rank " << r << " has root "
            << info.requests[r].root_rank << " for tensor " << name << ".";
        resp.type = ResponseType::ERROR;
        resp.error_message = err.str();
        return resp;
      }
    }
    resp.type = ResponseType::BROADCAST;
    resp.root_rank = first.root_rank;
    return resp;
  }
  if (first.type == RequestType::ALLGATHER) {
    // dim0 may differ per rank (the negotiated dynamic shape); the rest
    // must match.  tensor_sizes carries every rank's dim0.
    for (int r = 1; r < size_; ++r) {
      const auto& s = info.requests[r].shape;
      bool ok = s.size() == first.shape.size() && !s.empty();
      for (size_t d = 1; ok && d < s.size(); ++d) {
        ok = s[d] == first.shape[d];
      }
      if (first.shape.empty() || !ok) {
        err << "Mismatched allgather tensor shapes: all dimensions except "
               "the first must match across ranks for tensor "
            << name << ".";
        resp.type = ResponseType::ERROR;
        resp.error_message = err.str();
        return resp;
      }
    }
    resp.type = ResponseType::ALLGATHER;
    for (int r = 0; r < size_; ++r) {
      resp.tensor_sizes.push_back(info.requests[r].shape[0]);
    }
    return resp;
  }
  resp.type = ResponseType::ALLREDUCE;
  resp.red_op = first.red_op;
  // Committed wire: the non-probe ranks' (validated identical) format —
  // probing ranks adopt it from this response.
  resp.wire_dtype = wire_ref->wire_dtype;
  return resp;
}

// -- backup-worker partial commits (HOROVOD_BACKUP_WORKERS=k) --

bool Engine::RankInParticipants(const std::vector<uint32_t>& parts) const {
  for (uint32_t p : parts) {
    if (static_cast<int>(p) == rank_) return true;
  }
  return false;
}

static std::string RankListString(const std::vector<bool>& in_set, int size,
                                  bool invert) {
  std::string s;
  for (int r = 0; r < size; ++r) {
    if (in_set[r] == invert) continue;
    if (!s.empty()) s += ",";
    s += std::to_string(r);
  }
  return s;
}

// Validate + build a single-tensor partial response over `participants`
// (every one of them has a seen request).  Mirrors BuildResponse's
// ALLREDUCE validation but only across the committed set; the entry is
// consumed either way.  Partial commits are SUM-only (callers checked),
// so red_op needs no mismatch message of its own.
Response Engine::BuildPartialResponse(
    const std::string& name, const std::vector<uint32_t>& participants) {
  AssertBackgroundThread();
  PendingInfo info;
  {
    auto it = message_table_.find(name);
    info = std::move(it->second);
    message_table_.erase(it);
  }
  timeline_.NegotiateEnd(name);
  Response resp;
  resp.tensor_names.push_back(name);
  resp.cache_slots.assign(1, -1);
  resp.participants = participants;
  const Request& first = info.requests[participants[0]];
  // Committed wire: the first participant with an EXPLICIT override
  // wins, else the first participant's knob-derived value (same rule
  // as BuildResponse).
  const Request* wire_ref = &first;
  for (uint32_t p : participants) {
    if (!info.requests[p].wire_default) {
      wire_ref = &info.requests[p];
      break;
    }
  }
  std::ostringstream err;
  for (size_t i = 1; i < participants.size(); ++i) {
    const Request& q = info.requests[participants[i]];
    if (q.dtype != first.dtype) {
      err << "Mismatched data types: rank " << first.request_rank << " has "
          << DataTypeName(first.dtype) << " but rank " << q.request_rank
          << " has " << DataTypeName(q.dtype) << " for tensor " << name
          << ".";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
    if (q.shape != first.shape) {
      err << "Mismatched allreduce tensor shapes for tensor " << name
          << " (partial commit).";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
    // Same wire rule as BuildResponse: explicit overrides must agree;
    // knob-derived wires adopt the committed one (TUNE-race immunity).
    if (!q.wire_default && !wire_ref->wire_default &&
        q.wire_dtype != wire_ref->wire_dtype) {
      err << "Mismatched wire dtypes: rank " << wire_ref->request_rank
          << " requested " << WireDtypeName(wire_ref->wire_dtype)
          << " but rank " << q.request_rank << " requested "
          << WireDtypeName(q.wire_dtype) << " for tensor " << name << ".";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
  }
  resp.priority = first.priority;
  int64_t elems = 1;
  for (auto d : first.shape) elems *= d;
  resp.partial_elems = elems;
  resp.partial_dtype = static_cast<uint8_t>(first.dtype);
  if (first.type == RequestType::REDUCESCATTER) {
    // Partial reduce-scatter: same committed shard geometry as the full
    // path (largest-first dim-0 split over the WHOLE world — ghosts
    // drive the full-world cascade, so the geometry never shrinks).
    if (first.shape.empty()) {
      err << "reducescatter requires a tensor with at least one "
             "dimension for tensor " << name << " (partial commit).";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
    resp.type = ResponseType::REDUCESCATTER;
    resp.red_op = ReduceOp::SUM;
    resp.wire_dtype = wire_ref->wire_dtype;
    const int64_t rows = first.shape[0];
    for (int r = 0; r < size_; ++r) {
      resp.tensor_sizes.push_back(rows / size_ +
                                  (r < rows % size_ ? 1 : 0));
    }
    return resp;
  }
  resp.type = ResponseType::ALLREDUCE;
  resp.red_op = ReduceOp::SUM;
  resp.wire_dtype = wire_ref->wire_dtype;
  return resp;
}

// End-of-cycle scan for partially committable work.  Eligibility: SUM
// allreduce (zero is the identity the skipped ranks' ghost buffers
// contribute; MIN/MAX/PROD and every other collective wait for the full
// world — which is also what makes a MAX allreduce a reliable barrier
// under k > 0), no probes, pending longer than the grace window, and at
// least nvoters-k ready voters.  Under hierarchical coordination a voter
// is a HOST GROUP: a group counts only when every member reported, so a
// whole late host is one late voter and one slow member sidelines its
// host — exactly the sub-coordinator readiness-aggregation contract.
void Engine::MaybePartialCommits(ResponseList* out) {
  AssertBackgroundThread();
  int k = backup_workers_;
  if (backup_auto_) {
    bool armed;
    if (backup_auto_rule_ == 1) {
      // HOROVOD_BACKUP_AUTO_RULE=steptime (the PR 12 rule, kept as the
      // documented fallback): the coordinator's own completion-latency
      // window — cheap, but blind to rank 0 itself straggling (its own
      // enqueue delay inflates every sample equally).
      size_t nsamp;
      {
        std::lock_guard<std::mutex> lk(step_ns_mu_);
        nsamp = step_ns_samples_.size();
      }
      const int64_t p50 = step_time_ns_p50();
      const int64_t p99 = step_time_ns_p99();
      armed = nsamp >= 64 && p50 > 0 &&
              static_cast<double>(p99) >
                  backup_auto_ratio_ * static_cast<double>(p50);
    } else {
      // Default rule: per-entry QUORUM LAG (last voter's arrival minus
      // the second-to-last's, sampled on every committed negotiation).
      // It measures exactly what a k=1 partial commit would save — and
      // because arrival times are observed at the coordinator for EVERY
      // rank's requests, a straggling rank 0 shows up like any other
      // (closing the steptime rule's coordinator blind spot,
      // docs/performance.md).  The threshold is the GRACE WINDOW, not a
      // p99/p50 ratio: a persistent straggler makes lag p50 ≈ p99 (a
      // ratio test would never fire), and grace is the exact point
      // where an armed partial commit becomes actionable — median lag
      // above it means the last voter would be skipped on a typical
      // step, below it arming changes nothing.
      size_t nsamp;
      {
        std::lock_guard<std::mutex> lk(quorum_mu_);
        nsamp = quorum_lag_samples_.size();
      }
      const int64_t p50 = quorum_lag_ns_p50();
      armed = nsamp >= 64 &&
              static_cast<double>(p50) >
                  static_cast<double>(backup_grace_ms_) * 1e6;
    }
    backup_armed_.store(armed);
    k = armed ? 1 : 0;
  }
  if (k <= 0 || size_ <= 1) return;
  const bool hier = HierActive();
  const int nvoters = hier ? nnodes_ : size_;
  const int need = std::max(1, nvoters - k);
  if (need >= nvoters) return;  // k over-clamped on a tiny world
  const auto now = std::chrono::steady_clock::now();
  const auto grace = std::chrono::milliseconds(backup_grace_ms_);
  // Grace is measured from QUORUM formation: the commit may fire only
  // when the (nvoters-k)-th voter has been ready for >= the grace
  // window — i.e. a rank is skipped only when it lags the QUORUM by
  // more than the grace, never because one early-bird request (a
  // one-shot straggler catching up ahead of peers) aged the entry.
  // Returns how long the quorum has been waiting (ns) when the commit
  // may fire, -1 otherwise.  The wait doubles as the synthetic quorum-
  // lag sample stamped at commit time (NoteSkippedQuorumLag): a partial
  // commit means the skipped voter trails the quorum by AT LEAST this
  // long, and recording it keeps the backup=auto arming window
  // deterministic while skips are occurring (committed-without-the-
  // straggler entries otherwise stop feeding the window).
  auto quorum_wait_ns =
      [&](std::vector<std::chrono::steady_clock::time_point> times)
      -> int64_t {
        if (static_cast<int>(times.size()) < need) return -1;
        std::nth_element(times.begin(), times.begin() + (need - 1),
                         times.end());
        const auto waited = now - times[need - 1];
        if (waited < grace) return -1;
        return std::chrono::duration_cast<std::chrono::nanoseconds>(
                   waited)
            .count();
      };

  // Full-request pending entries.  Names first: the commit erases them.
  // Eligibility covers SUM allreduces AND SUM reducescatters (PR 12's
  // follow-on): an RS ghost contributes the same zero buffer to the
  // same full-world cascade, and the participants divisor flows through
  // the handle exactly like the allreduce's.
  std::vector<std::string> names;
  for (auto& kv : message_table_) {
    const PendingInfo& info = kv.second;
    if (info.count <= 0 || info.count >= size_) continue;
    if (now - info.first_seen < grace) continue;
    bool eligible = true;
    RequestType seen_type = RequestType::ALLREDUCE;
    bool first_seen_req = true;
    for (int r = 0; r < size_ && eligible; ++r) {
      if (!info.seen[r]) continue;
      const Request& q = info.requests[r];
      if (first_seen_req) {
        seen_type = q.type;
        first_seen_req = false;
      }
      eligible = (q.type == RequestType::ALLREDUCE ||
                  q.type == RequestType::REDUCESCATTER) &&
                 q.type == seen_type &&
                 q.red_op == ReduceOp::SUM && !q.probe;
    }
    if (eligible) names.push_back(kv.first);
  }
  for (const auto& name : names) {
    const PendingInfo& info = message_table_[name];
    std::vector<bool> rank_in(size_, false);
    std::vector<std::chrono::steady_clock::time_point> ready_times;
    int ready = 0;
    if (hier) {
      // A voter is a host group, ready when EVERY member reported;
      // its ready time is its slowest member's.
      std::vector<char> group_ready(nnodes_, 1);
      std::vector<std::chrono::steady_clock::time_point> group_time(
          nnodes_);
      for (int r = 0; r < size_; ++r) {
        const int g = rank_host_[r];
        if (!info.seen[r]) {
          group_ready[g] = 0;
        } else if (info.seen_time[r] > group_time[g]) {
          group_time[g] = info.seen_time[r];
        }
      }
      for (int g = 0; g < nnodes_; ++g) {
        if (group_ready[g]) {
          ready++;
          ready_times.push_back(group_time[g]);
        }
      }
      if (ready < need) continue;
      for (int r = 0; r < size_; ++r) rank_in[r] = group_ready[rank_host_[r]];
    } else {
      ready = info.count;
      if (ready < need) continue;
      for (int r = 0; r < size_; ++r) {
        rank_in[r] = info.seen[r];
        if (info.seen[r]) ready_times.push_back(info.seen_time[r]);
      }
    }
    const int64_t waited_ns = quorum_wait_ns(std::move(ready_times));
    if (waited_ns < 0) continue;
    std::vector<uint32_t> participants;
    for (int r = 0; r < size_; ++r) {
      if (rank_in[r]) participants.push_back(static_cast<uint32_t>(r));
    }
    if (participants.empty() ||
        static_cast<int>(participants.size()) >= size_) {
      continue;
    }
    timeline_.PartialCommit(name, RankListString(rank_in, size_, true));
    timeline_.FlowSend(name, epoch_.load());
    GlobalFlightRecorder().Record(
        "partial", control_cycle_seq_, "%s skipped=%s", name.c_str(),
        RankListString(rank_in, size_, true).c_str());
    out->responses.push_back(BuildPartialResponse(name, participants));
    NoteSkippedQuorumLag(waited_ns);
  }

  // Cached-slot readiness bits: same voter threshold, the replayed
  // response comes from each rank's replica (the coordinator's own
  // replica supplies the eligibility check — SUM allreduce only).
  std::vector<std::pair<uint32_t, int64_t>> pslots;
  for (auto& kv : coord_slot_bits_) {
    if (kv.second.count < need || kv.second.count >= nvoters) continue;
    std::vector<std::chrono::steady_clock::time_point> vt;
    for (size_t v = 0; v < kv.second.seen.size(); ++v) {
      if (kv.second.seen[v]) vt.push_back(kv.second.seen_time[v]);
    }
    const int64_t waited_ns = quorum_wait_ns(std::move(vt));
    if (waited_ns < 0) continue;
    auto ce = cache_entries_.find(kv.first);
    if (ce == cache_entries_.end()) continue;  // defensive
    if ((ce->second.response.type != ResponseType::ALLREDUCE &&
         ce->second.response.type != ResponseType::REDUCESCATTER) ||
        ce->second.response.red_op != ReduceOp::SUM) {
      continue;
    }
    pslots.emplace_back(kv.first, waited_ns);
  }
  std::sort(pslots.begin(), pslots.end());
  for (const auto& [slot, slot_waited_ns] : pslots) {
    const SlotPending& sp = coord_slot_bits_[slot];
    std::vector<bool> rank_in(size_, false);
    if (hier) {
      for (int r = 0; r < size_; ++r) {
        int g = rank_host_[r];
        rank_in[r] = g < static_cast<int>(sp.seen.size()) && sp.seen[g];
      }
    } else {
      for (int r = 0; r < size_ && r < static_cast<int>(sp.seen.size());
           ++r) {
        rank_in[r] = sp.seen[r];
      }
    }
    std::vector<uint32_t> participants;
    for (int r = 0; r < size_; ++r) {
      if (rank_in[r]) participants.push_back(static_cast<uint32_t>(r));
    }
    if (participants.empty() ||
        static_cast<int>(participants.size()) >= size_) {
      continue;
    }
    auto nit = coord_slot_names_.find(slot);
    const std::string pname =
        nit == coord_slot_names_.end() ? "?" : nit->second;
    timeline_.PartialCommit(pname, RankListString(rank_in, size_, true));
    timeline_.FlowSend(pname, epoch_.load());
    GlobalFlightRecorder().Record(
        "partial", control_cycle_seq_, "%s slot=%u skipped=%s",
        pname.c_str(), slot,
        RankListString(rank_in, size_, true).c_str());
    coord_slot_bits_.erase(slot);
    out->cached_slots.push_back(slot);
    ResponseList::PartialSlot ps;
    ps.slot = slot;
    ps.participants = std::move(participants);
    out->partial_slots.push_back(std::move(ps));
    NoteSkippedQuorumLag(slot_waited_ns);
  }
}

// Consecutive same-dtype allreduces merge into one response executed as a
// single ring collective over the fusion buffer.
void Engine::FuseResponses(std::vector<Response>& responses) {
  // One load per call: a TUNE can only land between cycles, but stats
  // readers race this, and a single snapshot keeps the merge self-
  // consistent regardless.
  const int64_t fusion_threshold = fusion_threshold_.load();
  if (fusion_threshold <= 0) return;
  // Priority bands: fusion only merges within a band (a 64 MB fused
  // buffer of tail gradients must never swallow an urgent front-layer
  // tensor), and each band may carry its own autotuner-learned fusion
  // threshold (the per-band ladder).  Bands off: one global threshold,
  // the legacy merge exactly.
  const int64_t bands = priority_bands_.load();
  auto band_threshold = [&](const Response& r) -> int64_t {
    if (bands <= 0) return fusion_threshold;
    const int64_t lad = fusion_ladder(
        static_cast<int>(ResponseBand(r)));
    return lad > 0 ? lad : fusion_threshold;
  };
  auto entry_bytes = [this](const std::string& name) -> int64_t {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = tensor_table_.find(name);
    if (it == tensor_table_.end()) return 0;
    return it->second.shape.num_elements() *
           static_cast<int64_t>(DataTypeSize(it->second.dtype));
  };
  auto entry_dtype = [this](const std::string& name) -> DataType {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = tensor_table_.find(name);
    if (it == tensor_table_.end()) return DataType::FLOAT32;
    return it->second.dtype;
  };
  std::vector<Response> fused;
  for (auto& resp : responses) {
    // Keep the slot-assignment vector parallel to tensor_names through
    // the merge (paths that never assign slots leave it empty).
    resp.cache_slots.resize(resp.tensor_names.size(), -1);
    // Partial (backup-worker) responses never fuse: the participant set
    // and ghost-buffer geometry are per-response, and fusing two
    // different survivor sets would mix zero-contribution semantics.
    if (resp.type == ResponseType::ALLREDUCE && !fused.empty() &&
        resp.participants.empty() &&
        fused.back().participants.empty() &&
        fused.back().type == ResponseType::ALLREDUCE &&
        fused.back().red_op == resp.red_op &&
        fused.back().wire_dtype == resp.wire_dtype &&
        (bands <= 0 ||
         ResponseBand(fused.back()) == ResponseBand(resp)) &&
        entry_dtype(fused.back().tensor_names[0]) ==
            entry_dtype(resp.tensor_names[0])) {
      int64_t total = 0;
      for (auto& n : fused.back().tensor_names) total += entry_bytes(n);
      if (total + entry_bytes(resp.tensor_names[0]) <=
          band_threshold(fused.back())) {
        fused.back().tensor_names.push_back(resp.tensor_names[0]);
        fused.back().cache_slots.push_back(resp.cache_slots[0]);
        continue;
      }
    }
    fused.push_back(std::move(resp));
  }
  responses = std::move(fused);
}

// ---------------------------------------------------------------------------
// Execution (the host data plane)
// ---------------------------------------------------------------------------

// Chunk size for streamed (pipelined) relay transfers: broadcast rings and
// the hierarchical local chains.  Large enough to amortize syscalls, small
// enough that a relay's first-byte latency is hops·chunk_time, not
// hops·full_transfer.
static constexpr size_t kRelayChunk = 4u << 20;

void Engine::ExecuteResponses(std::vector<Response>& responses) {
  if (responses.empty()) return;
  // Backup-worker skip bookkeeping runs HERE, on the background thread,
  // BEFORE any wave dispatch: skip_tokens_ and pending_cache_hits_ are
  // background-thread-only (AssertBackgroundThread-checked), and a
  // partial response landing at wave index >= 1 would otherwise mutate
  // them from a pool thread.  PerformResponse then only ghost-executes
  // (it never pops entries for a response that skipped this rank — an
  // entry enqueued AFTER this sweep keeps its banked token and is
  // finished by the next DrainMessageQueue, never stranded).
  for (auto& resp : responses) {
    if (resp.participants.empty() ||
        RankInParticipants(resp.participants)) {
      continue;
    }
    std::vector<TensorTableEntry> entries;
    {
      std::lock_guard<std::mutex> lk(mu_);
      for (const auto& name : resp.tensor_names) {
        auto it = tensor_table_.find(name);
        if (it != tensor_table_.end()) {
          entries.push_back(std::move(it->second));
          tensor_table_.erase(it);
        }
      }
    }
    NoteSkippedResponse(resp, entries);
  }
  last_exec_time_ = std::chrono::steady_clock::now();
  // Concurrency degree: the flat ring (TCP or shm — both wire
  // num_channels_ disjoint port pairs) can run up to that many
  // INDEPENDENT responses at once, each claiming one channel (assignment
  // by list index — the list is identical on every rank, so rank r's
  // channel c always talks to rank r+1's channel c about the same
  // response).  The two-level topology executes serially — its star
  // edges and leader gather are single-instance — but still hands the
  // serial context the full channel range so the intra reduce-scatter
  // and the leader cross ring shard across channels.
  const int fanout = (size_ > 1 && pool_.size() > 0) ? num_channels_ : 1;
  // Wave width: how many independent responses run concurrently, each on
  // one disjoint channel.  Capped by the channel fan-out; live-tuned via
  // TUNE frames (every rank applies the same value at the same cycle
  // boundary, so cross-rank channel assignment stays in lockstep).
  const int C =
      two_level_ ? 1 : std::min(fanout, wave_width_.load());
  if (C <= 1 || responses.size() <= 1) {
    ExecCtx all{0, std::max(1, fanout)};
    for (auto& resp : responses) PerformResponse(resp, all);
    last_exec_time_ = std::chrono::steady_clock::now();
    return;
  }
  // Band-ordered wave dispatch (HOROVOD_PRIORITY_BANDS > 0): a wave
  // never spans a band boundary — a low-priority 64 MB fusion buffer
  // cannot co-schedule with (and therefore head-of-line-block) a more
  // urgent response, which instead dispatches in its own earlier wave
  // with the full channel fan-out when it rides alone.  Partial
  // (backup-worker) responses always ride alone: their priority is
  // unknowable on ghost ranks, and the boundary rule must derive from
  // the response content every rank can see.  Bands off: fixed waves of
  // C in list order, the legacy grouping exactly.
  const int64_t bands = priority_bands_.load();
  for (size_t base = 0; base < responses.size();) {
    int wave = static_cast<int>(
        std::min<size_t>(C, responses.size() - base));
    if (bands > 0) {
      if (!responses[base].participants.empty()) {
        wave = 1;
      } else {
        const int64_t b0 = ResponseBand(responses[base]);
        int w = 1;
        while (w < wave &&
               responses[base + w].participants.empty() &&
               ResponseBand(responses[base + w]) == b0) {
          ++w;
        }
        wave = w;
      }
    }
    const size_t wave_base = base;
    base += static_cast<size_t>(wave);
    if (wave == 1) {
      // Lone response (trailing, band-isolated, or partial): give it
      // the full fan-out.
      PerformResponse(responses[wave_base], ExecCtx{0, fanout, nullptr});
      continue;
    }
    std::vector<int64_t> slice_walls(wave, 0);
    TaskLatch latch(wave - 1);
    for (int j = 1; j < wave; ++j) {
      pool_.Submit([this, &responses, &slice_walls, wave_base, j, &latch] {
        PerformResponse(responses[wave_base + j],
                        ExecCtx{j, 1, &slice_walls[j]});
        latch.Done();
      });
    }
    PerformResponse(responses[wave_base], ExecCtx{0, 1, &slice_walls[0]});
    // Wave barrier: a channel must be quiet before the next wave reuses
    // it, or two responses' streams would interleave on one socket.
    latch.Wait();
    // One wall-clock sample per wave: the longest allreduce slice
    // (bytes were summed per response, so the derived bus bandwidth
    // reflects real elapsed time, undiluted by co-scheduled
    // non-allreduce responses).
    int64_t wall = *std::max_element(slice_walls.begin(),
                                     slice_walls.end());
    if (wall > 0) allreduce_ns_.fetch_add(wall);
  }
  last_exec_time_ = std::chrono::steady_clock::now();
}

void Engine::ReleaseScratch() {
  for (auto& b : fusion_buffers_) std::vector<uint8_t>().swap(b);
}

void Engine::MaybeReleaseScratch() {
  bool any = false;
  for (auto& b : fusion_buffers_) any = any || b.capacity() > 0;
  if (!any) return;
  auto now = std::chrono::steady_clock::now();
  if (now - last_exec_time_ < std::chrono::seconds(2)) return;
  ReleaseScratch();
}

void Engine::ReduceIntoTimed(void* dst, const void* src, int64_t count,
                             DataType dtype, ReduceOp op) {
  auto t0 = std::chrono::steady_clock::now();
  const int64_t bytes = count * static_cast<int64_t>(DataTypeSize(dtype));
  // Large reductions split across IDLE pool workers (disjoint element
  // ranges of an elementwise kernel — bit-identical to the serial call
  // for any split).  TrySubmitIfIdle never queues behind a busy channel
  // task, so a shard either runs on a genuinely free core or inline here
  // — the pool cannot deadlock on its own reductions.  The cut sits
  // ABOVE the ring pipeline chunk (chunk_bytes_): chunk reduces are
  // already overlapped with the wire, and splitting them again just buys
  // latch traffic; only the big monolithic reduces (hierarchical chain
  // relays, oversized chunks) benefit.
  const int64_t kParallelCut =
      std::max<int64_t>(2 << 20, chunk_bytes_.load() * 2);
  if (bytes >= kParallelCut && pool_.size() > 0 && count >= 4) {
    int parts = std::min<int64_t>(pool_.size() + 1, bytes / (kParallelCut / 2));
    parts = std::min(parts, 4);
    if (parts > 1) {
      uint8_t* d = static_cast<uint8_t*>(dst);
      const uint8_t* s = static_cast<const uint8_t*>(src);
      const size_t esize = DataTypeSize(dtype);
      const int64_t per = count / parts;
      TaskLatch latch(parts - 1);
      for (int p = 1; p < parts; ++p) {
        int64_t off = per * p;
        int64_t n = (p == parts - 1) ? count - off : per;
        auto shard = [d, s, off, n, esize, dtype, op, &latch] {
          ReduceInto(d + off * esize, s + off * esize, n, dtype, op);
          latch.Done();
        };
        if (!pool_.TrySubmitIfIdle(shard)) shard();
      }
      ReduceInto(d, s, per, dtype, op);
      latch.Wait();
      reduce_ns_.fetch_add(
          std::chrono::duration_cast<std::chrono::nanoseconds>(
              std::chrono::steady_clock::now() - t0)
              .count());
      return;
    }
  }
  ReduceInto(dst, src, count, dtype, op);
  reduce_ns_.fetch_add(std::chrono::duration_cast<std::chrono::nanoseconds>(
                           std::chrono::steady_clock::now() - t0)
                           .count());
}

// The codec combine kernel: dequantize both operands' blocks to fp32
// staging, combine (same operand order as ReduceInto: dst op src),
// rescale and requantize into dst.  Per-hop requantization accumulates
// bounded quantization error — the wire contract for int8/fp8 is
// loss-parity convergence, not bitwise equality.  Counted as reduction
// time (reduce_ns); the buffer-edge quantize/dequantize passes are what
// quantize_ns measures.
void Engine::WireReduceBlocksTimed(uint8_t* dst, const uint8_t* src,
                                   int64_t nblocks, const WireCodec& codec,
                                   ReduceOp op) {
  auto t0 = std::chrono::steady_clock::now();
  // Thread-local staging: this runs on channel drivers and pool workers
  // concurrently, and a per-chunk heap allocation would dominate small
  // blocks.
  thread_local std::vector<float> a, b;
  const size_t n = static_cast<size_t>(codec.block_elems);
  if (a.size() < n) {
    a.resize(n);
    b.resize(n);
  }
  for (int64_t blk = 0; blk < nblocks; ++blk) {
    uint8_t* d = dst + blk * codec.block_bytes;
    const uint8_t* s = src + blk * codec.block_bytes;
    DequantizeBlock(d, codec.block_elems, codec.wire, a.data());
    DequantizeBlock(s, codec.block_elems, codec.wire, b.data());
    ReduceInto(a.data(), b.data(), codec.block_elems, DataType::FLOAT32, op);
    QuantizeBlock(a.data(), codec.block_elems, codec.wire, d,
                  codec.block_elems);
  }
  reduce_ns_.fetch_add(std::chrono::duration_cast<std::chrono::nanoseconds>(
                           std::chrono::steady_clock::now() - t0)
                           .count());
}

// Quantized (int8/fp8) allreduce over `spec`: quantize the fp32 payload
// into per-chunk-scaled blocks, run the SAME channel-sharded ring (the
// stepped legacy path or the streaming cascade, TCP or shm — the codec
// rides the spec) over the wire buffer, dequantize back into `base`.
// Blocks are sized to HOROVOD_CHUNK_BYTES worth of fp32 elements, so
// "per-chunk scales" and the pipeline chunk coincide; the last block is
// zero-padded to keep ring elements uniform.
bool Engine::CompressedRingAllreduce(uint8_t* base, int64_t count,
                                     WireDtype wire, ReduceOp op,
                                     RingSpec spec, const ExecCtx& ctx,
                                     const std::string& tname,
                                     std::string* err) {
  WireCodec codec;
  codec.wire = wire;
  codec.block_elems =
      std::min<int64_t>(std::max<int64_t>(64, chunk_bytes_.load() / 4),
                        count);
  codec.block_bytes = 4 + static_cast<size_t>(codec.block_elems);
  const int64_t nblocks =
      (count + codec.block_elems - 1) / codec.block_elems;
  std::vector<uint8_t> wirebuf(static_cast<size_t>(nblocks) *
                               codec.block_bytes);
  const float* src = reinterpret_cast<const float*>(base);
  auto q0 = std::chrono::steady_clock::now();
  for (int64_t blk = 0; blk < nblocks; ++blk) {
    const int64_t off = blk * codec.block_elems;
    const int64_t n = std::min(codec.block_elems, count - off);
    QuantizeBlock(src + off, n, wire,
                  wirebuf.data() + blk * codec.block_bytes,
                  codec.block_elems);
  }
  quantize_ns_.fetch_add(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now() - q0)
          .count());
  // Clamped at zero: a tiny tensor's wire form (scale header + padding)
  // can exceed its logical bytes, and a cumulative "saved" counter must
  // never run backwards over many small collectives.
  wire_bytes_saved_.fetch_add(std::max<int64_t>(
      0, count * 4 - static_cast<int64_t>(wirebuf.size())));
  spec.codec = &codec;
  spec.compressed = true;
  bool ok = ChanneledRingAllreduce(wirebuf.data(), nblocks,
                                   DataType::FLOAT32, op, spec, ctx, tname,
                                   err);
  if (!ok) return false;
  float* dst = reinterpret_cast<float*>(base);
  q0 = std::chrono::steady_clock::now();
  for (int64_t blk = 0; blk < nblocks; ++blk) {
    const int64_t off = blk * codec.block_elems;
    const int64_t n = std::min(codec.block_elems, count - off);
    DequantizeBlock(wirebuf.data() + blk * codec.block_bytes, n, wire,
                    dst + off);
  }
  quantize_ns_.fetch_add(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now() - q0)
          .count());
  return true;
}

void Engine::NoteSkippedResponse(const Response& response,
                                 std::vector<TensorTableEntry>& entries) {
  AssertBackgroundThread();  // skip_tokens_/pending_cache_hits_ owner
  backup_skips_.fetch_add(1);
  GlobalFlightRecorder().Record(
      "skipped", control_cycle_seq_, "%s",
      response.tensor_names.empty() ? "?"
                                    : response.tensor_names[0].c_str());
  std::set<std::string> held;
  for (auto& e : entries) held.insert(e.name);
  for (const auto& name : response.tensor_names) {
    if (held.count(name) != 0) continue;
    // Not even enqueued yet (the straggler's API thread is behind):
    // bank a token; the future enqueue consumes it and finishes
    // "skipped" locally instead of shipping a request the coordinator
    // already committed without us.
    skip_tokens_[name] += 1;
  }
  if (!held.empty()) {
    // The entry exists but its request raced this cycle's frame (it is
    // still in message_queue_, unsent): purge it, or the next cycle
    // would plant a stale pending entry on the coordinator.
    std::lock_guard<std::mutex> lk(mu_);
    for (auto it = message_queue_.begin(); it != message_queue_.end();) {
      if (held.count(it->tensor_name) != 0) {
        it = message_queue_.erase(it);
      } else {
        ++it;
      }
    }
  }
  // A hit bit we already sent for this tensor was consumed by the
  // partial slot commit (hier: one slow group member sidelines the
  // whole group, ready members included) — drop the pending record so
  // an evict can't resubmit a tensor that no longer exists.
  for (auto it = pending_cache_hits_.begin();
       it != pending_cache_hits_.end();) {
    if (held.count(it->second) != 0) {
      it = pending_cache_hits_.erase(it);
    } else {
      ++it;
    }
  }
  for (auto& e : entries) {
    FinishEntry(e, Status::PreconditionError(kSkippedStepError), 0);
  }
}

void Engine::PerformResponse(const Response& response, const ExecCtx& ctx) {
  // Backup-worker partial commit that left THIS rank out: the skip
  // bookkeeping (finish-skipped entries, banked tokens) already ran in
  // ExecuteResponses on the background thread — here (possibly a wave
  // pool thread) we only ghost-drive the collective so the ring still
  // spans the whole world (the ghost contributes zeros, the SUM
  // identity).  A ghost never pops entries: one enqueued after the
  // bookkeeping sweep is consumed by its banked token at the next
  // DrainMessageQueue, never stranded here.
  const bool ghost = !response.participants.empty() &&
                     !RankInParticipants(response.participants);
  if (ghost && ((response.type != ResponseType::ALLREDUCE &&
                 response.type != ResponseType::REDUCESCATTER) ||
                response.partial_elems <= 0)) {
    return;  // partial ERROR (or degenerate): nothing to ghost-run
  }
  std::vector<TensorTableEntry> entries;
  if (!ghost) {
    std::lock_guard<std::mutex> lk(mu_);
    for (const auto& name : response.tensor_names) {
      auto it = tensor_table_.find(name);
      if (it != tensor_table_.end()) {
        entries.push_back(std::move(it->second));
        tensor_table_.erase(it);
      }
    }
  }
  if (response.type == ResponseType::ERROR) {
    for (auto& e : entries) {
      FinishEntry(e, Status::PreconditionError(response.error_message));
    }
    return;
  }
  if (response.type == ResponseType::SPARSE_RETRY) {
    // Only ranks that enqueued the layout probe hold an entry; they fail
    // the handle with the magic message so the frontend re-enqueues
    // zero-entry sparse gathers.  Ranks without an entry ignore it.
    int64_t sd = response.tensor_sizes.empty() ? 1 : response.tensor_sizes[0];
    for (auto& e : entries) {
      FinishEntry(e, Status::PreconditionError(
          "__sparse_retry__:" + std::to_string(sd)));
    }
    return;
  }
  if (entries.empty() && !ghost) return;
  if (!ghost) {
    responses_executed_.fetch_add(1);
    tensors_executed_.fetch_add(static_cast<int64_t>(entries.size()));
  }
  // Flow sink: every executing rank closes the flow the coordinator's
  // commit opened — one "f" per tensor name (fusion preserves the name
  // set, so per-name flow counters stay aligned with the per-name "s"
  // counters on rank 0).  Ghost rides execute the response too: the
  // flow arrow correctly lands on the ghost's RING span.
  for (const auto& name : response.tensor_names) {
    timeline_.FlowRecv(name, epoch_.load());
  }
  // Priority scheduling: annotate which band this response dispatched
  // in (trace forensics for the overlap work — PRIO_BAND0 is the most
  // urgent).  Bands off or priority unknown (ghost ride): no marker.
  if (!response.tensor_names.empty() && response.priority >= 0 &&
      priority_bands_.load() > 0) {
    char pm[32];
    std::snprintf(pm, sizeof(pm), "PRIO_BAND%lld",
                  static_cast<long long>(ResponseBand(response)));
    timeline_.Algo(response.tensor_names[0], pm);
  }
  switch (response.type) {
    case ResponseType::ALLREDUCE:
      ExecAllreduce(response, entries, ctx);
      break;
    case ResponseType::ALLGATHER:
      ExecAllgather(response, entries, ctx);
      break;
    case ResponseType::BROADCAST:
      ExecBroadcast(response, entries, ctx);
      break;
    case ResponseType::REDUCESCATTER:
      ExecReducescatter(response, entries, ctx);
      break;
    case ResponseType::ALLTOALL:
      ExecAlltoall(response, entries, ctx);
      break;
    default:
      break;
  }
}

// Ring segment arithmetic, shared by every ring and by the star fold that
// emulates it.  `vrank` is the rank used for segment bookkeeping: after
// the reduce-scatter phase, vrank v owns the fully-reduced segment
// (v + 1) mod size.  Under the engine-wide convention vrank =
// position - 1 (see TcpRingSpec), PHYSICAL position s therefore owns
// segment s — the RS half terminates at each rank's own shard — and
// segment s is accumulated in ring order s+1, s+2, ..., s+size (mod
// size), the fold order StarFoldAllreduce reproduces exactly.
static void EvenSegments(int64_t count, int size,
                         std::vector<int64_t>* seg_count,
                         std::vector<int64_t>* seg_off) {
  seg_count->resize(size);
  seg_off->resize(size);
  int64_t off = 0;
  for (int s = 0; s < size; ++s) {
    (*seg_count)[s] = count / size + (s < count % size ? 1 : 0);
    (*seg_off)[s] = off;
    off += (*seg_count)[s];
  }
}

// Transport-generic duplex chunked transfer on one ring port: the TCP
// pair goes through the poll-multiplexed SendRecvChunked, an shm edge
// through its ring-buffer twin — same callback contract, same timeout
// semantics, so every phase below runs unchanged over either kind.
bool Engine::PortSendRecvChunked(
    const RingPort& port, const void* send_buf, size_t sn, void* recv_buf,
    size_t rn, size_t chunk,
    const std::function<void(size_t, size_t)>& on_chunk, int timeout_ms,
    std::string* err, int64_t* wire_ns) {
  if (port.is_shm()) {
    return ShmSendRecvChunked(*port.shm_tx, send_buf, sn, *port.shm_rx,
                              recv_buf, rn, chunk, on_chunk, timeout_ms,
                              err, wire_ns);
  }
  return SendRecvChunked(*port.next, send_buf, sn, *port.prev, recv_buf,
                         rn, chunk, on_chunk, timeout_ms, err, wire_ns);
}

bool Engine::PortSendAll(const RingPort& port, const void* p, size_t n,
                         std::string* err) {
  if (port.is_shm()) {
    std::string detail;
    if (!port.shm_tx->WriteAll(p, n, socket_timeout_sec_ * 1000, &detail)) {
      // "send" prefix so TransportError blames the ring-next neighbor,
      // exactly like the TCP branch below.
      *err = "send to peer: " + detail;
      return false;
    }
    return true;
  }
  if (!port.next->SendAll(p, n)) {
    *err = "send to peer: transport failure";
    return false;
  }
  return true;
}

bool Engine::PortRecvAllPatient(const RingPort& port, void* p, size_t n,
                                int patience_rounds, std::string* err) {
  if (port.is_shm()) {
    // Same patience contract as RecvAllPatient: `rounds` consecutive
    // no-progress windows of one socket timeout each before giving up
    // (0 timeout = wait forever, exactly like the disabled-socket-timeout
    // TCP path).
    int64_t ms = static_cast<int64_t>(std::max(1, patience_rounds)) *
                 socket_timeout_sec_ * 1000;
    std::string detail;
    if (!port.shm_rx->ReadAll(p, n, static_cast<int>(ms), &detail)) {
      *err = "recv from peer: " + detail;
      return false;
    }
    return true;
  }
  if (!port.prev->RecvAllPatient(p, n, patience_rounds)) {
    *err = "recv from peer: transport failure";
    return false;
  }
  return true;
}

// One channel's reduce-scatter phase over explicit per-segment slices,
// chunk-pipelined: the recv of chunk k+1 streams through the kernel
// buffers while ReduceInto processes chunk k (the chunked transfer fires
// the reduction from its progress loop the moment a chunk's bytes are
// in).  Runs over whichever ring `spec` describes — flat TCP, flat shm,
// the intra-host shm ring, or the leader cross ring.
bool Engine::RingReduceScatterPhaseCh(uint8_t* base,
                                      const std::vector<int64_t>& seg_count,
                                      const std::vector<int64_t>& seg_off,
                                      DataType dtype, ReduceOp op,
                                      const RingSpec& spec, int ch,
                                      std::string* err) {
  // Under a wire codec the ring element is one quantized BLOCK
  // (seg_count/seg_off are block-granular) and the combine kernel is the
  // dequant-add-requant block reduce; everything else is unchanged.
  const size_t esize =
      spec.codec ? spec.codec->block_bytes : DataTypeSize(dtype);
  const int rsize = spec.rsize;
  const int vrank = spec.vrank;
  int64_t max_seg = 0;
  for (auto c : seg_count) max_seg = std::max(max_seg, c);
  // Raw allocation: vector's value-init would memset up to segment-size
  // bytes per collective for data every chunk immediately overwrites.
  std::unique_ptr<uint8_t[]> tmp(
      new uint8_t[static_cast<size_t>(max_seg) * esize]);
  size_t chunk =
      static_cast<size_t>(chunk_bytes_.load()) / esize * esize;  // aligned
  if (chunk == 0) chunk = esize;  // a wire block can exceed the chunk knob
  const int timeout_ms = socket_timeout_sec_ * 1000;
  for (int step = 0; step < rsize - 1; ++step) {
    int send_seg = (vrank - step + 2 * rsize) % rsize;
    int recv_seg = (vrank - step - 1 + 2 * rsize) % rsize;
    const size_t sn = static_cast<size_t>(seg_count[send_seg]) * esize;
    const size_t rn = static_cast<size_t>(seg_count[recv_seg]) * esize;
    uint8_t* rbase = base + seg_off[recv_seg] * esize;
    int64_t wns = 0;
    bool ok = PortSendRecvChunked(
        spec.ports[ch], base + seg_off[send_seg] * esize, sn, tmp.get(), rn,
        chunk,
        [&](size_t off, size_t len) {
          if (spec.codec != nullptr) {
            WireReduceBlocksTimed(rbase + off, tmp.get() + off,
                                  static_cast<int64_t>(len / esize),
                                  *spec.codec, op);
          } else {
            ReduceIntoTimed(rbase + off, tmp.get() + off,
                            static_cast<int64_t>(len / esize), dtype, op);
          }
        },
        timeout_ms, err, &wns);
    wire_ns_.fetch_add(wns);
    if (!ok) return false;
    CountPortBytes(spec.ports[ch], static_cast<int64_t>(sn),
                   static_cast<int64_t>(rn), spec.compressed);
  }
  return true;
}


bool Engine::RingAllgatherPhaseCh(uint8_t* base,
                                  const std::vector<int64_t>& seg_count,
                                  const std::vector<int64_t>& seg_off,
                                  size_t esize, const RingSpec& spec, int ch,
                                  std::string* err) {
  const int timeout_ms = socket_timeout_sec_ * 1000;
  const int rsize = spec.rsize;
  const int vrank = spec.vrank;
  for (int step = 0; step < rsize - 1; ++step) {
    int send_seg = (vrank - step + 1 + rsize) % rsize;
    int recv_seg = (vrank - step + rsize) % rsize;
    const size_t sn = static_cast<size_t>(seg_count[send_seg]) * esize;
    const size_t rn = static_cast<size_t>(seg_count[recv_seg]) * esize;
    int64_t wns = 0;
    bool ok = PortSendRecvChunked(spec.ports[ch],
                                  base + seg_off[send_seg] * esize, sn,
                                  base + seg_off[recv_seg] * esize, rn,
                                  /*chunk=*/0, nullptr, timeout_ms, err,
                                  &wns);
    wire_ns_.fetch_add(wns);
    if (!ok) return false;
    CountPortBytes(spec.ports[ch], static_cast<int64_t>(sn),
                   static_cast<int64_t>(rn), spec.compressed);
  }
  return true;
}

// The streaming cascade (see engine.h): sender and receiver cursors walk
// the unified step schedule s = 0..2(N-1)-1 — reduce-scatter steps then
// allgather steps — with per-step eligibility fed by the receiver.
// ready[s] counts bytes of step s's send segment that may ship: step 0 is
// fully ready at start (local data); step s+1's segment IS the segment
// received at step s, so the receiver credits ready[s+1] as bytes land
// (allgather: raw bytes — final on arrival) or as chunks finish reducing
// (reduce-scatter: a chunk is sendable only once combined).  Both sides
// walk steps in the same order, so the two FIFO directions stay framed
// without any headers.
bool Engine::StreamingRingChannels(uint8_t* base,
                                   const std::vector<ChannelSegs>& channels,
                                   DataType dtype, ReduceOp op,
                                   const RingSpec& spec,
                                   const std::string& tname,
                                   std::string* err, bool rs_only) {
  const size_t esize =
      spec.codec ? spec.codec->block_bytes : DataTypeSize(dtype);
  const int N = spec.rsize;
  const int vrank = spec.vrank;
  // rs_only: the schedule simply stops after the reduce-scatter half —
  // an identical prefix of the full cascade, so the owned segment's
  // bits cannot differ from the full allreduce's.
  const int nsteps = rs_only ? (N - 1) : 2 * (N - 1);
  const int last_rs = N - 2;  // steps [0, last_rs] reduce; rest allgather
  // Step schedule (segment ids, shared by every channel).  RS step s:
  // send (vrank-s), recv (vrank-s-1), reduce.  AG step s' = s-(N-1):
  // send (vrank-s'+1), recv (vrank-s') — the continuation of the same
  // per-chunk dependency chain.
  std::vector<int> send_seg(nsteps), recv_seg(nsteps);
  for (int s = 0; s < nsteps; ++s) {
    if (s <= last_rs) {
      send_seg[s] = (vrank - s + 2 * N) % N;
      recv_seg[s] = (vrank - s - 1 + 2 * N) % N;
    } else {
      int sp = s - (N - 1);
      send_seg[s] = (vrank - sp + 1 + 2 * N) % N;
      recv_seg[s] = (vrank - sp + 2 * N) % N;
    }
  }
  size_t chunk =
      static_cast<size_t>(chunk_bytes_.load()) / esize * esize;  // aligned
  if (chunk == 0) chunk = esize;  // a wire block can exceed the chunk knob

  // Per-channel cascade state.
  struct ChState {
    const ChannelSegs* segs = nullptr;
    const RingPort* port = nullptr;
    std::vector<size_t> ready;
    int ss = 0;          // sender step
    size_t so = 0;       // bytes of step ss already sent
    int rs = 0;          // receiver step
    size_t ro = 0;       // bytes of step rs already received
    size_t reduced = 0;  // bytes of step rs already reduced (RS steps)
    size_t tx = 0, rx = 0;
    // RS receive scratch (chunks are reduced out of it as they
    // complete); raw allocation — value-init would memset a segment per
    // collective.
    std::unique_ptr<uint8_t[]> tmp;
  };
  // A spec's ports are homogeneous (a ring is wholly TCP or wholly shm),
  // so the transport branch is taken once, not per chunk.
  const bool is_shm = spec.ports[channels[0].ch].is_shm();
  std::vector<ChState> st(channels.size());
  for (size_t i = 0; i < channels.size(); ++i) {
    ChState& c = st[i];
    c.segs = &channels[i];
    c.port = &spec.ports[c.segs->ch];
    c.ready.assign(nsteps + 1, 0);
    int64_t max_seg = 0;
    for (auto n : c.segs->seg_count) max_seg = std::max(max_seg, n);
    c.tmp.reset(new uint8_t[static_cast<size_t>(max_seg) * esize]);
  }
  // Cascade stream sequences: one bump per channel per invocation.  Both
  // endpoints of an edge execute the same deterministic response sequence
  // over the same channels, so the counters agree — a link-heal RESUME's
  // seq names exactly one in-flight cascade on both sides.
  std::vector<int64_t> ch_seq(st.size(), 0);
  if (!is_shm && spec.seq != nullptr) {
    for (size_t i = 0; i < st.size(); ++i) {
      int ch = st[i].segs->ch;
      if (ch >= 0 && ch < static_cast<int>(spec.seq->size())) {
        ch_seq[i] = ++(*spec.seq)[ch];
      }
    }
  }
  // Link self-healing is a TCP-ring affair: shm edges have no socket to
  // heal, and HOROVOD_LINK_RETRIES=0 restores the fail-fast path exactly.
  const bool heal_on =
      !is_shm && link_retries_ > 0 && spec.ring_id >= 0 &&
      spec.seq != nullptr && spec.next_peer >= 0 && spec.prev_peer >= 0 &&
      spec.next_peer < static_cast<int>(peer_hosts_.size()) &&
      spec.prev_peer < static_cast<int>(peer_hosts_.size());
  auto seg_bytes = [&](const ChState& c, int seg) {
    return static_cast<size_t>(c.segs->seg_count[seg]) * esize;
  };
  auto advance_sender = [&](ChState& c) {
    while (c.ss < nsteps && c.so == seg_bytes(c, send_seg[c.ss])) {
      ++c.ss;
      c.so = 0;
    }
  };
  auto advance_receiver = [&](ChState& c) {
    while (c.rs < nsteps && c.ro == seg_bytes(c, recv_seg[c.rs])) {
      ++c.rs;
      c.ro = 0;
      c.reduced = 0;
    }
  };
  for (auto& c : st) {
    c.ready[0] = seg_bytes(c, send_seg[0]);
    advance_sender(c);
    advance_receiver(c);
  }
  const int timeout_ms = socket_timeout_sec_ * 1000;
  auto t0 = std::chrono::steady_clock::now();
  int64_t local_reduce_ns = 0;
  bool ok = true;
  // Receive-side bookkeeping shared by both transports: after `k` fresh
  // bytes of step c.rs landed, reduce every COMPLETED chunk (RS steps) or
  // credit the raw bytes downstream (allgather steps — final on arrival),
  // then advance the cursor past any finished/empty steps.
  auto credit_recv = [&](ChState& c, size_t k) {
    if (c.rs <= last_rs) {
      uint8_t* sb = base + c.segs->seg_off[recv_seg[c.rs]] * esize;
      const size_t total = seg_bytes(c, recv_seg[c.rs]);
      while (c.reduced < c.ro &&
             (c.ro - c.reduced >= chunk || c.ro == total)) {
        size_t len = std::min(chunk, c.ro - c.reduced);
        auto r0 = std::chrono::steady_clock::now();
        if (spec.codec != nullptr) {
          WireReduceBlocksTimed(sb + c.reduced, c.tmp.get() + c.reduced,
                                static_cast<int64_t>(len / esize),
                                *spec.codec, op);
        } else {
          ReduceIntoTimed(sb + c.reduced, c.tmp.get() + c.reduced,
                          static_cast<int64_t>(len / esize), dtype, op);
        }
        local_reduce_ns +=
            std::chrono::duration_cast<std::chrono::nanoseconds>(
                std::chrono::steady_clock::now() - r0)
                .count();
        c.reduced += len;
        if (c.rs + 1 < nsteps) c.ready[c.rs + 1] += len;
      }
    } else if (c.rs + 1 < nsteps) {
      c.ready[c.rs + 1] += k;
    }
    advance_receiver(c);
  };
  if (is_shm) {
    // Shm cascade: the SPSC rings are progressed with nonblocking
    // TryWrite/TryRead — no pollable fd, so idleness parks on a
    // spin-then-yield-then-nap ladder (the WaitSeqSlice futex path serves
    // single-ring waits; a multi-ring cascade would need one futex word
    // per ring and gVisor's coverage is spotty anyway).  timeout_ms
    // bounds time with NO forward progress across every channel, exactly
    // like the TCP poll timeout.
    auto last_progress = std::chrono::steady_clock::now();
    int idle = 0;
    while (ok) {
      bool all_done = true, progressed = false;
      for (auto& c : st) {
        while (c.ss < nsteps && c.so < c.ready[c.ss]) {
          const uint8_t* p =
              base + c.segs->seg_off[send_seg[c.ss]] * esize + c.so;
          size_t k = c.port->shm_tx->TryWrite(p, c.ready[c.ss] - c.so);
          if (k > 0) {
            c.so += k;
            c.tx += k;
            progressed = true;
            advance_sender(c);
          } else {
            if (c.port->shm_tx->Closed()) {
              *err = "send to peer: shm ring closed (peer exited?)";
              ok = false;
            }
            break;
          }
        }
        if (!ok) break;
        while (c.rs < nsteps) {
          const bool reducing = c.rs <= last_rs;
          const size_t want = seg_bytes(c, recv_seg[c.rs]) - c.ro;
          uint8_t* dst =
              reducing ? c.tmp.get() + c.ro
                       : base + c.segs->seg_off[recv_seg[c.rs]] * esize +
                             c.ro;
          size_t k = c.port->shm_rx->TryRead(dst, want);
          if (k > 0) {
            c.ro += k;
            c.rx += k;
            progressed = true;
            credit_recv(c, k);
          } else {
            if (c.port->shm_rx->Closed()) {
              *err = "recv from peer: shm ring closed (peer exited?)";
              ok = false;
            }
            break;
          }
        }
        if (!ok) break;
        if (c.ss < nsteps || c.rs < nsteps) all_done = false;
      }
      if (!ok || all_done) break;
      if (progressed) {
        last_progress = std::chrono::steady_clock::now();
        idle = 0;
        continue;
      }
      if (++idle < 64) {
        std::this_thread::yield();
      } else {
        std::this_thread::sleep_for(std::chrono::microseconds(50));
      }
      if (timeout_ms > 0 &&
          std::chrono::steady_clock::now() - last_progress >
              std::chrono::milliseconds(timeout_ms)) {
        *err = "link: no progress for " + std::to_string(timeout_ms / 1000) +
               "s (peer hung?)";
        ok = false;
      }
    }
  } else {
  // -- TCP branch: poll-multiplexed cascade with link self-healing --
  //
  // A hard socket failure on a ring edge is classified SUSPECT instead of
  // fatal when heal_on: the channel's cascade parks at its exact
  // step/offset cursor while the edge re-establishes — the SENDER
  // re-dials the receiver's data listener with a RESUME hello (bounded
  // attempts/backoff), the RECEIVER ACKs its authoritative cursor, the
  // sender rewinds, and the stream resumes bit-identically (un-received
  // bytes are still intact in `base`: overwriting a chunk requires the
  // ring to have cycled it all the way around, which implies the
  // downstream receiver already consumed it).  Exhaustion escalates to
  // the unchanged abort path carrying the ORIGINAL transport error, so
  // culprit attribution is exactly what it was before healing existed.
  struct Heal {
    bool snd = false, rcv = false;  // per-direction suspect flags
    std::string snd_err, rcv_err;   // the original (attributable) errors
    std::chrono::steady_clock::time_point snd_t0, rcv_t0;
    std::chrono::steady_clock::time_point snd_next;  // next re-dial
    int snd_attempts = 0;
    // The re-dial in flight: first a nonblocking connect awaiting
    // POLLOUT (pending_connecting), then — hello sent — awaiting the
    // ACK on POLLIN.  Both phases bounded by pending_deadline; neither
    // ever blocks the driver's other channels.
    Socket pending;
    bool pending_connecting = false;
    std::chrono::steady_clock::time_point pending_deadline;
    bool span_open = false;
  };
  std::vector<Heal> heal(st.size());
  const int64_t heal_ms = link_heal_timeout_ms_;
  auto ms_since = [](std::chrono::steady_clock::time_point t) {
    return std::chrono::duration_cast<std::chrono::milliseconds>(
               std::chrono::steady_clock::now() - t)
        .count();
  };
  // Backoff jitter (±25%): rank-keyed LCG so simultaneous two-sided
  // failures don't re-dial in lockstep.
  uint32_t jseed = static_cast<uint32_t>(rank_) * 2654435761u + 12345u;
  auto jittered = [&jseed](int msv) {
    jseed = jseed * 1664525u + 1013904223u;
    int span = msv / 2;
    return msv - msv / 4 + (span > 0 ? static_cast<int>(jseed % span) : 0);
  };
  auto set_nonblock = [](int fd) {
    int fl = ::fcntl(fd, F_GETFL, 0);
    if (fl >= 0) ::fcntl(fd, F_SETFL, fl | O_NONBLOCK);
  };
  auto set_block = [](int fd) {
    int fl = ::fcntl(fd, F_GETFL, 0);
    if (fl >= 0) ::fcntl(fd, F_SETFL, fl & ~O_NONBLOCK);
  };
  for (auto& c : st) {
    set_nonblock(c.port->next->fd());
    set_nonblock(c.port->prev->fd());
  }
  auto last_progress = std::chrono::steady_clock::now();
  // Injected recv-stall (rank:step:recv-stall:ms): stop draining the
  // first channel until the deadline — a transient stall, not a failure.
  std::chrono::steady_clock::time_point stall_until = last_progress;
  size_t stall_idx = st.size();  // >= size: no stall armed
  if (spec.ring_id == RING_GLOBAL) {
    int64_t sms = fault_stall_ms_.exchange(0);
    if (sms > 0) {
      stall_idx = 0;
      stall_until = last_progress + std::chrono::milliseconds(sms);
      std::fprintf(stderr,
                   "horovod_tpu rank %d: fault injection: not draining "
                   "data channel %d for %lldms\n",
                   rank_, st[0].segs->ch, static_cast<long long>(sms));
    }
  }
  auto heal_span_open = [&](size_t i) {
    if (!heal[i].span_open) {
      timeline_.ActivityStartCh(tname, "LINK_HEAL", st[i].segs->ch + 1);
      heal[i].span_open = true;
    }
  };
  auto heal_span_close = [&](size_t i) {
    if (heal[i].span_open && !heal[i].snd && !heal[i].rcv) {
      timeline_.ActivityEndCh(tname, st[i].segs->ch + 1);
      heal[i].span_open = false;
    }
  };
  // Swap a freshly established connection into a ring port slot with the
  // full data-socket option set the wiring path applies.
  auto arm_healed = [&](Socket* slot, Socket conn) {
    *slot = std::move(conn);
    slot->SetTimeouts(socket_timeout_sec_);
    ArmSocketDeadlines(*slot, socket_timeout_sec_);
    slot->SetBufSizes(socket_buf_bytes_);
    set_nonblock(slot->fd());
  };
  // Classify a hard failure.  Returns false (fatal, *err set) when
  // healing is off — the pre-heal behavior, bit for bit.
  auto suspect_snd = [&](size_t i, const std::string& what) -> bool {
    if (!heal_on) {
      *err = what;
      return false;
    }
    Heal& h = heal[i];
    if (h.snd) return true;  // already healing this direction
    h.snd = true;
    h.snd_err = what;
    h.snd_t0 = std::chrono::steady_clock::now();
    h.snd_next = h.snd_t0;  // first re-dial immediately
    h.snd_attempts = 0;
    heal_span_open(i);
    GlobalFlightRecorder().Record(
        "link", control_cycle_seq_, "suspect snd ch=%d seq=%lld: %s",
        st[i].segs->ch, static_cast<long long>(ch_seq[i]),
        what.c_str());
    return true;
  };
  auto suspect_rcv = [&](size_t i, const std::string& what) -> bool {
    if (!heal_on) {
      *err = what;
      return false;
    }
    Heal& h = heal[i];
    if (h.rcv) return true;
    h.rcv = true;
    h.rcv_err = what;
    h.rcv_t0 = std::chrono::steady_clock::now();
    heal_span_open(i);
    GlobalFlightRecorder().Record(
        "link", control_cycle_seq_, "suspect rcv ch=%d seq=%lld: %s",
        st[i].segs->ch, static_cast<long long>(ch_seq[i]),
        what.c_str());
    return true;
  };
  auto escalate = [&](size_t i, bool snd_dir) {
    Heal& h = heal[i];
    const std::string& base_err = snd_dir ? h.snd_err : h.rcv_err;
    *err = base_err + " (link healing gave up after " +
           std::to_string(snd_dir ? h.snd_attempts : 0) + " reconnect "
           "attempts in " +
           std::to_string(ms_since(snd_dir ? h.snd_t0 : h.rcv_t0)) + "ms)";
    ok = false;
    link_heal_failures_.fetch_add(1);
    GlobalFlightRecorder().Record(
        "link", control_cycle_seq_, "escalate %s ch=%d: %s",
        snd_dir ? "snd" : "rcv", st[i].segs->ch, base_err.c_str());
  };
  // Abandon the in-flight re-dial (if any) and schedule the next one.
  auto redial_backoff = [&](Heal& h) {
    h.pending.Close();
    h.pending_connecting = false;
    h.snd_next =
        std::chrono::steady_clock::now() +
        std::chrono::milliseconds(jittered(
            std::min(1000, 50 << std::min(h.snd_attempts, 5))));
  };
  // Send the RESUME hello on a freshly connected socket and start the
  // ACK wait.  The 48-byte hello lands in an empty send buffer, so the
  // (bounded, 2 s) blocking send cannot actually park the loop.
  auto send_hello = [&](size_t i, Socket s) {
    Heal& h = heal[i];
    LinkResume lr;
    lr.origin = rank_;
    lr.ring = spec.ring_id;
    lr.channel = st[i].segs->ch;
    lr.epoch = epoch_.load();
    lr.seq = ch_seq[i];
    s.SetTimeouts(2);
    if (!s.SendAll(&lr, sizeof(lr))) {
      redial_backoff(h);
      return;
    }
    h.pending = std::move(s);
    h.pending_connecting = false;
    h.pending_deadline = std::chrono::steady_clock::now() +
                         std::chrono::milliseconds(2000);
  };
  // One sender-heal re-dial: NONBLOCKING connect (the in-flight fd joins
  // the poll set — a driver multiplexing several channels must not park
  // its healthy channels for a connect timeout) + RESUME hello; the ACK
  // is collected asynchronously too, so concurrent two-sided heals
  // (both neighbors re-dialing each other) cannot deadlock on each
  // other's ACK waits.
  auto try_redial = [&](size_t i) {
    Heal& h = heal[i];
    auto now = std::chrono::steady_clock::now();
    if (!h.snd || h.pending.valid() || now < h.snd_next ||
        h.snd_attempts >= link_retries_) {
      return;
    }
    ++h.snd_attempts;
    auto deadline = h.snd_t0 + std::chrono::milliseconds(heal_ms);
    int64_t left = std::chrono::duration_cast<std::chrono::milliseconds>(
                       deadline - now)
                       .count();
    if (left <= 0) return;  // the escalation sweep handles expiry
    std::string cerr;
    bool in_progress = false;
    Socket s = ConnectStart(peer_hosts_[spec.next_peer],
                            peer_ports_[spec.next_peer], &in_progress,
                            &cerr);
    if (!s.valid()) {
      redial_backoff(h);
      return;
    }
    h.pending_deadline =
        now + std::chrono::milliseconds(
                  std::min<int64_t>(1000, std::max<int64_t>(50, left)));
    if (in_progress) {
      h.pending = std::move(s);
      h.pending_connecting = true;
      return;
    }
    send_hello(i, std::move(s));
  };
  // Service a RESUME naming one of THIS cascade's prev edges: ACK the
  // authoritative receive cursor, swap the healed socket in.  Returns
  // false only when the peer's stream moved past ours — the missing tail
  // is unrecoverable and the rcv suspect escalates.
  auto handle_resume = [&](size_t i, const LinkResume& lr,
                           Socket conn) -> bool {
    ChState& c = st[i];
    Heal& h = heal[i];
    LinkResumeAck ack;
    ack.ok = (lr.seq == ch_seq[i]) ? 1 : 0;
    ack.seq = ch_seq[i];
    ack.step = c.rs;
    ack.offset = static_cast<int64_t>(c.ro);
    conn.SetTimeouts(2);
    if (!conn.SendAll(&ack, sizeof(ack))) {
      return true;  // sender abandoned this conn; it will re-dial
    }
    if (ack.ok == 0) {
      if (lr.seq > ch_seq[i] && h.rcv) {
        escalate(i, /*snd_dir=*/false);
        *err = h.rcv_err +
               " (link heal failed: peer moved to a newer stream — the "
               "lost bytes are no longer replayable)";
        return false;
      }
      return true;  // stale resume for an older stream: declined
    }
    arm_healed(c.port->prev, std::move(conn));
    link_reconnects_.fetch_add(1);
    auto now = std::chrono::steady_clock::now();
    if (h.rcv) {
      RecordLinkHealNs(
          std::chrono::duration_cast<std::chrono::nanoseconds>(now -
                                                               h.rcv_t0)
              .count());
      h.rcv = false;
      heal_span_close(i);
      GlobalFlightRecorder().Record(
          "link", control_cycle_seq_,
          "healed rcv ch=%d seq=%lld step=%lld off=%lld", st[i].segs->ch,
          static_cast<long long>(ch_seq[i]),
          static_cast<long long>(ack.step),
          static_cast<long long>(ack.offset));
    } else {
      // Asymmetric failure: the sender detected a break our side never
      // saw (e.g. its TCP_USER_TIMEOUT fired while our direction only
      // went silent).  Adopt the fresh edge — the ACK cursor makes the
      // rewind exact either way.
      GlobalFlightRecorder().Record(
          "link", control_cycle_seq_,
          "peer-initiated resume ch=%d seq=%lld", st[i].segs->ch,
          static_cast<long long>(ch_seq[i]));
    }
    last_progress = now;
    return true;
  };
  std::vector<pollfd> fds;
  // (channel idx, kind): 0 = send, 1 = recv, 2 = pending ACK,
  // 3 = data listener, 4 = send-socket liveness probe (a broken edge is
  // only visible to an idle sender through the reverse direction's
  // EOF/error — without the probe, a receiver whose sender has nothing
  // left to send would park for the full heal budget and escalate).
  std::vector<std::pair<int, int>> owner;
  while (ok) {
    auto now = std::chrono::steady_clock::now();
    // Injected conn-reset: fire once bytes have moved (mid-cascade).
    if (spec.ring_id == RING_GLOBAL && fault_conn_reset_.load()) {
      int64_t moved = 0;
      for (auto& c : st) moved += static_cast<int64_t>(c.tx + c.rx);
      if (moved > 0 && fault_conn_reset_.exchange(false)) {
        ChState& c0 = st[0];
        int fd = fault_reset_prev_ ? c0.port->prev->fd()
                                   : c0.port->next->fd();
        std::fprintf(stderr,
                     "horovod_tpu rank %d: fault injection: shutting down "
                     "data channel %d %s socket mid-cascade\n",
                     rank_, c0.segs->ch,
                     fault_reset_prev_ ? "recv" : "send");
        GlobalFlightRecorder().Record(
            "link", control_cycle_seq_,
            "fault-inject conn-reset ch=%d side=%s", c0.segs->ch,
            fault_reset_prev_ ? "recv" : "send");
        ::shutdown(fd, SHUT_RDWR);
      }
    }
    // Escalate suspects that exhausted their budget.
    for (size_t i = 0; ok && i < st.size(); ++i) {
      Heal& h = heal[i];
      if (h.snd &&
          (ms_since(h.snd_t0) > heal_ms ||
           (h.snd_attempts >= link_retries_ && !h.pending.valid()))) {
        escalate(i, /*snd_dir=*/true);
      }
      if (ok && h.rcv && ms_since(h.rcv_t0) > heal_ms) {
        escalate(i, /*snd_dir=*/false);
      }
      // Per-attempt bound on the in-flight re-dial (connect or ACK
      // phase): expire it and let the backoff schedule the next one.
      if (ok && h.pending.valid() && now > h.pending_deadline) {
        redial_backoff(h);
      }
    }
    if (!ok) break;
    for (size_t i = 0; i < st.size(); ++i) try_redial(i);
    // Parked resumes deposited by other cascades/drivers.
    if (heal_on && heal_inbox_size_.load() > 0) {
      for (size_t i = 0; ok && i < st.size(); ++i) {
        LinkResume lr;
        Socket conn;
        if (HealInboxTake(spec.ring_id, st[i].segs->ch, &lr, &conn)) {
          if (lr.epoch == epoch_.load() && lr.origin == spec.prev_peer) {
            ok = handle_resume(i, lr, std::move(conn));
          }
        }
      }
      if (!ok) break;
    }
    bool all_done = true;
    for (auto& c : st) {
      if (c.ss < nsteps || c.rs < nsteps) {
        all_done = false;
        break;
      }
    }
    if (all_done) break;
    const bool stall_active = stall_idx < st.size() && now < stall_until;
    bool heals_active = false;
    fds.clear();
    owner.clear();
    for (size_t i = 0; i < st.size(); ++i) {
      ChState& c = st[i];
      Heal& h = heal[i];
      heals_active = heals_active || h.snd || h.rcv;
      if (!h.snd) {
        if (c.ss < nsteps && c.so < c.ready[c.ss]) {
          fds.push_back({c.port->next->fd(), POLLOUT, 0});
          owner.emplace_back(static_cast<int>(i), 0);
        } else if (heal_on && c.ss < nsteps) {
          // Liveness probe: nothing eligible to send, but the edge still
          // owes bytes — a reverse-direction EOF/error is the only
          // prompt signal that the connection died under an idle sender.
          fds.push_back({c.port->next->fd(),
                         static_cast<short>(POLLIN | POLLRDHUP), 0});
          owner.emplace_back(static_cast<int>(i), 4);
        }
      }
      if (h.pending.valid()) {
        fds.push_back({h.pending.fd(),
                       static_cast<short>(h.pending_connecting ? POLLOUT
                                                               : POLLIN),
                       0});
        owner.emplace_back(static_cast<int>(i), 2);
      }
      if (!h.rcv && c.rs < nsteps && !(stall_active && i == stall_idx)) {
        fds.push_back({c.port->prev->fd(), POLLIN, 0});
        owner.emplace_back(static_cast<int>(i), 1);
      }
    }
    if (heal_on && data_listener_.valid()) {
      fds.push_back({data_listener_.fd(), POLLIN, 0});
      owner.emplace_back(-1, 3);
    }
    // No-progress budget (the pre-heal "link:" abort): suspended while a
    // suspect's own deadline governs, restored the moment healing ends.
    int64_t budget_left = -1;
    if (timeout_ms > 0) {
      budget_left = timeout_ms - ms_since(last_progress);
      if (budget_left <= 0 && !heals_active) {
        *err = "link: no progress for " +
               std::to_string(timeout_ms / 1000) + "s (peer hung?)";
        ok = false;
        break;
      }
    }
    int64_t slice = timeout_ms > 0 ? std::max<int64_t>(budget_left, 1)
                                   : -1;
    if (heal_on) {
      // Bounded slices keep inbox pickup, re-dial backoff timers and
      // suspect deadlines responsive even when no fd fires.
      slice = slice < 0 ? 250 : std::min<int64_t>(slice, 250);
    }
    if (stall_active) {
      int64_t stall_left =
          std::chrono::duration_cast<std::chrono::milliseconds>(
              stall_until - now)
              .count() +
          1;
      slice = slice < 0 ? stall_left
                        : std::min<int64_t>(slice, stall_left);
    }
    if (fds.empty()) {
      // Everything pending is parked (suspect waits / stall): nap one
      // slice and re-evaluate — deadlines above bound the loop.
      std::this_thread::sleep_for(
          std::chrono::milliseconds(std::max<int64_t>(
              1, std::min<int64_t>(slice < 0 ? 50 : slice, 50))));
      continue;
    }
    int rc = ::poll(fds.data(), static_cast<nfds_t>(fds.size()),
                    static_cast<int>(slice));
    if (rc < 0) {
      if (errno == EINTR) continue;
      *err = std::string("poll: ") + strerror(errno);
      ok = false;
      break;
    }
    if (rc == 0) {
      if (!heal_on && !stall_active) {
        *err = "link: no progress for " +
               std::to_string(timeout_ms / 1000) + "s (peer hung?)";
        ok = false;
        break;
      }
      continue;  // deadline sweeps at the loop top decide what's next
    }
    // Drain loops: after one poll wakeup, move bytes until EAGAIN (or a
    // cursor runs out of eligible work) — poll syscalls are the
    // expensive part on sandboxed kernels, so each should amortize as
    // much IO as the buffers will take.
    for (size_t f = 0; ok && f < fds.size(); ++f) {
      const int kind = owner[f].second;
      if (kind == 3) {
        if ((fds[f].revents & POLLIN) == 0) continue;
        // Accept every ready connection: RESUME hellos for my channels
        // are serviced here; anyone else's are parked in the inbox.
        // Bounded per drain pass: a genuine RESUME arrives with its
        // hello bytes already in flight (the sender writes it right
        // after connect), so a connection with nothing readable within
        // a fraction of a slice is a silent stray (health probe,
        // scanner) — drop it instead of parking the cascade, the
        // PollJoinCandidate discipline applied to the data listener.
        // Worst-case synchronous stall: 2 × 50 ms per pass, only while
        // someone is actively dialing the data port.
        for (int accepts = 0; accepts < 2; ++accepts) {
          Socket conn = TryAcceptNow(data_listener_);
          if (!conn.valid()) break;
          if (!WaitReadable(conn, 50)) continue;  // silent stray: drop
          // Peek-validate before committing to a read: a genuine RESUME
          // arrives as one 48-byte write right behind the connect, so
          // anything shorter after the readability wait is a stray (a
          // prober that sent a byte) or a torn hello (the sender will
          // re-dial) — drop it rather than park the drain loop in a
          // blocking read on an untrusted connection.
          LinkResume lr;
          ssize_t pk = ::recv(conn.fd(), &lr, sizeof(lr),
                              MSG_PEEK | MSG_DONTWAIT);
          if (pk != static_cast<ssize_t>(sizeof(lr)) ||
              !ValidLinkResume(lr)) {
            continue;
          }
          conn.SetTimeouts(1);
          if (!conn.RecvAll(&lr, sizeof(lr))) {  // consume; cannot block
            continue;
          }
          if (lr.epoch != epoch_.load()) continue;  // dead incarnation
          bool mine = false;
          for (size_t i = 0; i < st.size(); ++i) {
            if (st[i].segs->ch == lr.channel &&
                spec.ring_id == lr.ring && spec.prev_peer == lr.origin) {
              ok = handle_resume(i, lr, std::move(conn));
              mine = true;
              break;
            }
          }
          if (!mine && conn.valid()) {
            HealInboxPut(static_cast<int32_t>(lr.ring),
                         static_cast<int32_t>(lr.channel), lr,
                         std::move(conn));
          }
          if (!ok) break;
        }
        continue;
      }
      ChState& c = st[owner[f].first];
      Heal& h = heal[owner[f].first];
      // A swap earlier in THIS drain pass (listener-serviced resume)
      // invalidates poll entries that captured the replaced fd — touching
      // them would recv/send on a closed (or reused) descriptor.
      if ((kind == 0 || kind == 4) && fds[f].fd != c.port->next->fd()) {
        continue;
      }
      if (kind == 1 && fds[f].fd != c.port->prev->fd()) continue;
      if (kind == 2 &&
          (!h.pending.valid() || fds[f].fd != h.pending.fd())) {
        continue;
      }
      if (kind == 2 && h.pending_connecting) {
        if ((fds[f].revents & (POLLOUT | POLLERR | POLLHUP)) == 0) {
          continue;
        }
        std::string cerr;
        if (!ConnectFinish(h.pending, &cerr)) {
          redial_backoff(h);
          continue;
        }
        send_hello(owner[f].first, std::move(h.pending));
        continue;  // the ACK arrives through a later POLLIN
      }
      if (kind == 2) {
        if ((fds[f].revents & (POLLIN | POLLERR | POLLHUP)) == 0) {
          continue;
        }
        LinkResumeAck ack;
        bool got = h.pending.RecvAll(&ack, sizeof(ack)) &&
                   ValidLinkResumeAck(ack);
        if (got && ack.ok == 1 && ack.seq == ch_seq[owner[f].first] &&
            ack.step >= 0 && ack.step <= nsteps &&
            (ack.step == nsteps ||
             static_cast<size_t>(ack.offset) <=
                 seg_bytes(c, send_seg[ack.step]))) {
          // REWIND to the receiver's authoritative cursor: everything at
          // or past it is still intact in `base` (credit-chain
          // guarantee), so the resent bytes are identical.
          c.ss = static_cast<int>(ack.step);
          c.so = ack.step == nsteps ? 0
                                    : static_cast<size_t>(ack.offset);
          advance_sender(c);
          auto healed_at = std::chrono::steady_clock::now();
          arm_healed(c.port->next, std::move(h.pending));
          link_reconnects_.fetch_add(1);
          RecordLinkHealNs(
              std::chrono::duration_cast<std::chrono::nanoseconds>(
                  healed_at - h.snd_t0)
                  .count());
          h.snd = false;
          heal_span_close(owner[f].first);
          GlobalFlightRecorder().Record(
              "link", control_cycle_seq_,
              "healed snd ch=%d seq=%lld rewind step=%lld off=%lld",
              c.segs->ch,
              static_cast<long long>(ch_seq[owner[f].first]),
              static_cast<long long>(ack.step),
              static_cast<long long>(ack.offset));
          last_progress = healed_at;
        } else if (got && (ack.ok == 0 ||
                           ack.seq != ch_seq[owner[f].first])) {
          if (ack.seq < ch_seq[owner[f].first]) {
            // The receiver is still on an OLDER cascade of this channel
            // (e.g. draining the broken socket's buffered tail of the
            // previous collective — a FIN'd socket keeps delivering
            // buffered bytes).  It will catch up to our stream; back
            // off and re-dial instead of aborting a healable blip.
            redial_backoff(h);
          } else {
            // The receiver's stream moved PAST ours: the bytes it
            // still owed us are unrecoverable — escalate with the
            // original attribution.
            h.pending.Close();
            escalate(owner[f].first, /*snd_dir=*/true);
          }
        } else {
          // Dead or garbled ACK conn: back off and re-dial.
          h.pending.Close();
          h.snd_next = std::chrono::steady_clock::now() +
                       std::chrono::milliseconds(jittered(std::min(
                           1000, 50 << std::min(h.snd_attempts, 5))));
        }
        continue;
      }
      if (kind == 4) {
        if ((fds[f].revents &
             (POLLIN | POLLRDHUP | POLLERR | POLLHUP)) == 0) {
          continue;
        }
        // The send socket should never become readable: EOF/error means
        // the edge died while this sender had nothing eligible to send.
        char probe;
        ssize_t k = ::recv(c.port->next->fd(), &probe, 1, 0);
        if (k == 0 ||
            (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
             errno != EINTR)) {
          ok = suspect_snd(
              owner[f].first,
              std::string("send to peer: ") +
                  (k == 0 ? "connection closed (peer process exited?)"
                          : strerror(errno)));
        }
        continue;
      }
      if (kind == 0) {
        if ((fds[f].revents & (POLLOUT | POLLERR | POLLHUP)) == 0) {
          continue;
        }
        while (c.ss < nsteps && c.so < c.ready[c.ss]) {
          const uint8_t* p =
              base + c.segs->seg_off[send_seg[c.ss]] * esize + c.so;
          ssize_t k = ::send(c.port->next->fd(), p,
                             c.ready[c.ss] - c.so, MSG_NOSIGNAL);
          if (k > 0) {
            c.so += static_cast<size_t>(k);
            c.tx += static_cast<size_t>(k);
            last_progress = std::chrono::steady_clock::now();
            advance_sender(c);
          } else if (k < 0 && (errno == EAGAIN || errno == EWOULDBLOCK ||
                               errno == EINTR)) {
            break;
          } else {
            ok = suspect_snd(owner[f].first,
                             std::string("send to peer: ") +
                                 strerror(errno));
            break;
          }
        }
      } else {
        if ((fds[f].revents & (POLLIN | POLLERR | POLLHUP)) == 0) continue;
        while (c.rs < nsteps) {
          const bool reducing = c.rs <= last_rs;
          const size_t want = seg_bytes(c, recv_seg[c.rs]) - c.ro;
          uint8_t* dst =
              reducing ? c.tmp.get() + c.ro
                       : base + c.segs->seg_off[recv_seg[c.rs]] * esize +
                             c.ro;
          ssize_t k = ::recv(c.port->prev->fd(), dst, want, 0);
          if (k > 0) {
            c.ro += static_cast<size_t>(k);
            c.rx += static_cast<size_t>(k);
            last_progress = std::chrono::steady_clock::now();
            credit_recv(c, static_cast<size_t>(k));
          } else if (k == 0) {
            ok = suspect_rcv(
                owner[f].first,
                "recv from peer: connection closed (peer process "
                "exited?)");
            break;
          } else if (errno == EAGAIN || errno == EWOULDBLOCK ||
                     errno == EINTR) {
            break;
          } else {
            ok = suspect_rcv(owner[f].first,
                             std::string("recv from peer: ") +
                                 strerror(errno));
            break;
          }
        }
      }
    }
  }
  // Close any mid-flight re-dial and restore blocking mode on the ring
  // sockets (frame-based ops — broadcast relays, allgather steps — rely
  // on blocking reads).  A failed cascade's sockets may already be dead;
  // restoring flags on them is harmless.
  for (auto& h : heal) h.pending.Close();
  for (auto& c : st) {
    if (c.port->next->valid()) set_block(c.port->next->fd());
    if (c.port->prev->valid()) set_block(c.port->prev->fd());
  }
  }  // transport branch
  wire_ns_.fetch_add(std::chrono::duration_cast<std::chrono::nanoseconds>(
                         std::chrono::steady_clock::now() - t0)
                         .count() -
                     local_reduce_ns);
  for (auto& c : st) {
    CountPortBytes(*c.port, static_cast<int64_t>(c.tx),
                   static_cast<int64_t>(c.rx), spec.compressed);
  }
  return ok;
}

// Minimum payload per extra channel: below this, sharding just multiplies
// per-message overhead (syscalls, poll wakeups) without any wire to hide,
// so the fan-out degrades gracefully toward 1 for small collectives.
static constexpr int64_t kMinBytesPerChannel = 256 * 1024;

bool Engine::ChanneledRingAllreduce(uint8_t* base, int64_t count,
                                    DataType dtype, ReduceOp op,
                                    const RingSpec& spec,
                                    const ExecCtx& ctx,
                                    const std::string& tname,
                                    std::string* err, bool rs_only) {
  // Under a wire codec, `count` is the number of quantized BLOCKS and
  // the element size is the block size — segment and channel-shard
  // arithmetic runs unchanged over uniform block elements.
  const size_t esize =
      spec.codec ? spec.codec->block_bytes : DataTypeSize(dtype);
  std::vector<int64_t> seg_count, seg_off;
  EvenSegments(count, spec.rsize, &seg_count, &seg_off);
  // Effective fan-out, deterministic across ranks (count, esize, and the
  // committed channel count all agree).  Any value is VALUE-safe: channel
  // shards slice WITHIN each ring segment, so an element's segment id —
  // hence the rank order its reduction applies in — never depends on the
  // fan-out, and results are bit-identical for channels = 1..N.
  int nch = std::max(1, ctx.nchannels);
  const int64_t bytes = count * static_cast<int64_t>(esize);
  while (nch > 1 && bytes / nch < kMinBytesPerChannel) --nch;
  // Per-channel slices of every segment: channel c owns
  // seg_count[s]/nch (+1 for the first seg_count[s]%nch channels)
  // elements at a contiguous offset inside segment s.
  auto channel_segs = [&](int c, std::vector<int64_t>* cnt,
                          std::vector<int64_t>* off) {
    cnt->resize(spec.rsize);
    off->resize(spec.rsize);
    for (int s = 0; s < spec.rsize; ++s) {
      int64_t n = seg_count[s], q = n / nch, r = n % nch;
      (*cnt)[s] = q + (c < r ? 1 : 0);
      (*off)[s] = seg_off[s] + q * c + std::min<int64_t>(c, r);
    }
  };
  if (nch == 1 && ctx.nchannels == 1 && num_channels_ == 1) {
    // HOROVOD_NUM_CHANNELS=1 restores the pre-channel discipline exactly:
    // the stepped reduce-scatter phase (with its within-step chunked
    // recv/reduce overlap) followed by the stepped allgather, one port
    // pair, per-step barriers.  The streaming cascade below is the
    // multi-channel data plane.
    const int ch = ctx.channel;
    timeline_.ActivityStartCh(tname, spec.span + std::to_string(ch), ch + 1);
    bool ok = RingReduceScatterPhaseCh(base, seg_count, seg_off, dtype, op,
                                       spec, ch, err);
    if (ok && !rs_only) {
      ok = RingAllgatherPhaseCh(base, seg_count, seg_off, esize, spec, ch,
                                err);
    }
    timeline_.ActivityEndCh(tname, ch + 1);
    return ok;
  }
  std::vector<ChannelSegs> all(nch);
  for (int c = 0; c < nch; ++c) {
    all[c].ch = ctx.channel + c;
    channel_segs(c, &all[c].seg_count, &all[c].seg_off);
  }
  // Driver threads: channels are cheap (a socket pair + cursor state) but
  // threads are not — one driver can multiplex several channels' cascades
  // in its poll loop, so the thread count follows the CORE budget
  // (HOROVOD_CHANNEL_DRIVERS), not the channel count.  A 2-core box runs
  // 4 channels on 1 driver; a 16-core host splits them across 4.
  const int drivers =
      std::max(1, std::min({nch, channel_drivers_, pool_.size() + 1}));
  auto run_part = [&](const std::vector<ChannelSegs>& part,
                      std::string* derr) -> bool {
    for (const auto& cs : part) {
      timeline_.ActivityStartCh(tname, spec.span + std::to_string(cs.ch),
                                cs.ch + 1);
    }
    bool ok = StreamingRingChannels(base, part, dtype, op, spec, tname,
                                    derr, rs_only);
    for (const auto& cs : part) timeline_.ActivityEndCh(tname, cs.ch + 1);
    return ok;
  };
  if (drivers <= 1) {
    return run_part(all, err);
  }
  std::vector<std::vector<ChannelSegs>> parts(drivers);
  for (int c = 0; c < nch; ++c) {
    parts[c % drivers].push_back(std::move(all[c]));
  }
  std::vector<std::string> derrs(drivers);
  std::vector<uint8_t> dok(drivers, 0);
  TaskLatch latch(drivers - 1);
  for (int d = 1; d < drivers; ++d) {
    pool_.Submit([&, d] {
      dok[d] = run_part(parts[d], &derrs[d]) ? 1 : 0;
      latch.Done();
    });
  }
  dok[0] = run_part(parts[0], &derrs[0]) ? 1 : 0;
  latch.Wait();
  for (int d = 0; d < drivers; ++d) {
    if (!dok[d]) {
      // First failed driver wins the attribution; a peer death EOFs
      // every channel to that neighbor, so the messages agree.
      *err = derrs[d];
      return false;
    }
  }
  return true;
}

bool Engine::UseSmallAlgo(int64_t nbytes, const ExecCtx& ctx) const {
  if (!shm_ring_active_ || shm_star_.empty() || group_size_ <= 1) {
    return false;
  }
  const int64_t thr = algo_threshold_.load();
  if (thr <= 0 || nbytes > thr) return false;
  // Serial execution context only: a concurrent wave slice owns ONE
  // channel, not the star edges (two responses folding on the same star
  // ring would interleave their streams).  The serial path always passes
  // the full committed fan-out, so for a given response list this
  // predicate evaluates identically on every member of the group — the
  // wire patterns cannot split.
  return ctx.nchannels >= num_channels_;
}

bool Engine::StarBroadcast(uint8_t* base, size_t nbytes, std::string* err) {
  const int to_ms = socket_timeout_sec_ * 1000;
  const int L = group_size_;
  // Chunk round-robin ACROSS members (chunk sized to half the ring so a
  // write never has to wait for a full drain): members consume
  // concurrently, so the leader's wall time is ~one buffer, not
  // (L-1) sequential full sends.
  const size_t chunk =
      std::min(kRelayChunk, static_cast<size_t>(shm_ring_bytes_ / 2));
  if (local_index_ == 0) {
    for (size_t off = 0; off < nbytes; off += chunk) {
      const size_t n = std::min(chunk, nbytes - off);
      for (int m = 1; m < L; ++m) {
        std::string detail;
        if (!shm_star_[m].tx.WriteAll(base + off, n, to_ms, &detail)) {
          *err = "rank " + std::to_string(group_members_[m]) +
                 " failed during star broadcast: send to member: " + detail;
          return false;
        }
        CountShmBytes(static_cast<int64_t>(n), 0);
      }
    }
  } else {
    // The first chunk's legitimate wait covers the leader's whole
    // cross-host ring (2(H-1) steps), hence the nnodes-scaled budget.
    const int wait_ms =
        to_ms > 0 ? to_ms * (2 * nnodes_ + group_size_ + 2) : 0;
    for (size_t off = 0; off < nbytes; off += chunk) {
      const size_t n = std::min(chunk, nbytes - off);
      std::string detail;
      if (!shm_star_[0].rx.ReadAll(base + off, n, wait_ms, &detail)) {
        *err = "rank " + std::to_string(group_members_[0]) +
               " failed during star broadcast: recv from leader: " + detail;
        return false;
      }
      CountShmBytes(0, static_cast<int64_t>(n));
    }
  }
  return true;
}

bool Engine::StarFoldAllreduce(uint8_t* base, int64_t count, DataType dtype,
                               ReduceOp op, bool broadcast_result,
                               std::string* err) {
  const size_t esize = DataTypeSize(dtype);
  const size_t nbytes = static_cast<size_t>(count) * esize;
  const int L = group_size_;
  const int to_ms = socket_timeout_sec_ * 1000;
  const int gather_ms = to_ms > 0 ? to_ms * (L + 2) : 0;
  if (local_index_ != 0) {
    std::string detail;
    if (!shm_star_[0].tx.WriteAll(base, nbytes, gather_ms, &detail)) {
      *err = "rank " + std::to_string(group_members_[0]) +
             " failed during star gather: send to leader: " + detail;
      return false;
    }
    CountShmBytes(static_cast<int64_t>(nbytes), 0);
    if (broadcast_result) return StarBroadcast(base, nbytes, err);
    return true;
  }
  // Leader: gather every member's RAW buffer, then reproduce the ring
  // reduce-scatter's fold segment by segment.  Segment s accumulates
  // contributions in group-position order s+1, s+2, ..., s+L (mod L) —
  // the order the ring's step schedule applies them in under the
  // vrank = position - 1 convention (see TcpRingSpec/EvenSegments) —
  // AND with the ring's exact operand roles (dst = the incoming
  // position's raw data, src = the running accumulator), because
  // ReduceInto's min/max tie-breaking and NaN propagation are operand-
  // ORDER-sensitive even where the math is commutative.  Identical
  // kernel, identical segment boundaries, identical operand sequence ⇒
  // the algo switch can never change a bit.
  std::vector<std::unique_ptr<uint8_t[]>> contrib(L);
  contrib[0].reset(new uint8_t[nbytes]);
  memcpy(contrib[0].get(), base, nbytes);
  for (int m = 1; m < L; ++m) {
    contrib[m].reset(new uint8_t[nbytes]);
    std::string detail;
    if (!shm_star_[m].rx.ReadAll(contrib[m].get(), nbytes, gather_ms,
                                 &detail)) {
      *err = "rank " + std::to_string(group_members_[m]) +
             " failed during star gather: recv from member: " + detail;
      return false;
    }
    CountShmBytes(0, static_cast<int64_t>(nbytes));
  }
  std::vector<int64_t> seg_count, seg_off;
  EvenSegments(count, L, &seg_count, &seg_off);
  int64_t max_seg = 0;
  for (auto c : seg_count) max_seg = std::max(max_seg, c);
  std::unique_ptr<uint8_t[]> acc(new uint8_t[max_seg * esize]);
  std::unique_ptr<uint8_t[]> nxt(new uint8_t[max_seg * esize]);
  for (int s = 0; s < L; ++s) {
    if (seg_count[s] == 0) continue;
    const size_t sb = static_cast<size_t>(seg_count[s]) * esize;
    const size_t boff = static_cast<size_t>(seg_off[s]) * esize;
    memcpy(acc.get(), contrib[(s + 1) % L].get() + boff, sb);
    for (int k = 1; k < L; ++k) {
      memcpy(nxt.get(), contrib[(s + 1 + k) % L].get() + boff, sb);
      ReduceIntoTimed(nxt.get(), acc.get(), seg_count[s], dtype, op);
      acc.swap(nxt);
    }
    memcpy(base + boff, acc.get(), sb);
  }
  if (broadcast_result) return StarBroadcast(base, nbytes, err);
  return true;
}

// Two-level allreduce over the committed topology: intra-host ring
// reduce-scatter over shm (or the star fold under the small-tensor algo) →
// owned-segment gather to the group leader → leaders' channel-sharded TCP
// ring across hosts → star broadcast back down.  The reference
// decomposition (NCCL reduce → cross-node MPI → NCCL broadcast,
// operations.cc:1025-1187), generalized from the eager
// HOROVOD_HIERARCHICAL_ALLREDUCE into the native engine.  Deterministic
// per topology; transport, channel count, and the algo threshold never
// change bits within one topology.
bool Engine::TwoLevelIntraReduce(uint8_t* base, int64_t count,
                                 DataType dtype, ReduceOp op,
                                 const std::string& name, const ExecCtx& ctx,
                                 bool compressed_payload, std::string* err) {
  const size_t esize = DataTypeSize(dtype);
  const size_t nbytes = static_cast<size_t>(count) * esize;
  const int L = group_size_;
  const int p = local_index_;
  const int to_ms = socket_timeout_sec_ * 1000;
  const int gather_ms = to_ms > 0 ? to_ms * (L + 2) : 0;
  std::string detail;
  if (L <= 1) return true;
  if (UseSmallAlgo(static_cast<int64_t>(nbytes), ctx)) {
    // Small path: 2 shm hops of latency instead of 2(L-1) ring steps;
    // leaves the leader holding the host-reduced buffer.
    return StarFoldAllreduce(base, count, dtype, op,
                             /*broadcast_result=*/false, err);
  }
  std::vector<int64_t> seg_count, seg_off;
  EvenSegments(count, L, &seg_count, &seg_off);
  RingSpec shm = ShmRingSpec();
  shm.compressed = compressed_payload;
  timeline_.ActivityStartCh(name, "SHM_CH0", 1);
  bool ok = RingReduceScatterPhaseCh(base, seg_count, seg_off, dtype,
                                     op, shm, 0, &detail);
  timeline_.ActivityEndCh(name, 1);
  if (!ok) {
    *err = TransportError("two-level allreduce (intra ring)", name,
                          detail, group_members_[(p + 1) % L],
                          group_members_[(p - 1 + L) % L]);
    return false;
  }
  // Gather the host-reduced segments onto the leader: position q owns
  // segment q after the reduce-scatter (the vrank = position - 1
  // convention, see EvenSegments), so the leader's buffer becomes the
  // full host sum (its own segment 0 is already in place).
  if (p == 0) {
    for (int q = 1; q < L; ++q) {
      const int s = q;
      if (seg_count[s] == 0) continue;
      const size_t n = static_cast<size_t>(seg_count[s]) * esize;
      if (!shm_star_[q].rx.ReadAll(base + seg_off[s] * esize, n,
                                   gather_ms, &detail)) {
        *err = "rank " + std::to_string(group_members_[q]) +
               " failed during two-level allreduce of '" + name +
               "' (segment gather): " + detail;
        return false;
      }
      CountShmBytes(0, static_cast<int64_t>(n));
    }
  } else {
    const int s = p;
    if (seg_count[s] > 0) {
      const size_t n = static_cast<size_t>(seg_count[s]) * esize;
      if (!shm_star_[0].tx.WriteAll(base + seg_off[s] * esize, n,
                                    gather_ms, &detail)) {
        *err = "rank " + std::to_string(group_members_[0]) +
               " failed during two-level allreduce of '" + name +
               "' (segment gather): " + detail;
        return false;
      }
      CountShmBytes(static_cast<int64_t>(n), 0);
    }
  }
  return true;
}

bool Engine::TwoLevelAllreduce(uint8_t* base, int64_t count, DataType dtype,
                               ReduceOp op, const std::string& name,
                               const ExecCtx& ctx, WireDtype wire,
                               bool compressed_payload, std::string* err) {
  const size_t esize = DataTypeSize(dtype);
  const size_t nbytes = static_cast<size_t>(count) * esize;
  const int L = group_size_;
  const int p = local_index_;
  std::string detail;
  if (L > 1) {
    if (!TwoLevelIntraReduce(base, count, dtype, op, name, ctx,
                             compressed_payload, err)) {
      return false;
    }
  }
  if (p == 0 && nnodes_ > 1) {
    RingSpec cross = CrossRingSpec();
    // Quantized wire compresses exactly the hop that crosses a real
    // network: the leaders' cross-host ring.  The intra-host shm phases
    // above stay at the buffer's dtype (intra-host bandwidth is cheap;
    // skipping their requantization also halves the accumulated error).
    bool ok;
    if ((wire == WireDtype::INT8 || wire == WireDtype::FP8) &&
        dtype == DataType::FLOAT32) {
      ok = CompressedRingAllreduce(base, count, wire, op, cross, ctx, name,
                                   &detail);
    } else {
      cross.compressed = compressed_payload;
      ok = ChanneledRingAllreduce(base, count, dtype, op, cross, ctx, name,
                                  &detail);
    }
    if (!ok) {
      *err = TransportError(
          "two-level allreduce (cross ring)", name, detail,
          group_leaders_[(node_id_ + 1) % nnodes_],
          group_leaders_[(node_id_ - 1 + nnodes_) % nnodes_]);
      return false;
    }
  }
  if (L > 1) {
    if (!StarBroadcast(base, nbytes, err)) return false;
  }
  return true;
}

bool Engine::StarScatterShards(uint8_t* base,
                               const std::vector<int64_t>& shard_count,
                               const std::vector<int64_t>& shard_off,
                               size_t esize, std::string* err) {
  const int to_ms = socket_timeout_sec_ * 1000;
  const int L = group_size_;
  if (L <= 1) return true;
  if (local_index_ == 0) {
    for (int m = 1; m < L; ++m) {
      if (shard_count[m] <= 0) continue;
      const size_t n = static_cast<size_t>(shard_count[m]) * esize;
      std::string detail;
      if (!shm_star_[m].tx.WriteAll(base + shard_off[m] * esize, n,
                                    to_ms > 0 ? to_ms * (L + 2) : 0,
                                    &detail)) {
        *err = "rank " + std::to_string(group_members_[m]) +
               " failed during star shard scatter: send to member: " +
               detail;
        return false;
      }
      CountShmBytes(static_cast<int64_t>(n), 0);
    }
  } else {
    // The legitimate wait covers the leader's whole cross-host ring,
    // like StarBroadcast's first chunk.
    const int wait_ms =
        to_ms > 0 ? to_ms * (2 * nnodes_ + group_size_ + 2) : 0;
    if (shard_count[local_index_] > 0) {
      const size_t n =
          static_cast<size_t>(shard_count[local_index_]) * esize;
      std::string detail;
      if (!shm_star_[0].rx.ReadAll(base + shard_off[local_index_] * esize,
                                   n, wait_ms, &detail)) {
        *err = "rank " + std::to_string(group_members_[0]) +
               " failed during star shard scatter: recv from leader: " +
               detail;
        return false;
      }
      CountShmBytes(0, static_cast<int64_t>(n));
    }
  }
  return true;
}

bool Engine::TwoLevelReduceScatter(uint8_t* base, int64_t count,
                                   DataType dtype, ReduceOp op,
                                   const std::vector<int64_t>& shard_count,
                                   const std::vector<int64_t>& shard_off,
                                   const std::string& name,
                                   const ExecCtx& ctx,
                                   bool compressed_payload,
                                   std::string* err) {
  // Preconditions (checked by ExecReducescatter): count % size == 0,
  // node-major contiguous host grouping, equal group sizes — together
  // they make the committed per-rank shards subdivide the cross ring's
  // EvenSegments(count, H) exactly, so every hop below slices along the
  // fold's own geometry and the bits equal the two-level allreduce's.
  const size_t esize = DataTypeSize(dtype);
  if (group_size_ > 1) {
    if (!TwoLevelIntraReduce(base, count, dtype, op, name, ctx,
                             compressed_payload, err)) {
      return false;
    }
  }
  if (local_index_ == 0 && nnodes_ > 1) {
    RingSpec cross = CrossRingSpec();
    cross.compressed = compressed_payload;
    // Engine-wide vrank convention: this leader ends the RS half owning
    // cross segment node_id — its own hosts' shard block.
    std::string detail;
    if (!ChanneledRingAllreduce(base, count, dtype, op, cross, ctx, name,
                                &detail, /*rs_only=*/true)) {
      *err = TransportError(
          "two-level reducescatter (cross ring)", name, detail,
          group_leaders_[(node_id_ + 1) % nnodes_],
          group_leaders_[(node_id_ - 1 + nnodes_) % nnodes_]);
      return false;
    }
  }
  if (group_size_ > 1) {
    // Leader → members: each member gets exactly its own global shard
    // (indexed by group position).
    std::vector<int64_t> mcount(group_size_), moff(group_size_);
    for (int m = 0; m < group_size_; ++m) {
      const int r = group_members_[m];
      mcount[m] = shard_count[r];
      moff[m] = shard_off[r];
    }
    if (!StarScatterShards(base, mcount, moff, esize, err)) return false;
  }
  return true;
}

bool Engine::RunAllreduceCascade(uint8_t* exec_buf, int64_t total,
                                 DataType exec_dtype, ReduceOp op,
                                 WireDtype wire, bool quantized,
                                 bool half_wire, bool small,
                                 const char* op_label,
                                 const std::string& tname,
                                 const ExecCtx& ctx, std::string* msg) {
  if (two_level_) {
    return TwoLevelAllreduce(exec_buf, total, exec_dtype, op, tname, ctx,
                             quantized ? wire : WireDtype::FP32,
                             half_wire, msg);
  }
  if (small) {
    // Whole-world host group: the star fold IS the collective —
    // 2 shm hops instead of 2(N-1) ring steps, bit-equal by the fold-
    // order emulation.
    return StarFoldAllreduce(exec_buf, total, exec_dtype, op,
                             /*broadcast_result=*/true, msg);
  }
  std::string err;
  RingSpec spec = FlatRingSpec();
  bool ok;
  if (quantized) {
    ok = CompressedRingAllreduce(exec_buf, total, wire, op, spec, ctx,
                                 tname, &err);
  } else {
    spec.compressed = half_wire;
    ok = ChanneledRingAllreduce(exec_buf, total, exec_dtype, op, spec,
                                ctx, tname, &err);
  }
  if (!ok) {
    *msg = TransportError(op_label, tname, err, (rank_ + 1) % size_,
                          (rank_ - 1 + size_) % size_);
  }
  return ok;
}

void Engine::ExecAllreduce(const Response& response,
                           std::vector<TensorTableEntry>& entries,
                           const ExecCtx& ctx) {
  // Ghost execution (backup workers): a rank OUTSIDE a partial commit's
  // participant set holds no entries but still drives the identical
  // full-world ring over a zeroed buffer — zero is the SUM identity, so
  // participants' results are exactly the survivors' sum while the wire
  // pattern (and therefore every rank's socket schedule) is unchanged.
  const bool ghost = entries.empty();
  const std::string tname =
      ghost ? response.tensor_names[0] : entries[0].name;
  for (auto& e : entries) timeline_.Start(e.name);
  DataType dtype = ghost ? static_cast<DataType>(response.partial_dtype)
                         : entries[0].dtype;
  int64_t total = response.partial_elems;
  if (!ghost) {
    total = 0;
    for (auto& e : entries) total += e.shape.num_elements();
  }
  // Divisor-correct averaging: the frontends divide by the COMMITTED
  // participant count, not blindly by size.
  const int nparticipants = response.participants.empty()
      ? size_ : static_cast<int>(response.participants.size());

  if (size_ > 1) {
    const size_t esize = DataTypeSize(dtype);
    std::vector<uint8_t> ghost_buf;
    void* buf;
    if (ghost) {
      ghost_buf.assign(static_cast<size_t>(total) * esize, 0);
      buf = ghost_buf.data();
    } else {
      buf = entries[0].data;
    }
    // Per-slot fusion scratch: ctx.channel doubles as the scratch slot so
    // concurrent wave responses never share a buffer.
    std::vector<uint8_t>& fusion_buffer = fusion_buffers_[ctx.channel];
    if (entries.size() > 1) {
      timeline_.ActivityStart(tname, "MEMCPY_IN_FUSION_BUFFER");
      if (fusion_buffer.size() < static_cast<size_t>(total) * esize) {
        fusion_buffer.resize(static_cast<size_t>(total) * esize);
      }
      int64_t off = 0;
      for (auto& e : entries) {
        size_t n = static_cast<size_t>(e.shape.num_elements()) * esize;
        memcpy(fusion_buffer.data() + off, e.data, n);
        off += n;
      }
      buf = fusion_buffer.data();
      timeline_.ActivityEnd(tname);
    }
    bool ok;
    std::string msg;
    auto t0 = std::chrono::steady_clock::now();
    // Committed wire format for this response (negotiated + validated;
    // FP32 unless every rank requested otherwise for an fp32 allreduce).
    WireDtype wire = dtype == DataType::FLOAT32 ? response.wire_dtype
                                                : WireDtype::FP32;
    const bool quantized =
        wire == WireDtype::INT8 || wire == WireDtype::FP8;
    const bool half_wire =
        wire == WireDtype::FP16 || wire == WireDtype::BF16;
    // fp16/bf16 wire: RNE-convert the whole payload to a half staging
    // buffer ONCE, run the ordinary collective at the half dtype (flat
    // ring, star fold, or the full two-level hierarchy — every transport
    // and path works unchanged), convert back at the end.  Wire traffic,
    // fusion staging and reduction all halve.
    std::vector<uint16_t> halfbuf;
    uint8_t* exec_buf = static_cast<uint8_t*>(buf);
    DataType exec_dtype = dtype;
    if (half_wire) {
      halfbuf.resize(static_cast<size_t>(total));
      const float* fp = static_cast<const float*>(buf);
      auto q0 = std::chrono::steady_clock::now();
      if (wire == WireDtype::FP16) {
        for (int64_t i = 0; i < total; ++i) halfbuf[i] = FloatToHalf(fp[i]);
      } else {
        for (int64_t i = 0; i < total; ++i) halfbuf[i] = FloatToBF16(fp[i]);
      }
      quantize_ns_.fetch_add(
          std::chrono::duration_cast<std::chrono::nanoseconds>(
              std::chrono::steady_clock::now() - q0)
              .count());
      wire_bytes_saved_.fetch_add(total * 2);  // 4 -> 2 bytes per element
      exec_buf = reinterpret_cast<uint8_t*>(halfbuf.data());
      exec_dtype = wire == WireDtype::FP16 ? DataType::FLOAT16
                                           : DataType::BFLOAT16;
    }
    switch (wire) {
      case WireDtype::FP16: wire_fp16_count_.fetch_add(1); break;
      case WireDtype::BF16: wire_bf16_count_.fetch_add(1); break;
      case WireDtype::INT8: wire_int8_count_.fetch_add(1); break;
      case WireDtype::FP8: wire_fp8_count_.fetch_add(1); break;
      case WireDtype::FP32: break;
    }
    if (wire != WireDtype::FP32) {
      // Per-response WIRE<dtype> marker: compressed responses are
      // visible in traces next to their ALGO marker.
      char wm[16];
      std::snprintf(wm, sizeof(wm), "WIRE_%s", WireDtypeName(wire));
      for (char* c = wm; *c; ++c) *c = static_cast<char>(toupper(*c));
      timeline_.Algo(tname, wm);
    }
    const int64_t exec_bytes =
        total * static_cast<int64_t>(DataTypeSize(exec_dtype));
    // Quantized responses skip the star fold: its gather/fold path has
    // no block semantics, and sub-threshold payloads gain nothing from
    // compression anyway.  Deterministic across ranks — the wire format
    // is committed per response.
    const bool small = UseSmallAlgo(exec_bytes, ctx) && !quantized;
    // One ALGO marker per response: which path this allreduce took (the
    // two-level intra phase applies the same size-based selection).
    timeline_.Algo(tname, small ? "ALGO_SMALL" : "ALGO_RING");
    (small ? algo_small_count_ : algo_ring_count_).fetch_add(1);
    timeline_.ActivityStart(tname, two_level_ ? "TWO_LEVEL_ALLREDUCE"
                                   : small   ? "STAR_ALLREDUCE"
                                             : "RING_ALLREDUCE");
    ok = RunAllreduceCascade(exec_buf, total, exec_dtype,
                             response.red_op, wire, quantized, half_wire,
                             small, "allreduce", tname, ctx, &msg);
    if (ok && half_wire) {
      float* fp = static_cast<float*>(buf);
      auto q0 = std::chrono::steady_clock::now();
      if (wire == WireDtype::FP16) {
        for (int64_t i = 0; i < total; ++i) fp[i] = HalfToFloat(halfbuf[i]);
      } else {
        for (int64_t i = 0; i < total; ++i) fp[i] = BF16ToFloat(halfbuf[i]);
      }
      quantize_ns_.fetch_add(
          std::chrono::duration_cast<std::chrono::nanoseconds>(
              std::chrono::steady_clock::now() - q0)
              .count());
    }
    int64_t wall = std::chrono::duration_cast<std::chrono::nanoseconds>(
                       std::chrono::steady_clock::now() - t0)
                       .count();
    if (ctx.wave_allreduce_wall_ns != nullptr) {
      *ctx.wave_allreduce_wall_ns = wall;  // wave accounts the max once
    } else {
      allreduce_ns_.fetch_add(wall);
    }
    allreduce_bytes_.fetch_add(total * static_cast<int64_t>(esize));
    timeline_.ActivityEnd(tname);
    if (!ok) {
      for (auto& e : entries) FinishEntry(e, Status::Aborted(msg));
      return;
    }
    if (entries.size() > 1) {
      timeline_.ActivityStart(tname, "MEMCPY_OUT_FUSION_BUFFER");
      int64_t off = 0;
      for (auto& e : entries) {
        size_t n = static_cast<size_t>(e.shape.num_elements()) * esize;
        memcpy(e.data, fusion_buffer.data() + off, n);
        off += n;
      }
      timeline_.ActivityEnd(tname);
      // High-water cap: a one-off oversized batch (> the fusion
      // threshold) must not pin its allocation for the process lifetime.
      if (static_cast<int64_t>(fusion_buffer.capacity()) >
          fusion_threshold_.load()) {
        std::vector<uint8_t>().swap(fusion_buffer);
      }
    }
  }
  for (auto& e : entries) {
    timeline_.End(e.name, e.dtype, e.shape.DebugString());
    FinishEntry(e, Status::OK(), nparticipants);
  }
}

void Engine::ExecAllgather(const Response& response,
                           std::vector<TensorTableEntry>& entries,
                           const ExecCtx& ctx) {
  // Allgather is never fused (matches the reference); one entry.
  TensorTableEntry& e = entries[0];
  timeline_.Start(e.name);
  const size_t esize = DataTypeSize(e.dtype);
  int64_t slice = 1;
  for (int d = 1; d < e.shape.ndim(); ++d) slice *= e.shape.dim(d);

  int64_t total_dim0 = 0;
  for (auto v : response.tensor_sizes) total_dim0 += v;

  auto hs = GetHandle(e.handle);
  if (hs == nullptr) return;
  hs->result.resize(static_cast<size_t>(total_dim0 * slice) * esize);
  hs->result_shape.clear();
  hs->result_shape.push_back(total_dim0);
  for (int d = 1; d < e.shape.ndim(); ++d) {
    hs->result_shape.push_back(e.shape.dim(d));
  }

  std::vector<int64_t> block_bytes(size_), block_off(size_);
  int64_t off = 0;
  for (int r = 0; r < size_; ++r) {
    block_bytes[r] = response.tensor_sizes[r] * slice *
                     static_cast<int64_t>(esize);
    block_off[r] = off;
    off += block_bytes[r];
  }
  memcpy(hs->result.data() + block_off[rank_], e.data,
         static_cast<size_t>(block_bytes[rank_]));

  if (size_ > 1) {
    // The sharded optimizer's parameter/update allgather gets its own
    // span so ZeRO steps are attributable in traces next to "RS", and
    // the FSDP plane's just-in-time parameter gathers get "FSDP_AG" so
    // prefetch overlap is visible against compute.
    timeline_.ActivityStart(e.name,
                            e.name.rfind("fsdp.", 0) == 0 ? "FSDP_AG"
                            : e.name.rfind("sharded.ag.", 0) == 0
                                ? "AG_PARAMS" : "RING_ALLGATHER");
    // Circulate blocks around the flat ring (shm on a whole-world host
    // group, TCP otherwise); after size-1 steps everyone has all.
    RingSpec spec = FlatRingSpec();
    const RingPort& port = spec.ports[ctx.channel];
    std::string err;
    bool failed = false;
    for (int step = 0; step < size_ - 1 && !failed; ++step) {
      int send_block = (rank_ - step + size_) % size_;
      int recv_block = (rank_ - step - 1 + size_) % size_;
      int64_t wns = 0;
      failed = !PortSendRecvChunked(
          port, hs->result.data() + block_off[send_block],
          static_cast<size_t>(block_bytes[send_block]),
          hs->result.data() + block_off[recv_block],
          static_cast<size_t>(block_bytes[recv_block]), /*chunk=*/0, nullptr,
          socket_timeout_sec_ * 1000, &err, &wns);
      wire_ns_.fetch_add(wns);
      if (!failed) {
        CountPortBytes(port, block_bytes[send_block],
                       block_bytes[recv_block]);
      }
    }
    timeline_.ActivityEnd(e.name);
    if (failed) {
      FinishEntry(e, Status::Aborted(TransportError(
          "allgather", e.name, err, (rank_ + 1) % size_,
          (rank_ - 1 + size_) % size_)));
      return;
    }
  }
  timeline_.End(e.name, e.dtype, e.shape.DebugString());
  FinishEntry(e, Status::OK());
}

void Engine::ExecBroadcast(const Response& response,
                           std::vector<TensorTableEntry>& entries,
                           const ExecCtx& ctx) {
  TensorTableEntry& e = entries[0];
  timeline_.Start(e.name);
  if (size_ > 1) {
    timeline_.ActivityStart(e.name, "RING_BROADCAST");
    RingSpec spec = FlatRingSpec();
    const RingPort& port = spec.ports[ctx.channel];
    size_t nbytes = static_cast<size_t>(e.shape.num_elements()) *
                    DataTypeSize(e.dtype);
    int root = response.root_rank;
    bool ok = true;
    std::string detail;
    // Pipeline root → root+1 → ... → root-1 along the ring, STREAMED in
    // chunks: each relay forwards chunk k while chunk k+1 is in flight
    // upstream, so (a) total time ≈ one transfer + hops·chunk_time instead
    // of hops·transfer, and (b) the longest legitimate zero-byte wait is
    // hops·chunk_time, comfortably inside one socket-timeout round even on
    // slow links (RecvAllPatient rides out skew; EOF from a crashed peer
    // still fails immediately).
    uint8_t* p = static_cast<uint8_t*>(e.data);
    bool forward = rank_ != root && (rank_ + 1) % size_ != root;
    int hops = (rank_ - root + size_) % size_;
    for (size_t off = 0; ok && off < nbytes; off += kRelayChunk) {
      size_t n = std::min(kRelayChunk, nbytes - off);
      if (rank_ == root) {
        ok = PortSendAll(port, p + off, n, &detail);
        if (ok) CountPortBytes(port, static_cast<int64_t>(n), 0);
      } else {
        ok = PortRecvAllPatient(port, p + off, n, hops + 2, &detail);
        if (ok) {
          CountPortBytes(port, 0, static_cast<int64_t>(n));
          if (forward) {
            ok = PortSendAll(port, p + off, n, &detail);
            if (ok) CountPortBytes(port, static_cast<int64_t>(n), 0);
          }
        }
      }
    }
    timeline_.ActivityEnd(e.name);
    if (!ok) {
      FinishEntry(e, Status::Aborted(TransportError(
          "broadcast", e.name, detail, (rank_ + 1) % size_,
          (rank_ - 1 + size_) % size_)));
      return;
    }
  }
  timeline_.End(e.name, e.dtype, e.shape.DebugString());
  FinishEntry(e, Status::OK());
}

void Engine::ExecReducescatter(const Response& response,
                               std::vector<TensorTableEntry>& entries,
                               const ExecCtx& ctx) {
  // Never fused; one entry.  First-class half of the allreduce cascade:
  // whenever the COMMITTED shard geometry coincides with the cascade's
  // own segment geometry (always for 1-D tensors — both use the same
  // largest-first split — and for multi-dim tensors with dim0 % size ==
  // 0), the data plane runs exactly the allreduce's reduce-scatter half
  // and stops: flat ring (TCP or shm, streaming multi-channel), star
  // fold + shard scatter under the small-tensor algo, or the two-level
  // hierarchy with a halved cross ring.  The anchor is bit-exactness:
  // reducescatter(x)[rank] == allreduce(x) sliced to the owned shard,
  // per dtype/op/transport — the allgather half only ever moves bytes
  // verbatim, so stopping after the fold cannot change them.  When the
  // geometry does NOT line up (unaligned multi-dim rows, block-
  // quantized int8/fp8 wire, or a hierarchy whose host blocks don't
  // subdivide the cross segments), the exact-parity FALLBACK runs the
  // full allreduce on a scratch buffer and slices the owned shard —
  // same bits by construction, no wire savings (counted in
  // reducescatter_fallback_count).
  // Ghost execution (backup workers): a rank OUTSIDE a partial RS
  // commit's participant set holds no entry but still drives the
  // IDENTICAL full-world cascade over a zeroed buffer (zero = the SUM
  // identity) and discards the shard it nominally owns — the wire
  // pattern never changes shape, exactly the allreduce ghost-ride
  // contract.  Geometry comes from the response alone: partial_dtype/
  // partial_elems + the committed per-rank row split in tensor_sizes.
  const bool ghost = entries.empty();
  TensorTableEntry* ep = ghost ? nullptr : &entries[0];
  const std::string tname = ghost ? response.tensor_names[0] : ep->name;
  if (!ghost) timeline_.Start(tname);
  const DataType in_dtype =
      ghost ? static_cast<DataType>(response.partial_dtype) : ep->dtype;
  const size_t esize = DataTypeSize(in_dtype);
  int64_t row_elems = 1;
  if (ghost) {
    int64_t rows_total = 0;
    for (auto v : response.tensor_sizes) rows_total += v;
    row_elems =
        rows_total > 0 ? response.partial_elems / rows_total : 1;
    if (row_elems <= 0) row_elems = 1;
  } else {
    for (int d = 1; d < ep->shape.ndim(); ++d) {
      row_elems *= ep->shape.dim(d);
    }
  }

  auto hs = ghost ? nullptr : GetHandle(ep->handle);
  if (!ghost && hs == nullptr) return;

  // Committed per-rank shard geometry (absolute element offsets).
  std::vector<int64_t> shard_count(size_), shard_off(size_);
  int64_t off = 0;
  for (int r = 0; r < size_; ++r) {
    shard_count[r] = response.tensor_sizes[r] * row_elems;
    shard_off[r] = off;
    off += shard_count[r];
  }
  const int64_t total = off;
  // Divisor-correct averaging under partial commits: the frontends
  // divide the shard by the COMMITTED participant count.
  const int nparticipants = response.participants.empty()
      ? size_ : static_cast<int>(response.participants.size());

  if (!ghost) {
    const int64_t my_rows = response.tensor_sizes[rank_];
    hs->result_shape.clear();
    hs->result_shape.push_back(my_rows);
    for (int d = 1; d < ep->shape.ndim(); ++d) {
      hs->result_shape.push_back(ep->shape.dim(d));
    }
  }

  std::vector<uint8_t> ghost_zeros;
  const uint8_t* input;
  if (ghost) {
    ghost_zeros.assign(static_cast<size_t>(total) * esize, 0);
    input = ghost_zeros.data();
  } else {
    input = static_cast<const uint8_t*>(ep->data);
  }
  if (size_ == 1 || total == 0) {
    if (!ghost) {
      hs->result.assign(
          input, input + static_cast<size_t>(shard_count[rank_]) * esize);
      timeline_.End(tname, in_dtype, ep->shape.DebugString());
      FinishEntry(*ep, Status::OK(), nparticipants);
    }
    return;
  }

  // Committed wire format (negotiated + validated like the allreduce's;
  // fp32 payloads only).
  const WireDtype wire = in_dtype == DataType::FLOAT32
                             ? response.wire_dtype : WireDtype::FP32;
  const bool quantized = wire == WireDtype::INT8 || wire == WireDtype::FP8;
  const bool half_wire = wire == WireDtype::FP16 || wire == WireDtype::BF16;

  // Alignment: the cascade's EvenSegments vs the committed shards.
  std::vector<int64_t> seg_count, seg_off;
  EvenSegments(total, size_, &seg_count, &seg_off);
  bool aligned = true;
  for (int r = 0; r < size_; ++r) {
    aligned = aligned && seg_count[r] == shard_count[r];
  }

  // Stage the payload: a scratch copy (the caller's input must survive —
  // reducescatter is out-of-place), or for the half wires an RNE-
  // converted half buffer, exactly like ExecAllreduce's staging.
  std::vector<uint8_t> scratch;
  std::vector<uint16_t> halfbuf;
  uint8_t* exec_buf;
  DataType exec_dtype = in_dtype;
  if (half_wire) {
    halfbuf.resize(static_cast<size_t>(total));
    const float* fp = reinterpret_cast<const float*>(input);
    auto q0 = std::chrono::steady_clock::now();
    if (wire == WireDtype::FP16) {
      for (int64_t i = 0; i < total; ++i) halfbuf[i] = FloatToHalf(fp[i]);
    } else {
      for (int64_t i = 0; i < total; ++i) halfbuf[i] = FloatToBF16(fp[i]);
    }
    quantize_ns_.fetch_add(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - q0)
            .count());
    wire_bytes_saved_.fetch_add(total * 2);
    exec_buf = reinterpret_cast<uint8_t*>(halfbuf.data());
    exec_dtype = wire == WireDtype::FP16 ? DataType::FLOAT16
                                         : DataType::BFLOAT16;
  } else {
    scratch.assign(input, input + static_cast<size_t>(total) * esize);
    exec_buf = scratch.data();
  }
  const size_t exec_esize = DataTypeSize(exec_dtype);
  const int64_t exec_bytes = total * static_cast<int64_t>(exec_esize);
  switch (wire) {
    case WireDtype::FP16: wire_fp16_count_.fetch_add(1); break;
    case WireDtype::BF16: wire_bf16_count_.fetch_add(1); break;
    case WireDtype::INT8: wire_int8_count_.fetch_add(1); break;
    case WireDtype::FP8: wire_fp8_count_.fetch_add(1); break;
    case WireDtype::FP32: break;
  }
  if (wire != WireDtype::FP32) {
    char wm[16];
    std::snprintf(wm, sizeof(wm), "WIRE_%s", WireDtypeName(wire));
    for (char* c = wm; *c; ++c) *c = static_cast<char>(toupper(*c));
    timeline_.Algo(tname, wm);
  }

  // Two-level eligibility: host blocks (node-major contiguous grouping)
  // must equal the cross ring's EvenSegments so the leaders' RS half
  // delivers exactly their members' shards.
  bool two_level_ok = false;
  if (two_level_ && aligned && !quantized) {
    bool contiguous = true;
    for (int r = 1; r < size_; ++r) {
      contiguous = contiguous && rank_host_[r] >= rank_host_[r - 1];
    }
    if (contiguous) {
      std::vector<int64_t> host_block(nnodes_, 0);
      for (int r = 0; r < size_; ++r) {
        host_block[rank_host_[r]] += shard_count[r];
      }
      std::vector<int64_t> cseg_count, cseg_off;
      EvenSegments(total, nnodes_, &cseg_count, &cseg_off);
      two_level_ok = true;
      for (int h = 0; h < nnodes_; ++h) {
        two_level_ok = two_level_ok && host_block[h] == cseg_count[h];
      }
    }
  }
  const bool small =
      !two_level_ && UseSmallAlgo(exec_bytes, ctx) && !quantized;
  const bool half_path =
      (two_level_ ? two_level_ok : (aligned || small)) && !quantized;

  bool ok;
  std::string msg;
  auto t0 = std::chrono::steady_clock::now();
  // FSDP grad reduce-scatters get their own span (like FSDP_AG) so a
  // ZeRO-3 step's backward cascade is attributable in traces.
  timeline_.ActivityStart(tname,
                          tname.rfind("fsdp.", 0) == 0 ? "FSDP_RS" : "RS");
  if (!half_path) {
    // Exact-parity fallback: the full allreduce cascade on the staged
    // buffer — the SAME RunAllreduceCascade selection ExecAllreduce
    // runs, so the bitwise anchor can never drift — then slice the
    // owned shard locally.
    reducescatter_fallback_count_.fetch_add(1);
    timeline_.Algo(tname, "RS_FALLBACK");
    ok = RunAllreduceCascade(exec_buf, total, exec_dtype,
                             response.red_op, wire, quantized, half_wire,
                             UseSmallAlgo(exec_bytes, ctx) && !quantized,
                             "reducescatter", tname, ctx, &msg);
  } else if (two_level_) {
    timeline_.Algo(tname, "RS_TWO_LEVEL");
    ok = TwoLevelReduceScatter(exec_buf, total, exec_dtype,
                               response.red_op, shard_count, shard_off,
                               tname, ctx, half_wire, &msg);
  } else if (small) {
    // Star fold + shard scatter: the leader reproduces the ring's exact
    // fold (bit-equal for ANY shard geometry), members get their slices.
    timeline_.Algo(tname, "RS_STAR");
    ok = StarFoldAllreduce(exec_buf, total, exec_dtype, response.red_op,
                           /*broadcast_result=*/false, &msg);
    if (ok) {
      // Shards by GROUP position (the whole-world host group's order,
      // identity on a single host but mapped for safety).
      std::vector<int64_t> mcount(group_size_), moff(group_size_);
      for (int m = 0; m < group_size_; ++m) {
        const int r = group_members_[m];
        mcount[m] = shard_count[r];
        moff[m] = shard_off[r];
      }
      ok = StarScatterShards(exec_buf, mcount, moff, exec_esize, &msg);
    }
  } else {
    // Flat ring RS half: under the engine-wide vrank convention this
    // rank ends owning segment `rank` — its committed shard, because
    // aligned geometry made the two splits identical — and the fold
    // order per segment is EXACTLY the allreduce's.
    timeline_.Algo(tname, "RS_HALF");
    std::string err;
    RingSpec spec = FlatRingSpec();
    spec.compressed = half_wire;
    ok = ChanneledRingAllreduce(exec_buf, total, exec_dtype,
                                response.red_op, spec, ctx, tname, &err,
                                /*rs_only=*/true);
    if (!ok) {
      msg = TransportError("reducescatter", tname, err,
                           (rank_ + 1) % size_,
                           (rank_ - 1 + size_) % size_);
    }
  }
  timeline_.ActivityEnd(tname);
  reducescatter_ns_.fetch_add(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now() - t0)
          .count());
  reducescatter_bytes_.fetch_add(total * static_cast<int64_t>(esize));
  if (!ok) {
    if (!ghost) FinishEntry(*ep, Status::Aborted(msg));
    return;
  }
  if (ghost) return;  // wire driven; the shard is nobody's result

  // Extract the owned shard (converting back from the half staging
  // buffer when the wire was fp16/bf16 — shard only: the rest of the
  // buffer is not this rank's to report).
  hs->result.resize(static_cast<size_t>(shard_count[rank_]) * esize);
  if (half_wire) {
    float* out = reinterpret_cast<float*>(hs->result.data());
    const uint16_t* hb = halfbuf.data() + shard_off[rank_];
    auto q0 = std::chrono::steady_clock::now();
    if (wire == WireDtype::FP16) {
      for (int64_t i = 0; i < shard_count[rank_]; ++i) {
        out[i] = HalfToFloat(hb[i]);
      }
    } else {
      for (int64_t i = 0; i < shard_count[rank_]; ++i) {
        out[i] = BF16ToFloat(hb[i]);
      }
    }
    quantize_ns_.fetch_add(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - q0)
            .count());
  } else {
    memcpy(hs->result.data(), exec_buf + shard_off[rank_] * esize,
           static_cast<size_t>(shard_count[rank_]) * esize);
  }
  timeline_.End(tname, in_dtype, ep->shape.DebugString());
  FinishEntry(*ep, Status::OK(), nparticipants);
}

void Engine::ExecAlltoall(const Response& response,
                          std::vector<TensorTableEntry>& entries,
                          const ExecCtx& ctx) {
  // Variable-split ring-rotation alltoall: circulate each rank's full
  // (wire-form) input around the ring; at step t a rank holds the input
  // of rank (rank - t - 1) and extracts the block addressed to it.
  // Link traffic is (size-1)·input — fine for the host control/data
  // plane this engine serves (the accelerator alltoall is an XLA
  // collective, ops/collective_ops.py); a pairwise exchange would need
  // all-to-all sockets the ring deliberately avoids.  The committed
  // size×size split matrix rides response.tensor_sizes row-major (row s
  // = rank s's send splits), so every rank derives every peer's buffer
  // geometry — including encoded sizes under a block-quantized wire —
  // without any extra negotiation.  Out-of-place: recv dim0 = Σ over
  // sources of split(src → this rank), which generally differs from the
  // send dim0.
  TensorTableEntry& e = entries[0];
  timeline_.Start(e.name);
  const size_t esize = DataTypeSize(e.dtype);
  int64_t row = 1;  // elements per dim-0 row (dims 1+ match cross-rank)
  for (int d = 1; d < e.shape.ndim(); ++d) row *= e.shape.dim(d);

  // Committed split matrix; synthesized for the legacy equal-split
  // contract if a (defensively handled) matrix-less response shows up.
  std::vector<int64_t> matrix = response.tensor_sizes;
  if (matrix.size() != static_cast<size_t>(size_) * size_) {
    matrix.assign(static_cast<size_t>(size_) * size_,
                  e.shape.ndim() > 0 ? e.shape.dim(0) / size_ : 0);
  }
  auto split = [&](int s, int d) -> int64_t {
    return matrix[static_cast<size_t>(s) * size_ + d];
  };

  // Geometry: per-source send dim0 and this rank's recv layout.
  std::vector<int64_t> src_rows(size_, 0);
  int64_t recv_rows = 0;
  for (int s = 0; s < size_; ++s) {
    for (int d = 0; d < size_; ++d) src_rows[s] += split(s, d);
    recv_rows += split(s, rank_);
  }
  // Output offsets (bytes): source blocks land in source-rank order.
  std::vector<int64_t> out_off(size_, 0);
  for (int s = 1; s < size_; ++s) {
    out_off[s] = out_off[s - 1] +
                 split(s - 1, rank_) * row * static_cast<int64_t>(esize);
  }

  auto hs = GetHandle(e.handle);
  if (hs == nullptr) return;
  hs->result.resize(static_cast<size_t>(recv_rows * row) * esize);
  hs->result_shape.clear();
  hs->result_shape.push_back(recv_rows);
  for (int d = 1; d < e.shape.ndim(); ++d) {
    hs->result_shape.push_back(e.shape.dim(d));
  }

  const uint8_t* input = static_cast<const uint8_t*>(e.data);
  const int64_t my_bytes = src_rows[rank_] * row *
                           static_cast<int64_t>(esize);
  alltoall_bytes_.fetch_add(my_bytes);
  auto t0 = std::chrono::steady_clock::now();

  if (size_ == 1) {
    // World of one: identity (the MoE plane's single-rank bit-exact
    // reference path — no wire, no codec).
    memcpy(hs->result.data(), input, static_cast<size_t>(my_bytes));
    alltoall_ns_.fetch_add(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - t0)
            .count());
    timeline_.End(e.name, e.dtype, e.shape.DebugString());
    FinishEntry(e, Status::OK());
    return;
  }

  // Committed wire format (fp32 payloads only, like the reductions).
  const WireDtype wire = e.dtype == DataType::FLOAT32
                             ? response.wire_dtype : WireDtype::FP32;
  const bool quantized = wire == WireDtype::INT8 || wire == WireDtype::FP8;
  const bool half_wire = wire == WireDtype::FP16 || wire == WireDtype::BF16;
  switch (wire) {
    case WireDtype::FP16: wire_fp16_count_.fetch_add(1); break;
    case WireDtype::BF16: wire_bf16_count_.fetch_add(1); break;
    case WireDtype::INT8: wire_int8_count_.fetch_add(1); break;
    case WireDtype::FP8: wire_fp8_count_.fetch_add(1); break;
    case WireDtype::FP32: break;
  }
  if (wire != WireDtype::FP32) {
    char wm[16];
    std::snprintf(wm, sizeof(wm), "WIRE_%s", WireDtypeName(wire));
    for (char* c = wm; *c; ++c) *c = static_cast<char>(toupper(*c));
    timeline_.Algo(e.name, wm);
  }

  // Per-source WIRE buffer geometry, identical on every rank.  Blocks
  // are encoded per DESTINATION so a receiver decodes exactly its own
  // block; under int8/fp8 each block is an independent run of
  // fixed-size scaled sub-blocks (deterministic encoded length from the
  // committed matrix + the committed chunk knob).
  const size_t wire_esize = half_wire ? 2 : esize;
  const int64_t qblock_elems =
      std::max<int64_t>(64, chunk_bytes_.load() / 4);
  const size_t qblock_bytes = 4 + static_cast<size_t>(qblock_elems);
  auto enc_bytes = [&](int64_t nelems) -> int64_t {
    if (!quantized) return nelems * static_cast<int64_t>(wire_esize);
    if (nelems == 0) return 0;
    return (nelems + qblock_elems - 1) / qblock_elems *
           static_cast<int64_t>(qblock_bytes);
  };
  std::vector<int64_t> buf_bytes(size_, 0);
  // blk_off[s*size_+d]: byte offset of block (s → d) in source s's wire
  // buffer.
  std::vector<int64_t> blk_off(static_cast<size_t>(size_) * size_, 0);
  int64_t max_buf = 0;
  for (int s = 0; s < size_; ++s) {
    int64_t off = 0;
    for (int d = 0; d < size_; ++d) {
      blk_off[static_cast<size_t>(s) * size_ + d] = off;
      off += enc_bytes(split(s, d) * row);
    }
    buf_bytes[s] = off;
    max_buf = std::max(max_buf, off);
  }

  // Stage this rank's input into wire form.  The codec round-trips the
  // OWN block too, so a block's bytes never depend on which rank it
  // stayed on — fp32 wire stays bitwise-verbatim, lossy wires are
  // uniformly lossy.
  std::vector<uint8_t> cur(static_cast<size_t>(max_buf));
  std::vector<uint8_t> nxt(static_cast<size_t>(max_buf));
  if (wire == WireDtype::FP32) {
    memcpy(cur.data(), input, static_cast<size_t>(my_bytes));
  } else {
    const float* fp = reinterpret_cast<const float*>(input);
    auto q0 = std::chrono::steady_clock::now();
    if (half_wire) {
      uint16_t* hb = reinterpret_cast<uint16_t*>(cur.data());
      const int64_t n = src_rows[rank_] * row;
      if (wire == WireDtype::FP16) {
        for (int64_t i = 0; i < n; ++i) hb[i] = FloatToHalf(fp[i]);
      } else {
        for (int64_t i = 0; i < n; ++i) hb[i] = FloatToBF16(fp[i]);
      }
    } else {
      int64_t elem_off = 0;
      for (int d = 0; d < size_; ++d) {
        const int64_t n = split(rank_, d) * row;
        uint8_t* dst =
            cur.data() + blk_off[static_cast<size_t>(rank_) * size_ + d];
        for (int64_t o = 0; o < n; o += qblock_elems) {
          QuantizeBlock(fp + elem_off + o, std::min(qblock_elems, n - o),
                        wire, dst + o / qblock_elems * qblock_bytes,
                        qblock_elems);
        }
        elem_off += n;
      }
    }
    quantize_ns_.fetch_add(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - q0)
            .count());
    wire_bytes_saved_.fetch_add(
        std::max<int64_t>(0, my_bytes - buf_bytes[rank_]));
  }

  // Decode block (src → this rank) out of src's wire buffer into the
  // output slot.
  auto extract = [&](int src, const uint8_t* buf) {
    const int64_t n = split(src, rank_) * row;
    if (n == 0) return;
    const uint8_t* blk =
        buf + blk_off[static_cast<size_t>(src) * size_ + rank_];
    uint8_t* out = hs->result.data() + out_off[src];
    if (wire == WireDtype::FP32) {
      memcpy(out, blk, static_cast<size_t>(n) * esize);
      return;
    }
    float* fout = reinterpret_cast<float*>(out);
    auto q0 = std::chrono::steady_clock::now();
    if (half_wire) {
      const uint16_t* hb = reinterpret_cast<const uint16_t*>(blk);
      if (wire == WireDtype::FP16) {
        for (int64_t i = 0; i < n; ++i) fout[i] = HalfToFloat(hb[i]);
      } else {
        for (int64_t i = 0; i < n; ++i) fout[i] = BF16ToFloat(hb[i]);
      }
    } else {
      for (int64_t o = 0; o < n; o += qblock_elems) {
        DequantizeBlock(blk + o / qblock_elems * qblock_bytes,
                        std::min(qblock_elems, n - o), wire, fout + o);
      }
    }
    quantize_ns_.fetch_add(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - q0)
            .count());
  };
  extract(rank_, cur.data());

  // MoE token routing gets its own span (like FSDP_AG) so expert
  // dispatch/combine traffic is attributable against compute in traces.
  timeline_.ActivityStart(e.name, e.name.rfind("moe.", 0) == 0
                                      ? "MOE_DISPATCH" : "ALLTOALL");
  RingSpec spec = FlatRingSpec();
  const RingPort& port = spec.ports[ctx.channel];
  bool failed = false;
  std::string err;
  for (int step = 0; step < size_ - 1 && !failed; ++step) {
    const int send_src = (rank_ - step + size_) % size_;
    const int recv_src = (rank_ - step - 1 + size_) % size_;
    int64_t wns = 0;
    failed = !PortSendRecvChunked(
        port, cur.data(), static_cast<size_t>(buf_bytes[send_src]),
        nxt.data(), static_cast<size_t>(buf_bytes[recv_src]),
        /*chunk=*/0, nullptr, socket_timeout_sec_ * 1000, &err, &wns);
    wire_ns_.fetch_add(wns);
    if (!failed) {
      CountPortBytes(port, buf_bytes[send_src], buf_bytes[recv_src]);
      if (wire != WireDtype::FP32) {
        compressed_bytes_tx_.fetch_add(buf_bytes[send_src]);
      }
      extract(recv_src, nxt.data());
      cur.swap(nxt);
    }
  }
  timeline_.ActivityEnd(e.name);
  alltoall_ns_.fetch_add(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now() - t0)
          .count());
  if (failed) {
    FinishEntry(e, Status::Aborted(TransportError(
        "alltoall", e.name, err, (rank_ + 1) % size_,
        (rank_ - 1 + size_) % size_)));
    return;
  }
  timeline_.End(e.name, e.dtype, e.shape.DebugString());
  FinishEntry(e, Status::OK());
}

void Engine::FinishEntry(TensorTableEntry& e, const Status& s,
                         int participants) {
  // Step-time sample: allreduce completion latency (enqueue → finish),
  // successful entries only — skipped/errored entries would poison the
  // percentiles the straggler gate compares.
  if (s.ok() && e.type == RequestType::ALLREDUCE) {
    RecordStepTimeNs(std::chrono::duration_cast<std::chrono::nanoseconds>(
                         std::chrono::steady_clock::now() - e.enqueue_time)
                         .count());
  }
  auto hs = GetHandle(e.handle);
  if (hs == nullptr) return;
  {
    std::lock_guard<std::mutex> lk(handle_mu_);
    hs->error = s.reason();
    hs->participants = participants >= 0 ? participants : size_;
    hs->done.store(s.ok() ? 1 : -1);
  }
  handle_cv_.notify_all();
}

void Engine::RecordStepTimeNs(int64_t ns) {
  std::lock_guard<std::mutex> lk(step_ns_mu_);
  constexpr size_t kCap = 4096;
  if (step_ns_samples_.size() < kCap) {
    step_ns_samples_.push_back(ns);
  } else {
    step_ns_samples_[step_ns_next_ % kCap] = ns;
  }
  ++step_ns_next_;
}

int64_t Engine::StepTimeNsPercentile(double p) const {
  std::vector<int64_t> snap;
  {
    std::lock_guard<std::mutex> lk(step_ns_mu_);
    snap = step_ns_samples_;
  }
  if (snap.empty()) return 0;
  size_t idx = static_cast<size_t>(p * (snap.size() - 1) + 0.5);
  if (idx >= snap.size()) idx = snap.size() - 1;
  std::nth_element(snap.begin(), snap.begin() + idx, snap.end());
  return snap[idx];
}

int Engine::ResultParticipants(int64_t handle) {
  auto hs = GetHandle(handle);
  if (hs == nullptr) return 0;
  std::lock_guard<std::mutex> lk(handle_mu_);
  return hs->participants;
}

// Rank-0-only stall warnings naming the missing ranks (reference
// CheckForStalledTensors, operations.cc:1366-1412).
void Engine::CheckForStalledTensors() {
  auto now = std::chrono::steady_clock::now();
  if (now - last_stall_check_ < std::chrono::seconds(stall_warning_sec_)) {
    return;
  }
  last_stall_check_ = now;
  // message_table_ is background-thread-only (see engine.h); no lock.
  AssertBackgroundThread();
  bool preamble = false;
  auto warn_preamble = [&] {
    if (preamble) return;
    std::fprintf(
        stderr,
        "One or more tensors were submitted to be reduced, gathered or "
        "broadcasted by subset of ranks and are waiting for remainder of "
        "ranks for more than %d seconds. This may indicate that different "
        "ranks are trying to submit different tensors or that only subset "
        "of ranks is submitting tensors, which will cause deadlock.\n",
        stall_warning_sec_);
    std::fprintf(stderr, "Stalled ops:\n");
    preamble = true;
  };
  // Once host grouping is active, a stalled negotiation names the slow
  // HOST alongside each rank — at fleet scale "rank 37" sends the
  // operator grepping rendezvous logs, "host 4" names the machine.
  auto missing_ranks = [&](const std::vector<bool>& seen) {
    std::string missing;
    for (int r = 0; r < size_; ++r) {
      if (!seen[r]) {
        if (!missing.empty()) missing += ", ";
        missing += std::to_string(r);
        if (nnodes_ > 1 && r < static_cast<int>(rank_host_.size())) {
          missing += " (host " + std::to_string(rank_host_[r]) + ")";
        }
      }
    }
    return missing;
  };
  // Under hierarchical coordination slot-readiness bits are GROUP
  // granular: name the silent hosts (and their leader ranks) directly.
  auto missing_voters = [&](const std::vector<bool>& seen) {
    if (!HierActive()) return missing_ranks(seen);
    std::string missing;
    for (int g = 0; g < nnodes_ && g < static_cast<int>(seen.size()); ++g) {
      if (!seen[g]) {
        if (!missing.empty()) missing += ", ";
        missing += "host " + std::to_string(g) + " (leader rank " +
                   std::to_string(group_leaders_[g]) + ")";
      }
    }
    return missing;
  };
  // Per-tensor rate limit (at most one warning per HOROVOD_STALL_WARNING
  // _SEC per tensor, independent of the scan cadence), with every emitted
  // warning counted (horovod_stall_warnings_total) and mirrored into the
  // flight recorder.  A tensor stalled past TWICE the warning interval
  // escalates: one flight-recorder dump per process, so the operator gets
  // the control-plane history even when the job later limps on.
  auto rate_limited = [&](const std::string& name) {
    auto it = stall_last_warned_.find(name);
    if (it != stall_last_warned_.end() &&
        now - it->second < std::chrono::seconds(stall_warning_sec_)) {
      return true;
    }
    stall_last_warned_[name] = now;
    return false;
  };
  auto escalate = [&](const std::string& name, long long age) {
    if (flight_escalated_ || age < 2ll * stall_warning_sec_) return;
    flight_escalated_ = true;
    GlobalFlightRecorder().Dump(
        ("stall-warning escalation: '" + name + "' stalled " +
         std::to_string(age) + "s")
            .c_str());
  };
  for (auto& kv : message_table_) {
    auto age = std::chrono::duration_cast<std::chrono::seconds>(
                   now - kv.second.first_seen)
                   .count();
    if (age < stall_warning_sec_ || rate_limited(kv.first)) continue;
    warn_preamble();
    const std::string missing = missing_ranks(kv.second.seen);
    std::fprintf(stderr, "%s [missing ranks: %s]\n", kv.first.c_str(),
                 missing.c_str());
    stall_warnings_.fetch_add(1);
    GlobalFlightRecorder().Record("stall", control_cycle_seq_,
                                  "%s age=%llds missing=%s",
                                  kv.first.c_str(),
                                  static_cast<long long>(age),
                                  missing.c_str());
    escalate(kv.first, age);
  }
  // Cache-hit readiness bits stall the same way full requests do (a
  // subset of ranks re-enqueued a cached tensor, the rest never did).
  const int nvoters = HierActive() ? nnodes_ : size_;
  for (auto& kv : coord_slot_bits_) {
    if (kv.second.count == 0 || kv.second.count == nvoters) continue;
    auto age = std::chrono::duration_cast<std::chrono::seconds>(
                   now - kv.second.first_seen)
                   .count();
    if (age < stall_warning_sec_) continue;
    auto nit = coord_slot_names_.find(kv.first);
    const std::string name =
        nit == coord_slot_names_.end() ? "?" : nit->second;
    if (rate_limited(name)) continue;
    warn_preamble();
    const std::string missing = missing_voters(kv.second.seen);
    std::fprintf(stderr, "%s [cached slot %u; missing: %s]\n", name.c_str(),
                 kv.first, missing.c_str());
    stall_warnings_.fetch_add(1);
    GlobalFlightRecorder().Record("stall", control_cycle_seq_,
                                  "%s slot=%u age=%llds missing=%s",
                                  name.c_str(), kv.first,
                                  static_cast<long long>(age),
                                  missing.c_str());
    escalate(name, age);
  }
  // Entries that resolved (or died with the world) drop out of the
  // rate-limit map so it cannot grow without bound across a long job.
  for (auto it = stall_last_warned_.begin();
       it != stall_last_warned_.end();) {
    if (now - it->second > std::chrono::seconds(4 * stall_warning_sec_)) {
      it = stall_last_warned_.erase(it);
    } else {
      ++it;
    }
  }
}

// ---------------------------------------------------------------------------
// Public enqueue / handle API
// ---------------------------------------------------------------------------

// Fires the armed HOROVOD_FAULT_INJECT action when this rank's enqueue
// counter reaches the configured step.  Runs in the enqueueing (API)
// thread; HANG/DROP_CONN only set flags the background loop acts on, so
// every effect lands at a deterministic point regardless of cycle timing.
void Engine::MaybeInjectFault() {
  if (fault_kind_ == FaultKind::NONE) return;
  int64_t idx = enqueue_count_.fetch_add(1);
  if (fault_kind_ == FaultKind::CONN_RESET && fault_step_ == -2) {
    // Flap schedule (step '*'): arm a reset every K-th enqueue, skipping
    // enqueue 0 so wiring warms up.  Recurring by design — never sets
    // fault_fired_, so a flap soak keeps flapping across the whole run.
    if (idx > 0 && idx % fault_reset_period_ == 0) {
      fault_conn_reset_.store(true);
    }
    return;
  }
  if (fault_step_ != -2 && idx != fault_step_) return;  // -2: every step
  if (fault_kind_ == FaultKind::SLOW) {
    // Straggler injection: delay THIS enqueue in the API thread (the
    // background loop keeps cycling, so control frames keep flowing and
    // peers see a slow rank, not a dead one).  '*' schedules recur —
    // they never set fault_fired_, so an elastic re-Init keeps the rank
    // slow, which is what a chaos soak wants.
    if (fault_step_ != -2) fault_fired_ = true;
    std::fprintf(stderr,
                 "horovod_tpu rank %d: fault injection: delaying enqueue "
                 "%lld by %lldms\n",
                 rank_, static_cast<long long>(idx),
                 static_cast<long long>(fault_slow_ms_));
    std::this_thread::sleep_for(std::chrono::milliseconds(fault_slow_ms_));
    return;
  }
  fault_fired_ = true;  // once per process, not per engine incarnation
  switch (fault_kind_) {
    case FaultKind::EXIT:
      std::fprintf(stderr,
                   "horovod_tpu rank %d: fault injection: exiting at "
                   "enqueue %lld\n",
                   rank_, static_cast<long long>(idx));
      _exit(41);
    case FaultKind::HANG:
      std::fprintf(stderr,
                   "horovod_tpu rank %d: fault injection: freezing the "
                   "background loop at enqueue %lld\n",
                   rank_, static_cast<long long>(idx));
      fault_hang_.store(true);
      break;
    case FaultKind::DROP_CONN:
      std::fprintf(stderr,
                   "horovod_tpu rank %d: fault injection: dropping all "
                   "connections at enqueue %lld\n",
                   rank_, static_cast<long long>(idx));
      fault_drop_.store(true);
      break;
    case FaultKind::SLOW:
      break;  // handled above
    case FaultKind::CONN_RESET:
      std::fprintf(stderr,
                   "horovod_tpu rank %d: fault injection: arming a data-"
                   "channel %s-socket reset at enqueue %lld\n",
                   rank_, fault_reset_prev_ ? "recv" : "send",
                   static_cast<long long>(idx));
      fault_conn_reset_.store(true);
      break;
    case FaultKind::RECV_STALL:
      std::fprintf(stderr,
                   "horovod_tpu rank %d: fault injection: arming a %lldms "
                   "recv stall at enqueue %lld\n",
                   rank_, static_cast<long long>(fault_stall_len_ms_),
                   static_cast<long long>(idx));
      fault_stall_ms_.store(fault_stall_len_ms_);
      break;
    case FaultKind::STALE_EPOCH:
      // Worker-only (the coordinator sends no RequestList frames): the
      // next control frame is preceded by a duplicate stamped epoch-1,
      // exercising the receiver's structural stale-epoch rejection.
      std::fprintf(stderr,
                   "horovod_tpu rank %d: fault injection: sending a "
                   "stale-epoch control frame at enqueue %lld\n",
                   rank_, static_cast<long long>(idx));
      fault_stale_epoch_.store(true);
      break;
    case FaultKind::NONE:
      break;
  }
}

int64_t Engine::Enqueue(RequestType type, const std::string& name,
                        DataType dtype, const std::vector<int64_t>& shape,
                        void* data, int root_rank, ReduceOp red_op,
                        bool probe, int wire_dtype, int priority,
                        bool wire_advisory,
                        const std::vector<int64_t>& splits) {
  MaybeInjectFault();
  if (!initialized_.load() || shutdown_requested_.load() ||
      shut_down_.load()) {
    return -2;
  }
  // Resolve the wire format at enqueue time: per-tensor override wins,
  // else the live global knob; compression only ever applies to FLOAT32
  // allreduce/reducescatter payloads (probes included — they are dense
  // allreduces).  Reducescatter rides the same codec seam: fp16/bf16
  // run the half-staged RS half, int8/fp8 take the exact-parity
  // fallback (full quantized ring + local slice).
  WireDtype wire = WireDtype::FP32;
  if ((type == RequestType::ALLREDUCE ||
       type == RequestType::REDUCESCATTER ||
       type == RequestType::ALLTOALL) &&
      dtype == DataType::FLOAT32) {
    int wv = wire_dtype >= 0 ? wire_dtype : wire_dtype_.load();
    if (wv >= 1 && wv <= 4) wire = static_cast<WireDtype>(wv);
  }
  // Knob-derived resolutions are advisory (the coordinator commits one
  // format at negotiation): sampling the live knob here inherently
  // races a TUNE landing on peers — see Request::wire_default.  An
  // explicit override may OPT INTO the advisory semantics too
  // (wire_advisory): the statistics-driven wire policy stamps formats
  // from per-rank gradient stats, which may legitimately disagree for a
  // step — the coordinator commits the first value instead of erroring.
  const bool wire_default = wire_dtype < 0 || wire_advisory;
  if (priority < 0) priority = 0;
  if (priority > (1 << 30)) priority = 1 << 30;
  int64_t handle = next_handle_.fetch_add(1);
  auto hs = std::make_shared<HandleState>();
  {
    std::lock_guard<std::mutex> lk(handle_mu_);
    handles_[handle] = hs;
  }
  TensorTableEntry e;
  e.name = name;
  e.type = type;
  e.dtype = dtype;
  for (auto d : shape) e.shape.AddDim(d);
  e.data = data;
  e.root_rank = root_rank;
  e.red_op = red_op;
  e.wire_dtype = wire;
  e.wire_default = wire_default;
  e.priority = static_cast<int32_t>(priority);
  if (type == RequestType::ALLTOALL) e.splits = splits;
  e.handle = handle;
  e.enqueue_time = std::chrono::steady_clock::now();

  Request q;
  q.request_rank = rank_;
  q.type = type;
  q.dtype = dtype;
  q.tensor_name = name;
  q.root_rank = root_rank;
  q.red_op = red_op;
  q.probe = probe;
  q.wire_dtype = wire;
  q.wire_default = wire_default;
  q.priority = static_cast<int32_t>(priority);
  q.shape = shape;
  if (type == RequestType::ALLTOALL) q.splits = splits;

  {
    std::lock_guard<std::mutex> lk(mu_);
    // Re-check liveness under mu_: the background loop's teardown drains
    // the table, stores shut_down_, then drains again — so an insert that
    // slipped past the entry check either lands before the second drain
    // (and is failed by it) or observes shut_down_ here and is rejected.
    if (shut_down_.load()) {
      std::lock_guard<std::mutex> hlk(handle_mu_);
      handles_.erase(handle);
      return -2;
    }
    if (tensor_table_.count(name) != 0) {
      std::lock_guard<std::mutex> hlk(handle_mu_);
      handles_.erase(handle);
      return -1;  // duplicate name in flight
    }
    tensor_table_.emplace(name, std::move(e));
    message_queue_.push_back(std::move(q));
  }
  // Wake the background loop immediately (event-driven cycle): the tensor
  // negotiates on the next control round trip instead of waiting out the
  // remainder of HOROVOD_CYCLE_TIME.
  cycle_cv_.notify_one();
  return handle;
}

std::shared_ptr<HandleState> Engine::GetHandle(int64_t handle) {
  std::lock_guard<std::mutex> lk(handle_mu_);
  auto it = handles_.find(handle);
  return it == handles_.end() ? nullptr : it->second;
}

int Engine::Poll(int64_t handle) {
  auto hs = GetHandle(handle);
  if (hs == nullptr) return -1;
  return hs->done.load();
}

int Engine::Wait(int64_t handle) {
  auto hs = GetHandle(handle);
  if (hs == nullptr) return -1;
  std::unique_lock<std::mutex> lk(handle_mu_);
  handle_cv_.wait(lk, [&] { return hs->done.load() != 0; });
  return hs->done.load();
}

std::string Engine::ErrorMessage(int64_t handle) {
  auto hs = GetHandle(handle);
  if (hs == nullptr) return "unknown handle";
  std::lock_guard<std::mutex> lk(handle_mu_);
  return hs->error;
}

int64_t Engine::ResultNumDims(int64_t handle) {
  auto hs = GetHandle(handle);
  if (hs == nullptr) return -1;
  return static_cast<int64_t>(hs->result_shape.size());
}

int64_t Engine::ResultDim(int64_t handle, int i) {
  auto hs = GetHandle(handle);
  if (hs == nullptr || i < 0 ||
      i >= static_cast<int>(hs->result_shape.size())) {
    return -1;
  }
  return hs->result_shape[i];
}

int64_t Engine::ResultByteSize(int64_t handle) {
  auto hs = GetHandle(handle);
  if (hs == nullptr) return -1;
  return static_cast<int64_t>(hs->result.size());
}

int Engine::CopyResult(int64_t handle, void* dst, int64_t nbytes) {
  auto hs = GetHandle(handle);
  if (hs == nullptr || nbytes < static_cast<int64_t>(hs->result.size())) {
    return -1;
  }
  memcpy(dst, hs->result.data(), hs->result.size());
  return 0;
}

void Engine::ReleaseHandle(int64_t handle) {
  std::lock_guard<std::mutex> lk(handle_mu_);
  handles_.erase(handle);
}

}  // namespace hvd
