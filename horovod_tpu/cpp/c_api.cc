// C ABI for the native engine, loaded from Python via ctypes.
//
// Surface parity with the reference C API (horovod/common/operations.h:
// 68-118: horovod_init/_shutdown/_rank/_size/_local_rank/_local_size/
// _mpi_threads_supported + EnqueueTensor*), reshaped for ctypes: instead of
// C++ callbacks, enqueue returns an int64 handle polled/waited on from
// Python (the pattern of the reference torch handle manager,
// horovod/torch/handle_manager.{h,cc}).
#include <cstring>

#include "engine.h"

using hvd::DataType;
using hvd::Engine;
using hvd::RequestType;

extern "C" {

int horovod_init(int rank, int size, int local_rank, int local_size,
                 const char* coordinator_addr) {
  return Engine::Get().Init(rank, size, local_rank, local_size,
                            coordinator_addr ? coordinator_addr : "");
}

void horovod_shutdown() { Engine::Get().Shutdown(); }

int horovod_is_initialized() {
  return Engine::Get().initialized() ? 1 : 0;
}

int horovod_rank() { return Engine::Get().rank(); }
int horovod_size() { return Engine::Get().size(); }
int horovod_local_rank() { return Engine::Get().local_rank(); }
int horovod_local_size() { return Engine::Get().local_size(); }

// Committed membership epoch: bumped by every successful rendezvous
// commit; all live members of a world agree on it, and an elastic resize
// increments it (stale-epoch control frames are rejected structurally).
int64_t horovod_epoch() { return Engine::Get().epoch(); }

// No MPI anywhere; the engine's own threading is unconditional.
int horovod_mpi_threads_supported() { return 1; }

const char* horovod_last_error() {
  return Engine::Get().last_error().c_str();
}

// op: 0 = allreduce, 1 = allgather, 2 = broadcast, 3 = reducescatter,
// 4 = alltoall (RequestType values).
// red_op: 0 = sum, 1 = min, 2 = max, 3 = prod (ReduceOp values;
// allreduce/reducescatter only).
// Returns handle >= 0, -1 on duplicate in-flight name, -2 if not running.
int64_t horovod_enqueue(int op, const char* name, int dtype, int ndim,
                        const int64_t* shape, void* data, int root_rank,
                        int red_op) {
  std::vector<int64_t> dims(shape, shape + ndim);
  return Engine::Get().Enqueue(static_cast<RequestType>(op), name,
                               static_cast<DataType>(dtype), dims, data,
                               root_rank, static_cast<hvd::ReduceOp>(red_op));
}

// Like horovod_enqueue with an explicit per-tensor WIRE dtype for the
// allreduce payload: 0 = fp32, 1 = fp16, 2 = bf16, 3 = int8, 4 = fp8
// (WireDtype values); < 0 defers to the live HOROVOD_WIRE_DTYPE knob —
// exactly what horovod_enqueue does.  Only fp32 allreduces compress.
int64_t horovod_enqueue_wire(int op, const char* name, int dtype, int ndim,
                             const int64_t* shape, void* data,
                             int root_rank, int red_op, int wire_dtype) {
  std::vector<int64_t> dims(shape, shape + ndim);
  return Engine::Get().Enqueue(static_cast<RequestType>(op), name,
                               static_cast<DataType>(dtype), dims, data,
                               root_rank, static_cast<hvd::ReduceOp>(red_op),
                               /*probe=*/false, wire_dtype);
}

// Like horovod_enqueue_wire with the full per-tensor scheduling surface:
// `priority` (>= 0; 0 = most urgent, the default) is the metadata the
// priority-banded coordinator orders responses by (frontends stamp it
// from registration order), and `wire_advisory` != 0 marks the explicit
// wire_dtype as knob-like (the coordinator commits the first value on a
// cross-rank disagreement instead of erroring — the seam the
// statistics-driven wire policy rides, since per-rank gradient stats may
// legitimately disagree for a step).
int64_t horovod_enqueue_priority(int op, const char* name, int dtype,
                                 int ndim, const int64_t* shape, void* data,
                                 int root_rank, int red_op, int wire_dtype,
                                 int wire_advisory, int priority) {
  std::vector<int64_t> dims(shape, shape + ndim);
  return Engine::Get().Enqueue(static_cast<RequestType>(op), name,
                               static_cast<DataType>(dtype), dims, data,
                               root_rank, static_cast<hvd::ReduceOp>(red_op),
                               /*probe=*/false, wire_dtype, priority,
                               wire_advisory != 0);
}

// Layout-probe allreduce (sum) for a tensor whose gradient never
// materialized locally: completes as a normal dense allreduce unless peers
// are gathering the tensor sparsely, in which case the handle fails with
// "__sparse_retry__:<sparse_dim>" and the caller re-enqueues zero-entry
// sparse gathers (see Request::probe in message.h).
int64_t horovod_enqueue_probe(const char* name, int dtype, int ndim,
                              const int64_t* shape, void* data) {
  std::vector<int64_t> dims(shape, shape + ndim);
  return Engine::Get().Enqueue(RequestType::ALLREDUCE, name,
                               static_cast<DataType>(dtype), dims, data,
                               /*root_rank=*/-1, hvd::ReduceOp::SUM,
                               /*probe=*/true);
}

// Execution stats: negotiation cycles that executed work, responses
// executed (a fused batch counts once), and tensors executed.  Lets
// frontends and tests assert the async+fusion property (N tensors batched
// into ~1 cycle, tensors/responses > 1) instead of trusting it.
int64_t horovod_exec_cycles() { return Engine::Get().exec_cycles(); }
int64_t horovod_responses_executed() {
  return Engine::Get().responses_executed();
}
int64_t horovod_tensors_executed() {
  return Engine::Get().tensors_executed();
}

// Control-plane / response-cache observability (see Engine accessors):
// cache hit/miss/eviction counts, control-frame bytes each way, and the
// number of completed coordinator round trips — bench and tests divide
// the last by step count to prove steady state needs ~1 round trip/step.
int64_t horovod_cache_hits() { return Engine::Get().cache_hits(); }
int64_t horovod_cache_misses() { return Engine::Get().cache_misses(); }
int64_t horovod_cache_evictions() {
  return Engine::Get().cache_evictions();
}
int64_t horovod_negotiation_bytes_tx() {
  return Engine::Get().negotiation_bytes_tx();
}
int64_t horovod_negotiation_bytes_rx() {
  return Engine::Get().negotiation_bytes_rx();
}
int64_t horovod_control_round_trips() {
  return Engine::Get().control_round_trips();
}
int64_t horovod_stale_epoch_msgs() {
  return Engine::Get().stale_epoch_msgs();
}

// Big-world control plane: rendezvous ASSIGN bytes this coordinator has
// sent (deterministic, the scale harness's frame-compaction metric), the
// coordinator's control-plane cycle-time percentiles over a sliding
// window of payload cycles (0 on workers / idle worlds), and whether
// hierarchical coordination (per-host sub-coordinators) is committed.
int64_t horovod_assign_bytes_tx() {
  return Engine::Get().assign_bytes_tx();
}
int64_t horovod_coordinator_cycle_ns_p50() {
  return Engine::Get().coordinator_cycle_ns_p50();
}
int64_t horovod_coordinator_cycle_ns_p99() {
  return Engine::Get().coordinator_cycle_ns_p99();
}
int64_t horovod_hier_coordinator() {
  return Engine::Get().hier_coordinator() ? 1 : 0;
}

// Data-plane observability: payload bytes moved over ring data sockets
// (all collectives, all channels), cumulative thread-time split between
// socket progress (wire) and reduction kernels (reduce) — each sums
// ACROSS channels, so either may exceed wall time when channels overlap —
// plus ring-allreduce payload bytes and wall time, from which Python's
// stats() derives allreduce_bus_bw_bytes_per_sec, and the committed
// per-edge channel count.
int64_t horovod_data_bytes_tx() { return Engine::Get().data_bytes_tx(); }
int64_t horovod_data_bytes_rx() { return Engine::Get().data_bytes_rx(); }
int64_t horovod_reduce_ns() { return Engine::Get().reduce_ns(); }
int64_t horovod_wire_ns() { return Engine::Get().wire_ns(); }
int64_t horovod_allreduce_bytes() {
  return Engine::Get().allreduce_bytes();
}
int64_t horovod_allreduce_ns() { return Engine::Get().allreduce_ns(); }
// Reduce-scatter observability (first-class collective + the ZeRO-style
// sharded optimizer riding it): payload bytes / wall time of
// REDUCESCATTER responses, responses that took the exact-parity
// fallback (full allreduce + slice), and sharded-optimizer steps the
// Python frontends completed (noted like local_sgd_syncs).
int64_t horovod_reducescatter_bytes() {
  return Engine::Get().reducescatter_bytes();
}
int64_t horovod_reducescatter_ns() {
  return Engine::Get().reducescatter_ns();
}
int64_t horovod_reducescatter_fallbacks() {
  return Engine::Get().reducescatter_fallback_count();
}
int64_t horovod_sharded_steps() { return Engine::Get().sharded_steps(); }
void horovod_note_sharded_step() { Engine::Get().NoteShardedStep(); }
// Alltoall observability (first-class collective + the MoE plane riding
// it): payload bytes / wall time of ALLTOALL responses — Python's
// stats() derives alltoall_bus_bw_bytes_per_sec = (N-1)/N·bytes/wall —
// plus cumulative MoE drop-token accounting (noted per dispatch from
// runtime/moe.py so it rides the TELEM fleet aggregation).
int64_t horovod_alltoall_bytes() { return Engine::Get().alltoall_bytes(); }
int64_t horovod_alltoall_ns() { return Engine::Get().alltoall_ns(); }
int64_t horovod_moe_tokens_dropped() {
  return Engine::Get().moe_tokens_dropped();
}
void horovod_note_moe_dispatch(int64_t dropped) {
  Engine::Get().NoteMoeDispatch(dropped);
}
// Alltoall enqueue with the variable per-rank split surface: `splits`
// (nsplits = world size entries, summing to shape[0]) is this rank's
// per-destination dim-0 row counts; nsplits = 0 is the legacy
// equal-split contract.  wire_dtype/wire_advisory/priority behave
// exactly as in horovod_enqueue_priority.
int64_t horovod_enqueue_alltoall(const char* name, int dtype, int ndim,
                                 const int64_t* shape, void* data,
                                 const int64_t* splits, int nsplits,
                                 int wire_dtype, int wire_advisory,
                                 int priority) {
  std::vector<int64_t> dims(shape, shape + ndim);
  std::vector<int64_t> sp;
  if (splits != nullptr && nsplits > 0) sp.assign(splits, splits + nsplits);
  return Engine::Get().Enqueue(RequestType::ALLTOALL, name,
                               static_cast<DataType>(dtype), dims, data,
                               /*root_rank=*/-1, hvd::ReduceOp::SUM,
                               /*probe=*/false, wire_dtype, priority,
                               wire_advisory != 0, sp);
}
int64_t horovod_num_channels() {
  return static_cast<int64_t>(Engine::Get().num_channels());
}

// Shared-memory / hierarchy observability: payload bytes through shm
// rings (also counted in data_bytes_*; shm is a transport of the same
// data plane), bytes exchanged with co-located ranks, allreduce responses
// per algorithm path (latency star vs. bandwidth ring), and the committed
// host topology (host count x this rank's group size).
int64_t horovod_shm_bytes_tx() { return Engine::Get().shm_bytes_tx(); }
int64_t horovod_shm_bytes_rx() { return Engine::Get().shm_bytes_rx(); }
int64_t horovod_intra_host_bytes() {
  return Engine::Get().intra_host_bytes();
}
int64_t horovod_algo_small_count() {
  return Engine::Get().algo_small_count();
}
int64_t horovod_algo_ring_count() {
  return Engine::Get().algo_ring_count();
}
int64_t horovod_topology_hosts() {
  return static_cast<int64_t>(Engine::Get().topology_hosts());
}
int64_t horovod_topology_local_ranks() {
  return static_cast<int64_t>(Engine::Get().topology_local_ranks());
}
int64_t horovod_shm_enabled() {
  return Engine::Get().shm_enabled() ? 1 : 0;
}
int64_t horovod_algo_threshold() { return Engine::Get().algo_threshold(); }

// Wire-compression observability (see Engine accessors): buffer-level
// bytes saved by the wire representation, compressed ring payload sent,
// cumulative (de)quantization kernel time, and per-mode response counts.
int64_t horovod_wire_bytes_saved() {
  return Engine::Get().wire_bytes_saved();
}
int64_t horovod_compressed_bytes_tx() {
  return Engine::Get().compressed_bytes_tx();
}
int64_t horovod_quantize_ns() { return Engine::Get().quantize_ns(); }
int64_t horovod_wire_fp16_count() {
  return Engine::Get().wire_fp16_count();
}
int64_t horovod_wire_bf16_count() {
  return Engine::Get().wire_bf16_count();
}
int64_t horovod_wire_int8_count() {
  return Engine::Get().wire_int8_count();
}
int64_t horovod_wire_fp8_count() {
  return Engine::Get().wire_fp8_count();
}
// Effective default wire dtype (WireDtype value; live-tunable knob #6).
int64_t horovod_wire_dtype() {
  return static_cast<int64_t>(Engine::Get().wire_dtype());
}

// Priority scheduling (HOROVOD_PRIORITY_BANDS): the committed band
// width (0 = off — legacy arrival ordering bit-for-bit) and the
// deterministic inversions counter (committed responses dispatched
// after a less-urgent response of the same cycle; 0 by construction
// with bands on).
int64_t horovod_priority_bands() {
  return Engine::Get().priority_bands();
}
int64_t horovod_priority_inversions() {
  return Engine::Get().priority_inversions();
}

// Straggler-tolerance observability (HOROVOD_BACKUP_WORKERS / local
// SGD): the committed over-provisioning, how many partial commits left
// THIS rank out, outer local-SGD syncs noted by the Python policy, and
// sliding-window percentiles of allreduce completion latency
// (enqueue → finish) — the deterministic instrument the straggler gate
// compares between k=0 and k=1 runs.
int64_t horovod_backup_workers() {
  return static_cast<int64_t>(Engine::Get().backup_workers());
}
// HOROVOD_BACKUP_WORKERS=auto: whether auto mode is on, the arming
// ratio threshold (milli-units — the C ABI stays int64-only), and
// whether the coordinator's step-time window currently arms k=1
// (workers report 0; commits reach them inside responses).
int64_t horovod_backup_auto() {
  return Engine::Get().backup_auto() ? 1 : 0;
}
int64_t horovod_backup_auto_ratio_milli() {
  return Engine::Get().backup_auto_ratio_milli();
}
int64_t horovod_backup_armed() {
  return Engine::Get().backup_armed() ? 1 : 0;
}
int64_t horovod_backup_skips() { return Engine::Get().backup_skips(); }
// Link self-healing (HOROVOD_LINK_RETRIES / HOROVOD_LINK_HEAL_TIMEOUT_MS):
// data-channel edges transparently re-established mid-collective, suspects
// that exhausted the retry/deadline budget and escalated to the unchanged
// abort path, sliding-window percentiles of suspect→healed durations, and
// the committed knob values (the coordinator's resolution rides the
// rendezvous ASSIGN, like the channel count).  All counters are provably
// zero under HOROVOD_LINK_RETRIES=0.
int64_t horovod_link_reconnects() {
  return Engine::Get().link_reconnects();
}
int64_t horovod_link_heal_failures() {
  return Engine::Get().link_heal_failures();
}
int64_t horovod_link_heal_ns_p50() {
  return Engine::Get().link_heal_ns_p50();
}
int64_t horovod_link_heal_ns_p99() {
  return Engine::Get().link_heal_ns_p99();
}
int64_t horovod_link_retries() {
  return static_cast<int64_t>(Engine::Get().link_retries());
}
int64_t horovod_link_heal_timeout_ms() {
  return Engine::Get().link_heal_timeout_ms();
}
int64_t horovod_local_sgd_syncs() {
  return Engine::Get().local_sgd_syncs();
}
void horovod_note_local_sgd_sync() { Engine::Get().NoteLocalSgdSync(); }
int64_t horovod_step_time_ns_p50() {
  return Engine::Get().step_time_ns_p50();
}
int64_t horovod_step_time_ns_p99() {
  return Engine::Get().step_time_ns_p99();
}
// Ranks whose data a finished handle's response actually reduced (size
// for a full commit, the participant count for a backup-worker partial
// commit, 0 for a skipped entry): divisor-correct averaging divides by
// this, never blindly by size.
int64_t horovod_result_participants(int64_t handle) {
  return static_cast<int64_t>(Engine::Get().ResultParticipants(handle));
}

// Effective (currently in-force) knob values for stats()["config"]:
// post-autotune, not the env defaults — chunk/fusion/cycle/wave are
// live-tunable, the rest report the committed wiring-time resolution.
int64_t horovod_chunk_bytes() { return Engine::Get().chunk_bytes(); }
int64_t horovod_fusion_threshold() {
  return Engine::Get().fusion_threshold();
}
int64_t horovod_cycle_time_ms() {
  return static_cast<int64_t>(Engine::Get().cycle_time_ms());
}
int64_t horovod_wave_width() {
  return static_cast<int64_t>(Engine::Get().wave_width());
}
int64_t horovod_channel_drivers() {
  return static_cast<int64_t>(Engine::Get().channel_drivers());
}
int64_t horovod_cache_capacity() { return Engine::Get().cache_capacity(); }
int64_t horovod_socket_buf_bytes() {
  return static_cast<int64_t>(Engine::Get().socket_buf_bytes());
}

// TUNE frames applied on this rank; zero under HOROVOD_AUTOTUNE=0 (the
// observable proof that the default path never sees a TUNE frame).
int64_t horovod_tune_trials() { return Engine::Get().tune_trials(); }

// Online-autotuner proposal (coordinator only): queue a knob config for
// the next cycle's epoch-stamped TUNE broadcast; every rank applies it
// between cycles.  Values <= 0 leave that knob unchanged — EXCEPT
// algo_threshold, where 0 is a real value (small path off) and "leave
// unchanged" is < 0; commit != 0 marks the search's final config.
// Returns 0 queued, -1 when not initialized or not the coordinator.
// `priority_bands` < 0 leaves the band width unchanged (0 is real:
// bands off); `fusion_ladder` (ladder_n entries, may be null/0) sets
// band b's fusion threshold where the entry is > 0.  Callers gate on
// the horovod_priority_bands symbol before using this signature (the
// same stale-.so discipline as the wire_dtype extension before it).
int horovod_autotune_set(int64_t chunk_bytes, int64_t fusion_threshold,
                         int64_t cycle_time_ms, int64_t wave_width,
                         int64_t algo_threshold, int64_t wire_dtype,
                         int64_t priority_bands,
                         const int64_t* fusion_ladder, int ladder_n,
                         int commit) {
  std::vector<int64_t> ladder;
  if (fusion_ladder != nullptr && ladder_n > 0) {
    ladder.assign(fusion_ladder, fusion_ladder + ladder_n);
  }
  return Engine::Get().QueueTune(chunk_bytes, fusion_threshold,
                                 cycle_time_ms, wave_width, algo_threshold,
                                 wire_dtype, priority_bands, ladder,
                                 commit != 0);
}

// -- fleet observability plane (HOROVOD_TELEMETRY_CYCLES /
//    HOROVOD_FLIGHT_RECORDER_*) --

// Telemetry cadence in force (0 = off: frames byte-identical to the
// pre-telemetry wire), bytes the TELEM piggyback added to this rank's
// control frames, and stalled-tensor warnings emitted by this process
// (the horovod_stall_warnings_total metric's source).
int64_t horovod_telemetry_cycles() {
  return Engine::Get().telemetry_cycles();
}
int64_t horovod_telem_bytes_tx() { return Engine::Get().telem_bytes_tx(); }
int64_t horovod_stall_warnings() { return Engine::Get().stall_warnings(); }

// Rendezvous-estimated monotonic clock offset to rank 0 (rank0_now ≈
// my_now + offset; 0 on rank 0) — the merged timeline's alignment term.
int64_t horovod_clock_offset_ns() {
  return Engine::Get().clock_offset_ns();
}

// Coordinator quorum-lag percentiles: per committed negotiation, how
// long the LAST voter trailed the second-to-last.  The default
// HOROVOD_BACKUP_WORKERS=auto rule arms from these (rule: 0 = quorum,
// 1 = steptime via HOROVOD_BACKUP_AUTO_RULE).
int64_t horovod_quorum_lag_ns_p50() {
  return Engine::Get().quorum_lag_ns_p50();
}
int64_t horovod_quorum_lag_ns_p99() {
  return Engine::Get().quorum_lag_ns_p99();
}
int64_t horovod_backup_auto_rule() {
  return static_cast<int64_t>(Engine::Get().backup_auto_rule());
}

// Rank 0's fleet table as JSON (per-rank/per-host rows of telemetry
// counter sums, step-time gauges, slowest-rank attribution, quorum-lag
// percentiles).  Fills buf when it fits; ALWAYS returns the required
// byte length (excluding the NUL) so callers can retry with a bigger
// buffer.  Number of rows via horovod_fleet_rows.
int64_t horovod_fleet_json(char* buf, int64_t buflen) {
  std::string json = Engine::Get().FleetJson();
  if (buf != nullptr && buflen > 0) {
    size_t n = std::min(json.size(), static_cast<size_t>(buflen - 1));
    memcpy(buf, json.data(), n);
    buf[n] = '\0';
  }
  return static_cast<int64_t>(json.size());
}
int64_t horovod_fleet_rows() { return Engine::Get().fleet_rows(); }

// Flight recorder: events recorded / dumps written so far, and a manual
// dump trigger (tests, operator tooling).  Dumps land in
// HOROVOD_FLIGHT_RECORDER_DIR as flightrec.rank<r>.json.
int64_t horovod_flight_events() {
  return hvd::GlobalFlightRecorder().events_recorded();
}
int64_t horovod_flight_dumps() {
  return hvd::GlobalFlightRecorder().dumps_written();
}
int horovod_flight_dump(const char* reason) {
  return Engine::Get().FlightDump(reason ? reason : "manual dump");
}
// Python-plane events (checkpoint commits/restores, weight pushes)
// recorded into the same ring as aborts/link events, so postmortem
// merges them into one timeline.  Cycle 0: these events originate
// outside the coordinator's control cycle.
void horovod_flight_note(const char* kind, const char* text) {
  hvd::GlobalFlightRecorder().Record(kind ? kind : "note", 0, "%s",
                                     text ? text : "");
}

// Why the engine aborted, copied into buf (truncated to buflen-1); empty
// while the engine is healthy or after a clean shutdown.  Lets callers
// attach the culprit rank to enqueues attempted AFTER the abort, whose
// handles never existed.
void horovod_abort_reason(char* buf, int buflen) {
  std::string msg = Engine::Get().AbortReason();
  if (buflen <= 0) return;
  size_t n = std::min(msg.size(), static_cast<size_t>(buflen - 1));
  memcpy(buf, msg.data(), n);
  buf[n] = '\0';
}

int horovod_poll(int64_t handle) { return Engine::Get().Poll(handle); }
int horovod_wait(int64_t handle) { return Engine::Get().Wait(handle); }

// Copies the handle's error message into buf (truncated to buflen-1).
void horovod_error_message(int64_t handle, char* buf, int buflen) {
  std::string msg = Engine::Get().ErrorMessage(handle);
  if (buflen <= 0) return;
  size_t n = std::min(msg.size(), static_cast<size_t>(buflen - 1));
  memcpy(buf, msg.data(), n);
  buf[n] = '\0';
}

int64_t horovod_result_ndim(int64_t handle) {
  return Engine::Get().ResultNumDims(handle);
}
int64_t horovod_result_dim(int64_t handle, int i) {
  return Engine::Get().ResultDim(handle, i);
}
int64_t horovod_result_bytes(int64_t handle) {
  return Engine::Get().ResultByteSize(handle);
}
int horovod_copy_result(int64_t handle, void* dst, int64_t nbytes) {
  return Engine::Get().CopyResult(handle, dst, nbytes);
}
void horovod_release_handle(int64_t handle) {
  Engine::Get().ReleaseHandle(handle);
}

}  // extern "C"
