"""Checkpoint/weight-push counters (pure Python, engine-optional).

Follows the ``_SPARSE_COUNT`` idiom from runtime.engine: module-level
counters bumped by the checkpoint plane, merged into
``NativeEngine.stats()`` so telemetry aggregation, the metrics endpoint
and ``--status`` all see them for free — and readable directly via
:func:`checkpoint_stats` in engine-free worlds (world size 1, unit
tests).

``checkpoint_ns_*`` measure the OFF-step-path write latency (host-copy
hand-off to manifest-commit barrier) over a sliding window — the cost a
training step never sees, which is the async writer's whole point.
"""

from __future__ import annotations

import threading
from collections import deque

import numpy as np

__all__ = [
    "note_checkpoint", "note_checkpoint_restore", "note_weight_push",
    "checkpoint_stats",
]

_LOCK = threading.Lock()
_BYTES = 0
_RESTORES = 0
_PUSHES = 0
_LAST_STEP = -1
_LAST_RESTORE_STEP = -1
#: Sliding window of end-to-end shard-write+commit durations (ns).
_NS_WINDOW: deque = deque(maxlen=256)


def note_checkpoint(step: int, nbytes: int, ns: int) -> None:
    """One committed checkpoint on this rank: its step, this rank's
    shard bytes, and the off-step-path write duration."""
    global _BYTES, _LAST_STEP
    with _LOCK:
        _BYTES += int(nbytes)
        _LAST_STEP = max(_LAST_STEP, int(step))
        _NS_WINDOW.append(int(ns))


def note_checkpoint_restore(step: int) -> None:
    """One restore-from-manifest on this rank."""
    global _RESTORES, _LAST_RESTORE_STEP
    with _LOCK:
        _RESTORES += 1
        _LAST_RESTORE_STEP = int(step)


def note_weight_push(n: int = 1) -> None:
    """``n`` completed live trainer→serve weight pushes."""
    global _PUSHES
    with _LOCK:
        _PUSHES += int(n)


def _pct(window, q: float) -> int:
    if not window:
        return 0
    return int(np.percentile(np.asarray(window, dtype=np.int64), q))


def checkpoint_stats() -> dict:
    """The checkpoint plane's slice of ``stats()`` (cumulative counters
    plus current-value gauges; see engine.stats_delta for which keys are
    delta'd vs carried)."""
    with _LOCK:
        window = list(_NS_WINDOW)
        return {
            "checkpoint_bytes": _BYTES,
            "checkpoint_restores": _RESTORES,
            "weight_push_count": _PUSHES,
            "last_checkpoint_step": _LAST_STEP,
            "checkpoint_ns_p50": _pct(window, 50),
            "checkpoint_ns_p99": _pct(window, 99),
        }


def _reset_for_tests() -> None:
    global _BYTES, _RESTORES, _PUSHES, _LAST_STEP, _LAST_RESTORE_STEP
    with _LOCK:
        _BYTES = 0
        _RESTORES = 0
        _PUSHES = 0
        _LAST_STEP = -1
        _LAST_RESTORE_STEP = -1
        _NS_WINDOW.clear()
