"""The unified weight plane: crash-consistent sharded async
checkpoints, elastic resharding restore, and live trainer→serve weight
push.

Three legs over one vocabulary (replicated pytrees + flat sharded
vectors under the engine's committed largest-first split):

- :class:`CheckpointWriter` — per-rank double-buffered async shard
  writes (tmp+rename), a MAX-allreduce commit barrier, and a rank-0
  step-stamped manifest: a kill at ANY instant leaves either the
  previous complete checkpoint set or the new one, never a torn mix
  (writer.py; proven under the ``ckpt-kill`` fault).
- :class:`CheckpointLoader` — reads a world-N manifest into a world-M
  process by re-slicing the flat vectors through ``shard_bounds(n, M)``
  (loader.py); :func:`maybe_restore` wires it into ``run_elastic`` so a
  relaunched fleet resumes from the last durable step instead of 0.
- :class:`WeightPusher` — live weight frames over the serve protocol
  with per-tensor wire policy, hot-swapped by replicas under a
  generation-epoch stamp (push.py; serve/scheduler.py applies them).

See docs/checkpointing.md for the manifest format and durability
contract.
"""

from horovod_tpu.checkpoint.loader import CheckpointLoader
from horovod_tpu.checkpoint.manifest import (CheckpointError,
                                             CheckpointIncompleteError,
                                             latest_manifest)
from horovod_tpu.checkpoint.stats import (checkpoint_stats,
                                          note_checkpoint,
                                          note_checkpoint_restore,
                                          note_weight_push)
from horovod_tpu.checkpoint.writer import (CheckpointConfig,
                                           CheckpointWriter,
                                           parse_ckpt_kill)

__all__ = [
    "CheckpointConfig", "CheckpointWriter", "CheckpointLoader",
    "CheckpointError", "CheckpointIncompleteError", "latest_manifest",
    "parse_ckpt_kill", "checkpoint_stats", "note_checkpoint",
    "note_checkpoint_restore", "note_weight_push", "maybe_restore",
    "jax_capture", "jax_restore", "torch_capture", "torch_restore",
    "WeightPusher", "encode_leaves", "decode_leaves", "apply_leaves",
]

from horovod_tpu.checkpoint.elastic import maybe_restore  # noqa: E402
from horovod_tpu.checkpoint.frontend import (jax_capture,  # noqa: E402
                                             jax_restore,
                                             torch_capture,
                                             torch_restore)
from horovod_tpu.checkpoint.push import (WeightPusher,  # noqa: E402
                                         apply_leaves, decode_leaves,
                                         encode_leaves)
