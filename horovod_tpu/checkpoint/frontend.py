"""Frontend adapters: jax/optax and torch state ↔ the weight plane.

The writer/loader core speaks (replicated pytrees + flat sharded
vectors); these helpers translate each frontend's optimizer into that
vocabulary so BOTH frontends get crash-consistent sharded checkpoints
and elastic resharding restore from the same code path.

Sharding classification is structural, matching how the optimizers are
built: under a ``FlatSharder`` every per-element state leaf (optax mu /
nu / trace, the torch fp32 master, torch momentum buffers) is a 1-D
vector of exactly ``sharder.count`` elements — those become flat
sharded entries keyed by their deterministic walk path; everything else
(step counters, hyperparameters, the replicated model params) rides the
replicated tree.  Restore runs the SAME walk over a freshly initialized
state at the new world, so each classification decision is re-derived
identically — the geometry is never trusted from the old world, only
``n`` is.

All framework imports are function-local: importing this module pulls
in neither jax nor torch.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from horovod_tpu.checkpoint.loader import CheckpointLoader
from horovod_tpu.elastic.state import _walk

__all__ = [
    "jax_capture", "jax_restore",
    "torch_capture", "torch_restore",
]


# -- jax / optax --

def jax_capture(opt, params, opt_state, step: int,
                extra: Optional[dict] = None):
    """``(state, sharded)`` for ``CheckpointWriter.save`` from a jax
    ``DistributedOptimizer`` (sharded or not), its state, and the
    params."""
    state = {"params": params, "opt_state": opt_state, "step": int(step)}
    if extra:
        state.update(extra)
    sharded: Dict[str, Tuple[np.ndarray, int]] = {}
    sh = getattr(opt, "_sharder", None)
    if sh is not None and sh.count > 0:

        def classify(path, leaf):
            arr = np.asarray(leaf)
            if arr.ndim == 1 and arr.size == sh.count:
                sharded[path] = (arr, sh.n)
            return leaf

        _walk(opt_state, "opt_state", classify)
    return state, sharded


def jax_restore(opt, params_template, loader: CheckpointLoader,
                step_slot: str = "step"):
    """``(params, opt_state, step)`` rebuilt at the CURRENT world from a
    checkpoint written at any world size.  ``opt.init`` anchors the new
    shard geometry first (the ``ShardResizeError`` recipe); the loader
    then fills shard-sized leaves from the resliced flat vectors and
    everything else bit-exactly from the replicated tree."""
    params = loader.restore_tree(params_template, "params")
    opt_state = opt.init(params)
    opt_state = loader.restore_tree(opt_state, "opt_state")
    step = int(np.asarray(loader.restore_tree(0, step_slot)))
    return params, opt_state, step


# -- torch --

def _torch_shard_groups(opt):
    """(group, inner-param, sharder) triples of a sharded torch
    optimizer, or None for a plain/hook-wrapped one."""
    groups = getattr(opt, "_groups", None)
    shard_opt = getattr(opt, "_shard_opt", None)
    if not groups or shard_opt is None:
        return None
    out = []
    for gi, g in enumerate(groups):
        inner_param = shard_opt.param_groups[gi]["params"][0]
        out.append((g, inner_param, g["sharder"]))
    return out


def torch_capture(opt, model, step: int, extra: Optional[dict] = None):
    """``(state, sharded)`` from a torch optimizer (the sharded
    ZeRO wrapper or any plain optimizer) and its model."""
    import torch

    model_np = {k: v.detach().cpu().numpy()
                for k, v in model.state_dict().items()}
    state = {"model": model_np, "step": int(step)}
    if extra:
        state.update(extra)
    sharded: Dict[str, Tuple[np.ndarray, int]] = {}
    triples = _torch_shard_groups(opt)
    if triples is None:
        # Unsharded: the whole optimizer state is replicated (every rank
        # holds an identical copy after the averaged allreduce step).
        state["torch_opt"] = opt.state_dict()
        return state, sharded
    scalars: Dict[str, object] = {}
    for gi, (g, inner_param, sh) in enumerate(triples):
        sharded[f"zero.master.{gi}"] = (
            g["master"].detach().cpu().numpy(), sh.n)
        for key, val in opt._shard_opt.state.get(inner_param, {}).items():
            if torch.is_tensor(val) and val.numel() == sh.count:
                sharded[f"zero.opt.{gi}.{key}"] = (
                    val.detach().cpu().to(torch.float32).numpy(), sh.n)
            else:
                scalars[f"{gi}.{key}"] = (
                    val.item() if torch.is_tensor(val) else val)
    state["zero_scalars"] = scalars
    return state, sharded


def torch_restore(opt, model, loader: CheckpointLoader,
                  step_slot: str = "step") -> int:
    """Fill ``model`` and ``opt`` (built for the CURRENT world) in place
    from the checkpoint; returns the restored step.  Sharded masters and
    per-element optimizer state are resliced through the new-world
    bounds; lazily-created torch state entries are materialized so a
    restore into a never-stepped optimizer works."""
    import torch

    model_np = {k: v.detach().cpu().numpy()
                for k, v in model.state_dict().items()}
    restored = loader.restore_tree(model_np, "model")
    model.load_state_dict({
        k: torch.from_numpy(np.ascontiguousarray(v)).reshape(
            model.state_dict()[k].shape).to(model.state_dict()[k].dtype)
        for k, v in restored.items()
    })
    triples = _torch_shard_groups(opt)
    if triples is None:
        if "torch_opt" in loader.slot_names():
            sd = loader.restore_tree(opt.state_dict(), "torch_opt")
            # restore_tree walks the TARGET, and a never-stepped torch
            # optimizer has an empty per-param state dict — rebuild the
            # state entries from the saved paths instead, re-tensorizing
            # buffers (torch kernels call tensor methods on them).
            pref = "torch_opt.state."
            st: Dict[int, dict] = {}
            for p in loader.replicated_paths():
                if not p.startswith(pref):
                    continue
                idx, _, key = p[len(pref):].partition(".")
                val = np.asarray(loader.read_replicated(p))
                st.setdefault(int(idx), {})[key] = (
                    torch.from_numpy(np.ascontiguousarray(val))
                    if val.ndim else val[()].item())
            sd["state"] = st
            opt.load_state_dict(sd)
        return int(np.asarray(loader.restore_tree(0, step_slot)))
    scalar_prefix = "zero_scalars."
    scalars = {p[len(scalar_prefix):]: loader.read_replicated(p)
               for p in loader.replicated_paths()
               if p.startswith(scalar_prefix)}
    for gi, (g, inner_param, sh) in enumerate(triples):
        with torch.no_grad():
            g["master"].copy_(torch.from_numpy(np.ascontiguousarray(
                loader.read_flat(f"zero.master.{gi}", sh.offset,
                                 sh.count))))
        opt_keys = [name[len(f"zero.opt.{gi}."):]
                    for name in loader.sharded_names()
                    if name.startswith(f"zero.opt.{gi}.")]
        st = opt._shard_opt.state.setdefault(inner_param, {})
        for key in opt_keys:
            st[key] = torch.from_numpy(np.ascontiguousarray(
                loader.read_flat(f"zero.opt.{gi}.{key}", sh.offset,
                                 sh.count))).to(g["master"].dtype)
        for skey, val in scalars.items():
            sgi, _, key = skey.partition(".")
            if int(sgi) == gi:
                st[key] = np.asarray(val).reshape(())[()].item()
        # Params follow the restored master (ZeRO invariant: the fp32
        # master is authoritative; replicate it back through the same
        # allgather the step uses so every rank's params agree even
        # when the model state_dict predates the master's step).
        full = sh.gather_updates(g["master"].detach().cpu().numpy())
        with torch.no_grad():
            off = 0
            for p, numel, shape in zip(g["params"], g["numels"],
                                       g["shapes"]):
                chunk = torch.from_numpy(
                    np.ascontiguousarray(full[off:off + numel]))
                p.data.copy_(chunk.reshape(shape).to(p.dtype))
                off += numel
    return int(np.asarray(loader.restore_tree(0, step_slot)))
