"""Checkpoint manifest: the commit record of the sharded weight plane.

Layout on disk (``HOROVOD_CHECKPOINT_DIR``)::

    <dir>/ckpt-<step>.manifest.json          # rank 0, tmp+rename, LAST
    <dir>/step-<step>/shard-<r>-of-<N>.npz   # per rank, tmp+rename

Durability contract: a manifest is written by rank 0 ONLY after a
MAX-allreduce barrier confirmed every rank's shard file landed (renamed
into place).  A manifest therefore IMPLIES a complete, loadable shard
set; readers trust nothing else.  Retention deletes in the reverse
order (manifest first, then shards) so the implication survives a crash
mid-cleanup.  A SIGKILL at any instant leaves either the previous
complete set or the new one — a half-written ``.tmp`` shard is invisible
(never renamed) and a shard set without its manifest is ignored.

Manifest fields (format 1):

- ``step`` / ``epoch`` / ``world_size``: the committed training step,
  the membership epoch the save ran under, and the world N it sharded
  across.
- ``shards``: one entry per rank — relative file path and byte size
  (size is re-checked by :func:`validate`, catching truncation).
- ``sharded``: the flat ZeRO vectors — name (a state walk path, see
  loader), total length ``n``, dtype, npz key, and the per-rank
  ``(offset, count)`` bounds at world N.  A world-M restore re-slices
  these through ``shard_bounds(n, M)`` — the resize semantics that pair
  with ``ShardResizeError``.
- ``replicated``: the walk paths of the replicated pytree leaves, all
  stored in rank 0's shard file (identical on every rank, so one copy).
- ``meta``: caller dict (e.g. ``{"model": "tiny"}`` for serve).
"""

from __future__ import annotations

import json
import os
import re
from typing import List, Optional, Tuple

__all__ = [
    "FORMAT_VERSION", "CheckpointError", "CheckpointIncompleteError",
    "manifest_path", "shard_dir", "shard_file", "list_manifest_steps",
    "read_manifest", "validate", "latest_manifest",
]

FORMAT_VERSION = 1

_MANIFEST_RE = re.compile(r"^ckpt-(\d+)\.manifest\.json$")


class CheckpointError(RuntimeError):
    """Malformed or unreadable checkpoint data."""


class CheckpointIncompleteError(CheckpointError):
    """A manifest references shard files that are missing or truncated:
    the set is incomplete (e.g. hand-deleted shards, a non-shared
    filesystem, or a manifest copied without its shard directory).
    Loaders refuse it rather than resume from a torn mix; pick an older
    complete set via :func:`latest_manifest`."""


def manifest_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"ckpt-{int(step)}.manifest.json")


def shard_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step-{int(step)}")


def shard_file(directory: str, step: int, rank: int, size: int) -> str:
    return os.path.join(shard_dir(directory, step),
                        f"shard-{int(rank)}-of-{int(size)}.npz")


def list_manifest_steps(directory: str) -> List[int]:
    """Steps with a manifest file present, ascending (completeness NOT
    checked — see :func:`validate` / :func:`latest_manifest`)."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    steps = []
    for name in names:
        m = _MANIFEST_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def read_manifest(directory: str, step: int) -> dict:
    path = manifest_path(directory, step)
    try:
        with open(path, "r", encoding="utf-8") as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointError(f"unreadable manifest {path}: {e}") from e
    if not isinstance(man, dict) or man.get("format") != FORMAT_VERSION:
        raise CheckpointError(
            f"manifest {path} has unsupported format "
            f"{man.get('format') if isinstance(man, dict) else man!r} "
            f"(want {FORMAT_VERSION})")
    return man


def validate(directory: str, man: dict) -> None:
    """Raise :class:`CheckpointIncompleteError` unless every shard file
    the manifest references exists with the recorded byte size."""
    missing = []
    for entry in man.get("shards", []):
        path = os.path.join(directory, entry["file"])
        try:
            actual = os.path.getsize(path)
        except OSError:
            missing.append(f"{entry['file']} (missing)")
            continue
        if int(entry.get("bytes", -1)) not in (-1, actual):
            missing.append(
                f"{entry['file']} (truncated: {actual} != "
                f"{entry['bytes']} bytes)")
    if missing:
        raise CheckpointIncompleteError(
            f"checkpoint step {man.get('step')} in {directory} is "
            f"incomplete — refusing to load a torn set: "
            + ", ".join(missing)
            + ". Delete the stale manifest (or restore the missing "
            "shards) to fall back to the previous complete checkpoint.")


def latest_manifest(directory: str) -> Optional[Tuple[dict, int]]:
    """The newest COMPLETE checkpoint: scan manifests newest-first,
    skip any whose shard set fails :func:`validate` (a stale manifest
    must never mask an older loadable set), return ``(manifest, step)``
    or ``None``."""
    for step in reversed(list_manifest_steps(directory)):
        try:
            man = read_manifest(directory, step)
            validate(directory, man)
        except CheckpointError:
            continue
        return man, step
    return None
