"""Live trainer→serve weight push: wire codec + pusher client.

The trainer ships its current model tree to the serve router as ONE
JSON-lines ``weights`` frame; the router fans it out to every replica
(and replays the latest frame to a relaunched replica, so a rejoin
never serves boot-time params); each replica hot-swaps between decode
iterations under a monotonic generation-epoch stamp
(serve/scheduler.py ``swap_weights``).

Wire policy mirrors the PR 15 per-tensor rules: small / 0-1-D leaves
(norm scales, biases — the "pinned" class) always ride fp32; bulk
matrices ride the requested compressed wire (``int8`` absmax-scaled by
default, ``fp8``/``bf16`` via ml_dtypes, ``fp32`` for lossless pushes).
Decode always reconstructs float32; the replica casts into its own
param dtype when swapping.

Deliberately engine-free: the push rides the serve plane's TCP
protocol, not the collective engine — a trainer can push into a fleet
it is not a member of.
"""

from __future__ import annotations

import base64
from typing import Dict, List, Optional

import numpy as np

from horovod_tpu.checkpoint.stats import note_weight_push
from horovod_tpu.elastic.state import _walk

__all__ = [
    "PIN_MIN_ELEMS", "encode_leaves", "decode_leaves", "apply_leaves",
    "WeightPusher",
]

#: Leaves below this element count stay fp32 on the wire (the pinned
#: class of the wire-policy rules: quantization noise on tiny tensors
#: is all signal, and the bytes saved are nothing).
PIN_MIN_ELEMS = 2048

_WIRES = ("fp32", "bf16", "fp8", "int8")


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode(
        "ascii")


def _unb64(data: str, dtype, shape) -> np.ndarray:
    return np.frombuffer(base64.b64decode(data),
                         dtype=dtype).reshape(shape).copy()


def encode_leaves(tree, *, wire: str = "int8",
                  min_elems: int = PIN_MIN_ELEMS) -> List[dict]:
    """Per-leaf wire frames for every float leaf of ``tree`` (walked in
    the deterministic sorted-key order, paths rooted at ``w``).
    Non-float leaves are shipped verbatim (fp32-rule equivalent)."""
    if wire not in _WIRES:
        raise ValueError(f"wire {wire!r} not in {_WIRES}")
    frames: List[dict] = []

    def visit(path, leaf):
        arr = np.asarray(leaf)
        frame = {"path": path, "shape": list(arr.shape),
                 "dtype": str(arr.dtype)}
        pinned = (not np.issubdtype(arr.dtype, np.floating)
                  or arr.ndim <= 1 or arr.size < min_elems)
        w = "fp32" if pinned or wire == "fp32" else wire
        x = arr.astype(np.float32, copy=False)
        if w == "fp32":
            frame.update(wire="fp32", data=_b64(
                arr if not np.issubdtype(arr.dtype, np.floating) else x))
            if not np.issubdtype(arr.dtype, np.floating):
                frame["wire"] = "raw"
        elif w == "bf16":
            import ml_dtypes

            frame.update(wire="bf16",
                         data=_b64(x.astype(ml_dtypes.bfloat16)))
        elif w == "fp8":
            import ml_dtypes

            absmax = float(np.max(np.abs(x))) if x.size else 0.0
            scale = absmax / 448.0 if absmax > 0 else 1.0
            frame.update(wire="fp8", scale=scale, data=_b64(
                (x / scale).astype(ml_dtypes.float8_e4m3fn)))
        else:  # int8 absmax
            absmax = float(np.max(np.abs(x))) if x.size else 0.0
            scale = absmax / 127.0 if absmax > 0 else 1.0
            frame.update(wire="int8", scale=scale, data=_b64(
                np.clip(np.rint(x / scale), -127, 127).astype(np.int8)))
        frames.append(frame)
        return leaf

    _walk(tree, "w", visit)
    return frames


def decode_leaves(frames: List[dict]) -> Dict[str, np.ndarray]:
    """``{path: array}`` — float wires reconstruct float32, ``raw``
    keeps the original dtype."""
    out: Dict[str, np.ndarray] = {}
    for f in frames:
        shape = tuple(f["shape"])
        w = f["wire"]
        if w == "raw":
            arr = _unb64(f["data"], np.dtype(f["dtype"]), shape)
        elif w == "fp32":
            arr = _unb64(f["data"], np.float32, shape)
        elif w == "bf16":
            import ml_dtypes

            arr = _unb64(f["data"], ml_dtypes.bfloat16, shape).astype(
                np.float32)
        elif w == "fp8":
            import ml_dtypes

            arr = _unb64(f["data"], ml_dtypes.float8_e4m3fn,
                         shape).astype(np.float32) * f.get("scale", 1.0)
            arr = arr.astype(np.float32)
        elif w == "int8":
            arr = (_unb64(f["data"], np.int8, shape).astype(np.float32)
                   * f.get("scale", 1.0)).astype(np.float32)
        else:
            raise ValueError(f"unknown wire {w!r} in weights frame")
        out[f["path"]] = arr
    return out


def apply_leaves(target, by_path: Dict[str, np.ndarray]):
    """Rebuild ``target`` with every leaf whose walk path appears in
    ``by_path`` replaced (cast to the leaf's dtype); untouched leaves
    pass through — partial pushes update only what they carry."""
    def visit(path, leaf):
        new = by_path.get(path)
        if new is None:
            return leaf
        arr = np.asarray(leaf)
        if tuple(new.shape) != tuple(arr.shape):
            raise ValueError(
                f"weights push leaf '{path}' has shape "
                f"{tuple(new.shape)}, replica expects {tuple(arr.shape)}"
                " — pushed model does not match the serving model")
        return np.asarray(new).astype(arr.dtype, copy=False)

    return _walk(target, "w", visit)


class WeightPusher:
    """Trainer-side client: encode the model tree and push it to the
    serve router (which fans out to every replica and caches the frame
    for rejoins).

    >>> pusher = WeightPusher("127.0.0.1", router_port)
    >>> ack = pusher.push(variables)          # epoch auto-increments
    >>> pusher.close()
    """

    def __init__(self, host: str, port: int, timeout: float = 120.0):
        from horovod_tpu.serve.server import ServeClient

        self._cli = ServeClient(host, port, timeout=timeout)
        self._epoch = 0

    def push(self, tree, *, epoch: Optional[int] = None,
             wire: str = "int8", min_elems: int = PIN_MIN_ELEMS) -> dict:
        if epoch is None:
            self._epoch += 1
            epoch = self._epoch
        else:
            self._epoch = int(epoch)
        frames = encode_leaves(tree, wire=wire, min_elems=min_elems)
        ack = self._cli.push_weights(frames, epoch)
        note_weight_push()
        return ack

    def close(self) -> None:
        self._cli.close()
