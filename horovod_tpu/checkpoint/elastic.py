"""Disk-restore hook for the elastic driver.

``run_elastic`` calls :func:`maybe_restore` at every (re-)entry when
``HOROVOD_CHECKPOINT_DIR`` is set, BEFORE ``ElasticState.sync()``:

- rank 0 (the sync authority — its values are what the broadcast
  imposes on everyone) compares its in-memory progress against the
  newest complete manifest and decides;
- the decision is agreed via a MAX-allreduce flag (only rank 0
  contributes a nonzero value), so a fresh relaunch and a survivor
  take the same branch;
- on restore, EVERY rank loads the replicated slots from disk — the
  subsequent ``sync()`` then broadcasts byte-identical values anyway,
  making the result independent of who restored from where.

Memory wins when it is ahead: survivors that committed past the last
durable checkpoint keep their (newer) state and ``sync()`` repairs the
relaunched rank, exactly as before this plane existed.  Disk wins only
when rank 0 itself lost progress (full-fleet relaunch, or rank 0 died)
— the case that used to mean "back to step 0".
"""

from __future__ import annotations

import numpy as np

from horovod_tpu.checkpoint.loader import CheckpointLoader
from horovod_tpu.checkpoint.manifest import latest_manifest
from horovod_tpu.checkpoint.stats import note_checkpoint_restore
from horovod_tpu.runtime import engine_or_none
from horovod_tpu.runtime.engine import flight_note

__all__ = ["maybe_restore"]


def _memory_step(state) -> int:
    """The state's own notion of progress: an integer ``step`` slot if
    it has one, else 0 (disk then wins whenever a manifest exists and
    rank 0 cannot prove it is ahead)."""
    step = getattr(state, "step", None)
    if isinstance(step, (bool, np.bool_)):
        return 0
    if isinstance(step, (int, np.integer)):
        return int(step)
    return 0


def maybe_restore(state, directory: str):
    """Restore ``state``'s slots from the newest complete checkpoint in
    ``directory`` if (and only if) it is ahead of rank 0's in-memory
    progress.  Collective when the engine is up (all ranks must call
    it together — run_elastic does).  Returns the restored step, or
    ``None`` when memory won / no checkpoint exists."""
    from horovod_tpu.common.basics import basics

    found = latest_manifest(directory)
    eng = engine_or_none() if basics.is_initialized() else None
    disk_step = found[1] if found is not None else -1
    want = 1 if (found is not None
                 and disk_step > _memory_step(state)) else 0
    if eng is not None:
        # Only rank 0's vote counts (it is the sync() authority); the
        # MAX over {rank0: want, others: 0} IS rank 0's decision, and
        # riding allreduce keeps this a single well-named collective.
        mine = want if basics.rank() == 0 else 0
        out = eng.allreduce(np.array([mine], dtype=np.float64),
                            red_op="max", name="ckpt.restore.decide")
        want = int(out[0])
    if not want or found is None:
        return None
    loader = CheckpointLoader(directory, step=disk_step)
    try:
        for k in state._keys:
            setattr(state, k,
                    loader.restore_tree(getattr(state, k), k,
                                        missing="keep"))
        state.commit()
    finally:
        loader.close()
    note_checkpoint_restore(disk_step)
    flight_note("ckpt", f"restore step={disk_step} "
                        f"world={loader.world_size}->"
                        f"{basics.size() if basics.is_initialized() else 1}")
    return disk_step
