"""Crash-consistent sharded async checkpoint writer.

Each rank snapshots its state OFF the step path: ``save()`` host-copies
the pytrees (the double buffer — the training step mutates the live
arrays freely while the writer thread serializes the copy), hands the
snapshot to a dedicated writer thread, and returns.  The writer:

1. serializes this rank's shard to ``step-<S>/shard-<r>-of-<N>.npz.tmp``
   and renames it into place (a kill mid-write leaves only an invisible
   ``.tmp``),
2. joins a MAX-allreduce barrier (``ckpt.commit.s<S>``) where every
   rank contributes its failure flag — the reduced max is 0 only when
   EVERY shard landed,
3. rank 0 then writes the step-stamped manifest, tmp+rename — the
   commit point,
4. applies retention (``HOROVOD_CHECKPOINT_KEEP``), deleting stale
   manifests BEFORE their shard dirs so "manifest ⇒ complete set"
   survives a crash mid-cleanup.

A rank SIGKILLed mid-write (or the injected ``ckpt-kill`` fault) never
reaches the barrier; the survivors' barrier collective aborts with
``HorovodInternalError``, the manifest is never written, and the
previous complete checkpoint remains the durable state — the torn-mix
impossibility the fault-marked tests prove.

State model: ``state`` is a dict of named slots (or an ``ElasticState``,
whose tracked slots are used), each an arbitrary pytree walked in the
deterministic sorted-key order of ``elastic.state._walk``.  ``sharded``
maps a walk path (or any stable name) to ``(local_shard, n)`` — this
rank's window of a flat length-``n`` ZeRO vector under the committed
largest-first split.  Paths named in ``sharded`` (and any ``exclude``
prefixes) are skipped by the replicated writer; everything else is
saved once, from rank 0's file.
"""

from __future__ import annotations

import io
import os
import signal
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from horovod_tpu.checkpoint import manifest as mf
from horovod_tpu.checkpoint.stats import note_checkpoint
from horovod_tpu.elastic.state import _host_copy, _walk
from horovod_tpu.runtime import engine_or_none
from horovod_tpu.runtime.engine import HorovodInternalError, flight_note

__all__ = ["CheckpointConfig", "CheckpointWriter", "parse_ckpt_kill"]


def _int_env(raw: Optional[str], default: int) -> int:
    try:
        return int(raw) if raw not in (None, "") else default
    except ValueError:
        return default


class CheckpointConfig:
    """The ``HOROVOD_CHECKPOINT_*`` knobs (all lenient-parsed like the
    rest of the env surface; see autotune/config.py for --print-config
    rows)."""

    def __init__(self, directory: Optional[str] = None,
                 interval_steps: Optional[int] = None,
                 keep: Optional[int] = None, environ=os.environ):
        env_dir = environ.get("HOROVOD_CHECKPOINT_DIR", "").strip()
        self.directory = directory if directory is not None else (
            env_dir or None)
        self.interval_steps = max(1, interval_steps if interval_steps
                                  is not None else _int_env(
                                      environ.get(
                                          "HOROVOD_CHECKPOINT_INTERVAL_STEPS"),
                                      50))
        self.keep = max(1, keep if keep is not None else _int_env(
            environ.get("HOROVOD_CHECKPOINT_KEEP"), 2))

    @property
    def enabled(self) -> bool:
        return bool(self.directory)


# -- ckpt-kill fault schedule (Python-owned leg of HOROVOD_FAULT_INJECT) --

_CKPT_KILL_FIRED = False


def _strict_int(tok: str) -> Optional[int]:
    """Mirror the C++ parser's strtol-with-endp validation: the whole
    token must be a (signed) decimal integer, else the entry is a typo
    and is IGNORED (parity with cpp/engine.cc)."""
    tok = tok.strip()
    if not tok:
        return None
    body = tok[1:] if tok[0] in "+-" else tok
    if not body.isdigit():
        return None
    return int(tok)


def parse_ckpt_kill(raw: Optional[str], rank: int) -> Optional[int]:
    """First ``<rank>:<step>:ckpt-kill`` entry of the shared
    ``HOROVOD_FAULT_INJECT`` schedule matching ``rank``; returns the arm
    step (``-2`` for ``*`` = first checkpoint) or ``None``.  The engine
    parser accepts the kind silently and leaves firing to us — the kill
    must land mid-shard-write, which only the writer can time."""
    if not raw:
        return None
    for token in raw.split(","):
        fields = token.split(":")
        if len(fields) < 3:
            continue
        frank = _strict_int(fields[0])
        if frank is None or frank != rank:
            continue
        step_tok = fields[1].strip()
        fstep = -2 if step_tok == "*" else _strict_int(step_tok)
        if fstep is None:
            continue
        if fields[2].strip() == "ckpt-kill":
            return fstep
    return None


def _maybe_fire_ckpt_kill(arm_step: Optional[int], step: int,
                          partial_file) -> None:
    """SIGKILL this process mid-shard-write: called after the tmp file
    holds a PARTIAL serialization (flushed so the torn bytes are really
    on disk).  One-shot per process, like the engine's fault_fired_."""
    global _CKPT_KILL_FIRED
    if arm_step is None or _CKPT_KILL_FIRED:
        return
    if arm_step != -2 and step < arm_step:
        return
    _CKPT_KILL_FIRED = True
    partial_file.flush()
    os.fsync(partial_file.fileno())
    print(f"[hvd] FAULT INJECT: ckpt-kill at step {step} "
          "(SIGKILL mid-shard-write)", flush=True)
    os.kill(os.getpid(), signal.SIGKILL)


class CheckpointWriter:
    """Async double-buffered per-rank shard writer + rank-0 committer.

    >>> w = CheckpointWriter(directory)         # or env-configured
    >>> w.maybe_save(step, state, sharded)      # interval-gated
    >>> w.wait()                                # drain (tests/shutdown)
    >>> w.close()

    ``save()`` is collective ONLY in the sense that every rank must
    eventually save the same step (the commit barrier rendezvous); the
    call itself returns after the host copy.  Latest-wins: a save
    arriving while the writer is busy replaces any queued snapshot —
    under backpressure the plane drops intermediate checkpoints, never
    blocks the step path.
    """

    def __init__(self, directory: Optional[str] = None, *,
                 interval_steps: Optional[int] = None,
                 keep: Optional[int] = None,
                 meta: Optional[dict] = None):
        self.config = CheckpointConfig(directory, interval_steps, keep)
        if not self.config.enabled:
            raise ValueError(
                "CheckpointWriter needs a directory (argument or "
                "HOROVOD_CHECKPOINT_DIR)")
        from horovod_tpu.common.basics import basics

        self.rank = basics.rank() if basics.is_initialized() else 0
        self.size = basics.size() if basics.is_initialized() else 1
        self.meta = dict(meta or {})
        self.last_committed_step = -1
        self.last_error: Optional[BaseException] = None
        self._kill_step = parse_ckpt_kill(
            os.environ.get("HOROVOD_FAULT_INJECT"), self.rank)
        os.makedirs(self.config.directory, exist_ok=True)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: Optional[tuple] = None
        self._busy = False
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name=f"ckpt-writer-r{self.rank}", daemon=True)
        self._thread.start()

    # -- producer side (training thread) --

    def maybe_save(self, step: int, state, sharded=None) -> bool:
        """Interval-gated :meth:`save` (every ``interval_steps``-th
        step, counting from step ``interval_steps``)."""
        if step <= 0 or step % self.config.interval_steps != 0:
            return False
        self.save(step, state, sharded)
        return True

    def save(self, step: int, state, sharded=None) -> None:
        """Snapshot now (host copies — the double buffer), write async.

        A stored writer-thread failure is SHED here, not raised: a
        failed attempt usually means a peer died mid-write (the barrier
        aborted) — raising would make only the SURVIVING ranks skip
        this save while a relaunched rank performs it, and the next
        commit barrier would never rendezvous.  ``wait()`` still
        re-raises, so tests and shutdown paths see persistent failures
        (disk full) instead of looping silently."""
        self.last_error = None
        slots = self._slots_of(state)
        snap = {k: _host_copy(v) for k, v in slots.items()}
        sh: Dict[str, Tuple[np.ndarray, int]] = {}
        for name, (shard, n) in (sharded or {}).items():
            arr = np.array(np.asarray(shard), copy=True).ravel()
            sh[name] = (arr, int(n))
        with self._cv:
            if self._closed:
                raise RuntimeError("CheckpointWriter is closed")
            self._pending = (int(step), snap, sh)
            self._cv.notify_all()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until the writer is idle with nothing queued; re-raise
        a writer-thread failure."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._pending is not None or self._busy:
                rem = None if deadline is None else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    raise TimeoutError("checkpoint writer did not drain")
                self._cv.wait(rem)
        if self.last_error is not None:
            err, self.last_error = self.last_error, None
            raise err

    def close(self, *, drain: bool = True) -> None:
        if drain and self._thread.is_alive():
            try:
                self.wait(timeout=120)
            except TimeoutError:
                pass
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=10)

    @staticmethod
    def _slots_of(state) -> dict:
        if hasattr(state, "_keys"):  # ElasticState duck type
            return {k: getattr(state, k) for k in state._keys}
        if not isinstance(state, dict):
            raise TypeError(
                "state must be a dict of named slots or an ElasticState")
        return dict(state)

    # -- writer thread --

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._pending is None and not self._closed:
                    self._cv.wait()
                if self._pending is None and self._closed:
                    return
                step, snap, sh = self._pending
                self._pending = None
                self._busy = True
            try:
                self._write_and_commit(step, snap, sh)
            except BaseException as e:  # noqa: BLE001 — surfaced on wait()
                self.last_error = e
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _write_and_commit(self, step: int, snap: dict, sh: dict) -> None:
        t0 = time.monotonic_ns()
        directory = self.config.directory
        from horovod_tpu.common.basics import basics as _b

        if _b.is_initialized():
            # Re-read the identity per attempt: an elastic re-rendezvous
            # may have resized the world or renumbered this rank since
            # the writer was constructed.
            self.rank, self.size = _b.rank(), _b.size()
        # The begin/commit note pair is what the postmortem's "died at
        # step S, last durable step C" line reads out of the merged
        # flight rings — a begin with no commit marks the torn attempt.
        flight_note("ckpt", f"begin step={step} world={self.size}")
        failed = 0
        nbytes = 0
        sharded_meta, replicated_paths = [], []
        try:
            nbytes, sharded_meta, replicated_paths = self._write_shard(
                step, snap, sh)
        except Exception as e:  # noqa: BLE001 — reported via the barrier
            failed = 1
            self.last_error = e
        from horovod_tpu.common.basics import basics

        eng = engine_or_none() if basics.is_initialized() else None
        if eng is not None:
            # The commit barrier: MAX over every rank's failure flag.
            # A rank that died mid-write never enqueues — the collective
            # aborts, no manifest, previous checkpoint stays durable.
            out = eng.allreduce(np.array([failed], dtype=np.float64),
                                red_op="max", name=f"ckpt.commit.s{step}")
            failed = int(out[0])
        if failed:
            raise HorovodInternalError(
                f"checkpoint step {step}: a rank failed to write its "
                "shard; commit aborted (previous checkpoint remains "
                "durable)")
        if self.rank == 0:
            self._commit_manifest(step, nbytes, sharded_meta,
                                  replicated_paths)
        self.last_committed_step = step
        ns = time.monotonic_ns() - t0
        note_checkpoint(step, nbytes, ns)
        flight_note("ckpt", f"commit step={step} bytes={nbytes} "
                            f"world={self.size}")
        if self.rank == 0:
            self._apply_retention()

    def _write_shard(self, step: int, snap: dict, sh: dict):
        """Serialize this rank's npz (tmp+rename).  Returns (bytes,
        sharded manifest entries, replicated path list)."""
        directory = self.config.directory
        sdir = mf.shard_dir(directory, step)
        os.makedirs(sdir, exist_ok=True)
        arrays: Dict[str, np.ndarray] = {}
        sharded_meta = []
        for i, (name, (shard, n)) in enumerate(sorted(sh.items())):
            from horovod_tpu.runtime.sharded import shard_bounds

            bounds = shard_bounds(n, self.size)
            off, cnt = bounds[self.rank]
            if shard.size != cnt:
                raise ValueError(
                    f"sharded entry '{name}': local shard has "
                    f"{shard.size} elements but rank {self.rank}/"
                    f"{self.size} owns {cnt} of n={n}")
            key = f"sh.{i}"
            arrays[key] = shard
            sharded_meta.append({
                "name": name, "n": n, "dtype": str(shard.dtype),
                "key": key, "bounds": [list(b) for b in bounds],
            })
        replicated_paths = []
        if self.rank == 0:
            skip = set(sh)

            def collect(path, leaf):
                if path not in skip:
                    arrays[f"rep.{len(replicated_paths)}"] = np.asarray(leaf)
                    replicated_paths.append(path)
                return leaf

            for k in sorted(snap):
                _walk(snap[k], k, collect)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        payload = buf.getvalue()
        final = mf.shard_file(directory, step, self.rank, self.size)
        tmp = final + ".tmp"
        with open(tmp, "wb") as f:
            # Two-phase write: the injected ckpt-kill fires between the
            # halves, leaving a REAL torn tmp file on disk — the case the
            # durability contract must shrug off.
            half = max(1, len(payload) // 2)
            f.write(payload[:half])
            _maybe_fire_ckpt_kill(self._kill_step, step, f)
            f.write(payload[half:])
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        return len(payload), sharded_meta, replicated_paths

    def _commit_manifest(self, step: int, nbytes: int, sharded_meta,
                         replicated_paths) -> None:
        from horovod_tpu.common.basics import basics

        directory = self.config.directory
        eng = engine_or_none() if basics.is_initialized() else None
        shards = []
        for r in range(self.size):
            path = mf.shard_file(directory, step, r, self.size)
            shards.append({
                "file": os.path.relpath(path, directory),
                "rank": r,
                "bytes": os.path.getsize(path),
            })
        man = {
            "format": mf.FORMAT_VERSION,
            "step": int(step),
            "epoch": int(eng.epoch()) if eng is not None else 0,
            "world_size": self.size,
            "meta": self.meta,
            "shards": shards,
            "sharded": sharded_meta,
            "replicated": {"paths": replicated_paths, "file_rank": 0},
        }
        final = mf.manifest_path(directory, step)
        tmp = final + ".tmp"
        import json

        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(man, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)

    def _apply_retention(self) -> None:
        """Keep the newest ``keep`` committed checkpoints.  Order is the
        durability contract in reverse: delete the MANIFEST first (the
        set instantly stops being advertised), then its shards — a crash
        between the two leaves an orphaned shard dir, never a manifest
        pointing at deleted shards."""
        import shutil

        directory = self.config.directory
        steps = mf.list_manifest_steps(directory)
        for step in steps[:-self.config.keep] if len(steps) > \
                self.config.keep else []:
            try:
                os.unlink(mf.manifest_path(directory, step))
            except OSError:
                pass
            shutil.rmtree(mf.shard_dir(directory, step),
                          ignore_errors=True)
