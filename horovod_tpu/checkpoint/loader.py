"""Elastic resharding checkpoint loader.

Reads a manifest written at world size N and redistributes into the
CURRENT world size M — the restore half of the ``ShardResizeError``
contract: the sharded optimizer refuses to run across a resize, and
this loader is how the rebuilt optimizer gets its new-world shard.

Sharded vectors: the manifest records the flat length ``n`` and the
old world's ``(offset, count)`` bounds; :meth:`CheckpointLoader.read_flat`
computes the new rank's window via ``shard_bounds(n, M)`` and assembles
it from whichever old shard files overlap (shared-filesystem
single-host assumption — every rank can read every shard file, which
is the same assumption the launcher's respawn path already makes).

Replicated pytrees: restored INTO a live target structure (a freshly
initialized state at the new world) by the same deterministic
sorted-key walk the writer used, with the scalar-type preservation
rules of ``ElasticState.sync`` — equal world size resumes are
bit-identical because every byte round-trips verbatim.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

from horovod_tpu.checkpoint import manifest as mf
from horovod_tpu.checkpoint.manifest import (CheckpointError,
                                             CheckpointIncompleteError,
                                             latest_manifest)
from horovod_tpu.elastic.state import _walk
from horovod_tpu.runtime.sharded import shard_bounds

__all__ = ["CheckpointLoader"]


class CheckpointLoader:
    """One complete checkpoint, opened for (re)sharded reads.

    >>> loader = CheckpointLoader(directory)            # newest complete
    >>> loader = CheckpointLoader(directory, step=200)  # explicit step
    >>> w = loader.restore_tree(template_params, "params")
    >>> shard = loader.read_flat("opt_state...mu", offset, count)

    Raises :class:`CheckpointIncompleteError` for a torn/stale set and
    ``FileNotFoundError`` when the directory holds no complete
    checkpoint at all.
    """

    def __init__(self, directory: str, step: Optional[int] = None):
        self.directory = directory
        if step is None:
            found = latest_manifest(directory)
            if found is None:
                steps = mf.list_manifest_steps(directory)
                if steps:
                    # Manifests exist but none validates: surface the
                    # refusal loudly instead of a generic not-found.
                    man = mf.read_manifest(directory, steps[-1])
                    mf.validate(directory, man)
                raise FileNotFoundError(
                    f"no complete checkpoint in {directory}")
            self.manifest, self.step = found
        else:
            self.manifest = mf.read_manifest(directory, step)
            mf.validate(directory, self.manifest)
            self.step = int(step)
        self.epoch = int(self.manifest.get("epoch", 0))
        self.world_size = int(self.manifest["world_size"])
        self.meta = dict(self.manifest.get("meta") or {})
        self._sharded = {e["name"]: e
                         for e in self.manifest.get("sharded", [])}
        self._npz_cache: Dict[int, np.lib.npyio.NpzFile] = {}
        self._replicated: Optional[Dict[str, np.ndarray]] = None

    # -- file plumbing --

    def _shard_npz(self, rank: int):
        npz = self._npz_cache.get(rank)
        if npz is None:
            path = mf.shard_file(self.directory, self.step, rank,
                                 self.world_size)
            try:
                npz = np.load(path)
            except (OSError, ValueError) as e:
                raise CheckpointIncompleteError(
                    f"shard file {path} vanished or is unreadable "
                    f"mid-restore: {e}") from e
            self._npz_cache[rank] = npz
        return npz

    def close(self) -> None:
        for npz in self._npz_cache.values():
            npz.close()
        self._npz_cache.clear()

    # -- sharded vectors --

    def sharded_names(self):
        return sorted(self._sharded)

    def flat_length(self, name: str) -> int:
        return int(self._sharded[name]["n"])

    def read_flat(self, name: str, offset: int = 0,
                  count: Optional[int] = None) -> np.ndarray:
        """The ``[offset, offset+count)`` window of sharded vector
        ``name``, assembled from the old-world shard files that overlap
        it — the resharding read."""
        entry = self._sharded.get(name)
        if entry is None:
            raise KeyError(
                f"checkpoint step {self.step} has no sharded vector "
                f"'{name}' (has: {self.sharded_names()})")
        n = int(entry["n"])
        if count is None:
            count = n - offset
        end = offset + count
        if not (0 <= offset <= end <= n):
            raise ValueError(
                f"window [{offset}, {end}) out of range for n={n}")
        parts = []
        for rank, (off, cnt) in enumerate(entry["bounds"]):
            lo, hi = max(offset, off), min(end, off + cnt)
            if lo >= hi:
                continue
            piece = self._shard_npz(rank)[entry["key"]]
            parts.append(piece[lo - off:hi - off])
        if not parts:
            return np.zeros(0, dtype=np.dtype(entry["dtype"]))
        out = parts[0] if len(parts) == 1 else np.concatenate(parts)
        return np.ascontiguousarray(out, dtype=np.dtype(entry["dtype"]))

    def my_flat_shard(self, name: str, rank: int, size: int) -> np.ndarray:
        """Rank ``rank``-of-``size``'s window of ``name`` under the
        committed largest-first split at the NEW world size."""
        off, cnt = shard_bounds(self.flat_length(name), size)[rank]
        return self.read_flat(name, off, cnt)

    # -- replicated pytrees --

    def _rep_arrays(self) -> Dict[str, np.ndarray]:
        if self._replicated is None:
            rep = self.manifest.get("replicated") or {}
            npz = self._shard_npz(int(rep.get("file_rank", 0)))
            self._replicated = {
                path: npz[f"rep.{i}"]
                for i, path in enumerate(rep.get("paths", []))
            }
        return self._replicated

    def replicated_paths(self):
        return sorted(self._rep_arrays())

    def read_replicated(self, path: str) -> np.ndarray:
        """The saved replicated array at an exact walk path."""
        rep = self._rep_arrays()
        if path not in rep:
            raise KeyError(
                f"checkpoint step {self.step} has no replicated leaf "
                f"'{path}'")
        return rep[path]

    def slot_names(self):
        """Top-level slot names present in the checkpoint (replicated
        paths' first components plus sharded-name roots)."""
        roots = set()
        for path in self._rep_arrays():
            roots.add(path.split(".", 1)[0])
        for name in self._sharded:
            roots.add(name.split(".", 1)[0])
        return sorted(roots)

    def restore_tree(self, target, prefix: str, *,
                     missing: str = "error"):
        """Rebuild ``target`` (a live pytree — the freshly initialized
        state at the CURRENT world) with every leaf replaced by the
        checkpointed value at the same walk path.

        - a path recorded as a SHARDED vector is filled from
          :meth:`read_flat` at this rank's new-world bounds (the leaf
          must be the new-world shard: 1-D, length = new count);
        - a replicated path adopts the saved array with the scalar-type
          preservation of ``ElasticState.sync`` (bit-exact resume);
        - ``missing="error"`` raises on a target leaf the checkpoint
          never saved; ``missing="keep"`` keeps the target's value
          (used for world-dependent geometry the caller re-derives).
        """
        from horovod_tpu.common.basics import basics

        rank = basics.rank() if basics.is_initialized() else 0
        size = basics.size() if basics.is_initialized() else 1
        rep = self._rep_arrays()

        def visit(path, leaf):
            if path in self._sharded:
                arr = np.asarray(leaf)
                off, cnt = shard_bounds(self.flat_length(path),
                                        size)[rank]
                if arr.ndim != 1 or arr.size != cnt:
                    raise CheckpointError(
                        f"target leaf at '{path}' has shape {arr.shape} "
                        f"but rank {rank}/{size} owns a ({cnt},) shard "
                        f"of n={self.flat_length(path)} — was the "
                        "optimizer rebuilt for the current world?")
                return self.read_flat(path, off, cnt).astype(
                    arr.dtype, copy=False).copy()
            saved = rep.get(path)
            if saved is None:
                if missing == "keep":
                    return leaf
                raise CheckpointError(
                    f"checkpoint step {self.step} has no value for "
                    f"'{path}' (slots: {self.slot_names()})")
            arr = np.asarray(leaf)
            if np.asarray(saved).ndim == 0 or arr.ndim == 0:
                val = np.asarray(saved).reshape(())[()]
                if isinstance(leaf, bool):
                    return bool(val)
                if isinstance(leaf, int):
                    return int(val)
                if isinstance(leaf, float):
                    return float(val)
                return np.asarray(saved).astype(arr.dtype, copy=False)
            if np.asarray(saved).shape != arr.shape:
                raise CheckpointError(
                    f"shape mismatch at '{path}': checkpoint has "
                    f"{np.asarray(saved).shape}, target expects "
                    f"{arr.shape}")
            return np.asarray(saved).astype(arr.dtype, copy=False).copy()

        return _walk(target, prefix, visit)
