"""Skip-gram word2vec with NCE loss.

Capability parity: ``examples/tensorflow_word2vec.py`` (reference) — an
embedding + NCE workload whose gradients are *sparse* (only the looked-up
rows receive gradient).  In the reference this exercises the
``tf.IndexedSlices`` allgather path (``horovod/tensorflow/__init__.py:67-78``)
and the ``sparse_as_dense`` densify option.  On TPU, embedding lookups are
one-hot matmuls / gathers inside XLA and gradients are dense scatters, so the
same workload exercises the fused dense-allreduce path plus the
``sparse_as_dense``-equivalent knob in the optimizer.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["SkipGramModel", "nce_loss"]


class SkipGramModel(nn.Module):
    """Input embedding table + NCE output weights/biases.

    Mirrors the variables of the reference graph
    (examples/tensorflow_word2vec.py:156-171): ``embeddings``,
    ``nce_weights``, ``nce_biases``.
    """

    vocab_size: int = 50000
    embedding_size: int = 128
    dtype: Any = jnp.float32

    def setup(self):
        self.embeddings = self.param(
            "embeddings",
            lambda key, shape: jax.random.uniform(key, shape, minval=-1.0, maxval=1.0),
            (self.vocab_size, self.embedding_size),
        )
        self.nce_weights = self.param(
            "nce_weights",
            nn.initializers.truncated_normal(stddev=1.0 / self.embedding_size ** 0.5),
            (self.vocab_size, self.embedding_size),
        )
        self.nce_biases = self.param(
            "nce_biases", nn.initializers.zeros, (self.vocab_size,)
        )

    def __call__(self, center_ids):
        """Embed a batch of center-word ids → [B, E]."""
        return jnp.take(self.embeddings, center_ids, axis=0)

    def paired_logits(self, embedded, word_ids):
        """Per-example logits: embedded [B, E] × word_ids [B] → [B]."""
        w = jnp.take(self.nce_weights, word_ids, axis=0)   # [B, E]
        b = jnp.take(self.nce_biases, word_ids, axis=0)
        return jnp.einsum("be,be->b", embedded, w) + b

    def candidate_logits(self, embedded, word_ids):
        """Per-example candidate logits: [B, E] × [B, K] → [B, K]."""
        w = jnp.take(self.nce_weights, word_ids, axis=0)   # [B, K, E]
        b = jnp.take(self.nce_biases, word_ids, axis=0)
        return jnp.einsum("be,bke->bk", embedded, w) + b


def nce_loss(model, params, center_ids, label_ids, negative_ids):
    """Noise-contrastive estimation loss (sigmoid form).

    ``negative_ids``: [B, K] pre-sampled negatives (sampling happens in the
    data pipeline — keeping the jitted step free of host RNG, unlike the
    reference's in-graph candidate sampler).
    """
    embedded = model.apply(params, center_ids)                      # [B, E]
    pos = model.apply(params, embedded, label_ids,
                      method=SkipGramModel.paired_logits)           # [B]
    neg = model.apply(params, embedded, negative_ids,
                      method=SkipGramModel.candidate_logits)        # [B, K]
    pos_ll = jax.nn.log_sigmoid(pos)
    neg_ll = jax.nn.log_sigmoid(-neg)
    return -(pos_ll.mean() + neg_ll.sum(axis=-1).mean())
