"""Autoregressive decoding with a KV cache for the Llama family.

No reference equivalent (Horovod 0.15.1 is a training add-on; it serves
models by exporting plain graphs — docs/inference.md).  This module
completes the train→serve story for the flagship model: greedy /
temperature sampling from a ``LlamaModel`` checkpoint with O(1) work per
generated token instead of re-running the full sequence.

Design (TPU-first):
* Pure functions over the ``LlamaModel`` parameter pytree — the exact
  params a train state holds; no module surgery, no separate decode
  checkpoint format.  Forward math mirrors ``models/llama.py`` (RMSNorm
  fp32, RoPE on the fly, GQA, SwiGLU) and is pinned to it by a
  logits-parity test.
* Static shapes end to end: the KV cache is [L, B, S0+N, Hkv, D] from
  the start, the decode loop is one ``lax.scan`` over N steps — a single
  compiled program, no per-step retrace, no dynamic shapes.
* Prefill computes the prompt's logits and cache in one batched pass
  (MXU-friendly), then scan steps decode one token at a time.

MoE configs are not supported here (dense decode path only).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from horovod_tpu.models.llama import LlamaConfig, apply_rope, rope_freqs

__all__ = ["prefill", "decode_step", "generate"]


def _rms(x, scale, eps):
    x32 = x.astype(jnp.float32)
    x32 = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype)


def _attend(q, k, v, *, q_pos, k_len):
    """q: [B,Sq,Hq,D]; k/v: [B,T,Hkv,D] (cache, only [:k_len] valid).
    ``q_pos``: [Sq] global positions.  fp32 logits, GQA via grouping."""
    B, Sq, Hq, D = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    qg = q.reshape(B, Sq, Hkv, Hq // Hkv, D)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(jnp.asarray(D, jnp.float32))
    k_pos = jnp.arange(T)
    mask = (k_pos[None, :] <= q_pos[:, None]) & (k_pos[None, :] < k_len)
    logits = jnp.where(mask[None, None, None], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
    return out.reshape(B, Sq, Hq, D)


def _layer(cfg: LlamaConfig, lp, x, cache_k, cache_v, *, pos0, k_len):
    """One decoder layer over x: [B,S,H], writing K/V at [pos0, pos0+S)
    into this layer's cache [B,T,Hkv,D].  Returns (x, cache_k, cache_v)."""
    D = cfg.head_dim
    B, S, _ = x.shape
    y = _rms(x, lp["norm_attn"]["scale"], cfg.rms_eps)
    a = lp["attn"]
    q = (y @ a["wq"]["kernel"].astype(cfg.dtype)).reshape(
        B, S, cfg.num_heads, D)
    k = (y @ a["wk"]["kernel"].astype(cfg.dtype)).reshape(
        B, S, cfg.num_kv_heads, D)
    v = (y @ a["wv"]["kernel"].astype(cfg.dtype)).reshape(
        B, S, cfg.num_kv_heads, D)
    cos, sin = rope_freqs(D, S, cfg.rope_theta, offset=pos0)
    q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
    cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, pos0, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, pos0, 0, 0))
    out = _attend(q, cache_k, cache_v,
                  q_pos=jnp.arange(S) + pos0, k_len=k_len)
    x = x + out.reshape(B, S, cfg.num_heads * D) @ \
        a["wo"]["kernel"].astype(cfg.dtype)
    y = _rms(x, lp["norm_mlp"]["scale"], cfg.rms_eps)
    m = lp["mlp"]
    gate, up = jnp.split(y @ m["w_gate_up"]["kernel"].astype(cfg.dtype), 2,
                         axis=-1)
    return x + (jax.nn.silu(gate) * up) @ \
        m["w_down"]["kernel"].astype(cfg.dtype), cache_k, cache_v


def _forward(cfg, p, ids, caches_k, caches_v, *, pos0, k_len):
    x = jnp.take(p["tok_emb"]["embedding"], ids, axis=0).astype(cfg.dtype)
    new_k, new_v = [], []
    for i in range(cfg.num_layers):
        x, ck, cv = _layer(cfg, p[f"layer_{i}"], x, caches_k[i],
                           caches_v[i], pos0=pos0, k_len=k_len)
        new_k.append(ck)
        new_v.append(cv)
    x = _rms(x, p["norm_f"]["scale"], cfg.rms_eps)
    # Same head dtype as LlamaModel (cfg.logits_dtype) so cached decode
    # is logit-exact against model.apply.
    logits = (x.astype(cfg.logits_dtype)
              @ p["lm_head"]["kernel"].astype(cfg.logits_dtype))
    return logits, jnp.stack(new_k), jnp.stack(new_v)


def _params(variables):
    return variables["params"] if "params" in variables else variables


def prefill(cfg: LlamaConfig, variables, prompt_ids, *, cache_len: int):
    """Run the prompt [B, S0] through the model once, returning
    (last-position logits [B, V], kv_cache) with caches sized
    ``cache_len`` (>= S0 + tokens to generate)."""
    if cfg.num_experts > 1:
        raise NotImplementedError("KV-cache decode supports dense (non-MoE)"
                                  " configs")
    p = _params(variables)
    B, S0 = prompt_ids.shape
    shape = (cfg.num_layers, B, cache_len, cfg.num_kv_heads, cfg.head_dim)
    ck = jnp.zeros(shape, cfg.dtype)
    cv = jnp.zeros(shape, cfg.dtype)
    logits, ck, cv = _forward(cfg, p, prompt_ids, ck, cv, pos0=0, k_len=S0)
    return logits[:, -1], (ck, cv)


def decode_step(cfg: LlamaConfig, variables, token, cache, *, pos):
    """One token [B] in, next-position logits [B, V] out; ``pos`` is the
    token's global position (traced ok)."""
    p = _params(variables)
    ck, cv = cache
    logits, ck, cv = _forward(cfg, p, token[:, None], ck, cv,
                              pos0=pos, k_len=pos + 1)
    return logits[:, -1], (ck, cv)


def generate(cfg: LlamaConfig, variables, prompt_ids, *,
             max_new_tokens: int, temperature: float = 0.0,
             rng: Optional[jax.Array] = None):
    """Generate ``max_new_tokens`` continuations of ``prompt_ids`` [B, S0].

    ``temperature == 0`` is greedy argmax; otherwise softmax sampling at
    the given temperature (``rng`` required).  Returns [B, max_new_tokens].
    Wrap in ``jax.jit`` (static cfg/max_new_tokens) for production use —
    the loop is a single ``lax.scan``, so it compiles once.
    """
    if temperature > 0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    B, S0 = prompt_ids.shape
    logits, cache = prefill(cfg, variables, prompt_ids,
                            cache_len=S0 + max_new_tokens)

    def pick(logits, key):
        if temperature <= 0:
            return jnp.argmax(logits, -1).astype(prompt_ids.dtype)
        return jax.random.categorical(
            key, logits / temperature, -1).astype(prompt_ids.dtype)

    keys = (jax.random.split(rng, max_new_tokens) if rng is not None
            else jnp.zeros((max_new_tokens, 2), jnp.uint32))
    tok0 = pick(logits, keys[0] if rng is not None else None)

    def body(carry, key_pos):
        tok, cache = carry
        key, pos = key_pos
        logits, cache = decode_step(cfg, variables, tok, cache, pos=pos)
        nxt = pick(logits, key if rng is not None else None)
        return (nxt, cache), nxt  # emit the NEW token

    # Step i consumes the token at global position S0+i and produces the
    # token for position S0+i+1; tok0 (from prefill) is position S0.
    (_, _), rest = jax.lax.scan(
        body, (tok0, cache),
        (keys[1:], S0 + jnp.arange(max_new_tokens - 1)))
    return jnp.concatenate([tok0[:, None], rest.T], axis=1)  # [B, N]
