"""Autoregressive decoding with a KV cache for the Llama family.

No reference equivalent (Horovod 0.15.1 is a training add-on; it serves
models by exporting plain graphs — docs/inference.md).  This module
completes the train→serve story for the flagship model: greedy /
temperature sampling from a ``LlamaModel`` checkpoint with O(1) work per
generated token instead of re-running the full sequence.

Design (TPU-first):
* Pure functions over the ``LlamaModel`` parameter pytree — the exact
  params a train state holds; no module surgery, no separate decode
  checkpoint format.  Forward math mirrors ``models/llama.py`` (RMSNorm
  fp32, RoPE on the fly, GQA, SwiGLU) and is pinned to it by a
  logits-parity test.
* Static shapes end to end: the KV cache is [L, B, S0+N, Hkv, D] from
  the start, the decode loop is one ``lax.scan`` over N steps — a single
  compiled program, no per-step retrace, no dynamic shapes.
* Prefill computes the prompt's logits and cache in one batched pass
  (MXU-friendly), then scan steps decode one token at a time.

MoE configs are not supported here (dense decode path only).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from horovod_tpu.models.llama import LlamaConfig, apply_rope, rope_freqs

__all__ = ["prefill", "decode_step", "generate",
           "paged_prefill", "paged_decode_step"]


def _rms(x, scale, eps):
    x32 = x.astype(jnp.float32)
    x32 = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype)


def _attend(q, k, v, *, q_pos, k_len):
    """q: [B,Sq,Hq,D]; k/v: [B,T,Hkv,D] (cache, only [:k_len] valid).
    ``q_pos``: [Sq] global positions.  fp32 logits, GQA via grouping."""
    B, Sq, Hq, D = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    qg = q.reshape(B, Sq, Hkv, Hq // Hkv, D)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(jnp.asarray(D, jnp.float32))
    k_pos = jnp.arange(T)
    mask = (k_pos[None, :] <= q_pos[:, None]) & (k_pos[None, :] < k_len)
    logits = jnp.where(mask[None, None, None], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
    return out.reshape(B, Sq, Hq, D)


def _layer(cfg: LlamaConfig, lp, x, cache_k, cache_v, *, pos0, k_len):
    """One decoder layer over x: [B,S,H], writing K/V at [pos0, pos0+S)
    into this layer's cache [B,T,Hkv,D].  Returns (x, cache_k, cache_v)."""
    D = cfg.head_dim
    B, S, _ = x.shape
    y = _rms(x, lp["norm_attn"]["scale"], cfg.rms_eps)
    a = lp["attn"]
    q = (y @ a["wq"]["kernel"].astype(cfg.dtype)).reshape(
        B, S, cfg.num_heads, D)
    k = (y @ a["wk"]["kernel"].astype(cfg.dtype)).reshape(
        B, S, cfg.num_kv_heads, D)
    v = (y @ a["wv"]["kernel"].astype(cfg.dtype)).reshape(
        B, S, cfg.num_kv_heads, D)
    cos, sin = rope_freqs(D, S, cfg.rope_theta, offset=pos0)
    q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
    cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, pos0, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, pos0, 0, 0))
    out = _attend(q, cache_k, cache_v,
                  q_pos=jnp.arange(S) + pos0, k_len=k_len)
    x = x + out.reshape(B, S, cfg.num_heads * D) @ \
        a["wo"]["kernel"].astype(cfg.dtype)
    y = _rms(x, lp["norm_mlp"]["scale"], cfg.rms_eps)
    m = lp["mlp"]
    gate, up = jnp.split(y @ m["w_gate_up"]["kernel"].astype(cfg.dtype), 2,
                         axis=-1)
    return x + (jax.nn.silu(gate) * up) @ \
        m["w_down"]["kernel"].astype(cfg.dtype), cache_k, cache_v


def _forward(cfg, p, ids, caches_k, caches_v, *, pos0, k_len):
    x = jnp.take(p["tok_emb"]["embedding"], ids, axis=0).astype(cfg.dtype)
    new_k, new_v = [], []
    for i in range(cfg.num_layers):
        x, ck, cv = _layer(cfg, p[f"layer_{i}"], x, caches_k[i],
                           caches_v[i], pos0=pos0, k_len=k_len)
        new_k.append(ck)
        new_v.append(cv)
    x = _rms(x, p["norm_f"]["scale"], cfg.rms_eps)
    # Same head dtype as LlamaModel (cfg.logits_dtype) so cached decode
    # is logit-exact against model.apply.
    logits = (x.astype(cfg.logits_dtype)
              @ p["lm_head"]["kernel"].astype(cfg.logits_dtype))
    return logits, jnp.stack(new_k), jnp.stack(new_v)


def _params(variables):
    return variables["params"] if "params" in variables else variables


def prefill(cfg: LlamaConfig, variables, prompt_ids, *, cache_len: int):
    """Run the prompt [B, S0] through the model once, returning
    (last-position logits [B, V], kv_cache) with caches sized
    ``cache_len`` (>= S0 + tokens to generate)."""
    if cfg.num_experts > 1:
        raise NotImplementedError("KV-cache decode supports dense (non-MoE)"
                                  " configs")
    p = _params(variables)
    B, S0 = prompt_ids.shape
    shape = (cfg.num_layers, B, cache_len, cfg.num_kv_heads, cfg.head_dim)
    ck = jnp.zeros(shape, cfg.dtype)
    cv = jnp.zeros(shape, cfg.dtype)
    logits, ck, cv = _forward(cfg, p, prompt_ids, ck, cv, pos0=0, k_len=S0)
    return logits[:, -1], (ck, cv)


def decode_step(cfg: LlamaConfig, variables, token, cache, *, pos):
    """One token [B] in, next-position logits [B, V] out; ``pos`` is the
    token's global position (traced ok)."""
    p = _params(variables)
    ck, cv = cache
    logits, ck, cv = _forward(cfg, p, token[:, None], ck, cv,
                              pos0=pos, k_len=pos + 1)
    return logits[:, -1], (ck, cv)


def generate(cfg: LlamaConfig, variables, prompt_ids, *,
             max_new_tokens: int, temperature: float = 0.0,
             rng: Optional[jax.Array] = None,
             cache_len: Optional[int] = None):
    """Generate ``max_new_tokens`` continuations of ``prompt_ids`` [B, S0].

    ``temperature == 0`` is greedy argmax; otherwise softmax sampling at
    the given temperature (``rng`` required).  Returns [B, max_new_tokens].
    Wrap in ``jax.jit`` (static cfg/max_new_tokens) for production use —
    the loop is a single ``lax.scan``, so it compiles once.

    ``cache_len`` pins the physical KV length (default: exactly
    ``S0 + max_new_tokens``).  Logits are a deterministic function of
    the prompt AND this physical length — XLA's reduction grouping over
    the key axis varies with it, so near-tied logits can argmax
    differently at different lengths.  The serving stack runs every
    forward at ``cache_len = max_model_len``; pass the same value here
    to get the bit-identical reference stream (tests/test_serve.py).
    """
    if temperature > 0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    B, S0 = prompt_ids.shape
    if cache_len is None:
        cache_len = S0 + max_new_tokens
    if cache_len < S0 + max_new_tokens:
        raise ValueError(f"cache_len {cache_len} < prompt + new tokens "
                         f"{S0 + max_new_tokens}")
    logits, cache = prefill(cfg, variables, prompt_ids,
                            cache_len=cache_len)

    def pick(logits, key):
        if temperature <= 0:
            return jnp.argmax(logits, -1).astype(prompt_ids.dtype)
        return jax.random.categorical(
            key, logits / temperature, -1).astype(prompt_ids.dtype)

    keys = (jax.random.split(rng, max_new_tokens) if rng is not None
            else jnp.zeros((max_new_tokens, 2), jnp.uint32))
    tok0 = pick(logits, keys[0] if rng is not None else None)

    def body(carry, key_pos):
        tok, cache = carry
        key, pos = key_pos
        logits, cache = decode_step(cfg, variables, tok, cache, pos=pos)
        nxt = pick(logits, key if rng is not None else None)
        return (nxt, cache), nxt  # emit the NEW token

    # Step i consumes the token at global position S0+i and produces the
    # token for position S0+i+1; tok0 (from prefill) is position S0.
    (_, _), rest = jax.lax.scan(
        body, (tok0, cache),
        (keys[1:], S0 + jnp.arange(max_new_tokens - 1)))
    return jnp.concatenate([tok0[:, None], rest.T], axis=1)  # [B, N]


# ---------------------------------------------------------------------------
# Paged (block-table) KV cache — the serving data path (horovod_tpu/serve/).
#
# The cache is a pool of fixed-size blocks [L, NB, BS, Hkv, D]; each
# sequence owns a table of physical block ids covering its logical
# positions.  The decode math gathers a sequence's blocks back into a
# contiguous [T, Hkv, D] view and then runs the EXACT per-element
# operations of the contiguous path above — a gather is a permutation
# copy, so paged ≡ contiguous bit-for-bit at equal physical length
# (tests/test_serve.py pins it).  Physical block id 0 is the TRASH block:
# padded batch rows and unfunded table entries point at it, it is written
# by every padded row and never read by a live one.
# ---------------------------------------------------------------------------


def _rope_at(head_dim: int, positions, theta: float):
    """cos/sin [B, head_dim/2] at per-sequence ``positions`` [B] — the
    batched counterpart of ``rope_freqs(head_dim, 1, theta, offset=p)``,
    computed with the identical fp32 ops so the bits match."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    t = positions.astype(jnp.float32)
    ang = t[:, None] * inv[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def _apply_rope_b(x, cos, sin):
    """apply_rope with per-batch-row tables: x [B, 1, H, D]; cos/sin
    [B, D/2]."""
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    c = cos[:, None, None, :]
    s = sin[:, None, None, :]
    r1 = x1 * c - x2 * s
    r2 = x1 * s + x2 * c
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def _attend_b(q, k, v, *, q_pos, k_len):
    """_attend with per-sequence positions: q [B,1,Hq,D]; k/v [B,T,Hkv,D];
    ``q_pos``/``k_len`` [B].  Same einsum strings / fp32 logits / mask
    value as :func:`_attend`, so valid entries carry identical bits."""
    B, Sq, Hq, D = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    qg = q.reshape(B, Sq, Hkv, Hq // Hkv, D)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(jnp.asarray(D, jnp.float32))
    k_pos = jnp.arange(T)
    mask = (k_pos[None, :] <= q_pos[:, None]) & \
        (k_pos[None, :] < k_len[:, None])                      # [B, T]
    logits = jnp.where(mask[:, None, None, None, :], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
    return out.reshape(B, Sq, Hq, D)


def _paged_layer(cfg: LlamaConfig, lp, x, pk, pv, tables, *, pos,
                 fused: bool = False):
    """One decoder layer over one decode token per sequence.

    x: [B, 1, H]; pk/pv: this layer's pool [NB, BS, Hkv, D];
    tables: [B, MAXB] physical block ids; pos: [B] global positions.
    Writes K/V at each sequence's ``pos`` slot, then attends — via the
    gather + :func:`_attend_b` oracle by default, or via the fused
    paged-attention kernel (``ops/paged_attention.py``: block-table
    reads, no contiguous staging) when ``fused``.  Returns (x, pk, pv).
    """
    D = cfg.head_dim
    B, S, _ = x.shape
    bs = pk.shape[1]
    y = _rms(x, lp["norm_attn"]["scale"], cfg.rms_eps)
    a = lp["attn"]
    q = (y @ a["wq"]["kernel"].astype(cfg.dtype)).reshape(
        B, S, cfg.num_heads, D)
    k = (y @ a["wk"]["kernel"].astype(cfg.dtype)).reshape(
        B, S, cfg.num_kv_heads, D)
    v = (y @ a["wv"]["kernel"].astype(cfg.dtype)).reshape(
        B, S, cfg.num_kv_heads, D)
    cos, sin = _rope_at(D, pos, cfg.rope_theta)
    q, k = _apply_rope_b(q, cos, sin), _apply_rope_b(k, cos, sin)
    blk = jnp.take_along_axis(tables, (pos // bs)[:, None], axis=1)[:, 0]
    off = pos % bs
    pk = pk.at[blk, off].set(k[:, 0])
    pv = pv.at[blk, off].set(v[:, 0])
    if fused:
        from horovod_tpu.ops.paged_attention import paged_attention_decode

        out = paged_attention_decode(q, pk, pv, tables, pos)
    else:
        maxb = tables.shape[1]
        ck = pk[tables].reshape(B, maxb * bs, cfg.num_kv_heads, D)
        cv = pv[tables].reshape(B, maxb * bs, cfg.num_kv_heads, D)
        out = _attend_b(q, ck, cv, q_pos=pos, k_len=pos + 1)
    x = x + out.reshape(B, S, cfg.num_heads * D) @ \
        a["wo"]["kernel"].astype(cfg.dtype)
    y = _rms(x, lp["norm_mlp"]["scale"], cfg.rms_eps)
    m = lp["mlp"]
    gate, up = jnp.split(y @ m["w_gate_up"]["kernel"].astype(cfg.dtype), 2,
                         axis=-1)
    return x + (jax.nn.silu(gate) * up) @ \
        m["w_down"]["kernel"].astype(cfg.dtype), pk, pv


def paged_decode_step(cfg: LlamaConfig, variables, tokens, pool_k, pool_v,
                      tables, pos, *, fused: bool = False):
    """One decode step for a batch of independent sequences over the
    paged pool.

    tokens: [B] current token per sequence; pool_k/pool_v:
    [L, NB, BS, Hkv, D]; tables: [B, MAXB] int32 block tables (unused
    tail entries and padded rows point at trash block 0); pos: [B]
    global position of each token.  Returns (next-position logits
    [B, V], pool_k, pool_v).  Rows are computed independently — a padded
    row (pos 0, all-trash table) produces garbage logits the caller
    discards, and never perturbs a live row.

    ``fused`` (static under jit) selects the fused paged-attention
    kernel instead of the gather oracle; numerically equivalent within
    the documented tolerance, argmax-stable on the greedy corpus, but
    NOT bitwise identical (online softmax re-associates the key
    reduction) — ``HOROVOD_SERVE_FUSED_ATTN=0`` keeps the oracle.
    """
    p = _params(variables)
    x = jnp.take(p["tok_emb"]["embedding"], tokens[:, None],
                 axis=0).astype(cfg.dtype)
    new_k, new_v = [], []
    for i in range(cfg.num_layers):
        x, pk, pv = _paged_layer(cfg, p[f"layer_{i}"], x, pool_k[i],
                                 pool_v[i], tables, pos=pos, fused=fused)
        new_k.append(pk)
        new_v.append(pv)
    x = _rms(x, p["norm_f"]["scale"], cfg.rms_eps)
    logits = (x.astype(cfg.logits_dtype)
              @ p["lm_head"]["kernel"].astype(cfg.logits_dtype))
    return logits[:, -1], jnp.stack(new_k), jnp.stack(new_v)


def paged_prefill(cfg: LlamaConfig, variables, prompt_ids, pool_k, pool_v,
                  table, *, prompt_len, cache_len=None, start_blk: int = 0):
    """Prefill one sequence's (padded) prompt into its pool blocks.

    prompt_ids: [1, S_pad] with S_pad a multiple of the block size
    (positions >= ``prompt_len`` may hold any id — their K/V rows land in
    cache slots that every later read either masks or overwrites);
    table: [cache_len/BS] physical block ids (unfunded tail = trash 0);
    ``prompt_len`` may be traced.  Returns (logits at the last prompt
    position [1, V], pool_k, pool_v).

    ``cache_len`` (default S_pad) is the physical length of the
    temporary contiguous cache the prompt attends over.  Logits depend
    bitwise on this length (reduction-order effect — see
    :func:`generate`), so the serving engine pins it to
    ``max_model_len``: prefill then attends the exact geometry the
    block-table decode steps do, and the whole serve stream is
    bit-reproducible against offline ``generate()`` at that
    ``cache_len``.

    ``start_blk`` (static) > 0 is the prefix-cache hit path: the first
    ``start_blk`` table blocks already hold this prompt's K/V (shared,
    content-hash matched — serve/kv_cache.py), ``prompt_ids`` is the
    PADDED SUFFIX starting at position ``start_blk * BS``, and only the
    suffix is computed.  The temporary contiguous cache is seeded by
    gathering the whole table from the pool — a permutation copy, so the
    shared positions carry the exact bits a full prefill of the same
    content would recompute — and only blocks ``>= start_blk`` are
    scattered back: shared blocks are never written (the copy-on-write
    invariant).  Positions beyond ``prompt_len`` hold junk from unfunded
    table entries; the ``k_len`` mask zeroes them exactly (finfo.min →
    exp → 0), so the hit path is bit-identical to the full prefill
    (tests/test_serve.py pins it).
    """
    if cfg.num_experts > 1:
        raise NotImplementedError("KV-cache decode supports dense (non-MoE)"
                                  " configs")
    p = _params(variables)
    B, S_pad = prompt_ids.shape
    bs = pool_k.shape[2]
    if cache_len is None:
        cache_len = S_pad
    nb = cache_len // bs
    if start_blk == 0:
        shape = (cfg.num_layers, B, cache_len, cfg.num_kv_heads,
                 cfg.head_dim)
        ck = jnp.zeros(shape, cfg.dtype)
        cv = jnp.zeros(shape, cfg.dtype)
        logits, ck, cv = _forward(cfg, p, prompt_ids, ck, cv, pos0=0,
                                  k_len=prompt_len)
        last = jax.lax.dynamic_index_in_dim(logits, prompt_len - 1, axis=1,
                                            keepdims=False)
        pool_k = pool_k.at[:, table].set(
            ck[:, 0].reshape(cfg.num_layers, nb, bs, cfg.num_kv_heads,
                             cfg.head_dim))
        pool_v = pool_v.at[:, table].set(
            cv[:, 0].reshape(cfg.num_layers, nb, bs, cfg.num_kv_heads,
                             cfg.head_dim))
        return last, pool_k, pool_v
    start = start_blk * bs
    ck = pool_k[:, table].reshape(cfg.num_layers, cache_len,
                                  cfg.num_kv_heads, cfg.head_dim)[:, None]
    cv = pool_v[:, table].reshape(cfg.num_layers, cache_len,
                                  cfg.num_kv_heads, cfg.head_dim)[:, None]
    logits, ck, cv = _forward(cfg, p, prompt_ids, ck, cv, pos0=start,
                              k_len=prompt_len)
    last = jax.lax.dynamic_index_in_dim(logits, prompt_len - 1 - start,
                                        axis=1, keepdims=False)
    tail = table[start_blk:]
    pool_k = pool_k.at[:, tail].set(
        ck[:, 0, start:].reshape(cfg.num_layers, nb - start_blk, bs,
                                 cfg.num_kv_heads, cfg.head_dim))
    pool_v = pool_v.at[:, tail].set(
        cv[:, 0, start:].reshape(cfg.num_layers, nb - start_blk, bs,
                                 cfg.num_kv_heads, cfg.head_dim))
    return last, pool_k, pool_v


def paged_prefill_suffix(cfg: LlamaConfig, variables, prompt_ids, pool_k,
                         pool_v, table, *, prompt_len, start, cache_len):
    """The prefix-cache hit path with a TRACED ``start``.

    Identical math to :func:`paged_prefill` with ``start_blk > 0`` —
    gather-seed the contiguous cache from the whole table, run only the
    padded suffix through the model at ``pos0=start`` — but ``start``
    (block-aligned positions, ``0 < start < prompt_len``) is an operand,
    so ONE compiled program serves every hit offset at a given suffix
    bucket instead of one per ``(bucket, start_blk)`` pair.  The price
    of the dynamic offset is the scatter-back: with no static block
    split available, the WHOLE table is written.  That stays correct
    under copy-on-write because positions below ``start`` pass through
    ``_forward`` untouched from the gather seed, so every shared block
    is rewritten with exactly its own bytes — shared content never
    changes.  The caller must guarantee ``start + S_pad <= cache_len``
    (a clamped ``dynamic_update_slice`` would silently shift the
    writes); the engine falls back to the static path otherwise.
    """
    if cfg.num_experts > 1:
        raise NotImplementedError("KV-cache decode supports dense (non-MoE)"
                                  " configs")
    p = _params(variables)
    bs = pool_k.shape[2]
    nb = cache_len // bs
    ck = pool_k[:, table].reshape(cfg.num_layers, cache_len,
                                  cfg.num_kv_heads, cfg.head_dim)[:, None]
    cv = pool_v[:, table].reshape(cfg.num_layers, cache_len,
                                  cfg.num_kv_heads, cfg.head_dim)[:, None]
    logits, ck, cv = _forward(cfg, p, prompt_ids, ck, cv, pos0=start,
                              k_len=prompt_len)
    last = jax.lax.dynamic_index_in_dim(logits, prompt_len - 1 - start,
                                        axis=1, keepdims=False)
    pool_k = pool_k.at[:, table].set(
        ck[:, 0].reshape(cfg.num_layers, nb, bs, cfg.num_kv_heads,
                         cfg.head_dim))
    pool_v = pool_v.at[:, table].set(
        cv[:, 0].reshape(cfg.num_layers, nb, bs, cfg.num_kv_heads,
                         cfg.head_dim))
    return last, pool_k, pool_v
