"""ResNet v1.5 family (18/34/50/101) in flax.linen.

Capability parity: the reference's headline workload is ResNet-50 ImageNet
training (``examples/keras_imagenet_resnet50.py``,
``examples/pytorch_imagenet_resnet50.py``) and its published benchmark is
ResNet-101 under tf_cnn_benchmarks (``docs/benchmarks.md:22-37``).  This is
the model the bench harness (`bench.py`) runs.

TPU-first design choices:
* NHWC activations — XLA TPU's native convolution layout.
* bf16 compute / fp32 params+batch-stats: convs ride the MXU at bf16 with
  fp32 accumulation (XLA default); batch-norm statistics are accumulated in
  fp32 (flax promotes internally) and running stats stored fp32, but the
  normalize/scale/relu chain stays in the model dtype end-to-end — keeping
  activations bf16 through BN halves the HBM traffic of the bandwidth-bound
  BN/elementwise passes, measured +7% step throughput on v5e.
* v1.5 stride placement (stride-2 on the 3x3, not the 1x1) — the variant
  every modern img/sec number quotes.
* No Python-level control flow on data — the whole forward is one traceable
  graph, so XLA can fuse BN+ReLU into the conv epilogues.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101"]


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16
    norm: Callable = nn.BatchNorm

    @nn.compact
    def __call__(self, x, *, train: bool):
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = self.norm(use_running_average=not train, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = self.norm(use_running_average=not train, dtype=self.dtype,
                      scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm(use_running_average=not train,
                                 dtype=self.dtype)(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 reduce → 3x3 (carries the stride: v1.5) → 1x1 expand ×4."""

    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16
    norm: Callable = nn.BatchNorm

    @nn.compact
    def __call__(self, x, *, train: bool):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = self.norm(use_running_average=not train, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype)(y)
        y = self.norm(use_running_average=not train, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        # Zero-init the last BN scale so each block starts as identity —
        # the standard large-batch trick (Goyal et al.), which the reference
        # pairs with its LR warmup callback (keras/callbacks_impl.py:149-168).
        y = self.norm(use_running_average=not train, dtype=self.dtype,
                      scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters * 4, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm(use_running_average=not train,
                                 dtype=self.dtype)(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: type
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        # x: [B, H, W, 3]
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, name="conv_init")(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = self.block_cls(self.width * 2 ** stage, strides=strides,
                                   dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
