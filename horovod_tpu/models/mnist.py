"""MNIST models.

Capability parity with the reference's MNIST workloads
(``examples/tensorflow_mnist.py:39-60`` conv net, ``examples/keras_mnist.py``
and ``examples/pytorch_mnist.py:63-78``): a small convnet (conv-pool ×2 →
dense) and an MLP, used by the example scripts and the end-to-end tests.

TPU notes: NHWC layout (XLA's native conv layout on TPU), bf16 compute with
fp32 params, feature counts kept multiples of 8 so the VPU/MXU tile cleanly.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistConvNet(nn.Module):
    """Conv(32) → pool → Conv(64) → pool → Dense(512) → Dense(10).

    Same topology family as the reference conv nets
    (examples/tensorflow_mnist.py:39-60, examples/pytorch_mnist.py:63-78).
    """

    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        # x: [B, 28, 28, 1] float in [0, 1]
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


class MnistMLP(nn.Module):
    """Dense(128) → Dense(10), the keras_mnist-style small model."""

    num_classes: int = 10
    hidden: int = 128
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        x = nn.Dense(self.hidden, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
