"""BERT encoder family.

No reference equivalent — Horovod 0.15.1 predates BERT — but the baseline
workload list (BASELINE.json / SURVEY.md §5.7) adds a BERT-base data/FSDP
workload, so the model zoo carries one.

TPU-first: bf16 compute / fp32 params, fused QKV projection (one large
matmul instead of three — keeps the MXU busy), attention via a pluggable
``attention_fn`` so sequence-parallel ring attention
(``horovod_tpu.parallel.ring_attention``) can drop in.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["BertConfig", "BertEncoder", "BertForPretraining"]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def tiny() -> "BertConfig":
        """CI-sized config for tests and dry runs."""
        return BertConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                          num_heads=4, intermediate_size=128, max_position=128)


def dot_product_attention(q, k, v, mask=None):
    """Default attention: softmax(QK^T/sqrt(d))V in fp32 logits."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class SelfAttention(nn.Module):
    config: BertConfig
    attention_fn: Callable = staticmethod(dot_product_attention)

    @nn.compact
    def __call__(self, x, mask=None, *, train: bool = False):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        # Fused QKV: one [H, 3H] matmul.
        qkv = nn.Dense(3 * cfg.hidden_size, dtype=cfg.dtype, name="qkv")(x)
        qkv = qkv.reshape(x.shape[0], x.shape[1], 3, cfg.num_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = self.attention_fn(q, k, v, mask)
        out = out.reshape(x.shape[0], x.shape[1], cfg.hidden_size)
        out = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="proj")(out)
        out = nn.Dropout(cfg.dropout_rate, deterministic=not train)(out)
        return out


class BertLayer(nn.Module):
    config: BertConfig
    attention_fn: Callable = staticmethod(dot_product_attention)

    @nn.compact
    def __call__(self, x, mask=None, *, train: bool = False):
        cfg = self.config
        y = SelfAttention(cfg, attention_fn=self.attention_fn,
                          name="attention")(x, mask, train=train)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x + y).astype(cfg.dtype)
        y = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, name="mlp_in")(x)
        y = nn.gelu(y)
        y = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlp_out")(y)
        y = nn.Dropout(cfg.dropout_rate, deterministic=not train)(y)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x + y).astype(cfg.dtype)
        return x


class BertEncoder(nn.Module):
    config: BertConfig
    attention_fn: Callable = staticmethod(dot_product_attention)

    def setup(self):
        cfg = self.config
        self.tok_emb = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                                dtype=cfg.dtype)
        self.pos_emb = nn.Embed(cfg.max_position, cfg.hidden_size,
                                dtype=cfg.dtype)
        self.type_emb = nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                                 dtype=cfg.dtype)
        self.ln_emb = nn.LayerNorm(dtype=jnp.float32)
        self.layers = [
            BertLayer(cfg, attention_fn=self.attention_fn, name=f"layer_{i}")
            for i in range(cfg.num_layers)
        ]

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 *, train: bool = False):
        cfg = self.config
        S = input_ids.shape[1]
        x = self.tok_emb(input_ids) + self.pos_emb(jnp.arange(S)[None, :])
        if token_type_ids is not None:
            x = x + self.type_emb(token_type_ids)
        x = self.ln_emb(x).astype(cfg.dtype)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        for layer in self.layers:
            x = layer(x, mask, train=train)
        return x

    def attend(self, h):
        """Project hidden states onto the (tied) token-embedding table."""
        return self.tok_emb.attend(h.astype(self.config.dtype))


class BertForPretraining(nn.Module):
    """Encoder + MLM head (output projection weight-tied to the token
    embedding, standard BERT pretraining) + NSP head."""

    config: BertConfig
    attention_fn: Callable = staticmethod(dot_product_attention)

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 *, train: bool = False):
        cfg = self.config
        enc = BertEncoder(cfg, attention_fn=self.attention_fn, name="encoder")
        x = enc(input_ids, token_type_ids, attention_mask, train=train)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlm_transform")(x)
        h = nn.gelu(h)
        h = nn.LayerNorm(dtype=jnp.float32, name="mlm_ln")(h).astype(cfg.dtype)
        mlm_bias = self.param("mlm_bias", nn.initializers.zeros,
                              (cfg.vocab_size,))
        mlm_logits = enc.attend(h).astype(jnp.float32) + mlm_bias
        nsp_logits = nn.Dense(2, dtype=jnp.float32, name="nsp")(x[:, 0])
        return mlm_logits, nsp_logits
