"""Llama-family decoder-only transformer — the framework's flagship model.

No reference equivalent (Horovod 0.15.1 predates LLMs); required by the
baseline workload list (SURVEY.md §5.7: "Llama FSDP-style workload") and used
as the flagship for ``__graft_entry__.py`` because it exercises every
parallelism axis the framework supports: data, fsdp, tensor, sequence
(ring attention), pipeline, and expert (MoE variant).

TPU-first design:
* RMSNorm in fp32, everything else bf16 — including logits
  (``logits_dtype``): the loss upcasts per-tile inside its reductions,
  so no logits-sized f32 tensor is ever stored (ops/losses.py).
* RoPE applied on-the-fly (no position-embedding table to shard).
* GQA: ``num_kv_heads <= num_heads`` — shrinks the KV all-gather under
  tensor parallelism.
* SwiGLU MLP with fused gate+up projection (one [H, 2F] matmul).
* Pluggable ``attention_fn`` — ``horovod_tpu.parallel.ring_attention``
  substitutes a ppermute-ring blockwise kernel for sequence parallelism.
* Optional MoE (``num_experts > 1``): top-k routed experts via einsum
  dispatch/combine, the expert-parallel workload.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["LlamaConfig", "LlamaModel", "RMSNorm", "apply_rope",
           "causal_attention"]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    intermediate_size: int = 11008
    max_seq_len: int = 8192
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    num_experts: int = 1          # >1 enables MoE
    experts_per_token: int = 2
    dtype: Any = jnp.bfloat16
    # Output-head compute dtype.  bf16 keeps every logits-sized tensor —
    # the forward residual AND the cross-entropy cotangent, 2 GB each in
    # f32 at B=8/S=2048/V=32k — in half the bytes; the loss
    # (ops/losses.py) upcasts per-tile inside its reductions, so lse and
    # loss stay f32-accurate.  Set to jnp.float32 to save f32 logits.
    logits_dtype: Any = jnp.bfloat16
    # Fused Pallas RMSNorm (see RMSNorm.fused): enable on shard_map /
    # single-device paths; leave off under GSPMD.
    fused_rmsnorm: bool = False

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(vocab_size=128256, hidden_size=4096, num_layers=32,
                           num_heads=32, num_kv_heads=8,
                           intermediate_size=14336, max_seq_len=8192,
                           rope_theta=500000.0)

    @staticmethod
    def tiny(num_experts: int = 1) -> "LlamaConfig":
        """CI-sized config for tests, dry runs, and compile checks."""
        return LlamaConfig(vocab_size=512, hidden_size=64, num_layers=2,
                           num_heads=4, num_kv_heads=2, intermediate_size=128,
                           max_seq_len=256, num_experts=num_experts)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # Fused Pallas kernel (ops/rms_norm.py).  Opt-in twice over: (a)
    # pallas_call cannot lower under non-Manual mesh axes, so it must
    # stay off for GSPMD (plain jit + sharded params) paths — shard_map
    # paths (make_train_step, ring attention, pipeline) are safe; (b) on
    # the 400M bench config it measured only ~0.5% end-to-end (XLA's norm
    # fusions were already fused with neighboring converts/residuals, and
    # the kernel boundary forfeits that), so the default stays off.
    fused: bool = False

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        if self.fused:
            from horovod_tpu.ops.rms_norm import rms_norm

            return rms_norm(x, scale, eps=self.eps, out_dtype=self.dtype)
        x32 = x.astype(jnp.float32)
        x32 = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1,
                                           keepdims=True) + self.eps)
        return (x32 * scale).astype(self.dtype)


def rope_freqs(head_dim: int, seq_len: int, theta: float,
               offset=0) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables [S, head_dim/2] in fp32.  ``offset`` may be a traced
    value (sequence-parallel shards pass ``axis_index * S_local``)."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32) + offset
    ang = jnp.outer(t, inv)
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate pairs (x[..., ::2], x[..., 1::2]).  x: [B, S, H, D]."""
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    r1 = x1 * c - x2 * s
    r2 = x1 * s + x2 * c
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def causal_attention(q, k, v, *, q_offset: int = 0):
    """Default causal attention, fp32 logits, GQA-aware.

    q: [B, Sq, Hq, D]; k, v: [B, Sk, Hkv, D] with Hq % Hkv == 0.
    ``q_offset``: global position of q[0] (for decode / sequence shards).
    """
    B, Sq, Hq, D = q.shape
    Hkv = k.shape[2]
    group = Hq // Hkv
    qg = q.reshape(B, Sq, Hkv, group, D)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(jnp.asarray(D, jnp.float32))
    q_pos = jnp.arange(Sq) + q_offset
    k_pos = jnp.arange(k.shape[1])
    mask = q_pos[:, None] >= k_pos[None, :]
    logits = jnp.where(mask[None, None, None], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
    return out.reshape(B, Sq, Hq, D)


class LlamaAttention(nn.Module):
    config: LlamaConfig
    attention_fn: Callable = staticmethod(causal_attention)

    @nn.compact
    def __call__(self, x, cos, sin):
        cfg = self.config
        B, S, _ = x.shape
        D = cfg.head_dim
        q = nn.Dense(cfg.num_heads * D, use_bias=False, dtype=cfg.dtype,
                     name="wq")(x).reshape(B, S, cfg.num_heads, D)
        k = nn.Dense(cfg.num_kv_heads * D, use_bias=False, dtype=cfg.dtype,
                     name="wk")(x).reshape(B, S, cfg.num_kv_heads, D)
        v = nn.Dense(cfg.num_kv_heads * D, use_bias=False, dtype=cfg.dtype,
                     name="wv")(x).reshape(B, S, cfg.num_kv_heads, D)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        out = self.attention_fn(q, k, v)
        out = out.reshape(B, S, cfg.num_heads * D)
        return nn.Dense(cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                        name="wo")(out)


class SwiGLU(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        # Fused gate+up: one [H, 2F] matmul.
        gu = nn.Dense(2 * cfg.intermediate_size, use_bias=False,
                      dtype=cfg.dtype, name="w_gate_up")(x)
        gate, up = jnp.split(gu, 2, axis=-1)
        return nn.Dense(cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                        name="w_down")(nn.silu(gate) * up)


class MoEBlock(nn.Module):
    """Top-k routed mixture of SwiGLU experts (expert-parallel workload).

    Dense dispatch/combine via einsum — dynamic-shape-free so it shards
    cleanly over an ``expert`` mesh axis.
    """

    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        B, S, H = x.shape
        E, K = cfg.num_experts, cfg.experts_per_token
        router = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          name="router")(x.astype(jnp.float32))   # [B,S,E]
        weights, sel = jax.lax.top_k(jax.nn.softmax(router, -1), K)
        weights = weights / jnp.sum(weights, -1, keepdims=True)
        one_hot = jax.nn.one_hot(sel, E, dtype=cfg.dtype)          # [B,S,K,E]
        combine = jnp.einsum("bske,bsk->bse", one_hot,
                             weights.astype(cfg.dtype))            # [B,S,E]
        # Expert-batched weights: [E, H, 2F] and [E, F, H].
        w_gu = self.param("w_gate_up", nn.initializers.lecun_normal(),
                          (E, H, 2 * cfg.intermediate_size)).astype(cfg.dtype)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (E, cfg.intermediate_size, H)).astype(cfg.dtype)
        sel_mask = (combine != 0).astype(cfg.dtype)                # [B,S,E]
        xe = jnp.einsum("bsh,bse->ebsh", x, sel_mask)              # masked copy
        gu = jnp.einsum("ebsh,ehf->ebsf", xe, w_gu)
        gate, up = jnp.split(gu, 2, axis=-1)
        ye = jnp.einsum("ebsf,efh->ebsh", nn.silu(gate) * up, w_down)
        return jnp.einsum("ebsh,bse->bsh", ye, combine)


class LlamaLayer(nn.Module):
    config: LlamaConfig
    attention_fn: Callable = staticmethod(causal_attention)

    @nn.compact
    def __call__(self, x, cos, sin):
        cfg = self.config
        y = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.fused_rmsnorm,
                    name="norm_attn")(x)
        x = x + LlamaAttention(cfg, attention_fn=self.attention_fn,
                               name="attn")(y, cos, sin)
        y = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.fused_rmsnorm,
                    name="norm_mlp")(x)
        if cfg.num_experts > 1:
            x = x + MoEBlock(cfg, name="moe")(y)
        else:
            x = x + SwiGLU(cfg, name="mlp")(y)
        return x


class LlamaModel(nn.Module):
    config: LlamaConfig
    attention_fn: Callable = staticmethod(causal_attention)

    @nn.compact
    def __call__(self, input_ids, *, positions_offset: int = 0):
        cfg = self.config
        B, S = input_ids.shape
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     name="tok_emb")(input_ids)
        cos, sin = rope_freqs(cfg.head_dim, S, cfg.rope_theta,
                              offset=positions_offset)
        for i in range(cfg.num_layers):
            x = LlamaLayer(cfg, attention_fn=self.attention_fn,
                           name=f"layer_{i}")(x, cos, sin)
        x = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.fused_rmsnorm,
                    name="norm_f")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False,
                          dtype=cfg.logits_dtype, name="lm_head")(x)
        return logits
