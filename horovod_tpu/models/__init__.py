"""Model zoo for the TPU-native framework.

Covers the reference's example workloads (reference ``examples/``:
MNIST convnets ×4, ImageNet ResNet-50 ×2, word2vec, synthetic ResNet
benchmark) plus the transformer families (BERT, Llama) used by the
FSDP-style baseline workloads.  All models are flax.linen modules designed
TPU-first: bfloat16 compute with float32 params, channels-last layouts,
MXU-friendly dimensions.
"""

from horovod_tpu.models.mnist import MnistConvNet, MnistMLP
from horovod_tpu.models.resnet import ResNet, ResNet18, ResNet34, ResNet50, ResNet101
from horovod_tpu.models.word2vec import SkipGramModel, nce_loss
from horovod_tpu.models.bert import BertConfig, BertEncoder, BertForPretraining
from horovod_tpu.models.generation import decode_step, generate, prefill
from horovod_tpu.models.llama import LlamaConfig, LlamaModel

__all__ = [
    "MnistConvNet",
    "MnistMLP",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "SkipGramModel",
    "nce_loss",
    "BertConfig",
    "BertEncoder",
    "BertForPretraining",
    "LlamaConfig",
    "LlamaModel",
    "prefill",
    "decode_step",
    "generate",
]
