"""Estimator-role train/eval harness.

Reference parity: ``examples/tensorflow_mnist_estimator.py:1-191`` — the
``tf.estimator`` workflow: a ``model_fn`` producing loss + metrics, an
``input_fn`` producing batches, periodic checkpointing to a ``model_dir``
with automatic warm-start, a train/evaluate cycle, and the Horovod fitting
recipe on top (DistributedOptimizer at :114, broadcast hook at :164,
steps divided by world size at :177).

TPU-native redesign: ``tf.estimator`` rebuilds a graph per mode; under JAX
the natural shape is one jitted SPMD train step plus a jitted metric
step, with state as an explicit pytree.  The Horovod recipe is built in:
gradients are averaged via :class:`~horovod_tpu.jax.DistributedOptimizer`,
initial/restored state is broadcast from rank 0, checkpoints are written
by rank 0 only, and evaluation metrics are allreduce-averaged across
ranks.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Estimator"]


class Estimator:
    """Train/evaluate harness with a ``model_dir`` lifecycle.

    Parameters
    ----------
    loss_fn: ``loss_fn(params, batch) -> (loss, metrics_dict)`` — the
        ``model_fn`` role; metrics are scalar jnp values.
    init_fn: ``init_fn(rng) -> params``.
    optimizer: an optax transformation (wrapped in DistributedOptimizer)
        or a DistributedOptimizer.
    model_dir: checkpoint directory; None disables checkpointing.
    mesh: device mesh (default: the data-parallel mesh).
    """

    def __init__(self, loss_fn: Callable, init_fn: Callable, optimizer,
                 model_dir: Optional[str] = None, *, mesh=None,
                 seed: int = 0):
        import horovod_tpu.jax as hvd

        self._hvd = hvd
        self.loss_fn = loss_fn
        self.model_dir = model_dir
        self.mesh = mesh or hvd.data_parallel_mesh()
        if not isinstance(optimizer, hvd.DistributedOptimizer):
            optimizer = hvd.DistributedOptimizer(optimizer)
        self.optimizer = optimizer

        def train_loss(params, batch):
            loss, _ = loss_fn(params, batch)
            return loss

        self._train_step = hvd.make_train_step(
            train_loss, optimizer, self.mesh, donate=False)
        self._metric_step = jax.jit(lambda params, batch: loss_fn(params, batch)[1])

        self.params = init_fn(jax.random.key(seed))
        self.opt_state = jax.jit(optimizer.inner.init)(self.params)
        self._step_count = 0
        self._restore_or_broadcast()

    # -- lifecycle ---------------------------------------------------------

    def _restore_or_broadcast(self) -> None:
        """Warm-start from ``model_dir`` if a checkpoint exists (estimator
        semantics), else broadcast freshly-initialized state from rank 0
        (the BroadcastGlobalVariablesHook role)."""
        from horovod_tpu.flax import checkpoint as ckpt

        state = {"params": self.params, "opt_state": self.opt_state}
        if self.model_dir:
            state, start_epoch = ckpt.restore_and_broadcast(
                self.model_dir, state)
            self._start_epoch = start_epoch
        else:
            self._start_epoch = 0
        if self._start_epoch == 0:
            state = self._hvd.broadcast_parameters(state, root_rank=0)
        self.params = state["params"]
        self.opt_state = state["opt_state"]

    def _save(self, epoch: int) -> None:
        if not self.model_dir:
            return
        from horovod_tpu.flax import checkpoint as ckpt

        ckpt.save_checkpoint(
            self.model_dir,
            {"params": self.params, "opt_state": self.opt_state},
            epoch,
        )

    # -- estimator surface -------------------------------------------------

    def train(self, input_fn: Callable[[], Iterable], *,
              epochs: int = 1, steps_per_epoch: Optional[int] = None):
        """Run training epochs over ``input_fn()`` batches; checkpoint per
        epoch on rank 0.  Resumes from the last checkpoint epoch."""
        hvd = self._hvd
        last_loss = None
        for epoch in range(self._start_epoch, epochs):
            n = 0
            for batch in input_fn():
                if steps_per_epoch is not None and n >= steps_per_epoch:
                    break
                self.params, self.opt_state, loss = self._train_step(
                    self.params, self.opt_state, batch)
                self._step_count += 1
                n += 1
                last_loss = loss
            self._save(epoch)
            if hvd.rank() == 0 and last_loss is not None:
                print(f"estimator epoch {epoch + 1}/{epochs}: "
                      f"loss={float(last_loss):.4f}", flush=True)
        # Never roll the resume point backwards: train(epochs=k) with
        # k <= the restored epoch runs zero steps and must not make a
        # later train() re-train (and overwrite) finished epochs.
        self._start_epoch = max(self._start_epoch, epochs)
        return self

    def evaluate(self, input_fn: Callable[[], Iterable], *,
                 steps: Optional[int] = None) -> dict:
        """Average ``loss_fn`` metrics over ``input_fn()`` batches, then
        allreduce-average across ranks (the reference's final
        ``hvd.allreduce`` of the eval score, keras_imagenet_resnet50.py:176)."""
        hvd = self._hvd
        totals: dict = {}
        n = 0
        for batch in input_fn():
            if steps is not None and n >= steps:
                break
            for k, v in self._metric_step(self.params, batch).items():
                totals[k] = totals.get(k, 0.0) + float(v)
            n += 1
        means = {k: v / max(n, 1) for k, v in totals.items()}
        return {
            k: float(np.asarray(
                hvd.allreduce(jnp.asarray(v), op=hvd.Average)))
            for k, v in means.items()
        }

    def train_and_evaluate(self, train_input_fn: Callable,
                           eval_input_fn: Callable, *, epochs: int = 1,
                           steps_per_epoch: Optional[int] = None,
                           eval_steps: Optional[int] = None) -> dict:
        """The ``tf.estimator.train_and_evaluate`` role: evaluate after
        each training epoch; returns the final metrics."""
        metrics: Optional[dict] = None
        for epoch in range(self._start_epoch, epochs):
            self.train(train_input_fn, epochs=epoch + 1,
                       steps_per_epoch=steps_per_epoch)
            metrics = self.evaluate(eval_input_fn, steps=eval_steps)
            if self._hvd.rank() == 0:
                rendered = ", ".join(
                    f"{k}={v:.4f}" for k, v in metrics.items())
                print(f"estimator eval after epoch {epoch + 1}: {rendered}",
                      flush=True)
        if metrics is None:
            # Already trained to >= epochs (resumed run): still report.
            metrics = self.evaluate(eval_input_fn, steps=eval_steps)
        return metrics
