"""Checkpoint / resume: the rank-0-writes, broadcast-on-resume pattern.

Reference parity: checkpointing in the reference is an application-level
pattern, not a library feature (SURVEY.md §5.4): rank 0 alone writes
(``examples/keras_imagenet_resnet50.py:156-158``), the resume epoch is
discovered on rank 0 and broadcast (``keras_imagenet_resnet50.py:64-73``),
and state re-syncs via broadcast / ``hvd.load_model``
(``keras/impl.py:93-109``).  Here the pattern is a library feature:
flax.serialization msgpack files with atomic rank-0 writes and
broadcast-on-resume.  For the GSPMD path (sharded params over a device
mesh) ``save_sharded``/``restore_sharded`` use orbax: every shard is
written/read with its sharding preserved, so FSDP/TP states checkpoint
without gathering to one host — the TPU-native upgrade the replicated
msgpack pattern cannot provide.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

__all__ = [
    "save_checkpoint",
    "load_checkpoint",
    "latest_checkpoint",
    "resume_epoch",
    "restore_and_broadcast",
    "save_sharded",
    "restore_sharded",
    "latest_sharded",
]


def _ckpt_path(directory: str, epoch: int) -> str:
    return os.path.join(directory, f"checkpoint-{epoch}.msgpack")


def save_checkpoint(directory: str, state: Any, epoch: int,
                    *, only_rank0: bool = True) -> Optional[str]:
    """Serialize ``state`` (any pytree / flax TrainState) for ``epoch``.

    Writes on rank 0 only by default — the reference's pattern
    (examples/tensorflow_mnist.py:106-108).  Returns the path (or None on
    non-writing ranks).
    """
    import horovod_tpu.jax as hvd

    if only_rank0 and hvd.rank() != 0:
        return None
    os.makedirs(directory, exist_ok=True)
    path = _ckpt_path(directory, epoch)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.to_bytes(jax.device_get(state)))
    os.replace(tmp, path)  # atomic: resume never sees partial files
    return path


def latest_checkpoint(directory: str) -> Optional[tuple[str, int]]:
    """(path, epoch) of the newest checkpoint, or None."""
    if not os.path.isdir(directory):
        return None
    best = None
    for fname in os.listdir(directory):
        m = re.fullmatch(r"checkpoint-(\d+)\.msgpack", fname)
        if m:
            epoch = int(m.group(1))
            if best is None or epoch > best[1]:
                best = (os.path.join(directory, fname), epoch)
    return best


def load_checkpoint(path: str, target: Any) -> Any:
    """Deserialize into the structure of ``target``."""
    with open(path, "rb") as f:
        return serialization.from_bytes(target, f.read())


def resume_epoch(directory: str) -> int:
    """Discover the resume epoch on rank 0 and broadcast it so all ranks
    agree even when the filesystem is not shared (reference
    keras_imagenet_resnet50.py:64-73).  Returns 0 when starting fresh."""
    import horovod_tpu.jax as hvd
    import jax.numpy as jnp

    found = latest_checkpoint(directory) if hvd.rank() == 0 else None
    epoch = 0 if found is None else found[1] + 1
    agreed = hvd.broadcast(jnp.asarray(epoch, jnp.int32), root_rank=0,
                           name="resume_epoch")
    return int(np.asarray(agreed))


def restore_and_broadcast(directory: str, target: Any,
                          *, root_rank: int = 0) -> tuple[Any, int]:
    """Full resume: rank 0 loads the newest checkpoint, every rank receives
    it by broadcast, and the next epoch index is agreed globally.

    Returns ``(state, start_epoch)``; ``(target, 0)`` if no checkpoint.
    """
    import horovod_tpu.jax as hvd

    start_epoch = resume_epoch(directory)
    if start_epoch == 0:
        return target, 0
    state = target
    if hvd.rank() == root_rank:
        found = latest_checkpoint(directory)
        state = load_checkpoint(found[0], target)
    state = hvd.broadcast_parameters(state, root_rank=root_rank)
    return state, start_epoch


# ---------------------------------------------------------------------------
# Sharded checkpoints (GSPMD path) via orbax
# ---------------------------------------------------------------------------

def _sharded_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"sharded-{step}")


def save_sharded(directory: str, state: Any, step: int) -> str:
    """Checkpoint a SHARDED pytree (params/opt_state laid out over a mesh
    with ``NamedSharding``) without gathering: orbax writes each process's
    owned shards and records the shardings.  Use for FSDP/TP states where
    the replicated ``save_checkpoint`` would materialize the full model on
    one host.  Within one JAX process group only (the jit/GSPMD world) —
    the engine's independent multi-process ranks each see their own JAX
    runtime and should use the rank-0 msgpack pattern instead.

    Requires orbax-checkpoint (``pip install horovod-tpu[sharded-checkpoint]``).
    """
    import orbax.checkpoint as ocp

    path = _sharded_path(directory, step)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
    return path


def restore_sharded(directory: str, target: Any, step: Optional[int] = None):
    """Restore a sharded checkpoint directly INTO the shardings of
    ``target`` (a pytree of sharded arrays or ShapeDtypeStructs with
    ``.sharding`` set): each device reads only its own shards.  ``step``
    defaults to the newest.  Returns ``(state, step)`` or ``(target, None)``
    when no sharded checkpoint exists."""
    import orbax.checkpoint as ocp

    if step is None:
        found = latest_sharded(directory)
        if found is None:
            return target, None
        step = found[1]
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, "sharding", None)),
        target)
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(_sharded_path(directory, step), abstract)
    return state, step


def latest_sharded(directory: str) -> Optional[tuple[str, int]]:
    """(path, step) of the newest sharded checkpoint, or None."""
    if not os.path.isdir(directory):
        return None
    best = None
    for fname in os.listdir(directory):
        m = re.fullmatch(r"sharded-(\d+)", fname)
        if m:
            step = int(m.group(1))
            if best is None or step > best[1]:
                best = (os.path.join(directory, fname), step)
    return best
