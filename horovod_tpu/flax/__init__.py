"""Flax frontend — the Keras-role layer of the framework.

Reference parity: ``horovod/keras`` + ``horovod/tensorflow/keras``
(P8-P10 in SURVEY.md §2.2): optimizer wrapping, the four callbacks,
``load_model``-style checkpoint restore, metric averaging.  Keras's
``model.fit`` becomes :func:`fit` — a callback-orchestrated epoch loop over
a user-supplied jitted train step; flax's ``TrainState`` plays the role of
the compiled Keras model (params + optimizer + step in one pytree).

Typical use::

    import horovod_tpu.flax as hvdk
    import horovod_tpu.jax as hvd

    hvd.init()
    opt = optax.inject_hyperparams(optax.sgd)(
        learning_rate=0.01 * hvd.num_chips(), momentum=0.9)
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=hvd.DistributedOptimizer(opt))
    state = hvdk.fit(
        state, data_fn, epochs=90, steps_per_epoch=spe,
        train_step=step,
        callbacks=[
            hvdk.callbacks.BroadcastGlobalVariablesCallback(0),
            hvdk.callbacks.MetricAverageCallback(),
            hvdk.callbacks.LearningRateWarmupCallback(0.01, 5,
                                                      steps_per_epoch=spe),
        ])
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional, Sequence

from horovod_tpu.common import (
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from horovod_tpu.flax import callbacks
from horovod_tpu.flax.callbacks import (
    BroadcastGlobalVariablesCallback,
    Callback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    get_learning_rate,
    set_learning_rate,
)
from horovod_tpu.flax.checkpoint import (
    latest_checkpoint,
    load_checkpoint,
    restore_and_broadcast,
    resume_epoch,
    save_checkpoint,
)
from horovod_tpu.flax.estimator import Estimator

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size",
    "callbacks", "Callback",
    "BroadcastGlobalVariablesCallback", "MetricAverageCallback",
    "LearningRateScheduleCallback", "LearningRateWarmupCallback",
    "get_learning_rate", "set_learning_rate",
    "save_checkpoint", "load_checkpoint", "latest_checkpoint",
    "resume_epoch", "restore_and_broadcast",
    "Estimator",
    "fit",
]


def fit(state, data_fn, *, epochs: int, train_step: Callable,
        steps_per_epoch: Optional[int] = None,
        callbacks: Sequence[Callback] = (),
        initial_epoch: int = 0, verbose: Optional[bool] = None):
    """Callback-orchestrated training loop (the ``model.fit`` role).

    ``data_fn(epoch) -> iterable of batches`` (or a re-iterable passed
    directly); ``train_step(state, batch) -> (state, logs)`` is the user's
    jitted step.  Callbacks receive functional hooks in Keras order.
    Rank 0 prints per-epoch logs when ``verbose`` (default: rank 0 only).
    """
    import horovod_tpu.jax as hvd

    if verbose is None:
        verbose = hvd.rank() == 0

    cbs = list(callbacks)
    for cb in cbs:
        state = cb.on_train_begin(state)
    for epoch in range(initial_epoch, epochs):
        for cb in cbs:
            state = cb.on_epoch_begin(epoch, state)
        batches = data_fn(epoch) if callable(data_fn) else data_fn
        logs: dict = {}
        n_batches = 0
        for batch_idx, batch in enumerate(batches):
            if steps_per_epoch is not None and batch_idx >= steps_per_epoch:
                break
            for cb in cbs:
                state = cb.on_batch_begin(epoch, batch_idx, state)
            state, step_logs = train_step(state, batch)
            n_batches += 1
            for k, v in dict(step_logs).items():
                logs[k] = logs.get(k, 0.0) + float(v)
            for cb in cbs:
                state = cb.on_batch_end(epoch, batch_idx, state, step_logs)
        logs = {k: v / max(n_batches, 1) for k, v in logs.items()}
        for cb in cbs:
            state = cb.on_epoch_end(epoch, state, logs)
        if verbose:
            rendered = ", ".join(f"{k}={v:.4f}" for k, v in logs.items())
            print(f"Epoch {epoch + 1}/{epochs}: {rendered}", flush=True)
    for cb in cbs:
        state = cb.on_train_end(state)
    return state
