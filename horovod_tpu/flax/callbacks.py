"""Training callbacks — the Keras callback stack, flax-style.

Reference parity: ``horovod/keras/callbacks.py`` + ``callbacks_impl.py``
(317 LoC): ``BroadcastGlobalVariablesCallback``, ``MetricAverageCallback``,
``LearningRateScheduleCallback`` (staircase or smooth, with momentum
correction), ``LearningRateWarmupCallback`` (Goyal et al. linear warmup).

TPU-native design: flax has no ``model.fit``, so callbacks plug into the
``horovod_tpu.flax.fit`` loop and are *functional*: each hook takes and
returns the train state.  Learning-rate control uses
``optax.inject_hyperparams`` state (the optax-idiomatic mutable-lr
mechanism) instead of mutating a tf Variable; momentum correction rescales
the SGD trace by new_lr/old_lr exactly as the reference does to keep the
effective update magnitude continuous across lr steps
(callbacks_impl.py:70-147).
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

__all__ = [
    "Callback",
    "BroadcastGlobalVariablesCallback",
    "MetricAverageCallback",
    "LearningRateScheduleCallback",
    "LearningRateWarmupCallback",
    "get_learning_rate",
    "set_learning_rate",
]


class Callback:
    """Hook protocol for ``horovod_tpu.flax.fit``.  All hooks are
    functional: they receive the ``TrainState`` and return it (possibly
    updated)."""

    def on_train_begin(self, state):
        return state

    def on_epoch_begin(self, epoch: int, state):
        return state

    def on_batch_begin(self, epoch: int, batch: int, state):
        return state

    def on_batch_end(self, epoch: int, batch: int, state, logs: dict):
        return state

    def on_epoch_end(self, epoch: int, state, logs: dict):
        return state

    def on_train_end(self, state):
        return state


# ---------------------------------------------------------------------------
# Learning-rate state plumbing (optax.inject_hyperparams)
# ---------------------------------------------------------------------------

def _find_hyperparams(opt_state):
    """Locate InjectHyperparamsState dicts holding 'learning_rate'."""
    found = []

    def visit(s):
        hp = getattr(s, "hyperparams", None)
        if isinstance(hp, dict) and "learning_rate" in hp:
            found.append(s)
        if isinstance(s, tuple) and not hasattr(s, "hyperparams"):
            for item in s:
                visit(item)

    visit(opt_state)
    return found


def get_learning_rate(opt_state) -> float:
    states = _find_hyperparams(opt_state)
    if not states:
        raise ValueError(
            "optimizer state carries no mutable learning_rate; build the "
            "optimizer with optax.inject_hyperparams, e.g. "
            "optax.inject_hyperparams(optax.sgd)(learning_rate=0.01)"
        )
    return float(states[0].hyperparams["learning_rate"])


def set_learning_rate(opt_state, lr: float):
    """Return opt_state with learning_rate replaced (functional)."""
    states = _find_hyperparams(opt_state)
    if not states:
        raise ValueError(
            "optimizer state carries no mutable learning_rate; build the "
            "optimizer with optax.inject_hyperparams"
        )

    def replace(s):
        if getattr(s, "hyperparams", None) is not None and \
                "learning_rate" in s.hyperparams:
            hp = dict(s.hyperparams)
            hp["learning_rate"] = jnp.asarray(
                lr, dtype=jnp.asarray(hp["learning_rate"]).dtype)
            return s._replace(hyperparams=hp)
        if isinstance(s, tuple) and not hasattr(s, "hyperparams") and \
                not hasattr(s, "_fields"):
            return tuple(replace(item) for item in s)
        return s

    return replace(opt_state)


def _scale_momentum(opt_state, factor: float):
    """Momentum correction: scale the SGD velocity by new_lr/old_lr
    (reference callbacks_impl.py:81-91 restarts momentum at the corrected
    magnitude).

    Only momentum-SGD-style traces are corrected — the reference likewise
    applies correction only to optimizers with a ``momentum`` slot;
    adaptive optimizers (adam, lamb, ...) need none.  Returns
    ``(opt_state, found)`` so callers can warn when correction was
    requested but the optimizer carries no momentum trace.
    """
    momentum_types = [optax.TraceState]
    for name in ("ScaleByMomentumState",):  # newer optax momentum variants
        t = getattr(optax, name, None)
        if t is not None:
            momentum_types.append(t)
    momentum_types = tuple(momentum_types)
    found = False

    def visit(s):
        nonlocal found
        if isinstance(s, momentum_types):
            found = True
            return s._replace(
                trace=jax.tree.map(lambda t: t * factor, s.trace))
        if hasattr(s, "inner_state"):
            return s._replace(inner_state=visit(s.inner_state))
        if isinstance(s, tuple) and not hasattr(s, "_fields"):
            return tuple(visit(item) for item in s)
        return s

    return visit(opt_state), found


# ---------------------------------------------------------------------------
# Callbacks
# ---------------------------------------------------------------------------

class BroadcastGlobalVariablesCallback(Callback):
    """Sync initial params + optimizer state from ``root_rank`` at train
    start (reference callbacks_impl.py:20-30 / TF hook
    tensorflow/__init__.py:101-132)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, state):
        import horovod_tpu.jax as hvd

        params = hvd.broadcast_parameters(state.params, self.root_rank)
        opt_state = hvd.broadcast_optimizer_state(state.opt_state,
                                                  self.root_rank)
        return state.replace(params=params, opt_state=opt_state)


class MetricAverageCallback(Callback):
    """Average epoch metrics over all processes before reporting
    (reference callbacks_impl.py:33-67)."""

    def on_epoch_end(self, epoch: int, state, logs: dict):
        import horovod_tpu.jax as hvd

        for key in list(logs.keys()):
            value = logs[key]
            if isinstance(value, (int, float, np.floating, jnp.ndarray,
                                  np.ndarray)):
                logs[key] = float(np.asarray(
                    hvd.allreduce(jnp.asarray(value, jnp.float32),
                                  op=hvd.Average, name=f"metric.{key}")))
        return state


class LearningRateScheduleCallback(Callback):
    """Epoch-windowed LR multiplier, staircase or per-batch smooth, with
    momentum correction (reference callbacks_impl.py:70-147).

    ``multiplier``: constant or ``f(epoch) -> factor`` applied to
    ``initial_lr``.  With ``staircase=False``, ``epoch`` is fractional
    (epoch + batch/steps_per_epoch) and the lr updates every batch.
    """

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None):
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier
        self._last_lr: Optional[float] = None
        self._warned_no_momentum = False

    def _in_window(self, epoch: int) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def _apply(self, state, epoch: float):
        lr = self.initial_lr * self.multiplier(epoch)
        old = self._last_lr
        opt_state = set_learning_rate(state.opt_state, lr)
        if self.momentum_correction and old is not None and old > 0 \
                and lr != old:
            opt_state, found = _scale_momentum(opt_state, lr / old)
            if not found and not self._warned_no_momentum:
                self._warned_no_momentum = True
                import warnings

                warnings.warn(
                    "momentum_correction=True but the optimizer state "
                    "carries no SGD momentum trace (adaptive optimizers "
                    "like adam need no correction) — correction is a "
                    "no-op; pass momentum_correction=False to silence",
                    stacklevel=2)
        self._last_lr = lr
        return state.replace(opt_state=opt_state)

    def on_epoch_begin(self, epoch: int, state):
        if self.staircase and self._in_window(epoch):
            return self._apply(state, epoch)
        return state

    def on_batch_begin(self, epoch: int, batch: int, state):
        if not self.staircase and self._in_window(epoch):
            if self.steps_per_epoch is None:
                raise ValueError(
                    "staircase=False requires steps_per_epoch")
            return self._apply(state, epoch + batch / self.steps_per_epoch)
        return state


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Linear warmup from lr/size to lr over ``warmup_epochs`` (Goyal et
    al., reference callbacks_impl.py:149-168): at the start of large-batch
    training each process's lr ramps so the size-scaled rate arrives after
    warmup instead of at step 0."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None,
                 verbose: bool = False):
        import horovod_tpu.jax as hvd

        self.warmup_epochs = warmup_epochs
        self.verbose = verbose
        size = hvd.size() if hvd.is_initialized() else 1
        n = max(hvd.num_chips(), size)

        def multiplier(epoch: float) -> float:
            if epoch >= warmup_epochs:
                return 1.0
            # epoch/warmup linear ramp from 1/n to 1.
            return 1.0 / n * (epoch * (n - 1) / warmup_epochs + 1)

        super().__init__(initial_lr, multiplier, start_epoch=0,
                         end_epoch=warmup_epochs + 1, staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)

    def on_epoch_end(self, epoch: int, state, logs: dict):
        if self.verbose and epoch < self.warmup_epochs \
                and self._last_lr is not None:
            print(f"Epoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {self._last_lr:.6g}.")
        return state
