"""Gradient compression for the TensorFlow frontend.

Reference parity: ``horovod/tensorflow/compression.py`` (74 LoC) — a
``Compressor`` interface with ``none``/``fp16`` members; compress casts
floats down for the wire, decompress casts back.  Adds ``bf16``: on the
host data plane bf16 halves wire bytes with float32's exponent range, and
it round-trips exactly through the TPU compute dtype.
"""

from __future__ import annotations

import tensorflow as tf

__all__ = ["Compressor", "NoneCompressor", "FP16Compressor",
           "BF16Compressor", "Compression"]


class Compressor:
    """Interface for compressing and decompressing a given tensor."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, ctx) where ctx feeds decompress."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: tf.DType

    @classmethod
    def compress(cls, tensor):
        if tensor.dtype.is_floating and tensor.dtype != cls.wire_dtype:
            return tf.cast(tensor, cls.wire_dtype), tensor.dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tf.cast(tensor, ctx) if ctx is not None else tensor


class FP16Compressor(_CastCompressor):
    wire_dtype = tf.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = tf.bfloat16


class Compression:
    """Registry (reference compression.py:67-74)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
