"""Reference-style import alias: ``horovod.tensorflow.keras`` users
import ``horovod_tpu.tf.keras``.

Reference parity: ``horovod/tensorflow/keras/__init__.py`` is a thin
re-export of the same impl as ``horovod/keras`` (SURVEY.md §2.2 P10, a
byte-level near-copy of P8).  Here the real implementation lives in
``horovod_tpu.keras`` (Keras 3, multi-backend — on TF 2.21 ``tf.keras``
IS Keras 3, so one frontend serves both import styles); this module
re-exports it under the familiar path.
"""

from horovod_tpu.keras import *                    # noqa: F401,F403
from horovod_tpu.keras import callbacks, __all__   # noqa: F401
