"""TensorFlow collective ops over the native engine.

Reference parity: ``horovod/tensorflow/mpi_ops.py`` (182 LoC) — the
``_allreduce``/``allgather``/``broadcast`` op surface with gradient
registrations (mpi_ops.py:93-182: allreduce-grad = allreduce;
allgather-grad = allreduce + own slice; broadcast-grad = allreduce,
zeroed off-root).

TPU-native design: the reference registers custom async C++ TF ops
(``tensorflow/mpi_ops.cc:276-463``) whose callbacks re-enter the TF
executor.  On this stack TensorFlow is a HOST-side frontend — the
accelerator compute path is JAX/XLA — so collectives execute inside
``tf.py_function`` against the same native TCP engine the torch frontend
uses (zero-copy numpy buffers, ``horovod_tpu/cpp``), and gradients come
from ``tf.custom_gradient`` instead of ``ops.RegisterGradient``.  One
implementation then serves eager, ``tf.function`` graphs, and
``tf.compat.v1`` Sessions, with no TF build-time dependency.

Multi-step backward collectives (allgather's sizes-gather + grad
allreduce) run inside a SINGLE ``tf.py_function`` with async enqueues:
two separate py_functions could be scheduled in opposite orders on
different ranks and deadlock a thread-starved executor, while async
enqueue + joint synchronize is order-independent.

Naming contract: the engine rendezvous is keyed by tensor name, which
must match across ranks.  Auto-names come from a per-kind counter at
trace/eager-call time — identical across ranks when ranks build the same
program in the same order, the same contract as the reference's
graph-determined op names (mpi_ops.py:88-89).
"""

from __future__ import annotations

import re
import threading
from typing import Optional

import numpy as np
import tensorflow as tf

from horovod_tpu.common.basics import basics
from horovod_tpu.runtime import engine_or_none as _engine

__all__ = [
    "init", "shutdown", "size", "rank", "local_size", "local_rank",
    "epoch", "_allreduce", "_grouped_allreduce", "allgather", "broadcast",
]

init = basics.init
shutdown = basics.shutdown
rank = basics.rank
size = basics.size
local_rank = basics.local_rank
local_size = basics.local_size
epoch = basics.epoch


def _normalize_name(name: str) -> str:
    """Normalizes an op name to TensorFlow rules (reference
    mpi_ops.py:72-74)."""
    return re.sub("[^a-zA-Z0-9_]", "_", name)


_name_lock = threading.Lock()
_name_counters: dict = {}


def _auto_name(kind: str, name: Optional[str]) -> str:
    if name is not None:
        return _normalize_name(name)
    with _name_lock:
        idx = _name_counters.get(kind, 0)
        _name_counters[kind] = idx + 1
    return f"tf_{kind}_noname_{idx}"


def _np(t: tf.Tensor) -> np.ndarray:
    """Fresh writable contiguous host buffer (the engine reduces in
    place; ``.numpy()`` may alias TF-owned memory).  bf16 arrives as an
    ``ml_dtypes.bfloat16`` array, which the engine understands."""
    return t.numpy().copy()


# The collective builders below (and their tf/__init__ wrappers) carry
# @do_not_convert: they stage py_function/custom_gradient ops with no
# tensor-dependent Python control flow, so autograph conversion buys
# nothing — and its converted-call cache can MISRESOLVE a callee under a
# long test session (observed: `_allreduce(x, name=...)` dispatching to
# the converted `_np`), breaking tf.function-traced training loops.
@tf.autograph.experimental.do_not_convert
def _allreduce(tensor, name: Optional[str] = None, parts_out=None,
               priority: Optional[int] = None):
    """Sum ``tensor`` over all processes (reference mpi_ops.py:77-90).

    Same shape/dtype on every rank for a given name; differentiable
    (gradient of a sum-allreduce is an allreduce, mpi_ops.py:93-104).

    ``parts_out`` (optional list): receives one int64 scalar tensor —
    the committed PARTICIPANT count of the reduction (0 = unknown,
    caller falls back to size).  Divisor-correct averaging under
    backup-worker partial commits (HOROVOD_BACKUP_WORKERS) divides by
    it instead of blindly by size.

    ``priority`` (0 = most urgent) is the scheduling priority the
    priority-banded coordinator (HOROVOD_PRIORITY_BANDS) orders
    responses by; the grouped builder stamps it from batch position
    (registration order).
    """
    op_name = _auto_name("allreduce", name)
    # Written by the host call, read by the participants py_function
    # strictly after it (data dependency through the output): per-op
    # cell, same trace-lifetime caveat as any py_function state.
    parts_cell = [0]

    @tf.custom_gradient
    def fn(x):
        def _host(xt):
            eng = _engine()
            if eng is None:
                parts_cell[0] = 1
                return xt.numpy()
            arr = _np(xt)
            info = {}
            out = eng.synchronize(
                eng.enqueue_allreduce(arr, name=op_name,
                                      priority=priority), info)
            parts_cell[0] = int(info.get("participants") or 0)
            return out

        out = tf.py_function(_host, [x], Tout=x.dtype)
        out.set_shape(x.shape)

        def grad(dy):
            return _allreduce(dy, name=op_name + "_grad")

        return out, grad

    out = fn(tf.convert_to_tensor(tensor))
    if parts_out is not None:
        # tf.size(out) is a cheap scalar data-dependency on the host
        # call's output, ordering this read after the cell write without
        # shipping the payload through a second py_function.
        parts_out.append(tf.py_function(
            lambda _s: np.int64(parts_cell[0]), [tf.size(out)], tf.int64))
    return out


@tf.autograph.experimental.do_not_convert
def _grouped_allreduce(tensors, names, parts_out=None):
    """Sum-allreduce a batch of tensors through ONE ``py_function``.

    Every tensor is async-enqueued before any is synchronized, so the
    coordinator negotiates the whole batch in a single cycle and the
    engine's fusion packs same-dtype tensors into single ring
    collectives — the reference's async-kernel + fusion property
    (``tensorflow/mpi_ops.cc:281-303`` + ``operations.cc:1815-1842``)
    carried onto the host data plane.  One host call per batch is also
    order-independent across ranks (see module docstring), where N
    independent blocking py_functions would each burn a negotiation
    cycle and could deadlock a thread-starved executor.

    Differentiable: the cotangent batch rides the same grouped path.
    """
    if len(tensors) != len(names):
        raise ValueError(f"{len(tensors)} tensors but {len(names)} names")
    if not tensors:
        return []
    names = list(names)
    # Per-tensor committed participant counts (see _allreduce.parts_out).
    parts_cells = [0] * len(names)

    @tf.custom_gradient
    def fn(*xs):
        def _host(*xts):
            eng = _engine()
            if eng is None:
                for i in range(len(parts_cells)):
                    parts_cells[i] = 1
                return [x.numpy() for x in xts]
            arrs = [_np(x) for x in xts]
            # Batch position = registration order = scheduling priority
            # (the priority-banded coordinator dispatches the
            # first-registered — front-layer — gradients first).
            handles = [eng.enqueue_allreduce(a, name=n, priority=i)
                       for i, (a, n) in enumerate(zip(arrs, names))]
            # eng.drain: every handle finishes even when one fails (an
            # abandoned handle leaks its buffer and leaves the name in
            # flight for the next step's batch).
            outs, infos, first_err = eng.drain(handles)
            for i, info in enumerate(infos):
                parts_cells[i] = int(info.get("participants") or 0)
            if first_err is not None:
                raise first_err
            return outs

        outs = tf.py_function(_host, list(xs), Tout=[x.dtype for x in xs])
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        for o, x in zip(outs, xs):
            o.set_shape(x.shape)

        def grad(*dys):
            return _grouped_allreduce(
                list(dys), [n + "_grad" for n in names])

        return list(outs), grad

    outs = fn(*[tf.convert_to_tensor(t) for t in tensors])
    if parts_out is not None:
        for i, o in enumerate(outs):
            parts_out.append(tf.py_function(
                lambda _s, i=i: np.int64(parts_cells[i]),
                [tf.size(o)], tf.int64))
    return outs


@tf.autograph.experimental.do_not_convert
def allgather(tensor, name: Optional[str] = None):
    """Concatenate each rank's tensor along dim 0 (reference
    mpi_ops.py:107-123).  Per-rank dim 0 may differ — it is negotiated at
    runtime — and the backward pass slices this rank's grad at its TRUE
    offset using a sizes-gather (mpi_ops.py:126-147)."""
    op_name = _auto_name("allgather", name)

    @tf.custom_gradient
    def fn(x):
        def _host(xt):
            eng = _engine()
            arr = xt.numpy()
            if arr.ndim == 0:
                arr = arr.reshape(1)
            if eng is None:
                return arr.copy()
            return eng.synchronize(
                eng.enqueue_allgather(np.ascontiguousarray(arr),
                                      name=op_name))

        out = tf.py_function(_host, [x], Tout=x.dtype)
        rest = ([x.shape[i] for i in range(1, x.shape.rank)]
                if x.shape.rank else [])
        out.set_shape([None] + rest)

        def grad(dy):
            def _host_grad(dyt, xt):
                eng = _engine()
                g = _np(dyt)
                if eng is None:
                    # gather was identity (modulo the scalar->[1] reshape)
                    return g.reshape(xt.shape)
                d0 = xt.shape[0] if xt.ndim > 0 else 1
                # Async enqueue both, then synchronize: one host call,
                # order-independent across ranks (see module docstring).
                h_sizes = eng.enqueue_allgather(
                    np.array([d0], np.int64), name=op_name + "_sizes")
                h_grad = eng.enqueue_allreduce(g, name=op_name + "_grad")
                sizes = eng.synchronize(h_sizes)
                eng.synchronize(h_grad)  # in-place into g
                off = int(sizes[: basics.rank()].sum())
                sl = g[off:off + d0]
                # scalars were reshaped to [1] on the way in
                return sl.reshape(()) if xt.ndim == 0 else sl

            gout = tf.py_function(_host_grad, [dy, x], Tout=dy.dtype)
            gout.set_shape(x.shape)
            return gout

        return out, grad

    return fn(tf.convert_to_tensor(tensor))


@tf.autograph.experimental.do_not_convert
def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Broadcast root's value to every rank (reference mpi_ops.py:150-164).

    Backward: sum-allreduce the grads, keep the result on the root, zero
    elsewhere (mpi_ops.py:167-182)."""
    if root_rank < 0 or root_rank >= basics.size():
        raise ValueError(
            f"root_rank {root_rank} out of range for size {basics.size()}")
    op_name = _auto_name("broadcast", name)

    @tf.custom_gradient
    def fn(x):
        def _host(xt):
            eng = _engine()
            if eng is None:
                return xt.numpy()
            arr = _np(xt)
            eng.synchronize(
                eng.enqueue_broadcast(arr, root_rank, name=op_name))
            return arr

        out = tf.py_function(_host, [x], Tout=x.dtype)
        out.set_shape(x.shape)

        def grad(dy):
            reduced = _allreduce(dy, name=op_name + "_grad")
            if basics.rank() != root_rank:
                reduced = reduced * tf.constant(0, dtype=reduced.dtype)
            return reduced

        return out, grad

    return fn(tf.convert_to_tensor(tensor))
