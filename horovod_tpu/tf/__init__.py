"""TensorFlow frontend: user API, optimizers, broadcast hooks.

Reference parity: ``horovod/tensorflow/__init__.py`` (225 LoC) —
``allreduce`` with IndexedSlices + compression (45-87),
``broadcast_global_variables`` (90-98), ``BroadcastGlobalVariablesHook``
(101-132), ``DistributedOptimizer`` overriding ``compute_gradients``
(135-225).

TPU-native design: TensorFlow here is a host-side frontend over the same
native TCP engine as the torch frontend (the accelerator path is
JAX/XLA) — see ``horovod_tpu/tf/mpi_ops.py``.  Beyond the reference's
v1-Session surface this module adds the TF2-native idioms the reference
predates: ``DistributedGradientTape`` for eager/`tf.function` training
loops, ``broadcast_variables`` for object-based checkpointing code, and
``create_distributed_optimizer`` wrapping Keras-3 optimizers (`tf.keras`
IS Keras 3 in the installed TF 2.21).
"""

from __future__ import annotations

from typing import Optional

import tensorflow as tf

from horovod_tpu.tf.compression import Compression
from horovod_tpu.tf.mpi_ops import (
    init, shutdown, size, rank, local_size, local_rank, epoch,
    _allreduce, _grouped_allreduce, _auto_name, allgather, broadcast,
    _normalize_name,
)

__all__ = [
    "init", "shutdown", "size", "rank", "local_size", "local_rank", "epoch",
    "allreduce", "grouped_allreduce", "allgather", "broadcast",
    "broadcast_variables", "broadcast_global_variables",
    "BroadcastGlobalVariablesHook", "DistributedOptimizer",
    "DistributedGradientTape", "create_distributed_optimizer",
    "Compression",
]


def _avg(summed, dtype, parts=None):
    """sum → average.  ``parts`` (optional int64 scalar tensor) is the
    committed participant count from the reduction — divisor-correct
    under backup-worker partial commits (HOROVOD_BACKUP_WORKERS), where
    fewer than ``size`` ranks contributed; 0/None falls back to size."""
    n = tf.cast(size(), dtype)
    if parts is not None:
        p = tf.cast(parts, dtype)
        n = tf.where(p > 0, p, n)
    if summed.dtype.is_floating or summed.dtype.is_complex:
        return summed / n
    return summed // n


@tf.autograph.experimental.do_not_convert
def allreduce(tensor, average: bool = True, device_dense: str = "",
              device_sparse: str = "", compression=Compression.none,
              name: Optional[str] = None):
    """Allreduce a ``tf.Tensor`` or ``tf.IndexedSlices``.

    IndexedSlices are reduced as two allgathers over values and indices —
    the represented dense sum — instead of densifying (reference
    __init__.py:67-78).  Dense tensors ride the compression wire format
    (__init__.py:79-87).  ``device_dense``/``device_sparse`` are accepted
    for API parity; placement is meaningless on the host data plane.
    """
    if isinstance(tensor, tf.IndexedSlices):
        values = allgather(tensor.values,
                           name=None if name is None else name + "_values")
        indices = allgather(tensor.indices,
                            name=None if name is None else name + "_indices")
        new_values = _avg(values, values.dtype) if average else values
        return tf.IndexedSlices(new_values, indices,
                                dense_shape=tensor.dense_shape)
    tensor = tf.convert_to_tensor(tensor)
    compressed, ctx = compression.compress(tensor)
    parts_out = [] if average else None
    summed = _allreduce(compressed, name=name, parts_out=parts_out)
    summed = compression.decompress(summed, ctx)
    if not average:
        return summed
    return _avg(summed, tensor.dtype, parts_out[0] if parts_out else None)


@tf.autograph.experimental.do_not_convert
def grouped_allreduce(tensors, average: bool = True,
                      compression=Compression.none,
                      name: Optional[str] = None, names=None):
    """Allreduce a list of dense tensors in ONE negotiation cycle (one
    ``py_function`` async-enqueues the whole batch; the engine fuses
    same-dtype tensors into single ring collectives).  This is the hot
    path under :class:`DistributedOptimizer` and
    :class:`DistributedGradientTape`.

    ``name`` prefixes auto-generated per-tensor names (a fresh counter
    suffix is drawn when omitted, so overlapping default-named calls
    cannot collide in the engine); ``names`` supplies exact per-tensor
    rendezvous names instead."""
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    if names is None:
        prefix = _auto_name("grouped_allreduce", name and
                            _normalize_name(name))
        names = [f"{prefix}_{i}" for i in range(len(tensors))]
    compressed, ctxs = [], []
    for t in tensors:
        c, ctx = compression.compress(t)
        compressed.append(c)
        ctxs.append(ctx)
    parts_out = [] if average else None
    summed = _grouped_allreduce(compressed, names, parts_out=parts_out)
    outs = []
    for i, (s, ctx, t) in enumerate(zip(summed, ctxs, tensors)):
        s = compression.decompress(s, ctx)
        if average:
            p = parts_out[i] if parts_out and i < len(parts_out) else None
            outs.append(_avg(s, t.dtype, p))
        else:
            outs.append(s)
    return outs


def _group_reduce_grads(grads, names, compression, sparse_as_dense,
                        average: bool = True):
    """Average a gradient structure across ranks: ``None`` passes
    through, IndexedSlices ride the sparse allgather path per tensor,
    and every dense gradient joins ONE grouped allreduce."""
    out = list(grads)
    dense_idx = []
    for i, g in enumerate(grads):
        if g is None:
            continue
        if isinstance(g, tf.IndexedSlices):
            if sparse_as_dense:
                out[i] = tf.convert_to_tensor(g)
                dense_idx.append(i)
            else:
                out[i] = allreduce(g, average=average,
                                   compression=compression, name=names[i])
        else:
            dense_idx.append(i)
    if dense_idx:
        reduced = grouped_allreduce(
            [out[i] for i in dense_idx], average=average,
            compression=compression,
            names=[names[i] for i in dense_idx])
        for j, i in enumerate(dense_idx):
            out[i] = reduced[j]
    return out


# ---------------------------------------------------------------------------
# variable broadcast
# ---------------------------------------------------------------------------

def broadcast_variables(variables, root_rank: int):
    """Assign root's value of every variable on every rank (the TF2
    object-based counterpart of ``broadcast_global_variables``)."""
    return tf.group(*[
        var.assign(broadcast(var, root_rank,
                             name=_normalize_name(getattr(var, "name", None)
                                                  or f"var_{i}")))
        for i, var in enumerate(variables)
    ])


def broadcast_global_variables(root_rank: int):
    """Broadcast all v1 global variables from ``root_rank`` (reference
    __init__.py:90-98; requires a ``tf.compat.v1`` graph context)."""
    return broadcast_variables(tf.compat.v1.global_variables(), root_rank)


class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
    """SessionRunHook broadcasting all global variables from root rank
    after session creation, so every worker starts from identical weights
    whether initialized randomly or restored from a checkpoint
    (reference __init__.py:101-132)."""

    def __init__(self, root_rank: int, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        self.bcast_op = None
        self.device = device  # API parity; host data plane has no devices

    def begin(self):
        graph = tf.compat.v1.get_default_graph()
        if self.bcast_op is None or self.bcast_op.graph is not graph:
            self.bcast_op = broadcast_global_variables(self.root_rank)

    def after_create_session(self, session, coord):
        session.run(self.bcast_op)


# ---------------------------------------------------------------------------
# optimizers
# ---------------------------------------------------------------------------

class DistributedOptimizer(tf.compat.v1.train.Optimizer):
    """Wraps a ``tf.compat.v1.train.Optimizer``; ``compute_gradients``
    also averages the gradients across ranks before they are applied
    (reference __init__.py:135-225).  All dense gradients ride a single
    grouped allreduce — one negotiation cycle, fused rings — matching
    the reference's async+fusion hot path.

    For a Keras optimizer, use :func:`create_distributed_optimizer`; for
    an eager/`tf.function` training loop, :class:`DistributedGradientTape`.
    """

    def __init__(self, optimizer, name: Optional[str] = None,
                 use_locking: bool = False, device_dense: str = "",
                 device_sparse: str = "", compression=Compression.none,
                 sparse_as_dense: bool = False):
        if name is None:
            name = "Distributed{}".format(type(optimizer).__name__)
        self._optimizer = optimizer
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        super().__init__(name=name, use_locking=use_locking)

    def compute_gradients(self, *args, **kwargs):
        """Averages the wrapped optimizer's gradients across ranks
        (reference __init__.py:183-209)."""
        gradients = self._optimizer.compute_gradients(*args, **kwargs)
        if size() <= 1:
            return gradients
        with tf.name_scope(self._name + "_Allreduce"):
            grads = [g for g, _ in gradients]
            names = ["DistributedGrad_" + _normalize_name(v.name)
                     for _, v in gradients]
            reduced = _group_reduce_grads(
                grads, names, self._compression, self._sparse_as_dense)
            return [(g, v) for g, (_, v) in zip(reduced, gradients)]

    def apply_gradients(self, *args, **kwargs):
        return self._optimizer.apply_gradients(*args, **kwargs)

    def get_slot(self, *args, **kwargs):
        return self._optimizer.get_slot(*args, **kwargs)

    def get_slot_names(self, *args, **kwargs):
        return self._optimizer.get_slot_names(*args, **kwargs)

    def variables(self, *args, **kwargs):
        return self._optimizer.variables(*args, **kwargs)


def create_distributed_optimizer(optimizer, name: Optional[str] = None,
                                 compression=Compression.none,
                                 sparse_as_dense: bool = False):
    """Wrap a Keras-3 optimizer (``tf.keras`` IS Keras 3 on TF 2.21): a
    dynamic subclass whose ``apply``/``apply_gradients`` first averages
    the incoming gradients across ranks.

    The reference's counterpart (``horovod/keras/impl.py:20-70``) hooked
    Keras-2's ``get_gradients``; Keras 3 funnels both ``apply_gradients``
    and ``Model.fit`` through ``apply``, which is the single choke point
    here.  Config round-trips (``get_config``/``from_config``), so
    ``keras.models.load_model`` reconstruction works — see
    ``horovod_tpu/keras``.
    """
    cls = type(optimizer)

    class _DistributedKerasOptimizer(cls):
        _hvd_compression = compression
        _hvd_sparse_as_dense = sparse_as_dense

        def apply(self, grads, trainable_variables=None, **kwargs):
            if size() > 1:
                grads = _group_reduce_grads(
                    list(grads),
                    [f"DistributedGrad_{i}" for i in range(len(grads))],
                    self._hvd_compression, self._hvd_sparse_as_dense)
            return super().apply(grads, trainable_variables, **kwargs)

    _DistributedKerasOptimizer.__name__ = "Distributed" + cls.__name__
    dist = _DistributedKerasOptimizer.from_config(optimizer.get_config())
    if name is not None:
        dist.name = name
    return dist


class DistributedGradientTape:
    """A ``tf.GradientTape`` wrapper whose ``gradient()`` averages the
    results across ranks — the TF2-native replacement for
    ``compute_gradients`` interception::

        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = loss_fn(model(x), y)
        grads = tape.gradient(loss, model.trainable_variables)

    Gradient names are positional (the structure of ``sources`` is
    identical across ranks), so rendezvous needs no variable names.
    """

    def __init__(self, gradtape: tf.GradientTape,
                 compression=Compression.none,
                 sparse_as_dense: bool = False, average: bool = True):
        self._tape = gradtape
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        self._average = average

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        return self._tape.__exit__(exc_type, exc, tb)

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        if size() <= 1:
            return grads
        flat = tf.nest.flatten(grads)
        reduced = _group_reduce_grads(
            flat,
            [f"DistributedGradientTape_grad_{i}" for i in range(len(flat))],
            self._compression, self._sparse_as_dense,
            average=self._average)
        return tf.nest.pack_sequence_as(grads, reduced)
