"""One serving replica: model runner + scheduler + TCP endpoint.

``python -m horovod_tpu.serve.replica --port P`` builds the model from
the serve env knobs (every replica derives identical weights from
``HOROVOD_SERVE_PARAM_SEED``), starts the continuous-batching scheduler
on its own thread, and serves the JSON-lines protocol.  Prints
``SERVE_REPLICA_READY port=<p> replica=<i>`` once accepting.

Engine world: under ``HOROVOD_SERVE_ENGINE=1`` the replica calls
``hvd.init()`` so it IS an engine world (the launcher env decides the
world size) — its stats/autotune/elastic machinery runs alongside
serving.  The default keeps the replica engine-free: the serve data path
is pure JAX and a one-rank world adds nothing but startup cost.

Fault injection: the replica honors the engine's
``HOROVOD_FAULT_INJECT`` schedule format (``rank:step:kind[,...]``) with
the *replica index* (``HOROVOD_REPLICA_ID``) standing in for the rank
and the scheduler's decode-step counter for the step — ``exit`` hard-
kills the process (exit 41, matching the engine's injected-exit code),
``hang`` wedges the scheduler thread, ``conn-reset`` aborts every open
connection ONCE (transient link loss: router sessions park and heal
under HOROVOD_SERVE_LINK_RETRIES; the process keeps serving).  The router's supervisor scrubs
the schedule on relaunch exactly like ``run.py --restart-on-failure``
does, so a fault fires once, not on every incarnation.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import threading
import time
from typing import Callable, Optional, Tuple

__all__ = ["main", "parse_fault_schedule"]


def parse_fault_schedule(raw: Optional[str],
                         replica_id: int) -> Optional[Tuple[int, str]]:
    """The engine's ``rank:step:kind`` comma schedule, applied to this
    replica index.  Returns (step, kind) or None; malformed entries are
    ignored (same leniency as the engine's parser)."""
    if not raw:
        return None
    for part in raw.split(","):
        bits = part.strip().split(":")
        if len(bits) != 3:
            continue
        try:
            rank, step = int(bits[0]), int(bits[1])
        except ValueError:
            continue
        if rank == replica_id and bits[2] in ("exit", "hang",
                                              "conn-reset"):
            return step, bits[2]
    return None


def _fault_hook(replica_id: int,
                server_cell=None) -> Optional[Callable[[int], None]]:
    sched = parse_fault_schedule(os.environ.get("HOROVOD_FAULT_INJECT"),
                                 replica_id)
    if sched is None:
        return None
    fire_step, kind = sched
    fired = [False]

    def hook(step: int) -> None:
        if step < fire_step:
            return
        if kind == "conn-reset":
            # One-shot: a transient reset, not a dead link every step.
            # The hook runs on the scheduler thread; drop_connections
            # trampolines onto the server's event loop.
            if fired[0] or not server_cell:
                return
            fired[0] = True
            sys.stderr.write(f"[serve replica {replica_id}] injected "
                             f"fault 'conn-reset' at decode step "
                             f"{step}\n")
            sys.stderr.flush()
            server_cell[0].drop_connections()
            return
        sys.stderr.write(f"[serve replica {replica_id}] injected fault "
                         f"{kind!r} at decode step {step}\n")
        sys.stderr.flush()
        if kind == "exit":
            os._exit(41)
        time.sleep(3600)  # hang: wedge the scheduler thread

    return hook


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.serve.replica",
        description="One inference-serving replica (JSON lines over TCP).")
    parser.add_argument("--port", type=int, default=0,
                        help="listen port (0 = ephemeral; the bound port "
                             "is printed in the READY line)")
    parser.add_argument("--host", default="127.0.0.1")
    args = parser.parse_args(argv)

    from horovod_tpu.serve.config import ServeConfig
    from horovod_tpu.serve.engine import ModelRunner
    from horovod_tpu.serve.scheduler import Scheduler
    from horovod_tpu.serve.server import ReplicaServer

    replica_id = int(os.environ.get("HOROVOD_REPLICA_ID", "0"))
    cfg = ServeConfig.from_env()

    if os.environ.get("HOROVOD_SERVE_ENGINE") == "1":
        # The replica is an engine world: rendezvous with whatever ranks
        # the launcher spawned for it (stats/autotune/elastic live).
        import horovod_tpu as hvd

        hvd.init()

    runner = ModelRunner(cfg)
    if cfg.warmup_tokens:
        n = runner.warmup()
        print(f"SERVE_REPLICA_WARMUP replica={replica_id} programs={n}",
              flush=True)
    # The conn-reset fault needs the server, which is built inside the
    # loop AFTER the scheduler — hand the hook a late-bound cell.
    server_cell: list = []
    scheduler = Scheduler(runner, cfg,
                          step_hook=_fault_hook(replica_id, server_cell))
    sched_thread = threading.Thread(target=scheduler.run, daemon=True)
    sched_thread.start()

    async def amain() -> None:
        server = ReplicaServer(scheduler)
        server_cell.append(server)
        port = await server.start(args.host, args.port)
        print(f"SERVE_REPLICA_READY port={port} replica={replica_id}",
              flush=True)
        await server.serve_until_shutdown()

    asyncio.run(amain())
    scheduler.stop()
    sched_thread.join(timeout=10)
    return 0


if __name__ == "__main__":
    sys.exit(main())
