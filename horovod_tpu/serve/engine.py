"""The replica's model execution engine: jitted paged prefill/decode.

Owns the parameter pytree and the physical KV block pool, and exposes
two host-level calls the scheduler drives:

* ``prefill(prompt, table)`` — one sequence's prompt through the model
  in a single batched pass, K/V scattered into its funded blocks;
  returns the last-position logits.
* ``decode(tokens, tables, pos)`` — one token for every running
  sequence in a single batched step over the paged pool.

Static shapes via power-of-two padding buckets (prompt length for
prefill, batch width for decode), so each bucket compiles once; padded
batch rows point at the trash block and their outputs are discarded on
the host.  Every forward attends a physical cache of exactly
``max_blocks_per_seq * block_size`` slots — logits depend bitwise on
that length AND on eager-vs-jit program structure, so pinning it makes
serve streams bit-identical to offline ``jax.jit(generate)`` at
``cache_len=max_model_len`` regardless of batch composition
(``tests/test_serve.py`` pins paged ≡ contiguous and serve ≡ offline).

Parameters are built deterministically from ``HOROVOD_SERVE_PARAM_SEED``
so every replica serves identical weights without shipping a checkpoint;
a checkpointed deployment sets ``HOROVOD_SERVE_CHECKPOINT`` (what
``run.py --serve --serve-model <dir>`` does) and every replica loads
the newest complete manifest's ``params`` tree instead — trained
weights at boot, with live trainer pushes layering on top
(docs/checkpointing.md).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Sequence

import numpy as np

from horovod_tpu.serve.config import ServeConfig, _pow2_at_least
from horovod_tpu.serve.kv_cache import TRASH_BLOCK

__all__ = ["ModelRunner", "build_model_config",
           "serve_collective_priority", "SERVE_DECODE_BAND"]

#: The band serve-plane collectives stamp: 0 = most urgent, so decode
#: traffic preempts bulk gradient fusion when a replica shares an engine
#: world with training (the PR 15 priority seam).
SERVE_DECODE_BAND = 0


def serve_collective_priority(environ=None) -> Optional[int]:
    """Priority the serve engine stamps on its collectives, or None when
    stamping does not apply (no engine world, or priority bands off —
    the engine then uses its legacy unstamped path, exactly as before).

    Jax-free and cheap: replicas call it per enqueue.  Only meaningful
    under ``HOROVOD_SERVE_ENGINE=1`` (the replica IS an engine world)
    with ``HOROVOD_PRIORITY_BANDS>0``; serve decode always takes band
    ``SERVE_DECODE_BAND`` (0, most urgent) so mixed serve+train traffic
    dispatches serve first — ``priority_inversions`` stays 0
    (tests/test_priority.py).
    """
    env = os.environ if environ is None else environ
    if env.get("HOROVOD_SERVE_ENGINE") != "1":
        return None
    try:
        bands = int(env.get("HOROVOD_PRIORITY_BANDS", "0") or "0")
    except ValueError:
        bands = 0
    return SERVE_DECODE_BAND if bands > 0 else None


def build_model_config(serve_cfg: ServeConfig):
    """Resolve HOROVOD_SERVE_MODEL/_DTYPE into a LlamaConfig."""
    import jax.numpy as jnp

    from horovod_tpu.models.llama import LlamaConfig

    builder = getattr(LlamaConfig, serve_cfg.model, None)
    if builder is None:
        raise ValueError(f"unknown serve model {serve_cfg.model!r} "
                         "(no LlamaConfig builder of that name)")
    cfg = builder()
    if serve_cfg.dtype:
        dt = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}.get(
            serve_cfg.dtype)
        if dt is None:
            raise ValueError(f"unsupported HOROVOD_SERVE_DTYPE "
                             f"{serve_cfg.dtype!r}")
        cfg = dataclasses.replace(cfg, dtype=dt, logits_dtype=dt)
    return cfg


class ModelRunner:
    """Jitted paged-KV model execution for one replica."""

    def __init__(self, serve_cfg: ServeConfig):
        import jax
        import jax.numpy as jnp

        from horovod_tpu.models.llama import LlamaModel

        self._jax, self._jnp = jax, jnp
        self.serve_cfg = serve_cfg
        self.model_cfg = build_model_config(serve_cfg)
        mcfg = self.model_cfg
        model = LlamaModel(mcfg)
        dummy = jnp.zeros((1, 8), jnp.int32)
        self.variables = model.init(jax.random.key(serve_cfg.param_seed),
                                    dummy)
        #: manifest step the params came from (None = seeded params)
        self.checkpoint_step = None
        if serve_cfg.checkpoint:
            self._restore_checkpoint(serve_cfg.checkpoint)
        self.block_size = serve_cfg.block_size
        self.max_blocks_per_seq = serve_cfg.max_blocks_per_seq
        #: pool blocks INCLUDING the reserved trash block 0
        self.num_blocks = serve_cfg.kv_blocks + 1
        shape = (mcfg.num_layers, self.num_blocks, self.block_size,
                 mcfg.num_kv_heads, mcfg.head_dim)
        self.pool_k = jnp.zeros(shape, mcfg.dtype)
        self.pool_v = jnp.zeros(shape, mcfg.dtype)
        #: fused paged-attention decode (HOROVOD_SERVE_FUSED_ATTN) —
        #: static per runner, baked into every decode jit.
        self.fused_attn = bool(serve_cfg.fused_attn)
        self._prefill_fns: Dict[object, object] = {}
        self._decode_fns: Dict[int, object] = {}
        self.compilations = 0

    def _restore_checkpoint(self, directory: str) -> None:
        """Replace the seeded params with the newest complete
        checkpoint's ``params`` tree (walk-path fill: shape-checked per
        leaf, cast into the model's own dtype).  Raises loudly on a
        torn/absent checkpoint or a geometry mismatch — serving random
        weights silently is worse than not starting."""
        from horovod_tpu.checkpoint import CheckpointError, CheckpointLoader

        loader = CheckpointLoader(directory)
        try:
            if "params" not in loader.slot_names():
                raise CheckpointError(
                    f"checkpoint step {loader.step} in {directory} has "
                    f"no 'params' slot (slots: {loader.slot_names()}) — "
                    "was it written by a trainer capture?")
            variables = dict(self.variables)
            variables["params"] = loader.restore_tree(
                variables["params"], "params")
            self.variables = variables
            self.checkpoint_step = loader.step
        finally:
            loader.close()

    # -- jit caches --

    def _prefill_fn(self, s_pad: int, start_blk: int = 0):
        key = s_pad if start_blk == 0 else (s_pad, start_blk)
        fn = self._prefill_fns.get(key)
        if fn is None:
            from horovod_tpu.models.generation import paged_prefill

            # Physical cache length is pinned to the decode geometry
            # (max_blocks_per_seq * block_size) so prefill and every
            # decode step attend the same reduction shape — the
            # bit-reproducibility contract (see paged_prefill).
            cache_len = self.max_blocks_per_seq * self.block_size

            def impl(variables, pool_k, pool_v, prompt, table, prompt_len):
                return paged_prefill(self.model_cfg, variables, prompt,
                                     pool_k, pool_v, table,
                                     prompt_len=prompt_len,
                                     cache_len=cache_len,
                                     start_blk=start_blk)

            fn = self._jax.jit(impl, donate_argnums=(1, 2))
            self._prefill_fns[key] = fn
            self.compilations += 1
        return fn

    def _prefill_suffix_fn(self, s_pad: int):
        """Prefix-cache hit path: ONE program per suffix bucket, the hit
        offset rides as a traced operand (``paged_prefill_suffix``) —
        compile count stays O(buckets), not O(buckets x hit offsets)."""
        key = ("sfx", s_pad)
        fn = self._prefill_fns.get(key)
        if fn is None:
            from horovod_tpu.models.generation import paged_prefill_suffix

            cache_len = self.max_blocks_per_seq * self.block_size

            def impl(variables, pool_k, pool_v, prompt, table, prompt_len,
                     start):
                return paged_prefill_suffix(self.model_cfg, variables,
                                            prompt, pool_k, pool_v, table,
                                            prompt_len=prompt_len,
                                            start=start,
                                            cache_len=cache_len)

            fn = self._jax.jit(impl, donate_argnums=(1, 2))
            self._prefill_fns[key] = fn
            self.compilations += 1
        return fn

    def _decode_fn(self, b_pad: int):
        fn = self._decode_fns.get(b_pad)
        if fn is None:
            from horovod_tpu.models.generation import paged_decode_step

            fused = self.fused_attn

            def impl(variables, pool_k, pool_v, tokens, tables, pos):
                return paged_decode_step(self.model_cfg, variables, tokens,
                                         pool_k, pool_v, tables, pos,
                                         fused=fused)

            fn = self._jax.jit(impl, donate_argnums=(1, 2))
            self._decode_fns[b_pad] = fn
            self.compilations += 1
        return fn

    # -- host API --

    def warmup(self, max_tokens: int = 0) -> int:
        """Pre-compile the programs steady-state serving will need —
        every pow2 decode batch bucket up to ``max_batch`` and every
        pow2 prefill bucket up to ``max_tokens`` (0 = the
        ``HOROVOD_SERVE_WARMUP`` knob; includes the prefix-cache hit
        path's suffix programs when prefix caching is on).  Run before
        taking traffic so jit compilation lands in replica startup
        rather than inside the first unlucky requests' latency window.
        Dummy operands route every K/V write to the trash block, so no
        allocatable pool block is touched.  Returns the number of
        programs compiled."""
        jnp = self._jnp
        cap = int(max_tokens) or self.serve_cfg.warmup_tokens
        if cap <= 0:
            return 0
        before = self.compilations
        cache_len = self.max_blocks_per_seq * self.block_size
        tbl = jnp.asarray(np.full((self.max_blocks_per_seq,), TRASH_BLOCK,
                                  np.int32))
        b = 1
        while True:
            tbls = jnp.asarray(np.full((b, self.max_blocks_per_seq),
                                       TRASH_BLOCK, np.int32))
            zeros = jnp.zeros((b,), jnp.int32)
            fn = self._decode_fn(b)
            _, self.pool_k, self.pool_v = fn(
                self.variables, self.pool_k, self.pool_v, zeros, tbls,
                zeros)
            if b >= self.serve_cfg.max_batch:
                break
            b *= 2
        s = self.block_size
        top = min(_pow2_at_least(cap, self.block_size), cache_len)
        while s <= top:
            prompt = jnp.zeros((1, s), jnp.int32)
            fn = self._prefill_fn(s)
            _, self.pool_k, self.pool_v = fn(
                self.variables, self.pool_k, self.pool_v, prompt, tbl, s)
            if self.serve_cfg.prefix_cache and self.block_size + s <= \
                    cache_len:
                # Hit-path suffix program for the same bucket; the start
                # offset is traced, so one dummy offset compiles it for
                # every future offset.
                fn = self._prefill_suffix_fn(s)
                _, self.pool_k, self.pool_v = fn(
                    self.variables, self.pool_k, self.pool_v, prompt, tbl,
                    self.block_size + s, self.block_size)
            s *= 2
        return self.compilations - before

    def prefill(self, prompt: Sequence[int], table: Sequence[int],
                *, start: int = 0) -> np.ndarray:
        """Prompt (len S0 >= 1) through the model; ``table`` must fund
        ceil(S0/block_size) blocks.  Returns fp32 last-position logits
        [V].

        ``start`` (block-aligned, < S0) is the prefix-cache hit path:
        the first ``start`` positions' K/V already sit in the table's
        shared leading blocks, so only the suffix is computed — and only
        blocks from ``start // block_size`` on are written (copy-on-
        write).  ``start=0`` is byte-for-byte the pre-prefix-cache
        program; the hit path is bit-identical to it
        (tests/test_serve.py pins both)."""
        jnp = self._jnp
        s0 = len(prompt)
        cache_len = self.max_blocks_per_seq * self.block_size
        if start % self.block_size or not 0 <= start < s0:
            raise ValueError(f"start {start} not block-aligned in [0, {s0})")
        start_blk = start // self.block_size
        # Pow2 bucket of the computed span, for few compiles.
        s_pad = _pow2_at_least(s0 - start, self.block_size)
        dynamic = bool(start) and start + s_pad <= cache_len
        if not dynamic:
            # Clip to the pinned physical cache length (always a block
            # multiple >= any legal prompt/suffix).
            s_pad = min(s_pad, cache_len - start)
        prompt_pad = np.zeros((1, s_pad), np.int32)
        prompt_pad[0, :s0 - start] = np.asarray(prompt[start:], np.int32)
        tbl = np.full((self.max_blocks_per_seq,), TRASH_BLOCK, np.int32)
        tbl[:len(table)] = np.asarray(table, np.int32)
        if dynamic:
            # Hit path: the offset is an operand, one compile per
            # bucket.  The guard keeps the UNCLIPPED padded suffix
            # inside the cache (a clamped dynamic_update_slice would
            # shift the writes); near-end overshoots take the static
            # fallback, whose clipped bucket is start-dependent anyway.
            fn = self._prefill_suffix_fn(s_pad)
            logits, self.pool_k, self.pool_v = fn(
                self.variables, self.pool_k, self.pool_v,
                jnp.asarray(prompt_pad), jnp.asarray(tbl), s0, start)
        else:
            fn = self._prefill_fn(s_pad, start_blk)
            logits, self.pool_k, self.pool_v = fn(
                self.variables, self.pool_k, self.pool_v,
                jnp.asarray(prompt_pad), jnp.asarray(tbl), s0)
        return np.asarray(logits[0]).astype(np.float32)

    def decode(self, tokens: Sequence[int], tables: Sequence[np.ndarray],
               pos: Sequence[int]) -> np.ndarray:
        """One token per running sequence; ``tables[i]`` is a
        [max_blocks_per_seq] int32 array.  Returns fp32 logits [B, V]."""
        jnp = self._jnp
        b = len(tokens)
        b_pad = _pow2_at_least(b, 1)
        toks = np.zeros((b_pad,), np.int32)
        toks[:b] = np.asarray(tokens, np.int32)
        tbls = np.full((b_pad, self.max_blocks_per_seq), TRASH_BLOCK,
                       np.int32)
        for i, t in enumerate(tables):
            tbls[i] = t
        ps = np.zeros((b_pad,), np.int32)
        ps[:b] = np.asarray(pos, np.int32)
        fn = self._decode_fn(b_pad)
        logits, self.pool_k, self.pool_v = fn(
            self.variables, self.pool_k, self.pool_v, jnp.asarray(toks),
            jnp.asarray(tbls), jnp.asarray(ps))
        return np.asarray(logits[:b]).astype(np.float32)
