"""Serve-plane autotuning: the scheduler's live knobs under the same
seeded coordinate-descent machinery the engine tuner uses.

The engine autotuner (horovod_tpu/autotune/) searches data-plane knobs
scored on bus bandwidth; the serve tuner reuses its
:class:`~horovod_tpu.autotune.search.CoordinateSearch` over the
scheduler's live-tunable knobs — ``max_batch`` (decode batch width) and
``prefill_waves`` (admissions per step) — scored on *tokens/sec* over
fixed-step windows of real traffic.  Trials apply atomically between
scheduler steps (the scheduler reads its knobs once per step), the
schedule is deterministic for a fixed ``HOROVOD_SERVE_AUTOTUNE_SEED``,
and the search commits the best point at convergence or at the trial
cap.  ``stats()["tune_trials"]`` counts completed trials; committed
values show up in ``stats()["config"]``.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from horovod_tpu.autotune.search import CoordinateSearch, ladder
from horovod_tpu.serve.config import ServeConfig

__all__ = ["ServeTuner"]


class ServeTuner:
    """Drives trial windows from the scheduler's own step loop —
    ``on_step()`` is called after every decode step, so an idle replica
    never burns a trial on an empty window."""

    def __init__(self, scheduler, cfg: ServeConfig):
        self._sched = scheduler
        self._window_steps = cfg.autotune_window_steps
        space = {
            "max_batch": ladder(1, max(1, cfg.max_batch)),
            "prefill_waves": ladder(1, max(1, cfg.prefill_waves * 4)),
        }
        base = {"max_batch": cfg.max_batch,
                "prefill_waves": cfg.prefill_waves}
        self.search = CoordinateSearch(space, seed=cfg.autotune_seed,
                                       base=base,
                                       max_trials=cfg.autotune_max_trials)
        self.trials = 0
        self.committed: Optional[Dict[str, int]] = None
        self._active = False
        self._steps = 0
        self._t0 = 0.0
        self._tokens0 = 0

    def _apply(self, cfg: Dict[str, int]) -> None:
        self._sched.max_batch = int(cfg["max_batch"])
        self._sched.prefill_waves = int(cfg["prefill_waves"])

    def stats(self) -> dict:
        """The tuner's stats surface (merged into scheduler stats):
        trial progress plus the serve counters its windows are scored
        against — tokens throughput and the prefix-cache/fused-kernel
        instruments, so a trial log can attribute a window's score."""
        kv = self._sched.kv
        hits, misses = kv.prefix_hits, kv.prefix_misses
        return {
            "tune_trials": self.trials,
            "tune_committed": int(self.committed is not None),
            "tune_window_steps": self._window_steps,
            "tune_prefix_hit_rate": (hits / (hits + misses)
                                     if hits + misses else 0.0),
            "tune_fused_attn_steps": self._sched._c["fused_attn_steps"],
        }

    def on_step(self) -> None:
        if self.committed is not None:
            return
        if not self._active:
            trial = self.search.propose()
            if trial is None:
                self.committed = dict(self.search.best)
                self._apply(self.committed)
                return
            self._apply(trial)
            self._active = True
            self._steps = 0
            self._t0 = time.monotonic()
            self._tokens0 = self._sched._c["tokens_streamed"]
            return
        self._steps += 1
        if self._steps < self._window_steps:
            return
        dt = time.monotonic() - self._t0
        tokens = self._sched._c["tokens_streamed"] - self._tokens0
        self.search.observe(tokens / dt if dt > 0 else None)
        self.trials = self.search.trials
        self._active = False
