"""Serve-plane knob resolution (env -> default -> effective).

Same contract as :mod:`horovod_tpu.autotune.config` for the engine
knobs: one place that resolves what the serving stack will actually
use — clamps and derived defaults included — without importing jax or
starting anything.  ``python -m horovod_tpu.run --print-config`` renders
these rows after the engine table (autotune/config.py pulls
:data:`SERVE_KNOBS` in), and a live replica's ``stats()["config"]``
reports the values in force (post serve-autotune).
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional

__all__ = ["ServeConfig", "resolved_serve_config", "SERVE_KNOBS",
           "resolve_probe_knobs", "resolve_link_retries"]


def _int_env(environ, name: str, dflt: int) -> int:
    raw = environ.get(name)
    if raw is None or raw == "":
        return dflt
    try:
        return int(raw)
    except ValueError:
        return dflt


def _pow2_at_least(v: int, lo: int) -> int:
    out = lo
    while out < v:
        out *= 2
    return out


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """The resolved serving knobs, all clamped/derived.

    ``block_size`` is forced to a power of two so prompt padding buckets
    stay block-aligned; ``kv_blocks`` counts allocatable blocks PLUS the
    reserved trash block is added internally by the pool; ``max_batch``
    and ``prefill_waves`` are live-tunable (the serve autotuner may
    rewrite them between steps).
    """

    model: str = "tiny"
    dtype: str = ""                 # "" = the model config's own dtype
    param_seed: int = 0
    checkpoint: str = ""            # "" = seeded params, no checkpoint
    block_size: int = 16
    kv_blocks: int = 64
    max_model_len: int = 256
    max_batch: int = 8
    prefill_waves: int = 1
    fused_attn: int = 0
    prefix_cache: int = 1
    warmup_tokens: int = 0
    autotune: int = 0
    autotune_seed: int = 0
    autotune_window_steps: int = 32
    autotune_max_trials: int = 12

    @property
    def max_blocks_per_seq(self) -> int:
        return -(-self.max_model_len // self.block_size)

    @staticmethod
    def from_env(environ=os.environ) -> "ServeConfig":
        block = _pow2_at_least(
            max(1, _int_env(environ, "HOROVOD_SERVE_BLOCK_SIZE", 16)), 1)
        # Rounded UP to a block multiple so the engine's pinned physical
        # cache length IS max_model_len exactly — the documented
        # bit-reproducibility reference (docs/serving.md).
        max_len = max(block,
                      _int_env(environ, "HOROVOD_SERVE_MAX_MODEL_LEN", 256))
        max_len = block * (-(-max_len // block))
        # Default pool: enough for max_batch full-length sequences would
        # defeat admission-control testing; default to half that so the
        # pool is a real resource, overridable per deployment.
        max_batch = max(1, _int_env(environ, "HOROVOD_SERVE_MAX_BATCH", 8))
        blocks_dflt = max(
            2, (max_batch * (-(-max_len // block)) + 1) // 2)
        return ServeConfig(
            model=environ.get("HOROVOD_SERVE_MODEL", "tiny"),
            dtype=environ.get("HOROVOD_SERVE_DTYPE", ""),
            param_seed=_int_env(environ, "HOROVOD_SERVE_PARAM_SEED", 0),
            checkpoint=environ.get("HOROVOD_SERVE_CHECKPOINT", "").strip(),
            block_size=block,
            kv_blocks=max(1, _int_env(environ, "HOROVOD_SERVE_KV_BLOCKS",
                                      blocks_dflt)),
            max_model_len=max_len,
            max_batch=max_batch,
            prefill_waves=max(1, _int_env(environ,
                                          "HOROVOD_SERVE_PREFILL_WAVES", 1)),
            fused_attn=_int_env(environ, "HOROVOD_SERVE_FUSED_ATTN", 0),
            prefix_cache=_int_env(environ, "HOROVOD_SERVE_PREFIX_CACHE", 1),
            warmup_tokens=max(0, _int_env(environ, "HOROVOD_SERVE_WARMUP",
                                          0)),
            autotune=_int_env(environ, "HOROVOD_SERVE_AUTOTUNE", 0),
            autotune_seed=_int_env(environ, "HOROVOD_SERVE_AUTOTUNE_SEED",
                                   0),
            autotune_window_steps=max(
                4, _int_env(environ,
                            "HOROVOD_SERVE_AUTOTUNE_WINDOW_STEPS", 32)),
            autotune_max_trials=max(
                1, _int_env(environ,
                            "HOROVOD_SERVE_AUTOTUNE_MAX_TRIALS", 12)),
        )


#: (env, default-doc, doc) rows for the --print-config table; the
#: effective value is computed by resolving the whole ServeConfig so
#: derived defaults (kv_blocks from max_batch/max_model_len) are real.
SERVE_KNOBS = [
    ("HOROVOD_SERVE_MODEL", "tiny", "model",
     "served model config (LlamaConfig.<name>)"),
    ("HOROVOD_SERVE_DTYPE", "(model default)", "dtype",
     "activation/cache dtype override (float32|bfloat16)"),
    ("HOROVOD_SERVE_PARAM_SEED", "0", "param_seed",
     "deterministic parameter seed — every replica builds identical "
     "weights from it"),
    ("HOROVOD_SERVE_CHECKPOINT", "(unset: seeded params)", "checkpoint",
     "checkpoint directory: replicas load the newest complete "
     "manifest's params instead of seeding (run.py --serve-model "
     "<dir> sets it)"),
    ("HOROVOD_SERVE_BLOCK_SIZE", "16", "block_size",
     "paged KV-cache block size in tokens (forced to a power of two)"),
    ("HOROVOD_SERVE_KV_BLOCKS", "auto: max_batch*max_len/2", "kv_blocks",
     "allocatable KV blocks in the pool (admission control funds "
     "sequences from it)"),
    ("HOROVOD_SERVE_MAX_MODEL_LEN", "256", "max_model_len",
     "hard cap on prompt+generation length per sequence (rounded up to "
     "a block multiple; also the pinned physical cache length)"),
    ("HOROVOD_SERVE_MAX_BATCH", "8", "max_batch",
     "max concurrently decoding sequences (live-tunable)"),
    ("HOROVOD_SERVE_PREFILL_WAVES", "1", "prefill_waves",
     "admissions prefilled per scheduler step (live-tunable)"),
    ("HOROVOD_SERVE_FUSED_ATTN", "0", "fused_attn",
     "1 = fused paged-attention decode kernel (block-table reads, no "
     "gather; tolerance-equivalent); 0 = gather oracle, byte-identical "
     "to offline generate"),
    ("HOROVOD_SERVE_PREFIX_CACHE", "1", "prefix_cache",
     "content-hash prefix caching: shared prompt blocks are refcounted "
     "and copy-on-write forked; 0 restores per-request full prefill "
     "bit-for-bit"),
    ("HOROVOD_SERVE_WARMUP", "0", "warmup_tokens",
     "pre-compile decode + prefill programs up to this many prompt "
     "tokens before the replica reports READY, so jit compilation "
     "lands in startup instead of the first unlucky requests' latency "
     "(0 disables)"),
    ("HOROVOD_SERVE_AUTOTUNE", "0", "autotune",
     "serve-plane knob search scored on tokens/sec windows"),
    ("HOROVOD_SERVE_AUTOTUNE_SEED", "0", "autotune_seed",
     "deterministic serve trial-schedule seed"),
    ("HOROVOD_SERVE_AUTOTUNE_WINDOW_STEPS", "32", "autotune_window_steps",
     "scheduler steps per serve scoring window"),
    ("HOROVOD_SERVE_AUTOTUNE_MAX_TRIALS", "12", "autotune_max_trials",
     "hard cap on serve trials (commits best-so-far at the cap)"),
]


def resolved_serve_config(environ=os.environ) -> List[dict]:
    """Rows of {env, set, default, effective, doc} for every serve knob —
    the same row shape autotune/config.py renders."""
    cfg = ServeConfig.from_env(environ)
    rows = []
    for env, dflt, field, doc in SERVE_KNOBS:
        raw: Optional[str] = environ.get(env)
        rows.append({
            "env": env,
            "set": raw if raw is not None else "",
            "default": dflt,
            "effective": str(getattr(cfg, field)),
            "doc": doc,
        })
    # Router-side liveness-probe knobs (not ServeConfig fields): the
    # ONE resolver the router itself uses, so --print-config can never
    # drift from the live values.
    probe, deadline = resolve_probe_knobs(environ)
    rows.append({
        "env": "HOROVOD_SERVE_PROBE_SEC",
        "set": environ.get("HOROVOD_SERVE_PROBE_SEC") or "",
        "default": "5", "effective": str(probe),
        "doc": "router liveness-probe ping interval for WEDGED (not "
               "dead) replicas (<= 0 disables)"})
    rows.append({
        "env": "HOROVOD_SERVE_PROBE_DEADLINE_SEC",
        "set": environ.get("HOROVOD_SERVE_PROBE_DEADLINE_SEC") or "",
        "default": "max(60, 3*probe)", "effective": str(deadline),
        "doc": "no-healthy-pong bound: a replica whose scheduler "
               "heartbeat stays stale this long is killed so its "
               "requests requeue like the death path (keep it above "
               "the model's worst single-call time — first-request "
               "jit compiles run inside one scheduler phase)"})
    rows.append({
        "env": "HOROVOD_SERVE_LINK_RETRIES",
        "set": environ.get("HOROVOD_SERVE_LINK_RETRIES") or "",
        "default": "2", "effective": str(resolve_link_retries(environ)),
        "doc": "router->replica control-link reconnect attempts after a "
               "transient socket failure (the replica parks the session "
               "and replays missed events) before escalating to the "
               "kill/requeue/relaunch path; 0 disables healing"})
    raw_chunk = environ.get("HOROVOD_PAGED_ATTN_CHUNK") or ""
    rows.append({
        "env": "HOROVOD_PAGED_ATTN_CHUNK",
        "set": raw_chunk,
        "default": "whole table", "effective": raw_chunk or "whole table",
        "doc": "table columns per online-softmax iteration in the "
               "blockwise XLA fused-attention path (off-TPU stand-in "
               "for the Pallas kernel); 1 = the kernel's exact "
               "per-block reduction order, default folds the whole "
               "table into one dense pass"})
    return rows


def _float_env(environ, name: str, dflt: float) -> float:
    raw = environ.get(name)
    if raw is None or raw == "":
        return dflt
    try:
        return float(raw)
    except ValueError:
        return dflt


def resolve_probe_knobs(environ=os.environ):
    """(probe_interval_sec, probe_deadline_sec) for the router's
    wedged-replica liveness probes — shared by Router and the
    --print-config rows (one resolver, no drift; empty/garbled values
    fall back to defaults instead of crashing the serve plane).

    The deadline default is deliberately generous (60 s): the scheduler
    heartbeat is stamped per PHASE, and a first-request jit compile
    legitimately runs inside one phase — a deadline below the model's
    worst single-call time would kill a healthy, compiling fleet one
    replica at a time."""
    probe = _float_env(environ, "HOROVOD_SERVE_PROBE_SEC", 5.0)
    deadline = _float_env(environ, "HOROVOD_SERVE_PROBE_DEADLINE_SEC",
                          max(60.0, 3 * probe))
    return probe, deadline


def resolve_link_retries(environ=os.environ) -> int:
    """Router->replica control-link reconnect budget (PR 14 spirit:
    bounded healing before honest escalation).  Shared by Router and the
    --print-config row — one resolver, no drift."""
    return max(0, _int_env(environ, "HOROVOD_SERVE_LINK_RETRIES", 2))
