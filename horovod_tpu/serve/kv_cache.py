"""Paged KV-cache block accounting (the vLLM PagedAttention insight).

The physical cache is a pool of ``num_blocks`` fixed-size blocks; a
sequence owns a *block table* — the ordered list of physical block ids
covering its logical positions.  This module is the pure-Python
bookkeeping side: funding decisions (admission control), per-token
growth, recycling on completion/eviction.  The tensors themselves live
in :mod:`horovod_tpu.serve.engine`, and the block-table decode math in
``models/generation.py`` (``paged_decode_step`` / ``paged_prefill``).

Physical block id 0 is reserved as the TRASH block: padded batch rows
and unfunded table entries point at it, so the jitted scatter/gather
always has a valid target without the allocator ever handing it out.
Every refusal leaves the allocator untouched — a sequence that cannot
be funded *now* simply waits (or is preempted back to the queue), it is
never half-funded.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List

import numpy as np

__all__ = ["PagedKVCache", "TRASH_BLOCK"]

#: Reserved physical block id — never allocated, written only by padded
#: rows, never read by a live sequence.
TRASH_BLOCK = 0


def blocks_for(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``n_tokens`` cache slots."""
    return -(-int(n_tokens) // int(block_size))


class PagedKVCache:
    """Block allocator + per-sequence block tables.

    ``num_blocks`` counts the whole pool INCLUDING the trash block, so
    ``capacity_blocks = num_blocks - 1`` are allocatable.  All methods
    are O(blocks touched); none raise on refusal — they return False and
    leave state unchanged, which is what admission control keys off.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int):
        if num_blocks < 2:
            raise ValueError("need at least one allocatable block "
                             "besides the trash block")
        if block_size < 1:
            raise ValueError("block_size must be positive")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self._free: deque[int] = deque(range(1, self.num_blocks))
        self._tables: Dict[int, List[int]] = {}
        # Cumulative recycling counters (serve stats).
        self.allocated_blocks_total = 0
        self.freed_blocks_total = 0

    # -- capacity --

    @property
    def capacity_blocks(self) -> int:
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return self.capacity_blocks - len(self._free)

    def fits_model(self, n_tokens: int) -> bool:
        """Whether a sequence of ``n_tokens`` total positions can EVER be
        funded (table width + pool size) — False means reject the
        request outright, not queue it."""
        need = blocks_for(n_tokens, self.block_size)
        return need <= min(self.max_blocks_per_seq, self.capacity_blocks)

    def can_fund(self, n_tokens: int) -> bool:
        """Whether ``n_tokens`` cache slots are fundable right now."""
        return blocks_for(n_tokens, self.block_size) <= len(self._free)

    # -- lifecycle --

    def allocate(self, seq_id: int, n_tokens: int) -> bool:
        """Fund a new sequence with blocks for ``n_tokens`` slots.
        All-or-nothing: False (state unchanged) when the pool can't
        cover it."""
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id} already funded")
        need = blocks_for(n_tokens, self.block_size)
        if need > self.max_blocks_per_seq or need > len(self._free):
            return False
        self._tables[seq_id] = [self._free.popleft() for _ in range(need)]
        self.allocated_blocks_total += need
        return True

    def append_slot(self, seq_id: int, n_tokens: int) -> bool:
        """Ensure the table covers ``n_tokens`` slots (one decode step =
        one more slot).  Allocates at most one block; False when the pool
        is exhausted or the table is at ``max_blocks_per_seq``."""
        table = self._tables[seq_id]
        need = blocks_for(n_tokens, self.block_size)
        if need <= len(table):
            return True
        if need > self.max_blocks_per_seq or not self._free:
            return False
        table.append(self._free.popleft())
        self.allocated_blocks_total += 1
        return True

    def free(self, seq_id: int) -> int:
        """Recycle a sequence's blocks (completion or eviction); returns
        how many went back to the pool."""
        table = self._tables.pop(seq_id)
        self._free.extend(table)
        self.freed_blocks_total += len(table)
        return len(table)

    # -- views --

    def table(self, seq_id: int) -> List[int]:
        return list(self._tables[seq_id])

    def table_array(self, seq_id: int, width: int) -> np.ndarray:
        """The block table padded to ``width`` with the trash block —
        the shape the jitted decode consumes."""
        table = self._tables[seq_id]
        if len(table) > width:
            raise ValueError(f"table wider than {width}")
        out = np.full((width,), TRASH_BLOCK, dtype=np.int32)
        out[:len(table)] = table
        return out

    def stats(self) -> dict:
        return {
            "kv_blocks_total": self.capacity_blocks,
            "kv_blocks_in_use": self.blocks_in_use,
            "kv_blocks_free": self.free_blocks,
            "kv_block_size": self.block_size,
            "kv_blocks_allocated_total": self.allocated_blocks_total,
            "kv_blocks_freed_total": self.freed_blocks_total,
            "kv_sequences": len(self._tables),
        }
