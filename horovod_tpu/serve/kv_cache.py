"""Paged KV-cache block accounting (the vLLM PagedAttention insight).

The physical cache is a pool of ``num_blocks`` fixed-size blocks; a
sequence owns a *block table* — the ordered list of physical block ids
covering its logical positions.  This module is the pure-Python
bookkeeping side: funding decisions (admission control), per-token
growth, recycling on completion/eviction.  The tensors themselves live
in :mod:`horovod_tpu.serve.engine`, and the block-table decode math in
``models/generation.py`` (``paged_decode_step`` / ``paged_prefill``).

Physical block id 0 is reserved as the TRASH block: padded batch rows
and unfunded table entries point at it, so the jitted scatter/gather
always has a valid target without the allocator ever handing it out.
Every refusal leaves the allocator untouched — a sequence that cannot
be funded *now* simply waits (or is preempted back to the queue), it is
never half-funded.

Prefix caching (``prefix_cache=True``, vLLM's automatic prefix caching):
full blocks are additionally keyed by a *chained* content hash —
``h_i = blake2b(h_{i-1} || tokens of block i)`` — so a block's key
commits to ALL content up to its end, and equal keys imply bitwise-equal
K/V (the programs are deterministic and causal).  A new sequence whose
leading full blocks hash-match cached ones shares them (refcounted) and
funds only the non-shared suffix; the first divergent or partial block
is a fresh block — a copy-on-write fork, since sequences only ever
WRITE at positions beyond their shared prefix (decode writes at
``pos >= prompt_len``; a hit's suffix prefill scatters only blocks
``>= start_blk``), shared blocks are immutable by construction.  The
block holding the last prompt token is never shared, so a hit always
leaves at least one suffix token to prefill — the query that produces
the first output logits.  When a sequence releases a registered block
the refcount drops; at zero the block parks on an LRU list, still
cached, and is the eviction victim when the free list runs dry.  A
weight-epoch swap calls :meth:`flush_prefix`, dropping every cached
block and all registrations — stale-epoch KV is structurally
unreachable afterwards.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["PagedKVCache", "TRASH_BLOCK"]

#: Reserved physical block id — never allocated, written only by padded
#: rows, never read by a live sequence.
TRASH_BLOCK = 0


def blocks_for(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``n_tokens`` cache slots."""
    return -(-int(n_tokens) // int(block_size))


class PagedKVCache:
    """Block allocator + per-sequence block tables.

    ``num_blocks`` counts the whole pool INCLUDING the trash block, so
    ``capacity_blocks = num_blocks - 1`` are allocatable.  All methods
    are O(blocks touched); none raise on refusal — they return False and
    leave state unchanged, which is what admission control keys off.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int, *, prefix_cache: bool = False):
        if num_blocks < 2:
            raise ValueError("need at least one allocatable block "
                             "besides the trash block")
        if block_size < 1:
            raise ValueError("block_size must be positive")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.prefix_cache = bool(prefix_cache)
        self._free: deque[int] = deque(range(1, self.num_blocks))
        self._tables: Dict[int, List[int]] = {}
        # Prefix-cache state: chained content hash <-> physical block
        # (bijective — a hash is registered by at most one block), live
        # refcounts, and the refcount-0 LRU parking lot.
        self._hash_to_block: Dict[bytes, int] = {}
        self._block_hash: Dict[int, bytes] = {}
        self._block_ref: Dict[int, int] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # Cumulative recycling counters (serve stats).
        self.allocated_blocks_total = 0
        self.freed_blocks_total = 0
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_evictions = 0
        self.cow_forks = 0

    # -- capacity --

    @property
    def capacity_blocks(self) -> int:
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def cached_blocks(self) -> int:
        """Refcount-0 registered blocks (reusable, evictable)."""
        return len(self._lru)

    @property
    def blocks_in_use(self) -> int:
        """Blocks held by live sequences (cached-idle blocks excluded —
        they are reclaimable on demand, so drain accounting still ends
        at zero)."""
        return self.capacity_blocks - len(self._free) - len(self._lru)

    def fits_model(self, n_tokens: int) -> bool:
        """Whether a sequence of ``n_tokens`` total positions can EVER be
        funded (table width + pool size) — False means reject the
        request outright, not queue it."""
        need = blocks_for(n_tokens, self.block_size)
        return need <= min(self.max_blocks_per_seq, self.capacity_blocks)

    def can_fund(self, n_tokens: int) -> bool:
        """Whether ``n_tokens`` cache slots are fundable right now
        (cached-idle blocks count — they evict on demand)."""
        need = blocks_for(n_tokens, self.block_size)
        return need <= len(self._free) + len(self._lru)

    # -- prefix hashing --

    def _chain_hashes(self, tokens: Sequence[int]) -> List[bytes]:
        """Chained digests of the FULL blocks of ``tokens`` — entry i
        commits to every token through block i's end."""
        out: List[bytes] = []
        h = b""
        bs = self.block_size
        for i in range(len(tokens) // bs):
            blk = np.asarray(tokens[i * bs:(i + 1) * bs],
                             dtype=np.int64).tobytes()
            h = hashlib.blake2b(h + blk, digest_size=16).digest()
            out.append(h)
        return out

    def _take_block(self) -> Optional[int]:
        """One block from the free list, else evict the LRU cached
        block (dropping its registration)."""
        if self._free:
            return self._free.popleft()
        if self._lru:
            bid, _ = self._lru.popitem(last=False)
            self._hash_to_block.pop(self._block_hash.pop(bid))
            self._block_ref.pop(bid, None)
            self.prefix_evictions += 1
            return bid
        return None

    # -- lifecycle --

    def allocate(self, seq_id: int, n_tokens: int) -> bool:
        """Fund a new sequence with blocks for ``n_tokens`` slots.
        All-or-nothing: False (state unchanged) when the pool can't
        cover it."""
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id} already funded")
        need = blocks_for(n_tokens, self.block_size)
        if need > self.max_blocks_per_seq or \
                need > len(self._free) + len(self._lru):
            return False
        self._tables[seq_id] = [self._take_block() for _ in range(need)]
        self.allocated_blocks_total += need
        return True

    def allocate_prefix(self, seq_id: int,
                        tokens: Sequence[int]) -> Optional[int]:
        """Fund a new sequence for ``len(tokens)`` slots, sharing cached
        leading blocks by content hash.  Returns the number of shared
        (hit) blocks — the prefill may skip ``shared * block_size``
        positions — or None when unfundable (state unchanged).  With
        prefix caching off this is exactly :meth:`allocate`."""
        n_tokens = len(tokens)
        if not self.prefix_cache:
            return 0 if self.allocate(seq_id, n_tokens) else None
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id} already funded")
        need_total = blocks_for(n_tokens, self.block_size)
        if need_total > self.max_blocks_per_seq:
            return None
        shareable = min((n_tokens - 1) // self.block_size, need_total)
        shared: List[int] = []
        for h in self._chain_hashes(tokens)[:shareable]:
            bid = self._hash_to_block.get(h)
            if bid is None:
                break
            shared.append(bid)
        need_fresh = need_total - len(shared)
        # Shared blocks parked in the LRU are about to be reserved, so
        # they must not count as evictable headroom for the fresh part.
        avail = len(self._free) + len(self._lru) \
            - sum(1 for bid in shared if bid in self._lru)
        if need_fresh > avail:
            return None
        for bid in shared:
            self._block_ref[bid] += 1
            self._lru.pop(bid, None)
        fresh = [self._take_block() for _ in range(need_fresh)]
        self._tables[seq_id] = shared + fresh
        self.allocated_blocks_total += need_fresh
        self.prefix_hits += len(shared)
        self.prefix_misses += shareable - len(shared)
        if shared and fresh:
            self.cow_forks += 1
        return len(shared)

    def register_prefix(self, seq_id: int, tokens: Sequence[int]) -> int:
        """Publish a funded sequence's FULL blocks into the hash map so
        future identical prefixes hit (call after prefill — the blocks
        must actually hold the K/V).  Blocks already registered (shared
        hits) and hashes already published by another block are left
        alone.  Returns the number of newly registered blocks."""
        if not self.prefix_cache:
            return 0
        table = self._tables[seq_id]
        n_full = min(len(tokens) // self.block_size, len(table))
        new = 0
        for h, bid in zip(self._chain_hashes(tokens)[:n_full],
                          table[:n_full]):
            if bid in self._block_hash or h in self._hash_to_block:
                continue
            self._block_hash[bid] = h
            self._hash_to_block[h] = bid
            self._block_ref[bid] = 1
            new += 1
        return new

    def append_slot(self, seq_id: int, n_tokens: int) -> bool:
        """Ensure the table covers ``n_tokens`` slots (one decode step =
        one more slot).  Allocates at most one block; False when the pool
        is exhausted or the table is at ``max_blocks_per_seq``.  Growth
        blocks are always private (never registered) — decode writes
        only ever land outside shared blocks."""
        table = self._tables[seq_id]
        need = blocks_for(n_tokens, self.block_size)
        if need <= len(table):
            return True
        if need > self.max_blocks_per_seq:
            return False
        bid = self._take_block()
        if bid is None:
            return False
        table.append(bid)
        self.allocated_blocks_total += 1
        return True

    def free(self, seq_id: int) -> int:
        """Recycle a sequence's blocks (completion or eviction); returns
        how many the sequence released.  Registered blocks drop a
        refcount and park on the LRU at zero (still cached); private
        blocks go straight back to the free list."""
        table = self._tables.pop(seq_id)
        for bid in table:
            if bid in self._block_hash:
                self._block_ref[bid] -= 1
                if self._block_ref[bid] == 0:
                    self._lru[bid] = None
                    self._lru.move_to_end(bid)
            else:
                self._free.append(bid)
        self.freed_blocks_total += len(table)
        return len(table)

    def flush_prefix(self) -> int:
        """Weight-epoch flush: drop every cached block to the free list
        and forget ALL registrations — stale-epoch KV is structurally
        unreachable afterwards.  Registered blocks still referenced by a
        live table (none at swap time; the scheduler frees all running
        sequences first) are demoted to private.  Returns blocks
        recycled."""
        dropped = len(self._lru)
        self._free.extend(self._lru)
        self._lru.clear()
        self._hash_to_block.clear()
        self._block_hash.clear()
        self._block_ref.clear()
        self.prefix_evictions += dropped
        return dropped

    # -- views --

    def table(self, seq_id: int) -> List[int]:
        return list(self._tables[seq_id])

    def table_array(self, seq_id: int, width: int) -> np.ndarray:
        """The block table padded to ``width`` with the trash block —
        the shape the jitted decode consumes."""
        table = self._tables[seq_id]
        if len(table) > width:
            raise ValueError(f"table wider than {width}")
        out = np.full((width,), TRASH_BLOCK, dtype=np.int32)
        out[:len(table)] = table
        return out

    def assert_consistent(self) -> None:
        """Exact pool accounting (test hook): every allocatable block is
        in exactly one of free / cached-LRU / live tables, refcounts
        match table membership, and the hash maps are bijective."""
        held = set()
        for t in self._tables.values():
            held.update(t)
        free_set, lru_set = set(self._free), set(self._lru)
        assert TRASH_BLOCK not in held | free_set | lru_set
        assert len(self._free) == len(free_set), "free list duplicates"
        assert not (free_set & lru_set) and not (free_set & held) \
            and not (lru_set & held), "block in two pools"
        assert free_set | lru_set | held == \
            set(range(1, self.num_blocks)), "pool accounting leak"
        assert set(self._block_hash) == set(self._block_ref)
        assert len(self._hash_to_block) == len(self._block_hash)
        for bid, h in self._block_hash.items():
            assert self._hash_to_block[h] == bid
        for bid, ref in self._block_ref.items():
            n = sum(1 for t in self._tables.values() if bid in t)
            assert n == ref, (bid, ref, n)
            assert (ref == 0) == (bid in lru_set), (bid, ref)

    def stats(self) -> dict:
        return {
            "kv_blocks_total": self.capacity_blocks,
            "kv_blocks_in_use": self.blocks_in_use,
            "kv_blocks_free": self.free_blocks,
            "kv_blocks_cached": self.cached_blocks,
            "kv_block_size": self.block_size,
            "kv_blocks_allocated_total": self.allocated_blocks_total,
            "kv_blocks_freed_total": self.freed_blocks_total,
            "kv_sequences": len(self._tables),
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_evictions": self.prefix_evictions,
            "cow_forks": self.cow_forks,
        }
