"""Production inference serving on the training stack.

The repo's train→serve story (docs/serving.md): an async request
front-end (:mod:`.server`) feeds a continuous-batching scheduler
(:mod:`.scheduler`) that admits new sequences into the running decode
loop at step granularity, funds them from a paged KV-cache block pool
(:mod:`.kv_cache` + the block-table decode path in
``models/generation.py``), and streams tokens back as they are
produced.  A multi-replica router (:mod:`.router`) treats each engine
world as one replica — least-loaded dispatch, and on replica death the
unfinished requests are re-queued onto the survivors while the
supervisor relaunches the dead world (the serve-plane analogue of the
elastic shrink/rejoin cycle).

Entry points: ``python -m horovod_tpu.run --serve`` (router + replicas),
``python -m horovod_tpu.serve.replica`` (one replica), ``bench_serve.py``
(Poisson open-loop load generator).
"""

from horovod_tpu.serve.config import ServeConfig, resolved_serve_config
from horovod_tpu.serve.kv_cache import PagedKVCache

__all__ = ["ServeConfig", "resolved_serve_config", "PagedKVCache"]
