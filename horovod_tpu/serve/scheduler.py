"""Continuous (iteration-level) batching over the paged KV cache.

The Orca insight: scheduling decisions happen at *decode-step*
granularity, not request granularity — a new sequence joins the running
batch the moment it is funded and prefilled, and a finished sequence
frees its slot (and blocks) without draining the batch.  Phases are
separated: each scheduler step runs at most ``prefill_waves`` prompt
prefills (one whole prompt per forward) and then ONE batched decode
step for every running sequence, so a long prompt never stalls
in-flight decodes for more than one wave.

Admission control is block-funded: a sequence is admitted only when the
paged pool can fund its whole prompt (all-or-nothing); a sequence whose
decode needs a new block from an exhausted pool triggers preemption —
the *youngest* running sequence is evicted back to the wait queue
(blocks recycled) and later resumes by recomputing its prefix
(prompt + tokens generated so far becomes its new prompt).  Greedy
decoding makes the recompute reproduce the identical continuation;
temperature sampling stays preemption-stable because sample keys are
derived from (request seed, absolute position), not from how many times
the sequence was scheduled.  (One caveat, same risk class as the
cache-length effect documented in ``models/generation.py``: the resume
token comes from the prefill program where the uninterrupted run used
the decode program — bit-identical on the CI target, asserted by the
preemption parity tests, but revalidate on new backends.)

Thread model: ``run()`` owns the model; ``submit``/``cancel``/``stats``
are thread-safe and non-blocking.  Token events are delivered through
the per-request ``emit`` callback FROM THE SCHEDULER THREAD — the
server wraps it with ``loop.call_soon_threadsafe``.

Live weight swaps: ``swap_weights(epoch, frames)`` (thread-safe,
blocking) parks a decoded-on-arrival weight push that the scheduler
applies at the NEXT step boundary — never inside a decode — under a
monotonic generation epoch.  Every in-flight sequence restarts from
its original prompt on the new weights (a ``requeued`` frame, same
client contract as a replica death), so a finished stream's ``tokens``
are always the product of exactly ONE weight epoch — no mixed-epoch
continuations.  Stale pushes (epoch <= current) ack without applying.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np

from horovod_tpu.serve.config import ServeConfig
from horovod_tpu.serve.engine import ModelRunner
from horovod_tpu.serve.kv_cache import PagedKVCache

__all__ = ["Request", "Scheduler"]


@dataclass
class Request:
    id: str
    prompt: List[int]
    max_tokens: int
    temperature: float = 0.0
    seed: int = 0


@dataclass
class _Seq:
    """One live sequence: the request plus its generation state."""

    req: Request
    emit: Callable[[dict], None]
    sid: int
    out: List[int] = field(default_factory=list)
    preemptions: int = 0
    cancelled: bool = False

    @property
    def prefix(self) -> List[int]:
        """What a (re)prefill must run: prompt + everything generated."""
        return self.req.prompt + self.out

    @property
    def done(self) -> bool:
        return len(self.out) >= self.req.max_tokens


def _sample(logits: np.ndarray, temperature: float, seed: int,
            pos: int) -> int:
    """Greedy argmax at temperature<=0; otherwise categorical with a key
    derived from (seed, position) so a preempted-and-recomputed sequence
    resamples the SAME token at the same position."""
    if temperature <= 0:
        return int(np.argmax(logits))
    x = logits.astype(np.float64) / float(temperature)
    x -= x.max()
    p = np.exp(x)
    p /= p.sum()
    rng = np.random.default_rng([seed & 0x7FFFFFFF, pos])
    return int(rng.choice(len(p), p=p))


class Scheduler:
    """Continuous-batching scheduler over one :class:`ModelRunner`."""

    def __init__(self, runner: ModelRunner, serve_cfg: ServeConfig,
                 step_hook: Optional[Callable[[int], None]] = None):
        self.runner = runner
        self.cfg = serve_cfg
        # The allocator view may be tighter than the runner's physical
        # pool (smaller HOROVOD_SERVE_KV_BLOCKS than the runner was
        # built with) but never wider — block ids must stay in range.
        self.kv = PagedKVCache(
            min(runner.num_blocks, serve_cfg.kv_blocks + 1),
            runner.block_size, runner.max_blocks_per_seq,
            prefix_cache=bool(serve_cfg.prefix_cache))
        # Live-tunable knobs (the serve autotuner rewrites them between
        # steps; reads happen once per step so a mid-step change cannot
        # tear a batch).
        self.max_batch = serve_cfg.max_batch
        self.prefill_waves = serve_cfg.prefill_waves
        self._step_hook = step_hook
        self._tuner = None
        if serve_cfg.autotune:
            from horovod_tpu.serve.tuner import ServeTuner

            self._tuner = ServeTuner(self, serve_cfg)

        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._new: deque = deque()
        self._cancelled: set = set()
        self._stop = False
        # Live weight push (trainer→serve): the pending swap is a
        # latest-wins slot applied at the next STEP BOUNDARY, never
        # mid-decode; _weight_epoch stamps every token/done event.
        self._weight_epoch = 0
        self._pending_weights: Optional[dict] = None
        self._waiting: deque[_Seq] = deque()
        self._running: List[_Seq] = []
        self._next_sid = 1
        self._t0 = time.monotonic()
        # Liveness heartbeat: stamped every loop iteration (idle waits
        # included), so a scheduler thread wedged inside a step — a hung
        # model call, an injected `hang` fault — is distinguishable from
        # a merely idle one.  The server's pong carries its age; the
        # router's probe treats a stale heartbeat like a dead replica.
        self.last_beat = time.monotonic()
        # Counters (cumulative; stats() snapshots them).
        self._c = {
            "requests_submitted": 0,
            "requests_completed": 0,
            "requests_rejected": 0,
            "requests_cancelled": 0,
            "preemptions": 0,
            "prefills": 0,
            "decode_steps": 0,
            "decode_seq_steps": 0,
            "tokens_streamed": 0,
            "weight_swaps": 0,
            "fused_attn_steps": 0,
            "prefill_tokens_saved": 0,
        }

    # -- thread-safe API --

    def submit(self, req: Request, emit: Callable[[dict], None]) -> None:
        with self._wake:
            self._new.append((req, emit))
            self._c["requests_submitted"] += 1
            self._wake.notify()

    def cancel(self, rid: str) -> None:
        with self._wake:
            self._cancelled.add(rid)
            self._wake.notify()

    def stop(self) -> None:
        with self._wake:
            self._stop = True
            self._wake.notify()

    def swap_weights(self, epoch: int, frames: list,
                     timeout: float = 60.0) -> dict:
        """Hot-swap the served weights (thread-safe, BLOCKING).

        ``frames`` are wire frames from
        :func:`horovod_tpu.checkpoint.push.encode_leaves`; decode
        happens here (caller's thread) so the scheduler thread only
        pays the apply.  Blocks until the scheduler thread installs
        them at a step boundary and restarts every in-flight sequence,
        then returns ``{"applied", "epoch", "restarted"}``.  A stale
        epoch (<= the installed one) or a stopped scheduler acks with
        ``applied=False``; only the LATEST concurrent push wins a race
        (the superseded caller is released with ``applied=False``).
        """
        from horovod_tpu.checkpoint.push import decode_leaves

        pending = {"epoch": int(epoch), "by_path": decode_leaves(frames),
                   "done": threading.Event(), "applied": False,
                   "restarted": 0}
        with self._wake:
            if self._stop:
                pending["done"].set()
            else:
                stale = self._pending_weights
                if stale is not None:
                    stale["done"].set()   # superseded, never applied
                self._pending_weights = pending
                self._wake.notify()
        if not pending["done"].wait(timeout=timeout):
            raise TimeoutError(
                f"weight swap to epoch {epoch} not applied in "
                f"{timeout:.0f}s (scheduler thread wedged?)")
        return {"applied": pending["applied"],
                "epoch": self._weight_epoch,
                "restarted": pending["restarted"]}

    def stats(self) -> dict:
        with self._lock:
            c = dict(self._c)
            queue_depth = len(self._waiting) + len(self._new)
            running = len(self._running)
        elapsed = max(1e-9, time.monotonic() - self._t0)
        out = dict(c)
        out["queue_depth"] = queue_depth
        out["running"] = running
        out["batch_occupancy"] = (
            c["decode_seq_steps"] / c["decode_steps"]
            if c["decode_steps"] else 0.0)
        out["tokens_per_sec"] = c["tokens_streamed"] / elapsed
        out["weight_epoch"] = self._weight_epoch
        out.update(self.kv.stats())
        out["tune_trials"] = self._tuner.trials if self._tuner else 0
        if self._tuner is not None:
            out.update(self._tuner.stats())
        out["config"] = {
            "max_batch": self.max_batch,
            "prefill_waves": self.prefill_waves,
            "block_size": self.kv.block_size,
            "kv_blocks": self.kv.capacity_blocks,
            "max_model_len": self.cfg.max_model_len,
            "model": self.cfg.model,
            "autotune": int(self._tuner is not None),
            "fused_attn": int(self.runner.fused_attn),
            "prefix_cache": int(self.kv.prefix_cache),
            "checkpoint_step": getattr(self.runner, "checkpoint_step",
                                       None),
        }
        return out

    def metrics_counters(self) -> dict:
        """The small numeric counter set the replica piggybacks on pong
        frames; the router sums it across replicas for the ``serve``
        /metrics mount (``horovod_serve_*`` gauges)."""
        with self._lock:
            return {
                "prefix_hits": self.kv.prefix_hits,
                "prefix_misses": self.kv.prefix_misses,
                "prefix_evictions": self.kv.prefix_evictions,
                "cow_forks": self.kv.cow_forks,
                "fused_attn_steps": self._c["fused_attn_steps"],
                "prefill_tokens_saved": self._c["prefill_tokens_saved"],
            }

    # -- scheduler thread --

    def run(self) -> None:
        """Loop until :meth:`stop`; call from a dedicated thread."""
        while True:
            with self._wake:
                self.last_beat = time.monotonic()
                if self._stop:
                    self._drain_all_locked()
                    return
                if not (self._new or self._waiting or self._running
                        or self._cancelled or self._pending_weights):
                    self._wake.wait(timeout=0.05)
                    continue
            self.step()

    def step(self) -> None:
        """One scheduling iteration: intake, admission+prefill waves,
        one batched decode step.  The liveness heartbeat is stamped at
        every PHASE boundary (not just per loop pass): a long-but-
        progressing step — first-request jit compiles live inside one
        prefill/decode call — keeps beating between phases, while a
        genuinely wedged phase freezes the beat."""
        self.last_beat = time.monotonic()
        self._apply_weight_swap()
        self._intake()
        self._apply_cancellations()
        max_batch = max(1, int(self.max_batch))
        for _ in range(max(1, int(self.prefill_waves))):
            self.last_beat = time.monotonic()
            if len(self._running) >= max_batch or not self._waiting:
                break
            if not self._admit_and_prefill():
                break  # head-of-line sequence not fundable yet
        self.last_beat = time.monotonic()
        self._decode(max_batch)
        if self._tuner is not None:
            self._tuner.on_step()

    # -- internals (scheduler thread only) --

    def _apply_weight_swap(self) -> None:
        """Install a parked weight push at the step boundary: swap the
        runner's variables, then restart every in-flight sequence from
        its ORIGINAL prompt so no finished stream ever mixes tokens
        from two weight epochs.  The restart reuses the death-requeue
        client contract: a ``requeued`` frame, then the token stream
        starts over at index 0."""
        with self._lock:
            pending = self._pending_weights
            self._pending_weights = None
        if pending is None:
            return
        if pending["epoch"] <= self._weight_epoch:
            pending["done"].set()   # stale replay: ack without applying
            return
        from horovod_tpu.checkpoint.push import apply_leaves

        self.runner.variables = apply_leaves(self.runner.variables,
                                             pending["by_path"])
        self._weight_epoch = pending["epoch"]
        self._c["weight_swaps"] += 1
        restarted = 0
        for seq in list(self._running):
            self._running.remove(seq)
            self.kv.free(seq.sid)
            # Restart from scratch, NOT a preemption resume: a resumed
            # prefix would replay old-epoch tokens through new weights.
            seq.out.clear()
            seq.emit({"event": "requeued", "id": seq.req.id,
                      "reason": "weights",
                      "weight_epoch": self._weight_epoch})
            self._waiting.appendleft(seq)
            restarted += 1
        # New weights invalidate every cached prefix block: flush the
        # hash map and recycle cached blocks so stale-epoch KV is
        # structurally unreachable (nothing can hash-hit it anymore and
        # no table points at it).
        self.kv.flush_prefix()
        pending["applied"] = True
        pending["restarted"] = restarted
        pending["done"].set()

    def _intake(self) -> None:
        with self._lock:
            fresh = list(self._new)
            self._new.clear()
        for req, emit in fresh:
            total = len(req.prompt) + req.max_tokens
            reason = None
            if not req.prompt:
                reason = "empty prompt"
            elif req.max_tokens < 1:
                reason = f"max_tokens must be >= 1, got {req.max_tokens}"
            elif (total > self.cfg.max_model_len
                    or not self.kv.fits_model(total)):
                # Report the BINDING cap: length limit or pool size,
                # whichever is smaller.
                cap = min(self.cfg.max_model_len,
                          min(self.kv.max_blocks_per_seq,
                              self.kv.capacity_blocks)
                          * self.kv.block_size)
                reason = (f"request needs {total} cache slots; the "
                          f"model/pool cap is {cap}")
            if reason is not None:
                self._c["requests_rejected"] += 1
                emit({"event": "error", "id": req.id,
                      "error": f"{reason} (unservable, rejected)"})
                continue
            seq = _Seq(req=req, emit=emit, sid=self._next_sid)
            self._next_sid += 1
            self._waiting.append(seq)

    def _apply_cancellations(self) -> None:
        with self._lock:
            if not self._cancelled:
                return
            gone = self._cancelled
            self._cancelled = set()
        for seq in list(self._running):
            if seq.req.id in gone:
                self._running.remove(seq)
                self.kv.free(seq.sid)
                self._finish(seq, cancelled=True)
        for seq in list(self._waiting):
            if seq.req.id in gone:
                self._waiting.remove(seq)
                self._finish(seq, cancelled=True)

    def _admit_and_prefill(self) -> bool:
        """Fund + prefill the head of the wait queue; False when it
        cannot be funded right now (admission control refusal)."""
        seq = self._waiting[0]
        prefix = seq.prefix
        # Prefix-cache aware funding: leading blocks whose chained
        # content hash matches cached ones are shared (refcounted) and
        # only the non-shared suffix is funded and prefilled; a resumed
        # preemption hits its own earlier blocks the same way.  With
        # caching off this is plain allocate + full prefill, byte-for-
        # byte the old path.
        shared = self.kv.allocate_prefix(seq.sid, prefix)
        if shared is None:
            return False
        self._waiting.popleft()
        start = shared * self.kv.block_size
        logits = self.runner.prefill(
            prefix, self.kv.table(seq.sid), start=start)
        # Publish the full blocks AFTER the prefill wrote them, so a
        # later hit always shares blocks that really hold the K/V.
        self.kv.register_prefix(seq.sid, prefix)
        self._c["prefills"] += 1
        self._c["prefill_tokens_saved"] += start
        tok = _sample(logits, seq.req.temperature, seq.req.seed,
                      len(prefix))
        self._emit_token(seq, tok)
        if seq.done:
            self.kv.free(seq.sid)
            self._finish(seq)
        else:
            self._running.append(seq)
        return True

    def _decode(self, max_batch: int) -> None:
        if not self._running:
            return
        group = self._running[:max_batch]
        # Fund one more slot per sequence, preempting the youngest
        # running sequences when the pool runs dry.
        funded: List[_Seq] = []
        for seq in list(group):
            if seq not in self._running:
                continue  # preempted as a victim earlier in this loop
            pos = len(seq.prefix) - 1  # position of the last token
            # This step writes K/V at `pos`, so pos+1 slots fund it.
            while not self.kv.append_slot(seq.sid, pos + 1):
                victim = self._pick_victim(exclude=funded + [seq])
                if victim is None:
                    break
                self._preempt(victim)
                if victim in group:
                    group.remove(victim)
            else:
                funded.append(seq)
                continue
            # No victim left and still unfundable: the sequence itself
            # yields back to the queue (cannot happen while another
            # running sequence holds blocks — _pick_victim would have
            # found it).
            self._preempt(seq)
            if seq in group:
                group.remove(seq)
        if not funded:
            return
        tokens = [s.out[-1] for s in funded]
        pos = [len(s.prefix) - 1 for s in funded]
        tables = [self.kv.table_array(s.sid, self.runner.max_blocks_per_seq)
                  for s in funded]
        logits = self.runner.decode(tokens, tables, pos)
        self._c["decode_steps"] += 1
        self._c["decode_seq_steps"] += len(funded)
        if self.runner.fused_attn:
            self._c["fused_attn_steps"] += 1
        for i, seq in enumerate(funded):
            tok = _sample(logits[i], seq.req.temperature, seq.req.seed,
                          pos[i] + 1)
            self._emit_token(seq, tok)
            if seq.done:
                self._running.remove(seq)
                self.kv.free(seq.sid)
                self._finish(seq)
        if self._step_hook is not None:
            self._step_hook(self._c["decode_steps"])

    def _pick_victim(self, exclude: Sequence[_Seq]) -> Optional[_Seq]:
        """Preemption policy: evict the YOUNGEST running sequence (vLLM's
        recompute preemption) — it has the least cached work to redo."""
        for seq in reversed(self._running):
            if seq not in exclude:
                return seq
        return None

    def _preempt(self, seq: _Seq) -> None:
        if seq in self._running:
            self._running.remove(seq)
        self.kv.free(seq.sid)
        seq.preemptions += 1
        self._c["preemptions"] += 1
        # Front of the queue: it arrived before anything still waiting.
        self._waiting.appendleft(seq)

    def _emit_token(self, seq: _Seq, tok: int) -> None:
        index = len(seq.out)
        seq.out.append(tok)
        self._c["tokens_streamed"] += 1
        seq.emit({"event": "token", "id": seq.req.id, "token": tok,
                  "index": index, "weight_epoch": self._weight_epoch})

    def _finish(self, seq: _Seq, cancelled: bool = False) -> None:
        if cancelled:
            self._c["requests_cancelled"] += 1
            seq.emit({"event": "cancelled", "id": seq.req.id})
            return
        self._c["requests_completed"] += 1
        seq.emit({"event": "done", "id": seq.req.id, "tokens": seq.out,
                  "preemptions": seq.preemptions,
                  "weight_epoch": self._weight_epoch})

    def _drain_all_locked(self) -> None:
        """On stop: fail whatever is still queued so no caller hangs."""
        if self._pending_weights is not None:
            self._pending_weights["done"].set()   # applied stays False
            self._pending_weights = None
        for seq in list(self._running) + list(self._waiting):
            seq.emit({"event": "error", "id": seq.req.id,
                      "error": "replica shutting down"})
        for req, emit in self._new:
            emit({"event": "error", "id": req.id,
                  "error": "replica shutting down"})
        self._running.clear()
        self._waiting.clear()
        self._new.clear()
