"""Async request front-end: newline-delimited JSON over TCP.

One protocol serves both tiers — a client talking to the router and the
router talking to a replica speak the same frames, so a single replica
can also be driven directly (no router) for tests and benchmarks.

Requests (one JSON object per line)::

    {"op": "generate", "id": "r1", "prompt": [1,2,3], "max_tokens": 8,
     "temperature": 0.0, "seed": 0}
    {"op": "cancel", "id": "r1"}
    {"op": "stats"}
    {"op": "ping"}
    {"op": "weights", "epoch": 3, "frames": [...]}
    {"op": "shutdown"}

Streamed responses (interleaved across in-flight requests)::

    {"event": "token", "id": "r1", "token": 42, "index": 0}
    {"event": "done", "id": "r1", "tokens": [...], "preemptions": 0}
    {"event": "error", "id": "r1", "error": "..."}
    {"event": "cancelled", "id": "r1"}
    {"event": "requeued", "id": "r1"}   # stream restarts (replica
                                        # death via the router, or a
                                        # live weight swap in place)
    {"event": "stats", "stats": {...}}
    {"event": "pong", "sched_age_sec": 0.004,
     "counters": {"prefix_hits": 0, ...}}   # scheduler metrics ride
                                            # the liveness probes
    {"event": "weights_ack", "epoch": 3, "applied": true,
     "restarted": 2}

Tokens stream as they are produced by the continuous-batching scheduler;
after a replica death the router re-queues the request and the token
stream RESTARTS at index 0 on a survivor — the ``done`` frame's
``tokens`` list is always the complete, authoritative output.

A small blocking :class:`ServeClient` (reader-thread + per-request
queues) is included for tests and simple callers; the open-loop
benchmark drives the asyncio side directly.

Router sessions (link healing): a connection whose FIRST frame is
``{"op": "hello", "role": "router", "session": "<token>", "last_seq": N}``
gets durable stream state — a :class:`_RouterSession` owning the live
request set and a sequence-stamped event history.  On socket loss the
session PARKS (generation keeps running, events accumulate) for a grace
window instead of cancelling; the router reconnects, replays its token
in a new hello, and the replica re-sends exactly the events with
``seq > last_seq`` — the healed stream is bit-identical to an unbroken
one.  A hello the replica cannot resume faithfully (history aged out,
or an unknown token with ``last_seq > 0``) answers ``resume: false`` so
the router escalates to its kill/requeue path — never a silent gap.
Plain clients (no hello) keep today's cancel-on-disconnect semantics
bit-for-bit.
"""

from __future__ import annotations

import asyncio
import json
import socket
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from horovod_tpu.serve.scheduler import Request, Scheduler

__all__ = ["ReplicaServer", "ServeClient"]

#: How long a parked router session survives without a reconnect before
#: its live requests are cancelled (pool blocks must not leak forever
#: behind a router that is never coming back).  Comfortably above the
#: router's whole retry schedule (resolve_link_retries attempts with
#: sub-second backoff).
_PARK_GRACE_SEC = 15.0


class _RouterSession:
    """One router's durable stream state, surviving socket loss.

    ``seq`` stamps every stream event (token/done/error/cancelled/
    requeued) in emission order; ``history`` keeps the recent tail so a
    reconnecting router replays exactly the events it missed.  Control
    replies (stats/pong/weights_ack/hello_ack/bye) are connection-scoped
    and never recorded — a lost one times out on the router side, which
    is already how those paths fail.
    """

    def __init__(self, token: str):
        self.token = token
        self.live: set = set()
        self.seq = 0
        self.history: deque = deque(maxlen=4096)
        #: the attached connection's queue; None while parked
        self.outbox: Optional[asyncio.Queue] = None
        self.park_handle: Optional[asyncio.TimerHandle] = None


class ReplicaServer:
    """Serves one Scheduler over asyncio TCP (JSON lines)."""

    def __init__(self, scheduler: Scheduler):
        self.scheduler = scheduler
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None
        self._shutdown = asyncio.Event()
        self._conns: set = set()
        self._sessions: Dict[str, _RouterSession] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        # limit: a weights frame is one JSON line carrying a base64
        # model — far over the 64 KiB readline default.
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(self._handle, host, port,
                                                  limit=1 << 26)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def serve_until_shutdown(self) -> None:
        """Run until a ``shutdown`` frame (or :meth:`shutdown`)."""
        await self._shutdown.wait()
        self._server.close()
        await self._server.wait_closed()
        # Nudge lingering connections so their handler tasks can finish
        # before the loop goes away (quiet teardown in test harnesses).
        for writer in list(self._conns):
            try:
                writer.close()
            except OSError:
                pass
        # Parked sessions must not outlive the server: their live
        # requests release pool blocks now, not at park expiry.
        for token in list(self._sessions):
            self._end_session(self._sessions[token])
        await asyncio.sleep(0)
        self.scheduler.stop()

    def shutdown(self) -> None:
        self._shutdown.set()

    def drop_connections(self) -> None:
        """Abort every open connection (fault injection: a transient
        link reset).  Router sessions park and heal; plain clients see
        today's cancel-on-disconnect.  Threadsafe — callable from the
        scheduler thread's fault hook."""
        loop = self._loop
        if loop is None:
            return

        def _abort() -> None:
            for w in list(self._conns):
                try:
                    tr = w.transport
                    if tr is not None:
                        tr.abort()   # RST, not FIN: a real reset
                    else:
                        w.close()
                except (OSError, RuntimeError):
                    pass

        try:
            loop.call_soon_threadsafe(_abort)
        except RuntimeError:
            pass   # loop already gone — nothing left to drop

    # -- router sessions --

    def _end_session(self, sess: _RouterSession) -> None:
        """Forget the session and cancel whatever it still owns."""
        self._sessions.pop(sess.token, None)
        if sess.park_handle is not None:
            sess.park_handle.cancel()
            sess.park_handle = None
        for rid in list(sess.live):
            self.scheduler.cancel(rid)
        sess.live.clear()

    def _expire_session(self, token: str) -> None:
        sess = self._sessions.get(token)
        if sess is None or sess.outbox is not None:
            return   # reattached while the park timer was pending
        self._end_session(sess)

    def _session_emit(self, loop, sess: _RouterSession,
                      rid: str) -> Callable[[dict], None]:
        def emit(ev: dict) -> None:
            def push(ev=dict(ev)) -> None:
                if ev["event"] in ("done", "error", "cancelled"):
                    sess.live.discard(rid)
                sess.seq += 1
                ev["seq"] = sess.seq
                sess.history.append(ev)
                if sess.outbox is not None:
                    sess.outbox.put_nowait(ev)
            try:
                loop.call_soon_threadsafe(push)
            except RuntimeError:
                pass   # loop torn down mid-shutdown
        return emit

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        try:
            try:
                first = await reader.readline()
            except (ConnectionResetError, asyncio.IncompleteReadError):
                first = b""
            hello = None
            if first:
                try:
                    parsed = json.loads(first)
                    if isinstance(parsed, dict) \
                            and parsed.get("op") == "hello":
                        hello = parsed
                except json.JSONDecodeError:
                    pass
            if hello is not None:
                await self._handle_router(hello, reader, writer)
            else:
                await self._handle_plain(first, reader, writer)
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass

    async def _handle_router(self, hello: dict,
                             reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        loop = asyncio.get_running_loop()
        token = str(hello.get("session", ""))
        try:
            last_seq = int(hello.get("last_seq", 0) or 0)
        except (TypeError, ValueError):
            last_seq = 0
        sess = self._sessions.get(token)
        if sess is None and last_seq > 0:
            # The router remembers a session we no longer hold (park
            # expired, or a restarted replica) — resuming would silently
            # drop events.  Refuse so the router escalates honestly.
            sess = None
        elif sess is None:
            sess = _RouterSession(token)
            self._sessions[token] = sess
        if sess is not None and sess.park_handle is not None:
            sess.park_handle.cancel()
            sess.park_handle = None
        if sess is not None and sess.history:
            oldest = sess.history[0]["seq"]
        else:
            oldest = (sess.seq + 1) if sess is not None else 0
        if sess is None or (last_seq < sess.seq
                            and oldest > last_seq + 1):
            # Unknown token with history, or events aged out of the
            # replay window: the stream cannot be made whole.
            if sess is not None:
                self._end_session(sess)
            try:
                writer.write((json.dumps(
                    {"event": "hello_ack", "session": token,
                     "resume": False}) + "\n").encode())
                await writer.drain()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass
            return
        outbox: asyncio.Queue = asyncio.Queue()
        sess.outbox = outbox
        # Ack carries the live set so the router re-sends generates the
        # replica never received (lost in flight during the reset);
        # replay pushes exactly the unseen stream events, in order.
        outbox.put_nowait({"event": "hello_ack", "session": token,
                           "resume": True, "seq": sess.seq,
                           "live": sorted(sess.live)})
        for ev in sess.history:
            if ev["seq"] > last_seq:
                outbox.put_nowait(ev)

        async def write_loop() -> None:
            try:
                while True:
                    ev = await outbox.get()
                    if ev is None:
                        break
                    writer.write((json.dumps(ev) + "\n").encode())
                    await writer.drain()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass   # events live on in sess.history for the replay

        wtask = asyncio.ensure_future(write_loop())
        ended = False
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    outbox.put_nowait({"event": "error", "id": None,
                                       "error": "malformed frame"})
                    continue
                op = msg.get("op")
                if op == "generate":
                    rid = str(msg.get("id", ""))
                    try:
                        req = Request(
                            id=rid,
                            prompt=[int(t) for t in msg["prompt"]],
                            max_tokens=int(msg["max_tokens"]),
                            temperature=float(msg.get("temperature", 0.0)),
                            seed=int(msg.get("seed", 0)))
                    except (KeyError, TypeError, ValueError) as e:
                        outbox.put_nowait({"event": "error", "id": rid,
                                           "error": f"bad request: {e}"})
                        continue
                    sess.live.add(rid)
                    self.scheduler.submit(
                        req, self._session_emit(loop, sess, rid))
                elif op == "cancel":
                    self.scheduler.cancel(str(msg.get("id", "")))
                elif op == "stats":
                    outbox.put_nowait({"event": "stats",
                                       "stats": self.scheduler.stats()})
                elif op == "ping":
                    outbox.put_nowait({
                        "event": "pong",
                        "sched_age_sec": round(
                            time.monotonic() - self.scheduler.last_beat,
                            3),
                        "counters": self.scheduler.metrics_counters()})
                elif op == "weights":
                    try:
                        ack = await loop.run_in_executor(
                            None, self.scheduler.swap_weights,
                            int(msg.get("epoch", 0)),
                            msg.get("frames") or [])
                        outbox.put_nowait({"event": "weights_ack", **ack})
                    except (TimeoutError, ValueError, KeyError) as e:
                        outbox.put_nowait({"event": "error", "id": None,
                                           "error": f"weights push "
                                                    f"failed: {e}"})
                elif op == "shutdown":
                    outbox.put_nowait({"event": "bye"})
                    ended = True
                    self.shutdown()
                    break
                elif op == "hello":
                    pass   # duplicate hello on a live link: ignore
                else:
                    outbox.put_nowait({"event": "error", "id": None,
                                       "error": f"unknown op {op!r}"})
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            if sess.outbox is outbox:
                sess.outbox = None
            outbox.put_nowait(None)
            try:
                await asyncio.wait_for(wtask, timeout=5)
            except (asyncio.TimeoutError, ConnectionResetError,
                    BrokenPipeError):
                wtask.cancel()
            if ended or self._shutdown.is_set():
                self._end_session(sess)
            elif sess.outbox is None and token in self._sessions:
                # Park: generation keeps running and events accumulate
                # in the history; the grace timer is the honest bound —
                # a router that never returns must not pin pool blocks.
                sess.park_handle = loop.call_later(
                    _PARK_GRACE_SEC, self._expire_session, token)

    async def _handle_plain(self, first_line: bytes,
                            reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        loop = asyncio.get_running_loop()
        outbox: asyncio.Queue = asyncio.Queue()
        live: set = set()

        def emit_threadsafe(rid: str) -> Callable[[dict], None]:
            def emit(ev: dict) -> None:
                if ev["event"] in ("done", "error", "cancelled"):
                    live.discard(rid)
                try:
                    loop.call_soon_threadsafe(outbox.put_nowait, ev)
                except RuntimeError:
                    # Loop already torn down (shutdown drain racing the
                    # scheduler thread) — the client saw EOF anyway.
                    pass
            return emit

        async def write_loop() -> None:
            while True:
                ev = await outbox.get()
                if ev is None:
                    break
                writer.write((json.dumps(ev) + "\n").encode())
                await writer.drain()

        wtask = asyncio.ensure_future(write_loop())
        pending = first_line   # the frame _handle read to sniff hello
        try:
            while True:
                if pending is not None:
                    line, pending = pending, None
                else:
                    line = await reader.readline()
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    outbox.put_nowait({"event": "error", "id": None,
                                       "error": "malformed frame"})
                    continue
                op = msg.get("op")
                if op == "generate":
                    rid = str(msg.get("id", ""))
                    try:
                        req = Request(
                            id=rid,
                            prompt=[int(t) for t in msg["prompt"]],
                            max_tokens=int(msg["max_tokens"]),
                            temperature=float(msg.get("temperature", 0.0)),
                            seed=int(msg.get("seed", 0)))
                    except (KeyError, TypeError, ValueError) as e:
                        outbox.put_nowait({"event": "error", "id": rid,
                                           "error": f"bad request: {e}"})
                        continue
                    live.add(rid)
                    self.scheduler.submit(req, emit_threadsafe(rid))
                elif op == "cancel":
                    self.scheduler.cancel(str(msg.get("id", "")))
                elif op == "stats":
                    outbox.put_nowait({"event": "stats",
                                       "stats": self.scheduler.stats()})
                elif op == "ping":
                    # The pong carries the scheduler heartbeat's age: the
                    # asyncio front-end answers even when the scheduler
                    # THREAD is wedged (hung model call, injected hang),
                    # so liveness probes must judge the scheduler, not
                    # the socket.  See Router._probe_replicas.
                    outbox.put_nowait({
                        "event": "pong",
                        "sched_age_sec": round(
                            time.monotonic() - self.scheduler.last_beat,
                            3),
                        "counters": self.scheduler.metrics_counters()})
                elif op == "weights":
                    # Live trainer→serve push: decode + apply happen on
                    # the scheduler's step boundary; swap_weights BLOCKS
                    # until installed, so run it off the event loop (the
                    # front-end keeps answering pings while the swap
                    # parks).
                    try:
                        ack = await loop.run_in_executor(
                            None, self.scheduler.swap_weights,
                            int(msg.get("epoch", 0)),
                            msg.get("frames") or [])
                        outbox.put_nowait({"event": "weights_ack", **ack})
                    except (TimeoutError, ValueError, KeyError) as e:
                        outbox.put_nowait({"event": "error", "id": None,
                                           "error": f"weights push "
                                                    f"failed: {e}"})
                elif op == "shutdown":
                    outbox.put_nowait({"event": "bye"})
                    self.shutdown()
                    break
                else:
                    outbox.put_nowait({"event": "error", "id": None,
                                       "error": f"unknown op {op!r}"})
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            # A vanished client must not keep burning pool blocks.
            for rid in list(live):
                self.scheduler.cancel(rid)
            outbox.put_nowait(None)
            try:
                await asyncio.wait_for(wtask, timeout=5)
            except (asyncio.TimeoutError, ConnectionResetError,
                    BrokenPipeError):
                wtask.cancel()


class ServeClient:
    """Blocking JSON-lines client (tests / simple callers).

    A reader thread fans events out to per-request queues;
    :meth:`generate` blocks until the ``done`` frame and returns the
    full event list.  Concurrent generates from different threads are
    fine — the socket write side is lock-guarded.
    """

    def __init__(self, host: str, port: int, timeout: float = 120.0):
        self.timeout = timeout
        self._sock = socket.create_connection((host, port), timeout=timeout)
        # timeout bounds the CONNECT only.  An established connection
        # must tolerate arbitrary idle (a caller may sit between
        # requests far longer than any per-request deadline); left in
        # place, the recv timeout fires in the reader thread on an idle
        # socket and falsely marks the connection dead.  Deadlines are
        # enforced per-request in collect()/_wait_plain() instead.
        self._sock.settimeout(None)
        self._file = self._sock.makefile("rb")
        self._wlock = threading.Lock()
        self._qlock = threading.Lock()
        self._queues: Dict[str, deque] = {}
        self._events: Dict[str, threading.Event] = {}
        self._plain: deque = deque()         # events with no request id
        self._plain_ev = threading.Event()
        self._dead = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            for line in iter(self._file.readline, b""):
                ev = json.loads(line)
                # Client-side receive timestamp: what latency benchmarks
                # (bench_serve.py TTFT/p99) measure from.
                ev["_recv_ts"] = time.monotonic()
                rid = ev.get("id")
                if rid is not None and rid in self._queues:
                    with self._qlock:
                        self._queues[rid].append(ev)
                        self._events[rid].set()
                else:
                    self._plain.append(ev)
                    self._plain_ev.set()
        except (OSError, ValueError):
            pass
        self._dead = True
        with self._qlock:
            for ev in self._events.values():
                ev.set()
        self._plain_ev.set()

    def _send(self, msg: dict) -> None:
        with self._wlock:
            self._sock.sendall((json.dumps(msg) + "\n").encode())

    def start_generate(self, rid: str, prompt, max_tokens: int,
                       temperature: float = 0.0, seed: int = 0) -> None:
        with self._qlock:
            self._queues[rid] = deque()
            self._events[rid] = threading.Event()
        self._send({"op": "generate", "id": rid, "prompt": list(prompt),
                    "max_tokens": max_tokens, "temperature": temperature,
                    "seed": seed})

    def collect(self, rid: str, timeout: Optional[float] = None) -> list:
        """Block until the request finishes; returns every event for it
        (token stream incl. any requeue restarts, then done/error)."""
        deadline = time.monotonic() + (timeout or self.timeout)
        out = []
        while True:
            with self._qlock:
                q = self._queues[rid]
                ev = q.popleft() if q else None
                if not q:
                    self._events[rid].clear()
            if ev is not None:
                out.append(ev)
                if ev["event"] in ("done", "error", "cancelled"):
                    with self._qlock:
                        del self._queues[rid], self._events[rid]
                    return out
                continue
            if self._dead:
                raise ConnectionError("server connection lost")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"request {rid} did not finish")
            self._events[rid].wait(timeout=min(remaining, 1.0))

    def generate(self, rid: str, prompt, max_tokens: int,
                 temperature: float = 0.0, seed: int = 0,
                 timeout: Optional[float] = None) -> list:
        self.start_generate(rid, prompt, max_tokens, temperature, seed)
        return self.collect(rid, timeout=timeout)

    def _plain_request(self, op: str, want_event: str,
                       timeout: float = 30.0) -> dict:
        self._send({"op": op})
        return self._wait_plain(want_event, timeout)

    def _wait_plain(self, want_event: str, timeout: float) -> dict:
        deadline = time.monotonic() + timeout
        while True:
            while self._plain:
                ev = self._plain.popleft()
                if ev.get("event") == want_event:
                    return ev
            if self._dead:
                raise ConnectionError("server connection lost")
            if time.monotonic() > deadline:
                raise TimeoutError(f"no {want_event} reply")
            self._plain_ev.wait(timeout=0.5)
            self._plain_ev.clear()

    def stats(self) -> dict:
        return self._plain_request("stats", "stats")["stats"]

    def push_weights(self, frames: list, epoch: int,
                     timeout: float = 120.0) -> dict:
        """Push wire frames (checkpoint.push.encode_leaves) and block
        for the ``weights_ack`` — works against a replica directly (one
        hot-swap) or the router (fan-out to the whole fleet)."""
        self._send({"op": "weights", "frames": list(frames),
                    "epoch": int(epoch)})
        return self._wait_plain("weights_ack", timeout)

    def ping(self) -> None:
        self._plain_request("ping", "pong")

    def shutdown(self) -> None:
        try:
            self._send({"op": "shutdown"})
        except OSError:
            pass

    def close(self) -> None:
        # shutdown() FIRST: the reader thread blocks in readinto()
        # holding the BufferedReader lock, and _file.close() takes that
        # same lock — without the wakeup (recv returns EOF) close would
        # deadlock against our own reader.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._reader.join(timeout=10)
        # makefile() dup'd the fd: both must close or the server never
        # sees EOF (and never cancels this client's in-flight work).
        for closer in (self._file.close, self._sock.close):
            try:
                closer()
            except OSError:
                pass
