"""Async request front-end: newline-delimited JSON over TCP.

One protocol serves both tiers — a client talking to the router and the
router talking to a replica speak the same frames, so a single replica
can also be driven directly (no router) for tests and benchmarks.

Requests (one JSON object per line)::

    {"op": "generate", "id": "r1", "prompt": [1,2,3], "max_tokens": 8,
     "temperature": 0.0, "seed": 0}
    {"op": "cancel", "id": "r1"}
    {"op": "stats"}
    {"op": "ping"}
    {"op": "weights", "epoch": 3, "frames": [...]}
    {"op": "shutdown"}

Streamed responses (interleaved across in-flight requests)::

    {"event": "token", "id": "r1", "token": 42, "index": 0}
    {"event": "done", "id": "r1", "tokens": [...], "preemptions": 0}
    {"event": "error", "id": "r1", "error": "..."}
    {"event": "cancelled", "id": "r1"}
    {"event": "requeued", "id": "r1"}   # stream restarts (replica
                                        # death via the router, or a
                                        # live weight swap in place)
    {"event": "stats", "stats": {...}}
    {"event": "pong", "sched_age_sec": 0.004}
    {"event": "weights_ack", "epoch": 3, "applied": true,
     "restarted": 2}

Tokens stream as they are produced by the continuous-batching scheduler;
after a replica death the router re-queues the request and the token
stream RESTARTS at index 0 on a survivor — the ``done`` frame's
``tokens`` list is always the complete, authoritative output.

A small blocking :class:`ServeClient` (reader-thread + per-request
queues) is included for tests and simple callers; the open-loop
benchmark drives the asyncio side directly.
"""

from __future__ import annotations

import asyncio
import json
import socket
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from horovod_tpu.serve.scheduler import Request, Scheduler

__all__ = ["ReplicaServer", "ServeClient"]


class ReplicaServer:
    """Serves one Scheduler over asyncio TCP (JSON lines)."""

    def __init__(self, scheduler: Scheduler):
        self.scheduler = scheduler
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None
        self._shutdown = asyncio.Event()
        self._conns: set = set()

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        # limit: a weights frame is one JSON line carrying a base64
        # model — far over the 64 KiB readline default.
        self._server = await asyncio.start_server(self._handle, host, port,
                                                  limit=1 << 26)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def serve_until_shutdown(self) -> None:
        """Run until a ``shutdown`` frame (or :meth:`shutdown`)."""
        await self._shutdown.wait()
        self._server.close()
        await self._server.wait_closed()
        # Nudge lingering connections so their handler tasks can finish
        # before the loop goes away (quiet teardown in test harnesses).
        for writer in list(self._conns):
            try:
                writer.close()
            except OSError:
                pass
        await asyncio.sleep(0)
        self.scheduler.stop()

    def shutdown(self) -> None:
        self._shutdown.set()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        loop = asyncio.get_running_loop()
        self._conns.add(writer)
        outbox: asyncio.Queue = asyncio.Queue()
        live: set = set()

        def emit_threadsafe(rid: str) -> Callable[[dict], None]:
            def emit(ev: dict) -> None:
                if ev["event"] in ("done", "error", "cancelled"):
                    live.discard(rid)
                try:
                    loop.call_soon_threadsafe(outbox.put_nowait, ev)
                except RuntimeError:
                    # Loop already torn down (shutdown drain racing the
                    # scheduler thread) — the client saw EOF anyway.
                    pass
            return emit

        async def write_loop() -> None:
            while True:
                ev = await outbox.get()
                if ev is None:
                    break
                writer.write((json.dumps(ev) + "\n").encode())
                await writer.drain()

        wtask = asyncio.ensure_future(write_loop())
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    outbox.put_nowait({"event": "error", "id": None,
                                       "error": "malformed frame"})
                    continue
                op = msg.get("op")
                if op == "generate":
                    rid = str(msg.get("id", ""))
                    try:
                        req = Request(
                            id=rid,
                            prompt=[int(t) for t in msg["prompt"]],
                            max_tokens=int(msg["max_tokens"]),
                            temperature=float(msg.get("temperature", 0.0)),
                            seed=int(msg.get("seed", 0)))
                    except (KeyError, TypeError, ValueError) as e:
                        outbox.put_nowait({"event": "error", "id": rid,
                                           "error": f"bad request: {e}"})
                        continue
                    live.add(rid)
                    self.scheduler.submit(req, emit_threadsafe(rid))
                elif op == "cancel":
                    self.scheduler.cancel(str(msg.get("id", "")))
                elif op == "stats":
                    outbox.put_nowait({"event": "stats",
                                       "stats": self.scheduler.stats()})
                elif op == "ping":
                    # The pong carries the scheduler heartbeat's age: the
                    # asyncio front-end answers even when the scheduler
                    # THREAD is wedged (hung model call, injected hang),
                    # so liveness probes must judge the scheduler, not
                    # the socket.  See Router._probe_replicas.
                    outbox.put_nowait({
                        "event": "pong",
                        "sched_age_sec": round(
                            time.monotonic() - self.scheduler.last_beat,
                            3)})
                elif op == "weights":
                    # Live trainer→serve push: decode + apply happen on
                    # the scheduler's step boundary; swap_weights BLOCKS
                    # until installed, so run it off the event loop (the
                    # front-end keeps answering pings while the swap
                    # parks).
                    try:
                        ack = await loop.run_in_executor(
                            None, self.scheduler.swap_weights,
                            int(msg.get("epoch", 0)),
                            msg.get("frames") or [])
                        outbox.put_nowait({"event": "weights_ack", **ack})
                    except (TimeoutError, ValueError, KeyError) as e:
                        outbox.put_nowait({"event": "error", "id": None,
                                           "error": f"weights push "
                                                    f"failed: {e}"})
                elif op == "shutdown":
                    outbox.put_nowait({"event": "bye"})
                    self.shutdown()
                    break
                else:
                    outbox.put_nowait({"event": "error", "id": None,
                                       "error": f"unknown op {op!r}"})
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            # A vanished client must not keep burning pool blocks.
            for rid in list(live):
                self.scheduler.cancel(rid)
            outbox.put_nowait(None)
            try:
                await asyncio.wait_for(wtask, timeout=5)
            except (asyncio.TimeoutError, ConnectionResetError,
                    BrokenPipeError):
                wtask.cancel()
            self._conns.discard(writer)
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass


class ServeClient:
    """Blocking JSON-lines client (tests / simple callers).

    A reader thread fans events out to per-request queues;
    :meth:`generate` blocks until the ``done`` frame and returns the
    full event list.  Concurrent generates from different threads are
    fine — the socket write side is lock-guarded.
    """

    def __init__(self, host: str, port: int, timeout: float = 120.0):
        self.timeout = timeout
        self._sock = socket.create_connection((host, port), timeout=timeout)
        # timeout bounds the CONNECT only.  An established connection
        # must tolerate arbitrary idle (a caller may sit between
        # requests far longer than any per-request deadline); left in
        # place, the recv timeout fires in the reader thread on an idle
        # socket and falsely marks the connection dead.  Deadlines are
        # enforced per-request in collect()/_wait_plain() instead.
        self._sock.settimeout(None)
        self._file = self._sock.makefile("rb")
        self._wlock = threading.Lock()
        self._qlock = threading.Lock()
        self._queues: Dict[str, deque] = {}
        self._events: Dict[str, threading.Event] = {}
        self._plain: deque = deque()         # events with no request id
        self._plain_ev = threading.Event()
        self._dead = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            for line in iter(self._file.readline, b""):
                ev = json.loads(line)
                # Client-side receive timestamp: what latency benchmarks
                # (bench_serve.py TTFT/p99) measure from.
                ev["_recv_ts"] = time.monotonic()
                rid = ev.get("id")
                if rid is not None and rid in self._queues:
                    with self._qlock:
                        self._queues[rid].append(ev)
                        self._events[rid].set()
                else:
                    self._plain.append(ev)
                    self._plain_ev.set()
        except (OSError, ValueError):
            pass
        self._dead = True
        with self._qlock:
            for ev in self._events.values():
                ev.set()
        self._plain_ev.set()

    def _send(self, msg: dict) -> None:
        with self._wlock:
            self._sock.sendall((json.dumps(msg) + "\n").encode())

    def start_generate(self, rid: str, prompt, max_tokens: int,
                       temperature: float = 0.0, seed: int = 0) -> None:
        with self._qlock:
            self._queues[rid] = deque()
            self._events[rid] = threading.Event()
        self._send({"op": "generate", "id": rid, "prompt": list(prompt),
                    "max_tokens": max_tokens, "temperature": temperature,
                    "seed": seed})

    def collect(self, rid: str, timeout: Optional[float] = None) -> list:
        """Block until the request finishes; returns every event for it
        (token stream incl. any requeue restarts, then done/error)."""
        deadline = time.monotonic() + (timeout or self.timeout)
        out = []
        while True:
            with self._qlock:
                q = self._queues[rid]
                ev = q.popleft() if q else None
                if not q:
                    self._events[rid].clear()
            if ev is not None:
                out.append(ev)
                if ev["event"] in ("done", "error", "cancelled"):
                    with self._qlock:
                        del self._queues[rid], self._events[rid]
                    return out
                continue
            if self._dead:
                raise ConnectionError("server connection lost")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"request {rid} did not finish")
            self._events[rid].wait(timeout=min(remaining, 1.0))

    def generate(self, rid: str, prompt, max_tokens: int,
                 temperature: float = 0.0, seed: int = 0,
                 timeout: Optional[float] = None) -> list:
        self.start_generate(rid, prompt, max_tokens, temperature, seed)
        return self.collect(rid, timeout=timeout)

    def _plain_request(self, op: str, want_event: str,
                       timeout: float = 30.0) -> dict:
        self._send({"op": op})
        return self._wait_plain(want_event, timeout)

    def _wait_plain(self, want_event: str, timeout: float) -> dict:
        deadline = time.monotonic() + timeout
        while True:
            while self._plain:
                ev = self._plain.popleft()
                if ev.get("event") == want_event:
                    return ev
            if self._dead:
                raise ConnectionError("server connection lost")
            if time.monotonic() > deadline:
                raise TimeoutError(f"no {want_event} reply")
            self._plain_ev.wait(timeout=0.5)
            self._plain_ev.clear()

    def stats(self) -> dict:
        return self._plain_request("stats", "stats")["stats"]

    def push_weights(self, frames: list, epoch: int,
                     timeout: float = 120.0) -> dict:
        """Push wire frames (checkpoint.push.encode_leaves) and block
        for the ``weights_ack`` — works against a replica directly (one
        hot-swap) or the router (fan-out to the whole fleet)."""
        self._send({"op": "weights", "frames": list(frames),
                    "epoch": int(epoch)})
        return self._wait_plain("weights_ack", timeout)

    def ping(self) -> None:
        self._plain_request("ping", "pong")

    def shutdown(self) -> None:
        try:
            self._send({"op": "shutdown"})
        except OSError:
            pass

    def close(self) -> None:
        # shutdown() FIRST: the reader thread blocks in readinto()
        # holding the BufferedReader lock, and _file.close() takes that
        # same lock — without the wakeup (recv returns EOF) close would
        # deadlock against our own reader.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._reader.join(timeout=10)
        # makefile() dup'd the fd: both must close or the server never
        # sees EOF (and never cancels this client's in-flight work).
        for closer in (self._file.close, self._sock.close):
            try:
                closer()
            except OSError:
                pass
