"""Multi-replica routing: least-loaded dispatch + requeue-on-death.

The router owns a fleet of replica worlds (one
``horovod_tpu.serve.replica`` process each — the launcher env decides
how many engine ranks back each one), speaks the same JSON-lines
protocol to clients on its front port, and forwards each ``generate``
to the live replica with the fewest outstanding requests.

Failure semantics are the serve-plane analogue of the elastic
shrink/rejoin cycle (docs/elastic.md):

* *shrink* — a replica death (connection loss or process exit) removes
  it from the routing set; every request it still owed is immediately
  re-queued onto the survivors.  The client sees a ``requeued`` frame
  and the token stream restarts at index 0 — the ``done`` frame's
  ``tokens`` is always the complete output, so **no request is ever
  dropped**, only re-run (generation is deterministic per request:
  greedy, or seeded position-stable sampling, so the rerun streams the
  identical tokens).
* *rejoin* — the supervisor relaunches the dead replica (scrubbing
  ``HOROVOD_FAULT_INJECT`` exactly like ``run.py --restart-on-failure``)
  up to the restart budget; once it prints READY and reconnects it
  rejoins the routing set and starts taking new load.

With every replica down and no budget left, queued requests fail with a
clean error — the router never hangs a client.

Live weight pushes (``{"op": "weights", ...}``, produced by
``horovod_tpu.checkpoint.push.WeightPusher``) fan out to every live
replica, which hot-swaps between decode iterations under the frame's
generation epoch; the router caches the LATEST frame and replays it to
a relaunched replica before it takes load, so a rejoin serves the
current pushed epoch — never boot-time params (docs/checkpointing.md).
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import sys
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["Router", "serve_main"]

_READY_RE = re.compile(rb"SERVE_REPLICA_READY port=(\d+)")


class _Replica:
    def __init__(self, idx: int):
        self.idx = idx
        self.proc: Optional[asyncio.subprocess.Process] = None
        self.port: Optional[int] = None
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.pending: Dict[str, "_ClientConn"] = {}
        self.alive = False
        # Link healing (HOROVOD_SERVE_LINK_RETRIES): session token the
        # replica parks our stream state under across a socket loss,
        # the highest event seq we have PROCESSED (the replay cursor),
        # and whether a reconnect attempt is in flight (a healing
        # replica takes no new dispatches and no probe pings).
        self.session_token = ""
        self.last_seq = 0
        self.healing = False
        # Latest scheduler metrics counters piggybacked on pongs —
        # summed into the /metrics "serve" mount (fleet-wide view).
        self.metrics: Dict[str, int] = {}
        # Liveness probing (wedged-replica detection): when the last
        # HEALTHY pong — answered AND its scheduler heartbeat fresh —
        # was seen, reset on (re)spawn so a slow cold start is not
        # mistaken for a wedge.
        self.last_healthy = 0.0
        #: set by the supervisor once this replica can NEVER come back
        #: (clean exit, budget exhausted, or relaunch failed) — the
        #: router's queue-parking hope is "any replica not terminal".
        self.terminal = False
        self.stats_waiter: Optional[asyncio.Future] = None
        self.weights_waiter: Optional[asyncio.Future] = None
        # Serializes request/reply exchanges (stats, weight pushes):
        # concurrent clients must not clobber each other's waiter.
        self.stats_lock = asyncio.Lock()


class _ClientConn:
    _next_id = 0

    def __init__(self, writer: asyncio.StreamWriter):
        _ClientConn._next_id += 1
        self.cid = _ClientConn._next_id
        self.writer = writer
        self.outbox: asyncio.Queue = asyncio.Queue()
        self.live: Dict[str, str] = {}   # internal rid -> client rid

    def emit(self, ev: dict) -> None:
        self.outbox.put_nowait(ev)


class Router:
    def __init__(self, *, num_replicas: int, restart_budget: int = 0,
                 relaunch_delay: float = 0.0, host: str = "127.0.0.1",
                 port: int = 0, replica_env: Optional[dict] = None):
        self.num_replicas = num_replicas
        self.restart_budget = restart_budget
        self.relaunch_delay = relaunch_delay
        self.host, self.port = host, port
        self.replica_env = dict(replica_env or {})
        self.replicas: List[_Replica] = [_Replica(i)
                                         for i in range(num_replicas)]
        self._reqs: Dict[str, dict] = {}    # internal rid -> request frame
        self._owners: Dict[str, _ClientConn] = {}
        self._queue: deque[str] = deque()   # awaiting a live replica
        self._restarts_left = restart_budget
        self._next_rid = 0
        self._shutdown = asyncio.Event()
        self._tasks: List[asyncio.Task] = []
        self.counters = {
            "dispatched": 0, "completed": 0, "requeued": 0,
            "replica_deaths": 0, "rejoins": 0, "failed": 0,
            "cancelled": 0, "wedged_kills": 0, "weight_pushes": 0,
            "weight_replays": 0, "link_reconnects": 0,
        }
        #: the latest weights frame pushed through the router, replayed
        #: to every relaunched replica BEFORE it takes load (a rejoin
        #: must serve the current epoch, not boot-time params).
        self._last_push: Optional[dict] = None
        # Liveness probes for WEDGED (not dead) replicas: a replica whose
        # scheduler thread hangs keeps its socket open and its asyncio
        # front-end answering, so death detection alone never fires.  The
        # router pings every probe_sec; a replica with no HEALTHY pong —
        # answered, with a fresh scheduler heartbeat — inside
        # probe_deadline_sec is killed, which routes it through the
        # normal death path: in-flight requests requeue onto survivors
        # and the supervisor relaunches it under the restart budget
        # (fault schedule scrubbed).  probe_sec <= 0 disables.  Resolved
        # by serve.config.resolve_probe_knobs (the --print-config rows
        # use the same resolver, and the deadline default is sized for
        # in-phase jit compiles).
        from horovod_tpu.serve.config import (
            resolve_link_retries,
            resolve_probe_knobs,
        )

        self.probe_sec, self.probe_deadline_sec = resolve_probe_knobs()
        # Control-link healing budget (PR 14 spirit for the serve
        # plane): a transient replica-socket failure retries this many
        # reconnects (the replica parks our session and replays missed
        # events) before the honest fallback — the kill/requeue/relaunch
        # death path.  0 disables: today's plain links, bit-for-bit.
        self.link_retries = resolve_link_retries()
        self._spawn_count = 0

    # -- replica lifecycle --

    async def _spawn(self, rep: _Replica, scrub_fault: bool) -> None:
        env = dict(os.environ)
        env.update(self.replica_env)
        env["HOROVOD_REPLICA_ID"] = str(rep.idx)
        # The replica must import this exact package even when the
        # launcher was started outside the repo / without installation.
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH",
                                                            "")
        if scrub_fault:
            # A relaunched incarnation must not re-fire the injected
            # fault (same contract as run.py --restart-on-failure).
            env.pop("HOROVOD_FAULT_INJECT", None)
        rep.proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "horovod_tpu.serve.replica", "--port", "0",
            env=env, stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT)
        # Pump output; the READY line carries the ephemeral port.
        ready = asyncio.get_running_loop().create_future()

        async def pump(proc=rep.proc) -> None:
            async for line in proc.stdout:
                m = _READY_RE.search(line)
                if m and not ready.done():
                    ready.set_result(int(m.group(1)))
                sys.stdout.write(f"[replica {rep.idx}] "
                                 f"{line.decode(errors='replace')}")
                sys.stdout.flush()
            if not ready.done():
                ready.set_exception(
                    RuntimeError(f"replica {rep.idx} exited before READY"))

        self._tasks.append(asyncio.ensure_future(pump()))
        rep.port = await asyncio.wait_for(ready, timeout=300)
        for attempt in range(50):
            try:
                # The stream limit must fit a whole weights frame (one
                # JSON line carrying a base64 model) — the 64 KiB
                # default readline cap would sever the connection.
                rep.reader, rep.writer = await asyncio.open_connection(
                    "127.0.0.1", rep.port, limit=1 << 26)
                break
            except OSError:
                await asyncio.sleep(0.1)
        else:
            raise RuntimeError(f"cannot connect to replica {rep.idx}")
        rep.healing = False
        rep.last_seq = 0
        self._spawn_count += 1
        rep.session_token = f"r{rep.idx}.{self._spawn_count}"
        if self.link_retries > 0:
            # Open a durable session so a transient socket loss parks
            # our stream state replica-side instead of cancelling it.
            rep.writer.write((json.dumps(
                {"op": "hello", "role": "router",
                 "session": rep.session_token, "last_seq": 0})
                + "\n").encode())
            await rep.writer.drain()
        rep.alive = True
        rep.last_healthy = time.monotonic()
        self._tasks.append(asyncio.ensure_future(self._replica_reader(rep)))
        self._tasks.append(asyncio.ensure_future(self._supervise(rep)))

    async def _supervise(self, rep: _Replica) -> None:
        proc = rep.proc
        rc = await proc.wait()
        if self._shutdown.is_set():
            return
        self._on_replica_down(rep)
        if rc == 0 or self._restarts_left <= 0:
            rep.terminal = True
            self._fail_queue_if_hopeless()
            return
        self._restarts_left -= 1
        sys.stderr.write(
            f"replica {rep.idx} exited with code {rc}; relaunching "
            f"({self._restarts_left} restarts left)\n")
        sys.stderr.flush()
        if self.relaunch_delay > 0:
            await asyncio.sleep(self.relaunch_delay)
        try:
            await self._spawn(rep, scrub_fault=True)
        except (RuntimeError, OSError, asyncio.TimeoutError) as e:
            if not self._shutdown.is_set():   # not noise mid-teardown
                sys.stderr.write(f"replica {rep.idx} relaunch "
                                 f"failed: {e}\n")
                rep.terminal = True
                self._fail_queue_if_hopeless()
            return
        if self._last_push is not None:
            # The relaunched replica rebuilt BOOT-TIME params (seed or
            # checkpoint); replay the latest pushed frame before it
            # takes load so the whole fleet serves one weight epoch.
            ack = await self._push_weights_rep(rep, self._last_push)
            if ack is not None:
                self.counters["weight_replays"] += 1
            else:
                sys.stderr.write(
                    f"replica {rep.idx} rejoined but the weight replay "
                    f"failed; it may serve a stale epoch until the "
                    f"next push\n")
                sys.stderr.flush()
        self.counters["rejoins"] += 1
        self._drain_queue()

    def _fail_queue_if_hopeless(self) -> None:
        """Error out parked requests once no replica can ever serve them
        — the no-hang guarantee.  Hope is "some replica is not
        terminal": its supervisor has not yet concluded (it may still
        relaunch with remaining budget), or it is alive.  Leftover
        budget with every supervisor concluded is NOT hope — nothing
        will ever spend it (a clean rc-0 exit, budget exhaustion, or a
        failed relaunch ends a supervisor for good)."""
        if any(not r.terminal for r in self.replicas):
            return
        for rid in list(self._queue):
            self._queue.remove(rid)
            client = self._owners.get(rid)
            if client is not None:
                self.counters["failed"] += 1
                client.emit({"event": "error", "id": client.live.get(rid),
                             "error": "no live replica and no restart "
                                      "budget left"})
            self._forget(rid)

    def _on_replica_down(self, rep: _Replica) -> None:
        if not rep.alive:
            return
        rep.alive = False
        self.counters["replica_deaths"] += 1
        if rep.writer is not None:
            try:
                rep.writer.close()
            except OSError:
                pass
        if rep.stats_waiter is not None and not rep.stats_waiter.done():
            rep.stats_waiter.set_result(None)
        if rep.weights_waiter is not None \
                and not rep.weights_waiter.done():
            rep.weights_waiter.set_result(None)
        orphans = list(rep.pending)
        rep.pending.clear()
        for rid in orphans:
            client = self._owners.get(rid)
            if client is None:
                continue
            self.counters["requeued"] += 1
            client.emit({"event": "requeued", "id": client.live.get(rid)})
            self._dispatch(rid)

    async def _replica_reader(self, rep: _Replica) -> None:
        try:
            while True:
                line = await rep.reader.readline()
                if not line:
                    break
                ev = json.loads(line)
                seq = ev.pop("seq", None)
                if seq is not None:
                    # Replay cursor for link healing: the highest event
                    # we processed.  Popped so downstream client frames
                    # stay byte-identical to the sessionless protocol.
                    rep.last_seq = max(rep.last_seq, int(seq))
                if ev.get("event") == "hello_ack":
                    continue   # fresh-session ack (resume handled in
                               # _heal_link's inline exchange)
                if ev.get("event") == "stats":
                    if rep.stats_waiter is not None \
                            and not rep.stats_waiter.done():
                        rep.stats_waiter.set_result(ev["stats"])
                    continue
                if ev.get("event") == "weights_ack":
                    if rep.weights_waiter is not None \
                            and not rep.weights_waiter.done():
                        rep.weights_waiter.set_result(ev)
                    continue
                if ev.get("event") == "pong":
                    # Healthy = the asyncio side answered AND the
                    # scheduler thread's heartbeat is FRESH — a wedged
                    # scheduler behind a live socket must not refresh
                    # the liveness clock.  Freshness is judged against a
                    # few probe intervals, NOT the kill deadline: a pong
                    # whose heartbeat is already deadline-old refreshing
                    # the clock would double the effective detection
                    # latency (stale clock only starts after the beat
                    # has been stale a whole deadline).  The deadline
                    # itself remains the grace for legitimately long
                    # single phases (first-request jit compiles).
                    age = ev.get("sched_age_sec")
                    fresh = min(self.probe_deadline_sec,
                                max(2 * self.probe_sec, 5.0))
                    if age is None or age <= fresh:
                        rep.last_healthy = time.monotonic()
                    counters = ev.get("counters")
                    if isinstance(counters, dict):
                        rep.metrics = counters
                    continue
                rid = ev.get("id")
                client = self._owners.get(rid)
                if client is None:
                    continue   # cancelled/disconnected client
                ev["id"] = client.live.get(rid)
                if ev["event"] in ("done", "error", "cancelled"):
                    rep.pending.pop(rid, None)
                    self._forget(rid)
                    self.counters[{"done": "completed",
                                   "error": "failed",
                                   "cancelled": "cancelled"}
                                  [ev["event"]]] += 1
                client.emit(ev)
        except (ConnectionResetError, json.JSONDecodeError, OSError):
            pass
        await self._heal_or_down(rep)

    # -- link healing (HOROVOD_SERVE_LINK_RETRIES) --

    async def _heal_or_down(self, rep: _Replica) -> None:
        """A broken replica socket first tries a bounded reconnect (the
        replica parked our session and replays the events we missed);
        only when healing is off, the process is actually gone, or every
        attempt fails does it escalate to the battle-tested death path
        (requeue in-flight work + supervisor relaunch)."""
        if (self.link_retries <= 0 or self._shutdown.is_set()
                or rep.healing or not rep.alive
                or rep.proc is None or rep.proc.returncode is not None):
            self._on_replica_down(rep)
            return
        rep.healing = True
        try:
            for attempt in range(self.link_retries):
                await asyncio.sleep(0.2 * (attempt + 1))
                if (self._shutdown.is_set() or not rep.alive
                        or rep.proc.returncode is not None):
                    break   # real death: its path already ran/will run
                if await self._heal_link(rep):
                    rep.healing = False
                    self.counters["link_reconnects"] += 1
                    sys.stderr.write(
                        f"replica {rep.idx} control link healed "
                        f"(attempt {attempt + 1}/"
                        f"{self.link_retries})\n")
                    sys.stderr.flush()
                    self._tasks.append(asyncio.ensure_future(
                        self._replica_reader(rep)))
                    self._drain_queue()
                    return
        finally:
            rep.healing = False
        self._on_replica_down(rep)

    async def _heal_link(self, rep: _Replica) -> bool:
        """One reconnect + resume exchange.  True iff the replica
        accepted the resume — the new socket is installed and every
        pending generate it never received has been re-sent."""
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", rep.port, limit=1 << 26)
        except OSError:
            return False
        try:
            writer.write((json.dumps(
                {"op": "hello", "role": "router",
                 "session": rep.session_token,
                 "last_seq": rep.last_seq}) + "\n").encode())
            await writer.drain()
            line = await asyncio.wait_for(reader.readline(), timeout=10)
            ack = json.loads(line) if line else {}
            if not (ack.get("event") == "hello_ack"
                    and ack.get("resume")):
                raise OSError("resume refused")
            rep.reader, rep.writer = reader, writer
            # Generates lost in flight during the reset: dispatched on
            # our books but absent from the replica's live set.
            seen = set(ack.get("live") or [])
            for rid in list(rep.pending):
                if rid in seen or rid not in self._reqs:
                    continue
                frame = dict(self._reqs[rid])
                frame["id"] = rid
                rep.writer.write((json.dumps(frame) + "\n").encode())
            await rep.writer.drain()
            return True
        except (OSError, asyncio.TimeoutError, json.JSONDecodeError,
                ValueError):
            try:
                writer.close()
            except OSError:
                pass
            return False

    # -- dispatch --

    def _forget(self, rid: str) -> None:
        self._reqs.pop(rid, None)
        client = self._owners.pop(rid, None)
        if client is not None:
            client.live.pop(rid, None)

    def _pick(self) -> Optional[_Replica]:
        # A healing replica is alive but its socket is mid-reconnect:
        # no new dispatches until the link is back (park in the queue —
        # _heal_or_down drains it either way).
        live = [r for r in self.replicas if r.alive and not r.healing]
        if not live:
            return None
        return min(live, key=lambda r: (len(r.pending), r.idx))

    def _dispatch(self, rid: str) -> None:
        rep = self._pick()
        if rep is None:
            # Park only while some replica is not terminal (its
            # supervisor may still relaunch it) — see
            # _fail_queue_if_hopeless.
            if any(not r.terminal for r in self.replicas):
                self._queue.append(rid)   # a rejoin may still come
            else:
                client = self._owners.get(rid)
                if client is not None:
                    self.counters["failed"] += 1
                    client.emit({"event": "error",
                                 "id": client.live.get(rid),
                                 "error": "no live replica and no restart "
                                          "budget left"})
                self._forget(rid)
            return
        frame = dict(self._reqs[rid])
        frame["id"] = rid
        rep.pending[rid] = self._owners[rid]
        try:
            rep.writer.write((json.dumps(frame) + "\n").encode())
        except (ConnectionResetError, OSError):
            self._on_replica_down(rep)

    def _drain_queue(self) -> None:
        pending = list(self._queue)
        self._queue.clear()
        for rid in pending:
            self._dispatch(rid)

    # -- live weight pushes --

    async def _push_weights_rep(self, rep: _Replica, frame: dict,
                                timeout: float = 90.0) -> Optional[dict]:
        """One replica's weights exchange; ``None`` on death or timeout
        (the death path owns the failure — its requests requeue and the
        cached frame replays on the relaunch).  A replica mid-link-heal
        is skipped the same way; the next push (or a relaunch replay)
        covers it."""
        if not rep.alive or rep.healing:
            return None
        async with rep.stats_lock:
            rep.weights_waiter = asyncio.get_running_loop() \
                .create_future()
            try:
                rep.writer.write((json.dumps(frame) + "\n").encode())
                await rep.writer.drain()
                return await asyncio.wait_for(rep.weights_waiter,
                                              timeout=timeout)
            except (asyncio.TimeoutError, OSError):
                return None
            finally:
                rep.weights_waiter = None

    # -- liveness probes (wedged-replica detection) --

    async def _probe_loop(self) -> None:
        while not self._shutdown.is_set():
            await asyncio.sleep(self.probe_sec)
            if self._shutdown.is_set():
                return
            now = time.monotonic()
            for rep in self.replicas:
                if not rep.alive or rep.healing or rep.proc is None:
                    continue
                stale = now - rep.last_healthy
                if stale > self.probe_deadline_sec:
                    # Kill, don't just mark down: the process is alive
                    # but useless, and killing it routes everything
                    # through the one battle-tested failure path — the
                    # supervisor requeues its in-flight requests onto
                    # survivors and relaunches it under the restart
                    # budget with the fault schedule scrubbed.
                    self.counters["wedged_kills"] += 1
                    sys.stderr.write(
                        f"replica {rep.idx} is wedged (no healthy pong "
                        f"for {stale:.1f}s > "
                        f"{self.probe_deadline_sec:.1f}s deadline); "
                        f"killing it so its requests requeue\n")
                    sys.stderr.flush()
                    try:
                        rep.proc.kill()
                    except ProcessLookupError:
                        pass
                    continue
                try:
                    rep.writer.write(b'{"op": "ping"}\n')
                except (ConnectionResetError, OSError):
                    self._on_replica_down(rep)

    # -- client side --

    async def _handle_client(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        client = _ClientConn(writer)

        async def write_loop() -> None:
            while True:
                ev = await client.outbox.get()
                if ev is None:
                    break
                writer.write((json.dumps(ev) + "\n").encode())
                await writer.drain()

        wtask = asyncio.ensure_future(write_loop())
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    client.emit({"event": "error", "id": None,
                                 "error": "malformed frame"})
                    continue
                op = msg.get("op")
                if op == "generate":
                    self._next_rid += 1
                    rid = f"q{client.cid}.{self._next_rid}"
                    self._reqs[rid] = {k: msg[k] for k in
                                       ("prompt", "max_tokens")
                                       if k in msg}
                    self._reqs[rid]["op"] = "generate"
                    for k in ("temperature", "seed"):
                        if k in msg:
                            self._reqs[rid][k] = msg[k]
                    self._owners[rid] = client
                    client.live[rid] = str(msg.get("id", rid))
                    self.counters["dispatched"] += 1
                    self._dispatch(rid)
                elif op == "cancel":
                    want = str(msg.get("id", ""))
                    for rid, crid in list(client.live.items()):
                        if crid != want:
                            continue
                        for rep in self.replicas:
                            if rid in rep.pending and rep.alive:
                                rep.writer.write((json.dumps(
                                    {"op": "cancel", "id": rid})
                                    + "\n").encode())
                        if rid in self._queue:
                            self._queue.remove(rid)
                            client.emit({"event": "cancelled", "id": want})
                            self._forget(rid)
                elif op == "weights":
                    frame = {"op": "weights",
                             "frames": msg.get("frames") or [],
                             "epoch": int(msg.get("epoch", 0))}
                    # Cache FIRST: a replica that dies mid-push gets
                    # the frame replayed when it rejoins.
                    self._last_push = frame
                    self.counters["weight_pushes"] += 1
                    acks = []
                    for rep in self.replicas:
                        ack = await self._push_weights_rep(rep, frame)
                        if ack is not None:
                            acks.append({
                                "replica": rep.idx,
                                "applied": ack.get("applied"),
                                "epoch": ack.get("epoch"),
                                "restarted": ack.get("restarted")})
                    client.emit({"event": "weights_ack",
                                 "epoch": frame["epoch"],
                                 "replicas": acks})
                elif op == "stats":
                    client.emit({"event": "stats",
                                 "stats": await self._gather_stats()})
                elif op == "ping":
                    client.emit({"event": "pong"})
                elif op == "shutdown":
                    client.emit({"event": "bye"})
                    self._shutdown.set()
                    break
                else:
                    client.emit({"event": "error", "id": None,
                                 "error": f"unknown op {op!r}"})
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            for rid in list(client.live):
                for rep in self.replicas:
                    if rid in rep.pending and rep.alive:
                        try:
                            rep.writer.write((json.dumps(
                                {"op": "cancel", "id": rid}) + "\n")
                                .encode())
                        except OSError:
                            pass
                        rep.pending.pop(rid, None)
                if rid in self._queue:
                    self._queue.remove(rid)
                self._forget(rid)
            client.outbox.put_nowait(None)
            try:
                await asyncio.wait_for(wtask, timeout=5)
            except (asyncio.TimeoutError, ConnectionResetError,
                    BrokenPipeError):
                wtask.cancel()
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass

    async def _gather_stats(self) -> dict:
        out = {"router": dict(self.counters)}
        out["router"]["queue_depth"] = len(self._queue)
        out["router"]["restarts_left"] = self._restarts_left
        reps = []
        for rep in self.replicas:
            entry = {"replica": rep.idx, "alive": rep.alive,
                     "pending": len(rep.pending)}
            if rep.alive and not rep.healing:
                async with rep.stats_lock:
                    rep.stats_waiter = asyncio.get_running_loop() \
                        .create_future()
                    try:
                        rep.writer.write(b'{"op": "stats"}\n')
                        stats = await asyncio.wait_for(rep.stats_waiter,
                                                       timeout=10)
                        if stats is not None:
                            entry["scheduler"] = stats
                    except (asyncio.TimeoutError, OSError):
                        pass
                    finally:
                        rep.stats_waiter = None
            reps.append(entry)
        out["replicas"] = reps
        return out

    # -- entry --

    async def run(self) -> int:
        t0 = time.monotonic()
        try:
            await asyncio.gather(*[self._spawn(rep, scrub_fault=False)
                                   for rep in self.replicas])
        except BaseException:
            # Partial fleet startup must not leak the replicas that DID
            # launch (the gate checks for exactly this).
            for rep in self.replicas:
                if rep.proc is not None and rep.proc.returncode is None:
                    rep.proc.kill()
                    await rep.proc.wait()
            raise
        # limit: a weights push is one (large) JSON line from a client.
        server = await asyncio.start_server(self._handle_client, self.host,
                                            self.port, limit=1 << 26)
        if self.probe_sec > 0:
            self._tasks.append(asyncio.ensure_future(self._probe_loop()))
        port = server.sockets[0].getsockname()[1]
        # Observability mount: when HOROVOD_METRICS_PORT is set the
        # router's counters + fleet liveness join the same HTTP endpoint
        # the engine plane serves (horovod_serve_* gauges on /metrics,
        # key "serve" on /json) — one scrape covers train AND serve.
        if os.environ.get("HOROVOD_METRICS_PORT", "") not in ("", "0"):
            from horovod_tpu.monitor.server import (
                get_metrics_server,
                start_metrics_server,
            )

            def _router_stats() -> dict:
                out = dict(self.counters)
                out["replicas"] = self.num_replicas
                out["replicas_alive"] = sum(
                    1 for r in self.replicas if r.alive)
                # Fleet-wide scheduler counters (prefix cache / fused
                # kernel instruments), summed from the latest
                # pong-piggybacked snapshot of each replica — no extra
                # round trips on the scrape path.
                totals: Dict[str, int] = {}
                for r in self.replicas:
                    for k, v in r.metrics.items():
                        if isinstance(v, (int, float)):
                            totals[k] = totals.get(k, 0) + v
                out.update(totals)
                return out

            try:
                mport = start_metrics_server(
                    int(os.environ["HOROVOD_METRICS_PORT"]),
                    lambda: {}, lambda: {})
                srv = get_metrics_server()
                if srv is not None:
                    srv.mount("serve", _router_stats)
                print(f"SERVE_METRICS_READY port={mport}", flush=True)
            except (OSError, RuntimeError, ValueError) as exc:
                print(f"serve metrics endpoint disabled: {exc}",
                      flush=True)
        print(f"SERVE_ROUTER_READY port={port} replicas="
              f"{self.num_replicas} startup_sec="
              f"{time.monotonic() - t0:.1f}", flush=True)
        await self._shutdown.wait()
        server.close()
        await server.wait_closed()
        # Clean teardown: polite shutdown frame, then terminate/kill.
        for rep in self.replicas:
            if rep.alive and rep.writer is not None:
                try:
                    rep.writer.write(b'{"op": "shutdown"}\n')
                except OSError:
                    pass
        for rep in self.replicas:
            if rep.proc is None or rep.proc.returncode is not None:
                continue
            try:
                await asyncio.wait_for(rep.proc.wait(), timeout=10)
            except asyncio.TimeoutError:
                rep.proc.terminate()
                try:
                    await asyncio.wait_for(rep.proc.wait(), timeout=5)
                except asyncio.TimeoutError:
                    rep.proc.kill()
                    await rep.proc.wait()
        for task in self._tasks:
            if not task.done():
                task.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        return 0


def serve_main(args) -> int:
    """The ``run.py --serve`` entry: router + replica fleet.

    ``--serve-model`` is EITHER a LlamaConfig builder name or a
    checkpoint directory: a directory containing manifests makes every
    replica load the newest complete checkpoint's params
    (HOROVOD_SERVE_CHECKPOINT) — the model name rides the manifest's
    ``meta.model`` when the trainer recorded one.
    """
    replica_env = {}
    model_arg = getattr(args, "serve_model", None)
    if model_arg and os.path.isdir(model_arg):
        from horovod_tpu.checkpoint import latest_manifest

        found = latest_manifest(model_arg)
        if found is None:
            sys.stderr.write(
                f"--serve-model {model_arg}: directory holds no "
                "complete checkpoint manifest\n")
            return 1
        manifest, step = found
        replica_env["HOROVOD_SERVE_CHECKPOINT"] = model_arg
        meta_model = (manifest.get("meta") or {}).get("model")
        if meta_model:
            replica_env["HOROVOD_SERVE_MODEL"] = str(meta_model)
        print(f"serving checkpoint step {step} from {model_arg}",
              flush=True)
    elif model_arg:
        replica_env["HOROVOD_SERVE_MODEL"] = model_arg
    router = Router(
        num_replicas=max(1, args.replicas),
        restart_budget=max(0, args.restart_on_failure),
        relaunch_delay=max(0.0, args.relaunch_delay_sec),
        port=args.serve_port,
        replica_env=replica_env)
    try:
        return asyncio.run(router.run())
    except KeyboardInterrupt:
        return 130
