"""Process launcher: ``python -m horovod_tpu.run -np N -- python train.py``.

The reference delegates process orchestration entirely to ``mpirun``
(reference docs/running.md:25-42; Horovod 0.15 has no horovodrun).  On TPU
there is no MPI: this launcher spawns N copies of the command with
HOROVOD_RANK/SIZE/LOCAL_RANK/LOCAL_SIZE/COORDINATOR set, picks a free
coordinator port, streams output with rank prefixes, and propagates the
first failure (terminating the rest, like mpirun's default behavior).

Multi-host: run the launcher once per host with ``--hosts-total`` /
``--host-index`` / ``--coordinator host0:port`` so ranks are globally
numbered and all processes rendezvous at host 0.

Fault tolerance: ``--restart-on-failure N`` switches the launcher into a
supervisor that relaunches a dead worker (same rank, same env) up to N
times total instead of tearing the job down — pair it with workers built
on :func:`horovod_tpu.elastic.run_elastic`, whose surviving ranks roll
back to their last commit and re-rendezvous with the replacement.  A
relaunched worker's env is scrubbed of ``HOROVOD_FAULT_INJECT`` so an
injected fault fires once, not on every incarnation.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _stream(prefix: str, pipe, out):
    for line in iter(pipe.readline, b""):
        out.write(f"[{prefix}] ".encode() + line)
        out.flush()
    pipe.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.run",
        description="Launch N coordinated worker processes.")
    parser.add_argument("-np", "--num-proc", type=int, required=True,
                        help="processes on this host")
    parser.add_argument("--coordinator", default=None,
                        help="host:port of rank 0's coordinator "
                             "(default: 127.0.0.1:<free port>)")
    parser.add_argument("--host-index", type=int, default=0,
                        help="this host's index (multi-host)")
    parser.add_argument("--procs-per-host", type=int, default=None,
                        help="ranks per host (default: -np)")
    parser.add_argument("--hosts-total", type=int, default=1)
    parser.add_argument("--restart-on-failure", type=int, default=0,
                        metavar="N",
                        help="supervisor mode: relaunch a worker that "
                             "exits non-zero (same rank/env), up to N "
                             "relaunches total, instead of terminating "
                             "the job (pair with horovod_tpu.elastic)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command to run (prefix with --)")
    args = parser.parse_args(argv)

    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given")

    pph = args.procs_per_host or args.num_proc
    world = pph * args.hosts_total
    coordinator = args.coordinator or f"127.0.0.1:{_free_port()}"

    threads = []

    def spawn(local_rank: int, scrub_fault_inject: bool = False):
        rank = args.host_index * pph + local_rank
        env = dict(os.environ)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(world),
            "HOROVOD_LOCAL_RANK": str(local_rank),
            "HOROVOD_LOCAL_SIZE": str(pph),
            "HOROVOD_COORDINATOR": coordinator,
        })
        if scrub_fault_inject:
            # A relaunched incarnation must not re-fire the injected
            # fault at the same step, or the job would never converge.
            env.pop("HOROVOD_FAULT_INJECT", None)
        p = subprocess.Popen(command, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        t = threading.Thread(target=_stream, args=(str(rank), p.stdout,
                                                   sys.stdout.buffer),
                             daemon=True)
        t.start()
        threads.append(t)
        return p

    procs: list[subprocess.Popen] = [
        spawn(local_rank) for local_rank in range(args.num_proc)
    ]
    restarts_left = max(0, args.restart_on_failure)

    rc = 0
    try:
        remaining = set(range(len(procs)))
        while remaining:
            for i in list(remaining):
                code = procs[i].poll()
                if code is None:
                    continue
                # Report the global rank, matching the stream prefixes
                # (local index i != rank when --host-index > 0).
                rank = args.host_index * pph + i
                if code != 0 and restarts_left > 0:
                    restarts_left -= 1
                    sys.stderr.write(
                        f"rank {rank} exited with code {code}; "
                        f"relaunching ({restarts_left} restarts left)\n")
                    sys.stderr.flush()
                    procs[i] = spawn(i, scrub_fault_inject=True)
                    continue
                remaining.discard(i)
                if code != 0 and rc == 0:
                    rc = code
                    sys.stderr.write(
                        f"rank {rank} exited with "
                        f"code {code}; terminating remaining ranks\n")
                    for j in remaining:
                        procs[j].terminate()
            if remaining:
                import time

                time.sleep(0.1)
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        rc = 130
    for t in threads:
        t.join(timeout=5)
    return rc


if __name__ == "__main__":
    sys.exit(main())
