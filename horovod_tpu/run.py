"""Process launcher: ``python -m horovod_tpu.run -np N -- python train.py``.

The reference delegates process orchestration entirely to ``mpirun``
(reference docs/running.md:25-42; Horovod 0.15 has no horovodrun).  On TPU
there is no MPI: this launcher spawns N copies of the command with
HOROVOD_RANK/SIZE/LOCAL_RANK/LOCAL_SIZE/COORDINATOR set, picks a free
coordinator port, streams output with rank prefixes, and propagates the
first failure (terminating the rest, like mpirun's default behavior).

Multi-host: run the launcher once per host with ``--hosts-total`` /
``--host-index`` / ``--coordinator host0:port`` so ranks are globally
numbered and all processes rendezvous at host 0.

Fault tolerance: ``--restart-on-failure N`` switches the launcher into a
supervisor that relaunches a dead worker (same rank, same env) up to N
times total instead of tearing the job down — pair it with workers built
on :func:`horovod_tpu.elastic.run_elastic`, whose surviving ranks roll
back to their last commit and re-rendezvous with the replacement.  A
relaunched worker's env is scrubbed of ``HOROVOD_FAULT_INJECT`` so an
injected fault fires once, not on every incarnation.

Inference serving: ``--serve`` starts the multi-replica serving stack
instead of launching a training command — a router on ``--serve-port``
dispatching to ``--replicas`` replica worlds with continuous batching
and a paged KV cache (docs/serving.md); ``--restart-on-failure`` doubles
as the replica relaunch budget.

Live status: ``--status host:port`` queries a running job's metrics
endpoint (rank 0 serves it when ``HOROVOD_METRICS_PORT`` is set — see
docs/observability.md) and prints a fleet summary; ``--raw`` dumps the
/json payload.

Elastic membership: ``--elastic`` additionally sets ``HOROVOD_ELASTIC=1``
so the engine may re-form the world IN PLACE around the survivors — the
env rank becomes a persistent worker id (a join candidacy, not the final
rank), and the coordinator commits contiguous re-ranked membership
epochs.  Under ``--elastic`` a worker that dies with no restart budget
left is ABANDONED (the survivors shrink and keep training) instead of
terminating the job; a relaunched worker joins the RUNNING world as a
candidate and the world grows back.  The job fails only when worker id 0
(the coordinator/authority) fails or no worker exits cleanly.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _stream(prefix: str, pipe, out):
    for line in iter(pipe.readline, b""):
        out.write(f"[{prefix}] ".encode() + line)
        out.flush()
    pipe.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.run",
        description="Launch N coordinated worker processes.")
    # Required unless --print-config short-circuits (validated below —
    # argparse's required= cannot express "required for the launch path").
    parser.add_argument("-np", "--num-proc", type=int, default=None,
                        help="processes on this host")
    parser.add_argument("--coordinator", default=None,
                        help="host:port of rank 0's coordinator "
                             "(default: 127.0.0.1:<free port>)")
    parser.add_argument("--host-index", type=int, default=0,
                        help="this host's index (multi-host)")
    parser.add_argument("--procs-per-host", type=int, default=None,
                        help="ranks per host (default: -np)")
    parser.add_argument("--hosts-total", type=int, default=1)
    parser.add_argument("--restart-on-failure", type=int, default=0,
                        metavar="N",
                        help="supervisor mode: relaunch a worker that "
                             "exits non-zero (same rank/env), up to N "
                             "relaunches total, instead of terminating "
                             "the job (pair with horovod_tpu.elastic)")
    parser.add_argument("--elastic", action="store_true",
                        help="in-place elastic membership: set "
                             "HOROVOD_ELASTIC=1 for every worker, abandon "
                             "a dead worker once the restart budget is "
                             "spent (survivors shrink and continue), and "
                             "let relaunched workers rejoin the running "
                             "world as candidates")
    parser.add_argument("--relaunch-delay-sec", type=float, default=0.0,
                        metavar="SEC",
                        help="supervisor mode: wait SEC before relaunching "
                             "a dead worker (forces an elastic shrink "
                             "before the rejoin; mainly for tests)")
    parser.add_argument("--status", default=None, metavar="HOST:PORT",
                        help="query a LIVE job's metrics endpoint "
                             "(HOROVOD_METRICS_PORT on rank 0) and print "
                             "a fleet summary; add --raw for the JSON")
    parser.add_argument("--raw", action="store_true",
                        help="with --status: print the raw /json payload")
    parser.add_argument("--print-config", action="store_true",
                        help="dump the full resolved engine knob table "
                             "(env -> default -> effective) and exit; "
                             "mirrors the table in docs/performance.md")
    parser.add_argument("--serve", action="store_true",
                        help="inference serving mode: start the "
                             "multi-replica router + replica fleet "
                             "(docs/serving.md) instead of launching a "
                             "training command")
    parser.add_argument("--serve-port", type=int, default=8070,
                        help="router listen port under --serve "
                             "(0 = ephemeral, printed in the READY line)")
    parser.add_argument("--replicas", type=int, default=1,
                        help="serving replicas under --serve (each one "
                             "engine world; --restart-on-failure is the "
                             "per-fleet relaunch budget on replica death)")
    parser.add_argument("--serve-model", default=None, metavar="NAME",
                        help="served model under --serve: a LlamaConfig "
                             "name (LlamaConfig.<NAME>) OR a checkpoint "
                             "directory (replicas load the newest "
                             "complete manifest's weights instead of "
                             "seeded params; default: HOROVOD_SERVE_MODEL "
                             "or tiny)")
    parser.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                        help="set HOROVOD_CHECKPOINT_DIR for every "
                             "worker: training built on run_elastic "
                             "saves crash-consistent sharded checkpoints "
                             "there and a relaunched/resized world "
                             "resumes from the newest complete manifest "
                             "(docs/checkpointing.md)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command to run (prefix with --)")
    args = parser.parse_args(argv)

    if args.status:
        from horovod_tpu.monitor.server import format_status, query_status

        try:
            payload = query_status(args.status)
        except (OSError, ValueError) as exc:
            # ValueError covers a malformed host:port and a non-JSON
            # response from something else squatting on the port.
            sys.stderr.write(
                f"cannot reach metrics endpoint at {args.status}: {exc}\n"
                "(is the job running with HOROVOD_METRICS_PORT set?)\n")
            return 1
        if args.raw:
            import json

            print(json.dumps(payload, indent=2, sort_keys=True))
        else:
            print(format_status(payload))
        return 0
    if args.print_config:
        from horovod_tpu.autotune import format_table

        print(format_table())
        return 0
    if args.serve:
        from horovod_tpu.serve.router import serve_main

        return serve_main(args)
    if args.num_proc is None:
        parser.error("the following arguments are required: -np/--num-proc")

    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given")

    pph = args.procs_per_host or args.num_proc
    world = pph * args.hosts_total
    coordinator = args.coordinator or f"127.0.0.1:{_free_port()}"

    threads = []

    def spawn(local_rank: int, scrub_fault_inject: bool = False):
        rank = args.host_index * pph + local_rank
        env = dict(os.environ)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(world),
            "HOROVOD_LOCAL_RANK": str(local_rank),
            "HOROVOD_LOCAL_SIZE": str(pph),
            "HOROVOD_COORDINATOR": coordinator,
        })
        if args.elastic:
            # The env rank is a persistent worker id / join candidacy under
            # elastic membership; the engine's coordinator commits the
            # actual (epoch, rank, size) at rendezvous.
            env["HOROVOD_ELASTIC"] = "1"
        if args.checkpoint_dir:
            env["HOROVOD_CHECKPOINT_DIR"] = args.checkpoint_dir
        if scrub_fault_inject:
            # A relaunched incarnation must not re-fire the injected
            # fault at the same step, or the job would never converge.
            env.pop("HOROVOD_FAULT_INJECT", None)
        p = subprocess.Popen(command, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        t = threading.Thread(target=_stream, args=(str(rank), p.stdout,
                                                   sys.stdout.buffer),
                             daemon=True)
        t.start()
        threads.append(t)
        return p

    procs: list[subprocess.Popen] = [
        spawn(local_rank) for local_rank in range(args.num_proc)
    ]
    restarts_left = max(0, args.restart_on_failure)
    pending_respawn: dict[int, float] = {}  # local index → respawn due time
    exit_codes: dict[int, int] = {}         # local index → last exit code

    import time

    rc = 0
    try:
        remaining = set(range(len(procs)))
        while remaining or pending_respawn:
            if not remaining and pending_respawn:
                # Everyone else already finished: there is no running world
                # for a delayed replacement to rejoin — don't spawn it into
                # a doomed rendezvous.
                sys.stderr.write(
                    "job finished before the delayed relaunch; "
                    "cancelling it\n")
                sys.stderr.flush()
                break
            now = time.time()
            for i in [i for i, due in pending_respawn.items() if due <= now]:
                del pending_respawn[i]
                procs[i] = spawn(i, scrub_fault_inject=True)
                remaining.add(i)
            for i in list(remaining):
                code = procs[i].poll()
                if code is None:
                    continue
                # Report the global rank, matching the stream prefixes
                # (local index i != rank when --host-index > 0).
                rank = args.host_index * pph + i
                exit_codes[i] = code
                if code != 0 and restarts_left > 0:
                    restarts_left -= 1
                    sys.stderr.write(
                        f"rank {rank} exited with code {code}; "
                        f"relaunching ({restarts_left} restarts left)\n")
                    sys.stderr.flush()
                    if args.relaunch_delay_sec > 0:
                        remaining.discard(i)
                        pending_respawn[i] = now + args.relaunch_delay_sec
                    else:
                        procs[i] = spawn(i, scrub_fault_inject=True)
                    continue
                remaining.discard(i)
                if code != 0:
                    # Compare the GLOBAL rank, not the local index: on a
                    # --host-index > 0 supervisor no local worker is the
                    # coordinator, and all of them are abandonable.
                    if args.elastic and rank != 0:
                        # In-place shrink: abandon the dead worker; the
                        # surviving ranks re-form the world without it
                        # (worker id 0 is the coordinator/authority — its
                        # death still terminates the job below).
                        sys.stderr.write(
                            f"rank {rank} exited with code {code}; "
                            "abandoning it (elastic shrink — survivors "
                            "continue)\n")
                        sys.stderr.flush()
                        continue
                    if rc == 0:
                        rc = code
                        sys.stderr.write(
                            f"rank {rank} exited with "
                            f"code {code}; terminating remaining ranks\n")
                        for j in remaining:
                            procs[j].terminate()
            if remaining or pending_respawn:
                time.sleep(0.1)
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        rc = 130
    if (args.elastic and rc == 0 and exit_codes
            and all(c != 0 for c in exit_codes.values())):
        # Elastic abandons individual failures, but a job where NO worker
        # exited cleanly still failed (e.g. the world shrank below
        # HOROVOD_ELASTIC_MIN_SIZE and every survivor terminated).
        rc = next(c for c in exit_codes.values() if c != 0)
    for t in threads:
        t.join(timeout=5)
    return rc


if __name__ == "__main__":
    sys.exit(main())
