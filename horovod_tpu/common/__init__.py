"""Common layer: process identity, lifecycle, shared enums.

Reference parity: ``horovod/common/__init__.py``.
"""

from horovod_tpu.common.basics import HorovodBasics, basics

init = basics.init
shutdown = basics.shutdown
is_initialized = basics.is_initialized
rank = basics.rank
size = basics.size
local_rank = basics.local_rank
local_size = basics.local_size
epoch = basics.epoch
fleet_stats = basics.fleet_stats
mpi_threads_supported = basics.mpi_threads_supported

__all__ = [
    "HorovodBasics",
    "basics",
    "init",
    "shutdown",
    "is_initialized",
    "rank",
    "size",
    "local_rank",
    "local_size",
    "epoch",
    "fleet_stats",
    "mpi_threads_supported",
]
