"""Compatibility shims for the installed JAX version.

``jax.shard_map`` only exists as a top-level API in newer JAX; on the
0.4.x line it lives in ``jax.experimental.shard_map`` and spells the
replication-checking knob ``check_rep`` instead of ``check_vma``.  The
seed assumed the new spelling, which broke every jit-path test on this
image's jax 0.4.37.  Importing this module gives library code one
``shard_map`` symbol that works on both, and (when needed) aliases it
onto the ``jax`` namespace so existing ``jax.shard_map(...)`` call sites
keep working.

Kept in ``common`` (imported lazily by jax-facing modules) so the
jax-free surfaces — torch/tf frontends, the native-engine workers, the
elastic module — never pull jax in.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "axis_size"]

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma,
                              **kw)

    # Alias for call sites written against the new spelling.
    jax.shard_map = shard_map

if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        # psum of the literal 1 is special-cased to a compile-time
        # constant equal to the (possibly tuple) axis size — the
        # long-standing idiom lax.axis_size formalized.
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = axis_size
