"""Process identity + lifecycle for the TPU-native Horovod rebuild.

Reference parity: ``horovod/common/__init__.py`` (HorovodBasics, the ctypes
bridge to the C ABI ``horovod_init/_shutdown/_rank/_size/_local_rank/
_local_size/_mpi_threads_supported`` declared in
``horovod/common/operations.h:68-98``).

TPU-native design
-----------------
Horovod's identity model is "one process per accelerator, ranks assigned by
mpirun".  On TPU the natural model is SPMD over a device mesh: one process per
*host*, each owning several chips, with JAX's distributed runtime (not MPI)
providing process_index/process_count.  We therefore keep Horovod's
rank/size/local_rank/local_size vocabulary but define it over *processes*
(hosts), and additionally expose device counts, because data parallelism on
TPU spans devices-within-a-process as well as processes.

The native C++ core (``horovod_tpu/cpp``, built separately) provides the
background coordinator (negotiation, fusion, timeline, stall detection)
behind the same C ABI as the reference.  This module loads it via ctypes when
the shared library is present, with a pure-Python fallback so the framework
is importable without the native build.
"""

from __future__ import annotations

import atexit
import ctypes
import os
import threading
from typing import Optional, Sequence

__all__ = ["HorovodBasics", "basics"]

# Env vars understood for rank discovery, in priority order.  The OMPI/PMI
# names are accepted for drop-in familiarity with the reference's mpirun
# workflow (reference test/common.py:24-56 reads the same names).
_RANK_ENV = ("HOROVOD_RANK", "OMPI_COMM_WORLD_RANK", "PMI_RANK")
_SIZE_ENV = ("HOROVOD_SIZE", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE")
_LOCAL_RANK_ENV = ("HOROVOD_LOCAL_RANK", "OMPI_COMM_WORLD_LOCAL_RANK")
_LOCAL_SIZE_ENV = ("HOROVOD_LOCAL_SIZE", "OMPI_COMM_WORLD_LOCAL_SIZE")


def _env_int(names: Sequence[str]) -> Optional[int]:
    for name in names:
        value = os.environ.get(name)
        if value is not None and value != "":
            return int(value)
    return None


def _find_native_lib() -> Optional[str]:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    candidate = os.path.join(here, "libhorovod_core.so")
    if os.path.exists(candidate):
        return candidate
    # Primary locations + self-healing compile from the shipped sources
    # (install-time build is setup.py's job; this covers source checkouts
    # and compiler-at-runtime installs).
    from horovod_tpu.common.native_build import ensure_native_lib

    return ensure_native_lib()


class HorovodBasics:
    """init/shutdown/rank/size lifecycle, optionally backed by the C++ core.

    Mirrors the reference ``HorovodBasics`` (common/__init__.py:51-154): the
    same method surface, raising if queried before ``init()``.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._initialized = False
        self._rank = 0
        self._size = 1
        self._local_rank = 0
        self._local_size = 1
        self._lib = None
        self._atexit_registered = False

    # -- lifecycle ---------------------------------------------------------

    def init(
        self,
        comm: Optional[Sequence[int]] = None,
        *,
        rank: Optional[int] = None,
        size: Optional[int] = None,
        local_rank: Optional[int] = None,
        local_size: Optional[int] = None,
        coordinator: Optional[str] = None,
        jax_distributed: Optional[bool] = None,
    ) -> None:
        """Initialize the runtime.

        ``comm`` accepts a rank subset (a list of WORLD ranks), matching the
        reference's ``hvd.init(comm=...)`` (common/__init__.py:58-84,
        operations.cc:1469-1488): the listed ranks form their own
        communicator — own rank numbering, own coordinator, own ring —
        and collectives span only them.  Processes NOT in the list
        initialize as a world of one (their collectives are identities),
        where the reference leaves them outside the MPI group entirely; a
        self-communicator is the functional equivalent without a second
        process group concept.  The subset coordinator listens on the world
        coordinator's port + 1 + min(comm) (deterministic and distinct for
        disjoint subsets); pass ``coordinator=`` to choose explicitly.
        mpi4py communicator objects are not accepted — there is no MPI here.

        Identity resolution order: explicit kwargs > HOROVOD_*/OMPI_*/PMI_*
        env vars > JAX distributed runtime (process_index/process_count) >
        single-process defaults.  Unlike the reference there is no MPI_Init:
        process rendezvous is the JAX coordination service's job (SURVEY.md
        §3.1 "TPU equivalent").

        ``jax_distributed=True`` (or ``HOROVOD_JAX_DISTRIBUTED=1``)
        additionally bootstraps JAX's own multi-process runtime
        (``jax.distributed.initialize``) from the same identity, so the
        launcher-provided rank/size/coordinator stands in for the pod's
        usual metadata discovery: after init, ``jax.devices()`` spans every
        process's chips and the jit/GSPMD path runs true multi-host.  The JAX
        coordination service listens on the engine coordinator's port + 64
        (override with ``HOROVOD_JAX_COORDINATOR=host:port``).  Must be
        called before the first JAX backend use, and is not compatible with
        ``comm=`` subsets (JAX has one global process group).
        """
        with self._lock:
            if self._initialized:
                return
            if comm is not None and not isinstance(comm, (list, tuple)):
                raise TypeError(
                    "comm must be a list of world ranks (mpi4py communicators "
                    "are not supported in the TPU-native runtime)"
                )

            if rank is None:
                rank = _env_int(_RANK_ENV)
            if size is None:
                size = _env_int(_SIZE_ENV)
            if (rank is None) != (size is None):
                raise ValueError(
                    "half-specified identity: rank and size must be given "
                    "together (via kwargs or HOROVOD_RANK/HOROVOD_SIZE "
                    "style env vars); got "
                    f"rank={rank!r}, size={size!r}"
                )
            from_jax = False
            if rank is None:
                rank, size = self._jax_identity()
                from_jax = True
            if local_rank is None:
                local_rank = _env_int(_LOCAL_RANK_ENV)
            if local_size is None:
                local_size = _env_int(_LOCAL_SIZE_ENV)
            if local_size is None:
                if from_jax:
                    # JAX multi-host deployments run one process per host.
                    local_size = 1
                else:
                    # Env-launched N processes with no local info: the
                    # single-host CI/test topology.
                    local_size = size
            if local_rank is None:
                local_rank = rank % local_size

            rank, size = int(rank), int(size)
            local_rank, local_size = int(local_rank), int(local_size)

            if comm:
                members = sorted({int(r) for r in comm})
                if members[0] < 0 or members[-1] >= size:
                    raise ValueError(
                        f"comm={members} contains ranks outside the world "
                        f"[0, {size})"
                    )
                world_rank, world_local_size = rank, local_size
                if world_rank not in members:
                    # Excluded process: world of one, no coordinator.
                    rank, size, local_rank, local_size = 0, 1, 0, 1
                else:
                    rank = members.index(world_rank)
                    size = len(members)
                    # Local identity follows the WORLD node layout so a
                    # subset spanning hosts still gets a meaningful
                    # intra-host split.
                    my_node = world_rank // world_local_size
                    same_node = [m for m in members
                                 if m // world_local_size == my_node]
                    local_rank = same_node.index(world_rank)
                    local_size = len(same_node)
                    if coordinator is None and size > 1:
                        base = os.environ.get("HOROVOD_COORDINATOR", "")
                        if base and ":" in base:
                            host, _, port = base.rpartition(":")
                            coordinator = (
                                f"{host}:{int(port) + 1 + members[0]}"
                            )

            if jax_distributed is None:
                jax_distributed = os.environ.get(
                    "HOROVOD_JAX_DISTRIBUTED", "") not in ("", "0")
            if jax_distributed and comm:
                raise ValueError(
                    "jax_distributed cannot be combined with comm= subsets "
                    "(JAX has one global process group)"
                )

            if not (0 < size and 0 <= rank < size):
                raise ValueError(
                    f"invalid identity: rank={rank}, size={size}"
                )
            if not (0 < local_size <= size and 0 <= local_rank < local_size):
                raise ValueError(
                    f"invalid local identity: local_rank={local_rank}, "
                    f"local_size={local_size} (size={size})"
                )

            # After identity validation, so a bad rank/size raises the
            # clear error above instead of hanging inside JAX's
            # coordination service.
            if jax_distributed and from_jax:
                raise ValueError(
                    "jax_distributed=True needs an explicit identity "
                    "(rank/size kwargs or HOROVOD_RANK/HOROVOD_SIZE env): "
                    "discovering it from JAX already initialized the "
                    "backend, which is too late for "
                    "jax.distributed.initialize"
                )
            if jax_distributed and size > 1:
                jaddr = os.environ.get("HOROVOD_JAX_COORDINATOR")
                if not jaddr:
                    base = coordinator or os.environ.get(
                        "HOROVOD_COORDINATOR", "")
                    if not base or ":" not in base:
                        raise ValueError(
                            "jax_distributed needs a coordinator address "
                            "(HOROVOD_COORDINATOR / coordinator= / "
                            "HOROVOD_JAX_COORDINATOR)"
                        )
                    host, _, port = base.rpartition(":")
                    jaddr = f"{host}:{int(port) + 64}"
                import jax

                # A retried init() after a failure elsewhere finds the JAX
                # runtime already up — that is fine.  Ask the runtime's own
                # API rather than parsing exception text (which is brittle
                # across JAX versions); jax < 0.5 has no public
                # is_initialized, so fall back to the distributed client
                # singleton it tracks internally.
                is_init = getattr(jax.distributed, "is_initialized", None)
                if callable(is_init):
                    already = is_init()
                else:
                    from jax._src import distributed as _jax_dist

                    already = getattr(_jax_dist.global_state, "client",
                                      None) is not None
                if not already:
                    jax.distributed.initialize(
                        coordinator_address=jaddr,
                        num_processes=size,
                        process_id=rank,
                    )
            self._rank = rank
            self._size = size
            self._local_rank = local_rank
            self._local_size = local_size

            self._load_native()
            if self._lib is not None:
                if os.environ.get("HOROVOD_AUTOTUNE", "0") not in ("", "0"):
                    # Warm start for the WIRING-time knobs: the state
                    # file's probed channels/drivers must land in the env
                    # before horovod_init wires the rings (explicit user
                    # env values win inside the helper).
                    from horovod_tpu.autotune.store import (
                        apply_wiring_warm_start,
                    )

                    apply_wiring_warm_start(os.environ)
                addr = coordinator or os.environ.get("HOROVOD_COORDINATOR", "")
                ret = self._lib.horovod_init(
                    self._rank,
                    self._size,
                    self._local_rank,
                    self._local_size,
                    addr.encode(),
                )
                if ret != 0:
                    try:
                        detail = self._lib.horovod_last_error().decode()
                    except Exception:
                        detail = ""
                    raise RuntimeError(
                        f"native horovod_init failed with code {ret}"
                        + (f": {detail}" if detail else "")
                    )
                # Adopt the COMMITTED identity: under elastic membership
                # (HOROVOD_ELASTIC=1) the coordinator may have re-formed
                # the world around the survivors — contiguous re-ranked,
                # smaller (or re-grown) size — so the env-pinned identity
                # is only the join candidacy, not the final word.  Gated
                # on the elastic flag: outside it the engine never
                # reassigns, and the process-wide engine singleton may
                # predate this (test-local) HorovodBasics instance.
                if os.environ.get("HOROVOD_ELASTIC", "") not in ("", "0"):
                    self._rank = int(self._lib.horovod_rank())
                    self._size = int(self._lib.horovod_size())
            self._initialized = True
            self._maybe_start_autotuner()
            self._maybe_start_monitor()
            if not self._atexit_registered:
                # Reference registers shutdown via atexit (common/__init__.py:69).
                atexit.register(self.shutdown)
                self._atexit_registered = True

    def _maybe_start_autotuner(self) -> None:
        """Start the online autotuner thread on the coordinator when
        HOROVOD_AUTOTUNE=1 (default 0: no thread, no TUNE frames — the
        untuned path is behaviorally untouched).  The probe's re-init
        churn sets HOROVOD_AUTOTUNE_SUSPEND so mid-probe worlds are
        never tuned underneath the measurement."""
        if self._lib is None or self._size <= 1 or self._rank != 0:
            return
        if os.environ.get("HOROVOD_AUTOTUNE", "0") in ("", "0"):
            return
        if os.environ.get("HOROVOD_AUTOTUNE_SUSPEND", "") not in ("", "0"):
            return
        from horovod_tpu.autotune.tuner import start_autotuner
        from horovod_tpu.runtime.engine import get_engine

        start_autotuner(get_engine())

    def _maybe_start_monitor(self) -> None:
        """Start the live metrics endpoint on rank 0 when
        HOROVOD_METRICS_PORT is set (default unset: no thread, no
        socket — provably off).  Serves Prometheus text on /metrics and
        JSON on /json from the engine's stats() + fleet table; see
        docs/observability.md."""
        port_raw = os.environ.get("HOROVOD_METRICS_PORT", "")
        if self._lib is None or self._rank != 0 or port_raw in ("", "0"):
            return
        try:
            port = int(port_raw)
        except ValueError:
            import sys

            print(f"horovod_tpu: bad HOROVOD_METRICS_PORT={port_raw!r}; "
                  "metrics endpoint disabled", file=sys.stderr)
            return
        from horovod_tpu.monitor.server import start_metrics_server
        from horovod_tpu.runtime.engine import get_engine

        import sys

        eng = get_engine()
        try:
            bound = start_metrics_server(port, eng.stats, eng.fleet_stats)
        except (OSError, RuntimeError) as exc:
            # Monitoring must degrade, never fail init: a busy port
            # (stale job, two jobs on one box) costs the endpoint, not
            # the training run.
            print(f"horovod_tpu: metrics endpoint disabled: {exc}",
                  file=sys.stderr)
            return
        print(f"horovod_tpu: metrics endpoint on :{bound} "
              "(/metrics /json /fleet)", file=sys.stderr)

    def fleet_stats(self) -> dict:
        """Rank 0's fleet telemetry table (``{}`` on workers, with
        telemetry off, or before the first TELEM frame) — see
        :meth:`horovod_tpu.runtime.engine.NativeEngine.fleet_stats`."""
        if self._lib is None:
            return {}
        from horovod_tpu.runtime.engine import get_engine

        return get_engine().fleet_stats()

    def shutdown(self) -> None:
        # Stop the monitor first: it only reads counters, but its
        # providers must not race the native teardown's state swaps.
        if os.environ.get("HOROVOD_METRICS_PORT", "") not in ("", "0"):
            from horovod_tpu.monitor.server import stop_metrics_server

            stop_metrics_server()
        # Stop the tuner BEFORE taking the lock and the engine down: its
        # thread only reads counters/queues frames, but it must not race
        # the native shutdown with a TUNE proposal.
        if os.environ.get("HOROVOD_AUTOTUNE", "0") not in ("", "0"):
            from horovod_tpu.autotune.tuner import stop_autotuner

            stop_autotuner()
        with self._lock:
            if not self._initialized:
                return
            if self._lib is not None:
                self._lib.horovod_shutdown()
                # A later init() restarts the native core with an empty
                # tensor table; the Python wrapper's auto-name counters
                # must restart with it or unnamed collectives never
                # rendezvous with relaunched peers (elastic recovery).
                from horovod_tpu.runtime.engine import reset_engine_naming

                reset_engine_naming()
            self._initialized = False

    # -- queries -----------------------------------------------------------

    def _check(self) -> None:
        if not self._initialized:
            # Same contract as reference CheckInitialized (operations.cc:1933).
            raise ValueError(
                "Horovod has not been initialized; use hvd.init()."
            )

    def is_initialized(self) -> bool:
        return self._initialized

    def rank(self) -> int:
        self._check()
        return self._rank

    def size(self) -> int:
        self._check()
        return self._size

    def local_rank(self) -> int:
        self._check()
        return self._local_rank

    def local_size(self) -> int:
        self._check()
        return self._local_size

    def epoch(self) -> int:
        """Committed membership epoch — 0 before init or without the
        native core.  Bumped by every successful rendezvous commit, so an
        in-place elastic resize (shrink to survivors, worker rejoin)
        increments it on every live member; control frames from older
        epochs are structurally rejected by the engine."""
        if self._lib is None or not hasattr(self._lib, "horovod_epoch"):
            return 0
        return int(self._lib.horovod_epoch())

    def mpi_threads_supported(self) -> bool:
        """Parity shim: there is no MPI; the coordination service is
        inherently multi-threaded, so report True (reference
        common/__init__.py:147-154)."""
        self._check()
        return True

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _jax_identity() -> tuple[int, int]:
        try:
            import jax

            return jax.process_index(), jax.process_count()
        except Exception:
            return 0, 1

    def _load_native(self) -> None:
        if self._lib is not None:
            return
        path = _find_native_lib()
        if path is None:
            return
        try:
            lib = ctypes.CDLL(path, mode=ctypes.RTLD_GLOBAL)
        except OSError:
            return
        lib.horovod_init.argtypes = [
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_char_p,
        ]
        lib.horovod_init.restype = ctypes.c_int
        lib.horovod_shutdown.argtypes = []
        lib.horovod_shutdown.restype = None
        if hasattr(lib, "horovod_last_error"):
            lib.horovod_last_error.argtypes = []
            lib.horovod_last_error.restype = ctypes.c_char_p
        if hasattr(lib, "horovod_epoch"):
            lib.horovod_epoch.argtypes = []
            lib.horovod_epoch.restype = ctypes.c_int64
        self._lib = lib

    @property
    def native_lib(self):
        """The loaded C++ core (ctypes CDLL) or None."""
        return self._lib


#: Singleton, mirroring the reference's module-level basics object.
basics = HorovodBasics()
