"""Robust lazy build of the native engine.

Reference parity: the reference compiles its C++ core at ``pip install``
time (setup.py:244-465).  Here the install-time build (setup.py) is the
primary path; this module is the fallback that makes a source checkout or
a compiler-less install self-healing: the first ``hvd.init()`` (or an
explicit :func:`ensure_native_lib`) compiles ``libhorovod_core.so`` from
the shipped sources with ``make``.

Build location: next to the sources when that directory is writable
(source checkout), else ``$XDG_CACHE_HOME/horovod_tpu`` (installed
site-packages are often read-only).  A file lock serializes concurrent
builders (the launcher starts N ranks at once).
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import tempfile
from typing import Optional

__all__ = ["ensure_native_lib", "native_lib_path"]

_LIB_NAME = "libhorovod_core.so"
_build_failed = False  # per-process: don't retry a failing make on every init


def _cpp_dir() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "cpp"
    )


def _source_digest() -> str:
    """Hash of the shipped C++ sources — keys the cache so an upgraded
    package never loads a stale engine built from older sources."""
    h = hashlib.sha256()
    cpp = _cpp_dir()
    try:
        names = sorted(
            f for f in os.listdir(cpp)
            if f.endswith((".cc", ".h")) or f == "Makefile"
        )
        for name in names:
            with open(os.path.join(cpp, name), "rb") as f:
                h.update(name.encode())
                h.update(f.read())
    except OSError:
        pass
    return h.hexdigest()[:16]


def _cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return os.path.join(base, "horovod_tpu", _source_digest())


def native_lib_path() -> Optional[str]:
    """Path of an already-built engine library, or None."""
    for candidate in (
        os.path.join(_cpp_dir(), _LIB_NAME),
        os.path.join(_cache_dir(), _LIB_NAME),
    ):
        if os.path.exists(candidate):
            return candidate
    return None


def ensure_native_lib(timeout: float = 300.0) -> Optional[str]:
    """Return the engine library path, building it with ``make`` if needed.

    Returns None when no build is possible (no ``make``/compiler); callers
    fall back to pure-Python single-process mode.
    """
    global _build_failed
    path = native_lib_path()
    if path is not None:
        return path
    if _build_failed or shutil.which("make") is None:
        return None

    cpp = _cpp_dir()
    if os.access(cpp, os.W_OK):
        build_dir, out = cpp, os.path.join(cpp, _LIB_NAME)
    else:
        # Installed read-only: copy sources to the cache and build there.
        cache = _cache_dir()
        os.makedirs(cache, exist_ok=True)
        build_dir = os.path.join(cache, "build")
        if not os.path.isdir(build_dir):
            shutil.copytree(cpp, build_dir)
        out = os.path.join(cache, _LIB_NAME)

    lock_path = os.path.join(
        tempfile.gettempdir(), f"horovod_tpu_build_{os.getuid()}.lock"
    )
    with open(lock_path, "w") as lock:
        try:
            import fcntl

            fcntl.flock(lock, fcntl.LOCK_EX)
        except ImportError:  # non-POSIX: best effort, races rebuild harmlessly
            pass
        # Another rank may have finished the build while we waited.
        path = native_lib_path()
        if path is not None:
            return path
        try:
            subprocess.run(
                ["make", "-C", build_dir],
                check=True,
                capture_output=True,
                timeout=timeout,
            )
        except (OSError, subprocess.CalledProcessError,
                subprocess.TimeoutExpired):
            _build_failed = True
            return None
        built = os.path.join(build_dir, _LIB_NAME)
        if built != out and os.path.exists(built):
            shutil.copy2(built, out)
    return native_lib_path()
