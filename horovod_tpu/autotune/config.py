"""The resolved engine knob table: env -> default -> effective.

One place that mirrors the native engine's env resolution (engine.cc
``Engine::Init``) so ``python -m horovod_tpu.run --print-config`` and the
consolidated table in docs/performance.md can show the value the engine
would actually use — clamps, auto-from-cores defaults and all — without
starting a world.  ``stats()["config"]`` is the live counterpart: it
reports the values currently in force (post-autotune) from the running
engine itself.
"""

from __future__ import annotations

import os
from typing import Callable, List, NamedTuple, Optional

__all__ = ["KNOBS", "resolved_config", "format_table"]


def _cores() -> int:
    return os.cpu_count() or 1


def _clamp(v: int, lo: int, hi: int) -> int:
    return max(lo, min(hi, v))


class Knob(NamedTuple):
    env: str
    default: str                      # human-readable default
    resolve: Callable[[Optional[str]], str]  # raw env value -> effective
    doc: str


def _int_env(raw: Optional[str], dflt: int) -> int:
    if raw is None or raw == "":
        return dflt
    try:
        return int(raw)
    except ValueError:
        return dflt


def _num_channels(raw):
    v = _int_env(raw, 0)
    if v <= 0:
        v = min(4, max(1, _cores()))
    return str(_clamp(v, 1, 16))


def _channel_drivers(raw):
    v = _int_env(raw, 0)
    if v <= 0:
        v = max(1, _cores())
    return str(_clamp(v, 1, 16))


def _chunk_bytes(raw):
    v = max(4096, _int_env(raw, 1 << 20))
    return str(v & ~7)


def _wave_width(raw, environ=os.environ):
    v = _int_env(raw, 0)
    if v <= 0:
        return _num_channels(environ.get("HOROVOD_NUM_CHANNELS"))
    return str(_clamp(v, 1, 16))


def _sparse_topk(raw):
    # Mirrors runtime/sparse.default_topk_ratio exactly: float parse
    # with 0.01 fallback, clamped to [1e-6, 1.0].
    try:
        v = float(raw) if raw else 0.01
    except ValueError:
        v = 0.01
    return str(min(1.0, max(1e-6, v)))


def _moe_experts(raw):
    # Mirrors runtime/moe.moe_experts_default: lenient int parse,
    # default = world size (one expert per rank), clamped up to the
    # world size.  --print-config runs worldless, so the floor shows as
    # the symbolic default.
    try:
        n = int(raw) if raw and raw.strip() else 0
    except ValueError:
        n = 0
    return str(n) if n > 0 else "(world size)"


def _moe_capacity_factor(raw):
    # Mirrors runtime/moe.moe_capacity_factor_default exactly.
    try:
        return str(max(0.0, float(raw)) if raw and raw.strip() else 1.25)
    except ValueError:
        return "1.25"


def _moe_topk(raw):
    # Mirrors runtime/moe.moe_topk_default exactly.
    try:
        return str(max(1, int(raw)) if raw and raw.strip() else 2)
    except ValueError:
        return "2"


#: Every performance/robustness knob the engine reads, in the order the
#: docs table presents them.  Live-tunable knobs (autotune may rewrite
#: them at runtime) are marked in the doc string.
KNOBS: List[Knob] = [
    Knob("HOROVOD_NUM_CHANNELS", "auto: min(4, cores)", _num_channels,
         "socket pairs per ring edge (wiring-time; probed by "
         "autotune.startup_probe)"),
    Knob("HOROVOD_CHANNEL_DRIVERS", "auto: cores", _channel_drivers,
         "poll-loop threads driving the channel fan-out (wiring-time; "
         "probed by autotune.startup_probe)"),
    Knob("HOROVOD_CHUNK_BYTES", "1048576", _chunk_bytes,
         "ring pipeline chunk, 8-aligned (live-tunable)"),
    Knob("HOROVOD_FUSION_THRESHOLD", "67108864",
         lambda raw: str(_int_env(raw, 64 << 20)),
         "max fused allreduce batch bytes (live-tunable)"),
    Knob("HOROVOD_CYCLE_TIME", "5",
         lambda raw: str(max(1, _int_env(raw, 5))),
         "idle-heartbeat upper bound on a negotiation cycle, ms "
         "(live-tunable)"),
    Knob("HOROVOD_WAVE_WIDTH", "auto: num_channels", _wave_width,
         "concurrent responses per execution wave (live-tunable)"),
    Knob("HOROVOD_CACHE_CAPACITY", "1024",
         lambda raw: str(_clamp(max(0, _int_env(raw, 1024)), 0, 1 << 20)),
         "negotiation response-cache slots (0 disables)"),
    Knob("HOROVOD_SOCKET_BUF_BYTES", "0 (kernel default)",
         lambda raw: str(_int_env(raw, 0)),
         "SO_SNDBUF/SO_RCVBUF on ring data sockets"),
    Knob("HOROVOD_SOCKET_TIMEOUT_SEC", "120",
         lambda raw: str(_int_env(raw, 120)),
         "no-progress bound per transport op (0 disables)"),
    Knob("HOROVOD_CONTROL_PATIENCE_SEC", "max(600, size*30)",
         lambda raw: raw if raw else "max(600, size*30)",
         "idle allowance for control frames"),
    Knob("HOROVOD_FAULT_TIMEOUT_SEC", "0 (off)",
         lambda raw: str(_int_env(raw, 0)),
         "hard failure-detection bound (caps the two above)"),
    Knob("HOROVOD_LINK_RETRIES", "3",
         lambda raw: str(max(0, min(1000, _int_env(raw, 3)))),
         "link self-healing: reconnect attempts per suspect data-channel "
         "socket before escalating to the abort path (0 = heal off, "
         "fail-fast exactly as before; committed at rendezvous; see "
         "docs/elastic.md 'Link self-healing')"),
    Knob("HOROVOD_LINK_HEAL_TIMEOUT_MS", "10000",
         lambda raw: str(max(1, _int_env(raw, 10000))),
         "per-suspect heal deadline; clamped to 3/4 of the socket "
         "timeout so healing always finishes inside every other rank's "
         "no-progress patience (committed at rendezvous)"),
    Knob("HOROVOD_STALL_WARNING_SEC", "60",
         lambda raw: str(_int_env(raw, 60)),
         "stalled-tensor warning cadence"),
    Knob("HOROVOD_WIRE_DTYPE", "fp32",
         lambda raw: raw if raw in ("fp16", "bf16", "int8", "fp8")
         else "fp32",
         "wire format for fp32 allreduce payloads: fp32 is byte-exact; "
         "fp16/bf16 halve wire bytes (RNE), int8/fp8 quarter them with "
         "per-chunk scales (live-tunable; per-tensor override via "
         "wire_dtype=; see docs/performance.md 'Wire compression')"),
    Knob("HOROVOD_PRIORITY_BANDS", "0 (off)",
         lambda raw: str(_clamp(max(0, _int_env(raw, 0)), 0, 1 << 20)),
         "priority band WIDTH (band = priority / width): the coordinator "
         "orders each cycle's responses by (priority, name), fusion only "
         "merges within a band, and waves dispatch in band order — so "
         "front-layer gradients fly first (0 = off: legacy arrival "
         "ordering bit-for-bit; committed at rendezvous, live-tunable; "
         "docs/performance.md 'Priority scheduling & overlap')"),
    Knob("HOROVOD_FUSION_LADDER", "(unset: global threshold)",
         lambda raw: raw or "(unset: global threshold)",
         "per-band fusion thresholds 't0,t1,...' (band b fuses up to "
         "ladder[b] bytes; missing/zero entries fall back to "
         "HOROVOD_FUSION_THRESHOLD; autotuner-learnable via the "
         "fusion_ladder_<b> dims)"),
    Knob("HOROVOD_WIRE_POLICY", "0",
         lambda raw: str(1 if (raw or "") not in ("", "0") else 0),
         "statistics-driven per-tensor wire dtypes on the gradient "
         "paths: int8 for large embedding-shaped grads, fp32 for "
         "norm/bias leaves, stamped as ADVISORY overrides so per-rank "
         "stats can never split negotiation (runtime/wire_policy.py)"),
    Knob("HOROVOD_WIRE_POLICY_MIN_ELEMS", "65536",
         lambda raw: str(max(1, _int_env(raw, 65536))),
         "wire policy: leaves below this many elements (or 0/1-D) stay "
         "fp32"),
    Knob("HOROVOD_WIRE_POLICY_RATIO", "64.0",
         lambda raw: raw or "64.0",
         "wire policy: max rolling abs-max/rms dynamic range for the "
         "int8 wire (spiky leaves stay fp32)"),
    Knob("HOROVOD_WIRE_POLICY_WARMUP", "3",
         lambda raw: str(max(0, _int_env(raw, 3))),
         "wire policy: observed steps per leaf before compressing"),
    Knob("HOROVOD_SPARSE_TOPK", "0.01", _sparse_topk,
         "default top-k ratio for Compression.topk sparse allreduce "
         "(indices+values ride the allgather path; error-feedback "
         "residuals per gradient leaf, cleared per membership epoch)"),
    Knob("HOROVOD_TOPK_SEED", "0",
         lambda raw: str(_int_env(raw, 0)),
         "seeded tie-break for deterministic top-k selection"),
    Knob("HOROVOD_ALGO_THRESHOLD", "32768",
         lambda raw: str(max(0, _int_env(raw, 32 << 10))),
         "size-based algorithm crossover: allreduces at or under this "
         "many bytes take the latency star path over shm (0 disables; "
         "live-tunable)"),
    Knob("HOROVOD_SHM_DISABLE", "0",
         lambda raw: str(_int_env(raw, 0)),
         "1 = pure-TCP data plane (bit-identical; escape hatch for "
         "broken /dev/shm)"),
    Knob("HOROVOD_SHM_RING_BYTES", "2097152",
         lambda raw: str(max(1 << 16, _int_env(raw, 2 << 20))),
         "per-direction shm ring-buffer capacity"),
    Knob("HOROVOD_HOST_KEY", "(hostname#boot-id)",
         lambda raw: raw or "(hostname#boot-id)",
         "co-location grouping override for rendezvous (two-level "
         "hierarchy + shm edges form per host key)"),
    Knob("HOROVOD_HIERARCHICAL_COORDINATOR", "1",
         lambda raw: str(1 if _int_env(raw, 1) else 0),
         "per-host sub-coordinators aggregate readiness so rank 0 "
         "handles O(hosts) control frames per cycle (active on >1-group "
         "topologies; 0 restores the flat rank-0 star bit-for-bit; "
         "docs/scaling.md)"),
    Knob("HOROVOD_RENDEZVOUS_TIMEOUT_SEC", "120",
         lambda raw: str(max(5, _int_env(raw, 120))),
         "first-rendezvous / join-exchange deadline"),
    Knob("HOROVOD_BACKUP_WORKERS", "0",
         lambda raw: raw if (raw or "").strip() == "auto"
         else str(max(0, _int_env(raw, 0))),
         "backup-worker collectives: SUM allreduces commit at size-k "
         "voter readiness; skipped ranks get the clean StepSkipped "
         "status and averaging divides by participants (0 = fully "
         "synchronous; 'auto' arms k=1 from the step-time p99/p50 "
         "window ratio; docs/elastic.md 'Straggler tolerance')"),
    Knob("HOROVOD_BACKUP_AUTO_RATIO", "3.0",
         lambda raw: raw or "3.0",
         "steptime-rule arming threshold on the step_time_ns_p99/p50 "
         "window ratio (>=64 samples; reported in stats()['config'] as "
         "backup_auto/backup_armed)"),
    Knob("HOROVOD_BACKUP_AUTO_RULE", "quorum",
         lambda raw: raw if raw in ("quorum", "steptime") else "quorum",
         "backup=auto arming instrument: 'quorum' arms k=1 while the "
         "per-entry quorum-lag p50 exceeds the grace window (sees a "
         "straggling rank 0 too); 'steptime' keeps the legacy rank-0 "
         "completion-latency rule (docs/observability.md)"),
    Knob("HOROVOD_BACKUP_GRACE_MS", "50",
         lambda raw: str(max(0, _int_env(raw, 50))),
         "minimum pending age before a partial commit may skip a rank"),
    Knob("HOROVOD_SHARDED", "0",
         lambda raw: str(1 if (raw or "").strip() not in
                         ("", "0", "false", "False") else 0),
         "DistributedOptimizer(sharded=) default: ZeRO-1 sharded "
         "optimizer — reducescatter(grads), shard-local update, "
         "allgather(params); ~1/N optimizer memory per rank "
         "(docs/zero.md)"),
    Knob("HOROVOD_FSDP", "0",
         lambda raw: str(1 if (raw or "").strip() not in
                         ("", "0", "false", "False") else 0),
         "DistributedOptimizer(fsdp=) default: ZeRO-3/FSDP full "
         "parameter sharding — per-unit JIT allgather forward, async "
         "reducescatter backward, free-after-use; peak param residency "
         "~1/N + one gathered unit (docs/zero.md)"),
    Knob("HOROVOD_FSDP_PREFETCH", "1",
         lambda raw: str(max(0, _int_env(raw, 1))),
         "FSDP prefetch depth in units: each gather enqueues the next "
         "k allgathers at priority band 0 so the banded scheduler "
         "overlaps them with compute (0 disables — every gather "
         "blocks)"),
    Knob("HOROVOD_MOE_EXPERTS", "(world size)", _moe_experts,
         "global expert count for the MoE plane (runtime/moe.py): "
         "defaults to one expert per rank and is clamped up to the "
         "world size so every rank owns at least one expert; must "
         "divide evenly across ranks (docs/moe.md)"),
    Knob("HOROVOD_MOE_CAPACITY_FACTOR", "1.25", _moe_capacity_factor,
         "slack multiplier on the perfect-balance per-expert token "
         "budget: capacity = ceil(cf * topk * tokens / experts); "
         "overflow tokens drop deterministically in global token order "
         "(moe_tokens_dropped counter)"),
    Knob("HOROVOD_MOE_TOPK", "2", _moe_topk,
         "experts per token for top-k gating (stable tie-break toward "
         "the lower expert id; full-softmax gate weights)"),
    Knob("HOROVOD_LOCAL_SGD_STEPS", "1",
         lambda raw: str(max(1, _int_env(raw, 1))),
         "local-SGD periodic sync: H local steps per outer model-delta "
         "allreduce (1 = fully synchronous, byte-identical; "
         "DistributedOptimizer(local_sgd_steps=))"),
    Knob("HOROVOD_TELEMETRY_CYCLES", "50",
         lambda raw: str(max(0, _int_env(raw, 50))),
         "fleet telemetry cadence: every N negotiation cycles each rank "
         "piggybacks counter deltas on its control frame; rank 0 keeps "
         "the fleet table (hvd.fleet_stats(); 0 disables — frames are "
         "then byte-identical to the pre-telemetry wire)"),
    Knob("HOROVOD_METRICS_PORT", "(unset: off)",
         lambda raw: raw or "(unset: off)",
         "rank 0 serves Prometheus text on /metrics and JSON on /json "
         "over HTTP at this port; query live with `python -m "
         "horovod_tpu.run --status host:port` (docs/observability.md)"),
    Knob("HOROVOD_FLIGHT_RECORDER_EVENTS", "256",
         lambda raw: str(max(0, min(1 << 16, _int_env(raw, 256)))),
         "in-memory ring of the last N control-plane events per rank "
         "(0 disables recording)"),
    Knob("HOROVOD_FLIGHT_RECORDER_DIR", "(unset: no dumps)",
         lambda raw: raw or "(unset: no dumps)",
         "flight-recorder dump sink: flightrec.rank<r>.json written on "
         "abort, stall-warning escalation and fatal signals; post-mortem "
         "via `python -m horovod_tpu.monitor.postmortem <dir>`"),
    Knob("HOROVOD_TIMELINE_ALL_RANKS", "0",
         lambda raw: str(_int_env(raw, 0)),
         "1 = every rank writes HOROVOD_TIMELINE + '.rank<r>'; merge "
         "into one clock-aligned Chrome trace with `python -m "
         "horovod_tpu.timeline merge` (docs/timeline.md)"),
    Knob("HOROVOD_TIMELINE_MAX_MB", "0 (unbounded)",
         lambda raw: str(max(0, _int_env(raw, 0))),
         "timeline rotation: past this size the file is terminated as "
         "valid JSON, kept as '<path>.old', and the newest events "
         "continue at the configured path"),
    Knob("HOROVOD_ELASTIC", "0", lambda raw: str(_int_env(raw, 0)),
         "in-place elastic membership"),
    Knob("HOROVOD_CHECKPOINT_DIR", "(unset: off)",
         lambda raw: raw or "(unset: off)",
         "crash-consistent sharded checkpoint directory: run_elastic "
         "trainers save async double-buffered shards there and resume "
         "from the newest complete manifest — across world resizes "
         "(docs/checkpointing.md; run.py --checkpoint-dir sets it)"),
    Knob("HOROVOD_CHECKPOINT_INTERVAL_STEPS", "50",
         lambda raw: str(max(1, _int_env(raw, 50))),
         "steps between interval-gated checkpoint saves "
         "(CheckpointWriter.maybe_save)"),
    Knob("HOROVOD_CHECKPOINT_KEEP", "2",
         lambda raw: str(max(1, _int_env(raw, 2))),
         "committed checkpoints retained; older manifests are deleted "
         "BEFORE their shard dirs so 'manifest => complete set' "
         "survives a crash mid-cleanup"),
    Knob("HOROVOD_AUTOTUNE", "0", lambda raw: str(_int_env(raw, 0)),
         "online knob search over the live data plane (docs/autotune.md)"),
    Knob("HOROVOD_AUTOTUNE_SEED", "0",
         lambda raw: str(_int_env(raw, 0)),
         "deterministic trial-schedule seed"),
    Knob("HOROVOD_AUTOTUNE_WINDOW_BYTES", "67108864",
         lambda raw: str(_int_env(raw, 64 << 20)),
         "allreduce bytes per scoring window"),
    Knob("HOROVOD_AUTOTUNE_MAX_TRIALS", "32",
         lambda raw: str(_int_env(raw, 32)),
         "hard cap on trials (search commits best-so-far at the cap)"),
    Knob("HOROVOD_AUTOTUNE_TRIAL_TIMEOUT_SEC", "30",
         lambda raw: str(_int_env(raw, 30)),
         "a trial whose window never fills is discarded after this"),
    Knob("HOROVOD_AUTOTUNE_STATE_FILE", "(unset)",
         lambda raw: raw or "(unset)",
         "warm-start file: a relaunch skips straight to the committed "
         "config"),
]


def resolved_config(environ=os.environ) -> List[dict]:
    """Rows of {env, set, default, effective, doc} for every knob —
    the engine table followed by the serve-plane knobs
    (horovod_tpu/serve/config.py), so ``--print-config`` is the one
    consolidated view."""
    rows = []
    for knob in KNOBS:
        raw = environ.get(knob.env)
        # The wave default depends on ANOTHER knob's resolution
        # (num_channels), so it alone needs the full environ.
        if knob.resolve is _wave_width:
            effective = _wave_width(raw, environ)
        else:
            effective = knob.resolve(raw)
        rows.append({
            "env": knob.env,
            "set": raw if raw is not None else "",
            "default": knob.default,
            "effective": effective,
            "doc": knob.doc,
        })
    from horovod_tpu.serve.config import resolved_serve_config

    rows.extend(resolved_serve_config(environ))
    return rows


def format_table(environ=os.environ) -> str:
    """The --print-config rendering: one aligned row per knob."""
    rows = resolved_config(environ)
    w_env = max(len(r["env"]) for r in rows)
    w_set = max(len("env"), max(len(r["set"]) for r in rows))
    w_dflt = max(len("default"), max(len(r["default"]) for r in rows))
    w_eff = max(len("effective"), max(len(r["effective"]) for r in rows))
    lines = [f"{'knob':<{w_env}}  {'env':<{w_set}}  "
             f"{'default':<{w_dflt}}  {'effective':<{w_eff}}  description"]
    lines.append("-" * len(lines[0]))
    for r in rows:
        lines.append(
            f"{r['env']:<{w_env}}  {r['set']:<{w_set}}  "
            f"{r['default']:<{w_dflt}}  {r['effective']:<{w_eff}}  "
            f"{r['doc']}")
    return "\n".join(lines)
