"""Seeded coordinate-descent-with-doubling over log-scaled knob ranges.

The schedule is **score-independent**: which knob is swept when, and
which ladder values it tries, are fully determined by ``(space, seed)``
— scores only pick the winner once a knob's ladder completes, after
which later knobs are swept with the winner held in place (the
coordinate-descent part).  That makes the trial sequence reproducible
for a fixed ``HOROVOD_AUTOTUNE_SEED`` (tests/test_autotune.py asserts
it), while the noisy live measurements can only affect which values get
*committed*, never which get *tried*.

Ladders are doublings across a log-scaled range (the Horovod
``ParameterManager`` insight, arXiv:1802.05799 §5: these knobs act
multiplicatively, so linear grids waste trials at the top of the range).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["ladder", "CoordinateSearch"]


def ladder(lo: int, hi: int) -> List[int]:
    """Doubling ladder [lo, 2lo, 4lo, ..] clipped to hi (hi included)."""
    out = []
    v = int(lo)
    while v < int(hi):
        out.append(v)
        v *= 2
    out.append(int(hi))
    return out


class CoordinateSearch:
    """One pass of coordinate descent over ``space``.

    ``space`` maps knob name -> ladder of candidate values; ``base`` is
    the starting config (knobs missing from ``space`` are never touched).
    ``propose()`` yields the next trial config (base with exactly one
    knob swept) or ``None`` once the schedule is exhausted or
    ``max_trials`` is hit; ``observe(score)`` reports the last trial's
    score (``None`` = trial discarded — e.g. the window timed out — it
    can never win its ladder).
    """

    def __init__(self, space: Dict[str, Sequence[int]], seed: int = 0,
                 base: Optional[Dict[str, int]] = None,
                 max_trials: Optional[int] = None):
        self.space = {k: list(v) for k, v in space.items() if v}
        self.seed = int(seed)
        self.base: Dict[str, int] = dict(base or {})
        for k, vals in self.space.items():
            self.base.setdefault(k, vals[0])
        self.max_trials = max_trials
        # Knob order is the seeded part; ladders run in ascending order.
        self._order = sorted(self.space)
        random.Random(self.seed).shuffle(self._order)
        self._schedule: List[Tuple[str, int]] = [
            (k, v) for k in self._order for v in self.space[k]
        ]
        if max_trials is not None:
            self._schedule = self._schedule[:max(0, int(max_trials))]
        self._idx = 0            # next schedule entry to propose
        self._awaiting = False   # propose() called, observe() pending
        self._knob_scores: Dict[str, List[Tuple[int, Optional[float]]]] = {
            k: [] for k in self.space
        }
        self.trials = 0
        # Score MEASURED AT the current best point: the winning trial of
        # the most recently completed ladder ran with every earlier
        # winner already fixed in base, so its config IS `best`.  A max
        # over all trials would generally belong to a DIFFERENT config
        # (an earlier ladder's winner before later knobs moved) — a
        # throughput the committed config never achieved.
        self.best_score: Optional[float] = None

    # -- schedule introspection (tests assert determinism on this) --

    def planned_schedule(self) -> List[Tuple[str, int]]:
        """The full (knob, value) trial sequence — fixed by (space, seed),
        independent of any observed score."""
        return list(self._schedule)

    # -- driving --

    @property
    def converged(self) -> bool:
        return self._idx >= len(self._schedule) and not self._awaiting

    @property
    def best(self) -> Dict[str, int]:
        """The current coordinate-descent point: every completed knob at
        its ladder winner, the rest at base."""
        return dict(self.base)

    def propose(self) -> Optional[Dict[str, int]]:
        if self._awaiting:
            raise RuntimeError("observe() the previous trial first")
        if self._idx >= len(self._schedule):
            return None
        knob, value = self._schedule[self._idx]
        self._awaiting = True
        cfg = dict(self.base)
        cfg[knob] = value
        return cfg

    def observe(self, score: Optional[float]) -> None:
        if not self._awaiting:
            raise RuntimeError("no trial pending")
        knob, value = self._schedule[self._idx]
        self._awaiting = False
        self._idx += 1
        self.trials += 1
        self._knob_scores[knob].append((value, score))
        # Ladder complete for this knob (next entry sweeps another knob,
        # or the schedule ends): fix the winner into the base so later
        # knobs are swept around it.  All-discarded ladders keep base
        # (and leave best_score alone — nothing was measured there).
        done = (self._idx >= len(self._schedule)
                or self._schedule[self._idx][0] != knob)
        if done:
            scored = [(v, s) for v, s in self._knob_scores[knob]
                      if s is not None]
            if scored:
                winner, winner_score = max(scored, key=lambda vs: vs[1])
                self.base[knob] = winner
                self.best_score = winner_score
