"""Online autotuner: coordinator-driven knob search over the live data
plane (``HOROVOD_AUTOTUNE=1``; see docs/autotune.md).

The engine's performance knobs are host- and workload-dependent — the
right ``CHUNK_BYTES``/``CYCLE_TIME``/wave width on a 2-core CI box and
on a multi-NIC production host differ by integer factors.  Horovod
itself shipped this subsystem one release after the version this repo
reproduces (the ``ParameterManager`` autotuner, Sergeev & Del Balso,
arXiv:1802.05799); here the search rides the engine's own cycle
counters and epoch-stamped control plane, so tuning is observation plus
between-cycle knob flips — numerics-neutral by the data plane's
bit-exactness guarantee.

Public surface:

* :class:`Autotuner` / :func:`get_tuner` — the rank-0 search thread
  (started automatically by ``hvd.init()`` under ``HOROVOD_AUTOTUNE=1``);
* :func:`startup_probe` — collective micro-probe for the two
  wiring-time knobs (``NUM_CHANNELS``/``CHANNEL_DRIVERS``);
* :class:`CoordinateSearch` — the deterministic seeded schedule;
* :func:`resolved_config` / :func:`format_table` — the env -> default ->
  effective knob table behind ``python -m horovod_tpu.run
  --print-config``;
* :func:`load_state` / :func:`save_state` — the
  ``HOROVOD_AUTOTUNE_STATE_FILE`` warm-start format.
"""

from horovod_tpu.autotune.config import (  # noqa: F401
    KNOBS,
    format_table,
    resolved_config,
)
from horovod_tpu.autotune.search import CoordinateSearch, ladder  # noqa: F401
from horovod_tpu.autotune.store import (  # noqa: F401
    apply_wiring_warm_start,
    load_state,
    save_state,
)
from horovod_tpu.autotune.tuner import (  # noqa: F401
    Autotuner,
    default_space,
    get_tuner,
    start_autotuner,
    startup_probe,
    stop_autotuner,
)

__all__ = [
    "Autotuner", "CoordinateSearch", "KNOBS", "apply_wiring_warm_start",
    "default_space", "format_table", "get_tuner", "ladder", "load_state",
    "resolved_config", "save_state", "start_autotuner", "startup_probe",
    "stop_autotuner",
]
