"""Autotune state persistence (``HOROVOD_AUTOTUNE_STATE_FILE``).

One small JSON document holding the committed config and the probe's
wiring choices, so a relaunch warm-starts: the live knobs are re-applied
via one TUNE frame instead of re-running the search, and the
channels/drivers choice is injected into the env *before* the engine
wires its rings (those two knobs cannot change without re-wiring).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Optional

__all__ = ["load_state", "save_state", "apply_wiring_warm_start"]

_VERSION = 1

#: Live-tunable knob names a committed config may carry.  For
#: ``algo_threshold`` 0 is a REAL value (small-tensor star path off),
#: for ``wire_dtype`` 0 is fp32 (the uncompressed default), and for
#: ``priority_bands`` 0 is bands-off — so the sanitizer below accepts
#: >= 0 for them while the others need > 0.  ``fusion_ladder_<b>``
#: (the per-band bucket sizes) round-trip by prefix.
LIVE_KNOBS = ("chunk_bytes", "fusion_threshold", "cycle_time_ms",
              "wave_width", "algo_threshold", "wire_dtype",
              "priority_bands")
_ZERO_OK_KNOBS = ("algo_threshold", "wire_dtype", "priority_bands")
_LADDER_PREFIX = "fusion_ladder_"


def _knob_ok(k: str) -> bool:
    if k in LIVE_KNOBS:
        return True
    if k.startswith(_LADDER_PREFIX):
        suffix = k[len(_LADDER_PREFIX):]
        return suffix.isdigit() and int(suffix) < 8
    return False
#: Wiring-time knobs the startup micro-probe may pin.
WIRING_KNOBS = {"num_channels": "HOROVOD_NUM_CHANNELS",
                "channel_drivers": "HOROVOD_CHANNEL_DRIVERS"}


def load_state(path: str) -> Optional[dict]:
    """Parse a state file; None when missing, corrupt, or from another
    format version (a bad file must degrade to a cold search, never
    crash init)."""
    if not path:
        return None
    try:
        with open(path, "r") as f:
            state = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(state, dict) or state.get("version") != _VERSION:
        return None
    committed = state.get("committed")
    if not isinstance(committed, dict):
        return None
    clean = {k: int(v) for k, v in committed.items()
             if _knob_ok(k) and isinstance(v, (int, float)) and
             (v > 0 or (v == 0 and k in _ZERO_OK_KNOBS))}
    if not clean:
        return None
    state["committed"] = clean
    # Sanitize wiring with the same discipline: a hand-edited entry like
    # "two" must degrade the wiring warm start, not crash init.
    wiring = state.get("wiring")
    state["wiring"] = {
        k: int(v) for k, v in wiring.items()
        if k in WIRING_KNOBS and isinstance(v, (int, float)) and v > 0
    } if isinstance(wiring, dict) else {}
    return state


def save_state(path: str, committed: dict, score: Optional[float],
               seed: int, wiring: Optional[dict] = None) -> None:
    """Atomic write (tmp + rename) so a relaunch racing a save never
    reads a torn file."""
    if not path:
        return
    state = {
        "version": _VERSION,
        "committed": {k: int(v) for k, v in committed.items()
                      if _knob_ok(k)},
        "score": score,
        "seed": int(seed),
    }
    if wiring:
        state["wiring"] = {k: int(v) for k, v in wiring.items()
                          if k in WIRING_KNOBS}
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".autotune.", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(state, f, indent=1)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def apply_wiring_warm_start(environ=os.environ) -> Optional[dict]:
    """Pre-init: inject the state file's probed channels/drivers into the
    env so the engine wires the committed fan-out straight away.  An
    explicit user env value always wins over the state file."""
    state = load_state(environ.get("HOROVOD_AUTOTUNE_STATE_FILE", ""))
    if state is None:
        return None
    wiring = state.get("wiring") or {}
    for knob, env_name in WIRING_KNOBS.items():
        value = wiring.get(knob)
        if value and not environ.get(env_name):
            environ[env_name] = str(int(value))
    return state
