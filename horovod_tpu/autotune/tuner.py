"""The online autotuner: coordinator-driven knob search over the live
data plane.

One daemon thread on the coordinator (rank 0), started by
``basics.init()`` under ``HOROVOD_AUTOTUNE=1``:

* it proposes a trial config through the native ``horovod_autotune_set``
  C API — the engine broadcasts it in the next cycle's **epoch-stamped
  TUNE frame**, and every rank applies it atomically between negotiation
  cycles (a TUNE from a dead incarnation is dropped by the engine's
  structural stale-epoch rejection, like any other control frame);
* it scores each trial from ``stats_delta`` counter windows — bus
  bandwidth over a **fixed-bytes** window of allreduce traffic, so fast
  configs are not penalized with shorter measurements;
* the trial schedule is a seeded coordinate descent
  (:mod:`horovod_tpu.autotune.search`) — deterministic for a fixed
  ``HOROVOD_AUTOTUNE_SEED``;
* on convergence it commits the best config (one final TUNE with the
  commit flag), persists it to ``HOROVOD_AUTOTUNE_STATE_FILE``, and
  keeps watching: a **sustained** regression (several consecutive
  completed windows far below the committed score) restarts the search.

All of it is observation + between-cycle knob flips: the tuned knobs are
numerics-neutral by the PR 4 bit-exactness guarantee, so a trial can be
slow but never wrong.

``startup_probe`` handles the two knobs a TUNE frame cannot reach —
``HOROVOD_NUM_CHANNELS`` / ``HOROVOD_CHANNEL_DRIVERS`` require
(re)wiring — with a short collective micro-probe reusing the bench sweep
machinery (shutdown + re-init per candidate, rank 0's verdict broadcast
through the engine itself).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from horovod_tpu.autotune.search import CoordinateSearch, ladder
from horovod_tpu.autotune.store import load_state, save_state

__all__ = ["Autotuner", "start_autotuner", "stop_autotuner", "get_tuner",
           "startup_probe", "default_space"]


def _env_int(name: str, dflt: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else dflt
    except ValueError:
        return dflt


def _env_float(name: str, dflt: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else dflt
    except ValueError:
        return dflt


def default_space(num_channels: int,
                  priority_bands: int = 0) -> Dict[str, List[int]]:
    """Log-scaled ladders for the live-tunable knobs.  The wave ladder is
    bounded by the committed channel fan-out (waves cannot exceed it).
    ``HOROVOD_AUTOTUNE_KNOBS`` (comma list) restricts which knobs are
    swept — tests and the CI gate use it to keep schedules short.

    The WIRE DTYPE knob (fp32=0, fp16=1, int8=3 — WireDtype codes) only
    joins the sweep under ``HOROVOD_AUTOTUNE_WIRE=1``: unlike every
    other knob it changes NUMERICS (compressed wires are value-lossy by
    design), so the tuner flipping it silently under a training job
    would violate the bit-exactness default.  When enabled, trials are
    scored on the same busbw counters as everything else — and since
    ``allreduce_bytes`` counts LOGICAL (pre-compression) payload, the
    score is automatically the EFFECTIVE bus bandwidth: logical bytes
    over wall time, exactly what compression is supposed to improve."""
    space: Dict[str, List[int]] = {
        "chunk_bytes": ladder(64 << 10, 4 << 20),
        "fusion_threshold": ladder(8 << 20, 128 << 20),
        "cycle_time_ms": [1, 2, 4, 8],
        "wave_width": ladder(1, max(1, num_channels)),
        # Size-based algorithm crossover: 0 (star path off) plus a log
        # ladder around the default 32 KB — the latency/bandwidth
        # crossover is host-dependent, which is exactly why it's a knob.
        "algo_threshold": [0] + ladder(8 << 10, 256 << 10),
    }
    # Per-band fusion-threshold LADDER (priority scheduling): with
    # HOROVOD_PRIORITY_BANDS committed on, each band's bucket size is
    # its own coordinate — urgent bands typically want SMALL buckets
    # (dispatch sooner), bulk bands big ones (amortize) — so the model's
    # bucket sizes are LEARNED instead of one-size-fits-all.
    # HOROVOD_AUTOTUNE_LADDER_BANDS caps how many leading bands get a
    # dimension (default 2; bands past the ladder share the global
    # fusion threshold).
    if priority_bands > 0:
        nb = _env_int("HOROVOD_AUTOTUNE_LADDER_BANDS", 2)
        for b in range(max(0, min(8, nb))):
            space[f"fusion_ladder_{b}"] = ladder(1 << 20, 64 << 20)
    only = os.environ.get("HOROVOD_AUTOTUNE_KNOBS", "")
    keep = {k.strip() for k in only.split(",") if k.strip()}
    if os.environ.get("HOROVOD_AUTOTUNE_WIRE", "") not in ("", "0") or \
            "wire_dtype" in keep:
        space["wire_dtype"] = [0, 1, 3]
    if keep:
        space = {k: v for k, v in space.items() if k in keep}
    return space


#: Committed config of the last converged search in this process: an
#: in-place elastic resize restarts the tuner (shutdown + re-init), and
#: the new incarnation re-applies this under the new epoch instead of
#: re-searching — the state file is the cross-process equivalent.
_LAST_COMMITTED: Optional[Dict[str, int]] = None
_LAST_SCORE: Optional[float] = None


class Autotuner(threading.Thread):
    """See the module docstring.  Public observability (read from the
    main thread, e.g. by tests and ``bench_engine.py``):

    * ``trace`` — list of ``{"config", "score"}`` per finished trial;
    * ``committed`` — the committed config dict (None mid-search);
    * ``converged`` — True once committed;
    * ``epoch`` — the membership epoch the tuner is operating under.
    """

    def __init__(self, engine):
        super().__init__(name="hvd-autotune", daemon=True)
        self._eng = engine
        self._lib = engine._lib
        self._stop_evt = threading.Event()
        self.seed = _env_int("HOROVOD_AUTOTUNE_SEED", 0)
        self.window_bytes = _env_int("HOROVOD_AUTOTUNE_WINDOW_BYTES",
                                     64 << 20)
        self.max_trials = _env_int("HOROVOD_AUTOTUNE_MAX_TRIALS", 32)
        self.trial_timeout = _env_int("HOROVOD_AUTOTUNE_TRIAL_TIMEOUT_SEC",
                                      30)
        self.reprobe_ratio = _env_float("HOROVOD_AUTOTUNE_REPROBE_RATIO",
                                        0.5)
        self.reprobe_windows = _env_int("HOROVOD_AUTOTUNE_REPROBE_WINDOWS",
                                        3)
        self.state_file = os.environ.get("HOROVOD_AUTOTUNE_STATE_FILE", "")
        self.trace: List[dict] = []
        self.committed: Optional[Dict[str, int]] = None
        self.committed_score: Optional[float] = None
        self.epoch: int = 0
        self._converged = threading.Event()
        self.planned: List[tuple] = []

    # -- public surface ----------------------------------------------------

    @property
    def converged(self) -> bool:
        return self._converged.is_set()

    def wait_converged(self, timeout: Optional[float] = None) -> bool:
        return self._converged.wait(timeout)

    def stop(self) -> None:
        self._stop_evt.set()

    # -- engine liveness / plumbing ---------------------------------------

    def _alive(self) -> bool:
        if self._stop_evt.is_set():
            return False
        try:
            if not self._lib.horovod_is_initialized():
                return False
            return self._eng.abort_reason() == ""
        except Exception:
            return False

    def _sleep(self, sec: float) -> None:
        self._stop_evt.wait(sec)

    def _apply(self, cfg: Dict[str, int], commit: bool) -> bool:
        """Queue a TUNE and wait until it has APPLIED (tune_trials moved)
        so the scoring window never starts under the previous config.

        The wait has no timer of its own: TUNE application is not
        traffic-dependent (QueueTune wakes the cycle loop, an idle
        heartbeat carries the frame), so the only things that can delay
        it are the engine's own stalls — and those end in the engine's
        failure detectors firing (`_alive` goes false) or an epoch move.
        A private deadline here would misread a legitimately slow cycle
        (a big collective may hold the loop up to the socket timeout) as
        failure and restart the whole search, unbounding the trial count
        and breaking the deterministic-schedule contract."""
        before = self._lib.horovod_tune_trials()
        epoch0 = self._eng.epoch()
        # Per-band fusion ladder rides as a positional array (band b's
        # threshold; 0 = leave that band unchanged).
        ladder_keys = sorted(
            (k for k in cfg if k.startswith("fusion_ladder_")),
            key=lambda k: int(k.rsplit("_", 1)[1]))
        fusion_ladder = None
        if ladder_keys:
            nb = max(int(k.rsplit("_", 1)[1]) for k in ladder_keys) + 1
            fusion_ladder = [0] * nb
            for k in ladder_keys:
                fusion_ladder[int(k.rsplit("_", 1)[1])] = int(cfg[k])
        ok = self._eng.autotune_set(
            chunk_bytes=cfg.get("chunk_bytes", 0),
            fusion_threshold=cfg.get("fusion_threshold", 0),
            cycle_time_ms=cfg.get("cycle_time_ms", 0),
            wave_width=cfg.get("wave_width", 0),
            algo_threshold=cfg.get("algo_threshold", -1),
            wire_dtype=cfg.get("wire_dtype", -1),
            priority_bands=cfg.get("priority_bands", -1),
            fusion_ladder=fusion_ladder,
            commit=commit)
        if not ok:
            return False
        while self._alive() and self._eng.epoch() == epoch0:
            if self._lib.horovod_tune_trials() > before:
                return True
            self._sleep(0.002)
        return False

    def _score_window(self) -> Optional[float]:
        """Bus bandwidth (bytes/s) over the next fixed-bytes window of
        allreduce traffic; None when the window never filled (idle world,
        wedged trial, epoch change) — the trial is discarded, the engine
        keeps cycling, nothing wedges."""
        base = self._eng.stats()
        epoch0 = self._eng.epoch()
        deadline = time.monotonic() + self.trial_timeout
        while self._alive() and time.monotonic() < deadline:
            if self._eng.epoch() != epoch0:
                return None  # resized mid-window: measurement is garbage
            delta = self._eng.stats_delta(base)
            if delta["allreduce_bytes"] >= self.window_bytes:
                bw = delta["allreduce_bus_bw_bytes_per_sec"]
                return bw if bw > 0 else None
            self._sleep(0.01)
        return None

    # -- the search --------------------------------------------------------

    def run(self) -> None:  # noqa: C901 — one explicit state machine
        try:
            self.epoch = self._eng.epoch()
            warm = self._warm_config()
            if warm is not None:
                if self._apply(warm, commit=True):
                    self.committed = dict(warm)
                    self.committed_score = _LAST_SCORE
                    self._converged.set()
                    self._monitor()
                return
            while self._alive():
                if self._search_once():
                    self._monitor()
                    return
        except Exception:
            # A tuner bug must never take the training process down; the
            # engine simply keeps running its current config.
            import traceback
            traceback.print_exc()

    def _warm_config(self) -> Optional[Dict[str, int]]:
        if os.environ.get("HOROVOD_AUTOTUNE_FORCE_SEARCH", "") not in \
                ("", "0"):
            return None
        warm = None
        state = load_state(self.state_file)
        if state is not None:
            global _LAST_SCORE
            _LAST_SCORE = state.get("score")
            warm = dict(state["committed"])
        elif _LAST_COMMITTED is not None:
            warm = dict(_LAST_COMMITTED)
        if warm is not None and \
                os.environ.get("HOROVOD_AUTOTUNE_WIRE", "") in ("", "0"):
            # A persisted wire dtype is NUMERICS-changing and only ever
            # entered the search under the HOROVOD_AUTOTUNE_WIRE opt-in;
            # a warm restart without that opt-in must not silently put
            # the new job on a lossy wire.
            warm.pop("wire_dtype", None)
        if warm is not None:
            # The band width is never swept (ordering semantics belong
            # to the user's env), and a LIVE flip races enqueue-time
            # priority stamping across ranks for one step — a state
            # file carrying priority_bands (hand-edited; the store's
            # sanitizer admits the key for the ladder's sake) must not
            # re-apply it mid-run.  The env knob is the only way in.
            warm.pop("priority_bands", None)
        return warm or None

    def _search_once(self) -> bool:
        """One full search under the current epoch.  Returns True when it
        committed; False when the epoch moved underneath it (the caller
        restarts the search under the new epoch)."""
        self.epoch = self._eng.epoch()
        cfg_now = self._eng.stats()["config"]
        base = {k: int(v) for k, v in cfg_now.items()
                if k in ("chunk_bytes", "fusion_threshold",
                         "cycle_time_ms", "wave_width", "algo_threshold")}
        space = default_space(cfg_now["num_channels"],
                              int(cfg_now.get("priority_bands", 0)))
        for k in space:
            # Ladder dims start from the global fusion threshold (the
            # engine's effective per-band value when unset).
            if k.startswith("fusion_ladder_"):
                base.setdefault(k, int(cfg_now["fusion_threshold"]))
        if "wire_dtype" in space:
            # Only when the wire knob is actually swept does it join the
            # base/committed config (config reports it as a NAME; the
            # TUNE frame and the ladder use the WireDtype code).  Keeping
            # it out otherwise preserves the invariant that a committed
            # config compares equal to stats()["config"] key-for-key.
            from horovod_tpu.runtime.engine import WIRE_DTYPES
            base["wire_dtype"] = WIRE_DTYPES.get(
                cfg_now.get("wire_dtype", "fp32"), 0)
        search = CoordinateSearch(space, seed=self.seed, base=base,
                                  max_trials=self.max_trials)
        self.planned = search.planned_schedule()
        while self._alive():
            if self._eng.epoch() != self.epoch:
                return False  # world resized: restart under the new epoch
            cfg = search.propose()
            if cfg is None:
                break
            if not self._apply(cfg, commit=False):
                # Engine gone -> True (stop quietly); epoch moved -> False
                # (the caller restarts the search under the new epoch).
                return self._alive() is False
            score = self._score_window()
            search.observe(score)
            self.trace.append({"config": dict(cfg), "score": score})
        if not self._alive():
            return True  # stop requested; don't loop
        committed = search.best
        if self._apply(committed, commit=True):
            global _LAST_COMMITTED, _LAST_SCORE
            self.committed = dict(committed)
            self.committed_score = search.best_score
            _LAST_COMMITTED = dict(committed)
            _LAST_SCORE = search.best_score
            save_state(self.state_file, committed, search.best_score,
                       self.seed,
                       wiring={
                           "num_channels":
                               self._eng.stats()["config"]["num_channels"],
                           "channel_drivers":
                               self._eng.stats()["config"]
                               ["channel_drivers"],
                       })
            self._converged.set()
        return True

    def _monitor(self) -> None:
        """Post-commit regression watch: several consecutive COMPLETED
        windows below reprobe_ratio x the baseline re-open the search
        (workload or host conditions changed); idle/timed-out windows
        never count — an idle trainer is not a regression.

        The baseline is an EWMA over the monitor's own non-regressing
        windows, seeded from the FIRST completed one — not from the
        search's best trial score, which is a max over noisy windows (a
        peak, not a typical value: loopback busbw on a loaded host
        swings well over 2x), and never ratcheted to a maximum: a
        transient fast window nudges it up a fraction and later normal
        windows pull it back, so noise cannot inflate the baseline until
        ordinary throughput reads as a phantom regression and the tuner
        churns full searches through live training."""
        bad = 0
        baseline: Optional[float] = None
        while self._alive():
            if self._eng.epoch() != self.epoch:
                # Resized world: re-assert the committed config under the
                # new epoch (the engine re-read env defaults at re-init).
                # The old baseline is void — busbw scales with the size.
                self.epoch = self._eng.epoch()
                if self.committed is not None:
                    self._apply(self.committed, commit=True)
                bad = 0
                baseline = None
                continue
            score = self._score_window()
            if score is None:
                bad = 0
                continue
            if baseline is None or baseline <= 0:
                baseline = score
                self.committed_score = score
                continue
            if score < self.reprobe_ratio * baseline:
                # Regressing windows only count — folding them into the
                # EWMA would decay the baseline toward the regressed
                # level and mask a persistent shift.
                bad += 1
            else:
                bad = 0
                baseline += 0.2 * (score - baseline)
                self.committed_score = baseline
            if bad >= self.reprobe_windows:
                self._converged.clear()
                self.committed = None
                bad = 0
                baseline = None
                while self._alive():
                    if self._search_once():
                        break
                if not self.converged:
                    return


# -- process-wide lifecycle (driven by basics.init/shutdown) ---------------

_TUNER: Optional[Autotuner] = None
_TUNER_LOCK = threading.Lock()


def start_autotuner(engine) -> Autotuner:
    """Start (or restart) the coordinator's tuner thread; returns it."""
    global _TUNER
    with _TUNER_LOCK:
        if (_TUNER is not None and _TUNER.is_alive()
                and not _TUNER._stop_evt.is_set()):
            return _TUNER
        _TUNER = Autotuner(engine)
        _TUNER.start()
        return _TUNER


def stop_autotuner(timeout: float = 5.0) -> None:
    global _TUNER
    with _TUNER_LOCK:
        tuner = _TUNER
    if tuner is None:
        return
    tuner.stop()
    tuner.join(timeout)


def get_tuner() -> Optional[Autotuner]:
    """The live (or last) Autotuner of this process — rank 0 only."""
    return _TUNER


# -- startup micro-probe (wiring-time knobs) -------------------------------

def startup_probe(candidates=None, nbytes: int = 4 << 20,
                  iters: int = 4) -> Dict[str, int]:
    """Collective: EVERY rank must call this, before training starts.

    Measures allreduce bus bandwidth at each candidate
    ``(num_channels, channel_drivers)`` wiring — 0 = auto — via
    shutdown + re-init per candidate (the bench gate's alternation
    machinery), then re-wires the world with rank 0's winner (its
    verdict is broadcast through the engine, so every rank re-inits
    with the same env and the rendezvous cannot split)."""
    import numpy as np

    from horovod_tpu.common.basics import basics
    from horovod_tpu.runtime.engine import get_engine

    eng = get_engine()
    if candidates is None:
        candidates = [(1, 0), (2, 0), (4, 0)]
    # The online tuner must not mutate knobs mid-probe; and if a
    # candidate re-init fails mid-probe, the exception must not leave
    # the env pinned to the failing candidate — a caller that catches
    # and re-inits would silently wire a fan-out the user never chose.
    saved = {k: os.environ.get(k)
             for k in ("HOROVOD_NUM_CHANNELS", "HOROVOD_CHANNEL_DRIVERS")}
    os.environ["HOROVOD_AUTOTUNE_SUSPEND"] = "1"
    try:
        x = np.ones(max(1, nbytes // 4), dtype=np.float32)
        scores = []
        for ch, dr in candidates:
            os.environ["HOROVOD_NUM_CHANNELS"] = str(ch) if ch else ""
            os.environ["HOROVOD_CHANNEL_DRIVERS"] = str(dr) if dr else ""
            basics.shutdown()
            basics.init()
            eng.allreduce(x.copy(), name="autotune.probe.warm")
            before = eng.stats()
            for _ in range(iters):
                eng.synchronize(eng.enqueue_allreduce(
                    x.copy(), name="autotune.probe.t"))
            scores.append(
                eng.stats_delta(before)["allreduce_bus_bw_bytes_per_sec"])
        best = int(np.argmax(np.asarray(scores)))
        pick = eng.broadcast(
            np.asarray(list(candidates[best]), dtype=np.int64),
            root_rank=0, name="autotune.probe.pick")
        ch, dr = int(pick[0]), int(pick[1])
    except BaseException:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        raise
    finally:
        os.environ.pop("HOROVOD_AUTOTUNE_SUSPEND", None)
    os.environ["HOROVOD_NUM_CHANNELS"] = str(ch) if ch else ""
    os.environ["HOROVOD_CHANNEL_DRIVERS"] = str(dr) if dr else ""
    basics.shutdown()
    basics.init()  # the online tuner (if enabled) restarts here
    return {"num_channels": ch, "channel_drivers": dr}
