"""Fleet observability plane: live metrics endpoint + post-mortem tools.

* :mod:`horovod_tpu.monitor.metrics` — the metric registry driving the
  Prometheus/JSON renderers and the docs reference table.
* :mod:`horovod_tpu.monitor.server` — rank 0's HTTP endpoint
  (HOROVOD_METRICS_PORT; started by ``hvd.init``), plus the
  ``--status`` client helpers.
* :mod:`horovod_tpu.monitor.postmortem` — cross-correlates per-rank
  flight-recorder dumps (HOROVOD_FLIGHT_RECORDER_DIR) and names the
  divergence point: ``python -m horovod_tpu.monitor.postmortem <dir>``.

See docs/observability.md.
"""

from horovod_tpu.monitor.metrics import (
    STATS_METRICS,
    TELEM_COUNTERS,
    format_reference,
    render_json,
    render_prometheus,
)
from horovod_tpu.monitor.server import (
    MetricsServer,
    format_status,
    get_metrics_server,
    query_status,
    start_metrics_server,
    stop_metrics_server,
)

__all__ = [
    "MetricsServer",
    "STATS_METRICS",
    "TELEM_COUNTERS",
    "format_reference",
    "format_status",
    "get_metrics_server",
    "query_status",
    "render_json",
    "render_prometheus",
    "start_metrics_server",
    "stop_metrics_server",
]
