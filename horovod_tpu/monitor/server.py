"""Live metrics endpoint: Prometheus text + JSON over HTTP.

Rank 0 serves the fleet view of a running job on
``HOROVOD_METRICS_PORT`` (started by ``hvd.init`` when the port is set;
provably off when unset — no thread, no socket).  Same asyncio patterns
as ``serve/server.py``, but speaking just enough HTTP/1.1 for curl,
Prometheus scrapers and ``python -m horovod_tpu.run --status``:

    GET /metrics   Prometheus text exposition (rank-0 stats + fleet table)
    GET /json      {"stats": ..., "fleet": ..., <mounted providers>}
    GET /fleet     the fleet table alone
    GET /healthz   200 "ok"

Additional stats providers mount on the same endpoint (the serve
plane's router mounts its replica stats as ``"serve"``); each provider
is a zero-arg callable returning a dict, called per request so the
response is always live.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Callable, Dict, Optional

from horovod_tpu.monitor.metrics import render_json, render_prometheus

__all__ = ["MetricsServer", "start_metrics_server", "stop_metrics_server",
           "query_status", "format_status"]


class MetricsServer:
    """Tiny HTTP/1.1 server over asyncio streams, run in its own daemon
    thread (the engine's API threads must never block on a scrape)."""

    def __init__(self, port: int, host: str = "0.0.0.0",
                 stats_provider: Optional[Callable[[], dict]] = None,
                 fleet_provider: Optional[Callable[[], dict]] = None):
        self._host = host
        self._port_req = port
        self.port: Optional[int] = None
        self._stats = stats_provider
        self._fleet = fleet_provider
        self._extra: Dict[str, Callable[[], dict]] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._stop = None  # asyncio.Event, created on the loop

    def mount(self, name: str, provider: Callable[[], dict]) -> None:
        """Expose another stats dict on /json (key ``name``) and as
        ``horovod_<name>_*`` gauges on /metrics."""
        self._extra[name] = provider

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> int:
        """Start the serving thread; returns the bound port."""
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="horovod-metrics")
        self._thread.start()
        self._started.wait(timeout=10)
        if self.port is None:
            raise RuntimeError(
                f"metrics endpoint failed to bind {self._host}:"
                f"{self._port_req}")
        return self.port

    def stop(self) -> None:
        loop, stop = self._loop, self._stop
        if loop is not None and stop is not None:
            loop.call_soon_threadsafe(stop.set)
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        asyncio.run(self._serve())

    async def _serve(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop = asyncio.Event()
        try:
            server = await asyncio.start_server(self._handle, self._host,
                                                self._port_req)
        except OSError as exc:
            import sys

            print(f"horovod_tpu: metrics endpoint bind failed: {exc}",
                  file=sys.stderr)
            self._started.set()
            return
        self.port = server.sockets[0].getsockname()[1]
        self._started.set()
        await self._stop.wait()
        server.close()
        await server.wait_closed()

    # -- request handling --------------------------------------------------

    def _gather(self):
        def safe(fn):
            try:
                return fn() if fn is not None else {}
            except Exception as exc:  # a dying engine must not 500 forever
                return {"error": str(exc)}

        stats = safe(self._stats)
        fleet = safe(self._fleet)
        extra = {name: safe(fn) for name, fn in self._extra.items()}
        return stats, fleet, extra

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=10)
            parts = request.decode(errors="replace").split()
            path = parts[1] if len(parts) >= 2 else "/"
            # Drain headers (ignored — no body on GET).
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=10)
                if line in (b"\r\n", b"\n", b""):
                    break
            status, ctype, body = self._route(path)
            payload = body.encode()
            writer.write(
                (f"HTTP/1.1 {status}\r\n"
                 f"Content-Type: {ctype}\r\n"
                 f"Content-Length: {len(payload)}\r\n"
                 "Connection: close\r\n\r\n").encode() + payload)
            await writer.drain()
        except (asyncio.TimeoutError, OSError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except OSError:
                pass

    def _route(self, path: str):
        path = path.split("?", 1)[0]
        if path == "/healthz":
            return "200 OK", "text/plain", "ok\n"
        if path == "/metrics":
            stats, fleet, extra = self._gather()
            return ("200 OK", "text/plain; version=0.0.4",
                    render_prometheus(stats, fleet, extra))
        if path in ("/json", "/"):
            stats, fleet, extra = self._gather()
            return ("200 OK", "application/json",
                    json.dumps(render_json(stats, fleet, extra)) + "\n")
        if path == "/fleet":
            _, fleet, _ = self._gather()
            return ("200 OK", "application/json",
                    json.dumps(fleet or {}) + "\n")
        return "404 Not Found", "text/plain", "not found\n"


# -- module-level singleton (hvd.init / hvd.shutdown lifecycle) ------------

_server: Optional[MetricsServer] = None
_server_lock = threading.Lock()


def start_metrics_server(port: int,
                         stats_provider: Callable[[], dict],
                         fleet_provider: Callable[[], dict]) -> int:
    """Start (or reuse) the process-wide metrics endpoint; returns the
    bound port.  Called by ``hvd.init`` on rank 0 when
    HOROVOD_METRICS_PORT is set."""
    global _server
    with _server_lock:
        if _server is not None:
            return _server.port or 0
        srv = MetricsServer(port, stats_provider=stats_provider,
                            fleet_provider=fleet_provider)
        bound = srv.start()
        _server = srv
        return bound


def get_metrics_server() -> Optional[MetricsServer]:
    return _server


def stop_metrics_server() -> None:
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None


# -- shell-side status client (`python -m horovod_tpu.run --status`) ------

def query_status(addr: str, timeout: float = 5.0) -> dict:
    """GET http://<addr>/json from a live job's metrics endpoint."""
    import urllib.request

    if "://" not in addr:
        addr = f"http://{addr}"
    with urllib.request.urlopen(f"{addr}/json", timeout=timeout) as resp:
        return json.loads(resp.read().decode(errors="replace"))


def format_status(payload: dict) -> str:
    """Human summary of a /json payload for the --status CLI."""
    stats = payload.get("stats", {}) or {}
    fleet = payload.get("fleet", {}) or {}
    lines = ["== horovod_tpu live job status =="]
    cfg = stats.get("config", {}) or {}
    lines.append(
        f"epoch {fleet.get('epoch', '?')} · world {fleet.get('world_size', '?')}"
        f" · hosts {fleet.get('hosts', '?')} · ranks reporting "
        f"{fleet.get('ranks_reporting', 0)} · telemetry every "
        f"{fleet.get('telemetry_cycles', cfg.get('telemetry_cycles', '?'))}"
        " cycles")
    totals = fleet.get("totals", {}) or {}
    if totals:
        gib = 1024.0 ** 3
        lines.append(
            f"fleet: data_tx {totals.get('data_bytes_tx', 0) / gib:.3f} GiB"
            f" · allreduce {totals.get('allreduce_bytes', 0) / gib:.3f} GiB"
            f" · round_trips {totals.get('control_round_trips', 0)}"
            f" · cache_hits {totals.get('cache_hits', 0)}"
            f" · stall_warnings {totals.get('stall_warnings', 0)}"
            f" · backup_skips {totals.get('backup_skips', 0)}")
    slow = fleet.get("slowest", {}) or {}
    if slow.get("rank", -1) >= 0:
        lines.append(
            f"slowest rank: {slow['rank']} "
            f"(step p99 {slow.get('step_time_ns_p99', 0) / 1e6:.2f} ms); "
            f"fleet quorum lag p50/p99 "
            f"{fleet.get('quorum_lag_ns_p50', 0) / 1e6:.2f}/"
            f"{fleet.get('quorum_lag_ns_p99', 0) / 1e6:.2f} ms")
    lag_by_rank = fleet.get("quorum_lag_by_rank", {}) or {}
    for row in fleet.get("rows", []) or []:
        c = row.get("counters", {})
        attr = lag_by_rank.get(str(row.get("rank")), {})
        lines.append(
            f"  row rank {row.get('rank')} (host {row.get('host')}, "
            f"nranks {row.get('nranks')}): data_tx {c.get('data_bytes_tx', 0)}"
            f" · tensors {c.get('tensors', 0)}"
            f" · step p99 {row.get('step_time_ns_p99', 0) / 1e6:.2f} ms"
            f" · lag attributions {attr.get('attributions', 0)}")
    for name, values in payload.items():
        if name in ("stats", "fleet") or not isinstance(values, dict):
            continue
        keys = ", ".join(f"{k}={v}" for k, v in sorted(values.items())
                         if isinstance(v, (int, float)))
        if keys:
            lines.append(f"{name}: {keys}")
    return "\n".join(lines)
