"""Flight-recorder post-mortem: cross-correlate per-rank dumps.

Usage::

    python -m horovod_tpu.monitor.postmortem <HOROVOD_FLIGHT_RECORDER_DIR>
    python -m horovod_tpu.monitor.postmortem dir/ --tail 80

Each surviving rank dumps ``flightrec.rank<r>.json`` on abort,
stall-warning escalation, and fatal signals (a crashed culprit leaves no
dump — its absence is itself evidence).  This tool merges the per-rank
event rings onto rank 0's clock (each dump carries the rendezvous
clock offset), votes a CULPRIT out of the abort verdicts, reports every
rank's last committed control cycle, and prints the merged tail so the
cycles LEADING INTO the failure are readable in one place.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

__all__ = ["load_dumps", "analyze", "format_report", "main"]


def load_dumps(path: str) -> Dict[int, dict]:
    """dir (or a glob of dump files) → {rank: dump dict}."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "flightrec.rank*.json")))
    else:
        files = sorted(glob.glob(path))
    dumps: Dict[int, dict] = {}
    for f in files:
        try:
            with open(f, encoding="utf-8", errors="replace") as fh:
                d = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"postmortem: skipping unreadable dump {f}: {exc}",
                  file=sys.stderr)
            continue
        dumps[int(d.get("rank", -1))] = d
    return dumps


_RANK_RE = re.compile(r"(?:rank|culprit=)\s*(\d+)")


def analyze(dumps: Dict[int, dict], world_size: Optional[int] = None) -> dict:
    """The cross-rank verdict: culprit vote, per-rank last cycles, the
    fleet's last fully-committed cycle, and the aligned merged events."""
    votes: Dict[int, int] = {}
    verdicts: List[str] = []
    last_cycle: Dict[int, int] = {}
    # Link-heal history per rank: (suspects, healed, escalated).  A world
    # that "flapped then died" reads differently from one that just died —
    # suspect/healed events before the abort say the link was unstable
    # long before the fatal failure.
    link_events: Dict[int, Dict[str, int]] = {}
    # Checkpoint history per rank, from the weight plane's begin/commit/
    # restore notes: the "died at step S, last durable step C" readout.
    ckpt_events: Dict[int, Dict[str, int]] = {}
    ckpt_step_re = re.compile(r"step=(\d+)")
    merged: List[Tuple[int, int, dict]] = []  # (aligned_ns, rank, event)
    for rank, d in sorted(dumps.items()):
        offset = int(d.get("clock_offset_ns", 0))
        for e in d.get("events", []):
            merged.append((int(e.get("mono_ns", 0)) + offset, rank, e))
            if e.get("kind") == "cycle":
                last_cycle[rank] = max(last_cycle.get(rank, 0),
                                       int(e.get("cycle", 0)))
            if e.get("kind") == "link":
                text = e.get("text", "")
                lk = link_events.setdefault(
                    rank, {"suspect": 0, "healed": 0, "escalate": 0})
                for key in lk:
                    if text.startswith(key):
                        lk[key] += 1
            if e.get("kind") == "ckpt":
                text = e.get("text", "")
                m = ckpt_step_re.search(text)
                if m:
                    s = int(m.group(1))
                    ck = ckpt_events.setdefault(
                        rank, {"last_attempt": -1, "last_durable": -1,
                               "restores": 0})
                    if text.startswith("commit"):
                        ck["last_durable"] = max(ck["last_durable"], s)
                        ck["last_attempt"] = max(ck["last_attempt"], s)
                    elif text.startswith("begin"):
                        ck["last_attempt"] = max(ck["last_attempt"], s)
                    elif text.startswith("restore"):
                        ck["restores"] += 1
            if e.get("kind") == "abort":
                text = e.get("text", "")
                verdicts.append(f"rank {rank}: {text}")
                m = _RANK_RE.search(text)
                if m:
                    c = int(m.group(1))
                    if c != rank:  # a verdict never blames its reporter
                        votes[c] = votes.get(c, 0) + 1
        reason = d.get("reason", "")
        if reason:
            m = _RANK_RE.search(reason)
            if m and int(m.group(1)) != rank:
                votes[int(m.group(1))] = votes.get(int(m.group(1)), 0) + 1
    merged.sort(key=lambda t: (t[0], t[1]))
    culprit = max(votes, key=votes.get) if votes else None
    # A rank missing from the dumps while every survivor aborted is the
    # classic crashed-culprit signature; corroborate the vote with it.
    missing = []
    if world_size:
        missing = [r for r in range(world_size) if r not in dumps]
        if culprit is None and len(missing) == 1:
            culprit = missing[0]
    return {
        "ranks": sorted(dumps.keys()),
        "missing_ranks": missing,
        "culprit": culprit,
        "votes": votes,
        "verdicts": verdicts,
        "last_cycle": last_cycle,
        # The last cycle EVERY reporting rank committed: the fleet's
        # last consistent control-plane state — the divergence point is
        # right after it.
        "last_committed_cycle": min(last_cycle.values()) if last_cycle
        else 0,
        "link_events": link_events,
        "ckpt_events": ckpt_events,
        "merged": merged,
    }


def format_report(result: dict, tail: int = 60) -> str:
    lines = [f"flight-recorder post-mortem: {len(result['ranks'])} dump(s) "
             f"from rank(s) {result['ranks']}"]
    if result["missing_ranks"]:
        lines.append(f"no dump from rank(s) {result['missing_ranks']} — "
                     "a crashed process leaves none (evidence, not error)")
    if result["culprit"] is not None:
        nvotes = result["votes"].get(result["culprit"], 0)
        lines.append(
            f"verdict: rank {result['culprit']} is the culprit "
            f"({nvotes} abort verdict(s) name it"
            + (", and it left no dump)" if result["culprit"]
               in result["missing_ranks"] else ")"))
    else:
        lines.append("verdict: no culprit named (no abort verdicts in "
                     "the dumps — stall escalation or manual dump?)")
    for v in result["verdicts"][:8]:
        lines.append(f"  verdict · {v}")
    link = result.get("link_events") or {}
    if link:
        healed = sum(v["healed"] for v in link.values())
        escal = sum(v["escalate"] for v in link.values())
        per_link = ", ".join(
            f"rank {r}: {v['suspect']} suspect / {v['healed']} healed / "
            f"{v['escalate']} escalated" for r, v in sorted(link.items()))
        lines.append(
            ("link health: the world FLAPPED before it died — " if healed
             else "link health: ") + per_link +
            ("; the fatal failure followed earlier healed blips"
             if healed and (escal or result["culprit"] is not None)
             else ""))
    ckpt = result.get("ckpt_events") or {}
    if ckpt:
        durable = max((v["last_durable"] for v in ckpt.values()),
                      default=-1)
        attempt = max((v["last_attempt"] for v in ckpt.values()),
                      default=-1)
        if durable >= 0:
            died = (f"died at step {attempt}" if attempt > durable
                    else f"died at or after step {durable}")
            lines.append(
                f"checkpoint: {died}, last durable step {durable} — a "
                f"relaunch resumes from {durable}; work after it is "
                "recomputed, never torn")
        elif attempt >= 0:
            lines.append(
                f"checkpoint: died at step {attempt} with NO durable "
                "commit — the write began but the commit barrier never "
                "passed (previous manifest, if any, stays authoritative)")
        restores = sum(v["restores"] for v in ckpt.values())
        if restores:
            lines.append(f"checkpoint: {restores} restore(s) recorded "
                         "before the failure (an earlier incarnation "
                         "already recovered once)")
    per = ", ".join(f"rank {r}={c}" for r, c in
                    sorted(result["last_cycle"].items()))
    lines.append(
        f"last committed control cycle: {result['last_committed_cycle']} "
        f"fleet-wide ({per}); divergence begins after it")
    lines.append(f"merged tail (aligned to rank 0's clock, last {tail} "
                 "events):")
    events = result["merged"][-tail:]
    t0 = events[0][0] if events else 0
    for t, rank, e in events:
        lines.append(
            f"  +{(t - t0) / 1e6:10.3f}ms rank {rank} cycle "
            f"{e.get('cycle', 0):>5} {e.get('kind', '?'):<8} "
            f"{e.get('text', '')}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.monitor.postmortem",
        description="Cross-correlate per-rank flight-recorder dumps and "
                    "name the divergence point.")
    parser.add_argument("path", help="HOROVOD_FLIGHT_RECORDER_DIR (or a "
                                     "glob of flightrec.rank*.json files)")
    parser.add_argument("--world-size", type=int, default=None,
                        help="expected world size (missing dumps then "
                             "corroborate the culprit vote)")
    parser.add_argument("--tail", type=int, default=60,
                        help="merged events to print (default 60)")
    args = parser.parse_args(argv)
    dumps = load_dumps(args.path)
    if not dumps:
        print(f"postmortem: no flightrec.rank*.json dumps under "
              f"{args.path}", file=sys.stderr)
        return 1
    result = analyze(dumps, world_size=args.world_size)
    try:
        print(format_report(result, tail=args.tail))
    except BrokenPipeError:
        return 0  # `... | head` closed the pipe; the report was served
    return 0


if __name__ == "__main__":
    sys.exit(main())
