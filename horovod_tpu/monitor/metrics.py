"""Metric registry + Prometheus/JSON renderers for the live endpoint.

One table drives everything: the ``/metrics`` Prometheus text, the
``/json`` payload shape, and the reference table in
docs/observability.md (regenerate with
``python -c "from horovod_tpu.monitor.metrics import format_reference; print(format_reference())"``).

``TELEM_COUNTERS`` mirrors the native engine's ``kTelemCounterNames``
(cpp/engine.h TelemCounter) — the TELEM wire carries positions, not
names, so the two lists must stay in lockstep.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional

__all__ = [
    "TELEM_COUNTERS",
    "STATS_METRICS",
    "SERVE_METRICS",
    "render_prometheus",
    "render_json",
    "reference_rows",
    "format_reference",
]

#: Fleet-telemetry counter order — lockstep with cpp/engine.h
#: kTelemCounterNames (the wire carries positions).
TELEM_COUNTERS = [
    "data_bytes_tx", "data_bytes_rx",
    "allreduce_bytes", "reducescatter_bytes",
    "negotiation_bytes_tx", "negotiation_bytes_rx",
    "control_round_trips", "cache_hits",
    "cache_misses", "tensors",
    "responses", "cycles",
    "shm_bytes_tx", "compressed_bytes_tx",
    "wire_bytes_saved", "backup_skips",
    "stale_epoch_msgs", "stall_warnings",
    "priority_inversions", "alltoall_bytes",
    "moe_tokens_dropped",
]


class Metric(NamedTuple):
    stats_key: str   # key in eng.stats()
    prom: str        # Prometheus metric name
    kind: str        # "counter" | "gauge"
    help: str


#: Per-process metrics exported from ``stats()`` (rank 0's own view; the
#: fleet table below carries every rank's).
STATS_METRICS: List[Metric] = [
    Metric("cycles", "horovod_exec_cycles_total", "counter",
           "negotiation cycles that executed at least one response"),
    Metric("responses", "horovod_responses_executed_total", "counter",
           "responses executed (a fused batch counts once)"),
    Metric("tensors", "horovod_tensors_executed_total", "counter",
           "tensors executed"),
    Metric("cache_hits", "horovod_cache_hits_total", "counter",
           "enqueues negotiated via a cache-slot bit"),
    Metric("cache_misses", "horovod_cache_misses_total", "counter",
           "cacheable enqueues that took full negotiation"),
    Metric("cache_evictions", "horovod_cache_evictions_total", "counter",
           "cache slots dropped from this rank's replica"),
    Metric("negotiation_bytes_tx", "horovod_negotiation_bytes_tx_total",
           "counter", "control-frame bytes sent (incl. length prefix)"),
    Metric("negotiation_bytes_rx", "horovod_negotiation_bytes_rx_total",
           "counter", "control-frame bytes received"),
    Metric("control_round_trips", "horovod_control_round_trips_total",
           "counter", "negotiation round trips (idle heartbeats excluded)"),
    Metric("stale_epoch_msgs", "horovod_stale_epoch_msgs_total", "counter",
           "control frames dropped for a stale membership epoch"),
    Metric("assign_bytes_tx", "horovod_assign_bytes_tx_total", "counter",
           "rendezvous ASSIGN bytes sent by this coordinator"),
    Metric("data_bytes_tx", "horovod_data_bytes_tx_total", "counter",
           "data-plane payload bytes sent (all collectives/channels)"),
    Metric("data_bytes_rx", "horovod_data_bytes_rx_total", "counter",
           "data-plane payload bytes received"),
    Metric("allreduce_bytes", "horovod_allreduce_bytes_total", "counter",
           "ring-allreduce payload bytes"),
    Metric("reducescatter_bytes", "horovod_reducescatter_bytes_total",
           "counter", "reduce-scatter payload bytes"),
    Metric("shm_bytes_tx", "horovod_shm_bytes_tx_total", "counter",
           "payload bytes sent through shared-memory rings"),
    Metric("compressed_bytes_tx", "horovod_compressed_bytes_tx_total",
           "counter", "compressed-wire ring payload bytes sent"),
    Metric("wire_bytes_saved", "horovod_wire_bytes_saved_total", "counter",
           "buffer-level bytes saved by compressed wire formats"),
    Metric("backup_skips", "horovod_backup_skips_total", "counter",
           "backup-worker partial commits that left this rank out"),
    Metric("priority_inversions", "horovod_priority_inversions_total",
           "counter",
           "committed responses dispatched after a less-urgent response "
           "of the same cycle (0 by construction with "
           "HOROVOD_PRIORITY_BANDS on)"),
    Metric("alltoall_bytes", "horovod_alltoall_bytes_total", "counter",
           "alltoall payload bytes (variable-split block exchange; "
           "MoE dispatch/combine rides this)"),
    Metric("alltoall_ns", "horovod_alltoall_ns_total", "counter",
           "wall nanoseconds spent in alltoall exchanges"),
    Metric("alltoall_bus_bw_bytes_per_sec",
           "horovod_alltoall_bus_bw_bytes_per_sec", "gauge",
           "alltoall bus bandwidth ((N-1)/N * bytes / wall) over the "
           "stats window"),
    Metric("moe_tokens_dropped", "horovod_moe_tokens_dropped_total",
           "counter",
           "expert-capacity overflow tokens dropped by the MoE plane "
           "(receiver-side, deterministic in global token order)"),
    Metric("moe_dispatches", "horovod_moe_dispatches_total", "counter",
           "MoE dispatch/combine round trips completed by this process"),
    Metric("moe_capacity_factor", "horovod_moe_capacity_factor", "gauge",
           "capacity factor of the most recent MoE dispatch"),
    Metric("moe_experts", "horovod_moe_experts", "gauge",
           "expert count of the most recent MoE dispatch"),
    Metric("link_reconnects", "horovod_link_reconnects_total", "counter",
           "data-channel edges transparently re-established mid-collective "
           "(link self-healing, HOROVOD_LINK_RETRIES)"),
    Metric("link_heal_failures", "horovod_link_heal_failures_total",
           "counter",
           "link-heal suspects that exhausted the retry/deadline budget "
           "and escalated to the abort path"),
    Metric("local_sgd_syncs", "horovod_local_sgd_syncs_total", "counter",
           "outer local-SGD delta syncs completed"),
    Metric("sharded_steps", "horovod_sharded_steps_total", "counter",
           "ZeRO-1 sharded-optimizer steps completed"),
    Metric("stall_warnings", "horovod_stall_warnings_total", "counter",
           "stalled-tensor warnings emitted (rate-limited per tensor, "
           "mirrored into the flight recorder)"),
    Metric("telem_bytes_tx", "horovod_telem_bytes_tx_total", "counter",
           "bytes the TELEM piggyback added to control frames"),
    Metric("flight_events", "horovod_flight_events_total", "counter",
           "flight-recorder events recorded"),
    Metric("flight_dumps", "horovod_flight_dumps_total", "counter",
           "flight-recorder dumps written"),
    Metric("tune_trials", "horovod_tune_trials_total", "counter",
           "TUNE frames applied on this rank"),
    Metric("step_time_ns_p50", "horovod_step_time_ns_p50", "gauge",
           "allreduce completion latency p50 (sliding window)"),
    Metric("step_time_ns_p99", "horovod_step_time_ns_p99", "gauge",
           "allreduce completion latency p99"),
    Metric("coordinator_cycle_ns_p50", "horovod_coordinator_cycle_ns_p50",
           "gauge", "coordinator control-cycle wall time p50"),
    Metric("coordinator_cycle_ns_p99", "horovod_coordinator_cycle_ns_p99",
           "gauge", "coordinator control-cycle wall time p99"),
    Metric("quorum_lag_ns_p50", "horovod_quorum_lag_ns_p50", "gauge",
           "per-entry quorum lag p50 (last voter vs second-to-last)"),
    Metric("quorum_lag_ns_p99", "horovod_quorum_lag_ns_p99", "gauge",
           "per-entry quorum lag p99 — backup=auto's default instrument"),
    Metric("link_heal_ns_p50", "horovod_link_heal_ns_p50", "gauge",
           "link-heal suspect-to-healed duration p50 (sliding window)"),
    Metric("link_heal_ns_p99", "horovod_link_heal_ns_p99", "gauge",
           "link-heal suspect-to-healed duration p99"),
    Metric("clock_offset_ns", "horovod_clock_offset_ns", "gauge",
           "rendezvous-estimated monotonic clock offset to rank 0"),
    Metric("checkpoint_bytes", "horovod_checkpoint_bytes_total", "counter",
           "bytes written into committed checkpoint shards by this rank"),
    Metric("checkpoint_restores", "horovod_checkpoint_restores_total",
           "counter", "restores completed from a checkpoint manifest"),
    Metric("weight_push_count", "horovod_weight_push_count_total",
           "counter", "live trainer→serve weight pushes sent"),
    Metric("checkpoint_ns_p50", "horovod_checkpoint_ns_p50", "gauge",
           "off-path checkpoint write+commit wall time p50 "
           "(sliding window)"),
    Metric("checkpoint_ns_p99", "horovod_checkpoint_ns_p99", "gauge",
           "off-path checkpoint write+commit wall time p99"),
    Metric("last_checkpoint_step", "horovod_last_checkpoint_step", "gauge",
           "step of the last committed (durable) checkpoint manifest"),
]

#: Serve-plane counters mounted by the router as the ``"serve"``
#: provider (``horovod_serve_*``): its own fleet counters plus the
#: per-replica scheduler counters piggybacked on probe pongs and summed
#: fleet-wide.  Keys absent from this table still export as bare gauges
#: (the mount is schemaless by design); listing here adds HELP/TYPE rows
#: and a docs/observability.md entry.
SERVE_METRICS: List[Metric] = [
    Metric("completed", "horovod_serve_completed", "counter",
           "streams finished with a done event (router fleet view)"),
    Metric("requeued", "horovod_serve_requeued", "counter",
           "in-flight requests transparently requeued after a replica "
           "death"),
    Metric("replica_deaths", "horovod_serve_replica_deaths", "counter",
           "replica processes declared down by the router"),
    Metric("link_reconnects", "horovod_serve_link_reconnects", "counter",
           "router→replica links transparently healed in place "
           "(HOROVOD_SERVE_LINK_RETRIES; streams resume seq-exact, "
           "no requeue)"),
    Metric("weight_pushes", "horovod_serve_weight_pushes", "counter",
           "live trainer→serve weight swaps fanned out to the fleet"),
    Metric("prefix_hits", "horovod_serve_prefix_hits", "counter",
           "prompt KV blocks served from the content-hash prefix cache "
           "instead of being prefilled (summed over replicas)"),
    Metric("prefix_misses", "horovod_serve_prefix_misses", "counter",
           "shareable prompt blocks that missed the prefix cache and "
           "were prefilled"),
    Metric("prefix_evictions", "horovod_serve_prefix_evictions", "counter",
           "cached prefix blocks recycled under pool pressure (LRU) or "
           "a weight-epoch flush"),
    Metric("cow_forks", "horovod_serve_cow_forks", "counter",
           "copy-on-write forks where a sequence diverged from a "
           "shared cached prefix"),
    Metric("fused_attn_steps", "horovod_serve_fused_attn_steps", "counter",
           "decode steps executed by the fused paged-attention kernel "
           "(HOROVOD_SERVE_FUSED_ATTN)"),
    Metric("prefill_tokens_saved", "horovod_serve_prefill_tokens_saved",
           "counter",
           "prompt tokens whose prefill compute was skipped via prefix "
           "cache hits"),
]

_SERVE_HELP = {m.stats_key: m for m in SERVE_METRICS}


def render_prometheus(stats: Optional[dict], fleet: Optional[dict],
                      extra: Optional[Dict[str, dict]] = None) -> str:
    """Prometheus text exposition of rank 0's stats + the fleet table.

    ``extra`` maps a provider name (e.g. ``"serve"``) to a flat dict of
    numeric values, exported as ``horovod_<provider>_<key>`` gauges — the
    serve plane's router/replica stats mount through it."""
    lines: List[str] = []
    stats = stats or {}
    for m in STATS_METRICS:
        if m.stats_key not in stats:
            continue
        v = stats[m.stats_key]
        if not isinstance(v, (int, float)):
            continue
        lines.append(f"# HELP {m.prom} {m.help}")
        lines.append(f"# TYPE {m.prom} {m.kind}")
        lines.append(f"{m.prom} {v}")
    if fleet:
        lines.append("# HELP horovod_fleet_ranks_reporting fleet rows "
                     "(per rank, or per host under hierarchical "
                     "coordination)")
        lines.append("# TYPE horovod_fleet_ranks_reporting gauge")
        lines.append("horovod_fleet_ranks_reporting "
                     f"{fleet.get('ranks_reporting', 0)}")
        totals = fleet.get("totals", {})
        for name in TELEM_COUNTERS:
            if name not in totals:
                continue
            prom = f"horovod_fleet_{name}_total"
            lines.append(f"# HELP {prom} fleet-wide sum of per-rank "
                         f"{name} (TELEM aggregation)")
            lines.append(f"# TYPE {prom} counter")
            lines.append(f"{prom} {totals[name]}")
        for row in fleet.get("rows", []):
            labels = (f'rank="{row.get("rank", -1)}",'
                      f'host="{row.get("host", 0)}",'
                      f'nranks="{row.get("nranks", 1)}"')
            for name in TELEM_COUNTERS:
                v = row.get("counters", {}).get(name)
                if v is None:
                    continue
                lines.append(f"horovod_fleet_{name}{{{labels}}} {v}")
            for gauge in ("step_time_ns_p50", "step_time_ns_p99"):
                if gauge in row:
                    lines.append(
                        f"horovod_fleet_{gauge}{{{labels}}} {row[gauge]}")
        for rank, attr in sorted(
                (fleet.get("quorum_lag_by_rank", {}) or {}).items(),
                key=lambda kv: int(kv[0])):
            lines.append(
                f'horovod_fleet_quorum_lag_attributions{{rank="{rank}"}} '
                f"{attr.get('attributions', 0)}")
            lines.append(
                f'horovod_fleet_quorum_lag_max_ns{{rank="{rank}"}} '
                f"{attr.get('max_ns', 0)}")
        slow = fleet.get("slowest", {})
        if slow:
            lines.append("# HELP horovod_fleet_slowest_rank rank with the "
                         "worst step-time p99 across the fleet")
            lines.append("# TYPE horovod_fleet_slowest_rank gauge")
            lines.append(f"horovod_fleet_slowest_rank {slow.get('rank', -1)}")
        for key in ("quorum_lag_ns_p50", "quorum_lag_ns_p99"):
            if key in fleet:
                lines.append(f"horovod_fleet_{key} {fleet[key]}")
    for provider, values in (extra or {}).items():
        for key, v in sorted((values or {}).items()):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            name = f"horovod_{provider}_{key}".replace(".", "_")
            reg = _SERVE_HELP.get(key) if provider == "serve" else None
            if reg is not None:
                lines.append(f"# HELP {reg.prom} {reg.help}")
                lines.append(f"# TYPE {reg.prom} {reg.kind}")
            else:
                lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {v}")
    return "\n".join(lines) + "\n"


def render_json(stats: Optional[dict], fleet: Optional[dict],
                extra: Optional[Dict[str, dict]] = None) -> dict:
    """The ``/json`` payload: raw stats + fleet table + mounted extras."""
    out = {"stats": stats or {}, "fleet": fleet or {}}
    for provider, values in (extra or {}).items():
        out[provider] = values or {}
    return out


def reference_rows() -> List[dict]:
    """Rows for the docs/observability.md metrics reference table —
    generated from the same registry the endpoint serves."""
    rows = [{"metric": m.prom, "kind": m.kind, "source": f"stats()['{m.stats_key}']",
             "help": m.help} for m in STATS_METRICS]
    for name in TELEM_COUNTERS:
        rows.append({
            "metric": f"horovod_fleet_{name}_total", "kind": "counter",
            "source": f"fleet_stats()['totals']['{name}']",
            "help": f"fleet-wide sum of per-rank {name} "
                    "(per-rank/per-host rows carry labels)",
        })
    rows.append({"metric": "horovod_fleet_slowest_rank", "kind": "gauge",
                 "source": "fleet_stats()['slowest']",
                 "help": "rank with the worst step-time p99"})
    rows.extend({
        "metric": m.prom, "kind": m.kind,
        "source": f"serve mount ['{m.stats_key}']", "help": m.help,
    } for m in SERVE_METRICS)
    return rows


def format_reference() -> str:
    """Markdown rendering of :func:`reference_rows` (docs generator)."""
    rows = reference_rows()
    lines = ["| metric | kind | source | description |",
             "|---|---|---|---|"]
    for r in rows:
        lines.append(f"| `{r['metric']}` | {r['kind']} | `{r['source']}` "
                     f"| {r['help']} |")
    return "\n".join(lines)
