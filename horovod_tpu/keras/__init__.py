"""Drop-in alias for the Keras-role frontend.

Reference parity: users of the reference import ``horovod.keras`` (and
``horovod.tensorflow.keras``, a byte-level near-copy of it — SURVEY.md
§2.2 P8/P10).  In this framework the Keras role is played by the flax
frontend (``horovod_tpu.flax``): ``fit`` is the ``model.fit`` analogue,
``checkpoint.restore_and_broadcast`` the ``load_model`` analogue, and the
four callbacks keep their reference names.  This module re-exports that
frontend under the familiar name so reference-era imports read naturally::

    import horovod_tpu.keras as hvd_keras

    hvd_keras.init()
    state = hvd_keras.fit(state, data_fn, epochs=..., callbacks=[
        hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd_keras.callbacks.MetricAverageCallback(),
    ])
"""

from horovod_tpu.flax import *          # noqa: F401,F403
from horovod_tpu.flax import callbacks, checkpoint, estimator  # noqa: F401
from horovod_tpu.flax import __all__    # noqa: F401
