"""Keras-3 frontend: real ``keras.Model``/``keras.optimizers`` support.

Reference parity: ``horovod/keras/__init__.py`` (148 LoC) —
``DistributedOptimizer`` (:33-64), ``broadcast_global_variables`` /
``allreduce`` wrappers (:67-114), ``load_model`` (:117-148) — and
``horovod/tensorflow/keras``, its byte-level near-copy (SURVEY.md §2.2
P8/P10).

Keras 3 on this stack is multi-backend (JAX, TensorFlow, torch); the
JAX backend is the TPU-native flagship — the trainer jit-compiles the
train step and the gradient allreduce runs as an ``io_callback`` into
the native engine (see ``impl.py``).  The flax frontend
(``horovod_tpu.flax``) remains the Keras-ROLE surface for pure-JAX
training states; this module serves actual ``keras.Model`` users.

Usage::

    import keras
    import horovod_tpu.keras as hvd

    hvd.init()
    model = keras.Sequential([...])
    opt = hvd.DistributedOptimizer(keras.optimizers.Adam(1e-3 * hvd.size()))
    model.compile(optimizer=opt, loss="mse")
    model.fit(x, y, callbacks=[
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
    ])
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from horovod_tpu.common.basics import basics
from horovod_tpu.keras import callbacks
from horovod_tpu.keras.impl import (
    broadcast_variables, create_distributed_optimizer, wrap_optimizer_class,
    _engine,
)

__all__ = [
    "init", "shutdown", "rank", "size", "local_rank", "local_size",
    "DistributedOptimizer", "create_distributed_optimizer",
    "broadcast_variables", "broadcast_global_variables", "allreduce",
    "allgather", "broadcast", "load_model", "callbacks",
]

init = basics.init
shutdown = basics.shutdown
rank = basics.rank
size = basics.size
local_rank = basics.local_rank
local_size = basics.local_size


def DistributedOptimizer(optimizer, compression: str = "none"):
    """Wrap a ``keras.optimizers.Optimizer`` so ``apply`` averages the
    gradients across ranks first (reference __init__.py:33-64).  The
    wrapped class keeps the original class name, so saved models reload
    with or without this library."""
    return create_distributed_optimizer(optimizer, compression)


def broadcast_global_variables(model, root_rank: int = 0) -> None:
    """Broadcast a model's weights (and built optimizer slots) from
    ``root_rank`` (reference __init__.py:67-77; Keras 3 has no global
    graph, so the model is explicit)."""
    broadcast_variables(model.weights, root_rank, name_prefix="keras.bcast.w")
    opt = getattr(model, "optimizer", None)
    if opt is not None and getattr(opt, "built", False):
        broadcast_variables(opt.variables, root_rank,
                            name_prefix="keras.bcast.opt")


def allreduce(value, average: bool = True, name: Optional[str] = None):
    """Average (or sum) a host scalar/array across ranks — the metric
    path (reference __init__.py:80-98).  Returns a fresh numpy array
    (python float for scalar input); never mutates the input (the engine
    reduces in place, so a private copy goes on the wire)."""
    scalar = np.isscalar(value) or getattr(value, "ndim", None) == 0
    arr = np.array(value, dtype=np.float64 if scalar else None, copy=True,
                   order="C")
    if scalar:
        arr = arr.reshape(1)
    eng = _engine()
    if eng is not None:
        eng.synchronize(
            eng.enqueue_allreduce(arr, name=name or "keras.allreduce"))
        if average:
            n = basics.size()
            arr = arr / n if arr.dtype.kind == "f" else arr // n
    return float(arr[0]) if scalar else arr


def allgather(value, name: Optional[str] = None):
    """Concatenate each rank's array along dim 0 (reference
    __init__.py:101-107)."""
    arr = np.array(value, copy=True, order="C")
    if arr.ndim == 0:
        arr = arr.reshape(1)
    eng = _engine()
    if eng is None:
        return arr
    return eng.synchronize(
        eng.enqueue_allgather(arr, name=name or "keras.allgather"))


def broadcast(value, root_rank: int = 0, name: Optional[str] = None):
    """Broadcast a host array from ``root_rank`` (reference
    __init__.py:110-114).  Returns a fresh array; never mutates the
    input."""
    if root_rank < 0 or root_rank >= basics.size():
        raise ValueError(
            f"root_rank {root_rank} out of range for size {basics.size()}")
    arr = np.array(value, copy=True, order="C")
    eng = _engine()
    if eng is not None:
        eng.synchronize(eng.enqueue_broadcast(
            arr, root_rank, name=name or "keras.broadcast"))
    return arr


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression: str = "none"):
    """Load a saved ``keras.Model`` and make its optimizer distributed
    (reference __init__.py:117-148, impl.py:93-109).

    The file is loaded as plain keras (wrapped optimizers serialize
    under their base class's public name — see ``wrap_optimizer_class``),
    then the deserialized optimizer's class is swapped to the wrapped
    subclass IN PLACE, preserving the restored slot variables — which a
    from-config reconstruction would lose.  ``custom_optimizers`` /
    ``custom_objects`` feed deserialization of custom classes.
    """
    import keras

    objects = dict(custom_objects or {})
    if custom_optimizers is not None:
        objects.update({cls.__name__: cls for cls in custom_optimizers})
    with keras.saving.custom_object_scope(objects):
        model = keras.saving.load_model(filepath)
    opt = getattr(model, "optimizer", None)
    if opt is not None and not getattr(type(opt), "_hvd_wrapped", False):
        opt.__class__ = wrap_optimizer_class(type(opt), compression)
    return model
