"""Keras-3 callbacks: broadcast, metric averaging, LR schedule/warmup.

Reference parity: ``horovod/keras/callbacks_impl.py`` —
BroadcastGlobalVariables (:20-30), MetricAverage (:33-67),
LearningRateSchedule with momentum correction (:70-146), Warmup with the
Goyal et al. ramp (:149-168).  Rebuilt on ``keras.callbacks.Callback``
(Keras 3 objects, no sessions); metric averaging rides the host engine
directly instead of building per-metric graph variables.
"""

from __future__ import annotations

import warnings

import numpy as np
import keras

from horovod_tpu.common.basics import basics
from horovod_tpu.keras.impl import (_host_average_many, broadcast_variables)

__all__ = [
    "BroadcastGlobalVariablesCallback", "MetricAverageCallback",
    "LearningRateScheduleCallback", "LearningRateWarmupCallback",
]


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast all model variables (and optimizer slots, once built)
    from ``root_rank`` at train start, so every worker begins from
    identical state whether initialized randomly or restored from a
    checkpoint (reference callbacks_impl.py:20-30)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._weights_done = False
        self._opt_done = False

    def _broadcast_what_exists(self):
        # Keras builds lazily, backend-dependently: the JAX trainer
        # materializes weights before on_train_begin, the TF trainer only
        # inside the first train step, and optimizer slots appear after
        # the first apply everywhere.  Broadcast each group as soon as it
        # exists; until the weights broadcast lands, per-rank steps use
        # averaged (identical) gradients on divergent weights, and the
        # batch-0-end broadcast then equalizes — from batch 1 on, state
        # is bit-identical.
        if not self._weights_done and self.model.weights:
            broadcast_variables(self.model.weights, self.root_rank,
                                name_prefix="keras.bcast.w")
            self._weights_done = True
        opt = getattr(self.model, "optimizer", None)
        if not self._opt_done and opt is not None \
                and getattr(opt, "built", False):
            broadcast_variables(opt.variables, self.root_rank,
                                name_prefix="keras.bcast.opt")
            self._opt_done = True

    def on_train_begin(self, logs=None):
        self._broadcast_what_exists()

    def on_train_batch_end(self, batch, logs=None):
        if not (self._weights_done and self._opt_done):
            self._broadcast_what_exists()


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch-end metrics over ranks in place, so rank-0 logging,
    checkpoint-on-best, and LR plateaus act on global values (reference
    callbacks_impl.py:33-67).  Keys are sorted for cross-rank rendezvous
    order; non-scalar entries pass through untouched."""

    def on_epoch_end(self, epoch, logs=None):
        if not logs or basics.size() == 1:
            return
        keys = sorted(k for k, v in logs.items()
                      if np.isscalar(v) or getattr(v, "ndim", None) == 0)
        arrays = [np.asarray(float(logs[k]), dtype=np.float64).reshape(1)
                  for k in keys]
        reduced = _host_average_many(arrays, f"keras.metric.ep{epoch}")
        for k, r in zip(keys, reduced):
            logs[k] = float(r[0])


def _get_lr(optimizer) -> float:
    return float(keras.ops.convert_to_numpy(optimizer.learning_rate))


def _set_lr(optimizer, value: float) -> None:
    # Keras 3 exposes learning_rate as an assignable variable property
    # (raises for LearningRateSchedule objects, same as the reference's
    # backend.set_value on a schedule).
    optimizer.learning_rate = value


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiply the initial LR by ``multiplier(epoch)`` inside
    [start_epoch, end_epoch) (reference callbacks_impl.py:70-146).

    ``staircase=True`` adjusts on epoch boundaries; ``staircase=False``
    interpolates per batch using ``steps_per_epoch`` (autodetected from
    ``params['steps']`` when possible).  Momentum correction rescales
    momentum by new_lr/old_lr around the boundary (Goyal et al. 2017) —
    Keras 3 stores momentum as a plain python attribute, so under the
    JAX trainer's jitted step the corrected value only takes effect on
    retrace; a warning is emitted once there.
    """

    def __init__(self, multiplier, start_epoch: int = 0, end_epoch=None,
                 staircase: bool = True, momentum_correction: bool = True,
                 steps_per_epoch=None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr = None
        self.restore_momentum = None
        self.current_epoch = 0
        if not callable(multiplier):
            self.staircase = True
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _autodetect_steps_per_epoch(self):
        if self.params and self.params.get("steps"):
            return self.params["steps"]
        raise ValueError(
            "Could not autodetect steps_per_epoch; pass steps_per_epoch= "
            "to %s()" % type(self).__name__)

    def _adjust_lr(self, epoch):
        opt = self.model.optimizer
        old_lr = _get_lr(opt)
        new_lr = self.initial_lr * self.multiplier(epoch)
        _set_lr(opt, new_lr)
        if self.momentum_correction and hasattr(opt, "momentum") \
                and np.isscalar(opt.momentum) and opt.momentum:
            if keras.backend.backend() == "jax":
                warnings.warn(
                    "momentum correction is inert under the jitted JAX "
                    "trainer (momentum is a python attribute, baked at "
                    "trace time)", RuntimeWarning)
            else:
                self.restore_momentum = opt.momentum
                opt.momentum = opt.momentum * new_lr / old_lr

    def _restore_momentum_if_needed(self):
        if self.restore_momentum:
            self.model.optimizer.momentum = self.restore_momentum
            self.restore_momentum = None

    def on_train_begin(self, logs=None):
        self.initial_lr = _get_lr(self.model.optimizer)
        if not self.staircase and not self.steps_per_epoch:
            self.steps_per_epoch = self._autodetect_steps_per_epoch()

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def on_train_batch_begin(self, batch, logs=None):
        if (self.current_epoch < self.start_epoch or
                (self.end_epoch is not None and
                 self.current_epoch >= self.end_epoch)):
            return
        if self.staircase and batch == 0:
            self._adjust_lr(self.current_epoch)
        elif not self.staircase:
            epoch = self.current_epoch + float(batch) / self.steps_per_epoch
            self._adjust_lr(epoch)

    def on_train_batch_end(self, batch, logs=None):
        self._restore_momentum_if_needed()

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = _get_lr(self.model.optimizer)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from lr to lr*size over ``warmup_epochs`` (Goyal
    et al. 2017; reference callbacks_impl.py:149-168).  Pair with an
    initial lr already scaled by ``size()``."""

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True, steps_per_epoch=None,
                 verbose: int = 0):
        def multiplier(epoch):
            epoch += 1.0 / self.steps_per_epoch
            return 1.0 / basics.size() * (
                epoch * (basics.size() - 1) / warmup_epochs + 1)

        super().__init__(multiplier, start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose > 0 \
                and basics.rank() == 0:
            print("\nEpoch %d: finished gradual learning rate warmup to %g."
                  % (epoch + 1, _get_lr(self.model.optimizer)))
