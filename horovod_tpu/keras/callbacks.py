"""Keras-3 callbacks: broadcast, metric averaging, LR schedule/warmup.

Reference parity: ``horovod/keras/callbacks_impl.py`` —
BroadcastGlobalVariables (:20-30), MetricAverage (:33-67),
LearningRateSchedule with momentum correction (:70-146), Warmup with the
Goyal et al. ramp (:149-168).  Rebuilt on ``keras.callbacks.Callback``
(Keras 3 objects, no sessions); metric averaging rides the host engine
directly instead of building per-metric graph variables.
"""

from __future__ import annotations

import numpy as np
import keras

from horovod_tpu.common.basics import basics
from horovod_tpu.keras.impl import (_host_average_many, broadcast_variables)

__all__ = [
    "BroadcastGlobalVariablesCallback", "MetricAverageCallback",
    "LearningRateScheduleCallback", "LearningRateWarmupCallback",
]


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast all model variables (and optimizer slots, once built)
    from ``root_rank`` at train start, so every worker begins from
    identical state whether initialized randomly or restored from a
    checkpoint (reference callbacks_impl.py:20-30)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._weights_done = False
        self._opt_done = False
        self._tf_hooked = False
        self._tf_unhook = None

    def _broadcast_what_exists(self):
        # Keras builds lazily, backend-dependently: the JAX trainer
        # materializes weights before on_train_begin, the TF trainer only
        # while the first train step traces, and optimizer slots appear
        # after the first build everywhere.  Broadcast each group as soon
        # as it exists; on the TF backend _install_tf_first_step_hook
        # runs this inside the traced step, after build but strictly
        # before batch 0's variable reads.
        if not self._weights_done and self.model.weights:
            broadcast_variables(self.model.weights, self.root_rank,
                                name_prefix="keras.bcast.w")
            self._weights_done = True
        opt = getattr(self.model, "optimizer", None)
        if not self._opt_done and opt is not None \
                and getattr(opt, "built", False):
            broadcast_variables(opt.variables, self.root_rank,
                                name_prefix="keras.bcast.opt")
            self._opt_done = True

    def _install_tf_first_step_hook(self):
        # On the TF backend an unbuilt model only materializes weights
        # while the first train step TRACES — after on_train_begin, too
        # late for a strictly-before-batch-0 broadcast from callbacks
        # alone.  Wrap ``train_step`` to (1) force-build model+optimizer
        # symbolically at trace time (Keras's own ``_symbolic_build``, so
        # variables are eagerly initialized BEFORE the graph first runs —
        # deferred inits race the broadcast otherwise) and (2) run the
        # broadcast in a py_function ordered before every variable read.
        # Batch 0's forward then runs on equalized weights on every rank,
        # matching the reference's strictly-before-training broadcast
        # (callbacks_impl.py:20-30).
        #
        # XLA caveat: tf.py_function cannot lower under XLA, and Keras 3
        # resolves jit_compile='auto' to True whenever TF sees a non-CPU
        # device — embedding the hook would fail fit() at batch 0.  With
        # jit_compile on we instead run step 0 EAGERLY (run_eagerly wins
        # over jit_compile in the Keras trainer): the build + broadcast
        # happen as plain Python before the step body, and the unhook
        # restores the jitted path for every later step (one retrace).
        import tensorflow as tf

        model, cb = self.model, self
        orig_train_step = model.train_step
        jit = bool(getattr(model, "jit_compile", False))
        orig_run_eagerly = bool(getattr(model, "run_eagerly", False))

        def _host_broadcast():
            if not (cb._weights_done and cb._opt_done):
                cb._broadcast_what_exists()
            return np.int32(0)

        def train_step_with_broadcast(*args, **kwargs):
            if cb._weights_done and cb._opt_done:
                # Stale wrapper (fit raised before either unhook path
                # ran, then a new fit retraced): trace straight through
                # to the original step, zero steady-state overhead.
                return orig_train_step(*args, **kwargs)
            data = args[0] if args else kwargs.get("data")
            build = getattr(model, "_symbolic_build", None)
            if callable(build) and data is not None:
                build(data_batch=data)
            if jit:
                # Eager first step: broadcast directly, no py_function.
                _host_broadcast()
                return orig_train_step(*args, **kwargs)
            done = tf.py_function(_host_broadcast, [], Tout=tf.int32)
            with tf.control_dependencies([done]):
                return orig_train_step(*args, **kwargs)

        model.train_step = train_step_with_broadcast
        if jit:
            model.run_eagerly = True
        # fit() already captured the unwrapped train_step into its
        # train_function (make_train_function runs before
        # on_train_begin); rebuild so the wrapper is the one traced.
        if getattr(model, "train_function", None) is not None:
            model.make_train_function(force=True)
        self._tf_hooked = True

        def _unhook():
            model.train_step = orig_train_step
            if jit:
                model.run_eagerly = orig_run_eagerly
            if getattr(model, "train_function", None) is not None:
                model.make_train_function(force=True)

        self._tf_unhook = _unhook

    def on_train_begin(self, logs=None):
        self._broadcast_what_exists()
        if not (self._weights_done and self._opt_done) \
                and not self._tf_hooked \
                and keras.backend.backend() == "tensorflow":
            self._install_tf_first_step_hook()

    def on_train_batch_end(self, batch, logs=None):
        if not (self._weights_done and self._opt_done):
            self._broadcast_what_exists()
        if self._tf_unhook and self._weights_done and self._opt_done:
            # Broadcast landed: drop the traced-step wrapper (one retrace)
            # so steady-state steps pay no per-step host roundtrip.
            self._tf_unhook()
            self._tf_unhook = None

    def on_train_end(self, logs=None):
        # Safety net for fits that never reach a batch end (zero-step
        # epoch, early interrupt): never leave train_step wrapped.
        if self._tf_unhook:
            self._tf_unhook()
            self._tf_unhook = None


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch-end metrics over ranks in place, so rank-0 logging,
    checkpoint-on-best, and LR plateaus act on global values (reference
    callbacks_impl.py:33-67).  Keys are sorted for cross-rank rendezvous
    order; non-scalar entries pass through untouched."""

    def on_epoch_end(self, epoch, logs=None):
        if not logs or basics.size() == 1:
            return
        keys = sorted(k for k, v in logs.items()
                      if np.isscalar(v) or getattr(v, "ndim", None) == 0)
        arrays = [np.asarray(float(logs[k]), dtype=np.float64).reshape(1)
                  for k in keys]
        # The metric key is part of the collective name: if ranks ever see
        # different key sets (e.g. a rank-0-only callback injected a
        # metric earlier in the list), the engine fails with a clear
        # per-metric rendezvous error instead of positionally misaligned
        # values.
        reduced = _host_average_many(
            arrays, f"keras.metric.ep{epoch}", names=keys)
        for k, r in zip(keys, reduced):
            logs[k] = float(r[0])


def _get_lr(optimizer) -> float:
    return float(keras.ops.convert_to_numpy(optimizer.learning_rate))


def _set_lr(optimizer, value: float) -> None:
    # Keras 3 exposes learning_rate as an assignable variable property
    # (raises for LearningRateSchedule objects, same as the reference's
    # backend.set_value on a schedule).
    optimizer.learning_rate = value


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiply the initial LR by ``multiplier(epoch)`` inside
    [start_epoch, end_epoch) (reference callbacks_impl.py:70-146).

    ``staircase=True`` adjusts on epoch boundaries; ``staircase=False``
    interpolates per batch using ``steps_per_epoch`` (autodetected from
    ``params['steps']`` when possible).  Momentum correction rescales
    momentum by new_lr/old_lr around the boundary (Goyal et al. 2017).
    Keras 3 stores the momentum COEFFICIENT as a plain python attribute
    baked into the jitted JAX step at trace time, so on that backend the
    correction instead scales the velocity SLOTS once by new_lr/old_lr —
    the mathematically identical trace-safe form: v1 = m*(r*v0) -
    new_lr*g == (m*r)*v0 - new_lr*g, with no restore needed.  Any LR/slot
    change under the JAX trainer first flushes the live jitted state via
    ``jax_state_sync`` so the trainer re-reads variables next batch.
    """

    def __init__(self, multiplier, start_epoch: int = 0, end_epoch=None,
                 staircase: bool = True, momentum_correction: bool = True,
                 steps_per_epoch=None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr = None
        self.restore_momentum = None
        self.current_epoch = 0
        if not callable(multiplier):
            self.staircase = True
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _autodetect_steps_per_epoch(self):
        if self.params and self.params.get("steps"):
            return self.params["steps"]
        raise ValueError(
            "Could not autodetect steps_per_epoch; pass steps_per_epoch= "
            "to %s()" % type(self).__name__)

    def _adjust_lr(self, epoch):
        opt = self.model.optimizer
        on_jax = keras.backend.backend() == "jax"
        if on_jax:
            # Flush the live jitted state into the variables BEFORE
            # reading/writing lr or slots (mid-epoch the JAX trainer's
            # source of truth is its threaded state, not the variables);
            # the flag this sets makes the trainer re-read all variables
            # at the next batch, so the changes below take effect without
            # a retrace.
            sync = getattr(self.model, "jax_state_sync", None)
            if callable(sync):
                sync()
        old_lr = _get_lr(opt)
        new_lr = self.initial_lr * self.multiplier(epoch)
        _set_lr(opt, new_lr)
        if self.momentum_correction and hasattr(opt, "momentum") \
                and np.isscalar(opt.momentum) and opt.momentum:
            if on_jax:
                # Trace-safe equivalent of the reference's one-step
                # coefficient correction (callbacks_impl.py:108-113):
                # scale the velocity slots once by new_lr/old_lr (see
                # class docstring).  Unbuilt slots (before the first
                # apply) are all-zero — nothing to scale.
                slots = getattr(opt, "momentums", None)
                if slots and old_lr > 0:
                    ratio = new_lr / old_lr
                    for v in slots:
                        v.assign(keras.ops.multiply(v, ratio))
            else:
                self.restore_momentum = opt.momentum
                opt.momentum = opt.momentum * new_lr / old_lr

    def _restore_momentum_if_needed(self):
        if self.restore_momentum:
            self.model.optimizer.momentum = self.restore_momentum
            self.restore_momentum = None

    def on_train_begin(self, logs=None):
        self.initial_lr = _get_lr(self.model.optimizer)
        if not self.staircase and not self.steps_per_epoch:
            self.steps_per_epoch = self._autodetect_steps_per_epoch()

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def on_train_batch_begin(self, batch, logs=None):
        if (self.current_epoch < self.start_epoch or
                (self.end_epoch is not None and
                 self.current_epoch >= self.end_epoch)):
            return
        if self.staircase and batch == 0:
            self._adjust_lr(self.current_epoch)
        elif not self.staircase:
            epoch = self.current_epoch + float(batch) / self.steps_per_epoch
            self._adjust_lr(epoch)

    def on_train_batch_end(self, batch, logs=None):
        self._restore_momentum_if_needed()

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = _get_lr(self.model.optimizer)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from lr to lr*size over ``warmup_epochs`` (Goyal
    et al. 2017; reference callbacks_impl.py:149-168).  Pair with an
    initial lr already scaled by ``size()``."""

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True, steps_per_epoch=None,
                 verbose: int = 0):
        def multiplier(epoch):
            epoch += 1.0 / self.steps_per_epoch
            return 1.0 / basics.size() * (
                epoch * (basics.size() - 1) / warmup_epochs + 1)

        super().__init__(multiplier, start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose > 0 \
                and basics.rank() == 0:
            print("\nEpoch %d: finished gradual learning rate warmup to %g."
                  % (epoch + 1, _get_lr(self.model.optimizer)))
