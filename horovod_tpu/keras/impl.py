"""Core Keras-3 integration: backend-dispatched collectives + optimizer
wrapping.

Reference parity: ``horovod/keras/impl.py`` — ``create_distributed_optimizer``
(impl.py:20-70) wraps the optimizer class under its OWN name so saved
models reload with or without horovod, and ``load_model`` (impl.py:93-109)
maps optimizer class names to wrapped classes.

TPU-native design: Keras 3 is multi-backend, and ``BaseOptimizer.apply``
is the one choke point every path funnels through — ``apply_gradients``,
eager ``apply``, and the JAX trainer's jitted ``stateless_apply`` (which
calls ``self.apply`` inside a StatelessScope).  The gradient allreduce
dispatches on ``keras.backend.backend()``:

- ``jax``: a single ``jax.experimental.io_callback`` (legal inside jit,
  where the JAX trainer runs the whole train step) carrying ALL
  gradients at once — enqueued together so the engine negotiates them in
  one cycle and fuses same-dtype batches into single ring collectives.
- ``tensorflow``: one ``tf.py_function`` doing the same.
- ``torch``/``numpy``: direct host calls (those backends run eagerly).

Accelerator-resident large-scale training belongs to the JAX/XLA path
(``horovod_tpu.jax``/``parallel``); this frontend is the multi-process
host data plane for ``keras.Model`` users, same as the torch frontend.
"""

from __future__ import annotations

import numpy as np

from horovod_tpu.common.basics import basics
from horovod_tpu.runtime import engine_or_none as _engine

_COMPRESS_WIRE = {"none": None, "fp16": np.float16, "bf16": "bf16"}

_API_EXPORT_WARNED = False


def _check_compression(compression: str) -> str:
    if compression not in _COMPRESS_WIRE:
        raise ValueError(
            f"unknown compression {compression!r}; "
            f"one of {sorted(_COMPRESS_WIRE)}")
    return compression


def _wire_dtype(compression: str):
    wire = _COMPRESS_WIRE[_check_compression(compression)]
    if wire == "bf16":
        import ml_dtypes

        return ml_dtypes.bfloat16
    return wire


def _host_average_many(arrays, name_prefix: str, compression: str = "none",
                       names=None):
    """Average a batch of host arrays across ranks, NEVER mutating the
    inputs (the engine reduces in place, so every enqueued buffer is a
    fresh copy).

    Every allreduce is enqueued before any is synchronized, so the
    coordinator negotiates the whole batch in one cycle and the engine's
    fusion packs same-dtype tensors into single ring operations.

    ``names`` (optional, one per array) joins the rendezvous key — pass
    semantic names wherever ranks could disagree about the batch
    contents, so a divergence fails with a clear per-name error instead
    of positional misalignment.
    """
    eng = _engine()
    arrays = [np.ascontiguousarray(a) for a in arrays]
    keys = (list(range(len(arrays))) if names is None else list(names))
    if len(keys) != len(arrays):
        raise ValueError(
            f"{len(arrays)} arrays but {len(keys)} names")
    if eng is None:
        return arrays
    wire = _wire_dtype(compression)
    sent = []
    for a in arrays:
        if wire is not None and a.dtype.kind == "f" and a.dtype != wire:
            sent.append((a.astype(wire), a.dtype))
        else:
            sent.append((a.copy(), None))
    # Batch position = registration order = scheduling priority for the
    # priority-banded coordinator (HOROVOD_PRIORITY_BANDS).
    handles = [eng.enqueue_allreduce(w, name=f"{name_prefix}.{k}",
                                     priority=i)
               for i, (k, (w, _)) in enumerate(zip(keys, sent))]
    # Drain EVERY handle before raising (eng.drain hygiene), and divide
    # by the committed PARTICIPANT count — a backup-worker partial
    # commit (HOROVOD_BACKUP_WORKERS) reduces fewer than size
    # contributions, and dividing by size would silently downscale every
    # participant's gradients.
    results, infos, first_err = eng.drain(handles)
    if first_err is not None:
        raise first_err
    outs = []
    for (w, orig), out, info in zip(sent, results, infos):
        n = info.get("participants") or basics.size()
        out = (out / n).astype(orig if orig is not None else w.dtype,
                               copy=False)
        outs.append(out)
    return outs


def allreduce_gradients(grads, name_prefix: str = "keras.grad",
                        compression: str = "none"):
    """Average a list of backend-native gradient tensors across ranks
    (None entries pass through).  Works under the JAX trainer's jit via
    ``io_callback``; eager everywhere else."""
    import keras

    grads = list(grads)
    idx = [i for i, g in enumerate(grads) if g is not None]
    if not idx or basics.size() == 1:
        return grads
    vals = [grads[i] for i in idx]
    backend = keras.backend.backend()

    if backend == "jax":
        import jax
        from jax.experimental import io_callback

        shapes = tuple(jax.ShapeDtypeStruct(v.shape, v.dtype) for v in vals)
        outs = io_callback(
            lambda *arrs: tuple(
                _host_average_many(arrs, name_prefix, compression)),
            shapes, *vals, ordered=True)
    elif backend == "tensorflow":
        import tensorflow as tf

        outs = tf.py_function(
            lambda *arrs: _host_average_many(
                [a.numpy() for a in arrs], name_prefix, compression),
            vals, Tout=[v.dtype for v in vals])
        for o, v in zip(outs, vals):
            o.set_shape(v.shape)
    elif backend == "torch":
        import torch

        # torch cannot round-trip bf16 through .numpy(); reuse the torch
        # frontend's uint16/ml_dtypes reinterpretation in BOTH directions
        # (the engine understands the wire dtype natively).
        from horovod_tpu.torch.mpi_ops import _from_np, _np_view

        def _to_torch(r, v):
            r = np.ascontiguousarray(r)
            wire = (torch.bfloat16 if r.dtype.name == "bfloat16"
                    else torch.float32)  # selects _from_np's branch only
            return _from_np(r, wire).to(device=v.device, dtype=v.dtype)

        reduced = _host_average_many(
            [_np_view(g.detach().cpu().contiguous()) for g in vals],
            name_prefix, compression)
        outs = [_to_torch(r, v) for r, v in zip(reduced, vals)]
    else:  # numpy / openvino
        outs = _host_average_many([np.asarray(g) for g in vals],
                                  name_prefix, compression)

    for i, o in zip(idx, outs):
        grads[i] = o
    return grads


def broadcast_variables(variables, root_rank: int,
                        name_prefix: str = "keras.bcast") -> None:
    """Assign root's value of every ``keras.Variable`` on every rank.
    Names are positional — the variable structure is identical across
    ranks by construction."""
    eng = _engine()
    if eng is None:
        return
    import keras

    pending = []
    for i, v in enumerate(variables):
        # ascontiguousarray also promotes 0-d (e.g. the iteration
        # counter) to 1-d, which the wire wants anyway.
        arr = np.ascontiguousarray(keras.ops.convert_to_numpy(v))
        h = eng.enqueue_broadcast(arr, root_rank, name=f"{name_prefix}.{i}")
        pending.append((v, arr, h))
    for v, arr, h in pending:
        eng.synchronize(h)
        v.assign(arr.reshape(v.shape))


def wrap_optimizer_class(cls, compression: str = "none"):
    """Dynamic subclass of a Keras-3 optimizer class whose ``apply``
    first averages the incoming gradients across ranks.

    Named after the class it wraps (reference impl.py:64-67) so a model
    saved with the distributed optimizer reloads cleanly WITHOUT horovod
    too — the config schema is identical to the base class's.
    """

    class _Distributed(cls):
        _hvd_wrapped = True
        _hvd_compression = compression

        def apply(self, grads, trainable_variables=None, **kwargs):
            grads = allreduce_gradients(
                grads, compression=self._hvd_compression)
            return super().apply(grads, trainable_variables, **kwargs)

    _Distributed.__name__ = cls.__name__
    _Distributed.__qualname__ = cls.__qualname__
    # Serialize under the BASE class's public API name: a model saved
    # with the wrapped optimizer then records a plain-keras config
    # (module "keras.optimizers", no registered_name) and reloads in an
    # environment without this library — the reference's portability
    # property (impl.py:64-67), which Keras 3 would otherwise break by
    # recording the wrapper's module path.
    try:
        from keras.src import api_export as _ae

        public = _ae.get_name_from_symbol(cls)
        if public is not None:
            _ae.REGISTERED_OBJS_TO_NAMES[_Distributed] = public
    except (ImportError, AttributeError):
        # Private keras internals moved: saved configs will carry the
        # wrapper's module path, so models saved with this optimizer need
        # horovod_tpu installed to reload.  Losing that documented
        # portability property must be VISIBLE, not silent.
        global _API_EXPORT_WARNED
        if not _API_EXPORT_WARNED:
            _API_EXPORT_WARNED = True
            import warnings

            warnings.warn(
                "keras.src.api_export internals not found in this keras "
                "version; models saved with the distributed optimizer "
                "will record the wrapper module path and require "
                "horovod_tpu to reload", RuntimeWarning, stacklevel=2)
    return _Distributed


def create_distributed_optimizer(optimizer, compression: str = "none"):
    """Wrap a built ``keras.optimizers.Optimizer`` instance; config
    round-trips through the wrapped class (reference impl.py:20-70)."""
    if getattr(type(optimizer), "_hvd_wrapped", False):
        return optimizer
    cls = wrap_optimizer_class(type(optimizer), compression)
    return cls.from_config(optimizer.get_config())
