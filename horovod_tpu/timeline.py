"""Cross-rank merged timeline: ``python -m horovod_tpu.timeline merge``.

Per-rank Chrome traces (HOROVOD_TIMELINE on rank 0, plus
``<path>.rank<r>`` per worker under HOROVOD_TIMELINE_ALL_RANKS=1) each
carry a ``horovod_meta`` header with the writer's rank, its monotonic
base (the trace's ts=0 instant) and the rendezvous-estimated clock
offset to rank 0.  ``merge`` puts every file's events on ONE rank-0-
aligned time axis::

    rank0_mono_us(event) = ts + mono_base_us + clock_offset_us

remaps pids into disjoint per-rank bands (track labels become
``r<rank>/<tensor>``), and keeps the cross-rank flow ids intact — rank
0's NEGOTIATE commit emits the flow source ("s"), every rank's
execution span the sink ("f"), with the SAME ``"<name>#<epoch>#<n>"``
id, so chrome://tracing (or Perfetto) draws arrows from the
negotiation to each rank's execution.

Usage::

    python -m horovod_tpu.timeline merge tl.json tl.json.rank1 -o merged.json
    python -m horovod_tpu.timeline merge 'tl.json*' -o merged.json
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
from typing import List, Optional, Tuple

__all__ = ["load_trace", "merge_traces", "main"]

#: pid band per input file — tensors per rank stay comfortably below it.
_PID_BAND = 100000


def load_trace(path: str) -> List[dict]:
    """Lenient Chrome-trace reader: accepts the terminated (valid JSON)
    form, the streaming unterminated form (trailing comma, no ``]`` —
    what a crashed or still-running writer leaves), and a rotated file."""
    with open(path, encoding="utf-8", errors="replace") as fh:
        text = fh.read()
    try:
        return json.loads(text)
    except ValueError:
        pass
    events = []
    for line in text.splitlines():
        line = line.strip().rstrip(",")
        if not line.startswith("{"):
            continue
        try:
            events.append(json.loads(line))
        except ValueError:
            continue  # a torn final line from a crash
    return events


def _meta(events: List[dict]) -> Tuple[int, int, int]:
    """(rank, mono_base_us, clock_offset_us) from the horovod_meta
    header; zeros when absent (pre-offset trace: merge still works, the
    tracks just share one unaligned axis)."""
    for e in events:
        if e.get("name") == "horovod_meta" and e.get("ph") == "M":
            a = e.get("args", {})
            return (int(a.get("rank", 0)), int(a.get("mono_base_us", 0)),
                    int(a.get("clock_offset_us", 0)))
    return (0, 0, 0)


def merge_traces(paths: List[str]) -> List[dict]:
    """Merge per-rank traces into one event list on rank 0's clock.

    Offsets shift every file's ts to rank-0 monotonic time, then the
    whole merged axis is rebased so the earliest event sits at ts=0 —
    after alignment no span crosses zero (asserted by the tests)."""
    loaded = []
    for path in paths:
        events = load_trace(path)
        rank, base_us, off_us = _meta(events)
        loaded.append((path, rank, base_us + off_us, events))
    # Distinct pid bands per file, ordered by rank for stable display.
    loaded.sort(key=lambda t: (t[1], t[0]))
    shifts = []
    for _, _, shift, events in loaded:
        ts = [e["ts"] for e in events if "ts" in e]
        if ts:
            shifts.append(shift + min(ts))
    t0 = min(shifts) if shifts else 0
    merged: List[dict] = []
    for idx, (_, rank, shift, events) in enumerate(loaded):
        band = idx * _PID_BAND
        for e in events:
            e = dict(e)
            if e.get("name") == "horovod_meta":
                # Keep one meta per file for provenance, band-tagged.
                e.setdefault("args", {})["pid_band"] = band
            if "ts" in e:
                e["ts"] = e["ts"] + shift - t0
            if "pid" in e:
                e["pid"] = e["pid"] + band
            if (e.get("name") == "process_name" and e.get("ph") == "M"
                    and "args" in e):
                e["args"] = dict(e["args"])
                e["args"]["name"] = f"r{rank}/{e['args'].get('name', '')}"
            merged.append(e)
    merged.sort(key=lambda e: (e.get("ts", -1), e.get("pid", 0)))
    return merged


def check_flows(events: List[dict]) -> Tuple[int, int, List[str]]:
    """(flow sources, flow sinks, sink ids with NO matching source) —
    the merged-trace join the observability tests assert on."""
    sources = {e.get("id") for e in events if e.get("ph") == "s"}
    sinks = [e for e in events if e.get("ph") == "f"]
    unresolved = sorted({str(e.get("id")) for e in sinks
                         if e.get("id") not in sources})
    return (len(sources), len(sinks), unresolved)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.timeline",
        description="Timeline tools (docs/timeline.md).")
    sub = parser.add_subparsers(dest="cmd", required=True)
    m = sub.add_parser("merge", help="merge per-rank traces into one "
                                     "rank-0-aligned Chrome trace")
    m.add_argument("inputs", nargs="+",
                   help="per-rank timeline files (globs ok): the "
                        "HOROVOD_TIMELINE path + its .rank<r> siblings")
    m.add_argument("-o", "--output", default="merged_timeline.json")
    args = parser.parse_args(argv)

    paths: List[str] = []
    for pattern in args.inputs:
        hits = sorted(glob.glob(pattern))
        paths.extend(hits if hits else [pattern])
    # De-dup while keeping order (a glob often re-matches explicit args).
    seen = set()
    paths = [p for p in paths if not (p in seen or seen.add(p))]
    merged = merge_traces(paths)
    with open(args.output, "w", encoding="utf-8") as fh:
        json.dump(merged, fh)
    nsrc, nsink, unresolved = check_flows(merged)
    print(f"merged {len(paths)} trace(s), {len(merged)} events -> "
          f"{args.output} (flows: {nsrc} sources, {nsink} sinks"
          + (f", {len(unresolved)} UNRESOLVED: {unresolved[:5]}"
             if unresolved else "") + ")")
    return 0 if not unresolved else 2


if __name__ == "__main__":
    sys.exit(main())
