"""Bounded spike (round 5): can a Pallas matmul+BN-stats kernel beat
XLA's fused conv+stats on ResNet's bottleneck shapes?

Context (docs/perf-notes.md): ResNet MFU has been flat at 0.3047 for
three rounds.  The trace shows XLA already fuses BN statistics into every
conv's epilogue; the fwd+BN group sustains ~44 TF/s vs ~81 TF/s for the
pure conv chain.  The one remaining idea is a hand-written Pallas kernel
keeping the stats accumulators VMEM-resident across output tiles
(the MLPerf-class trick).  This spike implements that kernel for the
1x1 bottleneck convs (which are matmuls — the only conv family Pallas
can express without an im2col blowup) on the real stage-2 shapes, and
A/Bs it against XLA's own conv+stats on chained end-to-end loops
(microbenches through the tunnel are dispatch-dominated — memory:
tpu-environment-landmines).

Run:  python experiments/pallas_conv_bn_spike.py        (needs the TPU)
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Stage-2 bottleneck 1x1 shapes at the bench's batch 256:
# x: [256, 28, 28, 512] -> 1x1 conv -> [256, 28, 28, 128]
B, H, W, K, C = 256, 28, 28, 512, 128
N = B * H * W              # 200704 rows
BN_ROWS = 512              # row tile
BK = 512                   # full K in one step (512 fits VMEM easily)
REPEATS = 12               # chained iterations per timed call


def _kernel(x_ref, w_ref, y_ref, s1_ref, s2_ref):
    """One [BN_ROWS, K] x [K, C] tile: matmul in f32, write bf16 y, and
    accumulate per-channel sum / sum-of-squares into VMEM-resident
    accumulators shared across the whole row grid (grid dim is
    'arbitrary' = sequential on a TPU core, so += across steps is
    well-defined)."""
    i = pl.program_id(0)
    y = jnp.dot(x_ref[...], w_ref[...],
                preferred_element_type=jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)

    @pl.when(i == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    s1_ref[...] += jnp.sum(y, axis=0, keepdims=True)
    s2_ref[...] += jnp.sum(y * y, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=())
def pallas_conv_stats(x2d, w):
    grid = (N // BN_ROWS,)
    y, s1, s2 = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((BN_ROWS, K), lambda i: (i, 0)),
            pl.BlockSpec((K, C), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BN_ROWS, C), lambda i: (i, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, C), jnp.bfloat16),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(x2d, w)
    mean = s1[0] / N
    var = s2[0] / N - mean * mean
    return y, mean, var


@jax.jit
def xla_conv_stats(x4d, w4d):
    y = jax.lax.conv_general_dilated(
        x4d, w4d, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    mean = jnp.mean(y, axis=(0, 1, 2))
    var = jnp.mean(y * y, axis=(0, 1, 2)) - mean * mean
    return y.astype(jnp.bfloat16), mean, var


@jax.jit
def xla_conv_only(x4d, w4d):
    y = jax.lax.conv_general_dilated(
        x4d, w4d, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    return y.astype(jnp.bfloat16)


def _chain(one_step, x, w, shape_w):
    """REPEATS dependent iterations: each step's stats perturb the next
    step's weights (real data dependency, negligible FLOPs) so the chain
    can't be DCE'd or overlapped away — end-to-end A/B per the
    tunnel-microbench landmine."""

    def body(carry, _):
        w = carry
        out = one_step(w)
        y, mean, var = out if isinstance(out, tuple) else (out, None, None)
        if mean is None:
            mean = y[0, :C].astype(jnp.float32) if y.ndim == 2 \
                else y[0, 0, 0, :].astype(jnp.float32)
            var = mean
        w = w + (1e-12 * mean)[None, :].astype(w.dtype)  # [C] -> [K, C]
        return w, y[..., 0].sum()

    return jax.lax.scan(body, w, None, length=REPEATS)


def time_it(fn, *args, warmup=2, reps=3):
    f = jax.jit(fn)
    for _ in range(warmup):
        out = f(*args)
    jax.tree.map(lambda a: a.block_until_ready(), out)
    float(jax.tree.leaves(out)[-1].sum().astype(jnp.float32))
    dts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = f(*args)
        float(jax.tree.leaves(out)[-1].sum().astype(jnp.float32))
        dts.append(time.perf_counter() - t0)
    return sorted(dts)[len(dts) // 2]


def main(arm: str):
    assert jax.default_backend() == "tpu", jax.default_backend()
    rng = np.random.default_rng(0)
    x2d = jnp.asarray(rng.standard_normal((N, K)), jnp.bfloat16)
    w2d = jnp.asarray(0.05 * rng.standard_normal((K, C)), jnp.bfloat16)
    x4d = x2d.reshape(B, H, W, K)
    w4d = w2d.reshape(1, 1, K, C)

    flops = 2.0 * N * K * C * REPEATS

    if arm == "check":
        y_p, m_p, v_p = jax.jit(pallas_conv_stats)(x2d, w2d)
        y_x, m_x, v_x = xla_conv_stats(x4d, w4d)
        np.testing.assert_allclose(np.asarray(m_p), np.asarray(m_x),
                                   rtol=2e-2, atol=2e-2)
        np.testing.assert_allclose(
            np.asarray(y_p, np.float32).reshape(B, H, W, C)[:2],
            np.asarray(y_x, np.float32)[:2], rtol=5e-2, atol=5e-2)
        print("correctness ok", flush=True)
        return

    # Remote compile through the tunnel takes minutes per chain; each arm
    # therefore runs as its OWN invocation (argv) with its own budget.
    arms = {
        "pallas": lambda w: _chain(lambda v: pallas_conv_stats(x2d, v),
                                   x2d, w, (K, C)),
        "xla": lambda w: _chain(
            lambda v: xla_conv_stats(x4d, v.reshape(1, 1, K, C)),
            x2d, w, (K, C)),
        "conv_only": lambda w: _chain(
            lambda v: xla_conv_only(x4d, v.reshape(1, 1, K, C)),
            x2d, w, (K, C)),
    }
    dt = time_it(arms[arm], w2d)
    print(f"ARM {arm} ms {dt*1e3:.2f} tflops {flops/dt/1e12:.1f}",
          flush=True)


if __name__ == "__main__":
    import sys as _sys

    main(_sys.argv[1] if len(_sys.argv) > 1 else "check")
