"""Build hook: compile the native engine at install time.

Reference parity: the reference's 765-line setup.py exists to probe
MPI/CUDA/NCCL/TF/torch toolchains and build four C++ extensions
(reference setup.py:32-35, 244-465).  None of that probing applies here —
the TPU-native engine (``horovod_tpu/cpp``) depends only on a C++17
compiler and pthreads — so the build step is a ``make`` invocation that
produces ``libhorovod_core.so`` inside the package tree.  If the compile
fails (no compiler on the install host) the install still succeeds and the
runtime falls back to the lazy build in
``horovod_tpu/common/native_build.py`` or pure-Python single-process mode.
"""

import subprocess
import sys
from pathlib import Path

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildPyWithNative(build_py):
    def run(self):
        cpp = Path(__file__).parent / "horovod_tpu" / "cpp"
        try:
            subprocess.run(["make", "-C", str(cpp)], check=True)
        except (OSError, subprocess.CalledProcessError) as exc:
            print(
                f"warning: native engine build failed ({exc}); "
                "the runtime will retry lazily or run without the C++ core",
                file=sys.stderr,
            )
        super().run()


setup(cmdclass={"build_py": BuildPyWithNative})
