"""Host-engine data-plane benchmark: throughput of the TCP ring engine
under the torch and TF frontends at 2 and 4 ranks.

Role parity with the reference's benchmark methodology
(``examples/pytorch_synthetic_benchmark.py:96-110`` — timed fwd+bwd+step
loops, img/sec), applied to the part of THIS stack the main ``bench.py``
does not exercise: the native TCP engine serving the host frontends
(torch hooks, TF grouped allreduce).  The numbers are CPU-host numbers by
design — they track frontend + negotiation + ring-collective overhead,
so hot-path regressions (e.g. a fusion/batching break) become visible as
throughput drops.

The TF step loop runs twice per world size — negotiation response cache
ON (the default) and OFF (``HOROVOD_CACHE_CAPACITY=0``) — and reports
``control_round_trips_per_step`` alongside step time, so the control
plane's contribution is separable from the data plane's.

An allreduce size sweep (4 KB → 64 MB, 2 and 4 ranks) additionally
reports the data plane's bus bandwidth (NCCL convention:
``2(N-1)/N · bytes / wall``, wall from the native engine's own
``allreduce_ns`` counter so Python overhead is excluded) with the
multi-channel fan-out (``HOROVOD_NUM_CHANNELS=4``) and with the
single-channel legacy path (``..._1ch``), plus the small-allreduce
latency at 2 ranks on the single-channel path (the PR 2 control-plane
number, guarded against regression).

Prints ONE JSON line, e.g.::

    {"metric": "engine_data_plane", "torch_img_per_sec": {"2": ..,
     "4": ..}, "tf_img_per_sec": {"2": .., "4": ..},
     "tf_step_ms": {"2": .., "4": ..},
     "tf_step_ms_nocache": {"2": .., "4": ..},
     "control_round_trips_per_step": {"2": .., "4": ..},
     "control_round_trips_per_step_nocache": {"2": .., "4": ..},
     "allreduce_bus_bw_mb_s": {"2": {"4KB": .., ..}, "4": {..}},
     "allreduce_bus_bw_mb_s_1ch": {"2": {..}, "4": {..}},
     "allreduce_bus_bw_mb_s_shm": {"2": {..}, "4": {..}},
     "allreduce_small_latency_ms": {"2": ..},
     "allreduce_small_latency_ms_shm": {"2": ..},
     "algo_threshold_sweep": {"256B": {"star": .., "ring": ..}, ..},
     "allreduce_effective_bus_bw_mb_s_fp32": {"2": {..}, "4": {..}},
     "allreduce_effective_bus_bw_mb_s_fp16": {..},
     "allreduce_effective_bus_bw_mb_s_int8": {..},
     "wire_bytes_ratio_fp16": {"2": {..}, "4": {..}},
     "wire_bytes_ratio_int8": {"2": {..}, "4": {..}}}

The wire sweep (``HOROVOD_WIRE_DTYPE`` compression) reports EFFECTIVE
bus bandwidth — logical pre-compression bytes over wall time, since
``allreduce_bytes`` counts logical payload by design — plus the
deterministic per-rank ``data_bytes_tx`` ratio vs the fp32 wire, which
is what the ci compression gate judges (wall time on this loopback-
ceilinged box is noise; byte counters are exact).

The TCP-plane keys (``allreduce_bus_bw_mb_s``/``_1ch`` and
``allreduce_small_latency_ms``) pin ``HOROVOD_SHM_DISABLE=1`` so they
stay comparable with the pre-shm trajectory; the ``_shm`` variants
measure the default plane (shm flat ring + size-based algorithm
selection), and ``algo_threshold_sweep`` interleaves the star and ring
paths per payload size so the crossover is visible.

``bench.py`` merges these keys into the bench artifact under an
``engine_`` prefix; standalone use: ``python bench_engine.py``.

``python bench_engine.py --gate`` runs the CI data-plane gate instead:
one 4-rank worker set alternates channels=4 / channels=1 in-process
(shutdown + re-init between rounds, so slow machine drift hits both
configs equally) on 16 MB allreduces and fails loudly when the median
bandwidth ratio falls below the gate threshold.  ``--shm-gate`` is the
shm analogue: alternate shm on / off in-process on the small-allreduce
latency (2 ranks) and 16 MB bus bandwidth (4 ranks), judged as a
regression floor on the best interleaved round.
"""

from __future__ import annotations

import json
import os
import re
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


# ---------------------------------------------------------------------------
# TF worker (run as: bench_engine.py --tf-worker)
# ---------------------------------------------------------------------------

def _tf_worker() -> None:
    """MNIST-shaped training step over DistributedGradientTape: every
    dense gradient rides the grouped single-cycle allreduce
    (``horovod_tpu/tf/mpi_ops.py``)."""
    import numpy as np
    import tensorflow as tf

    sys.path.insert(0, REPO)
    import horovod_tpu.tf as hvd

    hvd.init()
    tf.keras.utils.set_random_seed(1 + hvd.rank())
    model = tf.keras.Sequential([
        tf.keras.layers.Dense(128, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    model(tf.zeros([1, 784]))
    hvd.broadcast_variables(model.trainable_variables, root_rank=0)
    opt = tf.keras.optimizers.SGD(0.01 * hvd.size())
    batch = 32
    rng = np.random.default_rng(7 + hvd.rank())
    X = tf.constant(rng.standard_normal((batch, 784)), dtype=tf.float32)
    Y = tf.constant(rng.integers(0, 10, batch), dtype=tf.int64)

    def step():
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            logits = model(X)
            loss = tf.reduce_mean(
                tf.nn.sparse_softmax_cross_entropy_with_logits(
                    labels=Y, logits=logits))
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))

    for _ in range(3):
        step()
    from horovod_tpu.runtime import engine_or_none

    eng = engine_or_none()
    iters = int(os.environ.get("HOROVOD_SMOKE_STEPS", "30"))
    before = eng.stats() if eng is not None else {}
    t0 = time.perf_counter()
    for _ in range(iters):
        step()
    dt = time.perf_counter() - t0
    after = eng.stats() if eng is not None else {}
    rt_per_step = (after.get("control_round_trips", 0)
                   - before.get("control_round_trips", 0)) / iters
    # Priority-scheduling instrument: inversions per step over the
    # measured window (0 by construction with HOROVOD_PRIORITY_BANDS on;
    # the legacy arrival ordering's count under HOROVOD_PRIORITY_STAMP=1
    # is the motivation metric).
    inv_per_step = (after.get("priority_inversions", 0)
                    - before.get("priority_inversions", 0)) / iters
    if hvd.rank() == 0:
        print(f"TF_STEP_MS {dt / iters * 1e3:.3f} "
              f"TF_IMG_PER_SEC {batch * hvd.size() * iters / dt:.1f} "
              f"TF_RT_PER_STEP {rt_per_step:.2f} "
              f"TF_PRIO_INV_PER_STEP {inv_per_step:.3f}",
              flush=True)
    hvd.shutdown()


# ---------------------------------------------------------------------------
# allreduce sweep / latency / gate workers (numpy + native engine only)
# ---------------------------------------------------------------------------

def _engine_setup():
    sys.path.insert(0, REPO)
    import numpy as np  # noqa: F401

    from horovod_tpu.common.basics import basics
    from horovod_tpu.runtime.engine import get_engine

    basics.init()
    return basics, get_engine()


def _measure_bus_bw_mb_s(basics, eng, nbytes: int, iters: int) -> float:
    """Bus bandwidth over `iters` allreduces from the engine's own
    allreduce byte/wall counters (NCCL busbw convention), via the
    stats_delta helper the autotuner scores trials with."""
    import numpy as np

    n = max(1, nbytes // 4)
    x = np.ones(n, dtype=np.float32)
    eng.allreduce(x.copy(), name="sweep.warm")
    before = eng.stats()
    for i in range(iters):
        eng.synchronize(eng.enqueue_allreduce(x.copy(), name="sweep.t"))
    return eng.stats_delta(before)["allreduce_bus_bw_bytes_per_sec"] / 1e6


def _sweep_worker() -> None:
    basics, eng = _engine_setup()
    nbytes = int(os.environ["BENCH_SWEEP_BYTES"])
    iters = max(2, min(30, (32 << 20) // max(nbytes, 1)))
    bw = _measure_bus_bw_mb_s(basics, eng, nbytes, iters)
    if basics.rank() == 0:
        print(f"SWEEP_BUS_MB_S {bw:.1f}", flush=True)
    basics.shutdown()


def _fleet_worker() -> None:
    """Fleet-telemetry snapshot source for the BENCH json: a short
    4-rank workload with per-cycle TELEM, quiesced so the fleet table
    converges, then rank 0 prints the table (the soak trend artifacts
    of ROADMAP item 5 ride these `fleet_` keys)."""
    import json as _json
    import time as _time

    import numpy as np

    basics, eng = _engine_setup()
    x = np.ones(1 << 16, dtype=np.float32)
    for i in range(12):
        eng.allreduce(x.copy(), name=f"fleet.t{i % 3}")
    eng.allreduce(np.ones(4, dtype=np.float32), name="fleet.barrier")
    _time.sleep(1.0)  # idle cycles flush the final TELEM deltas
    if basics.rank() == 0:
        _time.sleep(0.3)
        print("FLEET_SNAPSHOT " + _json.dumps(basics.fleet_stats()),
              flush=True)
    else:
        _time.sleep(0.5)
    basics.shutdown()


def _rs_sweep_worker() -> None:
    """Reduce-scatter bus bandwidth ((N-1)/N · bytes / wall — half the
    allreduce numerator, matching the RS wire pattern) from the
    engine's deterministic reducescatter counters."""
    import numpy as np

    basics, eng = _engine_setup()
    nbytes = int(os.environ["BENCH_SWEEP_BYTES"])
    n = max(1, nbytes // 4)
    iters = max(2, min(30, (32 << 20) // max(nbytes, 1)))
    x = np.ones(n, dtype=np.float32)
    eng.reducescatter(x, name="rs.sweep.warm")
    before = eng.stats()
    for _ in range(iters):
        eng.synchronize(eng.enqueue_reducescatter(x, name="rs.sweep.t"))
    d = eng.stats_delta(before)
    if basics.rank() == 0:
        print(f"RS_SWEEP_BUS_MB_S "
              f"{d['reducescatter_bus_bw_bytes_per_sec'] / 1e6:.1f} "
              f"FALLBACKS {d['reducescatter_fallbacks']}", flush=True)
    basics.shutdown()


def _alltoall_sweep_worker() -> None:
    """Alltoall bus bandwidth ((N-1)/N · bytes / wall — each rank keeps
    its own block, so that's the fraction crossing the wire) from the
    engine's deterministic alltoall counters.  Equal splits: the sweep
    measures the transport, not the split negotiation (the variable-
    split cases are correctness-gated in the moe marker)."""
    import numpy as np

    basics, eng = _engine_setup()
    nbytes = int(os.environ["BENCH_SWEEP_BYTES"])
    size = basics.size()
    n = max(size, nbytes // 4 // size * size)  # divisible by the world
    iters = max(2, min(30, (32 << 20) // max(nbytes, 1)))
    x = np.ones(n, dtype=np.float32)
    eng.alltoall(x, name="a2a.sweep.warm")
    before = eng.stats()
    for _ in range(iters):
        eng.synchronize(eng.enqueue_alltoall(x, name="a2a.sweep.t"))
    d = eng.stats_delta(before)
    if basics.rank() == 0:
        print(f"A2A_SWEEP_BUS_MB_S "
              f"{d['alltoall_bus_bw_bytes_per_sec'] / 1e6:.1f}",
              flush=True)
    basics.shutdown()


def _sharded_bytes_worker() -> None:
    """Per-step wire accounting of the ZeRO sharded step vs the
    unsharded allreduce, on the deterministic byte counters: the
    gradient reduce-scatter (the gate metric, ~0.5x by construction)
    and the FULL step incl. the parameter allgather (~1.0x — the honest
    ZeRO number; memory, not bytes, is the lever)."""
    import numpy as np

    from horovod_tpu.runtime.sharded import FlatSharder

    basics, eng = _engine_setup()
    n = int(os.environ.get("BENCH_SHARDED_ELEMS", str(1 << 20)))
    sharder = FlatSharder(n, np.float32, name="bench.zero")
    g = np.ones(n, dtype=np.float32)
    # Warm both paths (wiring, fusion scratch).
    eng.allreduce(g.copy(), name="zb.warm")
    sharder.step(g, lambda s: s, average=True)
    steps = 4
    s0 = eng.stats()
    for _ in range(steps):
        eng.allreduce(g.copy(), average=True, name="zb.ar")
    ar_tx = eng.stats_delta(s0)["data_bytes_tx"]
    s1 = eng.stats()
    shard = None
    for _ in range(steps):
        shard = sharder.reduce_grads(g, average=True)
    rs_tx = eng.stats_delta(s1)["data_bytes_tx"]
    s2 = eng.stats()
    for _ in range(steps):
        sharder.gather_updates(shard)
    ag_tx = eng.stats_delta(s2)["data_bytes_tx"]
    if basics.rank() == 0:
        print(f"SHARDED_BYTES ar_tx {ar_tx} rs_tx {rs_tx} "
              f"ag_tx {ag_tx}", flush=True)
    basics.shutdown()


def _latency_worker() -> None:
    import numpy as np

    basics, eng = _engine_setup()
    x = np.ones(1, dtype=np.float32)
    for _ in range(5):
        eng.allreduce(x.copy(), name="lat.warm")
    iters = 100
    t0 = time.perf_counter()
    for _ in range(iters):
        eng.synchronize(eng.enqueue_allreduce(x.copy(), name="lat.t"))
    dt = time.perf_counter() - t0
    if basics.rank() == 0:
        print(f"LATENCY_MS {dt / iters * 1e3:.3f}", flush=True)
    basics.shutdown()


def _link_heal_bench_worker() -> None:
    """Busbw + heal-latency under a seeded flap schedule (the test's
    conn-reset fault kind, recurring): the run must complete with ZERO
    aborts while edges break and heal, and rank 0 reports the engine's
    link_heal percentiles next to the flap-loaded bus bandwidth."""
    import numpy as np

    basics, eng = _engine_setup()
    nbytes = int(os.environ.get("BENCH_SWEEP_BYTES", str(1 << 20)))
    n = max(1, nbytes // 4)
    x = np.ones(n, dtype=np.float32)
    eng.allreduce(x.copy(), name="link.warm")
    before = eng.stats()
    for _ in range(40):
        eng.synchronize(eng.enqueue_allreduce(x.copy(), name="link.t"))
    d = eng.stats_delta(before)
    st = eng.stats()
    assert eng.abort_reason() == "", eng.abort_reason()
    assert st["link_heal_failures"] == 0, st["link_heal_failures"]
    if basics.rank() == 0:
        print(f"LINK_BENCH BUS_MB_S "
              f"{d['allreduce_bus_bw_bytes_per_sec'] / 1e6:.1f} "
              f"HEAL_MS_P50 {st['link_heal_ns_p50'] / 1e6:.3f} "
              f"RECONNECTS {st['link_reconnects']}", flush=True)
    basics.shutdown()


def _gate_worker() -> None:
    """Alternate channels=4 / channels=1 IN-PROCESS (re-init between
    rounds) so machine drift hits both configs; print the per-round
    bandwidth pairs for the driver to judge."""
    basics, eng = _engine_setup()
    nbytes = 16 << 20
    rounds = int(os.environ.get("BENCH_GATE_ROUNDS", "3"))
    pairs = []
    for _ in range(rounds):
        os.environ["HOROVOD_NUM_CHANNELS"] = "4"
        basics.shutdown()
        basics.init()
        multi = _measure_bus_bw_mb_s(basics, eng, nbytes, 5)
        os.environ["HOROVOD_NUM_CHANNELS"] = "1"
        basics.shutdown()
        basics.init()
        single = _measure_bus_bw_mb_s(basics, eng, nbytes, 5)
        pairs.append((multi, single))
    if basics.rank() == 0:
        for multi, single in pairs:
            print(f"GATE_PAIR {multi:.1f} {single:.1f}", flush=True)
    basics.shutdown()


def _shm_gate_worker() -> None:
    """Alternate shm ON / shm OFF in-process (re-init between rounds, so
    ambient-load drift hits both transports): per round, the small-
    allreduce latency and/or the 16 MB bus bandwidth under each —
    BENCH_GATE_METRIC=lat|bw measures only the judged metric (the gate
    judges one per world size; measuring the other would double the
    wall time inside ci.sh's hard timeout).  The driver judges the
    pairs."""
    import numpy as np

    basics, eng = _engine_setup()
    metric = os.environ.get("BENCH_GATE_METRIC", "both")

    def lat_ms(iters=100):
        x = np.ones(1, dtype=np.float32)
        for _ in range(5):
            eng.allreduce(x.copy(), name="sg.w")
        t0 = time.perf_counter()
        for _ in range(iters):
            eng.synchronize(eng.enqueue_allreduce(x.copy(), name="sg.t"))
        return (time.perf_counter() - t0) / iters * 1e3

    def bw_mb_s():
        return _measure_bus_bw_mb_s(basics, eng, 16 << 20, 5)

    rounds = int(os.environ.get("BENCH_GATE_ROUNDS", "3"))
    pairs = []
    for _ in range(rounds):
        os.environ.pop("HOROVOD_SHM_DISABLE", None)
        basics.shutdown()
        basics.init()
        assert eng.stats()["config"]["shm_enabled"], "shm did not engage"
        s_lat = lat_ms() if metric != "bw" else 0.0
        s_bw = bw_mb_s() if metric != "lat" else 0.0
        os.environ["HOROVOD_SHM_DISABLE"] = "1"
        basics.shutdown()
        basics.init()
        t_lat = lat_ms() if metric != "bw" else 0.0
        t_bw = bw_mb_s() if metric != "lat" else 0.0
        pairs.append((s_lat, t_lat, s_bw, t_bw))
    if basics.rank() == 0:
        for s_lat, t_lat, s_bw, t_bw in pairs:
            print(f"SHM_GATE_PAIR lat {s_lat:.3f} {t_lat:.3f} "
                  f"bw {s_bw:.1f} {t_bw:.1f}", flush=True)
    basics.shutdown()


def _wire_sweep_worker() -> None:
    """One wire-dtype point of the compression sweep: EFFECTIVE bus
    bandwidth (logical pre-compression bytes over the engine's own wall
    counter — allreduce_bytes is logical by design, so the standard
    busbw computation already measures effectiveness) plus this rank's
    data_bytes_tx for the deterministic byte-ratio keys."""
    import numpy as np

    basics, eng = _engine_setup()
    nbytes = int(os.environ["BENCH_SWEEP_BYTES"])
    wd = os.environ.get("BENCH_WIRE_DTYPE", "fp32")
    iters = max(2, min(30, (32 << 20) // max(nbytes, 1)))
    n = max(1, nbytes // 4)
    x = np.ones(n, dtype=np.float32)
    eng.allreduce(x.copy(), name="wsweep.warm", wire_dtype=wd)
    before = eng.stats()
    for _ in range(iters):
        eng.synchronize(eng.enqueue_allreduce(x.copy(), name="wsweep.t",
                                              wire_dtype=wd))
    delta = eng.stats_delta(before)
    bw = delta["allreduce_bus_bw_bytes_per_sec"] / 1e6
    if basics.rank() == 0:
        print(f"WIRE_SWEEP_BUS_MB_S {bw:.1f} TX {delta['data_bytes_tx']}",
              flush=True)
    basics.shutdown()


def _wire_gate_worker() -> None:
    """CI compression-gate body: the DETERMINISTIC byte-counter ratio on
    a 16 MB fp32 allreduce — int8 wire vs fp32 wire data_bytes_tx — plus
    the counter sanity the gate asserts on.  Byte counters, not wall
    time: loopback is CPU-ceilinged and noisy (docs/performance.md), but
    the bytes a wire format moves are exact."""
    import numpy as np

    basics, eng = _engine_setup()
    n = (16 << 20) // 4
    x = np.ones(n, dtype=np.float32)
    s0 = eng.stats()
    out = eng.allreduce(x.copy(), name="wg.fp32")
    assert np.allclose(out, float(basics.size()))
    s1 = eng.stats()
    out = eng.allreduce(x.copy(), name="wg.int8", wire_dtype="int8")
    assert np.allclose(out, float(basics.size()), atol=1e-2)
    s2 = eng.stats()
    fp32_tx = s1["data_bytes_tx"] - s0["data_bytes_tx"]
    int8_tx = s2["data_bytes_tx"] - s1["data_bytes_tx"]
    assert s2["wire_int8_count"] - s1["wire_int8_count"] == 1, s2
    assert s2["compressed_bytes_tx"] > s1["compressed_bytes_tx"], s2
    if basics.rank() == 0:
        print(f"WIRE_GATE_TX fp32 {fp32_tx} int8 {int8_tx}", flush=True)
    basics.shutdown()


def _algo_sweep_worker() -> None:
    """Per-payload-size latency with the star path engaged (threshold
    above every size) vs disabled (pure ring), interleaved in-process:
    the table shows where the latency/bandwidth crossover actually sits
    on this host."""
    import numpy as np

    basics, eng = _engine_setup()
    sizes = [("256B", 256), ("4KB", 4 << 10), ("32KB", 32 << 10),
             ("256KB", 256 << 10)]

    def lat_ms(nbytes, iters=60):
        x = np.ones(max(1, nbytes // 4), dtype=np.float32)
        for _ in range(3):
            eng.allreduce(x.copy(), name="as.w")
        t0 = time.perf_counter()
        for _ in range(iters):
            eng.synchronize(eng.enqueue_allreduce(x.copy(), name="as.t"))
        return (time.perf_counter() - t0) / iters * 1e3

    rows = []
    for label, nbytes in sizes:
        os.environ["HOROVOD_ALGO_THRESHOLD"] = str(1 << 20)
        basics.shutdown()
        basics.init()
        star = lat_ms(nbytes)
        os.environ["HOROVOD_ALGO_THRESHOLD"] = "0"
        basics.shutdown()
        basics.init()
        ring = lat_ms(nbytes)
        rows.append((label, star, ring))
    if basics.rank() == 0:
        for label, star, ring in rows:
            print(f"ALGO_SWEEP {label} {star:.3f} {ring:.3f}", flush=True)
    basics.shutdown()


# ---------------------------------------------------------------------------
# autotune workers (online knob search; see docs/autotune.md)
# ---------------------------------------------------------------------------

def _converge_autotuner(basics, eng, step_bytes: int, max_steps: int = 5000):
    """Drive allreduce traffic until rank 0's tuner converges; the stop
    is broadcast-driven so every rank exits on the same step.  Returns
    rank 0's tuner (None elsewhere)."""
    import numpy as np

    from horovod_tpu.autotune import get_tuner

    tuner = get_tuner() if basics.rank() == 0 else None
    if basics.rank() == 0:
        assert tuner is not None, "HOROVOD_AUTOTUNE=1 did not start a tuner"
    x = np.ones(max(1, step_bytes // 4), dtype=np.float32)
    keep, steps = 1, 0
    while keep:
        eng.synchronize(eng.enqueue_allreduce(x.copy(), name="at.bench.t"))
        steps += 1
        if basics.rank() == 0:
            keep = 0 if (tuner.converged or steps >= max_steps) else 1
        flag = eng.broadcast(np.asarray([keep], dtype="int8"), root_rank=0,
                             name="at.bench.ctl")
        keep = int(flag[0])
    if basics.rank() == 0:
        assert tuner.converged, f"tuner did not converge in {steps} steps"
    return tuner


def _apply_config_all(basics, eng, cfg: dict, last_tt: int) -> int:
    """rank 0 queues a TUNE; EVERY rank waits for its own application
    (the frame lands on all ranks at the same cycle boundary), so the
    next measurement runs under the new config everywhere.  Returns the
    new tune_trials watermark."""
    if basics.rank() == 0:
        assert eng.autotune_set(
            chunk_bytes=cfg.get("chunk_bytes", 0),
            fusion_threshold=cfg.get("fusion_threshold", 0),
            cycle_time_ms=cfg.get("cycle_time_ms", 0),
            wave_width=cfg.get("wave_width", 0))
    deadline = time.time() + 20
    while eng.stats()["tune_trials"] <= last_tt:
        assert time.time() < deadline, "TUNE frame never applied"
        time.sleep(0.002)
    return eng.stats()["tune_trials"]


#: Static chunk-size grid the gate compares the committed config
#: against (the sweep dimension PR 4 measured the big busbw swings on).
_GATE_GRID = [256 << 10, 1 << 20, 4 << 20]


def _autotune_worker() -> None:
    """Bench body: converge the online search, then measure the committed
    config's 16 MB bus bandwidth (same methodology as the static sweep
    numbers it prints next to)."""
    import json as _json

    from horovod_tpu.autotune import stop_autotuner

    basics, eng = _engine_setup()
    tuner = _converge_autotuner(basics, eng, step_bytes=4 << 20)
    if basics.rank() == 0:
        # Freeze the regression watcher: an ambient-load dip during the
        # measurement could otherwise re-open the search and flip knobs
        # underneath it (the gate worker does the same).
        stop_autotuner()
    bw = _measure_bus_bw_mb_s(basics, eng, 16 << 20, 5)
    if basics.rank() == 0:
        print(f"AUTOTUNE_BUS_MB_S {bw:.1f} TRIALS {len(tuner.trace)} "
              f"CONFIG {_json.dumps(tuner.committed, sort_keys=True)}",
              flush=True)
    basics.shutdown()


def _autotune_gate_worker() -> None:
    """CI gate body: converge, stop the tuner (so the regression watcher
    cannot fight the measurement flips), then interleave rounds of the
    committed config against each static grid point — alternation means
    machine drift hits both sides equally, exactly like the data-plane
    gate."""
    import json as _json

    from horovod_tpu.autotune import stop_autotuner

    basics, eng = _engine_setup()
    tuner = _converge_autotuner(basics, eng, step_bytes=4 << 20)
    committed = dict(tuner.committed) if basics.rank() == 0 else None
    max_trials = int(os.environ.get("HOROVOD_AUTOTUNE_MAX_TRIALS", "32"))
    if basics.rank() == 0:
        assert len(tuner.trace) <= max_trials, (len(tuner.trace), max_trials)
        stop_autotuner()
    # Ship the committed config so every rank drives the same schedule.
    import numpy as np

    keys = ("chunk_bytes", "fusion_threshold", "cycle_time_ms",
            "wave_width")
    payload = np.zeros(len(keys), dtype=np.int64)
    if basics.rank() == 0:
        payload = np.asarray([committed.get(k, 0) for k in keys],
                             dtype=np.int64)
    got = eng.broadcast(payload, root_rank=0, name="at.gate.cfg")
    committed = {k: int(v) for k, v in zip(keys, got)}
    base = {k: int(v) for k, v in eng.stats()["config"].items()
            if k in keys}
    rounds = int(os.environ.get("BENCH_GATE_ROUNDS", "3"))
    nbytes = 16 << 20
    tt = eng.stats()["tune_trials"]
    for _ in range(rounds):
        # The committed config is sampled at BOTH ends of the round (the
        # statics sandwiched between): taking max-of-3 statics against a
        # single auto sample would bias the ratio down on a noisy box,
        # and a monotone drift (the box settling after the convergence
        # phase) would otherwise load entirely onto whichever side runs
        # first.
        tt = _apply_config_all(basics, eng, committed, tt)
        auto_bw = _measure_bus_bw_mb_s(basics, eng, nbytes, 4)
        static_bws = []
        for chunk in _GATE_GRID:
            tt = _apply_config_all(basics, eng, {**base,
                                                 "chunk_bytes": chunk}, tt)
            static_bws.append(_measure_bus_bw_mb_s(basics, eng, nbytes, 4))
        tt = _apply_config_all(basics, eng, committed, tt)
        auto_bw = max(auto_bw, _measure_bus_bw_mb_s(basics, eng, nbytes, 4))
        if basics.rank() == 0:
            print(f"AUTOGATE_ROUND auto={auto_bw:.1f} "
                  f"static_best={max(static_bws):.1f}", flush=True)
    if basics.rank() == 0:
        print(f"AUTOGATE_TRIALS {len(tuner.trace)} MAX {max_trials}",
              flush=True)
        print(f"AUTOGATE_CONFIG {_json.dumps(committed, sort_keys=True)}",
              flush=True)
    basics.shutdown()


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_ranks(n: int, argv: list, timeout: int = 240,
               extra_env: dict | None = None) -> str:
    """Run ``argv`` as n engine ranks; returns rank 0's stdout."""
    port = _free_port()
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(n),
            "HOROVOD_COORDINATOR": f"127.0.0.1:{port}",
            "CUDA_VISIBLE_DEVICES": "-1",
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            argv, env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out.decode(), err.decode()))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for rank, (rc, out, err) in enumerate(outs):
        if rc != 0:
            raise RuntimeError(
                f"rank {rank} failed (rc={rc}):\n{out}\n{err}")
    return outs[0][1]


_TF_LINE = re.compile(r"TF_STEP_MS ([\d.]+) TF_IMG_PER_SEC ([\d.]+)"
                      r"(?: TF_RT_PER_STEP ([\d.]+))?"
                      r"(?: TF_PRIO_INV_PER_STEP ([\d.]+))?")


def main() -> None:
    result: dict = {"metric": "engine_data_plane"}
    torch_rates: dict = {}
    tf_rates: dict = {}
    tf_step_ms: dict = {}
    tf_step_ms_nocache: dict = {}
    rt_per_step: dict = {}
    rt_per_step_nocache: dict = {}
    for n in (2, 4):
        # No --smoke: it would force num_iters to 1, and these numbers
        # exist to catch regressions — keep the 3-sample mean the
        # example reports (its ±1.96σ methodology, ref :96-110).
        out = _run_ranks(n, [
            sys.executable,
            os.path.join(REPO, "examples", "torch_synthetic_benchmark.py"),
            "--batch-size", "16", "--num-iters", "3",
            "--num-batches-per-iter", "4",
        ])
        m = re.search(r"Total img/sec on \d+ device\(s\): ([\d.]+)", out)
        if m:
            torch_rates[str(n)] = float(m.group(1))

        # TF step loop, negotiation cache ON (default) and OFF — the
        # delta isolates the control plane's share of step time, and the
        # OFF run proves the legacy path still reproduces its numbers.
        for label, env, step_dict, rt_dict in (
                ("cache", {}, tf_step_ms, rt_per_step),
                ("nocache", {"HOROVOD_CACHE_CAPACITY": "0"},
                 tf_step_ms_nocache, rt_per_step_nocache)):
            out = _run_ranks(n, [sys.executable, os.path.abspath(__file__),
                                 "--tf-worker"], extra_env=env)
            m = _TF_LINE.search(out)
            if m:
                step_dict[str(n)] = float(m.group(1))
                if label == "cache":
                    tf_rates[str(n)] = float(m.group(2))
                if m.group(3) is not None:
                    rt_dict[str(n)] = float(m.group(3))
    result["torch_img_per_sec"] = torch_rates
    result["tf_img_per_sec"] = tf_rates
    result["tf_step_ms"] = tf_step_ms
    result["tf_step_ms_nocache"] = tf_step_ms_nocache
    result["control_round_trips_per_step"] = rt_per_step
    result["control_round_trips_per_step_nocache"] = rt_per_step_nocache

    # Priority scheduling: the SAME real-model loop with bands on
    # (engine_tf_step_ms_priority — judged as a regression floor in the
    # overlap gate) and, for the motivation metric, the legacy ordering
    # with stamping forced on so priority_inversions_per_step shows what
    # banding eliminates.
    tf_step_ms_priority: dict = {}
    inv_per_step: dict = {}
    for n in (2, 4):
        out = _run_ranks(n, [sys.executable, os.path.abspath(__file__),
                             "--tf-worker"],
                         extra_env={"HOROVOD_PRIORITY_BANDS": "1"})
        m = _TF_LINE.search(out)
        if m:
            tf_step_ms_priority[str(n)] = float(m.group(1))
        out = _run_ranks(n, [sys.executable, os.path.abspath(__file__),
                             "--tf-worker"],
                         extra_env={"HOROVOD_PRIORITY_STAMP": "1",
                                    "HOROVOD_FUSION_THRESHOLD": "0"})
        m = _TF_LINE.search(out)
        if m and m.group(4) is not None:
            inv_per_step[str(n)] = float(m.group(4))
    result["tf_step_ms_priority"] = tf_step_ms_priority
    result["priority_inversions_per_step"] = inv_per_step

    # Data-plane size sweep: bus bandwidth with the channel fan-out vs the
    # single-channel legacy path (both pinned to the TCP plane for
    # trajectory comparability) vs the default shm plane, 4 KB -> 64 MB
    # at 2 and 4 ranks.
    sweep: dict = {}
    sweep_1ch: dict = {}
    sweep_shm: dict = {}
    sizes = [("4KB", 4 << 10), ("64KB", 64 << 10), ("1MB", 1 << 20),
             ("16MB", 16 << 20), ("64MB", 64 << 20)]
    for n in (2, 4):
        for dest, env in ((sweep, {"HOROVOD_NUM_CHANNELS": "4",
                                   "HOROVOD_SHM_DISABLE": "1"}),
                          (sweep_1ch, {"HOROVOD_NUM_CHANNELS": "1",
                                       "HOROVOD_SHM_DISABLE": "1"}),
                          (sweep_shm, {"HOROVOD_NUM_CHANNELS": "4"})):
            per_size = dest.setdefault(str(n), {})
            for label, nbytes in sizes:
                out = _run_ranks(n, [sys.executable, os.path.abspath(__file__),
                                     "--sweep-worker"],
                                 extra_env={**env,
                                            "BENCH_SWEEP_BYTES": str(nbytes)})
                m = re.search(r"SWEEP_BUS_MB_S ([\d.]+)", out)
                if m:
                    per_size[label] = float(m.group(1))
    result["allreduce_bus_bw_mb_s"] = sweep
    result["allreduce_bus_bw_mb_s_1ch"] = sweep_1ch
    result["allreduce_bus_bw_mb_s_shm"] = sweep_shm

    # Reduce-scatter size sweep (the ZeRO gradient half) on the default
    # plane: RS bus bandwidth = (N-1)/N · bytes / wall — directly
    # comparable to the allreduce busbw above because both normalize to
    # per-link traffic.
    rs_sweep: dict = {}
    for n in (2, 4):
        per_size = rs_sweep.setdefault(str(n), {})
        for label, nbytes in sizes:
            out = _run_ranks(n, [sys.executable, os.path.abspath(__file__),
                                 "--rs-sweep-worker"],
                             extra_env={"BENCH_SWEEP_BYTES": str(nbytes)})
            m = re.search(r"RS_SWEEP_BUS_MB_S ([\d.]+)", out)
            if m:
                per_size[label] = float(m.group(1))
    result["reducescatter_bus_bw_mb_s"] = rs_sweep

    # Alltoall size sweep (the MoE dispatch/combine transport) on the
    # default plane and the single-channel TCP baseline: alltoall busbw
    # = (N-1)/N · bytes / wall, comparable to the RS busbw above.
    a2a_sweep: dict = {}
    a2a_sweep_1ch: dict = {}
    for n in (2, 4):
        for dest, env in ((a2a_sweep, {}),
                          (a2a_sweep_1ch, {"HOROVOD_NUM_CHANNELS": "1",
                                           "HOROVOD_SHM_DISABLE": "1"})):
            per_size = dest.setdefault(str(n), {})
            for label, nbytes in sizes:
                out = _run_ranks(n, [sys.executable, os.path.abspath(__file__),
                                     "--alltoall-sweep-worker"],
                                 extra_env={**env,
                                            "BENCH_SWEEP_BYTES": str(nbytes)})
                m = re.search(r"A2A_SWEEP_BUS_MB_S ([\d.]+)", out)
                if m:
                    per_size[label] = float(m.group(1))
    result["alltoall_bus_bw_mb_s"] = a2a_sweep
    result["alltoall_bus_bw_mb_s_1ch"] = a2a_sweep_1ch

    # ZeRO step wire accounting at 4 ranks, 4 MB flat model, on the
    # deterministic byte counters: grads_rs ~0.5 (the gated half),
    # full_step ~1.0 (RS + param allgather — the honest ZeRO total).
    out = _run_ranks(4, [sys.executable, os.path.abspath(__file__),
                         "--sharded-bytes-worker"])
    m = re.search(r"SHARDED_BYTES ar_tx (\d+) rs_tx (\d+) ag_tx (\d+)",
                  out)
    if m:
        ar_tx, rs_tx, ag_tx = (int(m.group(i)) for i in (1, 2, 3))
        result["sharded_step_bytes_ratio"] = {
            "grads_rs": round(rs_tx / max(1, ar_tx), 4),
            "full_step": round((rs_tx + ag_tx) / max(1, ar_tx), 4),
        }

    # ZeRO-3/FSDP residency + prefetch at 4 ranks, on the deterministic
    # counters: peak resident param bytes / total (the 1/N lever), and
    # the allgather-prefetch hit counters from the same run.
    fsdp_worker = os.path.join(REPO, "tests", "fsdp_worker.py")
    out = _run_ranks(4, [sys.executable, fsdp_worker, "mem"],
                     timeout=300,
                     extra_env={"HOROVOD_PRIORITY_BANDS": "1"})
    pairs = re.findall(r"FSDP_MEM rank=\d+ peak=(\d+) total=(\d+)", out)
    if pairs:
        result["fsdp_param_resident_peak_ratio"] = round(
            max(int(p) / max(1, int(t)) for p, t in pairs), 4)
    out = _run_ranks(2, [sys.executable, fsdp_worker, "overlap"],
                     timeout=300,
                     extra_env={"HOROVOD_PRIORITY_BANDS": "1"})
    m = re.search(r"FSDP_OVERLAP rank=\d+ on_ms=([\d.]+) "
                  r"off_ms=([\d.]+) inversions=(\d+) "
                  r"hits=(\d+) misses=(\d+)", out)
    if m:
        result["fsdp_forward_walk_ms_prefetch_on"] = float(m.group(1))
        result["fsdp_forward_walk_ms_prefetch_off"] = float(m.group(2))
        result["fsdp_ag_prefetch_hits"] = int(m.group(4))
        result["fsdp_ag_prefetch_misses"] = int(m.group(5))

    # Single-allreduce latency at 2 ranks: single-channel TCP (the PR 2
    # control-plane number; must not regress) and the default shm plane
    # (star path — the PR 6 gated metric).
    lat: dict = {}
    for key, env in (("allreduce_small_latency_ms",
                      {"HOROVOD_NUM_CHANNELS": "1",
                       "HOROVOD_SHM_DISABLE": "1"}),
                     ("allreduce_small_latency_ms_shm", {})):
        out = _run_ranks(2, [sys.executable, os.path.abspath(__file__),
                             "--latency-worker"], extra_env=env)
        m = re.search(r"LATENCY_MS ([\d.]+)", out)
        lat[key] = {"2": float(m.group(1))} if m else {}
    result["allreduce_small_latency_ms"] = lat["allreduce_small_latency_ms"]
    result["allreduce_small_latency_ms_shm"] = \
        lat["allreduce_small_latency_ms_shm"]

    # Link self-healing under a seeded flap schedule: two ranks shoot
    # their own data sockets every 7th/11th enqueue for the whole run
    # (the conn-reset fault kind, recurring), and the job must absorb
    # every break — the keys report the median transparent-reconnect
    # latency and the bus bandwidth the flapping plane still sustains,
    # next to the undisturbed sweep above.
    out = _run_ranks(4, [sys.executable, os.path.abspath(__file__),
                         "--link-heal-worker"],
                     extra_env={"HOROVOD_SHM_DISABLE": "1",
                                "HOROVOD_NUM_CHANNELS": "3",
                                "BENCH_SWEEP_BYTES": str(1 << 20),
                                "HOROVOD_FAULT_INJECT":
                                    "0:*:conn-reset:7,"
                                    "2:*:conn-reset:11:prev"})
    m = re.search(r"LINK_BENCH BUS_MB_S ([\d.]+) HEAL_MS_P50 ([\d.]+) "
                  r"RECONNECTS (\d+)", out)
    if m:
        result["allreduce_bus_bw_mb_s_flap"] = {"4": float(m.group(1))}
        result["link_heal_ms_p50"] = float(m.group(2))
        result["link_reconnects_flap"] = int(m.group(3))

    # Wire-dtype sweep (fp32/fp16/int8, 4 KB -> 64 MB, 2 and 4 ranks):
    # EFFECTIVE bus bandwidth per wire format, plus the deterministic
    # per-rank byte-counter ratio vs the fp32 wire — the gate metric
    # (wall time is loopback-noise; bytes are exact).
    wire_bw: dict = {w: {} for w in ("fp32", "fp16", "int8")}
    wire_tx: dict = {w: {} for w in ("fp32", "fp16", "int8")}
    for n in (2, 4):
        for wd in ("fp32", "fp16", "int8"):
            per_size = wire_bw[wd].setdefault(str(n), {})
            per_tx = wire_tx[wd].setdefault(str(n), {})
            for label, nbytes in sizes:
                out = _run_ranks(n, [sys.executable,
                                     os.path.abspath(__file__),
                                     "--wire-sweep-worker"],
                                 extra_env={
                                     "BENCH_SWEEP_BYTES": str(nbytes),
                                     "BENCH_WIRE_DTYPE": wd})
                m = re.search(r"WIRE_SWEEP_BUS_MB_S ([\d.]+) TX (\d+)",
                              out)
                if m:
                    per_size[label] = float(m.group(1))
                    per_tx[label] = int(m.group(2))
    for wd in ("fp32", "fp16", "int8"):
        result[f"allreduce_effective_bus_bw_mb_s_{wd}"] = wire_bw[wd]
        if wd == "fp32":
            continue
        ratios: dict = {}
        for n in ("2", "4"):
            ratios[n] = {
                label: round(wire_tx[wd][n][label]
                             / max(1, wire_tx["fp32"][n][label]), 4)
                for label in wire_tx[wd].get(n, {})
                if label in wire_tx["fp32"].get(n, {})
            }
        result[f"wire_bytes_ratio_{wd}"] = ratios

    # Algorithm-threshold sweep at 2 ranks: star vs ring latency per
    # payload size, interleaved in-process so drift hits both paths.
    algo_sweep: dict = {}
    out = _run_ranks(2, [sys.executable, os.path.abspath(__file__),
                         "--algo-sweep-worker"], timeout=300)
    for label, star, ring in re.findall(
            r"ALGO_SWEEP (\S+) ([\d.]+) ([\d.]+)", out):
        algo_sweep[label] = {"star": float(star), "ring": float(ring)}
    result["algo_threshold_sweep"] = algo_sweep

    # Online-autotuned 16 MB bus bandwidth next to the static numbers,
    # plus the config the search committed (docs/autotune.md).
    autotuned: dict = {}
    autotune_cfg: dict = {}
    for n in (2, 4):
        out = _run_ranks(n, [sys.executable, os.path.abspath(__file__),
                             "--autotune-worker"], timeout=300,
                         extra_env=_AUTOTUNE_ENV)
        m = re.search(
            r"AUTOTUNE_BUS_MB_S ([\d.]+) TRIALS (\d+) CONFIG (.*)", out)
        if m:
            autotuned[str(n)] = float(m.group(1))
            autotune_cfg[str(n)] = json.loads(m.group(3))
    result["allreduce_bus_bw_mb_s_autotuned"] = autotuned
    result["autotune_committed_config"] = autotune_cfg

    # Fleet-telemetry snapshot (docs/observability.md): the per-rank
    # counter table rank 0 aggregated over a short 4-rank run, flattened
    # under the `fleet_` prefix so nightly soak artifacts can trend the
    # fleet view next to the per-process numbers.
    try:
        out = _run_ranks(4, [sys.executable, os.path.abspath(__file__),
                             "--fleet-worker"],
                         extra_env={"HOROVOD_TELEMETRY_CYCLES": "1",
                                    "HOROVOD_CYCLE_TIME": "2"})
        m = re.search(r"FLEET_SNAPSHOT (.*)", out)
        if m:
            fleet = json.loads(m.group(1))
            result["fleet_ranks_reporting"] = fleet.get("ranks_reporting")
            result["fleet_quorum_lag_ns_p50"] = fleet.get(
                "quorum_lag_ns_p50")
            result["fleet_quorum_lag_ns_p99"] = fleet.get(
                "quorum_lag_ns_p99")
            result["fleet_slowest_rank"] = fleet.get("slowest", {}).get(
                "rank")
            for key, v in fleet.get("totals", {}).items():
                result[f"fleet_{key}"] = v
    except RuntimeError as exc:
        print(f"fleet snapshot skipped: {exc}", file=sys.stderr)

    # Big-world control-plane sweep (tests/scale harness): cycle latency,
    # coordinator control-cycle percentiles, rendezvous time and
    # steady-state negotiation bytes/cycle vs world size, hierarchical
    # coordination on.  HOROVOD_SKIP_SCALE_BENCH=1 skips (64 ranks).
    if os.environ.get("HOROVOD_SKIP_SCALE_BENCH") != "1":
        result["scale_sweep"] = _scale_sweep()
    print(json.dumps(result))


def _scale_sweep() -> dict:
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from scale.harness import run_world

    sweep: dict = {}
    for n, groups in ((4, 2), (16, 4), (64, 8)):
        r = run_world(n, groups=groups, steps=50, timeout=300)
        s = r["stats"] or {}
        sweep[str(n)] = {
            "cycle_latency_ms_p50": s.get("step_ms_p50"),
            "cycle_latency_ms_p99": s.get("step_ms_p99"),
            "coordinator_cycle_ms_p50":
                (s.get("coordinator_cycle_ns_p50") or 0) / 1e6,
            "coordinator_cycle_ms_p99":
                (s.get("coordinator_cycle_ns_p99") or 0) / 1e6,
            "rendezvous_ms": r["rendezvous_ms"],
            "negotiation_bytes_per_cycle":
                s.get("negotiation_bytes_per_cycle"),
            "hierarchical": s.get("hier"),
            "hosts": s.get("hosts"),
        }
    return sweep


def scale_gate() -> None:
    """CI big-world gate: 64 single-process engine ranks rendezvous and
    run 50 steady steps within the outer hard timeout (the hang
    detector), and hierarchical coordination cuts rank 0's steady-state
    negotiation bytes/cycle to <= HOROVOD_SCALE_GATE_RATIO (default 0.5)
    x the flat path.  Judged on DETERMINISTIC byte counters, never wall
    time — the PR 4/6 loopback-ceiling lesson: this box's wall numbers
    swing with ambient load, its byte counters do not."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from scale.harness import run_world

    threshold = float(os.environ.get("HOROVOD_SCALE_GATE_RATIO", "0.5"))
    hier = run_world(64, groups=8, steps=50, timeout=300)
    flat = run_world(64, groups=8, steps=50, hier=False, timeout=300)
    hs, fs = hier["stats"], flat["stats"]
    if not hs or not fs:
        print("SCALE GATE FAILED: missing rank-0 measurements")
        sys.exit(1)
    hb, fb = (hs["negotiation_bytes_per_cycle"],
              fs["negotiation_bytes_per_cycle"])
    ratio = hb / fb if fb > 0 else float("inf")
    print(f"scale gate: 64 ranks / 8 hosts — hier {hb:.0f} B/cycle vs "
          f"flat {fb:.0f} B/cycle (x{ratio:.3f}, threshold "
          f"x{threshold:.2f}); rendezvous {hier['rendezvous_ms']:.0f} ms "
          f"hier / {flat['rendezvous_ms']:.0f} ms flat; coordinator "
          f"cycle p50 {hs['coordinator_cycle_ns_p50'] / 1e6:.2f} ms / "
          f"p99 {hs['coordinator_cycle_ns_p99'] / 1e6:.2f} ms")
    failed = []
    if hs["hier"] != 1:
        failed.append("hierarchical coordination did not activate")
    if fs["hier"] != 0:
        failed.append("flat run unexpectedly hierarchical")
    if hs["cache_hits"] < 49 or fs["cache_hits"] < 49:
        failed.append("steady state did not ride the response cache")
    if ratio > threshold:
        failed.append(
            f"negotiation bytes/cycle ratio x{ratio:.3f} exceeds "
            f"x{threshold:.2f}")
    if failed:
        for f in failed:
            print(f"SCALE GATE FAILED: {f}")
        sys.exit(1)
    print("SCALE GATE PASSED")


#: Shared env for the autotune bench/gate runs: small fixed-bytes
#: windows so the full search converges in seconds of traffic, and a
#: pinned seed so the trial schedule is reproducible run to run.
_AUTOTUNE_ENV = {
    "HOROVOD_AUTOTUNE": "1",
    "HOROVOD_AUTOTUNE_SEED": "7",
    "HOROVOD_AUTOTUNE_WINDOW_BYTES": str(8 << 20),
    "HOROVOD_AUTOTUNE_TRIAL_TIMEOUT_SEC": "20",
}


def gate() -> None:
    """CI data-plane gate: channels=4 vs channels=1 on 16 MB 4-rank
    allreduce bus bandwidth (median of in-process alternating rounds),
    and pool liveness comes free — a deadlocked pool hangs the worker
    and the ci.sh timeout kills the run loudly.

    The default threshold is a REGRESSION FLOOR judged on the BEST of
    the interleaved rounds, not the multi-core speedup target: this CI
    box has 2 cores shared by 4 ranks, and its loopback is CPU-ceilinged
    at ~1.4 GB/s aggregate — measured, BOTH paths saturate it when the
    box is quiet (ratio ~1.0) and per-round ratios swing 0.7-2.4x with
    ambient load, while under contention the channeled path wins ~1.4x
    (stall smoothing).  Best-of still catches real data-plane breakage:
    a channel scheduling bug (e.g. serializing 4 channels on one driver)
    measured ~0.65 in EVERY round and fails it.  On hosts with >= 4
    cores per rank, set HOROVOD_GATE_RATIO=1.5 to assert the genuine
    link-parallelism win (there the rounds are stable)."""
    threshold = float(os.environ.get("HOROVOD_GATE_RATIO", "0.85"))
    # Pinned to the TCP plane: this gate was calibrated on it, and the
    # channels-vs-single comparison stays meaningful there; the shm
    # plane has its own gate (--shm-gate).
    out = _run_ranks(4, [sys.executable, os.path.abspath(__file__),
                         "--gate-worker"], timeout=420,
                     extra_env={"BENCH_GATE_ROUNDS": "4",
                                "HOROVOD_SHM_DISABLE": "1"})
    pairs = [(float(a), float(b)) for a, b in
             re.findall(r"GATE_PAIR ([\d.]+) ([\d.]+)", out)]
    if not pairs:
        print("DATA-PLANE GATE FAILED: no measurements produced")
        sys.exit(1)
    ratios = sorted(m / s for m, s in pairs if s > 0)
    if not ratios:
        print("DATA-PLANE GATE FAILED: no valid bandwidth measurements")
        sys.exit(1)
    median = ratios[len(ratios) // 2]
    best = ratios[-1]
    for m, s in pairs:
        ratio = f"x{m / s:.2f}" if s > 0 else "n/a"
        print(f"gate round: channels=4 {m:.0f} MB/s vs channels=1 "
              f"{s:.0f} MB/s ({ratio})")
    print(f"median ratio x{median:.2f}, best x{best:.2f}, "
          f"threshold x{threshold:.2f} (judged on best)")
    if best < threshold:
        print("DATA-PLANE GATE FAILED: multi-channel bus bandwidth did "
              "not clear the threshold in any round")
        sys.exit(1)
    print("DATA-PLANE GATE PASSED")


def shm_gate() -> None:
    """CI shm gate: shm ON vs OFF, interleaved in-process per round —
    small-allreduce latency at 2 ranks and 16 MB bus bandwidth at 4
    ranks.  Judged as a REGRESSION FLOOR on the best interleaved round
    (HOROVOD_SHM_GATE_RATIO, default 0.85), same convention as the
    data-plane gate: this box's loopback CPU ceiling makes single-round
    ratios swing with ambient load, while measured best-of rounds show
    shm ~2x ahead on both metrics (latency 0.8 vs 1.7 ms, 16 MB busbw
    ~1.0 vs ~0.5 GB/s under contention) — so a floor of 0.85 catches a
    broken shm path (those rounds measure 0.3-0.6x) without flaking on
    a quiet-box tie.  The bench JSON records both sides."""
    threshold = float(os.environ.get("HOROVOD_SHM_GATE_RATIO", "0.85"))
    failed = False
    for n, metric in ((2, "lat"), (4, "bw")):
        out = _run_ranks(n, [sys.executable, os.path.abspath(__file__),
                             "--shm-gate-worker"], timeout=420,
                         extra_env={"BENCH_GATE_ROUNDS": "3",
                                    "BENCH_GATE_METRIC": metric})
        pairs = [tuple(map(float, g)) for g in re.findall(
            r"SHM_GATE_PAIR lat ([\d.]+) ([\d.]+) bw ([\d.]+) ([\d.]+)",
            out)]
        if not pairs:
            print(f"SHM GATE FAILED at {n} ranks: no measurements "
                  f"produced\n{out}")
            sys.exit(1)
        ratios = []
        for s_lat, t_lat, s_bw, t_bw in pairs:
            if metric == "lat":
                # Latency: lower is better -> ratio = tcp / shm.
                ratio = t_lat / s_lat if s_lat > 0 else 0.0
                print(f"[{n} ranks] round: shm {s_lat:.3f} ms vs tcp "
                      f"{t_lat:.3f} ms (x{ratio:.2f})")
            else:
                ratio = s_bw / t_bw if t_bw > 0 else 0.0
                print(f"[{n} ranks] round: shm {s_bw:.0f} MB/s vs tcp "
                      f"{t_bw:.0f} MB/s (x{ratio:.2f})")
            ratios.append(ratio)
        best = max(ratios)
        print(f"[{n} ranks] best ratio x{best:.2f}, threshold "
              f"x{threshold:.2f} (judged on best)")
        if best < threshold:
            failed = True
    if failed:
        print("SHM GATE FAILED: the shm plane did not clear the "
              "regression floor in any round")
        sys.exit(1)
    print("SHM GATE PASSED")


def sharded_gate() -> None:
    """CI sharded (ZeRO-1) gate, three legs under ci.sh's hard timeout,
    all on DETERMINISTIC instruments (bitwise compares + byte
    counters — never wall time):

    1. bitwise sharded-vs-unsharded parity at 4 ranks: the
       sharded_worker numpy core asserts params bit-identical to the
       unsharded flat step after EVERY step, optimizer state ~1/N, and
       the per-step byte bounds rank-side;
    2. RS-vs-sliced-allreduce byte parity + the RS wire ratio at 4
       ranks (reducescatter_worker bytes scenario: tx in [0.40, 0.55]x
       the allreduce's);
    3. driver-side wire-bytes ratio: grads reduce-scatter tx <= 0.55x
       the unsharded allreduce tx on a 4 MB flat model (and the honest
       full-step total printed for the record — ZeRO trades no bytes
       for its 1/N memory, see docs/zero.md).
    """
    cap = float(os.environ.get("HOROVOD_SHARDED_GATE_RATIO", "0.55"))

    print("sharded gate 1/3: bitwise sharded-vs-unsharded parity @ 4")
    worker = os.path.join(REPO, "tests", "sharded_worker.py")
    _run_ranks(4, [sys.executable, worker, "numpy"], timeout=300)
    print("sharded parity OK")

    print("sharded gate 2/3: RS parity + wire ratio @ 4 ranks")
    rs_worker = os.path.join(REPO, "tests", "reducescatter_worker.py")
    _run_ranks(4, [sys.executable, rs_worker, "bytes"], timeout=300)
    print("RS byte ratio OK")

    print("sharded gate 3/3: step wire accounting @ 4 ranks")
    out = _run_ranks(4, [sys.executable, os.path.abspath(__file__),
                         "--sharded-bytes-worker"], timeout=300)
    m = re.search(r"SHARDED_BYTES ar_tx (\d+) rs_tx (\d+) ag_tx (\d+)",
                  out)
    if m is None:
        print("SHARDED GATE FAILED: no byte measurements produced")
        sys.exit(1)
    ar_tx, rs_tx, ag_tx = (int(m.group(i)) for i in (1, 2, 3))
    grads_ratio = rs_tx / max(1, ar_tx)
    full_ratio = (rs_tx + ag_tx) / max(1, ar_tx)
    print(f"data_bytes_tx: allreduce {ar_tx}, grads RS {rs_tx} "
          f"(x{grads_ratio:.3f}, cap {cap:.2f}), full sharded step "
          f"{rs_tx + ag_tx} (x{full_ratio:.3f} — the honest ZeRO "
          f"total; the lever is 1/N memory)")
    if grads_ratio > cap:
        print("SHARDED GATE FAILED: the gradient reduce-scatter did "
              "not halve the deterministic byte counter")
        sys.exit(1)
    print("SHARDED GATE PASSED")


def fsdp_gate() -> None:
    """CI ZeRO-3/FSDP gate, three legs under ci.sh's hard timeout:

    1. bitwise fsdp-vs-unsharded parity at 4 ranks (the fsdp_worker
       numpy core): per-unit RS -> shard update -> AG params bit-equal
       to the unsharded flat step after EVERY step, the grads-RS byte
       ratio in [0.40, 0.55]x the allreduce's on the ring path, and
       priority_inversions == 0 with bands on — all asserted
       rank-side;
    2. the deterministic residency ratio at 4 ranks over 16 near-equal
       units: fsdp_param_bytes_resident_peak / total_param_bytes <=
       0.45 (owned 1/N window + one gathered unit — never the full
       model; an unsharded plane sits at 1.0).  Byte counters, never
       RSS — RSS on this box is allocator- and import-noise;
    3. prefetch on vs off on the forward gather walk with real
       per-unit compute, PAIRED IN-PROCESS (two planes, prefetch 1 vs
       0, walked alternately in the same workers — the shm-gate trick,
       so scheduler placement and ambient drift hit both identically),
       best-of-round each, judged at prefetch-on >= 0.95x prefetch-off
       (the cross-process variant flaked: on this CPU-ceilinged
       loopback the engine thread competes with compute, and process
       placement alone swung walls ~20%), with priority_inversions ==
       0 on the banded run.

    HOROVOD_FSDP_GATE_MEM_RATIO / HOROVOD_FSDP_GATE_RATIO override the
    caps on capable hosts.
    """
    mem_cap = float(os.environ.get("HOROVOD_FSDP_GATE_MEM_RATIO", "0.45"))
    floor = float(os.environ.get("HOROVOD_FSDP_GATE_RATIO", "0.95"))
    worker = os.path.join(REPO, "tests", "fsdp_worker.py")

    print("fsdp gate 1/3: bitwise parity + RS wire ratio @ 4 ranks")
    _run_ranks(4, [sys.executable, worker, "numpy"], timeout=300,
               extra_env={"HOROVOD_PRIORITY_BANDS": "1"})
    print("fsdp parity OK (params bitwise == unsharded flat, every "
          "step; inversions == 0)")

    print("fsdp gate 2/3: deterministic peak-residency ratio @ 4 ranks")
    out = _run_ranks(4, [sys.executable, worker, "mem"], timeout=300,
                     extra_env={"HOROVOD_PRIORITY_BANDS": "1"})
    pairs = re.findall(r"FSDP_MEM rank=\d+ peak=(\d+) total=(\d+)", out)
    if not pairs:
        print("FSDP GATE FAILED: no residency measurements produced")
        sys.exit(1)
    ratio = max(int(p) / max(1, int(t)) for p, t in pairs)
    print(f"fsdp_param_bytes_resident_peak / total = x{ratio:.3f} "
          f"(cap {mem_cap:.2f}) — owned 1/N window + one gathered "
          f"unit, never the full model")
    if ratio > mem_cap:
        print("FSDP GATE FAILED: parameter residency did not shrink "
              "to ~1/N")
        sys.exit(1)

    print(f"fsdp gate 3/3: prefetch on/off, paired in-process, "
          f"floor {floor:.2f}")
    out = _run_ranks(2, [sys.executable, worker, "overlap"],
                     timeout=300,
                     extra_env={"HOROVOD_PRIORITY_BANDS": "1"})
    pairs = [m for line in out.splitlines()
             if (m := re.search(
                 r"FSDP_OVERLAP rank=\d+ on_ms=([\d.]+) "
                 r"off_ms=([\d.]+) inversions=(\d+) hits=\d+ "
                 r"misses=\d+ on_all=(\S+) off_all=(\S+)", line))]
    if not pairs:
        print("FSDP GATE FAILED: no overlap measurements produced")
        sys.exit(1)
    if any(int(m.group(3)) for m in pairs):
        print("FSDP GATE FAILED: the band-0 prefetch dispatched a "
              "priority inversion")
        sys.exit(1)
    # Best-of-interleaved, PAIRED: each round's on/off walks run
    # back-to-back on the same cores, so the per-round ratio isolates
    # the prefetch path from placement and ambient drift; the best
    # round is the protocol's verdict.  A broken prefetch (a blocking
    # wait re-serialized into every walk) drags EVERY round under the
    # floor; ambient spikes cannot manufacture a passing round.
    ratios = []
    for m in pairs:
        ons = [float(v) for v in m.group(4).split(",")]
        offs = [float(v) for v in m.group(5).split(",")]
        ratios += [off / on for on, off in zip(ons, offs)]
    best_ratio = max(ratios)
    on_ms = min(float(m.group(1)) for m in pairs)
    off_ms = min(float(m.group(2)) for m in pairs)
    print(f"forward walk: prefetch on best {on_ms:.3f} ms vs off "
          f"{off_ms:.3f} ms; paired off/on best {best_ratio:.3f} "
          f"over {len(ratios)} rounds (floor {floor:.2f})")
    if not (best_ratio >= floor):
        print("FSDP GATE FAILED: the prefetch-on walk regressed past "
              "the floor in every paired round")
        sys.exit(1)
    print("FSDP GATE PASSED")


def compression_gate() -> None:
    """CI wire-compression gate, three legs under ci.sh's hard timeout:

    1. fp32-wire bitwise parity at 4 ranks — HOROVOD_WIRE_DTYPE=fp32 and
       the per-tensor fp32 override must be BYTE-IDENTICAL to the
       default engine across the full dtype/op parity corpus (the
       native_worker wire_parity scenario asserts it rank-side);
    2. int8 wire byte ratio on a 16 MB fp32 allreduce:
       data_bytes_tx(int8) / data_bytes_tx(fp32) <= 0.30, judged on the
       DETERMINISTIC byte counters — never wall time, the loopback is
       CPU-ceilinged and ambient-load-noisy (docs/performance.md);
    3. the convergence worker at 2 ranks: int8 and top-k(1%)+error-
       feedback within their pinned loss bounds of the fp32 run, and
       top-k WITHOUT feedback measurably worse (asserted worker-side).
    """
    ratio_cap = float(os.environ.get("HOROVOD_WIRE_GATE_RATIO", "0.30"))
    worker = os.path.join(REPO, "tests", "native_worker.py")

    print("compression gate 1/3: fp32-wire bitwise parity at 4 ranks")
    _run_ranks(4, [sys.executable, worker, "wire_parity"], timeout=360)
    print("fp32 parity OK")

    print("compression gate 2/3: int8 byte ratio on 16 MB @ 4 ranks")
    out = _run_ranks(4, [sys.executable, os.path.abspath(__file__),
                         "--wire-gate-worker"], timeout=240)
    m = re.search(r"WIRE_GATE_TX fp32 (\d+) int8 (\d+)", out)
    if m is None:
        print("COMPRESSION GATE FAILED: no byte measurements produced")
        sys.exit(1)
    fp32_tx, int8_tx = int(m.group(1)), int(m.group(2))
    ratio = int8_tx / max(1, fp32_tx)
    print(f"data_bytes_tx: fp32 {fp32_tx} vs int8 {int8_tx} "
          f"(ratio {ratio:.3f}, cap {ratio_cap:.2f}, "
          f"cut x{fp32_tx / max(1, int8_tx):.2f})")
    if ratio > ratio_cap:
        print("COMPRESSION GATE FAILED: int8 wire did not cut the "
              "deterministic byte counter under the cap")
        sys.exit(1)

    print("compression gate 3/3: convergence worker at 2 ranks")
    conv = os.path.join(REPO, "tests", "compression_worker.py")
    out = _run_ranks(2, [sys.executable, conv], timeout=420)
    m = re.search(r"LOSSES (.*)", out)
    detail = m.group(1) if m else "bounds asserted worker-side"
    print(f"convergence OK ({detail})")
    print("COMPRESSION GATE PASSED")


def overlap_gate() -> None:
    """CI priority-scheduling / overlap gate, four legs under ci.sh's
    hard timeout:

    1. bands=0 vs bands=1 bitwise parity at 4 ranks (priority_worker
       bands_parity: ordering changes WHEN responses dispatch, never
       what they compute — fusion pinned off, since banding changes
       fusion GROUPING and grouping is a different deterministic fp
       order by design);
    2. a 2-rank REAL-MODEL loop (the tf bench worker, HOROVOD_SMOKE_STEPS)
       with bands on must dispatch with priority_inversions == 0 — the
       deterministic instrument, judged exactly, never wall time;
    3. best-of-interleaved engine_tf_step_ms: bands on vs off alternated
       in rounds (slow-box drift hits both configs equally), judged on a
       0.85 REGRESSION FLOOR — this box's loopback is CPU-ceilinged, so
       the floor guards against scheduling breakage rather than
       asserting a speedup (HOROVOD_OVERLAP_GATE_RATIO overrides);
    4. the wire-policy convergence worker at 2 ranks: the embedding-
       heavy model's policy run must cut the deterministic data_bytes_tx
       (<= 0.60x, the big leaf quartered) at fp32-parity convergence
       (asserted worker-side).
    """
    floor = float(os.environ.get("HOROVOD_OVERLAP_GATE_RATIO", "0.85"))
    prio_worker = os.path.join(REPO, "tests", "priority_worker.py")

    print("overlap gate 1/4: bands on/off bitwise parity at 4 ranks")
    _run_ranks(4, [sys.executable, prio_worker, "bands_parity"],
               timeout=300,
               extra_env={"HOROVOD_PRIORITY_BANDS": "1",
                          "HOROVOD_FUSION_THRESHOLD": "0"})
    print("bands parity OK")

    print("overlap gate 2/4: real-model inversions == 0 with bands on")
    out = _run_ranks(2, [sys.executable, os.path.abspath(__file__),
                         "--tf-worker"], timeout=300,
                     extra_env={"HOROVOD_PRIORITY_BANDS": "1",
                                "HOROVOD_SMOKE_STEPS":
                                    os.environ.get("HOROVOD_SMOKE_STEPS",
                                                   "50")})
    m = _TF_LINE.search(out)
    if m is None or m.group(4) is None:
        print("OVERLAP GATE FAILED: no inversions measurement produced")
        sys.exit(1)
    inv = float(m.group(4))
    print(f"priority_inversions_per_step = {inv:.3f} (bands on)")
    if inv != 0.0:
        print("OVERLAP GATE FAILED: banded ordering dispatched an "
              "inversion on the real-model loop")
        sys.exit(1)

    print("overlap gate 3/4: best-of-interleaved tf step time, "
          f"floor {floor:.2f}")
    best = {"on": float("inf"), "off": float("inf")}
    for _round in range(2):
        for label, env in (("on", {"HOROVOD_PRIORITY_BANDS": "1"}),
                           ("off", {})):
            out = _run_ranks(2, [sys.executable, os.path.abspath(__file__),
                                 "--tf-worker"], timeout=300,
                             extra_env=env)
            m = _TF_LINE.search(out)
            if m:
                best[label] = min(best[label], float(m.group(1)))
    print(f"engine_tf_step_ms best-of: bands on {best['on']:.3f} "
          f"vs off {best['off']:.3f} "
          f"(ratio off/on {best['off'] / best['on']:.3f})")
    if not (best["off"] / best["on"] >= floor):
        print("OVERLAP GATE FAILED: bands-on step time regressed past "
              "the floor")
        sys.exit(1)

    print("overlap gate 4/4: wire-policy bytes + convergence at 2 ranks")
    wp = os.path.join(REPO, "tests", "wire_policy_worker.py")
    out = _run_ranks(2, [sys.executable, wp], timeout=420,
                     extra_env={"HOROVOD_WIRE_POLICY": "1"})
    m = re.search(r"WIRE_POLICY (.*)", out)
    print(f"wire policy OK ({m.group(1) if m else 'asserted worker-side'})")
    print("OVERLAP GATE PASSED")


def autotune_gate() -> None:
    """CI autotune gate at 2 AND 4 ranks: the search must converge
    within HOROVOD_AUTOTUNE_MAX_TRIALS (the worker asserts it), and the
    committed config's 16 MB bus bandwidth must reach the gate ratio of
    the BEST static grid point, judged on the best of interleaved
    rounds — same regression-floor convention as the data-plane gate
    (this box's loopback is CPU-ceilinged and ambient-load-noisy; both
    sides usually tie at ~1.0, and the floor catches a search that
    commits a genuinely broken config).  HOROVOD_AUTOTUNE_GATE_RATIO
    overrides the 0.85 default on capable hosts."""
    threshold = float(os.environ.get("HOROVOD_AUTOTUNE_GATE_RATIO", "0.85"))
    env = {
        **_AUTOTUNE_ENV,
        # chunk + wave only: the full 4-knob schedule buys the gate
        # nothing but wall time (fusion/cycle barely move single-tensor
        # busbw), and the grid it is judged against is the chunk axis.
        "HOROVOD_AUTOTUNE_KNOBS": "chunk_bytes,wave_width",
        "BENCH_GATE_ROUNDS": "3",
    }
    failed = False
    for n in (2, 4):
        out = _run_ranks(n, [sys.executable, os.path.abspath(__file__),
                             "--autotune-gate-worker"], timeout=420,
                         extra_env=env)
        rounds = [(float(a), float(s)) for a, s in re.findall(
            r"AUTOGATE_ROUND auto=([\d.]+) static_best=([\d.]+)", out)]
        trials = re.search(r"AUTOGATE_TRIALS (\d+) MAX (\d+)", out)
        cfg = re.search(r"AUTOGATE_CONFIG (.*)", out)
        if not rounds or trials is None:
            print(f"AUTOTUNE GATE FAILED at {n} ranks: no measurements "
                  f"produced\n{out}")
            sys.exit(1)
        print(f"[{n} ranks] converged in {trials.group(1)} trials "
              f"(cap {trials.group(2)}); committed "
              f"{cfg.group(1) if cfg else '?'}")
        ratios = []
        for a, s in rounds:
            ratio = a / s if s > 0 else 0.0
            ratios.append(ratio)
            print(f"[{n} ranks] round: autotuned {a:.0f} MB/s vs "
                  f"best-static {s:.0f} MB/s (x{ratio:.2f})")
        best = max(ratios) if ratios else 0.0
        print(f"[{n} ranks] best ratio x{best:.2f}, "
              f"threshold x{threshold:.2f} (judged on best)")
        if best < threshold:
            failed = True
    if failed:
        print("AUTOTUNE GATE FAILED: the committed config did not reach "
              "the static-grid floor in any round")
        sys.exit(1)
    print("AUTOTUNE GATE PASSED")


if __name__ == "__main__":
    if "--tf-worker" in sys.argv:
        _tf_worker()
    elif "--sweep-worker" in sys.argv:
        _sweep_worker()
    elif "--latency-worker" in sys.argv:
        _latency_worker()
    elif "--gate-worker" in sys.argv:
        _gate_worker()
    elif "--shm-gate-worker" in sys.argv:
        _shm_gate_worker()
    elif "--algo-sweep-worker" in sys.argv:
        _algo_sweep_worker()
    elif "--wire-sweep-worker" in sys.argv:
        _wire_sweep_worker()
    elif "--wire-gate-worker" in sys.argv:
        _wire_gate_worker()
    elif "--fleet-worker" in sys.argv:
        _fleet_worker()
    elif "--link-heal-worker" in sys.argv:
        _link_heal_bench_worker()
    elif "--rs-sweep-worker" in sys.argv:
        _rs_sweep_worker()
    elif "--alltoall-sweep-worker" in sys.argv:
        _alltoall_sweep_worker()
    elif "--sharded-bytes-worker" in sys.argv:
        _sharded_bytes_worker()
    elif "--sharded-gate" in sys.argv:
        sharded_gate()
    elif "--fsdp-gate" in sys.argv:
        fsdp_gate()
    elif "--compression-gate" in sys.argv:
        compression_gate()
    elif "--shm-gate" in sys.argv:
        shm_gate()
    elif "--autotune-worker" in sys.argv:
        _autotune_worker()
    elif "--autotune-gate-worker" in sys.argv:
        _autotune_gate_worker()
    elif "--autotune-gate" in sys.argv:
        autotune_gate()
    elif "--overlap-gate" in sys.argv:
        overlap_gate()
    elif "--scale-gate" in sys.argv:
        scale_gate()
    elif "--gate" in sys.argv:
        gate()
    else:
        main()
