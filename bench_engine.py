"""Host-engine data-plane benchmark: throughput of the TCP ring engine
under the torch and TF frontends at 2 and 4 ranks.

Role parity with the reference's benchmark methodology
(``examples/pytorch_synthetic_benchmark.py:96-110`` — timed fwd+bwd+step
loops, img/sec), applied to the part of THIS stack the main ``bench.py``
does not exercise: the native TCP engine serving the host frontends
(torch hooks, TF grouped allreduce).  The numbers are CPU-host numbers by
design — they track frontend + negotiation + ring-collective overhead,
so hot-path regressions (e.g. a fusion/batching break) become visible as
throughput drops.

The TF step loop runs twice per world size — negotiation response cache
ON (the default) and OFF (``HOROVOD_CACHE_CAPACITY=0``) — and reports
``control_round_trips_per_step`` alongside step time, so the control
plane's contribution is separable from the data plane's.

Prints ONE JSON line, e.g.::

    {"metric": "engine_data_plane", "torch_img_per_sec": {"2": ..,
     "4": ..}, "tf_img_per_sec": {"2": .., "4": ..},
     "tf_step_ms": {"2": .., "4": ..},
     "tf_step_ms_nocache": {"2": .., "4": ..},
     "control_round_trips_per_step": {"2": .., "4": ..},
     "control_round_trips_per_step_nocache": {"2": .., "4": ..}}

``bench.py`` merges these keys into the bench artifact under an
``engine_`` prefix; standalone use: ``python bench_engine.py``.
"""

from __future__ import annotations

import json
import os
import re
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


# ---------------------------------------------------------------------------
# TF worker (run as: bench_engine.py --tf-worker)
# ---------------------------------------------------------------------------

def _tf_worker() -> None:
    """MNIST-shaped training step over DistributedGradientTape: every
    dense gradient rides the grouped single-cycle allreduce
    (``horovod_tpu/tf/mpi_ops.py``)."""
    import numpy as np
    import tensorflow as tf

    sys.path.insert(0, REPO)
    import horovod_tpu.tf as hvd

    hvd.init()
    tf.keras.utils.set_random_seed(1 + hvd.rank())
    model = tf.keras.Sequential([
        tf.keras.layers.Dense(128, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    model(tf.zeros([1, 784]))
    hvd.broadcast_variables(model.trainable_variables, root_rank=0)
    opt = tf.keras.optimizers.SGD(0.01 * hvd.size())
    batch = 32
    rng = np.random.default_rng(7 + hvd.rank())
    X = tf.constant(rng.standard_normal((batch, 784)), dtype=tf.float32)
    Y = tf.constant(rng.integers(0, 10, batch), dtype=tf.int64)

    def step():
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            logits = model(X)
            loss = tf.reduce_mean(
                tf.nn.sparse_softmax_cross_entropy_with_logits(
                    labels=Y, logits=logits))
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))

    for _ in range(3):
        step()
    from horovod_tpu.runtime import engine_or_none

    eng = engine_or_none()
    iters = 30
    before = eng.stats() if eng is not None else {}
    t0 = time.perf_counter()
    for _ in range(iters):
        step()
    dt = time.perf_counter() - t0
    after = eng.stats() if eng is not None else {}
    rt_per_step = (after.get("control_round_trips", 0)
                   - before.get("control_round_trips", 0)) / iters
    if hvd.rank() == 0:
        print(f"TF_STEP_MS {dt / iters * 1e3:.3f} "
              f"TF_IMG_PER_SEC {batch * hvd.size() * iters / dt:.1f} "
              f"TF_RT_PER_STEP {rt_per_step:.2f}",
              flush=True)
    hvd.shutdown()


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_ranks(n: int, argv: list, timeout: int = 240,
               extra_env: dict | None = None) -> str:
    """Run ``argv`` as n engine ranks; returns rank 0's stdout."""
    port = _free_port()
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(n),
            "HOROVOD_COORDINATOR": f"127.0.0.1:{port}",
            "CUDA_VISIBLE_DEVICES": "-1",
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            argv, env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out.decode(), err.decode()))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for rank, (rc, out, err) in enumerate(outs):
        if rc != 0:
            raise RuntimeError(
                f"rank {rank} failed (rc={rc}):\n{out}\n{err}")
    return outs[0][1]


_TF_LINE = re.compile(r"TF_STEP_MS ([\d.]+) TF_IMG_PER_SEC ([\d.]+)"
                      r"(?: TF_RT_PER_STEP ([\d.]+))?")


def main() -> None:
    result: dict = {"metric": "engine_data_plane"}
    torch_rates: dict = {}
    tf_rates: dict = {}
    tf_step_ms: dict = {}
    tf_step_ms_nocache: dict = {}
    rt_per_step: dict = {}
    rt_per_step_nocache: dict = {}
    for n in (2, 4):
        # No --smoke: it would force num_iters to 1, and these numbers
        # exist to catch regressions — keep the 3-sample mean the
        # example reports (its ±1.96σ methodology, ref :96-110).
        out = _run_ranks(n, [
            sys.executable,
            os.path.join(REPO, "examples", "torch_synthetic_benchmark.py"),
            "--batch-size", "16", "--num-iters", "3",
            "--num-batches-per-iter", "4",
        ])
        m = re.search(r"Total img/sec on \d+ device\(s\): ([\d.]+)", out)
        if m:
            torch_rates[str(n)] = float(m.group(1))

        # TF step loop, negotiation cache ON (default) and OFF — the
        # delta isolates the control plane's share of step time, and the
        # OFF run proves the legacy path still reproduces its numbers.
        for label, env, step_dict, rt_dict in (
                ("cache", {}, tf_step_ms, rt_per_step),
                ("nocache", {"HOROVOD_CACHE_CAPACITY": "0"},
                 tf_step_ms_nocache, rt_per_step_nocache)):
            out = _run_ranks(n, [sys.executable, os.path.abspath(__file__),
                                 "--tf-worker"], extra_env=env)
            m = _TF_LINE.search(out)
            if m:
                step_dict[str(n)] = float(m.group(1))
                if label == "cache":
                    tf_rates[str(n)] = float(m.group(2))
                if m.group(3) is not None:
                    rt_dict[str(n)] = float(m.group(3))
    result["torch_img_per_sec"] = torch_rates
    result["tf_img_per_sec"] = tf_rates
    result["tf_step_ms"] = tf_step_ms
    result["tf_step_ms_nocache"] = tf_step_ms_nocache
    result["control_round_trips_per_step"] = rt_per_step
    result["control_round_trips_per_step_nocache"] = rt_per_step_nocache
    print(json.dumps(result))


if __name__ == "__main__":
    if "--tf-worker" in sys.argv:
        _tf_worker()
    else:
        main()
