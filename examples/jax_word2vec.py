"""Skip-gram word2vec with NCE loss — the embedding-gradient workload.

Role parity with reference ``examples/tensorflow_word2vec.py``: skip-gram
batches from a synthetic corpus (the reference downloads text8, ref
:54-78), direct ``broadcast_parameters`` use (:199 uses the broadcast op
directly), embedding lookups whose gradients exercise the
sparse-to-dense reduction path (``sparse_as_dense``; on TPU embedding
grads are dense scatters, SURVEY.md §2.3).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu.jax as hvd
from examples.common import example_args
from horovod_tpu.models import SkipGramModel, nce_loss


def synthetic_corpus(vocab, n_tokens, seed=0):
    """Zipf-distributed token stream (word2vec's natural input shape)."""
    rng = np.random.default_rng(seed)
    freq = 1.0 / np.arange(1, vocab + 1)
    return rng.choice(vocab, size=n_tokens, p=freq / freq.sum())


def skipgram_batches(corpus, batch, window, negatives, vocab, seed):
    rng = np.random.default_rng(seed)
    while True:
        centers = rng.integers(window, len(corpus) - window, batch)
        offsets = rng.integers(1, window + 1, batch) * \
            rng.choice([-1, 1], batch)
        yield (corpus[centers], corpus[centers + offsets],
               rng.integers(0, vocab, (batch, negatives)))


def main():
    args = example_args("JAX word2vec", batch_size=128, lr=0.2,
                        vocab=2000, embedding=64, negatives=8,
                        steps=400)
    hvd.init()

    vocab = 200 if args.smoke else args.vocab
    steps = 20 if args.smoke else args.steps
    model = SkipGramModel(vocab_size=vocab, embedding_size=args.embedding)
    params = model.init(jax.random.key(0), jnp.zeros((2,), jnp.int32))
    params = hvd.broadcast_parameters(params, root_rank=0)

    opt = hvd.DistributedOptimizer(optax.adagrad(args.lr * hvd.num_chips()))
    opt_state = opt.init(params)

    mesh = hvd.data_parallel_mesh()

    def loss_fn(params, batch):
        centers, labels, negs = batch
        return nce_loss(model, params, centers, labels, negs)

    step = hvd.make_train_step(loss_fn, opt, mesh, donate=False)

    corpus = synthetic_corpus(vocab, 10000 if args.smoke else 100000,
                              seed=hvd.rank())
    batches = skipgram_batches(corpus, args.batch_size, 2, args.negatives,
                               vocab, seed=hvd.rank())
    for i in range(steps):
        centers, labels, negs = next(batches)
        params, opt_state, loss = step(
            params, opt_state,
            (jnp.asarray(centers), jnp.asarray(labels), jnp.asarray(negs)))
        if i % max(steps // 5, 1) == 0 and hvd.rank() == 0:
            print(f"step {i}: nce loss={float(loss):.4f}", flush=True)
    print("done", flush=True)


if __name__ == "__main__":
    main()
