"""Packed-sequence Llama pretraining: many documents per row, flash
attention with segment masking.

The production LLM data recipe packs variable-length documents
back-to-back into fixed-length rows so no FLOPs are spent on padding; the
attention must then be BLOCK-DIAGONAL causal (a token never attends into
the previous document).  This example wires the framework's pieces
together: ``flash_attention(segment_ids=...)`` (an O(S) sideband, no
[S, S] mask), ``hvd.DistributedOptimizer``, and ``hvd.make_train_step``
over the data mesh — segment ids travel WITH the batch, so they shard
alongside the tokens.  No reference counterpart (the reference predates
transformers, SURVEY.md §5.7) — a BASELINE.json extras-family workload.

Run: ``python examples/llama_packed_pretraining.py --smoke``
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from examples.common import example_args  # noqa: E402


def make_packed_batch(rng, vocab, batch, seq, mean_doc_len):
    """Rows of documents packed back-to-back: returns (tokens [B, S+1],
    segment_ids [B, S])."""
    tokens = rng.integers(1, vocab, (batch, seq + 1), dtype=np.int64)
    seg = np.zeros((batch, seq), np.int32)
    for b in range(batch):
        pos, doc = 0, 0
        while pos < seq:
            length = max(1, int(rng.poisson(mean_doc_len)))
            seg[b, pos:pos + length] = doc
            pos += length
            doc += 1
    return jnp.asarray(tokens, jnp.int32), jnp.asarray(seg)


def main():
    import horovod_tpu.jax as hvd
    from horovod_tpu.models import LlamaConfig, LlamaModel
    from horovod_tpu.ops.flash_attention import flash_attention
    from horovod_tpu.ops.losses import softmax_cross_entropy

    args = example_args("packed-sequence Llama pretraining", steps=20)
    hvd.init()
    mesh = hvd.data_parallel_mesh()
    n = jax.device_count()

    if args.smoke:
        cfg = LlamaConfig.tiny()
        batch, seq, steps, mean_doc = n, 128, 3, 40
    else:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=512, num_layers=8,
                          num_heads=4, num_kv_heads=4,
                          intermediate_size=2048, max_seq_len=2048)
        batch, seq, steps, mean_doc = 4 * n, 1024, args.steps, 300

    rng = np.random.default_rng(0)
    tokens, seg = make_packed_batch(rng, cfg.vocab_size, batch, seq,
                                    mean_doc)

    def loss_fn(params, batch):
        toks, seg_ids = batch  # sharded together over the data axis
        # The segment mask rides the model's attention_fn seam; flax
        # modules are cheap dataclasses, so constructing one per trace
        # with the shard's segment ids closed over is free.
        model = LlamaModel(
            cfg,
            attention_fn=lambda q, k, v, *a: flash_attention(
                q, k, v, causal=True, segment_ids=seg_ids))
        logits = model.apply(params, toks[:, :-1])
        # Mask the loss at document boundaries: a doc's last token must
        # not be trained to predict the NEXT doc's first token (the
        # attention mask blocks cross-doc reads; this blocks cross-doc
        # targets).  softmax_cross_entropy (ops/losses.py) computes
        # lse - target_logit without materializing fp32 log-probs.
        valid = jnp.concatenate(
            [seg_ids[:, 1:] == seg_ids[:, :-1],
             jnp.zeros((toks.shape[0], 1), bool)], axis=1)
        return softmax_cross_entropy(logits, toks[:, 1:], where=valid)

    params = jax.jit(
        lambda: LlamaModel(cfg).init(jax.random.key(0), tokens[:, :-1]))()
    params = hvd.broadcast_parameters(params)
    opt = hvd.DistributedOptimizer(optax.adamw(args.lr))
    step_fn = hvd.make_train_step(loss_fn, opt, mesh)
    opt_state = jax.jit(opt.inner.init)(params)

    losses = []
    for step in range(steps):
        params, opt_state, loss = step_fn(params, opt_state, (tokens, seg))
        losses.append(float(loss))
        if hvd.rank() == 0:
            print(f"step {step}: loss {losses[-1]:.4f}", flush=True)
    assert losses[-1] < losses[0], "loss did not improve"
    if hvd.rank() == 0:
        print("packed pretraining done")


if __name__ == "__main__":
    main()
