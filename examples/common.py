"""Shared helpers for the example scripts.

Synthetic data stands in for MNIST/ImageNet downloads (examples must run
in air-gapped CI; the reference downloads real datasets in its examples,
which is orthogonal to what they demonstrate).
"""

from __future__ import annotations

import argparse

import numpy as np


def example_args(description: str, **extra) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--epochs", type=int, default=extra.pop("epochs", 4))
    p.add_argument("--batch-size", type=int,
                   default=extra.pop("batch_size", 64))
    p.add_argument("--lr", type=float, default=extra.pop("lr", 0.01))
    p.add_argument("--smoke", action="store_true",
                   help="tiny shapes / few steps, for CI")
    for name, default in extra.items():
        arg = "--" + name.replace("_", "-")
        if isinstance(default, bool):
            p.add_argument(arg, action="store_true")
        else:
            p.add_argument(arg, type=type(default), default=default)
    return p.parse_args()


def synthetic_mnist(n: int = 2048, seed: int = 0):
    """Deterministic stand-in for MNIST: class-dependent blobs, so models
    actually learn (accuracy climbs above chance within an epoch)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    centers = rng.standard_normal((10, 28, 28, 1)).astype(np.float32)
    images = centers[labels] + 0.3 * rng.standard_normal(
        (n, 28, 28, 1)).astype(np.float32)
    return images.astype(np.float32), labels.astype(np.int32)


def synthetic_imagenet(n: int, size: int = 224, classes: int = 1000,
                       seed: int = 0):
    rng = np.random.default_rng(seed)
    images = rng.standard_normal((n, size, size, 3)).astype(np.float32)
    labels = rng.integers(0, classes, n).astype(np.int32)
    return images, labels


def shard_for_rank(arrays, rank: int, size: int):
    """1/N sampling per rank — the reference's DistributedSampler role
    (examples/pytorch_mnist.py:50, keras_imagenet_resnet50.py:161-173)."""
    return tuple(a[rank::size] for a in arrays)
