"""Synthetic throughput benchmark for the torch frontend.

Role parity with reference ``examples/pytorch_synthetic_benchmark.py``:
timed fwd+bwd+step loop over synthetic batches, img/sec per device and
total with ±1.96σ (ref :96-110); broadcast at start (:66-67); fp16
compression flag (:33, here bf16 too).  The torch path runs on host CPU
(the TPU benchmark is bench.py); its numbers measure the frontend + ring
collective overhead, not TPU compute.
"""

import os
import sys
import timeit

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import torch

import horovod_tpu.torch as hvd
from examples.common import example_args


def main():
    args = example_args("torch synthetic benchmark", batch_size=8,
                        num_iters=3, num_batches_per_iter=4,
                        compression="none")
    hvd.init()
    torch.manual_seed(1)

    # A small convnet stands in for torchvision's resnet50 (no model hub
    # in an air-gapped environment; same measurement semantics).
    model = torch.nn.Sequential(
        torch.nn.Conv2d(3, 32, 3, stride=2, padding=1), torch.nn.ReLU(),
        torch.nn.Conv2d(32, 64, 3, stride=2, padding=1), torch.nn.ReLU(),
        torch.nn.AdaptiveAvgPool2d(1), torch.nn.Flatten(),
        torch.nn.Linear(64, 1000),
    )
    compression = {"none": hvd.Compression.none,
                   "fp16": hvd.Compression.fp16,
                   "bf16": hvd.Compression.bf16}[args.compression]
    optimizer = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size()),
        named_parameters=model.named_parameters(),
        compression=compression,
    )
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    size = 32 if args.smoke else 96
    data = torch.randn(args.batch_size, 3, size, size)
    target = torch.randint(0, 1000, (args.batch_size,))

    def benchmark_step():
        optimizer.zero_grad()
        loss = torch.nn.functional.cross_entropy(model(data), target)
        loss.backward()
        optimizer.step()

    benchmark_step()  # warmup
    img_secs = []
    iters = 1 if args.smoke else args.num_iters
    for _ in range(iters):
        t = timeit.timeit(benchmark_step, number=args.num_batches_per_iter)
        img_secs.append(args.batch_size * args.num_batches_per_iter / t)

    img_sec_mean = np.mean(img_secs)
    img_sec_conf = 1.96 * np.std(img_secs)
    if hvd.rank() == 0:
        print(f"Img/sec per device: {img_sec_mean:.1f} +-{img_sec_conf:.1f}")
        print(f"Total img/sec on {hvd.size()} device(s): "
              f"{hvd.size() * img_sec_mean:.1f} "
              f"+-{hvd.size() * img_sec_conf:.1f}")
    print("done", flush=True)


if __name__ == "__main__":
    main()
