"""MNIST with the torch frontend.

Role parity with reference ``examples/pytorch_mnist.py``: per-rank data
sharding in DistributedSampler style (ref :50), broadcast_parameters
(:91), DistributedOptimizer with named_parameters (:87-89), allreduce
metric averaging (:125).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd
from examples.common import example_args, shard_for_rank, synthetic_mnist


class Net(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = torch.nn.Conv2d(10, 20, kernel_size=5)
        self.fc1 = torch.nn.Linear(320, 50)
        self.fc2 = torch.nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2(x), 2))
        x = x.flatten(1)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    args = example_args("torch MNIST")
    hvd.init()
    torch.manual_seed(42)

    images, labels = synthetic_mnist(512 if args.smoke else 4096)
    images, labels = shard_for_rank((images, labels), hvd.rank(), hvd.size())
    X = torch.from_numpy(images).permute(0, 3, 1, 2)  # NCHW for torch
    Y = torch.from_numpy(labels).long()

    model = Net()
    optimizer = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(),
                        lr=args.lr * hvd.size(), momentum=0.5),
        named_parameters=model.named_parameters(),
    )
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    epochs = 1 if args.smoke else args.epochs
    batch = args.batch_size
    for epoch in range(epochs):
        perm = torch.randperm(len(X))
        losses = []
        for i in range(0, len(X) - batch + 1, batch):
            idx = perm[i:i + batch]
            optimizer.zero_grad()
            loss = F.nll_loss(model(X[idx]), Y[idx])
            loss.backward()
            optimizer.step()
            losses.append(loss.item())
        # Metric averaging via allreduce (reference :125).
        avg = hvd.allreduce(torch.tensor(float(np.mean(losses))),
                            name="epoch_loss")
        if hvd.rank() == 0:
            print(f"epoch {epoch + 1}: loss={avg.item():.4f}", flush=True)
    print("done", flush=True)


if __name__ == "__main__":
    main()
