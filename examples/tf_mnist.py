"""MNIST with the TensorFlow frontend.

Role parity with reference ``examples/tensorflow_mnist.py``: per-rank
data sharding, BroadcastGlobalVariables semantics via
``broadcast_variables`` (ref :49 hook), gradient averaging via
``create_distributed_optimizer`` (the TF2 counterpart of the reference's
v1 ``DistributedOptimizer``, ref :43) — the ONLY averaging point: the
tape stays a plain ``tf.GradientTape`` because wrapping it too would
average twice.  lr scaled by world size (ref :41), allreduce metric
averaging.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import tensorflow as tf

import horovod_tpu.tf as hvd
from examples.common import example_args, shard_for_rank, synthetic_mnist


def build_model():
    return tf.keras.Sequential([
        tf.keras.layers.Conv2D(10, 5, activation="relu"),
        tf.keras.layers.MaxPool2D(2),
        tf.keras.layers.Conv2D(20, 5, activation="relu"),
        tf.keras.layers.MaxPool2D(2),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(50, activation="relu"),
        tf.keras.layers.Dense(10),
    ])


def main():
    args = example_args("TensorFlow MNIST")
    hvd.init()
    tf.random.set_seed(42)

    images, labels = synthetic_mnist(512 if args.smoke else 4096)
    images, labels = shard_for_rank((images, labels), hvd.rank(), hvd.size())
    X = tf.constant(images)  # NHWC already
    Y = tf.constant(labels.astype(np.int32))

    model = build_model()
    model(X[:1])  # build variables
    optimizer = hvd.create_distributed_optimizer(
        tf.keras.optimizers.SGD(learning_rate=args.lr * hvd.size(),
                                momentum=0.5))
    hvd.broadcast_variables(model.trainable_variables, root_rank=0)

    loss_obj = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)

    @tf.function
    def train_step(x, y):
        with tf.GradientTape() as tape:
            loss = loss_obj(y, model(x, training=True))
        grads = tape.gradient(loss, model.trainable_variables)
        optimizer.apply_gradients(zip(grads, model.trainable_variables))
        return loss

    epochs = 1 if args.smoke else args.epochs
    batch = args.batch_size
    n = int(X.shape[0])
    for epoch in range(epochs):
        perm = np.random.default_rng(epoch).permutation(n)
        losses = []
        for i in range(0, n - batch + 1, batch):
            idx = perm[i:i + batch]
            losses.append(float(train_step(tf.gather(X, idx),
                                           tf.gather(Y, idx))))
        avg = hvd.allreduce(tf.constant(float(np.mean(losses))),
                            name="epoch_loss")
        if hvd.rank() == 0:
            print(f"epoch {epoch + 1}: loss={float(avg):.4f}", flush=True)
    print("done", flush=True)


if __name__ == "__main__":
    main()
