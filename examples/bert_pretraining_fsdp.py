"""BERT pretraining with FSDP-style sharding — the baseline's transformer
data-parallel workload (SURVEY.md §5.7: BASELINE adds a BERT FSDP config;
no reference example exists — Horovod 0.15.1 predates BERT).

Demonstrates the GSPMD path: parameters sharded over the ``fsdp`` axis
(ZeRO-style), batch over ``data``×``fsdp``, XLA inserting the
all-gather/reduce-scatter pairs the reference would have done with NCCL.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu.jax as hvd
from examples.common import example_args
from horovod_tpu.models import BertConfig, BertForPretraining
from horovod_tpu.ops.losses import softmax_cross_entropy
from horovod_tpu.parallel.api import shard_params


def main():
    args = example_args("BERT pretraining (FSDP, synthetic)", batch_size=8,
                        lr=1e-4, steps=40, seq_len=128, fsdp=-1,
                        flash=False)
    hvd.init()
    n = hvd.num_chips()
    fsdp = n if args.fsdp == -1 else args.fsdp
    mesh = hvd.build_mesh({"data": n // fsdp, "fsdp": fsdp})

    cfg = BertConfig.tiny() if args.smoke else BertConfig.base()
    seq = 32 if args.smoke else args.seq_len
    steps = 4 if args.smoke else args.steps
    if args.flash:
        # --flash: the Pallas kernel behind the encoder's attention seam
        # (key-padding masks honored; dense fallback off-tile shapes).
        from horovod_tpu.ops.flash_attention import flash_attention_fn

        model = BertForPretraining(cfg, attention_fn=flash_attention_fn)
    else:
        model = BertForPretraining(cfg)

    ids = jnp.zeros((args.batch_size, seq), jnp.int32)
    params = jax.jit(lambda: model.init(jax.random.key(0), ids))()
    params = shard_params(params, mesh)

    opt = optax.adamw(args.lr)
    opt_state = jax.jit(opt.init)(params)

    def loss_fn(params, batch):
        input_ids, mlm_labels, mask_positions, nsp_labels = batch
        # Explicit all-valid attention mask: BERT is BIDIRECTIONAL, and
        # the flash adapter treats a missing mask as decoder (causal)
        # semantics — passing the mask keeps both attention backends on
        # the same bidirectional math.
        attn_mask = jnp.ones_like(input_ids)
        mlm_logits, nsp_logits = model.apply(params, input_ids,
                                             attention_mask=attn_mask,
                                             train=False)
        # lse-form CE (ops/losses.py): no [B,S,V] fp32 log-prob tensor.
        mlm_loss = softmax_cross_entropy(mlm_logits, mlm_labels,
                                         where=mask_positions.astype(bool))
        nsp_loss = softmax_cross_entropy(nsp_logits, nsp_labels)
        return mlm_loss + nsp_loss

    from jax.sharding import NamedSharding, PartitionSpec as P

    @jax.jit
    def step(params, opt_state, batch):
        batch = jax.lax.with_sharding_constraint(
            batch, NamedSharding(mesh, P(("data", "fsdp"))))
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(hvd.rank())
    for i in range(steps):
        input_ids = rng.integers(0, cfg.vocab_size,
                                 (args.batch_size, seq), dtype=np.int32)
        mask_positions = (rng.random((args.batch_size, seq)) < 0.15) \
            .astype(np.float32)
        mlm_labels = rng.integers(0, cfg.vocab_size,
                                  (args.batch_size, seq), dtype=np.int32)
        nsp_labels = rng.integers(0, 2, args.batch_size, dtype=np.int32)
        params, opt_state, loss = step(
            params, opt_state,
            (jnp.asarray(input_ids), jnp.asarray(mlm_labels),
             jnp.asarray(mask_positions), jnp.asarray(nsp_labels)))
        if i % max(steps // 5, 1) == 0 and hvd.rank() == 0:
            print(f"step {i}: loss={float(loss):.4f}", flush=True)
    print("done", flush=True)


if __name__ == "__main__":
    main()
