"""The smallest possible integration: MNIST in flax + horovod_tpu.

Role parity with reference ``examples/keras_mnist.py`` (95 LoC, the
README on-ramp): ``hvd.init()``, LR scaled by world size, the
``DistributedOptimizer`` wrapper, initial-state broadcast, and the
epochs-divided-by-size convention (ref :25) — nothing else.  See
``flax_mnist_advanced.py`` for the full callback stack.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu.jax as hvd
from examples.common import example_args, shard_for_rank, synthetic_mnist
from horovod_tpu.models import MnistConvNet


def main():
    args = example_args("flax MNIST (minimal)")
    hvd.init()

    images, labels = synthetic_mnist(512 if args.smoke else 4096)
    # Each rank trains on its 1/N shard (DistributedSampler role).
    images, labels = shard_for_rank((images, labels), hvd.rank(), hvd.size())

    model = MnistConvNet(dtype=jnp.float32)
    params = model.init(jax.random.key(0), jnp.zeros((1, 28, 28, 1)))

    def loss_fn(params, batch):
        x, y = batch
        logp = jax.nn.log_softmax(model.apply(params, x))
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], -1))

    # LR x size + gradient averaging across the mesh: the whole Horovod
    # recipe in two lines.
    opt = hvd.DistributedOptimizer(
        optax.sgd(args.lr * hvd.num_chips(), momentum=0.9))
    step = hvd.make_train_step(loss_fn, opt, hvd.data_parallel_mesh())

    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = jax.jit(opt.inner.init)(params)

    batch = args.batch_size
    # Epochs scale down with world size (reference keras_mnist.py:25).
    epochs = max((1 if args.smoke else args.epochs) // hvd.size(), 1)
    n = hvd.num_chips()
    for epoch in range(epochs):
        perm = np.random.default_rng(epoch).permutation(len(images))
        losses = []
        for i in range(0, len(images) - batch + 1, batch):
            idx = perm[i:i + batch][: batch - batch % n]
            data = (jnp.asarray(images[idx]), jnp.asarray(labels[idx]))
            params, opt_state, loss = step(params, opt_state, data)
            losses.append(float(loss))
        avg = hvd.allreduce(jnp.float32(np.mean(losses)), name="epoch_loss")
        if hvd.rank() == 0:
            print(f"epoch {epoch + 1}: loss={float(avg):.4f}", flush=True)
    print("done", flush=True)


if __name__ == "__main__":
    main()
