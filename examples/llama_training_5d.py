"""Llama training with configurable multi-axis parallelism — the flagship.

No reference equivalent (data-parallel-only reference); this is the
framework's demonstration that one model family runs under every
parallelism strategy it ships:

    --strategy gspmd     data x fsdp x tensor (x expert with --experts)
    --strategy seq       ring-attention context parallelism x data
    --strategy pipeline  GPipe stages x data

Tiny synthetic LM data; sized by --smoke for CI, scale the config flags
up on real pods.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu.jax as hvd
from examples.common import example_args
from horovod_tpu.models import LlamaConfig, LlamaModel
from horovod_tpu.parallel.api import make_parallel_train_step, shard_params
from horovod_tpu.parallel.pipeline import (
    init_pipelined_llama,
    make_pipelined_llama_train_step,
)
from horovod_tpu.parallel.seq import make_context_parallel_train_step


def main():
    args = example_args("Llama multi-axis parallel training",
                        batch_size=8, lr=1e-3, steps=20, seq_len=64,
                        strategy="gspmd", tensor=2, experts=0, pipe=2,
                        seq_shards=2)
    hvd.init()
    n = hvd.num_chips()
    steps = 3 if args.smoke else args.steps
    seq = 32 if args.smoke else args.seq_len
    rng = np.random.default_rng(hvd.rank())

    def tokens(batch):
        return jnp.asarray(rng.integers(0, cfg.vocab_size,
                                        (batch, seq + 1), dtype=np.int32))

    if args.strategy == "gspmd":
        tensor = min(args.tensor, n)
        rest = n // tensor
        fsdp = 2 if rest % 2 == 0 else 1
        data = rest // fsdp
        axes = {"data": data, "fsdp": fsdp, "tensor": tensor}
        cfg = LlamaConfig.tiny(num_experts=args.experts) if args.smoke \
            else dataclasses.replace(
                LlamaConfig.llama3_8b(), num_layers=4,
                num_experts=args.experts)
        if args.experts:
            axes["expert"] = 1  # experts shard over tensor-free capacity
        mesh = hvd.build_mesh(axes)
        model = LlamaModel(cfg)
        with hvd.use_mesh(mesh):
            ids = jnp.zeros((args.batch_size, seq), jnp.int32)
            params = shard_params(
                jax.jit(lambda: model.init(jax.random.key(0), ids))(), mesh)
            opt = hvd.DistributedOptimizer(optax.adamw(args.lr))
            step = make_parallel_train_step(model, opt, mesh)
            opt_state = jax.jit(opt.init)(params)
            for i in range(steps):
                params, opt_state, loss = step(params, opt_state,
                                               tokens(args.batch_size))
                if hvd.rank() == 0:
                    print(f"step {i}: loss={float(loss):.4f}", flush=True)

    elif args.strategy == "seq":
        seq_shards = min(args.seq_shards, n)
        data = n // seq_shards
        mesh = hvd.build_mesh({"data": data, "seq": seq_shards})
        cfg = dataclasses.replace(LlamaConfig.tiny(), num_layers=2)
        model = LlamaModel(cfg)
        step = make_context_parallel_train_step(cfg, optax.adamw(args.lr),
                                                mesh)
        ids = tokens(args.batch_size)
        params = model.init(jax.random.key(0), ids[:, :-1])
        opt_state = jax.jit(optax.adamw(args.lr).init)(params)
        for i in range(steps):
            t = tokens(args.batch_size)
            params, opt_state, loss = step(params, opt_state,
                                           t[:, :-1], t[:, 1:])
            if hvd.rank() == 0:
                print(f"step {i}: loss={float(loss):.4f}", flush=True)

    elif args.strategy == "pipeline":
        pipe = min(args.pipe, n)
        data = n // pipe
        mesh = hvd.build_mesh({"pipe": pipe, "data": data})
        cfg = dataclasses.replace(LlamaConfig.tiny(), num_layers=2 * pipe)
        params = init_pipelined_llama(cfg, jax.random.key(0), pipe)
        from jax.sharding import NamedSharding, PartitionSpec as P

        params = {
            "stages": jax.tree.map(
                lambda a: jax.device_put(a, NamedSharding(mesh, P("pipe"))),
                params["stages"]),
            "rest": jax.tree.map(
                lambda a: jax.device_put(a, NamedSharding(mesh, P())),
                params["rest"]),
        }
        opt = optax.adamw(args.lr)
        step = make_pipelined_llama_train_step(cfg, opt, mesh,
                                               n_microbatches=2)
        opt_state = jax.jit(opt.init)(params)
        for i in range(steps):
            t = tokens(args.batch_size)
            params, opt_state, loss = step(params, opt_state,
                                           t[:, :-1], t[:, 1:])
            if hvd.rank() == 0:
                print(f"step {i}: loss={float(loss):.4f}", flush=True)
    else:
        raise SystemExit(f"unknown strategy {args.strategy}")
    print("done", flush=True)


if __name__ == "__main__":
    main()
