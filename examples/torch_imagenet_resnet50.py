"""ImageNet ResNet-50 with the torch frontend — the full torch workload.

Role parity with reference ``examples/pytorch_imagenet_resnet50.py``:
resume-from-checkpoint discovery with broadcast of the resume epoch
(ref :62-72), broadcast of params + optimizer state after (possibly)
restoring on rank 0 (:140-142), per-batch gradual LR warmup to
``lr * size`` per Goyal et al. plus the 30/60/80 staircase (:204-217),
allreduce-averaged ``Metric`` class (:237-249), rank-0-only checkpoints
(:226-233), DistributedSampler-style 1/N sharding (:92-103), validation
each epoch.

The model is a standard ResNet-50 (bottleneck v1) defined inline —
torchvision is not available in air-gapped CI, and the architecture is
the workload, not the point.  Synthetic ImageNet stands in for the real
dataset (examples/common.py).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd
from examples.common import example_args, shard_for_rank, synthetic_imagenet


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, width, stride=1):
        super().__init__()
        cout = width * self.expansion
        self.conv1 = nn.Conv2d(cin, width, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        self.conv2 = nn.Conv2d(width, width, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(width)
        self.conv3 = nn.Conv2d(width, cout, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(cout)
        self.down = None
        if stride != 1 or cin != cout:
            self.down = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        r = x if self.down is None else self.down(x)
        x = F.relu(self.bn1(self.conv1(x)))
        x = F.relu(self.bn2(self.conv2(x)))
        return F.relu(self.bn3(self.conv3(x)) + r)


class ResNet(nn.Module):
    def __init__(self, stage_sizes=(3, 4, 6, 3), classes=1000):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2d(3, 64, 7, 2, 3, bias=False), nn.BatchNorm2d(64),
            nn.ReLU(), nn.MaxPool2d(3, 2, 1))
        stages, cin = [], 64
        for i, blocks in enumerate(stage_sizes):
            width = 64 * 2 ** i
            for j in range(blocks):
                stages.append(Bottleneck(
                    cin, width, stride=2 if i > 0 and j == 0 else 1))
                cin = width * Bottleneck.expansion
        self.stages = nn.Sequential(*stages)
        self.fc = nn.Linear(cin, classes)

    def forward(self, x):
        x = self.stages(self.stem(x))
        return self.fc(torch.flatten(F.adaptive_avg_pool2d(x, 1), 1))


class Metric:
    """Allreduce-averaged running metric (reference :237-249): every
    update is averaged across ranks, so all workers report the global
    value."""

    def __init__(self, name):
        self.name = name
        self.sum = torch.tensor(0.0)
        self.n = torch.tensor(0.0)

    def update(self, val):
        self.sum += hvd.allreduce(val.detach().float(), name=self.name)
        self.n += 1

    @property
    def avg(self):
        return (self.sum / self.n).item() if self.n else 0.0


def main():
    args = example_args(
        "torch ImageNet ResNet-50 (synthetic)", epochs=4, batch_size=32,
        lr=0.0125, checkpoint_dir="./checkpoints-torch-resnet50",
        warmup_epochs=3)
    hvd.init()
    torch.manual_seed(42)

    ckpt_format = os.path.join(args.checkpoint_dir,
                               "checkpoint-{epoch}.pt")

    image_size = 32 if args.smoke else 224
    n_train = 128 if args.smoke else 4096
    images, labels = synthetic_imagenet(n_train, image_size)
    images, labels = shard_for_rank((images, labels), hvd.rank(), hvd.size())
    X = torch.from_numpy(images).permute(0, 3, 1, 2).contiguous()
    Y = torch.from_numpy(labels).long()
    val_images, val_labels = synthetic_imagenet(
        64 if args.smoke else 1024, image_size, seed=99)
    val_images, val_labels = shard_for_rank(
        (val_images, val_labels), hvd.rank(), hvd.size())
    VX = torch.from_numpy(val_images).permute(0, 3, 1, 2).contiguous()
    VY = torch.from_numpy(val_labels).long()

    model = ResNet((1, 1, 1, 1) if args.smoke else (3, 4, 6, 3))

    # LR will be scaled up to args.lr * size by the per-batch warmup.
    optimizer = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=args.lr, momentum=0.9,
                        weight_decay=5e-5),
        named_parameters=model.named_parameters())

    # ---- resume (reference :62-72): rank 0 owns the checkpoints; find the
    # newest epoch there and broadcast the decision to everyone.
    resume_from_epoch = 0
    if hvd.rank() == 0:
        for try_epoch in range(args.epochs, 0, -1):
            if os.path.exists(ckpt_format.format(epoch=try_epoch)):
                resume_from_epoch = try_epoch
                break
    resume_from_epoch = int(hvd.broadcast(
        torch.tensor(resume_from_epoch), root_rank=0,
        name="resume_from_epoch").item())
    if resume_from_epoch > 0 and hvd.rank() == 0:
        ckpt = torch.load(ckpt_format.format(epoch=resume_from_epoch),
                          weights_only=True)
        model.load_state_dict(ckpt["model"])
        optimizer.load_state_dict(ckpt["optimizer"])
        print(f"resuming from epoch {resume_from_epoch}", flush=True)

    # ---- initial state sync (reference :140-142): after the (possible)
    # rank-0 restore, broadcast covers both fresh init and resume.
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    batch = args.batch_size
    # Steps derive from the GLOBAL dataset size, not this rank's shard
    # length: 1/N sharding leaves ranks with lengths differing by one, and
    # a rank running an extra step would enqueue collectives nobody joins.
    min_shard = n_train // hvd.size()
    steps_per_epoch = max(min_shard // batch, 1)
    min_val_shard = (64 if args.smoke else 1024) // hvd.size()

    def adjust_learning_rate(epoch, batch_idx):
        """Per-batch warmup 1 -> size over warmup_epochs, then the
        30/60/80 staircase (reference :204-217)."""
        if epoch < args.warmup_epochs:
            e = epoch + float(batch_idx + 1) / steps_per_epoch
            adj = 1.0 / hvd.size() * (
                e * (hvd.size() - 1) / args.warmup_epochs + 1)
        elif epoch < 30:
            adj = 1.0
        elif epoch < 60:
            adj = 1e-1
        elif epoch < 80:
            adj = 1e-2
        else:
            adj = 1e-3
        for group in optimizer.param_groups:
            group["lr"] = args.lr * hvd.size() * adj

    def save_checkpoint(epoch):
        if hvd.rank() == 0:
            os.makedirs(args.checkpoint_dir, exist_ok=True)
            torch.save({"model": model.state_dict(),
                        "optimizer": optimizer.state_dict()},
                       ckpt_format.format(epoch=epoch + 1))

    def accuracy(output, target):
        return (output.argmax(1) == target).float().mean()

    epochs = min(args.epochs, resume_from_epoch + 1) if args.smoke \
        else args.epochs
    for epoch in range(resume_from_epoch, epochs):
        model.train()
        train_loss, train_acc = Metric("train_loss"), Metric("train_acc")
        perm = torch.randperm(len(X))
        for batch_idx in range(steps_per_epoch):
            adjust_learning_rate(epoch, batch_idx)
            idx = perm[batch_idx * batch:(batch_idx + 1) * batch]
            optimizer.zero_grad()
            output = model(X[idx])
            loss = F.cross_entropy(output, Y[idx])
            loss.backward()
            optimizer.step()
            train_loss.update(loss)
            train_acc.update(accuracy(output, Y[idx]))

        model.eval()
        val_loss, val_acc = Metric("val_loss"), Metric("val_acc")
        val_steps = max(min_val_shard // batch, 1)
        with torch.no_grad():
            for s in range(val_steps):
                i = min(s * batch, max(len(VX) - batch, 0))
                output = model(VX[i:i + batch])
                val_loss.update(F.cross_entropy(output, VY[i:i + batch]))
                val_acc.update(accuracy(output, VY[i:i + batch]))

        save_checkpoint(epoch)
        if hvd.rank() == 0:
            print(f"epoch {epoch + 1}: train_loss={train_loss.avg:.4f} "
                  f"train_acc={train_acc.avg:.4f} "
                  f"val_loss={val_loss.avg:.4f} "
                  f"val_acc={val_acc.avg:.4f}", flush=True)
    print("done", flush=True)


if __name__ == "__main__":
    main()
