"""MNIST training with the JAX frontend — the hello-world workload.

Role parity with reference ``examples/tensorflow_mnist.py``: hvd.init
(ref :67), LR scaled by world size (:79), DistributedOptimizer (:82),
initial-state broadcast (:92), steps divided by size (:95), rank-0-only
checkpointing (:108).

Run single-process (one host's chips form the mesh), or multi-process
with HOROVOD_RANK/SIZE/COORDINATOR set per process.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu.jax as hvd
from examples.common import example_args, shard_for_rank, synthetic_mnist
from horovod_tpu.models import MnistConvNet


def main():
    args = example_args("JAX MNIST", checkpoint_dir="")
    hvd.init()
    mesh = hvd.data_parallel_mesh()
    n = hvd.num_chips()

    images, labels = synthetic_mnist(512 if args.smoke else 4096)
    # Each process trains on its 1/size shard of the data.
    images, labels = shard_for_rank((images, labels), hvd.rank(), hvd.size())

    model = MnistConvNet(dtype=jnp.float32)
    params = model.init(jax.random.key(0), jnp.zeros((1, 28, 28, 1)))

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply(params, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

    # Scale LR by total chips (reference scales by hvd.size(), :79 — here
    # data parallelism spans chips within and across processes).
    opt = hvd.DistributedOptimizer(optax.sgd(args.lr * n, momentum=0.9))
    step = hvd.make_train_step(loss_fn, opt, mesh, donate=False)
    opt_state = jax.jit(opt.inner.init)(params)

    # Sync initial params across processes (reference bcast hook, :92).
    params = hvd.broadcast_parameters(params, root_rank=0)

    epochs = 1 if args.smoke else args.epochs
    batch = args.batch_size
    steps = max(len(images) // batch, 1)
    for epoch in range(epochs):
        perm = np.random.default_rng(epoch).permutation(len(images))
        epoch_loss = 0.0
        for i in range(steps):
            idx = perm[i * batch:(i + 1) * batch]
            if len(idx) < n:  # drop remainder not divisible by mesh
                continue
            idx = idx[: len(idx) - len(idx) % n]
            params, opt_state, loss = step(
                params, opt_state,
                (jnp.asarray(images[idx]), jnp.asarray(labels[idx])))
            epoch_loss += float(loss)
        # Average the metric across processes (reference averages via
        # allreduce in its torch examples).
        avg = hvd.allreduce(jnp.asarray(epoch_loss / steps), op=hvd.Average)
        if hvd.rank() == 0:
            print(f"epoch {epoch + 1}: loss={float(avg):.4f}", flush=True)

    if args.checkpoint_dir and hvd.rank() == 0:
        import horovod_tpu.flax as hvdk

        hvdk.save_checkpoint(args.checkpoint_dir, params, epochs - 1)
        print(f"checkpoint saved to {args.checkpoint_dir}", flush=True)
    print("done", flush=True)


if __name__ == "__main__":
    main()
